"""Host-side page allocator for :class:`~.cache.PagedKVCache` — free
list, refcounts, prefix hashing, and copy-on-write decisions.

Everything here is host/numpy state; the device only ever sees the
``(num_slots, max_pages)`` int32 page table (:meth:`device_table`
memoises the transfer until the table changes).  The allocator is the
single source of truth for what a page means:

* **Ownership** — ``refcount[p]`` counts the SLOTS mapping page ``p``.
  A page with refcount 1 is private to its slot and may be appended
  into in place; a page with refcount > 1 is **immutable** (shared) —
  any append must copy-on-write first (:meth:`needs_cow` /
  :meth:`remap`), which is how "mutating one sharer never perturbs
  another" is guaranteed structurally rather than numerically.
* **Prefix sharing** — prompt pages are content-hashed with a CHAINED
  hash (page ``i``'s digest covers tokens ``[0, (i+1)*page_size)``, so
  equal digests imply equal full prefixes, not just equal pages).  A
  partial tail page gets its own digest (exact-prefix only).  On
  admission :meth:`lookup_prefix` walks the chain and maps every hit to
  the existing page (refcount++) instead of recomputing/storing it;
  :meth:`register_prefix` publishes a freshly prefilled slot's pages.
  Registered pages stay safe to share while their owner decodes because
  writes are append-only (rows past the registered prefix) and any
  write to a page that has since been shared copy-on-writes away.
* **Reclamation** — when a slot is freed its pages' refcounts drop.
  A page reaching refcount 0 whose content is hash-registered becomes
  **free-but-cached** (vLLM's automatic prefix caching): it stays
  reachable through its digest — so the NEXT identical prompt still
  hits even after the first request retired — and is reclaimed (hashes
  purged, then reused) only when the truly-free list runs dry, oldest
  first.  A reused page is never reachable under a stale digest.
  Registered rows are never invalidated by appends: writes into a live
  page only target rows past its registered prefix, except the one
  capped-full-hit rewrite of the final prompt row, which recomputes the
  SAME token at the same position over the same prefix (the semantic
  content the digest stands for).
"""
from __future__ import annotations

import hashlib
from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from ..observability import tracing as _tracing

__all__ = ["PageAllocator", "PagePoolExhausted", "prompt_digest_chain"]


class PagePoolExhausted(RuntimeError):
    """No free page — the scheduler must evict a slot (or the caller,
    driving the engine directly, sized the pool too small)."""


def _digest(prev: bytes, tokens: np.ndarray, partial: bool) -> bytes:
    h = hashlib.sha256()
    h.update(prev)
    h.update(np.ascontiguousarray(tokens, np.int32).tobytes())
    if partial:
        h.update(b"|partial")
    return h.digest()


def prompt_digest_chain(ids: np.ndarray, page_size: int) -> List[bytes]:
    """The chained FULL-page digests of a prompt, allocator-free.

    This is the prefix-affinity consultation key (ISSUE 19): the router
    hashes a prompt ONCE and intersects the chain against each
    replica's advertised digest set (device hash table + host tier +
    cluster index, all chained with the same ``_digest``) to find the
    replica covering the longest prefix.  The partial tail is omitted
    on purpose — affinity scores whole pages; a tail hit moves the
    score by less than one page and admission re-derives exact coverage
    anyway."""
    ids = np.asarray(ids, np.int32).reshape(-1)
    out, prev = [], b""
    for i in range(len(ids) // page_size):
        prev = _digest(prev, ids[i * page_size:(i + 1) * page_size],
                       partial=False)
        out.append(prev)
    return out


class PageAllocator:
    def __init__(self, num_pages: int, num_slots: int, max_pages: int,
                 page_size: int, tracer=None):
        # page-lifecycle events (prefix share / CoW remap / reclaim) land
        # on the tracer's engine lane; the no-op tracer costs one empty
        # call per event (tracing.py discipline)
        self._tracer = (tracer if tracer is not None
                        else _tracing.default_tracer())
        self.num_pages = int(num_pages)
        self.num_slots = int(num_slots)
        self.max_pages = int(max_pages)
        self.page_size = int(page_size)
        self.table = np.zeros((self.num_slots, self.max_pages), np.int32)
        self.mapped = np.zeros((self.num_slots, self.max_pages), bool)
        self.refcount = np.zeros((self.num_pages,), np.int32)
        # LIFO free list: recently-freed pages are reused first (their
        # bytes are most likely still cache-resident)
        self._free: List[int] = list(range(self.num_pages - 1, -1, -1))
        # free-but-cached: refcount-0 pages still reachable by digest,
        # reclaimed LRU (insertion-ordered dict) when _free runs dry
        self._cached: Dict[int, None] = {}
        self._hash_to_page: Dict[bytes, int] = {}
        self._page_hashes: Dict[int, Set[bytes]] = {}
        self._device_table = None     # memoised jnp copy; None = dirty
        # host-tier spill hook (serving/kv_tier.py wiring): called with
        # (pid, frozenset of digests) just before a reclaim purges a
        # hash-reachable page, so its rows can fall to host RAM instead
        # of to recompute.  None = no tier.  Best-effort: a failed spill
        # must never fail the allocation it rode on.
        self.spill_hook = None

    # -- pool accounting ---------------------------------------------------

    def pages_free(self) -> int:
        """Allocatable pages: truly free + reclaimable cached."""
        return len(self._free) + len(self._cached)

    def pages_cached(self) -> int:
        return len(self._cached)

    def pages_used(self) -> int:
        """Pages mapped by at least one slot (cached pages are free)."""
        return self.num_pages - self.pages_free()

    def slot_pages(self, slot: int) -> int:
        return int(self.mapped[slot].sum())

    def unshared_pages(self, slot: int) -> int:
        """Pages ONLY this slot maps — what freeing the slot actually
        returns to the pool (shared pages just drop a reference)."""
        ids = self.table[slot][self.mapped[slot]]
        return int((self.refcount[ids] == 1).sum())

    def mapped_rows_total(self) -> int:
        """Sum over slots of mapped rows — the KV read bound a
        length-aware paged schedule pays per decode step (each slot
        reads its own mapped pages; sharing saves storage, not reads)."""
        return int(self.mapped.sum()) * self.page_size

    # -- allocation / mapping ----------------------------------------------

    def _purge_hashes(self, pid: int):
        for d in self._page_hashes.pop(pid, ()):
            self._hash_to_page.pop(d, None)

    def alloc(self) -> int:
        if self._free:
            pid = self._free.pop()
        elif self._cached:
            # reclaim the oldest cached page: purge its digests so the
            # rewritten page is never reachable under stale content —
            # but first offer it to the host tier (it is refcount-0 and
            # hash-reachable: exactly the page a repeat prompt would
            # have hit)
            pid = next(iter(self._cached))
            del self._cached[pid]
            if self.spill_hook is not None:
                digests = self._page_hashes.get(pid)
                if digests:
                    try:
                        self.spill_hook(pid, frozenset(digests))
                    except Exception as e:
                        import sys
                        sys.stderr.write("[kv_tier] spill of page %d "
                                         "failed (reclaiming anyway): "
                                         "%r\n" % (pid, e))
            self._purge_hashes(pid)
            self._tracer.instant("pages.reclaim", page=pid,
                                 cached_left=len(self._cached))
        else:
            raise PagePoolExhausted(
                "page pool exhausted: all %d pages are mapped"
                % self.num_pages)
        self.refcount[pid] = 1
        return pid

    def map(self, slot: int, idx: int, pid: int):
        if self.mapped[slot, idx]:
            raise ValueError("slot %d page-table entry %d already mapped"
                             % (slot, idx))
        self.table[slot, idx] = pid
        self.mapped[slot, idx] = True
        self._device_table = None

    def share(self, slot: int, idx: int, pid: int):
        """Map an EXISTING page into a slot (prefix hit): refcount++.
        A free-but-cached page comes back off the reclaim list."""
        if self.refcount[pid] == 0:
            self._cached.pop(pid, None)
        self.refcount[pid] += 1
        self.map(slot, idx, pid)
        self._tracer.instant("pages.prefix_share", page=pid, slot=slot,
                             refcount=int(self.refcount[pid]))

    def _release(self, pid: int):
        self.refcount[pid] -= 1
        if self.refcount[pid] < 0:
            raise AssertionError("page %d refcount underflow" % pid)
        if self.refcount[pid] == 0:
            if self._page_hashes.get(pid):
                # hash-reachable: keep it cached for future prefix hits
                self._cached[pid] = None
            else:
                self._free.append(pid)

    def free_slot(self, slot: int):
        for idx in np.nonzero(self.mapped[slot])[0]:
            self._release(int(self.table[slot, idx]))
        self.table[slot] = 0
        self.mapped[slot] = False
        self._device_table = None

    def reset(self):
        """Free every slot AND drop the prefix cache (a hard reset —
        engine.reset() semantics: nothing survives)."""
        for s in range(self.num_slots):
            self.free_slot(s)
        self.drop_prefix_cache()

    def drop_prefix_cache(self):
        """Forget every registered digest and return cached pages to the
        free list.  Called when the model parameters change
        (``engine.refresh_state``): a prefix hit must never map pages
        whose K/V was computed under STALE weights.  Pages still mapped
        by live slots keep decoding with their existing cache (the
        documented mid-flight semantics) — they just stop being
        hash-reachable, so no FUTURE admission shares them."""
        self._hash_to_page.clear()
        self._page_hashes.clear()
        self._free.extend(self._cached)
        self._cached.clear()

    def evict_cached(self, pid: int):
        """Purge one free-but-cached page to the truly-free list (the
        explicit cold-page path: the engine spills its rows to the host
        tier FIRST, then calls this so the device copy stops being
        hash-reachable — the content survives, the HBM does not)."""
        if pid not in self._cached:
            raise ValueError("page %d is not free-but-cached" % pid)
        del self._cached[pid]
        self._purge_hashes(pid)
        self._free.append(pid)
        self._device_table = None

    def adopt_page(self, pid: int, digests):
        """Register a freshly imported page (the host-tier fetch
        landing) as free-but-cached content: reachable under
        ``digests`` and immediately shareable by the admission that
        triggered the fetch — exactly the state a released,
        hash-registered page is in.  ``pid`` must have come from
        :meth:`alloc` (refcount 1, unmapped); adoption parks it at
        refcount 0 on the cached list."""
        if self.refcount[pid] != 1:
            raise AssertionError("adopt_page expects a fresh alloc "
                                 "(page %d refcount %d)"
                                 % (pid, int(self.refcount[pid])))
        self.refcount[pid] = 0
        self._cached[pid] = None
        s = self._page_hashes.setdefault(pid, set())
        for d in digests:
            self._hash_to_page[d] = pid
            s.add(d)

    # -- copy-on-write -----------------------------------------------------

    def needs_cow(self, slot: int, idx: int) -> bool:
        """True when appending into this entry's page must copy first:
        the page is mapped and some OTHER slot (or a pending sharer)
        also references it."""
        if not self.mapped[slot, idx]:
            return False
        return int(self.refcount[self.table[slot, idx]]) > 1

    def remap(self, slot: int, idx: int, new_pid: int) -> int:
        """Point ``slot``'s entry at ``new_pid`` (the freshly-copied
        private page), dropping its reference to the shared original.
        Returns the old page id (the copy source)."""
        old = int(self.table[slot, idx])
        self.table[slot, idx] = new_pid
        self._release(old)
        self._device_table = None
        self._tracer.instant("pages.cow_remap", slot=slot, old=old,
                             new=int(new_pid))
        return old

    # -- prefix hashing ----------------------------------------------------

    def _prompt_digests(self, ids: np.ndarray
                        ) -> Tuple[List[bytes], Optional[bytes]]:
        """(full-page digests, partial-tail digest or None) for a prompt."""
        ids = np.asarray(ids, np.int32).reshape(-1)
        P = self.page_size
        full = len(ids) // P
        out, prev = [], b""
        for i in range(full):
            prev = _digest(prev, ids[i * P:(i + 1) * P], partial=False)
            out.append(prev)
        tail = None
        if len(ids) % P:
            tail = _digest(prev, ids[full * P:], partial=True)
        return out, tail

    def lookup_prefix(self, ids: np.ndarray) -> Tuple[List[int], int]:
        """Longest shareable prefix of ``ids``: returns (page ids to map,
        tokens covered).  Walks full-page digests while they hit; when
        EVERY full page hit and a partial tail exists, tries the tail
        digest too — a tail hit means the whole prompt is cached."""
        full_digests, tail_digest = self._prompt_digests(ids)
        pages: List[int] = []
        for d in full_digests:
            pid = self._hash_to_page.get(d)
            if pid is None:
                return pages, len(pages) * self.page_size
            pages.append(pid)
        covered = len(pages) * self.page_size
        if tail_digest is not None:
            pid = self._hash_to_page.get(tail_digest)
            if pid is not None:
                pages.append(pid)
                covered = len(ids)
        return pages, covered

    def register_prefix(self, slot: int, ids: np.ndarray):
        """Publish a fully-prefilled slot's prompt pages for sharing.
        Digests already registered (e.g. the shared pages this slot
        itself mapped) are left pointing at their existing page.
        Returns every digest now servable for this prompt (newly
        registered or pre-existing) — the engine offers them to the
        cluster prefix index when one is attached."""
        full_digests, tail_digest = self._prompt_digests(ids)
        entries = list(enumerate(full_digests))
        if tail_digest is not None:
            entries.append((len(full_digests), tail_digest))
        servable = []
        for idx, d in entries:
            if d in self._hash_to_page:
                servable.append(d)
                continue
            if not self.mapped[slot, idx]:
                continue
            pid = int(self.table[slot, idx])
            self._hash_to_page[d] = pid
            self._page_hashes.setdefault(pid, set()).add(d)
            servable.append(d)
        return servable

    # -- device mirror -----------------------------------------------------

    def device_table(self):
        """The page table as a device int32 array, re-uploaded only when
        the host table changed since the last call."""
        if self._device_table is None:
            import jax.numpy as jnp
            self._device_table = jnp.asarray(self.table)
        return self._device_table
