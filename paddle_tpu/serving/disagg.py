"""Disaggregated prefill/decode serving (ISSUE 15 — ROADMAP 3(iii)).

Chunked prefill (PR 7) bounds how long one admission can stall
in-flight TPOT, but prefill compute still time-shares the decode chip:
under a prefill-heavy mix every chunk slice steals a decode step and
p99 TPOT degrades with QPS.  The production answer (DistServe /
Splitwise) is **role separation** — dedicated prefill workers hand
finished KV off to decode workers, so TTFT scales with prefill capacity
while decode TPOT stays flat regardless of the prompt-length mix.  This
module is that architecture in static-shape TPU-native form:

* The **prefill engine** (its own :class:`~.engine.DecodeEngine`,
  typically pinned to its own chip via ``device=``) runs bucketed/
  chunked prefill into its OWN paged pool and samples the first token —
  TTFT is prefill-complete, exactly like the colocated engine.
* The request's mapped pages then move to the **decode engine** through
  two static programs: ``kv_export`` (gather the pages into a dense
  donated transfer buffer on the prefill side) and ``kv_import``
  (scatter the staged buffer into freshly allocated pages of the decode
  pool) — ``handoff_pages`` pages per chunk, one chunk per scheduler
  iteration, interleaved *between* decode steps so an in-flight handoff
  never blocks a decode dispatch (the import donates the in-flight
  step's output pool and the device sequences it; same overlap
  discipline as the PR-12 one-step-in-flight loop).
* The transfer stages device-to-device via ``jax.device_put`` across
  the two engines' meshes; the **host-staging fallback**
  (``via_host=True`` / ``PADDLE_TPU_HANDOFF_HOST=1``) round-trips the
  chunk through a spilled ``.npz`` on the host — the transport
  stand-in for disjoint meshes / separate processes, and the natural
  home of the ``serve.handoff`` chaos site's ``TornFile`` injection.

**Routing.**  Admission is strict FIFO: the queue head routes to the
prefill engine unless the DECODE pool's prefix cache already covers the
whole prompt (n-1 tokens — then it admits decode-side in one 1-token
chunk, skipping prefill AND transfer entirely).  Prefix-cache
registration happens on the decode side at handoff completion — the
pool that lives long — so repeated prompts stop paying the transfer;
the prefill pool keeps its own (engine-native) registration so repeated
prompts also prefill in fewer chunks.

**Failure/pressure discipline.**  A failed handoff chunk (an injected
``SocketReset``/``TornFile`` at the ``serve.handoff`` faultpoint, or a
real transport error) REQUEUES the request at the queue front — the
recompute path, pages freed refcount-exactly on BOTH pools — instead of
dropping it.  Decode-pool pressure mid-handoff picks victims exactly
like PR 7's page-pressure path (refcount-aware, requeue-at-front,
``max_preemptions``-capped), and a mid-handoff victim cleans up both
pools.  A wedged transfer trips the ``serve.handoff`` liveness beacon —
a stall dump with all-thread stacks, not silence.

**Parity.**  Greedy output is BIT-IDENTICAL to the colocated engine:
the chunk programs are the same programs, the transfer copies page
bytes exactly (int8 codes + scales included), and per-slot decode math
is independent of batch composition.  Compile-once holds per role
(prefill: ``prefill_chunk`` + ``kv_export``; decode: ``decode``/
``spec_verify`` + ``kv_import`` — each budget 1 under the strict
watchdog).
"""
from __future__ import annotations

import os
import time
from collections import deque
from typing import Dict, List, Optional

import numpy as np

from ..observability import liveness as _liveness
from ..observability import registry as _metrics
from ..observability import tracing as _tracing
from ..robustness.faultpoints import declare as _declare, faultpoint
from .engine import PagePoolExhausted, PrefillTask
from .kv_tier import TRANSPORT_ERRORS, npz_roundtrip
from .scheduler import ContinuousBatchingScheduler, Request  # noqa: F401

__all__ = ["DisaggScheduler", "HandoffTask"]

#: chaos site on the per-chunk page transfer: a scheduled SocketReset
#: (device path) or TornFile (host-staging path — ctx carries the spill
#: file's ``path``) simulates a torn transport mid-handoff; the
#: scheduler must requeue the request, never drop it
HANDOFF_SITE = _declare(
    "serve.handoff",
    "fires once per disaggregated KV handoff chunk (device path: before "
    "the export dispatch; host-staging path: between spill write and "
    "read-back, ctx['path'] = the spill file, so TornFile models a torn "
    "transport)")

#: liveness beacon over one handoff chunk transfer: a wedged device_put
#: or spill read produces a stall dump naming this beacon
_liveness.declare_beacon(
    "serve.handoff",
    "one disaggregated KV handoff chunk (export -> stage -> import), "
    "interleaved between decode steps", deadline=600.0)

#: transport errors a handoff chunk treats as "the transfer failed —
#: requeue and recompute" — the ONE failure model shared with the
#: host-tier fetch transport (serving/kv_tier.py owns the definition)
_TRANSPORT_ERRORS = TRANSPORT_ERRORS


class HandoffTask:
    """One in-progress KV page handoff: the request finished prefill on
    the prefill engine and its pages are moving (chunk by chunk) into
    the decode pool.  ``dst_slot`` is None while the task waits in the
    bounded handoff queue for a free decode slot."""

    __slots__ = ("act", "ids", "src_slot", "dst_slot", "pages",
                 "n_pages", "pos", "bytes", "span")

    def __init__(self, act, ids, src_slot, pages):
        self.act = act
        self.ids = np.asarray(ids, np.int32)
        self.src_slot = int(src_slot)
        self.dst_slot: Optional[int] = None
        self.pages: List[int] = list(pages)   # prefill-pool page ids,
        self.n_pages = len(self.pages)        # page-table order
        self.pos = 0                          # pages transferred
        self.bytes = 0
        self.span = None                      # "handoff" request span


class DisaggScheduler(ContinuousBatchingScheduler):
    """Role-split continuous batching: ``engine`` decodes,
    ``prefill_engine`` prefills, and finished KV hands off between
    their pools.  Everything else — the overlapped decode loop,
    refcount-aware eviction, recompute preemption, tracing, streaming
    hooks — is the base scheduler, so the decode role behaves exactly
    like the colocated engine once a request's pages have landed."""

    def __init__(self, engine, prefill_engine, handoff_limit=4,
                 via_host=None, tracer=None, overlap=None, on_token=None,
                 on_finish=None):
        if prefill_engine is engine:
            raise ValueError("disaggregated serving needs TWO engines "
                             "(prefill_engine is the decode engine)")
        for e, role in ((engine, "decode"), (prefill_engine, "prefill")):
            if not e.paged:
                raise ValueError("%s engine must be paged (the slotted "
                                 "layout has no page pool to hand off)"
                                 % role)
        if prefill_engine.spec_k:
            raise ValueError("the prefill engine never decodes — build "
                             "it with spec_k=0")
        if prefill_engine.tp != 1:
            raise ValueError("tensor-parallel prefill is not supported "
                             "(shard the decode engine; prefill is "
                             "per-slot work)")
        for attr in ("page_size", "max_len", "handoff_pages",
                     "kv_dtype", "_cache_dtype", "_layers", "_heads",
                     "_head_dim"):
            a, b = getattr(prefill_engine, attr), getattr(engine, attr)
            if a != b:
                raise ValueError(
                    "prefill/decode engine geometry differs on %s: "
                    "%r vs %r (pages are copied byte-wise between the "
                    "pools)" % (attr.lstrip("_"), a, b))
        if prefill_engine.mesh is not None and engine.mesh is None:
            raise ValueError(
                "a device-pinned prefill engine needs a mesh-placed "
                "decode engine (device= or tp=): a meshless engine's "
                "world is uncommitted, and staging a committed buffer "
                "into it would split its jit caches on commitment")
        super().__init__(engine, tracer=tracer, overlap=overlap,
                         on_token=on_token, on_finish=on_finish)
        self.prefill_engine = prefill_engine
        self.handoff_limit = int(handoff_limit)
        if self.handoff_limit < 1:
            raise ValueError("handoff_limit must be >= 1")
        if via_host is None:
            via_host = os.environ.get("PADDLE_TPU_HANDOFF_HOST",
                                      "0") == "1"
        self.via_host = bool(via_host)
        self.pslots: List[Optional[object]] = \
            [None] * prefill_engine.num_slots
        self._ready: deque = deque()          # HandoffTasks, bounded
        self._handoffs: Dict[int, HandoffTask] = {}   # dst_slot -> task
        self._blocked_stamp = None            # admit()'s capacity-block
                                              # memo (see admit)
        # handoff accounting (the bench's per-line report)
        self.handoff_bytes_total = 0
        self.handoffs_total = 0
        # role-routing accounting (the bench's structural isolation
        # gate): every decode-side chunk must be a single-chunk
        # full-prefix-hit admission — real prefill compute only ever
        # runs on the prefill engine
        self.decode_route_admissions = 0
        self.decode_side_chunks = 0
        self.prefill_side_chunks = 0
        self._m_ho_bytes = _metrics.counter("serving.handoff_bytes")
        self._m_ho_secs = _metrics.histogram("serving.handoff_seconds")
        self._m_ho_depth = _metrics.gauge("serving.handoff_queue_depth")
        self._ho_beacon = _liveness.beacon("serve.handoff")

    # -- state -------------------------------------------------------------

    @property
    def handoff_depth(self) -> int:
        """Requests queued for or mid-transfer (the bounded queue plus
        the in-flight set)."""
        return len(self._ready) + len(self._handoffs)

    def has_work(self) -> bool:
        return (super().has_work()
                or any(a is not None for a in self.pslots)
                or bool(self._ready))

    def _set_depth(self):
        self._m_ho_depth.set(self.handoff_depth)

    # -- admission routing -------------------------------------------------

    def _decode_covers(self, ids) -> bool:
        """True when the DECODE pool's prefix cache covers the whole
        prompt (n-1 tokens after the cap): the request admits
        decode-side in one 1-token chunk — no prefill, no transfer."""
        _pages, covered = self.engine._alloc.lookup_prefix(ids)
        return covered >= int(np.asarray(ids).size) - 1

    def _free_decode_slot(self) -> Optional[int]:
        for idx, a in enumerate(self.slots):
            if a is None:
                return idx
        return None

    def admit(self) -> int:
        """Strict-FIFO admission with role routing: the queue head goes
        to the prefill engine unless the decode pool's prefix cache
        fully covers it (then it admits decode-side directly).  A head
        whose route has no free slot blocks the queue — FIFO order is
        never reordered around capacity."""
        n = 0
        while self.waiting:
            idx = self._free_decode_slot()
            pidx = next((i for i, a in enumerate(self.pslots)
                         if a is None), None)
            if idx is None and pidx is None:
                # both routes full: no admission is possible, so don't
                # hash the head's prompt (coverage lookup is O(prompt)
                # host work) on every iteration of the decode hot loop
                break
            req = self.waiting[0]
            parked = self._preempted.get(req.rid)
            ids = req.prompt
            if parked is not None and parked.generated:
                ids = np.concatenate(
                    [ids, np.asarray(parked.generated, np.int32)])
            # capacity-block memo: if the same head blocked last
            # iteration with the same free-route shape and the same
            # prefix-cache state (handoff completions and decode-route
            # admissions are the only events that register new decode-
            # side prefixes), the coverage lookup — O(prompt) host
            # hashing — would repeat last iteration's answer; skip it
            # on the hot loop.  Any component changing re-evaluates.
            stamp = (req.rid, int(np.asarray(ids).size), idx is None,
                     pidx is None, self.handoffs_total,
                     self.decode_route_admissions)
            if stamp == self._blocked_stamp:
                break
            if self._decode_covers(ids):
                if idx is None:
                    self._blocked_stamp = stamp
                    break
                self.waiting.popleft()
                self.decode_route_admissions += 1
                self._admit_paged(idx, req)
            else:
                if pidx is None:
                    self._blocked_stamp = stamp
                    break
                self.waiting.popleft()
                self._admit_paged(pidx, req,
                                  engine=self.prefill_engine,
                                  slots=self.pslots)
            self._blocked_stamp = None
            n += 1
        if n:
            self._m_queue_depth.set(len(self.waiting))
            self._m_occupancy.set(
                sum(a is not None for a in self.slots))
        return n

    # -- prefill side ------------------------------------------------------

    def prefill_once(self) -> int:
        n = super().prefill_once()      # decode-side tasks (full hits)
        self.decode_side_chunks += n
        pn = self._prefill_side_once()
        self.prefill_side_chunks += pn
        self._handoff_advance()
        return n + pn

    def _evict_prefill_pages(self, requester_pidx: int) -> str:
        """Prefill-pool pressure: preempt the prefill-side slot with the
        most unshared pages (excluding the requester), requeueing it at
        the queue front like the decode-side path.  Returns ``"retry"``
        (pages were freed), ``"wait"`` (the only other occupants are
        mid-handoff — their pages free when the transfers land, so the
        requester parks instead of dying), or ``"retired"`` (the
        requester itself was the last occupant and cannot fit alone —
        finished cache_full)."""
        candidates = [i for i, a in enumerate(self.pslots)
                      if a is not None and i != requester_pidx
                      and isinstance(a.prefill_task, PrefillTask)]
        if not candidates:
            if any(a is not None for i, a in enumerate(self.pslots)
                   if i != requester_pidx):
                return "wait"
            self._finish_pslot(requester_pidx, "cache_full")
            return "retired"
        victim = max(candidates,
                     key=lambda i: (
                         self.prefill_engine.unshared_pages(i),
                         -self.pslots[i].admit_order))
        act = self.pslots[victim]
        rid = act.req.rid
        cnt = self._preempt_count.get(rid, 0) + 1
        self._preempt_count[rid] = cnt
        if cnt > self.max_preemptions:
            self._finish_pslot(victim, "cache_full")
            return "retry"
        self.pslots[victim] = None
        self.prefill_engine.free_slot(victim)
        act.prefill_task = None
        self._requeue_front(act, "preempted", slot=victim)
        return "retry"

    def _requeue_front(self, act, event, **attrs):
        """Park ``act`` and put its request back at the FRONT of the
        waiting queue (preemption / handoff-abort recompute path)."""
        rid = act.req.rid
        self.waiting.appendleft(act.req)
        self._submit_t[rid] = act.submit_t
        self._preempted[rid] = act
        root = self._req_spans.get(rid, _tracing.NOOP_SPAN)
        root.event(event, **attrs)
        self._wait_spans[rid] = self._tracer.span("requeue", parent=root,
                                                  rework=True)
        self._m_preempt.inc()
        self._m_queue_depth.set(len(self.waiting))

    def _finish_pslot(self, pidx: int, reason: str):
        """Retire a request that never reached the decode engine (EOS or
        budget on its first token, prefill-side cache_full, cancel)."""
        act = self.pslots[pidx]
        self.pslots[pidx] = None
        self.prefill_engine.free_slot(pidx)
        task = act.prefill_task
        act.prefill_task = None
        if isinstance(task, HandoffTask) and task in self._ready:
            self._ready.remove(task)
            self._set_depth()
        self._retire(act, reason)

    def _prefill_side_once(self) -> int:
        """Advance every prefill-engine admission by ONE chunk.  Chunks
        dispatch with ``sync=False``: the final chunk's sampled token is
        POLLED (``is_ready()``) on later iterations, never blocked on —
        a prefill-engine program must not stall the decode loop's next
        dispatch (the role-isolation contract; the colocated baseline
        keeps its synchronous chunk loop)."""
        n = 0
        pe = self.prefill_engine
        for pidx, act in enumerate(self.pslots):
            if act is None or not isinstance(act.prefill_task,
                                             PrefillTask):
                continue
            task = act.prefill_task
            if task.done:
                # final chunk dispatched on an earlier iteration: poll
                # its token / retry a queue-full handoff
                self._after_final_chunk(pidx)
                continue

            def evict(pidx=pidx):
                # "retry" freed pages; "retired" / "wait" give up (the
                # slot parks — the next iteration retries after the
                # in-flight transfers freed pages)
                return self._evict_prefill_pages(pidx) == "retry"

            done = self._run_prefill_chunk(act, task, pe, evict,
                                           sync=False)
            if done is None:
                continue
            n += 1
            if done:
                self._after_final_chunk(pidx)
        return n

    def _after_final_chunk(self, pidx: int):
        """The final chunk is dispatched: once its sampled token is
        READY (polled between decode steps, never a blocking sync) emit
        it — TTFT is prefill-complete, the colocated contract — and
        queue the handoff, or retire outright when one token already
        ends the request (no transfer for a max_new_tokens=1 /
        instant-EOS prompt)."""
        act = self.pslots[pidx]
        task = act.prefill_task
        if task.first_token < 0:
            dev = task.first_token_dev
            if dev is not None and not dev.is_ready():
                return              # not landed yet: poll next iteration
            task.first_token = int(dev)
            task.first_token_dev = None
            now = time.perf_counter()
            rid = act.req.rid
            root = self._req_spans.get(rid, _tracing.NOOP_SPAN)
            if act.first_tok_t is None:
                root.event("first_token")
            act.first_token(task.first_token, now)
            self._notify_tokens(rid, act.generated[-1:])
            # one token may already end the request — retire on the
            # prefill side, the decode pool never hears about it
            req = act.req
            tok = act.generated[-1]
            if (req.eos_token_id is not None
                    and tok == int(req.eos_token_id)):
                self._finish_pslot(pidx, "eos")
                return
            if len(act.generated) >= req.max_new_tokens:
                self._finish_pslot(pidx, "length")
                return
        self._try_queue_handoff(pidx)

    def _try_queue_handoff(self, pidx: int) -> bool:
        """Move a prefill-complete slot into the bounded handoff queue;
        False (slot stays parked, pages held — backpressure on prefill
        capacity) when the queue is full."""
        if len(self._ready) >= self.handoff_limit:
            return False
        act = self.pslots[pidx]
        task = act.prefill_task
        pe = self.prefill_engine
        pages = [int(p) for p in
                 pe._alloc.table[pidx][pe._alloc.mapped[pidx]]]
        ho = HandoffTask(act, task.ids, pidx, pages)
        act.prefill_task = ho
        self._ready.append(ho)
        self._set_depth()
        return True

    # -- the handoff itself ------------------------------------------------

    def _handoff_advance(self):
        """Start queued handoffs into free decode slots, then advance
        every in-flight handoff by ONE chunk — between decode steps, so
        a transfer never blocks a decode dispatch."""
        while self._ready:
            idx = self._free_decode_slot()
            if idx is None:
                break
            task = self._ready.popleft()
            task.dst_slot = idx
            self.slots[idx] = task.act
            self._handoffs[idx] = task
            root = self._req_spans.get(task.act.req.rid,
                                       _tracing.NOOP_SPAN)
            task.span = self._tracer.span("handoff", parent=root,
                                          pages=task.n_pages)
            self._set_depth()
        for idx in list(self._handoffs):
            task = self._handoffs.get(idx)
            if task is None:
                # retired mid-loop: an earlier chunk's page-pressure
                # eviction (or cap retirement) picked this mid-handoff
                # slot as its victim and popped it via _preempt/_finish
                continue
            self._handoff_chunk(task)

    def _spill_roundtrip(self, bufs, rid, chunk_idx):
        """The host-staging transport — the SAME
        :func:`~.kv_tier.npz_roundtrip` the host-tier fetch path uses
        (one transport, two call sites, one failure model), fired here
        through the ``serve.handoff`` chaos site with this handoff's
        rid/chunk context.  Raises the transport error a torn/reset
        transfer produces."""
        return npz_roundtrip(bufs, HANDOFF_SITE,
                             prefix="paddle_tpu_handoff_",
                             rid=rid, chunk=chunk_idx)

    def _handoff_chunk(self, task: HandoffTask):
        """Move ONE chunk of ``task``'s pages: export on the prefill
        engine, stage across, allocate + map decode pages, import.
        Transport errors (the ``serve.handoff`` chaos site included)
        abort the whole handoff and requeue the request at the queue
        front; decode-pool pressure evicts refcount-aware first."""
        pe, de = self.prefill_engine, self.engine
        rid = task.act.req.rid
        chunk = task.pages[task.pos:task.pos + pe.handoff_pages]
        chunk_idx = task.pos // pe.handoff_pages
        with self._ho_beacon:
            t0 = time.perf_counter()
            try:
                if self.via_host:
                    bufs = self._spill_roundtrip(
                        pe.export_pages(chunk), rid, chunk_idx)
                    staged = de.stage_handoff(bufs)
                else:
                    faultpoint(HANDOFF_SITE, rid=rid, chunk=chunk_idx)
                    bufs = pe.export_pages(chunk)
                    try:
                        staged = de.stage_handoff(bufs)
                    except (ValueError, RuntimeError):
                        # meshes the runtime cannot bridge device-to-
                        # device (disjoint backends/processes): switch
                        # this scheduler to the host-staging transport
                        # for the rest of the run and retry the chunk
                        self.via_host = True
                        bufs = self._spill_roundtrip(bufs, rid,
                                                     chunk_idx)
                        staged = de.stage_handoff(bufs)
            except _TRANSPORT_ERRORS as e:
                self._handoff_abort(task, e)
                return
            dst = self._alloc_dst(task, len(chunk))
            if dst is None:
                return            # requester retired (cache_full)
            de.import_pages(staged, dst)
            de._m_pool.set(de._alloc.pages_used())
            task.pos += len(chunk)
            moved = pe.handoff_chunk_bytes(len(chunk))
            task.bytes += moved
            self.handoff_bytes_total += moved
            self._m_ho_bytes.inc(moved)
            self._m_ho_secs.observe(time.perf_counter() - t0)
        if task.pos >= task.n_pages:
            self._handoff_finish(task)

    def _alloc_dst(self, task: HandoffTask, n: int):
        """Allocate + map ``n`` fresh decode-pool pages for the chunk,
        evicting decode-side victims under pressure (in-flight step
        drained first — PR-7 discipline).  None when the handoff itself
        was retired by the eviction fallback."""
        de = self.engine
        while True:
            ids, failed = [], False
            try:
                for _ in range(n):
                    ids.append(de._alloc.alloc())
            except PagePoolExhausted:
                failed = True
            if not failed:
                break
            for pid in ids:
                de._alloc._release(pid)
            if self._drain_inflight():
                continue
            if not self._evict_for_pages(task.dst_slot):
                return None     # requester finished cache_full
            if task.dst_slot not in self._handoffs:
                return None     # eviction machinery retired the task
        for i, pid in enumerate(ids):
            de._alloc.map(task.dst_slot, task.pos + i, pid)
        return ids

    def _handoff_finish(self, task: HandoffTask):
        """All pages landed: publish the decode-side length mirror,
        register the prompt in the DECODE pool's prefix cache (the pool
        that lives long — later identical prompts skip prefill AND
        transfer), release the prefill-side slot, and activate the
        decode slot."""
        de, act = self.engine, task.act
        n = int(task.ids.size)
        de._set_length(task.dst_slot, n)
        act.cache_len = n
        de._alloc.register_prefix(task.dst_slot, task.ids)
        self._handoffs.pop(task.dst_slot, None)
        act.prefill_task = None
        self.pslots[task.src_slot] = None
        self.prefill_engine.free_slot(task.src_slot)
        if task.span is not None:
            task.span.end(bytes=task.bytes, pages=task.pos)
            task.span = None
        self.handoffs_total += 1
        self._set_depth()
        self._check_finished(task.dst_slot)

    def _handoff_abort(self, task: HandoffTask, exc):
        """A chunk's transport failed: free BOTH pools refcount-exactly
        and requeue the request at the queue front for recompute (the
        ``max_preemptions`` cap still bounds a persistently torn
        transport — then it finishes "cache_full" like any
        eviction-starved request)."""
        act = task.act
        rid = act.req.rid
        if task.dst_slot is not None:
            self._handoffs.pop(task.dst_slot, None)
            self.slots[task.dst_slot] = None
            self.engine.free_slot(task.dst_slot)
        self.pslots[task.src_slot] = None
        self.prefill_engine.free_slot(task.src_slot)
        act.prefill_task = None
        if task.span is not None:
            task.span.end(aborted=True, error=type(exc).__name__)
            task.span = None
        self._set_depth()
        cnt = self._preempt_count.get(rid, 0) + 1
        self._preempt_count[rid] = cnt
        if cnt > self.max_preemptions:
            self._retire(act, "cache_full")
            return
        self._requeue_front(act, "handoff_aborted",
                            error=type(exc).__name__)

    # -- lifecycle overrides (a decode slot may be mid-handoff) ------------

    def _release_handoff_src(self, idx: int):
        task = self._handoffs.pop(idx, None)
        if task is None:
            return
        self.pslots[task.src_slot] = None
        self.prefill_engine.free_slot(task.src_slot)
        if task.span is not None:
            task.span.end(aborted=True)
            task.span = None
        self._set_depth()

    def _finish(self, idx: int, reason: str):
        self._release_handoff_src(idx)
        super()._finish(idx, reason)

    def _preempt(self, idx: int):
        self._release_handoff_src(idx)
        super()._preempt(idx)

    def cancel(self, rid: int) -> bool:
        if rid in self.finished:
            return False
        for pidx, act in enumerate(self.pslots):
            if act is None or act.req.rid != rid:
                continue
            task = act.prefill_task
            if isinstance(task, HandoffTask) and task.dst_slot is not None:
                break           # mid-transfer: the decode-slot scan
                                # below cleans both sides (_finish)
            self._finish_pslot(pidx, "cancelled")
            return True
        return super().cancel(rid)
