"""The TPU-native decode engine: static-shape KV cache + a batched
decode step that compiles exactly once.

Two cache layouts (``paged=True`` is the default — ISSUE 7):

* **Paged** — a fixed pool of fixed-size KV pages plus a per-slot int32
  page table (:class:`~.cache.PagedKVCache` + the host-side
  :class:`~.pages.PageAllocator`).  Compiled entry points:

  - ``decode`` — ALL slots advance one token in one fixed-shape
    program: scatter-append into each slot's tail page, paged-gather
    length-masked attention (``kernels.decode_attention`` family
    ``decode_attn_paged``), per-slot sampling.  Compiles ONCE.
  - ``spec_verify`` (``spec_k > 0`` — ISSUE 8) — the speculative
    **batched verify**: each slot's iteration input is ``k + 1`` tokens
    (the last committed token plus ``k`` host-side prompt-lookup
    drafts, :mod:`.spec`), ONE forward scores all positions over the
    paged cache, and the standard accept/resample rule
    (:func:`~.sampling.spec_accept`) runs in-program: rejected drafts
    roll the per-slot length counters (and with them the tail-page
    rows, overwritten by the next append) back INSIDE the program — no
    host sync on the hot path.  Fixed ``k`` means this is ONE static
    program (watchdog budget 1) beside the single-token ``decode``
    fallback; accept-rate extremes change traced values, never the
    program.  Greedy output is bit-identical to non-speculative decode;
    temperature sampling consumes exactly ONE threaded key per
    iteration regardless of accepted count (PR 7's seed-reproducibility
    contract).
  - ``prefill_chunk`` — one fixed-size chunk of one slot's prompt:
    admitting a long prompt runs ``ceil(n / chunk)`` iterations of this
    ONE program, interleaved by the scheduler with live decode steps so
    a long admission can no longer stall in-flight TPOT.  The final
    chunk samples the first generated token.
  - ``cow_copy`` — copy one page (all layers, scale rows included) to a
    fresh page: the copy-on-write step that un-shares a prefix page
    before a write.

  **Prefix sharing**: prompt pages are content-hashed at admission; a
  hit maps the slot's leading page-table entries to existing refcounted
  pages instead of recomputing/storing them.  Sharing is capped at
  ``n - 1`` tokens so the final token always runs through the chunk
  program (producing the first-token logits); a fully-cached prompt
  admits in ONE 1-token chunk, whose write copy-on-writes the shared
  tail page.

* **Slotted** (``paged=False`` — the PR-5 layout, kept for A/B and
  parity): per-slot contiguous ``max_len`` buffers, bucketed whole-
  prompt prefill.

**Tensor-parallel sharded decode (``tp=N`` — ISSUE 12).**  The paged
engine decodes MULTI-CHIP: the KV pool (codes AND the int8 scale pools)
is partitioned over the HEADS axis of a private ``('mp',)`` mesh, the
model parameters carry their Megatron pspec annotations (qkv/fc1
column-, out/fc2 row-, embeddings vocab-sharded — the SAME machinery
the training TP path uses, ``distributed/mp_layers.py``), and every
jitted entry (decode, prefill_chunk, cow_copy, spec_verify) becomes its
sharded twin via ``jax.jit`` with in/out shardings — GSPMD inserts
exactly the collectives the training path gets (psum after the
row-parallel matmuls, the vocab-parallel logits gather), audited by
TPU503 on the lowered sharded entries.  Page table, lengths, tokens and
the whole sampling state stay REPLICATED; the host-side bookkeeping
(:class:`~.pages.PageAllocator`, the length mirror) is untouched —
sharding divides bytes, never meaning.  Donation stays intact (TPU502:
the sharded pool aliases input→output per shard), the compile-once
discipline holds (ONE sharded program per entry across slot churn,
prefix hits, chunked admissions and spec verify), and per-chip decode
KV bytes/token drop to ``1/tp`` of the single-chip bound
(``kv_row_bytes``/``kv_pool_bytes``/``kv_bytes_per_token`` all report
PER-SHARD truth).  ``tp=1`` (the default) is byte-identical to the
unsharded engine.

**Decomposed collective overlap (``overlap_comm`` — ISSUE 20).**  On a
tp>1 engine, ``overlap_comm=True`` (or ``PADDLE_TPU_MP_OVERLAP=1``;
explicit ``False`` pins it off) traces the sharded entries under
:mod:`~paddle_tpu.distributed.mp_overlap`'s scope: the per-layer
monolithic all-gather / all-reduce / all-to-all islands become chunked
``ppermute`` rings interleaved with the partial matmuls, so on real
ICI the transfer hides behind compute.  Same math, different schedule
— at tp=2 every partial sum has exactly two f32 terms and greedy
output is BIT-identical to the monolithic engine (test-asserted).
The switch is engine geometry: ``engine_for`` folds the resolved value
into its cache key, and the structural claim (zero monolithic
all-gathers, permute chain present) is auditable per-kind via
``observability.costs.collective_stats``.

**int8 KV cache (``kv_dtype="int8"`` — ISSUE 8).**  Either layout can
store the pool as int8 codes + per-(row, head) f32 scales
(:mod:`.cache`): appends quantize in-program, the attention families'
q8 variants dequantize inline in the gather, and decode KV HBM traffic
per row drops from ``head_dim * dtype_bytes`` to ``head_dim + 4`` —
about HALF the bf16 pool's read bound at head_dim 64
(``kv_bytes_per_token()`` accounts codes + scales honestly).  Composes
with speculative decode: the verify program runs the same q8 gather.
Opt-in ``PADDLE_TPU_METRICS_KV_QUANT_ERROR=1`` (at engine construction)
threads a max-abs-dequant-error accumulator through the decode/verify
entries and publishes the ``serving.kv_quant_error`` gauge (one device
sync per step, same caveat as ``train.grad_norm``).
``kv_dtype="fp8"`` (ISSUE 20) runs float8_e4m3fn codes through the
SAME codes+scales plumbing — identical 1-byte row accounting, an
amax/448 saturating grid in :func:`.cache.quantize_kv`, and the
canonical dtype string (``"float8_e4m3fn"``) in the autotune key and
flight dump.

Every argument that varies across steps (tokens, draft tokens, active
mask, sampling parameters, PRNG key, page table, lengths) is a traced
array — nothing retraces, ever; asserted by ``decode_compile_count``/
``verify_compile_count`` and the recompile watchdog.  All entries
**donate the cache buffers** (code pools AND scale pools): XLA aliases
them input→output, so the multi-hundred-MB pool is updated in place
instead of double-buffered (TPU502 audits that the aliasing actually
materializes — see ``analysis/trace/programs.py``'s ``serving``
builder).  The page table is a per-step *input* (host-owned, re-uploaded
only when it changes), not donated.

The engine is deliberately request-free: slot admission/eviction policy
lives in :mod:`.scheduler`; the engine only refuses page allocation
(:class:`~.pages.PagePoolExhausted`) and lets the scheduler pick a
victim.
"""
from __future__ import annotations

import contextlib
import dataclasses
import os
import time

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..core.dtype import x64_scope
from ..core.tensor import Tensor
from ..distributed import mesh as _mesh
from ..distributed import mp_overlap as _mp_overlap
from ..distributed.mp_layers import MP_AXIS
from ..observability import flight as _flight
from ..observability import registry as _metrics
from ..observability import tracing as _tracing
from . import cache as _cache_mod
from .cache import (DecodeView, PagedDecodeView, PagedKVCache,
                    PagedPrefillChunkView, PrefillView, SlottedKVCache,
                    _unwrap)
from .pages import PageAllocator, PagePoolExhausted
from .sampling import TOP_K_MAX, sample, spec_accept

__all__ = ["DecodeEngine", "InflightDecode", "PagePoolExhausted",
           "PrefillTask", "prefill_buckets_for"]


def prefill_buckets_for(max_len, min_bucket=16):
    """Power-of-two prefill buckets up to ``max_len`` (slotted mode); a
    non-power-of-two ``max_len`` is appended as the final bucket so every
    prompt that fits the cache has a bucket."""
    out = []
    b = min(int(min_bucket), int(max_len))
    while b <= int(max_len):
        out.append(b)
        b *= 2
    if not out or out[-1] < int(max_len):
        out.append(int(max_len))
    return out


@contextlib.contextmanager
def _eval_scope(model):
    """Run the engine's compiled entries with the model in eval mode but
    RESTORE the caller's mode after: generate() between training epochs
    must not silently disable dropout for the rest of the run (mode only
    matters at trace time, but the flip would otherwise leak out)."""
    was_training = bool(getattr(model, "training", False))
    model.eval()
    try:
        yield
    finally:
        if was_training:
            model.train()


@dataclasses.dataclass
class InflightDecode:
    """One dispatched, not-yet-consumed decode (or speculative verify)
    step — the handle the overlapped scheduler loop holds while the
    device runs the step and the host does the *previous* step's
    bookkeeping.  Every field except ``active`` is a device array
    (a future under jax's async dispatch): nothing here has forced a
    host sync yet.  ``decode_fetch``/``decode_spec_fetch`` consume it —
    the ONLY blocking point of an engine iteration."""
    kind: str                             # "decode" | "spec"
    active: "np.ndarray"                  # dispatch-time mask (host copy)
    tok: object = None                    # (S,) int32 next tokens (decode)
    emitted: object = None                # (S, k+1) int32 (spec)
    counts: object = None                 # (S,) int32 accepted+1 (spec)
    logits: object = None                 # last-position logits
    qerr: object = None                   # opt-in quant-error scalar
    paged_rows: int = 0                   # dispatch-time mapped-rows
    consumed: bool = False                # set by the fetch
    slot_epoch: object = None             # per-slot free-epoch at
                                          # dispatch (spec only): the
                                          # fetch advances the length
                                          # mirror ONLY for lanes not
                                          # freed/readmitted since


@dataclasses.dataclass
class PrefillTask:
    """Host-side state of one in-flight chunked admission."""
    slot: int
    ids: "np.ndarray"                     # the full prompt, int32
    pos: int                              # next position to compute
    temperature: float
    top_k: int
    top_p: float
    shared_tokens: int = 0                # prefix-cache coverage (capped)
    shared_pages: int = 0                 # pages mapped instead of computed
    chunks_run: int = 0
    done: bool = False
    first_token: int = -1                 # sampled by the FINAL chunk
    first_token_dev: object = None        # () device array (sync=False)
    last_logits: object = None            # (vocab,) device array


class DecodeEngine:
    """Compiled serving engine for a causal-LM Layer (``model(input_ids,
    cache=<view>) -> (logits, cache)`` with a ``config`` carrying the
    GPT geometry — :class:`paddle_tpu.models.gpt.GPTForCausalLM`)."""

    def __init__(self, model, num_slots=4, max_len=None, cache_dtype=None,
                 min_bucket=16, seed=0, top_k_max=TOP_K_MAX, donate=True,
                 paged=True, page_size=64, num_pages=None,
                 prefill_chunk=None, kv_dtype=None, spec_k=0,
                 spec_ngram=3, tracer=None, tp=1, device=None,
                 handoff_pages=4, kv_host_bytes=None, overlap_comm=None):
        cfg = model.config
        self.model = model
        # request-scoped tracing (ISSUE 9): the engine lane carries one
        # dispatch span per compiled-entry call with the watchdog's
        # compile-count delta; the no-op default costs one bool check
        self._tracer = (tracer if tracer is not None
                        else _tracing.default_tracer())
        self.num_slots = int(num_slots)
        self.max_len = int(max_len or cfg.max_position_embeddings)
        if self.max_len > cfg.max_position_embeddings:
            raise ValueError(
                "max_len %d exceeds the model's position budget %d"
                % (self.max_len, cfg.max_position_embeddings))
        self.top_k_max = int(top_k_max)
        self.paged = bool(paged)
        self.state = model.functional_state()
        # the UNSHARDED snapshot leaves, kept for refresh_state's
        # param-change identity test: tp engines replace self.state with
        # device_put COPIES, so comparing fresh functional_state leaves
        # against self.state would read "changed" on every cached-engine
        # reuse — silently dropping the prefix cache and re-uploading
        # the whole parameter tree per generate() round
        self._state_src_leaves = jax.tree_util.tree_leaves(self.state)
        if cache_dtype is None:
            # match the activation dtype: the embedding weight's dtype is
            # what the residual stream (and so K/V) runs in
            probe = getattr(getattr(model, "gpt", model), "wte", None)
            cache_dtype = (jnp.dtype(probe.weight._array.dtype)
                           if probe is not None
                           else jnp.dtype(next(iter(self.state.values()
                                                    )).dtype))
        self._heads = cfg.num_attention_heads
        self._head_dim = cfg.hidden_size // cfg.num_attention_heads
        self._layers = cfg.num_hidden_layers
        self._cache_dtype = jnp.dtype(cache_dtype)
        # canonicalize through the cache's own gate so the engine, the
        # pool, and the autotune key can never disagree on the code
        # dtype ("fp8" shorthand included — ISSUE 20)
        _code_dt, _ = _cache_mod._as_kv_dtypes(kv_dtype)
        self.kv_dtype = (_code_dt if _code_dt is not None
                         else self._cache_dtype)
        self._quantized = _code_dt is not None
        # opt-in quant-error gauge: the flag is read ONCE here — it
        # changes the traced entries (an extra carried scalar + output),
        # so toggling the env var mid-process must not retrace
        self._track_qerr = bool(self._quantized and os.environ.get(
            "PADDLE_TPU_METRICS_KV_QUANT_ERROR", "0") == "1")
        self.spec_k = int(spec_k)
        self.spec_ngram = int(spec_ngram)
        if self.spec_k < 0:
            raise ValueError("spec_k must be >= 0")
        if self.spec_k and not self.paged:
            raise ValueError(
                "speculative decode runs on the paged engine (spec_k "
                "with paged=False is not supported — the slotted layout "
                "is the A/B baseline)")
        if self.spec_k >= self.max_len:
            raise ValueError("spec_k %d must be < max_len %d"
                             % (self.spec_k, self.max_len))
        # -- tensor parallelism (ISSUE 12) ---------------------------------
        self.tp = int(tp)
        if self.tp < 1:
            raise ValueError("tp must be >= 1")
        if self.tp > 1 and not self.paged:
            raise ValueError(
                "tensor-parallel decode runs on the paged engine (tp > 1 "
                "with paged=False is not supported — the slotted layout "
                "is the single-chip A/B baseline)")
        self.mesh = None
        self._param_shard_specs = {}
        self._entry_shardings = {}
        if device is not None and self.tp > 1:
            raise ValueError(
                "device= pins a SINGLE-chip engine; tp > 1 engines pick "
                "their own devices (the first tp of jax.devices())")
        if device is not None and not self.paged:
            raise ValueError(
                "device= runs on the paged engine (the slotted layout "
                "is the single-chip A/B baseline)")
        if self.tp > 1:
            devices = jax.devices()
            if len(devices) < self.tp:
                raise ValueError(
                    "tp=%d needs %d devices, have %d (CPU: set XLA_FLAGS="
                    "--xla_force_host_platform_device_count before the "
                    "backend initializes)"
                    % (self.tp, self.tp, len(devices)))
            if self._heads % self.tp:
                raise ValueError(
                    "tp=%d must divide num_attention_heads=%d (the KV "
                    "pool is partitioned over heads)"
                    % (self.tp, self._heads))
            # a PRIVATE single-axis mesh over the first tp devices — the
            # engine never mutates the process-global mesh; its traced
            # calls install this one via mesh_scope so the model's
            # with_sharding_constraint sites (incl. the head constraints
            # in the cache walk) resolve the serving topology
            self.mesh = Mesh(np.asarray(devices[:self.tp]), (MP_AXIS,))
        elif device is not None:
            # device pinning (ISSUE 15): a 1-device ('mp',) mesh commits
            # the pool, the parameters, and every entry's outputs to the
            # GIVEN device through the same jit-with-shardings machinery
            # the tp path uses (single-device jit outputs are uncommitted
            # in this jax, so "create the buffers there" would not
            # survive the first call) — role-split disaggregated serving
            # places its prefill engine on its own chip this way
            self.mesh = Mesh(np.asarray([device]), (MP_AXIS,))
        # -- collective–matmul overlap (ISSUE 20) --------------------------
        # resolved ONCE at construction (arg > scope > PADDLE_TPU_MP_OVERLAP
        # env) and pinned into every entry trace via _trace_scope, so the
        # compiled programs can never flip lowering mid-process.  Only
        # meaningful on a tp>1 mesh: the rings need a >=2-device 'mp' axis.
        self.overlap_comm = bool(_mp_overlap.enabled(overlap_comm)
                                 and self.tp > 1)
        if self.mesh is not None:
            self._param_shard_specs = self._collect_param_specs()
            self.state = self._shard_state(self.state)
        self._base_key = jax.random.key(int(seed))
        self._rng_step = 0
        # metric handles, fetched once (no-op singletons when disabled)
        self._m_pool = _metrics.gauge("serving.page_pool_used")
        self._m_cow = _metrics.counter("serving.cow_copies")
        self._m_qerr = _metrics.gauge("serving.kv_quant_error")
        self._m_tp = _metrics.gauge("serving.tp_degree")
        self._m_tp.set(self.tp)
        self._m_coll = _metrics.counter("serving.collective_bytes")
        # opt-in per-step collective-bytes accounting: priced ONCE per
        # entry from the compiled sharded program's HLO (an extra AOT
        # compile on first use — grad_norm-style env opt-in, read once)
        self._track_coll = bool(
            self.tp > 1 and os.environ.get(
                "PADDLE_TPU_METRICS_COLLECTIVES", "0") == "1")
        self._coll_price = {}
        # decode KV-read accounting (the bench's kv_bytes_per_token A/B):
        # per decode/verify step, `paged_rows` accrues the rows a
        # length-aware paged schedule reads (mapped pages, ONE sweep per
        # step however many tokens the step commits) vs `flat_rows`, the
        # slotted slots*max_len PER-TOKEN bound — so speculative steps
        # show the read amortization and int8 halves the per-row cost
        # (row_bytes accounts codes + scales)
        self.kv_stats = {"tokens": 0, "paged_rows": 0, "flat_rows": 0}
        # speculative accounting: steps = verify iterations, proposed =
        # k per active lane, accepted = accepted draft tokens (the
        # bench's accepted_tokens_per_step = accepted/steps — the EXTRA
        # tokens per verify iteration beyond the baseline one-per-slot)
        self.spec_stats = {"steps": 0, "proposed": 0, "accepted": 0}
        if self.paged:
            self._init_paged(cfg, page_size, num_pages, prefill_chunk,
                             donate, handoff_pages)
        else:
            self._init_slotted(cfg, min_bucket, donate)
        # tiered KV host cache (ISSUE 17): a bounded host-RAM LRU behind
        # the device pool.  Reclaimed (or explicitly cold) refcount-0
        # cached pages spill through kv_export; a later hash-hit
        # admission that misses the device cache pulls them back through
        # kv_import.  Off unless a budget is given (param wins over the
        # PADDLE_TPU_KV_HOST_BYTES env).
        self._host_tier = None
        self._kv_index = None     # ClusterPrefixIndex, attach_cluster_index
        self._spill_buf = None    # spill's OWN persistent export buffer:
                                  # the handoff buffer may be mid-transfer
                                  # (staged but not yet imported) when a
                                  # reclaim fires inside _alloc_dst, and
                                  # re-donating it would tear the splice
        self._m_host_bytes = _metrics.gauge("serving.kv_host_bytes")
        self._m_host_misses = _metrics.counter("serving.kv_host_misses")
        self._m_host_spill = _metrics.counter(
            "serving.kv_host_spilled_pages")
        if self.paged:
            from .kv_tier import HostPageTier, host_bytes_default
            budget = (int(kv_host_bytes) if kv_host_bytes is not None
                      else host_bytes_default())
            if budget > 0:
                self._host_tier = HostPageTier(budget)
                self._alloc.spill_hook = self._spill_page
        # black-box flight recorder: dumps collect this engine's state
        # summary (weakref — registration never pins the engine); the
        # HBM ledger prices this engine's KV pool the same way
        _flight.register_engine(self)
        from ..observability import hbm as _hbm
        _hbm.register_engine(self)

    def _kv_dtype_arg(self):
        # canonical dtype string ("int8" / "float8_e4m3fn") — the cache
        # gate and the autotune keys both parse it back via jnp.dtype
        return str(self.kv_dtype) if self._quantized else None

    def _cache_scale_args(self):
        return (self.cache.k_scale, self.cache.v_scale)

    # ------------------------------------------------------------------
    # tensor-parallel sharding (ISSUE 12) — tp=1 engines never enter any
    # of these paths; tp>1 is paged-only (validated in __init__)
    # ------------------------------------------------------------------

    def _collect_param_specs(self):
        """{state name: PartitionSpec} from the parameters' Megatron
        pspec annotations (``distributed/mp_layers.py`` layouts baked
        into ``models/gpt.py``), filtered to the serving mesh's axes —
        training annotations also name dp/sep axes this single-purpose
        ('mp',) mesh does not carry.  A pspec IS one PartitionSpec, not
        a tuple of them (the TrainStep lesson).  Raises on a sharded dim
        the TP degree does not divide: GSPMD would reject the uneven
        NamedSharding at dispatch anyway, but this names the parameter."""
        axis_names = set(self.mesh.axis_names)
        specs = {}
        for name, t in self.model.state_dict().items():
            spec = getattr(t, "pspec", None)
            if spec is None:
                specs[name] = PartitionSpec()
                continue
            kept = []
            for el in tuple(spec):
                if isinstance(el, str):
                    kept.append(el if el in axis_names else None)
                elif isinstance(el, (tuple, list)):
                    sub = tuple(a for a in el if a in axis_names)
                    kept.append(sub if sub else None)
                else:
                    kept.append(None)
            for dim, el in enumerate(kept):
                if el is not None and t.shape[dim] % self.tp:
                    raise ValueError(
                        "parameter %r dim %d (size %d) is mp-sharded "
                        "but not divisible by tp=%d"
                        % (name, dim, int(t.shape[dim]), self.tp))
            specs[name] = PartitionSpec(*kept)
        return specs

    def _sh(self, *spec):
        return NamedSharding(self.mesh, PartitionSpec(*spec))

    def _state_shardings(self):
        return {k: NamedSharding(self.mesh, self._param_shard_specs[k])
                for k in self.state}

    def _shard_state(self, state):
        """Place a freshly snapshotted parameter tree onto the serving
        mesh per its pspec annotations.  Required, not cosmetic: after
        training, ``functional_state`` leaves are committed to their
        training placement, and feeding them to the sharded entries'
        ``in_shardings`` raises a device-assignment mismatch instead of
        silently resharding (the ``refresh_state`` regression).  The
        identity for meshless engines; device-pinned (1-device mesh)
        engines place the tree on their device the same way."""
        if self.mesh is None:
            return state
        sh = {k: NamedSharding(self.mesh, self._param_shard_specs[k])
              for k in state}
        return {k: jax.device_put(v, sh[k]) for k, v in state.items()}

    def _jit_kwargs(self, entry):
        """The sharding kwargs a given entry's jit (and any AOT re-jit
        that must price the SAME program — ``cost_reports``) carries:
        one definition so the served and the priced program can never
        drift."""
        if entry not in self._entry_shardings:
            return {}
        ins, outs = self._entry_shardings[entry]
        return dict(in_shardings=ins, out_shardings=outs)

    def _trace_scope(self):
        """Mesh context for the compiled entries' traced calls: the
        model's with_sharding_constraint sites — incl. the head
        constraints on the cache walk — must resolve the SERVING
        topology, whatever the process-global mesh is.  tp=1 engines
        install ``None`` (not a no-op!): a leftover TRAINING mesh
        declaring 'mp' would otherwise turn the single-chip decode
        trace into an SPMD program over the training devices — the
        'tp=1 is byte-identical to the unsharded engine' contract must
        hold in mesh-laden processes too.  The overlap switch is pinned
        the same way: an engine built with overlap_comm=False stays
        monolithic even if PADDLE_TPU_MP_OVERLAP flips on later (and
        vice versa) — retraces always reproduce the first lowering."""
        return self._entry_scope()

    @contextlib.contextmanager
    def _entry_scope(self):
        with _mesh.mesh_scope(self.mesh), \
                _mp_overlap.overlap_scope(self.overlap_comm):
            yield

    def _collective_price(self, entry):
        """Collective bytes ONE step of ``entry`` moves over the mesh,
        priced lazily from the compiled sharded program's partitioned
        HLO (``observability.costs.collective_stats``) and cached — the
        per-step counter increments by this constant."""
        price = self._coll_price.get(entry)
        if price is None:
            from ..observability import costs as _costs
            report = self.cost_reports(only=(entry,))[entry]
            price = int(report.collective_bytes or 0)
            self._coll_price[entry] = price
        return price

    # ------------------------------------------------------------------
    # slotted mode (PR 5 layout — kept for A/B and parity)
    # ------------------------------------------------------------------

    def _init_slotted(self, cfg, min_bucket, donate):
        self.buckets = prefill_buckets_for(self.max_len, min_bucket)
        self.prompt_cap = self.buckets[-1]
        model, k_max = self.model, self.top_k_max
        track_qerr = self._track_qerr
        self.cache = SlottedKVCache.create(
            self.num_slots, self._layers, self.max_len, self._heads,
            self._head_dim, self._cache_dtype,
            kv_dtype=self._kv_dtype_arg())

        def decode_fn(state, cache_k, cache_v, k_scale, v_scale, lengths,
                      tokens, active, key, temps, top_ks, top_ps):
            """One batched decode iteration over every slot."""
            model.eval()   # trace-time: cached decode is inference-only
            view = DecodeView(
                SlottedKVCache(cache_k, cache_v, lengths,
                               k_scale=k_scale, v_scale=v_scale),
                active=active, track_quant_err=track_qerr)
            from ..jit import functional_call
            (logits, _), _ = functional_call(model, state, Tensor(tokens),
                                             cache=view)
            logits = logits[:, -1, :]
            next_tok = sample(logits, key, temps, top_ks, top_ps, k_max)
            out = view.finalize()
            return (next_tok, logits, out.k, out.v, out.k_scale,
                    out.v_scale, out.lengths, view.quant_err)

        def prefill_fn(state, tokens, slot, true_len, cache_k, cache_v,
                       k_scale, v_scale, lengths, key, temp, top_k,
                       top_p):
            """Prefill one bucketed sequence into ``slot`` and sample the
            first generated token from the last REAL position."""
            model.eval()
            view = PrefillView(
                SlottedKVCache(cache_k, cache_v, lengths,
                               k_scale=k_scale, v_scale=v_scale),
                slot, true_len)
            from ..jit import functional_call
            (logits, _), _ = functional_call(model, state, Tensor(tokens),
                                             cache=view)
            last = jax.lax.dynamic_slice(
                logits, (jnp.zeros((), jnp.int32),
                         true_len - jnp.ones((), jnp.int32),
                         jnp.zeros((), jnp.int32)),
                (1, 1, logits.shape[-1]))[:, 0, :]
            tok = sample(last, key, temp[None], top_k[None], top_p[None],
                         k_max)[0]
            out = view.finalize()
            return (tok, last[0], out.k, out.v, out.k_scale, out.v_scale,
                    out.lengths)

        # hooks for the trace-tier audit (TPU501-505): the registry lowers
        # the un-jitted fns with keep_unused=True at these donate_argnums
        q = self._quantized
        self._decode_fn = decode_fn
        self._decode_donate_argnums = \
            ((1, 2, 5) + ((3, 4) if q else ())) if donate else ()
        self._prefill_fn = prefill_fn
        self._prefill_donate_argnums = \
            ((4, 5, 8) + ((6, 7) if q else ())) if donate else ()
        # recompile watchdog (observability.watchdog): decode is the
        # compile-ONCE entry — a second program is PR 5's silent-retrace
        # bug class and warns (raises under PADDLE_TPU_STRICT_COMPILE=1);
        # prefill's budget is its bucket count
        from ..observability.watchdog import watch
        self._decode = watch(
            "serving.decode",
            jax.jit(decode_fn, donate_argnums=self._decode_donate_argnums),
            expected=1)
        self._prefill = watch(
            "serving.prefill",
            jax.jit(prefill_fn,
                    donate_argnums=self._prefill_donate_argnums),
            expected=len(self.buckets))

    # ------------------------------------------------------------------
    # paged mode (ISSUE 7 layout — the default)
    # ------------------------------------------------------------------

    def _init_paged(self, cfg, page_size, num_pages, prefill_chunk,
                    donate, handoff_pages=4):
        self.page_size = min(int(page_size), self.max_len)
        self.max_pages = -(-self.max_len // self.page_size)
        # default pool: capacity parity with the slotted layout (every
        # slot can reach max_len).  Size it SMALLER to actually save
        # memory when typical lengths are short / prefixes shared.
        self.num_pages = int(num_pages if num_pages is not None
                             else self.num_slots * self.max_pages)
        self.prefill_chunk = int(prefill_chunk if prefill_chunk is not None
                                 else min(64, self.max_len))
        self.prompt_cap = self.max_len
        # disaggregated prefill/decode handoff (ISSUE 15): pages move
        # between role-split engines' pools through ONE fixed-size
        # transfer buffer of `handoff_pages` pages — a fixed chunk shape
        # keeps kv_export/kv_import each a single static program, and
        # the scheduler interleaves chunks between decode steps
        self.handoff_pages = max(1, min(int(handoff_pages),
                                        self.max_pages))
        self._handoff_buf = None       # lazily allocated, donated in
                                       # place by every kv_export call
        self._alloc = PageAllocator(self.num_pages, self.num_slots,
                                    self.max_pages, self.page_size,
                                    tracer=self._tracer)
        self._len_host = np.zeros((self.num_slots,), np.int64)
        # bumped by free_slot(): a speculative verify step consumed
        # AFTER its lane was freed (the overlapped loop's overshoot
        # step) must not advance the zeroed mirror — the in-program
        # advance landed in pages that free_slot already reclaimed
        self._slot_epoch = np.zeros((self.num_slots,), np.int64)
        self.cache = PagedKVCache.create(
            self.num_pages, self._layers, self.page_size, self._heads,
            self._head_dim, self.num_slots, self.max_pages,
            self._cache_dtype, kv_dtype=self._kv_dtype_arg())
        if self.mesh is not None:
            # the pool lives HEAD-SHARDED from birth: each chip holds
            # 1/tp of the KV bytes (the whole point), and the sharded
            # entries' donated aliasing needs matching input placement.
            # A device-pinned engine (1-device mesh) takes the same path
            # — 'sharding' there just means committed placement.
            c = self.cache
            pool = self._sh(None, None, None, MP_AXIS, None)
            scale = self._sh(None, None, None, MP_AXIS)
            rep = self._sh()
            self.cache = PagedKVCache(
                jax.device_put(c.k, pool), jax.device_put(c.v, pool),
                jax.device_put(c.page_table, rep),
                jax.device_put(c.lengths, rep),
                k_scale=(None if c.k_scale is None
                         else jax.device_put(c.k_scale, scale)),
                v_scale=(None if c.v_scale is None
                         else jax.device_put(c.v_scale, scale)))
        # hoist everything the traced closures need: capturing `self`
        # would pin the whole engine (buffers included) to the jitted fns
        model, k_max, L_max = self.model, self.top_k_max, self.max_len
        track_qerr = self._track_qerr
        quantized = self._quantized
        tp_deg = self.tp

        def decode_fn(state, cache_k, cache_v, k_scale, v_scale, lengths,
                      page_table, tokens, active, key, temps, top_ks,
                      top_ps):
            """One batched decode iteration over every slot (paged)."""
            model.eval()
            view = PagedDecodeView(
                PagedKVCache(cache_k, cache_v, page_table, lengths,
                             k_scale=k_scale, v_scale=v_scale),
                active=active, max_len=L_max, track_quant_err=track_qerr,
                tp=tp_deg)
            from ..jit import functional_call
            (logits, _), _ = functional_call(model, state, Tensor(tokens),
                                             cache=view)
            logits = logits[:, -1, :]
            next_tok = sample(logits, key, temps, top_ks, top_ps, k_max)
            out = view.finalize()
            return (next_tok, logits, out.k, out.v, out.k_scale,
                    out.v_scale, out.lengths, view.quant_err)

        def verify_fn(state, cache_k, cache_v, k_scale, v_scale, lengths,
                      page_table, tokens, active, key, temps, top_ks,
                      top_ps):
            """The speculative batched verify: ``tokens: (slots, k+1)``
            = [last committed token, draft_1..draft_k].  ONE forward
            scores every position; accept/resample and the rejected-
            draft length rollback run in-program."""
            model.eval()
            view = PagedDecodeView(
                PagedKVCache(cache_k, cache_v, page_table, lengths,
                             k_scale=k_scale, v_scale=v_scale),
                active=active, max_len=L_max, track_quant_err=track_qerr,
                tp=tp_deg)
            from ..jit import functional_call
            (logits, _), _ = functional_call(model, state, Tensor(tokens),
                                             cache=view)
            logits = _unwrap(logits).astype(jnp.float32)    # (S, k+1, V)
            # acceptance never reaches past the cache's append capacity:
            # position j's logits are valid only while n + j < max_len
            a_cap = jnp.asarray(L_max, jnp.int32) \
                - jnp.ones((), jnp.int32) - lengths
            emitted, counts = spec_accept(logits, _unwrap(tokens), key,
                                          temps, top_ks, top_ps, k_max,
                                          max_accept=a_cap)
            # rejected drafts roll back IN-PROGRAM: lengths advance by
            # accepted+1 only; the dead tail-page rows beyond are
            # overwritten by the next step's appends
            out = view.finalize(advance=counts)
            return (emitted, counts, logits, out.k, out.v, out.k_scale,
                    out.v_scale, out.lengths, view.quant_err)

        def prefill_chunk_fn(state, tokens, slot, n_before, n_valid,
                             cache_k, cache_v, k_scale, v_scale, lengths,
                             page_table, key, temp, top_k, top_p):
            """One fixed-size chunk of one slot's prompt.  Samples a
            token from the chunk's LAST REAL position — meaningful (and
            used) only on the final chunk."""
            model.eval()
            view = PagedPrefillChunkView(
                PagedKVCache(cache_k, cache_v, page_table, lengths,
                             k_scale=k_scale, v_scale=v_scale),
                slot, n_before, n_valid, tp=tp_deg)
            from ..jit import functional_call
            (logits, _), _ = functional_call(model, state, Tensor(tokens),
                                             cache=view)
            last = jax.lax.dynamic_slice(
                logits, (jnp.zeros((), jnp.int32),
                         n_valid - jnp.ones((), jnp.int32),
                         jnp.zeros((), jnp.int32)),
                (1, 1, logits.shape[-1]))[:, 0, :]
            tok = sample(last, key, temp[None], top_k[None], top_p[None],
                         k_max)[0]
            out = view.finalize()
            return (tok, last[0], out.k, out.v, out.k_scale, out.v_scale,
                    out.lengths)

        def cow_copy_fn(cache_k, cache_v, k_scale, v_scale, src, dst):
            """Copy one page (all layers — scale rows included for the
            int8 pool) src -> dst: the copy-on-write that un-shares a
            prefix page before a write targets it."""
            src = jnp.asarray(src, jnp.int32)
            dst = jnp.asarray(dst, jnp.int32)
            k_page = jax.lax.dynamic_index_in_dim(cache_k, src, axis=0)
            v_page = jax.lax.dynamic_index_in_dim(cache_v, src, axis=0)
            zero = jnp.zeros((), jnp.int32)
            start = (dst, zero, zero, zero, zero)
            cache_k = jax.lax.dynamic_update_slice(cache_k, k_page, start)
            cache_v = jax.lax.dynamic_update_slice(cache_v, v_page, start)
            if quantized:
                ks_page = jax.lax.dynamic_index_in_dim(k_scale, src,
                                                       axis=0)
                vs_page = jax.lax.dynamic_index_in_dim(v_scale, src,
                                                       axis=0)
                k_scale = jax.lax.dynamic_update_slice(k_scale, ks_page,
                                                       start[:-1])
                v_scale = jax.lax.dynamic_update_slice(v_scale, vs_page,
                                                       start[:-1])
            return cache_k, cache_v, k_scale, v_scale

        def kv_export_fn(cache_k, cache_v, k_scale, v_scale, buf_k,
                         buf_v, buf_ks, buf_vs, page_ids):
            """Gather up to ``handoff_pages`` pool pages (all layers,
            scale rows included for the int8 pool) into the dense
            transfer buffer — the prefill side of a disaggregated
            handoff.  The buffer operands are DONATED: every chunk
            reuses the same storage instead of allocating a fresh
            multi-page buffer per transfer (TPU502 verifies the
            aliasing materializes).  ``page_ids`` entries past the
            valid count are padded with 0 — they gather page 0's bytes,
            which the import side's scatter drops."""
            ids = jnp.asarray(page_ids, jnp.int32)
            # plain [] gather keeps the index math i32 (the PR-1
            # embedding-gather discipline); ids are host-validated
            out_k = cache_k[ids]
            out_v = cache_v[ids]
            out_ks = out_vs = None
            if quantized:
                out_ks = k_scale[ids]
                out_vs = v_scale[ids]
            return out_k, out_v, out_ks, out_vs

        def kv_import_fn(cache_k, cache_v, k_scale, v_scale, buf_k,
                         buf_v, buf_ks, buf_vs, dst_ids):
            """Scatter a staged transfer buffer into freshly allocated
            pages of THIS pool — the decode side of a disaggregated
            handoff.  The pool operands are donated (in-place update,
            like every other entry); ``dst_ids`` pad entries carry
            ``num_pages``, an out-of-bounds id the default scatter mode
            drops (the paged_scatter discipline)."""
            ids = jnp.asarray(dst_ids, jnp.int32)
            cache_k = cache_k.at[ids].set(buf_k)
            cache_v = cache_v.at[ids].set(buf_v)
            if quantized:
                k_scale = k_scale.at[ids].set(buf_ks)
                v_scale = v_scale.at[ids].set(buf_vs)
            return cache_k, cache_v, k_scale, v_scale

        q = self._quantized
        self._decode_fn = decode_fn
        self._decode_donate_argnums = \
            ((1, 2, 5) + ((3, 4) if q else ())) if donate else ()
        self._verify_fn = verify_fn
        self._verify_donate_argnums = self._decode_donate_argnums
        self._prefill_chunk_fn = prefill_chunk_fn
        self._prefill_chunk_donate_argnums = \
            ((5, 6, 9) + ((7, 8) if q else ())) if donate else ()
        self._cow_fn = cow_copy_fn
        self._cow_donate_argnums = \
            ((0, 1) + ((2, 3) if q else ())) if donate else ()
        self._kv_export_fn = kv_export_fn
        self._kv_export_donate_argnums = \
            ((4, 5) + ((6, 7) if q else ())) if donate else ()
        self._kv_import_fn = kv_import_fn
        self._kv_import_donate_argnums = \
            ((0, 1) + ((2, 3) if q else ())) if donate else ()
        if self.mesh is not None:
            # every entry's SHARDED TWIN is the same traced fn jitted
            # with explicit in/out shardings: pool (+ scale pools)
            # head-sharded, everything that varies per step replicated.
            # Donated pool inputs and their outputs carry the SAME
            # sharding, so XLA's input→output aliasing materializes per
            # shard (TPU502 audits the lowered sharded entries).  The
            # scale slots are None-sharded when unquantized (the args
            # are None) and the quant_err output likewise when tracking
            # is off — None means "no leaves here", not replication.
            rep = self._sh()
            pool = self._sh(None, None, None, MP_AXIS, None)
            scale = self._sh(None, None, None, MP_AXIS) if q else None
            qe = rep if self._track_qerr else None
            state_sh = self._state_shardings()
            decode_in = (state_sh, pool, pool, scale, scale, rep, rep,
                         rep, rep, rep, rep, rep, rep)
            # the handoff transfer buffer shares the pool's head layout
            # (axis 3), so a tp engine's export/import moves only its
            # own head shard; on a 1-device (pinned) mesh it is simply
            # committed placement
            ho_in = (pool, pool, scale, scale, pool, pool, scale, scale,
                     rep)
            self._entry_shardings = {
                "serving.decode": (
                    decode_in,
                    (rep, rep, pool, pool, scale, scale, rep, qe)),
                "serving.spec_verify": (
                    decode_in,
                    (rep, rep, rep, pool, pool, scale, scale, rep, qe)),
                "serving.prefill_chunk": (
                    (state_sh, rep, rep, rep, rep, pool, pool, scale,
                     scale, rep, rep, rep, rep, rep, rep),
                    (rep, rep, pool, pool, scale, scale, rep)),
                "serving.cow_copy": (
                    (pool, pool, scale, scale, rep, rep),
                    (pool, pool, scale, scale)),
                "serving.kv_export": (ho_in, (pool, pool, scale, scale)),
                "serving.kv_import": (ho_in, (pool, pool, scale, scale)),
            }

        def _jit(entry, fn, donate_argnums):
            return jax.jit(fn, donate_argnums=donate_argnums,
                           **self._jit_kwargs(entry))

        from ..observability.watchdog import watch
        self._decode = watch(
            "serving.decode",
            _jit("serving.decode", decode_fn,
                 self._decode_donate_argnums),
            expected=1)
        self._verify = None
        if self.spec_k:
            # fixed draft length k => ONE static verify program, full
            # stop — all-accept and all-reject are traced-value paths
            self._verify = watch(
                "serving.spec_verify",
                _jit("serving.spec_verify", verify_fn,
                     self._verify_donate_argnums),
                expected=1)
        # ONE chunk shape => ONE program (vs log2(max_len) buckets)
        self._prefill_chunk = watch(
            "serving.prefill_chunk",
            _jit("serving.prefill_chunk", prefill_chunk_fn,
                 self._prefill_chunk_donate_argnums),
            expected=1)
        self._cow = watch(
            "serving.cow_copy",
            _jit("serving.cow_copy", cow_copy_fn,
                 self._cow_donate_argnums),
            expected=1)
        # fixed chunk shape => ONE program each for the disaggregated
        # page handoff (ISSUE 15): export on the prefill role, import on
        # the decode role — an engine that never hands off never
        # compiles them (the jit objects are free)
        self._kv_export = watch(
            "serving.kv_export",
            _jit("serving.kv_export", kv_export_fn,
                 self._kv_export_donate_argnums),
            expected=1)
        self._kv_import = watch(
            "serving.kv_import",
            _jit("serving.kv_import", kv_import_fn,
                 self._kv_import_donate_argnums),
            expected=1)

    # -- host-side API -----------------------------------------------------

    def refresh_state(self, state=None):
        """Re-snapshot the model's parameters (same shapes/dtypes — no
        recompile).  Call after training between generate rounds.  When
        any parameter actually CHANGED, paged engines also drop the
        prefix cache: its pages hold K/V computed under the old
        parameters, and a hash hit would silently splice stale cache
        into a fresh prompt.  Unchanged re-snapshots (every cached-
        engine reuse via ``engine_for``) keep the cache — jax arrays are
        immutable, so leaf identity is an exact change test."""
        new = state if state is not None else \
            self.model.functional_state()
        # change test against the UNSHARDED source leaves (identity —
        # jax arrays are immutable): tp engines hold device_put COPIES
        # in self.state, so comparing against those would read every
        # unchanged re-snapshot as a change — dropping the prefix cache
        # and re-uploading the whole tree per cached-engine reuse
        old_leaves = self._state_src_leaves
        new_leaves = jax.tree_util.tree_leaves(new)
        changed = (len(old_leaves) != len(new_leaves)
                   or any(a is not b
                          for a, b in zip(new_leaves, old_leaves)))
        if not changed:
            # every engine_for reuse lands here: keep the (possibly
            # sharded) placed state AND the prefix cache
            return
        self._state_src_leaves = new_leaves
        if self.paged:
            self._alloc.drop_prefix_cache()
            if self._host_tier is not None:
                # spilled rows were computed under the OLD parameters —
                # a host hit would splice stale cache exactly like the
                # device-hash hit the drop above prevents
                if self._kv_index is not None:
                    self._kv_index.withdraw(self._host_tier.digests())
                self._host_tier.clear()
                self._m_host_bytes.set(0)
        # tensor-parallel engines must RE-SHARD the changed snapshot:
        # post-training leaves are committed to their training
        # placement, and the sharded entries' in_shardings raise a
        # device-assignment mismatch on a foreign device set instead of
        # silently resharding (regression-tested); _shard_state is the
        # identity for tp=1
        self.state = self._shard_state(new)

    def reset(self):
        """Free every slot (paged: pages return to the pool and prefix
        hashes are purged; slot contents are overwritten lazily)."""
        self.kv_stats = {"tokens": 0, "paged_rows": 0, "flat_rows": 0}
        self.spec_stats = {"steps": 0, "proposed": 0, "accepted": 0}
        c = self.cache
        if self.paged:
            self._alloc.reset()
            self._len_host[:] = 0
            self._m_pool.set(0)
            lengths = jnp.zeros((self.num_slots,), jnp.int32)
            if self.mesh is not None:
                # keep the lengths COMMITTED-replicated like every other
                # call's (init device_puts, the sharded entries' outputs
                # are committed): jit keys on commitment, so a fresh
                # uncommitted zeros here would open a second cache entry
                # on the next prefill_chunk — a compile-once violation
                # the strict watchdog turns fatal mid-bench
                lengths = jax.device_put(lengths, self._sh())
            self.cache = PagedKVCache(
                c.k, c.v, self._alloc.device_table(), lengths,
                k_scale=c.k_scale, v_scale=c.v_scale)
        else:
            self.cache = SlottedKVCache(
                c.k, c.v, jnp.zeros((self.num_slots,), jnp.int32),
                k_scale=c.k_scale, v_scale=c.v_scale)

    def reseed(self, seed):
        """Restart the threaded key stream: after ``reseed(s)`` the next
        prefill/decode sequence reproduces a fresh engine built with
        ``seed=s`` (generate() calls this so its ``seed=`` argument means
        the same thing on a cached engine as on a new one)."""
        self._base_key = jax.random.key(int(seed))
        self._rng_step = 0

    def bucket_for(self, n):
        if self.paged:
            raise AttributeError("paged engines have no prefill buckets "
                                 "(one chunk program) — use prefill_chunk")
        for b in self.buckets:
            if n <= b:
                return b
        raise ValueError(
            "prompt length %d exceeds the largest prefill bucket %d "
            "(max_len=%d)" % (n, self.buckets[-1], self.max_len))

    def _next_key(self):
        self._rng_step += 1
        return jax.random.fold_in(self._base_key, self._rng_step)

    def _set_quant_err(self, qerr):
        if qerr is not None:
            # opt-in: one device sync per step (same caveat as the
            # train.grad_norm gauge)
            self._m_qerr.set(float(np.asarray(qerr)))

    def _dispatch_span(self, name, entry, t0_ns, c0):
        """Engine-lane span for one compiled-entry dispatch, carrying the
        watchdog's compile-count delta: a nonzero ``compiles`` attr on a
        steady-state step IS the silent-retrace bug class, now visible
        at the exact call in the trace timeline."""
        c1 = int(entry.compile_count)
        self._tracer.add_span(name, t0_ns, time.perf_counter_ns(),
                              compile_count=c1, compiles=c1 - c0)

    # -- paged page bookkeeping (host side) --------------------------------

    def _set_length(self, slot, n):
        """Host-side length write (admission bookkeeping — off the
        per-token hot path)."""
        self._len_host[slot] = int(n)
        c = self.cache
        self.cache = PagedKVCache(
            c.k, c.v, c.page_table,
            c.lengths.at[int(slot)].set(int(n)),
            k_scale=c.k_scale, v_scale=c.v_scale)

    def free_slot(self, slot):
        """Release a retired slot's pages (refcounted) and zero its
        length.  Stale page-table entries are cleared so the decode
        program's (dropped) inactive-lane writes can never target a
        reassigned page."""
        if not self.paged:
            return
        self._alloc.free_slot(int(slot))
        self._set_length(int(slot), 0)
        self._slot_epoch[int(slot)] += 1
        self._m_pool.set(self._alloc.pages_used())

    def unshared_pages(self, slot):
        """Pages ONLY this slot maps — the scheduler's refcount-aware
        eviction score (freeing the max-unshared slot returns the most
        pages to the pool)."""
        return self._alloc.unshared_pages(int(slot)) if self.paged else 0

    def pages_free(self):
        return self._alloc.pages_free() if self.paged else 0

    def _cow_page(self, slot, idx):
        """Copy-on-write ``slot``'s page-table entry ``idx`` to a fresh
        private page (raises PagePoolExhausted when the pool is dry)."""
        new_pid = self._alloc.alloc()
        try:
            old_pid = int(self._alloc.table[int(slot), int(idx)])
            c = self.cache
            tr_on = self._tracer.enabled
            if tr_on:
                c0 = self._cow.compile_count
                t0_ns = time.perf_counter_ns()
            with x64_scope(False), self._trace_scope():
                k, v, ks, vs = self._cow(c.k, c.v, c.k_scale, c.v_scale,
                                         jnp.asarray(old_pid, jnp.int32),
                                         jnp.asarray(new_pid, jnp.int32))
            if tr_on:
                self._dispatch_span("engine.cow_copy", self._cow, t0_ns,
                                    c0)
        except Exception:
            # a torn COW dispatch must not strand the fresh page: the
            # pool outlives the failed step (the scheduler's tear paths
            # free the slot and keep serving the other slots)
            self._alloc._release(new_pid)
            raise
        self._alloc.remap(int(slot), int(idx), new_pid)
        self.cache = PagedKVCache(k, v, c.page_table, c.lengths,
                                  k_scale=ks, v_scale=vs)
        self._m_cow.inc()

    def _ensure_write_range(self, slot, start, stop):
        """Map (allocating) every page covering positions [start, stop)
        of ``slot`` and copy-on-write any shared page the range writes
        into.  Raises PagePoolExhausted if the pool is dry — the
        scheduler evicts a victim and retries."""
        P = self.page_size
        for idx in range(int(start) // P, (int(stop) - 1) // P + 1):
            if not self._alloc.mapped[slot, idx]:
                self._alloc.map(slot, idx, self._alloc.alloc())
            elif self._alloc.needs_cow(slot, idx):
                self._cow_page(slot, idx)
        self._m_pool.set(self._alloc.pages_used())

    def ensure_decode_ready(self, active, steps=1):
        """Pre-step page bookkeeping for one batched decode (or verify:
        ``steps = spec_k + 1`` append positions per slot): every active
        slot's append range must land in mapped, PRIVATE pages.
        Returns the first slot index that could not get a page (pool
        dry — evict and retry), or None when ready."""
        if not self.paged:
            return None
        steps = int(steps)
        for i, on in enumerate(active):
            if not on:
                continue
            p = int(self._len_host[i])
            if p >= self.max_len:
                continue        # scheduler retires this slot (cache_full)
            try:
                self._ensure_write_range(i, p, min(p + steps,
                                                   self.max_len))
            except PagePoolExhausted:
                return i
        return None

    # -- prefill -----------------------------------------------------------

    def prefill_begin(self, slot, token_ids, temperature=1.0, top_k=0,
                      top_p=1.0) -> PrefillTask:
        """Start admitting ``token_ids`` into ``slot``: map any
        hash-matched prefix pages (capped at n-1 tokens so the final
        token always runs through the chunk program and produces the
        first-token logits), then return the task whose chunks
        :meth:`prefill_step` advances."""
        if not self.paged:
            raise RuntimeError("chunked prefill is the paged path; "
                               "slotted engines use prefill()")
        ids = np.asarray(token_ids, np.int32).reshape(-1)
        n = int(ids.size)
        slot = int(slot)
        if n < 1:
            raise ValueError("empty prompt")
        if n > self.max_len:
            raise ValueError("prompt length %d > max_len %d"
                             % (n, self.max_len))
        if self._alloc.slot_pages(slot) or self._len_host[slot]:
            raise RuntimeError("slot %d admitted without free_slot()"
                               % slot)
        shared_pages, covered = self._alloc.lookup_prefix(ids)
        covered = min(covered, n - 1)
        # map only the pages the capped prefix actually covers (a capped
        # full hit keeps its tail page: its rows [.., n-1) stay valid
        # cache and the final chunk's write copy-on-writes it)
        P = self.page_size
        n_map = -(-covered // P) if covered else 0
        for idx in range(n_map):
            self._alloc.share(slot, idx, shared_pages[idx])
        self._set_length(slot, covered)
        self._m_pool.set(self._alloc.pages_used())
        return PrefillTask(slot=slot, ids=ids, pos=covered,
                           temperature=float(temperature),
                           top_k=int(top_k), top_p=float(top_p),
                           shared_tokens=covered, shared_pages=n_map)

    def prefill_step(self, task: PrefillTask, sync: bool = True) -> bool:
        """Run ONE chunk of an admission; returns True when the prompt
        is fully prefilled (``task.first_token``/``task.last_logits``
        are then set).  Raises PagePoolExhausted when the chunk's pages
        cannot be mapped — the scheduler evicts a victim and retries.

        ``sync=False`` leaves the final chunk's sampled token as the
        DEVICE array ``task.first_token_dev`` instead of blocking on
        ``int(tok)`` — the disaggregated scheduler polls
        ``.is_ready()`` between decode steps so a prefill-engine chunk
        never stalls a decode dispatch (the role-isolation contract);
        the colocated path keeps the synchronous default."""
        if task.done:
            return True
        n = int(task.ids.size)
        n_valid = min(self.prefill_chunk, n - task.pos)
        self._ensure_write_range(task.slot, task.pos, task.pos + n_valid)
        padded = np.zeros((1, self.prefill_chunk), np.int32)
        padded[0, :n_valid] = task.ids[task.pos:task.pos + n_valid]
        # only the FINAL chunk's sample is used, so only it may consume
        # a key from the threaded stream: the chunk COUNT depends on
        # prefix-cache state (a hit collapses the admission to one
        # 1-token chunk), and a per-chunk draw would shift every later
        # sample's key — generate(seed=s) must reproduce on a cached
        # engine (tested).  Non-final chunks get the never-used step-0
        # fold (_rng_step starts at 1, so it collides with nothing).
        final = task.pos + n_valid >= n
        key = (self._next_key() if final
               else jax.random.fold_in(self._base_key, 0))
        tr_on = self._tracer.enabled
        if tr_on:
            c0 = self._prefill_chunk.compile_count
            t0_ns = time.perf_counter_ns()
        # x64_scope(False) covers the (first-call) TRACE: the serving
        # programs carry no s64/f64 — jax.random's counters and gather
        # index widening follow the global x64 default otherwise (same
        # discipline as the Pallas kernel entries; asserted over the
        # compiled HLO by tests/test_serving.py)
        with x64_scope(False), _eval_scope(self.model), \
                self._trace_scope():
            tok, logits, k, v, ks, vs, lengths = self._prefill_chunk(
                self.state, jnp.asarray(padded),
                jnp.asarray(task.slot, jnp.int32),
                jnp.asarray(task.pos, jnp.int32),
                jnp.asarray(n_valid, jnp.int32),
                self.cache.k, self.cache.v, *self._cache_scale_args(),
                self.cache.lengths, self._alloc.device_table(), key,
                jnp.asarray(task.temperature, jnp.float32),
                jnp.asarray(min(task.top_k, self.top_k_max), jnp.int32),
                jnp.asarray(task.top_p, jnp.float32))
        if tr_on:
            self._dispatch_span("engine.prefill_chunk",
                                self._prefill_chunk, t0_ns, c0)
        self.cache = PagedKVCache(k, v, self._alloc.device_table(),
                                  lengths, k_scale=ks, v_scale=vs)
        task.pos += n_valid
        task.chunks_run += 1
        self._len_host[task.slot] = task.pos
        if task.pos >= n:
            task.done = True
            if sync:
                task.first_token = int(tok)
            else:
                task.first_token_dev = tok
            task.last_logits = logits
            # publish this prompt's pages for later admissions to share
            servable = self._alloc.register_prefix(task.slot, task.ids)
            if self._kv_index is not None and servable:
                self._kv_index.offer(servable)
        return task.done

    def prefill(self, slot, token_ids, temperature=1.0, top_k=0,
                top_p=1.0):
        """Admit ``token_ids`` (1-D) into ``slot``; returns the sampled
        first token (int) and the last-position logits (a jax array,
        (vocab,) — left on device; np.asarray() it if needed host-side).

        Paged mode: runs every chunk back to back (the scheduler uses
        :meth:`prefill_begin`/:meth:`prefill_step` to interleave chunks
        with decode instead)."""
        if self.paged:
            task = self.prefill_begin(slot, token_ids, temperature, top_k,
                                      top_p)
            while not self.prefill_step(task):
                pass
            return task.first_token, task.last_logits
        ids = np.asarray(token_ids, np.int32).reshape(-1)
        n = int(ids.size)
        if n < 1:
            raise ValueError("empty prompt")
        if n > self.max_len:
            raise ValueError("prompt length %d > max_len %d"
                             % (n, self.max_len))
        bucket = self.bucket_for(n)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :n] = ids
        tr_on = self._tracer.enabled
        if tr_on:
            c0 = self._prefill.compile_count
            t0_ns = time.perf_counter_ns()
        # x64/eval scopes: see prefill_step()
        with x64_scope(False), _eval_scope(self.model), \
                self._trace_scope():
            tok, logits, k, v, ks, vs, lengths = self._prefill(
                self.state, jnp.asarray(padded),
                jnp.asarray(slot, jnp.int32),
                jnp.asarray(n, jnp.int32), self.cache.k, self.cache.v,
                *self._cache_scale_args(),
                self.cache.lengths, self._next_key(),
                jnp.asarray(temperature, jnp.float32),
                jnp.asarray(min(int(top_k), self.top_k_max), jnp.int32),
                jnp.asarray(top_p, jnp.float32))
        if tr_on:
            self._dispatch_span("engine.prefill", self._prefill, t0_ns, c0)
        self.cache = SlottedKVCache(k, v, lengths, k_scale=ks, v_scale=vs)
        return int(tok), logits

    # -- decode ------------------------------------------------------------

    def _token_operand(self, tokens):
        """The decode entries' ``(S, 1)`` token operand.  Host arrays
        take the PR-5 path; a jax array — the previous step's sampled-
        token output, threaded back by the overlapped scheduler loop
        without a host round-trip — is reshaped eagerly.  Single-device
        jit outputs are UNCOMMITTED in this jax, so both spellings hit
        the SAME jit cache entry (compile-once holds across the mix —
        tested); tensor-parallel engines instead commit the host path
        onto the mesh so it matches the sharded outputs' placement (the
        PR-11 reset lesson: jit keys on commitment there)."""
        if isinstance(tokens, jax.Array):
            return jnp.reshape(tokens, (self.num_slots, 1))
        toks = np.asarray(tokens, np.int32).reshape(self.num_slots, 1)
        if self.mesh is not None:
            return jax.device_put(toks, self._sh())
        return jnp.asarray(toks)

    def decode_submit(self, tokens, active, temperature, top_k, top_p,
                      pages_ready=False) -> InflightDecode:
        """Dispatch one batched decode step WITHOUT fetching the sampled
        tokens: the returned :class:`InflightDecode` holds device-array
        futures only, so the call returns as soon as jax has enqueued
        the compiled program — the overlapped loop's *dispatch* half.
        ``tokens`` is a host array of last committed tokens, or a device
        ``(S,)`` int32 array threaded from the previous step's output.
        Host-visible bookkeeping that is deterministic at dispatch (the
        length mirror, the KV read accounting) happens HERE, so a
        sync ``decode()`` and a submit+fetch pair are byte-equivalent."""
        active_np = np.asarray(active, bool).reshape(self.num_slots)
        if self.paged and not pages_ready:
            blocked = self.ensure_decode_ready(active_np)
            if blocked is not None:
                raise PagePoolExhausted(
                    "no free page for slot %d's append — evict a slot "
                    "(the scheduler does this refcount-aware)" % blocked)
        tr_on = self._tracer.enabled
        if tr_on:
            c0 = self._decode.compile_count
            t0_ns = time.perf_counter_ns()
        # x64/eval scopes: see prefill_step() — keep the traced program
        # s64/f64-free and the caller's train/eval mode untouched
        with x64_scope(False), _eval_scope(self.model), \
                self._trace_scope():
            # both layouts share one call shape; paged inserts the page
            # table after lengths (donated argnums are identical)
            table = (self._alloc.device_table(),) if self.paged else ()
            tok, logits, k, v, ks, vs, lengths, qerr = self._decode(
                self.state, self.cache.k, self.cache.v,
                *self._cache_scale_args(), self.cache.lengths, *table,
                self._token_operand(tokens), jnp.asarray(active_np),
                self._next_key(),
                jnp.asarray(np.asarray(temperature, np.float32)),
                jnp.asarray(np.minimum(np.asarray(top_k, np.int32),
                                       self.top_k_max)),
                jnp.asarray(np.asarray(top_p, np.float32)))
        self.kv_stats["tokens"] += int(active_np.sum())
        self.kv_stats["flat_rows"] += self.num_slots * self.max_len
        if self.paged:
            self.cache = PagedKVCache(k, v, self._alloc.device_table(),
                                      lengths, k_scale=ks, v_scale=vs)
            # mirror the program's finalize exactly: lengths advance
            # for every active lane but clamp at max_len — a direct
            # caller keeping a full lane active has its append
            # dropped in-program, so the mirror must not advance
            # past it either.  Dispatch-time: a non-spec step's
            # advance is deterministic, so the mirror stays exact
            # even while the step is still in flight.
            self._len_host[active_np] += 1
            np.minimum(self._len_host, self.max_len,
                       out=self._len_host)
            self.kv_stats["paged_rows"] += \
                self._alloc.mapped_rows_total()
        else:
            # the slotted read bound IS the flat slots*max_len sweep
            self.cache = SlottedKVCache(k, v, lengths,
                                        k_scale=ks, v_scale=vs)
        if tr_on:
            self._dispatch_span("engine.decode", self._decode, t0_ns, c0)
        if self._track_coll:
            # per-step collective bytes over the mesh (opt-in; priced
            # once from the compiled sharded program, then a constant)
            self._m_coll.inc(self._collective_price("serving.decode"))
        return InflightDecode(kind="decode", active=active_np, tok=tok,
                              logits=logits, qerr=qerr)

    def decode_fetch(self, step: InflightDecode):
        """Consume a dispatched decode step: the one blocking host sync
        of an engine iteration.  Returns (next_tokens as an np array,
        logits as a jax device array)."""
        if step.kind != "decode":
            raise ValueError("decode_fetch() consumes decode steps; got "
                             "a %r step (use decode_spec_fetch)"
                             % step.kind)
        step.consumed = True
        toks = np.asarray(step.tok)
        self._set_quant_err(step.qerr)
        return toks, step.logits

    def decode(self, tokens, active, temperature, top_k, top_p,
               pages_ready=False):
        """One batched decode step.  All inputs are per-slot host arrays
        of length ``num_slots``; returns (next_tokens as an np array,
        logits as a jax device array) — callers ignore entries of
        inactive slots.  ``pages_ready=True`` skips the per-slot page
        bookkeeping — for callers (the scheduler) that already ran
        :meth:`ensure_decode_ready` this step to drive eviction;
        direct callers keep the default check-and-raise.

        This is the synchronous spelling: dispatch + immediate fetch.
        The overlapped scheduler loop calls the halves directly
        (:meth:`decode_submit` / :meth:`decode_fetch`) to keep one step
        in flight."""
        return self.decode_fetch(self.decode_submit(
            tokens, active, temperature, top_k, top_p,
            pages_ready=pages_ready))

    def decode_spec_submit(self, tokens, drafts, active, temperature,
                           top_k, top_p,
                           pages_ready=False) -> InflightDecode:
        """Dispatch one speculative verify step without fetching its
        results (the overlapped loop's dispatch half — see
        :meth:`decode_submit`).  Unlike a plain decode, the per-slot
        advance (``counts``) is data-dependent, so the host length
        mirror and the spec/KV accounting are deferred to
        :meth:`decode_spec_fetch` — an overlapped caller must map the
        append range conservatively (``ensure_decode_ready`` with
        ``steps`` covering the in-flight step's worst case)."""
        if not self.spec_k:
            raise RuntimeError("decode_spec needs an engine built with "
                               "spec_k > 0")
        S, k = self.num_slots, self.spec_k
        drafts_np = np.asarray(drafts, np.int32).reshape(S, k)
        active_np = np.asarray(active, bool).reshape(S)
        if not pages_ready:
            blocked = self.ensure_decode_ready(active_np, steps=k + 1)
            if blocked is not None:
                raise PagePoolExhausted(
                    "no free page for slot %d's speculative appends — "
                    "evict a slot (the scheduler does this "
                    "refcount-aware)" % blocked)
        if isinstance(tokens, jax.Array):
            # device-threaded last committed tokens (overlapped loop)
            step_toks = jnp.concatenate(
                [jnp.reshape(tokens, (S, 1)), jnp.asarray(drafts_np)],
                axis=1)
        else:
            toks = np.asarray(tokens, np.int32).reshape(S, 1)
            step_toks = np.concatenate([toks, drafts_np], axis=1)
            if self.mesh is not None:           # see _token_operand
                step_toks = jax.device_put(step_toks, self._sh())
        tr_on = self._tracer.enabled
        if tr_on:
            c0 = self._verify.compile_count
            t0_ns = time.perf_counter_ns()
        with x64_scope(False), _eval_scope(self.model), \
                self._trace_scope():
            emitted, counts, logits, kk, v, ks, vs, lengths, qerr = \
                self._verify(
                    self.state, self.cache.k, self.cache.v,
                    *self._cache_scale_args(), self.cache.lengths,
                    self._alloc.device_table(),
                    jnp.asarray(step_toks), jnp.asarray(active_np),
                    self._next_key(),
                    jnp.asarray(np.asarray(temperature, np.float32)),
                    jnp.asarray(np.minimum(np.asarray(top_k, np.int32),
                                           self.top_k_max)),
                    jnp.asarray(np.asarray(top_p, np.float32)))
            self.cache = PagedKVCache(kk, v, self._alloc.device_table(),
                                      lengths, k_scale=ks, v_scale=vs)
        if tr_on:
            self._dispatch_span("engine.spec_verify", self._verify,
                                t0_ns, c0)
        if self._track_coll:
            self._m_coll.inc(
                self._collective_price("serving.spec_verify"))
        return InflightDecode(
            kind="spec", active=active_np, emitted=emitted, counts=counts,
            logits=logits, qerr=qerr,
            # dispatch-time read accounting: one mapped-pages sweep
            # serves every token this step commits
            paged_rows=self._alloc.mapped_rows_total(),
            slot_epoch=self._slot_epoch.copy())

    def decode_spec_fetch(self, step: InflightDecode):
        """Consume a dispatched verify step: fetch ``counts`` (the one
        blocking sync — ``emitted`` rides the same transfer), advance
        the host length mirror by the in-program commit, and settle the
        spec/KV accounting.  Returns ``(emitted, counts, logits)`` as
        :meth:`decode_spec` documents.

        ``spec_stats`` meter DEVICE work: under the overlapped loop an
        overshoot verify step dispatched for a since-retired slot still
        counts here (the program really ran), while the scheduler —
        correctly — never credits its tokens to the request, so the
        per-request ``spec_proposed``/``spec_accepted`` pair can run
        below these totals (sync loop: the two agree exactly)."""
        if step.kind != "spec":
            raise ValueError("decode_spec_fetch() consumes spec steps; "
                             "got a %r step (use decode_fetch)"
                             % step.kind)
        step.consumed = True
        active_np = step.active
        k = self.spec_k
        counts_np = np.asarray(step.counts, np.int64)
        # mirror the program's rollback exactly: advance by the
        # accepted+1 commit, clamped at max_len — but ONLY for lanes
        # whose slot was not freed (and possibly readmitted) while the
        # step was in flight: the overlapped loop's overshoot step must
        # not resurrect a zeroed mirror entry (its in-program advance
        # landed in pages free_slot already reclaimed)
        adv = (active_np & (self._slot_epoch == step.slot_epoch)
               if step.slot_epoch is not None else active_np)
        self._len_host[adv] += counts_np[adv]
        np.minimum(self._len_host, self.max_len, out=self._len_host)
        n_active = int(active_np.sum())
        emitted_total = int(counts_np[active_np].sum())
        self.spec_stats["steps"] += 1
        self.spec_stats["proposed"] += k * n_active
        self.spec_stats["accepted"] += emitted_total - n_active
        # read accounting: ONE mapped-pages sweep serves every token the
        # step commits (the amortization lever).  The flat baseline is
        # what a slotted NON-spec engine would read for the same tokens:
        # one slots*max_len sweep per single-token step, n_active tokens
        # per sweep — emitted_total/n_active sweeps (same normalization
        # as the plain-decode accounting, so A/B lines compare).
        self.kv_stats["tokens"] += emitted_total
        if n_active:
            self.kv_stats["flat_rows"] += (self.num_slots * self.max_len
                                           * emitted_total) / n_active
        self.kv_stats["paged_rows"] += step.paged_rows
        self._set_quant_err(step.qerr)
        return (np.asarray(step.emitted), counts_np.astype(np.int64),
                step.logits)

    def decode_spec(self, tokens, drafts, active, temperature, top_k,
                    top_p, pages_ready=False):
        """One speculative verify step (paged engines with ``spec_k``).

        ``tokens``: (num_slots,) last committed token per slot;
        ``drafts``: (num_slots, spec_k) int32 proposals (see
        :func:`.spec.propose` — quality moves throughput, never
        correctness).  Returns ``(emitted, counts, logits)``: emitted
        (num_slots, spec_k+1) np int32 whose row ``b`` holds
        ``counts[b]`` usable tokens — the accepted drafts plus one
        sampled/corrected token; logits (slots, k+1, vocab) stays on
        device.  Each slot's cache length advanced by ``counts[b]``
        (committed context; the final emitted token is appended by the
        NEXT step, exactly like :meth:`decode`).  The synchronous
        spelling of :meth:`decode_spec_submit` + fetch."""
        return self.decode_spec_fetch(self.decode_spec_submit(
            tokens, drafts, active, temperature, top_k, top_p,
            pages_ready=pages_ready))

    # -- disaggregated prefill/decode handoff (ISSUE 15) -------------------

    def _require_paged(self, what):
        if not self.paged:
            raise RuntimeError("%s is a paged-engine operation (the "
                               "slotted layout has no page pool)" % what)

    def _handoff_buf_shapes(self):
        H = self.handoff_pages
        pool = (H, self._layers, self.page_size, self._heads,
                self._head_dim)
        return pool, pool[:-1]

    def _new_handoff_buf(self):
        """A fresh transfer buffer (k, v, k_scale, v_scale) placed like
        the pool (committed onto the engine mesh when there is one, so
        the donated aliasing has matching input placement)."""
        pool_shape, scale_shape = self._handoff_buf_shapes()
        bk = jnp.zeros(pool_shape, self.cache.k.dtype)
        bv = jnp.zeros(pool_shape, self.cache.v.dtype)
        bks = bvs = None
        if self._quantized:
            bks = jnp.zeros(scale_shape, jnp.float32)
            bvs = jnp.zeros(scale_shape, jnp.float32)
        if self.mesh is not None:
            psh = self._sh(None, None, None, MP_AXIS, None)
            ssh = self._sh(None, None, None, MP_AXIS)
            bk = jax.device_put(bk, psh)
            bv = jax.device_put(bv, psh)
            if self._quantized:
                bks = jax.device_put(bks, ssh)
                bvs = jax.device_put(bvs, ssh)
        return [bk, bv, bks, bvs]

    def export_pages(self, page_ids):
        """Gather up to ``handoff_pages`` pool pages into the engine's
        persistent (donated-in-place) transfer buffer — the prefill
        role's half of a disaggregated handoff.  Returns the
        ``(k, v, k_scale, v_scale)`` device arrays; rows past
        ``len(page_ids)`` hold pad garbage the import side drops.  The
        returned arrays ARE the persistent buffer: stage them onto the
        decode engine (``stage_handoff``) before the next export call
        donates the storage again (device execution order makes an
        already-dispatched stage safe)."""
        return self._export_pages_into("_handoff_buf", page_ids)

    def _export_pages_into(self, buf_attr, page_ids):
        """Shared export body: gather ``page_ids`` through the ONE
        compiled kv_export program into the persistent buffer named by
        ``buf_attr``.  The handoff path and the host-tier spill path use
        separate persistent buffers (same program — jit caches on
        shape/dtype/sharding, not array identity): a spill can fire from
        an allocator reclaim WHILE a handoff chunk sits staged, and
        re-donating the handoff buffer there would tear the splice."""
        self._require_paged("export_pages")
        n = len(page_ids)
        if not 0 < n <= self.handoff_pages:
            raise ValueError("export_pages moves 1..%d pages per chunk, "
                             "got %d" % (self.handoff_pages, n))
        ids = np.zeros((self.handoff_pages,), np.int32)
        ids[:n] = np.asarray(page_ids, np.int32)
        buf = getattr(self, buf_attr)
        if buf is None:
            buf = self._new_handoff_buf()
        tr_on = self._tracer.enabled
        if tr_on:
            c0 = self._kv_export.compile_count
            t0_ns = time.perf_counter_ns()
        with x64_scope(False), self._trace_scope():
            out = self._kv_export(self.cache.k, self.cache.v,
                                  *self._cache_scale_args(),
                                  *buf, jnp.asarray(ids))
        if tr_on:
            self._dispatch_span("engine.kv_export", self._kv_export,
                                t0_ns, c0)
        setattr(self, buf_attr, list(out))
        return tuple(out)

    def stage_handoff(self, bufs):
        """Place a peer engine's exported transfer buffer onto THIS
        engine's devices (``jax.device_put`` — device-to-device when the
        runtime can, committed to this engine's mesh placement so the
        import's in_shardings accept it).  ``bufs`` may be device arrays
        (the direct path) or host numpy arrays (the host-staging
        fallback the scheduler uses when the meshes are disjoint).

        Meshless engines do NOT ``device_put``: their whole world is
        uncommitted (single-device jit outputs are uncommitted in this
        jax), and a committed buffer would propagate commitment through
        the import's donated pool and split the decode jit cache on the
        next step — the PR-11 reset lesson.  A meshless engine therefore
        only accepts buffers already on its (default) device; the
        scheduler validates the engine pairing at construction."""
        self._require_paged("stage_handoff")
        if self.mesh is None:
            # same-device handoff: device arrays pass through untouched,
            # host arrays (the staging fallback) lift uncommitted
            return tuple(None if a is None
                         else (a if isinstance(a, jax.Array)
                               else jnp.asarray(a))
                         for a in bufs)
        psh = self._sh(None, None, None, MP_AXIS, None)
        ssh = self._sh(None, None, None, MP_AXIS)
        return tuple(None if a is None else jax.device_put(a, t)
                     for a, t in zip(bufs, (psh, psh, ssh, ssh)))

    def import_pages(self, bufs, dst_page_ids):
        """Scatter a staged transfer buffer into THIS pool at
        ``dst_page_ids`` (freshly allocated page ids — the decode role's
        half of a handoff; the caller owns the allocator bookkeeping
        that mapped them).  Pool buffers are donated: the in-flight
        decode step's outputs are consumed in place and the next
        dispatch sees the imported pages — no host sync."""
        self._require_paged("import_pages")
        n = len(dst_page_ids)
        if not 0 < n <= self.handoff_pages:
            raise ValueError("import_pages lands 1..%d pages per chunk, "
                             "got %d" % (self.handoff_pages, n))
        # pad with num_pages: an out-of-bounds id the scatter DROPS
        ids = np.full((self.handoff_pages,), self.num_pages, np.int32)
        ids[:n] = np.asarray(dst_page_ids, np.int32)
        c = self.cache
        tr_on = self._tracer.enabled
        if tr_on:
            c0 = self._kv_import.compile_count
            t0_ns = time.perf_counter_ns()
        with x64_scope(False), self._trace_scope():
            k, v, ks, vs = self._kv_import(
                c.k, c.v, *self._cache_scale_args(), *bufs,
                jnp.asarray(ids))
        if tr_on:
            self._dispatch_span("engine.kv_import", self._kv_import,
                                t0_ns, c0)
        self.cache = PagedKVCache(k, v, c.page_table, c.lengths,
                                  k_scale=ks, v_scale=vs)

    def handoff_chunk_bytes(self, n_pages):
        """Bytes ``n_pages`` transferred pages move (K+V rows, scale
        rows included — ``kv_row_bytes`` truth), for the handoff
        accounting."""
        return int(n_pages) * self.page_size * self.kv_row_bytes()

    # ------------------------------------------------------------------
    # tiered KV host cache (ISSUE 17) — spill / fetch-plan / staging.
    # The scheduler owns the interleaved chunk advance (kv_tier fetch
    # machinery mirrors the disagg handoff discipline).
    # ------------------------------------------------------------------

    def _spill_page(self, pid, digests):
        """Allocator spill hook (also the explicit cold-page path):
        export one refcount-0 page's K/V rows — int8 codes + scales
        included — through the compiled kv_export program into the host
        tier under every chained digest the page is reachable by, so a
        later host hit implies exact-prefix equality.  The one blocking
        device->host copy lives here, on the rare reclaim path — never
        on a decode dispatch."""
        tier = self._host_tier
        if tier is None or not digests:
            return
        out = self._export_pages_into("_spill_buf", [pid])
        # row 0 of the spill buffer is our page; np.asarray is the
        # device->host gather (full logical heads even under tp)
        arrays = {}
        for name, a in zip(("k", "v", "ks", "vs"), out):
            if a is not None:
                arrays[name] = np.asarray(a[0])
        stored = False
        for d in digests:
            stored = tier.put(d, arrays) or stored
        if stored:
            self._m_host_spill.inc()
            self._m_host_bytes.set(tier.bytes_used())
            if self._kv_index is not None:
                self._kv_index.offer(digests)

    def spill_cached_pages(self, limit=None):
        """Explicit cold-page policy: proactively export up to ``limit``
        free-but-cached (refcount-0, hash-reachable) pages to the host
        tier and return them to the truly-free list — the long-context
        lever (cold mid-context pages spill, the hot tail stays
        resident) and the bench's device-miss/host-hit forcing lever.
        Returns the number of pages evicted from the device cache."""
        self._require_paged("spill_cached_pages")
        if self._host_tier is None:
            raise RuntimeError(
                "spill_cached_pages needs a host tier (kv_host_bytes "
                "argument or PADDLE_TPU_KV_HOST_BYTES)")
        pids = list(self._alloc._cached)
        if limit is not None:
            pids = pids[:int(limit)]
        for pid in pids:
            digests = self._alloc._page_hashes.get(pid)
            if digests:
                self._spill_page(pid, frozenset(digests))
            self._alloc.evict_cached(pid)
        return len(pids)

    def host_fetch_plan(self, ids):
        """``[(page_index, digest)]`` of contiguous host-tier pages that
        would extend the device-resident coverage of prompt ``ids`` —
        what the scheduler pulls back (chunked, interleaved between
        decode steps) before admitting the request as a full prefix hit.
        Empty when the tier is off/cold or the device cache already
        covers everything the tier could add; counts one kv_host_miss
        when the tier was consulted at the coverage boundary and had
        nothing (called once per admission attempt, so misses count
        admissions, not polls)."""
        tier = self._host_tier
        if tier is None or not self.paged:
            return []
        ids = np.asarray(ids, np.int32).reshape(-1)
        full, tail = self._alloc._prompt_digests(ids)
        entries = list(enumerate(full))
        if tail is not None:
            entries.append((len(full), tail))
        plan = []
        consulted = False
        for idx, d in entries:
            if d in self._alloc._hash_to_page:
                continue            # device-resident — nothing to fetch
            consulted = True
            if d in tier:
                plan.append((idx, d))
            else:
                break               # contiguity: stop at the first hole
        if consulted and not plan:
            self._m_host_misses.inc()
        return plan

    def host_fetch_stage(self, digests, rid=None, chunk=0):
        """Stage one fetch chunk (up to ``handoff_pages`` host-tier
        entries): read the tier arrays, assemble a transfer-buffer-shaped
        host chunk, push it through the chaos-instrumented npz staging
        roundtrip (``serve.kv_tier`` faultpoint — a torn read surfaces
        here), and place it on this engine's devices.  Returns the
        staged arrays; they are NOT donated until ``import_pages``, so
        ``is_ready()`` polling is safe.  Raises ``KeyError`` when a tier
        entry vanished (LRU raced the fetch) or a ``TRANSPORT_ERRORS``
        member on a torn staging read — the scheduler's abort path owns
        both."""
        from .kv_tier import BUF_NAMES, KV_TIER_SITE, npz_roundtrip
        self._require_paged("host_fetch_stage")
        n = len(digests)
        if not 0 < n <= self.handoff_pages:
            raise ValueError("host_fetch_stage moves 1..%d pages per "
                             "chunk, got %d" % (self.handoff_pages, n))
        tier = self._host_tier
        if tier is None:
            raise RuntimeError("host_fetch_stage needs a host tier")
        pool_shape, scale_shape = self._handoff_buf_shapes()
        bufs = {"k": np.zeros(pool_shape, np.dtype(self.cache.k.dtype)),
                "v": np.zeros(pool_shape, np.dtype(self.cache.v.dtype))}
        if self._quantized:
            bufs["ks"] = np.zeros(scale_shape, np.float32)
            bufs["vs"] = np.zeros(scale_shape, np.float32)
        for i, d in enumerate(digests):
            arrays = tier.get(d)
            if arrays is None:
                raise KeyError("host-tier entry vanished mid-fetch "
                               "(LRU eviction raced the fetch)")
            for name in bufs:
                bufs[name][i] = arrays[name]
        tup = tuple(bufs.get(name) for name in BUF_NAMES)
        tup = npz_roundtrip(tup, KV_TIER_SITE, rid=rid, chunk=chunk)
        return self.stage_handoff(tup)

    def kv_host_bytes_used(self):
        """Host-tier occupancy in bytes (0 when the tier is off) — the
        HBM ledger's host-side row."""
        tier = self._host_tier
        return 0 if tier is None else tier.bytes_used()

    def prefix_digest_snapshot(self):
        """Advisory copy of every chained page digest this engine can
        serve a prefix hit from: the device pool's hash table, the
        host tier, and anything the attached cluster index still
        offers.  The router's prefix-affinity probe (ISSUE 19) calls
        this cross-thread while the replica keeps decoding — a
        concurrent mutation just yields a marginally stale set (one
        bounded retry, then next probe refreshes), which is fine
        because affinity is a routing HINT: admission re-derives exact
        coverage under the allocator's own bookkeeping."""
        digs = set()
        if not self.paged:
            return digs
        for _ in range(4):
            try:
                digs = set(self._alloc._hash_to_page)
                tier = self._host_tier
                if tier is not None:
                    digs.update(tier.digests())
                break
            except RuntimeError:   # dict mutated under the iteration
                digs = set()
                continue
        if self._kv_index is not None:
            from .kv_tier import _hex
            digs = {_hex(d) for d in digs}
            digs.update(self._kv_index.snapshot_digests())
            return digs
        from .kv_tier import _hex
        return {_hex(d) for d in digs}

    def attach_cluster_index(self, store, host=None, interval=None,
                             start=True):
        """Wire a TCPStore-backed ClusterPrefixIndex to this engine:
        every digest that becomes servable (registered device-side or
        spilled to the host tier) is offered to the publisher, so
        replicas share one logical system-prompt cache and a router can
        read the cluster's prefix map.  Returns the index (started as a
        daemon unless ``start=False``)."""
        from .kv_tier import ClusterPrefixIndex
        self._kv_index = ClusterPrefixIndex(store, host=host,
                                            interval=interval)
        if self._host_tier is not None:
            # LRU evictions must leave the published set immediately —
            # a replica that fetches a just-evicted digest gets a miss
            # and recomputes, but a stale advertisement lingering until
            # the next interval publish turns every hit into a miss
            # storm.  withdraw() only mutates the digest set under the
            # index's own lock (store I/O stays on the publisher
            # thread), so this is safe to run from the hook, which the
            # tier invokes after releasing its lock.
            self._host_tier.evict_hook = self._kv_index.withdraw
        if start:
            self._kv_index.start()
        return self._kv_index

    def slot_lengths(self):
        """Per-slot valid lengths.  Paged mode serves the host mirror —
        no device->host sync on the scheduler's per-iteration path."""
        if self.paged:
            return self._len_host.copy()
        return np.asarray(self.cache.lengths)

    def kv_row_bytes(self):
        """Bytes one K+V row costs a decode read PER CHIP (all layers,
        this chip's ``heads / tp`` head shard).  int8: codes + the
        per-(row, head) f32 scale — the honest read bound, not just the
        code bytes.  Tensor parallelism divides the per-chip row by the
        TP degree (the ISSUE-12 acceptance ratio): every derived figure
        — ``kv_pool_bytes``, ``kv_bytes_per_token``, the HBM ledger —
        inherits per-shard truth from this one place."""
        if self._quantized:
            # 1-byte codes (int8 AND fp8/e4m3) + the f32 scale
            per_head = self._head_dim * self.kv_dtype.itemsize + 4
        else:
            per_head = self._head_dim * self._cache_dtype.itemsize
        return self._layers * (self._heads // self.tp) * per_head * 2

    def kv_pool_bytes(self):
        """Bytes the KV pool holds resident PER CHIP — the HBM ledger's
        ``hbm.kv_pool_bytes`` term.  Rows * ``kv_row_bytes()`` so the
        int8 accounting (codes + scales) and the tensor-parallel head
        split carry over: paged pools price every page whether mapped or
        free (the allocation is static), slotted pools the full
        ``slots * max_len`` buffer."""
        rows = (self.num_pages * self.page_size if self.paged
                else self.num_slots * self.max_len)
        return rows * self.kv_row_bytes()

    def kv_bytes_per_token(self):
        """Observed decode KV-read accounting PER CHIP: bytes per
        generated token under (a) the paged true-length bound and (b)
        the slotted ``slots*max_len`` bound — the bench's A/B line.  Row
        cost covers K+V across all layers (int8: codes + scales;
        tensor parallelism: this chip's head shard only, so a tp=2 line
        reads ~1/2 the tp=1 bound — the ISSUE-12 acceptance ratio).
        Slotted engines report only ``flat`` (their real read bound): a
        fabricated ``paged: 0.0`` would read as a datum in the A/B
        trajectory.  Speculative steps amortize ONE paged sweep over
        every committed token, so the paged line reflects every
        multiplicative lever at once."""
        row = self.kv_row_bytes()
        t = self.kv_stats["tokens"]
        out = {"flat": (float(self.num_slots * self.max_len * row)
                        if not t    # no decode yet: the static bound
                        else self.kv_stats["flat_rows"] * row / t)}
        if self.paged:
            out["paged"] = (0.0 if not t
                            else self.kv_stats["paged_rows"] * row / t)
        return out

    # -- flight-recorder state summary -------------------------------------

    def flight_state(self):
        """JSON-ready engine state for a flight dump: the slot table
        (per-slot lengths + mapped page ids), page-pool occupancy, and
        the watchdog compile counts.  Paged engines read only host
        state; the slotted layout's lengths live on DEVICE — and in the
        strict-recompile crash this dump exists for, the offending call
        has already consumed that donated buffer, so the read is
        guarded: a deleted-buffer error costs the lengths field, never
        the rest of the summary."""
        try:
            lengths = [int(x) for x in self.slot_lengths()]
        except Exception as e:    # donated-away device buffer mid-crash
            lengths = "unavailable: %r" % (e,)
        st = {
            "paged": self.paged,
            "num_slots": self.num_slots,
            "max_len": self.max_len,
            "kv_dtype": str(self.kv_dtype),
            "spec_k": self.spec_k,
            "tp": self.tp,
            "slot_lengths": lengths,
            "compile_counts": {
                "decode": self.decode_compile_count,
                "prefill": self.prefill_compile_count,
                "verify": self.verify_compile_count,
            },
        }
        if self.paged:
            al = self._alloc
            st["compile_counts"]["kv_export"] = \
                int(self._kv_export._cache_size())
            st["compile_counts"]["kv_import"] = \
                int(self._kv_import._cache_size())
            st.update(
                num_pages=self.num_pages,
                page_size=self.page_size,
                pages_used=al.pages_used(),
                pages_free=al.pages_free(),
                pages_cached=al.pages_cached(),
                slot_pages={
                    str(i): [int(al.table[i, j])
                             for j in np.nonzero(al.mapped[i])[0]]
                    for i in range(self.num_slots)},
            )
            if self._host_tier is not None:
                st["kv_host"] = self._host_tier.state()
        return st

    # -- compile accounting (the "compiles exactly once" contract) ---------

    @property
    def decode_compile_count(self):
        """Number of programs the decode jit holds — MUST stay 1."""
        return int(self._decode._cache_size())

    @property
    def verify_compile_count(self):
        """Programs the speculative verify jit holds — MUST stay <= 1
        (0 until the first verify call; fixed k keeps it there)."""
        if not self.spec_k:
            return 0
        return int(self._verify._cache_size())

    @property
    def prefill_compile_count(self):
        """Paged: the single chunk program; slotted: <= len(buckets)."""
        if self.paged:
            return int(self._prefill_chunk._cache_size())
        return int(self._prefill._cache_size())

    # -- audit hooks (analysis/trace/programs.py `serving` builder) --------

    def decode_trace_args(self):
        """The exact argument avals ``self._decode`` runs with (fixed key,
        not drawn from the engine stream — lowering an audit must not
        shift the live engine's sampling sequence)."""
        s = self.num_slots
        common = (jnp.zeros((s, 1), jnp.int32), jnp.ones((s,), bool),
                  jax.random.key(0), jnp.ones((s,), jnp.float32),
                  jnp.zeros((s,), jnp.int32), jnp.ones((s,), jnp.float32))
        head = (self.state, self.cache.k, self.cache.v,
                *self._cache_scale_args(), self.cache.lengths)
        if self.paged:
            return head + (self._alloc.device_table(),) + common
        return head + common

    def verify_trace_args(self):
        """Argument avals for the speculative verify entry (paged +
        spec_k engines)."""
        if not self.spec_k:
            raise RuntimeError("verify_trace_args needs spec_k > 0")
        s = self.num_slots
        return (self.state, self.cache.k, self.cache.v,
                *self._cache_scale_args(), self.cache.lengths,
                self._alloc.device_table(),
                jnp.zeros((s, self.spec_k + 1), jnp.int32),
                jnp.ones((s,), bool), jax.random.key(0),
                jnp.ones((s,), jnp.float32), jnp.zeros((s,), jnp.int32),
                jnp.ones((s,), jnp.float32))

    def prefill_trace_args(self, bucket=None):
        if self.paged:
            raise RuntimeError("paged engines trace prefill_chunk — use "
                               "prefill_chunk_trace_args()")
        b = int(bucket or self.buckets[0])
        return (self.state, jnp.zeros((1, b), jnp.int32),
                jnp.zeros((), jnp.int32), jnp.asarray(b, jnp.int32),
                self.cache.k, self.cache.v, *self._cache_scale_args(),
                self.cache.lengths, jax.random.key(0),
                jnp.ones((), jnp.float32), jnp.zeros((), jnp.int32),
                jnp.ones((), jnp.float32))

    def prefill_chunk_trace_args(self):
        C = self.prefill_chunk
        return (self.state, jnp.zeros((1, C), jnp.int32),
                jnp.zeros((), jnp.int32), jnp.zeros((), jnp.int32),
                jnp.asarray(min(C, self.max_len), jnp.int32),
                self.cache.k, self.cache.v, *self._cache_scale_args(),
                self.cache.lengths, self._alloc.device_table(),
                jax.random.key(0), jnp.ones((), jnp.float32),
                jnp.zeros((), jnp.int32), jnp.ones((), jnp.float32))

    def cow_trace_args(self):
        return (self.cache.k, self.cache.v, *self._cache_scale_args(),
                jnp.zeros((), jnp.int32), jnp.ones((), jnp.int32))

    def kv_export_trace_args(self):
        """Argument avals for the handoff export entry (fresh zero
        buffers, NOT the live persistent one — lowering an audit must
        not race a real handoff's donated storage)."""
        self._require_paged("kv_export_trace_args")
        return (self.cache.k, self.cache.v, *self._cache_scale_args(),
                *self._new_handoff_buf(),
                jnp.zeros((self.handoff_pages,), jnp.int32))

    def kv_import_trace_args(self):
        self._require_paged("kv_import_trace_args")
        return (self.cache.k, self.cache.v, *self._cache_scale_args(),
                *self._new_handoff_buf(),
                jnp.full((self.handoff_pages,), self.num_pages,
                         jnp.int32))

    # -- cost reports (ISSUE 11) -------------------------------------------

    def cost_reports(self, only=None):
        """{watchdog entry name: ProgramReport} for every entry this
        engine watches — XLA cost/memory analysis of the programs that
        actually serve: audit trace args, production donation + x64
        scope, and NO keep_unused (unlike the audit wrap — pricing
        wants the pruned program that runs, not the alignment shim
        TPU502 needs).  Lowers + compiles each entry once per call (the jit
        dispatch cache is separate from the AOT path): cold path only —
        benches call it AFTER the timed drain.  ``only`` (an iterable of
        entry names) restricts pricing to those entries — a bench line
        that reports one program must not pay 3 extra compiles."""
        from ..observability import costs as _costs
        entries = [("serving.decode", self._decode_fn,
                    self._decode_donate_argnums, self.decode_trace_args())]
        if self.paged:
            entries.append(("serving.prefill_chunk", self._prefill_chunk_fn,
                            self._prefill_chunk_donate_argnums,
                            self.prefill_chunk_trace_args()))
            entries.append(("serving.cow_copy", self._cow_fn,
                            self._cow_donate_argnums, self.cow_trace_args()))
            entries.append(("serving.kv_export", self._kv_export_fn,
                            self._kv_export_donate_argnums,
                            self.kv_export_trace_args()))
            entries.append(("serving.kv_import", self._kv_import_fn,
                            self._kv_import_donate_argnums,
                            self.kv_import_trace_args()))
            if self.spec_k:
                entries.append(("serving.spec_verify", self._verify_fn,
                                self._verify_donate_argnums,
                                self.verify_trace_args()))
        else:
            entries.append(("serving.prefill", self._prefill_fn,
                            self._prefill_donate_argnums,
                            self.prefill_trace_args()))
        if only is not None:
            wanted = set(only)
            unknown = wanted - {name for name, *_ in entries}
            if unknown:
                raise ValueError(
                    "cost_reports(only=...) names entries this engine "
                    "does not watch: %s" % sorted(unknown))
            entries = [e for e in entries if e[0] in wanted]
        out = {}
        for name, fn, donate, args in entries:
            # tensor-parallel engines price the SHARDED twin — the
            # program that actually serves, per-chip FLOPs/bytes and
            # the partitioned collectives included (_jit_kwargs is the
            # one source of the sharding kwargs, shared with the
            # production jits)
            with x64_scope(False), self._trace_scope():
                compiled = jax.jit(fn, donate_argnums=donate,
                                   **self._jit_kwargs(name)) \
                    .lower(*args).compile()
            out[name] = _costs.report_from_compiled(name, compiled)
        return out
