"""The TPU-native decode engine: static-shape slotted KV cache + a
batched decode step that compiles exactly once.

Two compiled entry points over the :class:`~.cache.SlottedKVCache`:

* ``prefill`` — one sequence, right-padded to a power-of-two *bucket*
  (bounding the jit cache to ``log2(max_len)`` programs), written into
  one (dynamic) slot; samples the first token from the last real
  position's logits.
* ``decode`` — ALL slots advance one token in one fixed-shape program:
  append at per-slot lengths, length-masked attention
  (``kernels.decode_attention`` — autotune family ``decode_attn``),
  per-slot temperature/top-k/top-p sampling with a threaded PRNG key.
  Every argument that varies across steps (tokens, active mask, sampling
  parameters, key) is a traced array — nothing retraces, ever; asserted
  by ``decode_compile_count``.

Both entries **donate the cache buffers** (k, v, lengths): XLA aliases
them input→output, so the multi-hundred-MB cache is updated in place
instead of double-buffered (TPU502 audits that the aliasing actually
materializes — see ``analysis/trace/programs.py``'s ``serving`` builder).

The engine is deliberately request-free: slot admission/eviction policy
lives in :mod:`.scheduler`.
"""
from __future__ import annotations

import contextlib

import numpy as np

import jax
import jax.numpy as jnp

from ..core.dtype import x64_scope
from ..core.tensor import Tensor
from .cache import DecodeView, PrefillView, SlottedKVCache
from .sampling import TOP_K_MAX, sample

__all__ = ["DecodeEngine", "prefill_buckets_for"]


def prefill_buckets_for(max_len, min_bucket=16):
    """Power-of-two prefill buckets up to ``max_len``; a non-power-of-two
    ``max_len`` is appended as the final bucket so every prompt that fits
    the cache has a bucket."""
    out = []
    b = min(int(min_bucket), int(max_len))
    while b <= int(max_len):
        out.append(b)
        b *= 2
    if not out or out[-1] < int(max_len):
        out.append(int(max_len))
    return out


@contextlib.contextmanager
def _eval_scope(model):
    """Run the engine's compiled entries with the model in eval mode but
    RESTORE the caller's mode after: generate() between training epochs
    must not silently disable dropout for the rest of the run (mode only
    matters at trace time, but the flip would otherwise leak out)."""
    was_training = bool(getattr(model, "training", False))
    model.eval()
    try:
        yield
    finally:
        if was_training:
            model.train()


class DecodeEngine:
    """Compiled serving engine for a causal-LM Layer (``model(input_ids,
    cache=<view>) -> (logits, cache)`` with a ``config`` carrying the
    GPT geometry — :class:`paddle_tpu.models.gpt.GPTForCausalLM`)."""

    def __init__(self, model, num_slots=4, max_len=None, cache_dtype=None,
                 min_bucket=16, seed=0, top_k_max=TOP_K_MAX, donate=True):
        cfg = model.config
        self.model = model
        self.num_slots = int(num_slots)
        self.max_len = int(max_len or cfg.max_position_embeddings)
        if self.max_len > cfg.max_position_embeddings:
            raise ValueError(
                "max_len %d exceeds the model's position budget %d"
                % (self.max_len, cfg.max_position_embeddings))
        self.top_k_max = int(top_k_max)
        self.buckets = prefill_buckets_for(self.max_len, min_bucket)
        self.state = model.functional_state()
        if cache_dtype is None:
            # match the activation dtype: the embedding weight's dtype is
            # what the residual stream (and so K/V) runs in
            probe = getattr(getattr(model, "gpt", model), "wte", None)
            cache_dtype = (jnp.dtype(probe.weight._array.dtype)
                           if probe is not None
                           else jnp.dtype(next(iter(self.state.values()
                                                    )).dtype))
        self.cache = SlottedKVCache.create(
            self.num_slots, cfg.num_hidden_layers, self.max_len,
            cfg.num_attention_heads,
            cfg.hidden_size // cfg.num_attention_heads, cache_dtype)
        self._base_key = jax.random.key(int(seed))
        self._rng_step = 0

        k_max = self.top_k_max

        def decode_fn(state, cache_k, cache_v, lengths, tokens, active,
                      key, temps, top_ks, top_ps):
            """One batched decode iteration over every slot."""
            model.eval()   # trace-time: cached decode is inference-only
            view = DecodeView(SlottedKVCache(cache_k, cache_v, lengths),
                              active=active)
            from ..jit import functional_call
            (logits, _), _ = functional_call(model, state, Tensor(tokens),
                                             cache=view)
            logits = logits[:, -1, :]
            next_tok = sample(logits, key, temps, top_ks, top_ps, k_max)
            out = view.finalize()
            return next_tok, logits, out.k, out.v, out.lengths

        def prefill_fn(state, tokens, slot, true_len, cache_k, cache_v,
                       lengths, key, temp, top_k, top_p):
            """Prefill one bucketed sequence into ``slot`` and sample the
            first generated token from the last REAL position."""
            model.eval()
            view = PrefillView(SlottedKVCache(cache_k, cache_v, lengths),
                               slot, true_len)
            from ..jit import functional_call
            (logits, _), _ = functional_call(model, state, Tensor(tokens),
                                             cache=view)
            last = jax.lax.dynamic_slice(
                logits, (jnp.zeros((), jnp.int32),
                         true_len - jnp.ones((), jnp.int32),
                         jnp.zeros((), jnp.int32)),
                (1, 1, logits.shape[-1]))[:, 0, :]
            tok = sample(last, key, temp[None], top_k[None], top_p[None],
                         k_max)[0]
            out = view.finalize()
            return tok, last[0], out.k, out.v, out.lengths

        # hooks for the trace-tier audit (TPU501-505): the registry lowers
        # the un-jitted fns with keep_unused=True at these donate_argnums
        self._decode_fn = decode_fn
        self._decode_donate_argnums = (1, 2, 3) if donate else ()
        self._prefill_fn = prefill_fn
        self._prefill_donate_argnums = (4, 5, 6) if donate else ()
        # recompile watchdog (observability.watchdog): decode is the
        # compile-ONCE entry — a second program is PR 5's silent-retrace
        # bug class and warns (raises under PADDLE_TPU_STRICT_COMPILE=1);
        # prefill's budget is its bucket count
        from ..observability.watchdog import watch
        self._decode = watch(
            "serving.decode",
            jax.jit(decode_fn, donate_argnums=self._decode_donate_argnums),
            expected=1)
        self._prefill = watch(
            "serving.prefill",
            jax.jit(prefill_fn,
                    donate_argnums=self._prefill_donate_argnums),
            expected=len(self.buckets))

    # -- host-side API -----------------------------------------------------

    def refresh_state(self, state=None):
        """Re-snapshot the model's parameters (same shapes/dtypes — no
        recompile).  Call after training between generate rounds."""
        self.state = state if state is not None else \
            self.model.functional_state()

    def reset(self):
        """Zero the cache lengths (slot contents are overwritten lazily)."""
        self.cache = SlottedKVCache(
            self.cache.k, self.cache.v,
            jnp.zeros((self.num_slots,), jnp.int32))

    def reseed(self, seed):
        """Restart the threaded key stream: after ``reseed(s)`` the next
        prefill/decode sequence reproduces a fresh engine built with
        ``seed=s`` (generate() calls this so its ``seed=`` argument means
        the same thing on a cached engine as on a new one)."""
        self._base_key = jax.random.key(int(seed))
        self._rng_step = 0

    def bucket_for(self, n):
        for b in self.buckets:
            if n <= b:
                return b
        raise ValueError(
            "prompt length %d exceeds the largest prefill bucket %d "
            "(max_len=%d)" % (n, self.buckets[-1], self.max_len))

    def _next_key(self):
        self._rng_step += 1
        return jax.random.fold_in(self._base_key, self._rng_step)

    def prefill(self, slot, token_ids, temperature=1.0, top_k=0,
                top_p=1.0):
        """Admit ``token_ids`` (1-D) into ``slot``; returns the sampled
        first token (int) and the last-position logits (a jax array,
        (vocab,) — left on device; np.asarray() it if needed host-side)."""
        ids = np.asarray(token_ids, np.int32).reshape(-1)
        n = int(ids.size)
        if n < 1:
            raise ValueError("empty prompt")
        if n > self.max_len:
            raise ValueError("prompt length %d > max_len %d"
                             % (n, self.max_len))
        bucket = self.bucket_for(n)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :n] = ids
        # x64_scope(False) covers the (first-call) TRACE: the serving
        # programs carry no s64/f64 — jax.random's counters and gather
        # index widening follow the global x64 default otherwise (same
        # discipline as the Pallas kernel entries; asserted over the
        # compiled HLO by tests/test_serving.py)
        with x64_scope(False), _eval_scope(self.model):
            tok, logits, k, v, lengths = self._prefill(
                self.state, jnp.asarray(padded),
                jnp.asarray(slot, jnp.int32),
                jnp.asarray(n, jnp.int32), self.cache.k, self.cache.v,
                self.cache.lengths, self._next_key(),
                jnp.asarray(temperature, jnp.float32),
                jnp.asarray(min(int(top_k), self.top_k_max), jnp.int32),
                jnp.asarray(top_p, jnp.float32))
        self.cache = SlottedKVCache(k, v, lengths)
        return int(tok), logits

    def decode(self, tokens, active, temperature, top_k, top_p):
        """One batched decode step.  All inputs are per-slot host arrays
        of length ``num_slots``; returns (next_tokens as an np array,
        logits as a jax device array) — callers ignore entries of
        inactive slots."""
        toks = np.asarray(tokens, np.int32).reshape(self.num_slots, 1)
        # x64/eval scopes: see prefill() — keep the traced program
        # s64/f64-free and the caller's train/eval mode untouched
        with x64_scope(False), _eval_scope(self.model):
            tok, logits, k, v, lengths = self._decode(
                self.state, self.cache.k, self.cache.v, self.cache.lengths,
                jnp.asarray(toks), jnp.asarray(np.asarray(active, bool)),
                self._next_key(),
                jnp.asarray(np.asarray(temperature, np.float32)),
                jnp.asarray(np.minimum(np.asarray(top_k, np.int32),
                                       self.top_k_max)),
                jnp.asarray(np.asarray(top_p, np.float32)))
        self.cache = SlottedKVCache(k, v, lengths)
        return np.asarray(tok), logits

    def slot_lengths(self):
        return np.asarray(self.cache.lengths)

    # -- compile accounting (the "compiles exactly once" contract) ---------

    @property
    def decode_compile_count(self):
        """Number of programs the decode jit holds — MUST stay 1."""
        return int(self._decode._cache_size())

    @property
    def prefill_compile_count(self):
        """<= len(self.buckets) by construction."""
        return int(self._prefill._cache_size())

    # -- audit hooks (analysis/trace/programs.py `serving` builder) --------

    def decode_trace_args(self):
        """The exact argument avals ``self._decode`` runs with (fixed key,
        not drawn from the engine stream — lowering an audit must not
        shift the live engine's sampling sequence)."""
        s = self.num_slots
        return (self.state, self.cache.k, self.cache.v, self.cache.lengths,
                jnp.zeros((s, 1), jnp.int32), jnp.ones((s,), bool),
                jax.random.key(0), jnp.ones((s,), jnp.float32),
                jnp.zeros((s,), jnp.int32), jnp.ones((s,), jnp.float32))

    def prefill_trace_args(self, bucket=None):
        b = int(bucket or self.buckets[0])
        return (self.state, jnp.zeros((1, b), jnp.int32),
                jnp.zeros((), jnp.int32), jnp.asarray(b, jnp.int32),
                self.cache.k, self.cache.v, self.cache.lengths,
                jax.random.key(0), jnp.ones((), jnp.float32),
                jnp.zeros((), jnp.int32), jnp.ones((), jnp.float32))
