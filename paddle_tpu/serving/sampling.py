"""Sampling for the batched decode step — greedy / temperature / top-k /
top-p, vectorized over slots, with PER-SLOT parameters as traced arrays
so the jitted decode step never specializes on them.

Design constraints (each one is a regression test in
``tests/test_serving.py``):

* **Threaded PRNG key** — the key is an explicit argument threaded by the
  engine (``fold_in(base, step)``), never drawn from the global eager
  generator: sampling inside a compiled step must not shift the global
  RNG stream of the surrounding program (the same discipline as
  ``TrainStep.trace_args``).
* **int32-safe under the x64 audit** — paddle parity enables
  ``jax_enable_x64`` globally, so any dtype-less index math lands s64
  (flagged as s64 *compute* by the runtime HLO audit).  Token ids come
  from ``lax.top_k`` (int32 by construction — including the Gumbel-trick
  categorical, which avoids ``argmax``'s s64 result) and every index
  array is created int32.
* **top-p keeps ≥ 1 token** — the cutoff is on the *exclusive* cumulative
  mass (`mass before this token < p`), so the most-probable token always
  survives, even for ``p == 0``.
* **dynamic top-k without retracing** — ``lax.top_k`` needs a static k,
  so the kernel takes the top ``TOP_K_MAX`` once and thresholds per-slot
  at the (dynamic) k-th value; per-slot ``top_k`` stays a traced int32
  array and the decode program compiles once.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["sample", "apply_temperature", "apply_top_k", "apply_top_p",
           "TOP_K_MAX"]

#: static cap for per-slot top-k (requests are clamped host-side); the
#: top-TOP_K_MAX values are computed once and thresholded dynamically
TOP_K_MAX = 64

_NEG = -1e30


def apply_temperature(logits, temperature):
    """logits: (slots, vocab) — divide by per-slot temperature.  Zero (or
    negative) temperature means greedy; the division here just needs to be
    finite, :func:`sample` picks the argmax branch for those slots."""
    t = jnp.maximum(temperature.astype(jnp.float32), 1e-6)
    return logits.astype(jnp.float32) / t[:, None]


def apply_top_k(logits, top_k, k_max=TOP_K_MAX):
    """Per-slot dynamic top-k: keep logits >= the k-th largest value;
    ``top_k <= 0`` disables filtering for that slot."""
    k_max = min(int(k_max), int(logits.shape[-1]))
    vals, _ = jax.lax.top_k(logits, k_max)   # idx unused; vals sorted desc
    kth_idx = jnp.clip(top_k.astype(jnp.int32) - 1, 0, k_max - 1)
    # promise_in_bounds (the clip above guarantees it): under global x64
    # the default gather path widens indices to s64 — the same fix as the
    # cross-entropy gather (tests/test_x64_audit.py discipline)
    kth = jnp.take_along_axis(vals, kth_idx[:, None], axis=-1,
                              mode="promise_in_bounds")
    keep = (logits >= kth) | (top_k <= 0)[:, None]
    return jnp.where(keep, logits, jnp.asarray(_NEG, logits.dtype))


def apply_top_p(logits, top_p):
    """Per-slot nucleus filtering on the softmax of ``logits``.  A token
    is kept while the probability mass STRICTLY BEFORE it (in descending
    order) is < p — so the most-probable token is always kept (`mass
    before it` is 0), the "keep at least one" guarantee.  ``top_p >= 1``
    disables filtering for that slot.  Ties at the threshold probability
    are all kept (the filter thresholds on probability values)."""
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    sorted_p = jnp.sort(probs, axis=-1, descending=True)
    mass_before = jnp.cumsum(sorted_p, axis=-1) - sorted_p   # exclusive
    keep_sorted = mass_before < top_p.astype(jnp.float32)[:, None]
    # smallest kept probability = the per-slot threshold; the first
    # column of keep_sorted is mass_before==0 < p only when p > 0, so
    # force-keep column 0 (p == 0.0 must still emit the top token)
    keep_sorted = keep_sorted.at[:, 0].set(True)
    thresh = jnp.min(jnp.where(keep_sorted, sorted_p,
                               jnp.asarray(jnp.inf, jnp.float32)), axis=-1)
    keep = (probs >= thresh[:, None]) | (top_p >= 1.0)[:, None]
    return jnp.where(keep, logits, jnp.asarray(_NEG, logits.dtype))


def _int32_argmax(logits):
    """argmax via top_k: int32 result regardless of jax_enable_x64 (a
    bare ``jnp.argmax`` returns s64 under x64 and the cast back would
    itself be s64 compute under the HLO audit)."""
    _, idx = jax.lax.top_k(logits, 1)
    return idx[..., 0]


def sample(logits, key, temperature, top_k, top_p, k_max=TOP_K_MAX):
    """One sampled (or greedy) token per slot.

    logits: (slots, vocab); key: a single threaded PRNG key for this
    step; temperature/top_p: (slots,) float; top_k: (slots,) int32
    (<= 0 disables).  Returns (slots,) int32 token ids.
    """
    greedy_tok = _int32_argmax(logits)
    scaled = apply_temperature(logits, temperature)
    filtered = apply_top_p(apply_top_k(scaled, top_k, k_max), top_p)
    # Gumbel-max categorical: argmax(logits + G) ~ softmax(logits); the
    # top_k(…, 1) index is int32 by construction.  NOTE jax.random's
    # threefry loop counters follow the global x64 default — the engine
    # traces its whole entry under x64_scope(False) (the Pallas kernels'
    # discipline; a scope around just this draw would be a mid-trace x64
    # flip, which miscompiles — PERF.md/PR-1 history) so the compiled
    # decode program carries no s64 at all.
    g = jax.random.gumbel(key, filtered.shape, jnp.float32)
    sampled_tok = _int32_argmax(filtered + g)
    greedy = (temperature <= 0.0)
    return jnp.where(greedy, greedy_tok, sampled_tok).astype(jnp.int32)
