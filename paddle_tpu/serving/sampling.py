"""Sampling for the batched decode step — greedy / temperature / top-k /
top-p, vectorized over slots, with PER-SLOT parameters as traced arrays
so the jitted decode step never specializes on them.

Design constraints (each one is a regression test in
``tests/test_serving.py``):

* **Threaded PRNG key** — the key is an explicit argument threaded by the
  engine (``fold_in(base, step)``), never drawn from the global eager
  generator: sampling inside a compiled step must not shift the global
  RNG stream of the surrounding program (the same discipline as
  ``TrainStep.trace_args``).
* **int32-safe under the x64 audit** — paddle parity enables
  ``jax_enable_x64`` globally, so any dtype-less index math lands s64
  (flagged as s64 *compute* by the runtime HLO audit).  Token ids come
  from ``lax.top_k`` (int32 by construction — including the Gumbel-trick
  categorical, which avoids ``argmax``'s s64 result) and every index
  array is created int32.
* **top-p keeps ≥ 1 token** — the cutoff is on the *exclusive* cumulative
  mass (`mass before this token < p`), so the most-probable token always
  survives, even for ``p == 0``.
* **dynamic top-k without retracing** — ``lax.top_k`` needs a static k,
  so the kernel takes the top ``TOP_K_MAX`` once and thresholds per-slot
  at the (dynamic) k-th value; per-slot ``top_k`` stays a traced int32
  array and the decode program compiles once.

**Speculative verify (ISSUE 8).**  :func:`spec_accept` implements the
standard accept/resample rule (Leviathan et al. 2023) specialized to a
DETERMINISTIC draft (the engine's prompt-lookup proposals put
probability 1 on each drafted token): draft token ``d_j`` is accepted
with probability ``p(d_j)`` under the per-position FILTERED target
distribution (the same temperature/top-k/top-p chain :func:`sample`
uses), and a rejection resamples from ``p`` with ``d_j`` excluded — the
exact residual ``norm(max(0, p - q))`` for a point-mass ``q``, so the
output distribution is exactly the non-speculative one.  Greedy slots
(``temperature <= 0``) accept by exact argmax match, which makes greedy
output bit-identical to non-speculative decode.  All randomness comes
from ONE threaded key per iteration (two ``fold_in`` streams: the
per-draft uniforms and the bonus/correction Gumbel draw), so seed
reproducibility is independent of how many drafts are accepted.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["sample", "apply_temperature", "apply_top_k", "apply_top_p",
           "filter_logits", "spec_accept", "TOP_K_MAX"]

#: static cap for per-slot top-k (requests are clamped host-side); the
#: top-TOP_K_MAX values are computed once and thresholded dynamically
TOP_K_MAX = 64

_NEG = -1e30


def apply_temperature(logits, temperature):
    """logits: (slots, vocab) — divide by per-slot temperature.  Zero (or
    negative) temperature means greedy; the division here just needs to be
    finite, :func:`sample` picks the argmax branch for those slots."""
    t = jnp.maximum(temperature.astype(jnp.float32), 1e-6)
    return logits.astype(jnp.float32) / t[:, None]


def apply_top_k(logits, top_k, k_max=TOP_K_MAX):
    """Per-slot dynamic top-k: keep logits >= the k-th largest value;
    ``top_k <= 0`` disables filtering for that slot."""
    k_max = min(int(k_max), int(logits.shape[-1]))
    vals, _ = jax.lax.top_k(logits, k_max)   # idx unused; vals sorted desc
    kth_idx = jnp.clip(top_k.astype(jnp.int32) - 1, 0, k_max - 1)
    # promise_in_bounds (the clip above guarantees it): under global x64
    # the default gather path widens indices to s64 — the same fix as the
    # cross-entropy gather (tests/test_x64_audit.py discipline)
    kth = jnp.take_along_axis(vals, kth_idx[:, None], axis=-1,
                              mode="promise_in_bounds")
    keep = (logits >= kth) | (top_k <= 0)[:, None]
    return jnp.where(keep, logits, jnp.asarray(_NEG, logits.dtype))


def apply_top_p(logits, top_p):
    """Per-slot nucleus filtering on the softmax of ``logits``.  A token
    is kept while the probability mass STRICTLY BEFORE it (in descending
    order) is < p — so the most-probable token is always kept (`mass
    before it` is 0), the "keep at least one" guarantee.  ``top_p >= 1``
    disables filtering for that slot.  Ties at the threshold probability
    are all kept (the filter thresholds on probability values)."""
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    sorted_p = jnp.sort(probs, axis=-1, descending=True)
    mass_before = jnp.cumsum(sorted_p, axis=-1) - sorted_p   # exclusive
    keep_sorted = mass_before < top_p.astype(jnp.float32)[:, None]
    # smallest kept probability = the per-slot threshold; the first
    # column of keep_sorted is mass_before==0 < p only when p > 0, so
    # force-keep column 0 (p == 0.0 must still emit the top token)
    keep_sorted = keep_sorted.at[:, 0].set(True)
    thresh = jnp.min(jnp.where(keep_sorted, sorted_p,
                               jnp.asarray(jnp.inf, jnp.float32)), axis=-1)
    keep = (probs >= thresh[:, None]) | (top_p >= 1.0)[:, None]
    return jnp.where(keep, logits, jnp.asarray(_NEG, logits.dtype))


def _int32_argmax(logits):
    """argmax via top_k: int32 result regardless of jax_enable_x64 (a
    bare ``jnp.argmax`` returns s64 under x64 and the cast back would
    itself be s64 compute under the HLO audit)."""
    _, idx = jax.lax.top_k(logits, 1)
    return idx[..., 0]


def filter_logits(logits, temperature, top_k, top_p, k_max=TOP_K_MAX):
    """The shared per-slot filter chain: temperature scaling, then
    top-k, then top-p — the distribution :func:`sample` draws from and
    :func:`spec_accept` accepts against."""
    scaled = apply_temperature(logits, temperature)
    return apply_top_p(apply_top_k(scaled, top_k, k_max), top_p)


def sample(logits, key, temperature, top_k, top_p, k_max=TOP_K_MAX):
    """One sampled (or greedy) token per slot.

    logits: (slots, vocab); key: a single threaded PRNG key for this
    step; temperature/top_p: (slots,) float; top_k: (slots,) int32
    (<= 0 disables).  Returns (slots,) int32 token ids.
    """
    greedy_tok = _int32_argmax(logits)
    filtered = filter_logits(logits, temperature, top_k, top_p, k_max)
    # Gumbel-max categorical: argmax(logits + G) ~ softmax(logits); the
    # top_k(…, 1) index is int32 by construction.  NOTE jax.random's
    # threefry loop counters follow the global x64 default — the engine
    # traces its whole entry under x64_scope(False) (the Pallas kernels'
    # discipline; a scope around just this draw would be a mid-trace x64
    # flip, which miscompiles — PERF.md/PR-1 history) so the compiled
    # decode program carries no s64 at all.
    g = jax.random.gumbel(key, filtered.shape, jnp.float32)
    sampled_tok = _int32_argmax(filtered + g)
    greedy = (temperature <= 0.0)
    return jnp.where(greedy, greedy_tok, sampled_tok).astype(jnp.int32)


def spec_accept(logits, tokens, key, temperature, top_k, top_p,
                k_max=TOP_K_MAX, max_accept=None):
    """Accept/resample for the batched speculative verify step.

    logits: (slots, k+1, vocab) — position ``j`` was scored after the
    model consumed ``tokens[:, :j+1]``; tokens: (slots, k+1) int32 =
    ``[last committed token, draft_1..draft_k]``; key: the ONE threaded
    key for this iteration; temperature/top_p: (slots,) f32; top_k:
    (slots,) int32; max_accept: optional (slots,) int32 cap on accepted
    drafts (the engine passes ``max_len - 1 - lengths`` so acceptance
    never reaches past the cache's append capacity).

    Returns ``(emitted, counts)``: emitted (slots, k+1) int32 whose row
    ``b`` holds the accepted draft tokens followed by ONE
    sampled/corrected token (zeros beyond); counts (slots,) int32 =
    accepted + 1 — both the number of usable tokens in ``emitted`` and
    the slot's in-program length advance.

    Exactness: greedy slots accept ``d_j`` iff it IS the raw-logits
    argmax at ``j`` (emitted tokens are bit-identical to sequential
    greedy decode); sampling slots accept with probability
    ``p_filtered(d_j)`` and a rejection redraws from the filtered
    distribution with ``d_j`` masked out — the exact residual for a
    deterministic draft, so every emitted token is distributed exactly
    as a non-speculative sample.  The only degenerate residual (every
    non-draft token filtered away) implies ``p_filtered(d_j) == 1``, a
    branch rejection reaches with probability 0.
    """
    S, K1, V = logits.shape
    k = K1 - 1
    greedy_tok = _int32_argmax(logits)                       # (S, K1) i32
    rep = lambda a: jnp.broadcast_to(a[:, None], (S, K1)).reshape(S * K1)
    filtered = filter_logits(
        logits.reshape(S * K1, V), rep(temperature),
        rep(top_k).astype(jnp.int32), rep(top_p),
        k_max).reshape(S, K1, V)                             # f32
    draft = tokens[:, 1:].astype(jnp.int32)                  # (S, k)
    greedy = temperature <= 0.0                              # (S,) bool
    if k:
        probs = jax.nn.softmax(filtered[:, :k, :], axis=-1)
        p_draft = jnp.take_along_axis(probs, draft[..., None], axis=-1,
                                      mode="promise_in_bounds")[..., 0]
        r = jax.random.uniform(jax.random.fold_in(key, 0), (S, k),
                               jnp.float32)
        accept = jnp.where(greedy[:, None],
                           draft == greedy_tok[:, :k],
                           r < p_draft)
        # accepted prefix length: position j survives iff ALL of 0..j do
        a0 = jnp.sum(jnp.cumprod(accept.astype(jnp.int32), axis=1),
                     axis=1).astype(jnp.int32)
    else:
        a0 = jnp.zeros((S,), jnp.int32)
    a = a0
    if max_accept is not None:
        a = jnp.minimum(a, jnp.maximum(max_accept.astype(jnp.int32), 0))
    # the bonus/correction token comes from position a's distribution.
    # The residual exclusion applies ONLY when the stop at `a` was a real
    # probabilistic rejection (a == a0 < k) — a capacity clamp
    # (a < a0, max_accept) stopped an ACCEPTED run, and the
    # non-speculative equivalent at that position samples from the
    # filtered distribution unmasked (masking there would bias — or,
    # under top_k=1, empty — the last token before cache_full)
    f_a = jnp.take_along_axis(filtered, a[:, None, None], axis=1,
                              mode="promise_in_bounds")[:, 0, :]  # (S, V)
    d_rej = jnp.take_along_axis(tokens.astype(jnp.int32),
                                jnp.minimum(a + 1, k)[:, None], axis=1,
                                mode="promise_in_bounds")[:, 0]
    vocab = jnp.arange(V, dtype=jnp.int32)[None, :]
    rejected_here = (a == a0) & (a0 < k)
    mask_rej = rejected_here[:, None] & (vocab == d_rej[:, None])
    f_resid = jnp.where(mask_rej, jnp.asarray(_NEG, f_a.dtype), f_a)
    g = jax.random.gumbel(jax.random.fold_in(key, 1), f_resid.shape,
                          jnp.float32)
    sampled_next = _int32_argmax(f_resid + g)
    greedy_next = jnp.take_along_axis(greedy_tok, a[:, None], axis=1,
                                      mode="promise_in_bounds")[:, 0]
    next_tok = jnp.where(greedy, greedy_next, sampled_next)
    next_tok = next_tok.astype(jnp.int32)
    # emitted row: draft[:a], then next_tok at column a, zeros beyond
    cols = jnp.arange(K1, dtype=jnp.int32)[None, :]
    draft_pad = jnp.concatenate(
        [draft, jnp.zeros((S, 1), jnp.int32)], axis=1)       # (S, K1)
    emitted = jnp.where(cols == a[:, None], next_tok[:, None], draft_pad)
    emitted = jnp.where(cols <= a[:, None], emitted,
                        jnp.zeros((), jnp.int32)).astype(jnp.int32)
    return emitted, a + jnp.ones((), jnp.int32)
