"""paddle_tpu.serving — the TPU-native serving engine.

Static-shape paged/slotted KV caches with optional int8 quantization
(:mod:`.cache`), compile-once batched decode + chunked/bucketed prefill
+ the speculative batched verify (:mod:`.engine`), self-speculative
prompt-lookup drafting (:mod:`.spec`), Orca-style continuous batching
with the overlapped host/device decode loop (:mod:`.scheduler` —
ISSUE 13: one step in flight, host bookkeeping overlaps device
compute), per-slot greedy/temperature/top-k/top-p sampling plus the
accept/resample rule with a threaded PRNG key (:mod:`.sampling`), the
async streaming HTTP front-end (:mod:`.frontend` — SSE per-token
streaming, bounded admission, preemption-guard drain), and the Poisson
load harness (:mod:`.loadgen`).  See SERVING.md for the design and the
on-chip A/B protocol.

Import discipline: ``models/gpt.py`` imports :mod:`.cache`, so this
``__init__`` must not eagerly import :mod:`.engine` (which imports the
models back) — engine/scheduler resolve lazily via module ``__getattr__``.
"""
from __future__ import annotations

from .cache import (DecodeView, PagedDecodeView, PagedKVCache,
                    PagedPrefillChunkView, PrefillView, SlottedKVCache,
                    dequantize_kv, is_cache_view, quantize_kv)
from .pages import PageAllocator, PagePoolExhausted
from .sampling import TOP_K_MAX, sample, spec_accept
from .spec import propose

__all__ = [
    "SlottedKVCache", "DecodeView", "PrefillView", "PagedKVCache",
    "PagedDecodeView", "PagedPrefillChunkView", "PageAllocator",
    "PagePoolExhausted", "is_cache_view", "quantize_kv", "dequantize_kv",
    "sample", "spec_accept", "propose", "TOP_K_MAX", "DecodeEngine",
    "ContinuousBatchingScheduler", "Request", "RequestResult",
    "PrefillTask", "InflightDecode", "ServingFrontend", "generate",
    "engine_for", "DisaggScheduler", "HandoffTask",
]

_LAZY = {
    "DecodeEngine": ("paddle_tpu.serving.engine", "DecodeEngine"),
    "InflightDecode": ("paddle_tpu.serving.engine", "InflightDecode"),
    "PrefillTask": ("paddle_tpu.serving.engine", "PrefillTask"),
    "ContinuousBatchingScheduler": ("paddle_tpu.serving.scheduler",
                                    "ContinuousBatchingScheduler"),
    "Request": ("paddle_tpu.serving.scheduler", "Request"),
    "RequestResult": ("paddle_tpu.serving.scheduler", "RequestResult"),
    "ServingFrontend": ("paddle_tpu.serving.frontend", "ServingFrontend"),
    "DisaggScheduler": ("paddle_tpu.serving.disagg", "DisaggScheduler"),
    "HandoffTask": ("paddle_tpu.serving.disagg", "HandoffTask"),
}


def __getattr__(name):
    entry = _LAZY.get(name)
    if entry is None:
        raise AttributeError("module %r has no attribute %r"
                             % (__name__, name))
    import importlib
    return getattr(importlib.import_module(entry[0]), entry[1])


#: bound on cached engines per model: each holds two full preallocated
#: (slots, layers, max_len, heads, head_dim) KV buffers, so an unbounded
#: cache would pin hundreds of MB per distinct geometry at serving shapes
_MAX_CACHED_ENGINES = 4


def engine_for(model, num_slots=4, max_len=None, tp=1, **kw):
    """A per-model engine cache: repeated :func:`generate` calls with the
    same geometry reuse the compiled decode program (the compile-once
    contract spans calls).  The engine re-snapshots the model parameters
    on every use, so training between calls is reflected.  At most
    :data:`_MAX_CACHED_ENGINES` geometries are kept (LRU) — geometry is
    also bucketed by :func:`generate` so the default path reuses one.
    The RNG seed is NOT part of the geometry (it is a host-side base key
    — callers reseed the cached engine instead of building another).

    The tensor-parallel degree IS geometry: ``tp`` is a named parameter
    normalized into the cache key, so a tp=2 request after a tp=1 one
    builds a fresh engine with the head-sharded pool (reusing the
    unsharded cache geometry would feed single-chip buffers to the
    sharded program), while ``tp=1`` — spelled or defaulted — maps to
    the SAME key as before (a kwargs-carried tp would have split them
    into duplicate engines pinning two full KV pools).  ``tp`` engines
    also re-shard the refreshed parameter snapshot onto their mesh
    (``DecodeEngine.refresh_state``).

    ``overlap_comm`` is geometry too (an overlapped and a monolithic
    engine compile different programs), normalized through the same
    three-level switch the engine resolves (arg > scope >
    PADDLE_TPU_MP_OVERLAP) so ``overlap_comm=None`` under an enabled env
    and an explicit ``overlap_comm=True`` share one cached engine."""
    from ..distributed import mp_overlap as _mpo
    from .engine import DecodeEngine
    if int(tp) > 1 or "overlap_comm" in kw:
        kw["overlap_comm"] = bool(
            _mpo.enabled(kw.get("overlap_comm")) and int(tp) > 1)
    key = (int(num_slots), max_len if max_len is None else int(max_len),
           int(tp), tuple(sorted(kw.items())))
    kw = dict(kw, tp=int(tp))
    cache = model.__dict__.get("_serving_engines")
    if cache is None:
        cache = {}
        object.__setattr__(model, "_serving_engines", cache)
    eng = cache.pop(key, None)           # re-insert = move to LRU tail
    if eng is None:
        eng = DecodeEngine(model, num_slots=num_slots, max_len=max_len,
                           **kw)
        while len(cache) >= _MAX_CACHED_ENGINES:
            cache.pop(next(iter(cache)))
    else:
        eng.refresh_state()
    cache[key] = eng
    return eng


def generate(model, prompts, max_new_tokens=20, temperature=1.0, top_k=0,
             top_p=1.0, eos_token_id=None, seed=0, num_slots=None,
             max_len=None, **engine_kw):
    """Generate continuations for ``prompts`` through the engine +
    continuous-batching scheduler.  ``prompts``: a 2-D int array (each
    row one prompt), ONE 1-D prompt (a flat list of ints is one prompt,
    not N single-token prompts), or a list of 1-D prompts of ragged
    lengths.  Returns a list of 1-D int32 np arrays of generated ids,
    in submission order (a one-element list for 1-D input too).
    """
    import numpy as np

    from .scheduler import ContinuousBatchingScheduler, Request

    arr = prompts._array if hasattr(prompts, "_array") else prompts
    try:
        arr = np.asarray(arr)
    except ValueError:                    # ragged list of prompts
        arr = None
    if arr is not None and arr.dtype != object:
        if arr.ndim == 1:                 # one prompt, not N scalar ones
            arr = arr.reshape(1, -1)
        if arr.ndim != 2:
            raise ValueError("prompts must be 1-D, 2-D, or a list of 1-D "
                             "prompts; got shape %r" % (arr.shape,))
        prompt_list = [arr[i] for i in range(arr.shape[0])]
    else:
        prompt_list = [np.asarray(
            p._array if hasattr(p, "_array") else p).reshape(-1)
            for p in prompts]
    if num_slots is None:
        # bucket to a power of two (1/2/4/8): the engine geometry stays
        # stable across calls with nearby batch sizes, so the compiled
        # decode program (and its cache buffers) are reused, not rebuilt
        num_slots = 1
        while num_slots < min(len(prompt_list), 8):
            num_slots *= 2
    eng = engine_for(model, num_slots=num_slots, max_len=max_len,
                     **engine_kw)
    # restart the threaded key stream: generate(seed=s) is reproducible
    # whether the engine was cached or freshly built
    eng.reseed(seed)
    sched = ContinuousBatchingScheduler(eng)
    rids = [sched.submit(Request(
        prompt=p, max_new_tokens=max_new_tokens, temperature=temperature,
        top_k=top_k, top_p=top_p, eos_token_id=eos_token_id))
        for p in prompt_list]
    results = sched.run()
    return [results[r].tokens for r in rids]
