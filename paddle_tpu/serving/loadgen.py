"""Poisson load generation against the serving front-end (ISSUE 13).

A stdlib-asyncio HTTP client that offers load to a live
:class:`~.frontend.ServingFrontend` the way real traffic arrives:
**Poisson arrivals** at a target QPS (exponential inter-arrival gaps,
seeded — the same plan replays identically) over a named **prompt/output
length mix**, with every request streamed over SSE so TTFT is measured
at the first *delivered* token, exactly what a client sees.

Per request it records: HTTP status (sheds — 429/503 — are first-class
outcomes, not errors), TTFT (request write → first token event), TPOT
(mean gap over subsequent token events), and delivered token count.
:func:`summarize` rolls a run into the serve-bench line's fields:
**goodput** (tokens delivered on COMPLETED streams / wall — shed or
disconnected work earns nothing), shed rate, and nearest-rank p50/p99
TTFT+TPOT.  ``bench_serve.py`` sweeps (QPS, mix) pairs through this and
emits one schema'd ``BENCH_serve_*`` line each; the goodput-vs-QPS
curve's knee is where the bounded admission queue starts shedding.
"""
from __future__ import annotations

import asyncio
import json
import time
from typing import Dict, List, Optional

import numpy as np

__all__ = ["MIXES", "run_load", "run_load_sync", "run_interference",
           "run_interference_sync", "summarize", "percentile"]

#: named prompt/output length mixes: (prompt_len_range, max_new_range),
#: both inclusive.  Lengths are drawn uniformly per request from the
#: seeded plan RNG.  Kept small enough for the CPU smoke engine
#: (max_len 128); the on-chip protocol scales them via --mix overrides.
MIXES = {
    "short": ((8, 16), (4, 8)),
    "mixed": ((8, 48), (4, 16)),
    "long": ((32, 96), (8, 32)),
    # the interference worst case (ISSUE 15): long prompts, short
    # outputs — almost all of the request's compute is prefill, so a
    # wave of these steals the most decode iterations from a colocated
    # engine (the disaggregated A/B's admission wave)
    "prefill_heavy": ((64, 112), (2, 4)),
    # its counterpart: short prompts, long outputs — streams that live
    # long enough to BE in flight when the wave lands, so their
    # inter-token gaps sample exactly the decode-TPOT interference the
    # A/B measures (the steady stream of the isolation drive)
    "decode_heavy": ((8, 16), (24, 48)),
}


def percentile(values: List[float], q: float) -> float:
    """Nearest-rank percentile (the trace-report SLI convention); 0.0
    on an empty list — callers report counts alongside."""
    if not values:
        return 0.0
    v = sorted(values)
    idx = max(0, min(len(v) - 1, int(np.ceil(q * len(v))) - 1))
    return float(v[idx])


async def _one_request(host: str, port: int, payload: dict,
                       record_gaps: bool = False) -> dict:
    """POST one streaming generate and consume its SSE events.  Returns
    {status, ttft, tpot, tokens, finish_reason} — ttft/tpot are None
    when no token arrived (shed, error).  ``record_gaps=True`` also
    collects ``gaps``: one ``(arrival_time, gap_seconds)`` per
    post-first token event — the per-token samples the interference A/B
    classifies into quiet-vs-wave windows."""
    t0 = time.perf_counter()
    rec = {"status": 0, "ttft": None, "tpot": None, "tokens": 0,
           "finish_reason": None}
    if record_gaps:
        rec["gaps"] = []
    try:
        reader, writer = await asyncio.open_connection(host, port)
    except OSError:
        rec["finish_reason"] = "connect_error"
        return rec
    try:
        body = json.dumps(dict(payload, stream=True)).encode()
        writer.write(
            b"POST /v1/generate HTTP/1.1\r\nHost: loadgen\r\n"
            b"Content-Type: application/json\r\n"
            b"Content-Length: %d\r\n\r\n" % len(body) + body)
        await writer.drain()
        status_line = await reader.readline()
        parts = status_line.split()
        rec["status"] = int(parts[1]) if len(parts) > 1 else 0
        while True:                       # headers
            h = await reader.readline()
            if h in (b"\r\n", b"\n", b""):
                break
        if rec["status"] != 200:
            # shed/error body is a single JSON doc; drain and go
            await reader.read()
            return rec
        first_t = last_t = None
        n = 0
        while True:
            line = await reader.readline()
            if not line:
                break
            line = line.strip()
            if not line.startswith(b"data: "):
                continue
            ev = json.loads(line[6:])
            if ev.get("done"):
                rec["finish_reason"] = ev.get("finish_reason")
                break
            k = len(ev.get("tokens", ()))
            if k:
                now = time.perf_counter()
                if first_t is None:
                    first_t = now
                elif record_gaps:
                    # one sample per EVENT (a speculative run delivers
                    # several tokens at once): gap amortized per token
                    rec["gaps"].append((now, (now - last_t) / k))
                last_t = now
                n += k
        rec["tokens"] = n
        if first_t is not None:
            rec["ttft"] = first_t - t0
            if n > 1 and last_t > first_t:
                rec["tpot"] = (last_t - first_t) / (n - 1)
        return rec
    except (ConnectionResetError, ConnectionAbortedError,
            BrokenPipeError, asyncio.IncompleteReadError):
        rec["finish_reason"] = "connection_error"
        return rec
    finally:
        try:
            writer.close()
        except Exception:
            pass


async def run_load(host: str, port: int, qps: float, n_requests: int,
                   mix="short", seed: int = 0, vocab: int = 256,
                   temperature: float = 0.0,
                   eos_token_id: Optional[int] = None) -> dict:
    """Offer ``n_requests`` at Poisson rate ``qps`` and collect the
    summary.  ``mix`` is a name from :data:`MIXES` or a
    ``((plo, phi), (nlo, nhi))`` pair.  The arrival plan and every
    prompt are drawn from one seeded RNG — a rerun offers the identical
    workload."""
    rng = np.random.default_rng(seed)
    (plo, phi), (nlo, nhi) = MIXES[mix] if isinstance(mix, str) else mix
    loop = asyncio.get_running_loop()
    t_start = loop.time()
    t_next = 0.0
    tasks = []
    for _ in range(int(n_requests)):
        plen = int(rng.integers(plo, phi + 1))
        payload = {
            "prompt": [int(x) for x in rng.integers(0, vocab, (plen,))],
            "max_new_tokens": int(rng.integers(nlo, nhi + 1)),
            "temperature": float(temperature),
        }
        if eos_token_id is not None:
            payload["eos_token_id"] = int(eos_token_id)
        delay = (t_start + t_next) - loop.time()
        if delay > 0:
            await asyncio.sleep(delay)
        tasks.append(asyncio.ensure_future(
            _one_request(host, port, payload)))
        t_next += float(rng.exponential(1.0 / float(qps)))
    recs = await asyncio.gather(*tasks)
    wall = loop.time() - t_start
    return summarize(list(recs), wall, qps=float(qps),
                     mix=(mix if isinstance(mix, str) else "custom"))


def run_load_sync(host, port, qps, n_requests, **kw) -> dict:
    """:func:`run_load` from synchronous code (its own event loop)."""
    return asyncio.run(run_load(host, port, qps, n_requests, **kw))


async def run_interference(host: str, port: int, qps: float,
                           n_requests: int, mix="short",
                           wave_mix="prefill_heavy", wave_n: int = 4,
                           wave_qps: float = 8.0, seed: int = 0,
                           vocab: int = 256,
                           temperature: float = 0.0,
                           repeats: int = 1) -> dict:
    """The interference-isolation A/B drive (ISSUE 15): a steady Poisson
    stream of ``mix`` requests, plus a concurrent **admission wave** of
    ``wave_n`` ``wave_mix`` (long-prompt) requests offered at
    ``wave_qps`` starting once the steady stream is warm (~1/3 through).
    Every steady-stream token event records its inter-token gap with a
    timestamp; the summary classifies gaps into the **quiet** window vs
    the **wave** window (first wave request sent → last wave stream
    done), so ``wave_tpot_p99_ms / quiet_tpot_p99_ms`` measures exactly
    how much a long-prompt admission wave degrades IN-FLIGHT decode
    TPOT — flat for a disaggregated engine, inflated for the colocated
    chunked-prefill baseline.  Seeded like :func:`run_load`: a rerun
    offers the identical workload.

    ``repeats`` runs the whole steady+wave cycle that many times and
    POOLS the gap samples (per-cycle wave windows): a p99 over one
    cycle's ~10² wave-window gaps is essentially the max of the set and
    flaps on a single OS hiccup; pooling 3 cycles' samples makes the
    isolation gate CI-stable.  ``repeats=1`` is byte-identical to the
    pre-repeat behavior (cycle r>0 reseeds at ``seed + 1000*r``)."""
    loop = asyncio.get_running_loop()
    (plo, phi), (nlo, nhi) = MIXES[mix] if isinstance(mix, str) else mix
    (wplo, wphi), (wnlo, wnhi) = (MIXES[wave_mix]
                                  if isinstance(wave_mix, str) else wave_mix)

    async def _cycle(cycle_seed):
        rng = np.random.default_rng(cycle_seed)
        wave_rng = np.random.default_rng(cycle_seed + 1)
        t_start = loop.time()
        wave_window = {"t0": None, "t1": None}

        async def _steady():
            t_next, tasks = 0.0, []
            for _ in range(int(n_requests)):
                plen = int(rng.integers(plo, phi + 1))
                payload = {
                    "prompt": [int(x) for x in rng.integers(0, vocab,
                                                            (plen,))],
                    "max_new_tokens": int(rng.integers(nlo, nhi + 1)),
                    "temperature": float(temperature),
                }
                delay = (t_start + t_next) - loop.time()
                if delay > 0:
                    await asyncio.sleep(delay)
                tasks.append(asyncio.ensure_future(
                    _one_request(host, port, payload, record_gaps=True)))
                t_next += float(rng.exponential(1.0 / float(qps)))
            return await asyncio.gather(*tasks)

        async def _wave():
            # warm-up: let ~1/3 of the steady stream land first so the
            # quiet window has samples
            await asyncio.sleep((n_requests / 3.0) / float(qps))
            wave_window["t0"] = time.perf_counter()
            t_next, tasks = 0.0, []
            w0 = loop.time()
            for _ in range(int(wave_n)):
                plen = int(wave_rng.integers(wplo, wphi + 1))
                payload = {
                    "prompt": [int(x) for x in wave_rng.integers(
                        0, vocab, (plen,))],
                    "max_new_tokens": int(wave_rng.integers(wnlo,
                                                            wnhi + 1)),
                    "temperature": float(temperature),
                }
                delay = (w0 + t_next) - loop.time()
                if delay > 0:
                    await asyncio.sleep(delay)
                tasks.append(asyncio.ensure_future(
                    _one_request(host, port, payload)))
                t_next += float(wave_rng.exponential(
                    1.0 / float(wave_qps)))
            out = await asyncio.gather(*tasks)
            wave_window["t1"] = time.perf_counter()
            return out

        steady, wave = await asyncio.gather(_steady(), _wave())
        return steady, wave, wave_window, loop.time() - t_start

    all_steady, quiet, waved = [], [], []
    wave_sent = wave_done = 0
    wall = 0.0
    for rep in range(max(1, int(repeats))):
        steady, wave, window, cycle_wall = await _cycle(
            seed + 1000 * rep)
        wall += cycle_wall
        all_steady.extend(steady)
        t0, t1 = window["t0"], window["t1"]
        for r in steady:
            for ts, gap in r.get("gaps", ()):
                (waved if (t0 is not None and t0 <= ts <= t1)
                 else quiet).append(gap)
        wave_sent += int(wave_n)
        wave_done += sum(1 for r in wave if r["status"] == 200
                         and r["finish_reason"] not in
                         (None, "error", "connection_error"))
    summary = summarize(all_steady, wall, qps=float(qps),
                        mix=(mix if isinstance(mix, str) else "custom"))
    summary["wave"] = {
        "mix": (wave_mix if isinstance(wave_mix, str) else "custom"),
        "requests": wave_sent,
        "completed": wave_done,
        "repeats": max(1, int(repeats)),
        "quiet_gaps": len(quiet),
        "wave_gaps": len(waved),
        "quiet_tpot_p50_ms": round(1e3 * percentile(quiet, 0.50), 3),
        "quiet_tpot_p99_ms": round(1e3 * percentile(quiet, 0.99), 3),
        "wave_tpot_p50_ms": round(1e3 * percentile(waved, 0.50), 3),
        "wave_tpot_p99_ms": round(1e3 * percentile(waved, 0.99), 3),
    }
    return summary


def run_interference_sync(host, port, qps, n_requests, **kw) -> dict:
    """:func:`run_interference` from synchronous code."""
    return asyncio.run(run_interference(host, port, qps, n_requests,
                                        **kw))


def summarize(recs: List[dict], wall_s: float, qps: float,
              mix: str) -> dict:
    """Roll per-request records into the serve-bench metrics.  Goodput
    counts only tokens of streams that COMPLETED (got their done
    event); shed rate counts 429+503 over everything sent."""
    done = [r for r in recs if r["status"] == 200
            and r["finish_reason"] not in (None, "error",
                                           "connection_error")]
    shed = [r for r in recs if r["status"] in (429, 503)]
    n_errors = len(recs) - len(done) - len(shed)
    # a stream the server ACCEPTED (200) but never finished cleanly: the
    # number the replica-kill chaos line hard-asserts to be zero —
    # failover must resume streams, not drop them
    dropped = [r for r in recs if r["status"] == 200
               and r["finish_reason"] in (None, "error",
                                          "connection_error")]
    goodput_tokens = sum(r["tokens"] for r in done)
    ttfts = [r["ttft"] for r in done if r["ttft"] is not None]
    tpots = [r["tpot"] for r in done if r["tpot"] is not None]
    return {
        "qps": qps,
        "mix": mix,
        "sent": len(recs),
        "completed": len(done),
        "shed": len(shed),
        "errors": n_errors,
        "dropped_streams": len(dropped),
        "shed_rate": round(len(shed) / max(len(recs), 1), 4),
        "goodput_tokens": goodput_tokens,
        "goodput_tokens_per_sec": round(goodput_tokens / wall_s, 2)
        if wall_s > 0 else 0.0,
        "qps_achieved": round(len(recs) / wall_s, 2) if wall_s > 0
        else 0.0,
        "ttft_p50_ms": round(1e3 * percentile(ttfts, 0.50), 3),
        "ttft_p99_ms": round(1e3 * percentile(ttfts, 0.99), 3),
        "tpot_p50_ms": round(1e3 * percentile(tpots, 0.50), 3),
        "tpot_p99_ms": round(1e3 * percentile(tpots, 0.99), 3),
        "wall_s": round(wall_s, 3),
    }
