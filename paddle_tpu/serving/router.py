"""Replicated serving fleet (ISSUE 19) — a router tier over N
data-parallel scheduler+engine replicas.

The router is to replicas what the disaggregated scheduler is to roles:
one admission point in front of N independent
:class:`~.scheduler.ContinuousBatchingScheduler` +
:class:`~.engine.DecodeEngine` pairs, each driven by its own thread
("serve-replica-<i>" — the in-process stand-in for one serving
process; the TCPStore rendezvous path is stubbed behind the same
interface, see :class:`RemoteReplicaHandle`).

**Routing ladder.**  Admission consults, in order:

1. *Prefix affinity* — the prompt's chained page digests
   (:func:`~.pages.prompt_digest_chain`) are intersected against each
   replica's advertised digest view (device hash table + host tier +
   its :class:`~.kv_tier.ClusterPrefixIndex` offerings, refreshed by
   the health probe).  The replica covering the longest prefix wins;
   ties break least-loaded.  A replica whose view is STALE (older than
   ``snapshot_ttl``) makes no affinity claim — a stale index entry can
   only mis-score one routing decision, never error: admission
   re-derives exact coverage under the allocator's own bookkeeping.
2. *Least-loaded* — over replicas with a fresh telemetry snapshot
   (queue depth + active slots + command backlog, the PR-13 snapshot
   shape) whose step beacon isn't aging past ``route_around_after``: a
   stalling-but-not-yet-dead replica is routed AROUND before it is
   declared dead.
3. *Round-robin* — total telemetry blackout (cold start, probe not yet
   run) must not shed the fleet while replicas are alive.

**Failover** (the headline robustness mechanism).  A replica death —
the ``serve.replica`` faultpoint firing :class:`~..robustness.
faultpoints.HardExit` (contained to the thread by ``crash_scope``) or
``Hang``, or the health probe tripping on beacon age — drains that
replica's in-flight requests back through the router.  The router's own
per-request admission records (request, delivered tokens, timing, trace
lane — appended *before* each token is forwarded, on the same thread,
so the record always equals what the stream saw) are the source of
truth: a crashed scheduler exports nothing.  Each record is repacked as
a :class:`~.scheduler.RequeueState` and requeued onto a survivor via
the existing recompute-preemption path: the survivor re-prefills
``prompt + generated`` (mostly prefix-hitting its cache through the
cluster index), the SSE stream RESUMES at the next token instead of
dropping, and greedy output stays bit-identical to an undisturbed run.
Requeues respect a ``max_preemptions``-style bound (``max_requeues``,
shared with page-pressure evictions via ``_preempt_count`` seeding); a
request past it finishes ``"failover_limit"`` — a delivered done event,
never a silent drop.  The PR-4 launcher discipline respawns the dead
replica (delay doubles per death before ``healthy_interval`` of uptime,
resets after a healthy run); a respawned replica rejoins the routable
set only after a healthy interval.  In-process respawn reuses the
replica's engine (``engine.reset()``), so compiled programs survive and
the compile-once budget stays exactly 1 per watched entry per replica
across the failover wave; the multi-host path pays a real recompile and
is gated there.

Why token delivery can't tear: the faultpoint fires BETWEEN scheduler
iterations, and within one iteration token notification and finish
both happen inside ``step()`` — so a router record can never hold a
finished request's tokens without its finish having been forwarded.
"""
from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from ..observability import flight as _flight
from ..observability import registry as _metrics
from ..observability import tracing as _tracing
from ..robustness import faultpoints as _fp
from .kv_tier import _hex, fetch_index
from .pages import prompt_digest_chain
from .scheduler import (ContinuousBatchingScheduler, Request,
                        RequestResult, RequeueState)

__all__ = ["Router", "RemoteReplicaHandle", "NoHealthyReplicas",
           "REPLICA_SITE"]

#: chaos site inside every replica step-loop iteration: ``HardExit``
#: here is a replica crash (contained to the replica thread by the
#: faultpoints crash scope), ``Hang`` a wedged replica the health
#: probe trips on — both end in stream-preserving failover
REPLICA_SITE = _fp.declare(
    "serve.replica",
    "fires at the top of every router-tier replica step-loop iteration "
    "(HardExit = replica crash, contained to its thread by the crash "
    "scope; Hang = wedged replica for the health probe) — either way "
    "the router fails the replica's streams over to survivors")

_SNAP_FORMAT = "paddle_tpu-telemetry-v1"


class NoHealthyReplicas(RuntimeError):
    """Every replica is dead or still (re)joining — admission must shed
    (the front-end maps this to 503), it cannot queue onto nothing."""


class _Flight:
    """Router-side record of ONE accepted request — the failover source
    of truth.  ``tokens`` is appended on the owning replica's scheduler
    thread BEFORE the token callback is forwarded, so it always equals
    exactly what the downstream stream has seen."""

    __slots__ = ("req", "replica", "submit_t", "first_tok_t", "tokens",
                 "requeues", "trace_id", "root_span", "cancelled")

    def __init__(self, req, replica, submit_t, trace_id, root_span):
        self.req = req
        self.replica = replica          # owning replica idx
        self.submit_t = submit_t
        self.first_tok_t = None
        self.tokens: List[int] = []
        self.requeues = 0
        self.trace_id = trace_id
        self.root_span = root_span
        self.cancelled = False


class _Replica:
    """One in-process scheduler+engine replica and its driver thread.

    The thread is the replica's *scheduler thread* (tpu-race role):
    sole caller of scheduler methods.  Cross-thread intake happens
    through the command queues under ``lock`` (the front-end/router
    enqueue; the loop drains) — the disagg/front-end discipline one
    level up.  ``epoch`` guards zombies: a Hang-wedged thread that
    finally wakes after the probe declared it dead (and possibly
    respawned the replica) sees a bumped epoch and exits without
    touching the replacement scheduler."""

    def __init__(self, idx: int, engine, router: "Router"):
        self.idx = idx
        self.engine = engine
        self._router = router
        self.scheduler: Optional[ContinuousBatchingScheduler] = None
        self.thread: Optional[threading.Thread] = None
        self.lock = threading.Lock()
        self.wake = threading.Event()
        self._pending: List[Tuple[Request, tuple]] = []
        self._transfers: List[RequeueState] = []
        self._cancels: List[int] = []
        self.retiring = False           # graceful decommission flag
        self.stopping = False           # router shutdown flag
        # lifecycle (all guarded by the ROUTER lock): healthy | joining
        # | dead | stopped
        self.state = "joining"
        self.epoch = 0
        self.deaths = 0
        self.backoff = 0.0
        self.respawn_at: Optional[float] = None
        self.started_t = 0.0
        # progress + advisory views the probe refreshes (router lock)
        self.last_progress = 0.0
        self.steps_total = 0
        self.busy = False
        self.snap: Optional[dict] = None
        self.snap_ts: Optional[float] = None
        self.view_digests: Set[str] = set()
        self.view_ts: Optional[float] = None

    # -- cross-thread intake (any thread) ----------------------------------

    def enqueue_submit(self, req: Request, trace: tuple):
        with self.lock:
            self._pending.append((req, trace))
        self.wake.set()

    def enqueue_transfer(self, state: RequeueState):
        with self.lock:
            self._transfers.append(state)
        self.wake.set()

    def enqueue_cancel(self, rid: int):
        with self.lock:
            self._cancels.append(rid)
        self.wake.set()

    def backlog(self) -> int:
        with self.lock:
            return len(self._pending) + len(self._transfers)

    def clear_queues(self):
        with self.lock:
            self._pending, self._transfers, self._cancels = [], [], []

    # -- the replica thread ------------------------------------------------

    def _run(self, epoch: int):
        try:
            with _fp.crash_scope():
                self._loop(epoch)
        except _fp.CrashScopeExit as e:
            # the simulated process death: die like the process would —
            # report and stop, taking nothing else down
            self._router._replica_died(self, "crash", rc=e.rc)
        except BaseException as e:  # noqa: BLE001 — replica = process
            _flight.thread_exception_dump(
                "serve-replica-%d" % self.idx, e)
            self._router._replica_died(self, "error")

    def _loop(self, epoch: int):
        sched = self.scheduler
        while True:
            # the chaos site sits BETWEEN iterations: a Hang here wedges
            # the replica with the scheduler in a consistent state, so
            # the probe-tripped failover never races a half-applied step
            _fp.faultpoint(REPLICA_SITE, replica=self.idx,
                           scheduler=sched)
            if self.epoch != epoch or self.stopping:
                return              # declared dead (zombie) or shutdown
            with self.lock:
                pending, self._pending = self._pending, []
                transfers, self._transfers = self._transfers, []
                cancels, self._cancels = self._cancels, []
                self.wake.clear()
            for req, trace in pending:
                try:
                    sched.submit(req, trace=trace)
                except ValueError:
                    # the router pre-validates; a late mismatch (engine
                    # hot-swapped under a respawn) degrades to an error
                    # finish, never a dead replica thread
                    self._router._finish_flight(req.rid, "error")
            for state in transfers:
                sched.import_requeue(state)
            for rid in cancels:
                sched.cancel(rid)
            if self.retiring:
                # graceful decommission: commands above were drained
                # INTO the scheduler first so the export covers them
                states = sched.export_requeue_state()
                self._router._decommissioned(self, states)
                return
            if sched.has_work():
                sched.step()
                self.steps_total += 1
            else:
                self.wake.wait(0.005)
            self.busy = sched.has_work()
            self.last_progress = time.monotonic()


class RemoteReplicaHandle:
    """The TCPStore rendezvous path, stubbed behind the replica
    interface: a replica living in ANOTHER process/host whose routing
    views are real — :meth:`refresh` reads the same advisory documents
    the fleet already publishes (``kv_tier.fetch_index`` digests,
    PR-13 telemetry snapshots) — but whose intake requires the
    cross-host request transport that lands with the multi-host serving
    PR, so every enqueue raises :class:`NotImplementedError`.  Keeping
    the surface identical means the router's ladder code won't change
    when remote intake arrives; only this class does."""

    state = "remote"

    def __init__(self, host: int, store, world_size: int):
        self.idx = int(host)
        self.store = store
        self.world_size = int(world_size)
        self.snap: Optional[dict] = None
        self.snap_ts: Optional[float] = None
        self.view_digests: Set[str] = set()
        self.view_ts: Optional[float] = None

    def refresh(self, now: Optional[float] = None):
        """Pull this host's published digest set and telemetry snapshot
        from the store; missing/garbage documents leave the views stale
        (the router then routes around, exactly as for a silent local
        replica)."""
        from ..observability import aggregate as _agg
        now = time.monotonic() if now is None else now
        idx = fetch_index(self.store, self.world_size)
        if self.idx in idx:
            self.view_digests = idx[self.idx]
            self.view_ts = now
        docs = _agg.fetch_cluster(self.store, self.world_size)
        if self.idx in docs:
            self.snap = docs[self.idx]
            self.snap_ts = now

    def enqueue_submit(self, req, trace):
        raise NotImplementedError(
            "cross-host request intake lands with the multi-host "
            "serving PR; RemoteReplicaHandle is routing-view only")

    enqueue_transfer = enqueue_cancel = enqueue_submit


class Router:
    """N-replica admission tier: prefix-affinity routing, health-driven
    fallback, stream-preserving failover (module docstring has the
    protocol).  Thread model — four roles, audited by tpu-race:

    * *callers* (``submit``/``cancel``, any thread incl. the
      front-end's event loop): pure-CPU hashing + lock-scoped table
      writes, never a scheduler call, never blocking on device work;
    * *replica threads* (one per replica): sole scheduler callers;
      deliver token/finish callbacks through the router's wrappers;
    * the *health probe* ("serve-router-probe", monitor role): view
      refresh, stall tripping, respawn/rejoin — every transition under
      the router lock; ``probe_interval=None`` disables the thread and
      tests drive :meth:`probe_once` deterministically;
    * the *dying replica thread itself* runs crash failover (it owns
      the dying scheduler, so nothing races it).

    Lock discipline: the router lock guards the flight table, replica
    lifecycle and the cached views; each replica's lock guards only its
    command queues.  Neither is ever held while acquiring the other."""

    def __init__(self, engines, tracer=None, overlap=None,
                 on_token=None, on_finish=None, affinity=True,
                 snapshot_ttl=2.0, route_around_after=None,
                 stall_deadline=30.0, probe_interval=0.25,
                 max_requeues=3, respawn_delay=0.1,
                 respawn_max_delay=2.0, healthy_interval=1.0):
        if not engines:
            raise ValueError("Router needs at least one replica engine")
        self.on_token = on_token        # (rid, [ids]) — post-record
        self.on_finish = on_finish      # (RequestResult)
        self.affinity = bool(affinity)
        self.snapshot_ttl = float(snapshot_ttl)
        self.route_around_after = (float(stall_deadline) / 2.0
                                   if route_around_after is None
                                   else float(route_around_after))
        self.stall_deadline = float(stall_deadline)
        self.probe_interval = probe_interval
        self.max_requeues = int(max_requeues)
        self.respawn_delay = float(respawn_delay)
        self.respawn_max_delay = float(respawn_max_delay)
        self.healthy_interval = float(healthy_interval)
        self.prompt_cap = min(int(e.prompt_cap) for e in engines)
        self._paged = all(e.paged for e in engines)
        self._page_size = (min(int(e.page_size) for e in engines)
                           if self._paged else 0)
        self._overlap = overlap
        self._tracer = (tracer if tracer is not None
                        else _tracing.default_tracer())
        self._lock = threading.Lock()
        self._flights: Dict[int, _Flight] = {}
        self._next_rid = 0
        self._rr = 0                    # blackout round-robin cursor
        self._stop = threading.Event()
        self._probe_thread: Optional[threading.Thread] = None
        # metric handles fetched ONCE (no-op singletons when disabled)
        self._m_routed = _metrics.counter("router.routed", ("reason",))
        self._m_healthy = _metrics.gauge("router.replicas_healthy")
        self._m_failovers = _metrics.counter("router.failovers")
        self._replicas = [_Replica(i, e, self)
                          for i, e in enumerate(engines)]
        for r in self._replicas:
            r.scheduler = self._make_scheduler(r)

    # -- wiring ------------------------------------------------------------

    def _make_scheduler(self, replica: _Replica):
        on_token, on_finish = self._make_callbacks(replica)
        return ContinuousBatchingScheduler(
            replica.engine, tracer=self._tracer, overlap=self._overlap,
            on_token=on_token, on_finish=on_finish)

    def _make_callbacks(self, replica: _Replica):
        def on_token(rid, toks):
            with self._lock:
                fl = self._flights.get(rid)
                if fl is None or fl.replica != replica.idx:
                    return          # stale emission of a moved rid
                fl.tokens.extend(int(t) for t in toks)
                if fl.first_tok_t is None:
                    fl.first_tok_t = time.perf_counter()
                cb = self.on_token
            if cb is not None:
                cb(rid, toks)

        def on_finish(result: RequestResult):
            with self._lock:
                fl = self._flights.get(result.rid)
                if fl is None or fl.replica != replica.idx:
                    return
                del self._flights[result.rid]
                cb = self.on_finish
            # the scheduler's _retire already ended the adopted root
            # span — only synthesized finishes end it router-side
            if cb is not None:
                cb(result)

        return on_token, on_finish

    def start(self) -> "Router":
        now = time.monotonic()
        for r in self._replicas:
            with self._lock:
                # founding replicas are routable immediately: with no
                # survivor set yet there is nothing safer to prefer
                r.state = "healthy"
                r.started_t = now
                r.last_progress = now
                r.epoch += 1
            t = threading.Thread(target=r._run, args=(r.epoch,),
                                 name="serve-replica-%d" % r.idx,
                                 daemon=True)
            r.thread = t
            t.start()
        self._m_healthy.set(self.healthy_count())
        if self.probe_interval is not None:
            self._probe_thread = threading.Thread(
                target=self._probe_main, name="serve-router-probe",
                daemon=True)
            self._probe_thread.start()
        return self

    def stop(self, timeout: float = 10.0):
        self._stop.set()
        if self._probe_thread is not None:
            self._probe_thread.join(timeout)
            self._probe_thread = None
        for r in self._replicas:
            r.stopping = True
            r.wake.set()
        for r in self._replicas:
            if r.thread is not None:
                r.thread.join(timeout)
            with self._lock:
                if r.state not in ("dead",):
                    r.state = "stopped"
        self._m_healthy.set(0)

    # -- admission ---------------------------------------------------------

    def submit(self, req: Request, on_admit=None) -> int:
        """Route + dispatch one request; returns its fleet-unique rid.

        Validation mirrors ``scheduler.submit`` so a bad request fails
        HERE (the front-end 400s it) instead of on a replica thread.
        ``on_admit(rid, root_span)`` — when given — runs after the rid
        and trace root exist but BEFORE the request reaches a replica:
        the front-end registers its stream inside that window, so the
        first token can never race the registration."""
        prompt = np.asarray(req.prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("empty prompt")
        if prompt.size > self.prompt_cap:
            raise ValueError(
                "prompt length %d exceeds the fleet's prompt capacity %d"
                % (prompt.size, self.prompt_cap))
        if req.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        with self._lock:
            rid = self._next_rid
            self._next_rid += 1
        req = dataclasses.replace(req, prompt=prompt, rid=rid)
        # the trace lane is born at the ROUTER (not the replica): the
        # root must outlive any one replica for the tree to survive
        # failover; schedulers adopt it via submit(trace=...)
        tid = self._tracer.new_trace()
        root = self._tracer.span(
            "request", trace_id=tid, rid=rid,
            prompt_len=int(prompt.size),
            max_new_tokens=int(req.max_new_tokens))
        fl = _Flight(req, -1, time.perf_counter(), tid, root)
        target = None
        try:
            for _ in range(4):
                target, reason = self._route(prompt)
                with self._lock:
                    if target.state == "healthy":
                        fl.replica = target.idx
                        self._flights[rid] = fl
                        break
                    target = None       # died between route and claim
            if target is None:
                raise NoHealthyReplicas(
                    "no healthy replica to route to")
        except NoHealthyReplicas:
            root.end(reason="no_replica")
            raise
        self._tracer.span("router", trace_id=tid, parent=root,
                          replica=target.idx, reason=reason).end()
        self._m_routed.labels(reason=reason).inc()
        if on_admit is not None:
            on_admit(rid, root)
        target.enqueue_submit(req, (tid, root))
        return rid

    def cancel(self, rid: int) -> bool:
        """Forward a cancel to the owning replica (its thread applies
        it).  A rid mid-failover is flagged so the requeue synthesizes
        the ``"cancelled"`` finish instead of resuming."""
        with self._lock:
            fl = self._flights.get(rid)
            if fl is None:
                return False
            fl.cancelled = True
            target = self._replicas[fl.replica]
        target.enqueue_cancel(rid)
        return True

    # -- the routing ladder ------------------------------------------------

    def _fresh(self, r, now: float) -> bool:
        if r.snap_ts is None or now - r.snap_ts > self.snapshot_ttl:
            return False            # stale/missing snapshot: around it
        age = r.snap.get("beacon_age_s", 0.0)
        busy = r.snap.get("busy") or r.snap.get("backlog")
        return not (busy and age > self.route_around_after)

    @staticmethod
    def _load(r) -> int:
        snap = r.snap or {}
        return (int(snap.get("queue_depth", 0))
                + int(snap.get("slots_active", 0))
                + int(snap.get("backlog", 0)))

    def _route(self, prompt) -> Tuple[_Replica, str]:
        with self._lock:
            routable = [r for r in self._replicas
                        if r.state == "healthy"]
        if not routable:
            raise NoHealthyReplicas(
                "all %d replicas dead or joining" % len(self._replicas))
        now = time.monotonic()
        if self.affinity and self._paged:
            chain = [_hex(d) for d in
                     prompt_digest_chain(prompt, self._page_size)]
            best, best_cov = None, 0
            for r in routable:
                if (r.view_ts is None
                        or now - r.view_ts > self.snapshot_ttl):
                    continue        # stale view makes no affinity claim
                cov = 0
                for h in chain:
                    if h not in r.view_digests:
                        break
                    cov += 1
                if cov > best_cov or (cov == best_cov and cov
                                      and self._load(r)
                                      < self._load(best)):
                    best, best_cov = r, cov
            if best is not None and best_cov > 0:
                return best, "affinity"
        fresh = [r for r in routable if self._fresh(r, now)]
        if fresh:
            return (min(fresh, key=lambda r: (self._load(r), r.idx)),
                    "least_loaded")
        with self._lock:
            r = routable[self._rr % len(routable)]
            self._rr += 1
        return r, "least_loaded"

    # -- failover ----------------------------------------------------------

    def _replica_died(self, replica: _Replica, cause: str, rc=None):
        """Declare a replica dead and fail its streams over.  Runs on
        the dying replica thread (crash — it owns the scheduler, so
        nothing races it) or the probe (stall trip — the zombie is
        fenced by the epoch bump before anything else happens)."""
        now = time.monotonic()
        with self._lock:
            if replica.state in ("dead", "stopped"):
                return              # hang-trip raced the late crash
            replica.state = "dead"
            replica.epoch += 1      # fence any wedged zombie thread
            replica.deaths += 1
            uptime = now - replica.started_t
            if uptime >= self.healthy_interval:
                replica.backoff = self.respawn_delay
            else:
                replica.backoff = min(
                    max(replica.backoff, self.respawn_delay) * 2,
                    self.respawn_max_delay)
            replica.respawn_at = now + replica.backoff
            flights = [fl for fl in self._flights.values()
                       if fl.replica == replica.idx]
        self._m_failovers.inc()
        self._m_healthy.set(self.healthy_count())
        _flight.record("router_failover", replica=replica.idx,
                       cause=cause, rc=rc, inflight=len(flights),
                       deaths=replica.deaths,
                       respawn_backoff=round(replica.backoff, 3))
        for fl in flights:
            self._requeue_flight(fl)

    def _requeue_flight(self, fl: _Flight):
        """Move one orphaned flight to a survivor through the recompute
        path, honoring the cancel flag and the requeue budget."""
        if fl.cancelled:
            self._finish_flight(fl.req.rid, "cancelled")
            return
        fl.requeues += 1
        if fl.requeues > self.max_requeues:
            self._finish_flight(fl.req.rid, "failover_limit")
            return
        try:
            target, _ = self._route(fl.req.prompt)
        except NoHealthyReplicas:
            # total fleet death: deliver the error finish — a closed
            # stream with a reason, never a silent drop
            self._finish_flight(fl.req.rid, "error")
            return
        with self._lock:
            fl.replica = target.idx
        state = RequeueState(
            req=fl.req, generated=list(fl.tokens),
            submit_t=fl.submit_t, first_tok_t=fl.first_tok_t,
            requeues=fl.requeues, trace_id=fl.trace_id,
            root_span=fl.root_span,
            # queue_wait is scheduler-side state the router never sees:
            # None routes a token-less victim through fresh admission
            # (one queue_wait sample); a victim with delivered tokens
            # was certainly admitted — 0.0 parks it on the resume path
            # so the histogram is not re-fed
            queue_wait=0.0 if fl.tokens else None)
        self._m_routed.labels(reason="failover").inc()
        fl.root_span.event("failover", to_replica=target.idx,
                           requeues=fl.requeues,
                           tokens=len(fl.tokens))
        target.enqueue_transfer(state)

    def _finish_flight(self, rid: int, reason: str):
        """Synthesize a finish the owning scheduler can no longer (or
        should not) deliver; forwards through the normal callback."""
        with self._lock:
            fl = self._flights.pop(rid, None)
            cb = self.on_finish
        if fl is None:
            return
        got_first = fl.first_tok_t is not None
        res = RequestResult(
            rid=rid, tokens=np.asarray(fl.tokens, np.int32),
            finish_reason=reason,
            ttft=(fl.first_tok_t - fl.submit_t) if got_first else 0.0,
            tpot=0.0, trace_id=fl.trace_id)
        fl.root_span.end(reason=reason, tokens=len(fl.tokens))
        if cb is not None:
            cb(res)

    def _decommissioned(self, replica: _Replica, states):
        """Graceful retirement: the replica thread exported its whole
        unfinished intake (full-fidelity RequeueStates — timing and
        queue_wait travel exactly) and exits; the router re-places each
        on a survivor."""
        with self._lock:
            replica.state = "stopped"
            replica.epoch += 1
        self._m_healthy.set(self.healthy_count())
        for st in states:
            with self._lock:
                fl = self._flights.get(st.req.rid)
            if fl is None:
                continue
            if fl.cancelled:
                self._finish_flight(st.req.rid, "cancelled")
                continue
            st.requeues += 1
            fl.requeues = st.requeues
            if st.requeues > self.max_requeues:
                self._finish_flight(st.req.rid, "failover_limit")
                continue
            try:
                target, _ = self._route(st.req.prompt)
            except NoHealthyReplicas:
                self._finish_flight(st.req.rid, "error")
                continue
            with self._lock:
                fl.replica = target.idx
            self._m_routed.labels(reason="failover").inc()
            target.enqueue_transfer(st)

    def decommission(self, idx: int):
        """Ask replica ``idx`` to gracefully retire: it drains its
        scheduler through :meth:`~.scheduler.ContinuousBatchingScheduler.
        export_requeue_state` on its own thread and the router requeues
        every unfinished request onto survivors.  The replica leaves
        the routable set permanently."""
        r = self._replicas[idx]
        with self._lock:
            if r.state == "healthy":
                r.state = "joining"     # unroutable while draining
        r.retiring = True
        r.wake.set()

    # -- health probe ------------------------------------------------------

    def _probe_main(self):
        while not self._stop.is_set():
            try:
                self.probe_once()
            except Exception as e:  # the probe must never die silently
                _flight.thread_exception_dump("serve-router-probe", e)
            self._stop.wait(self.probe_interval)

    def probe_once(self, now: Optional[float] = None):
        """One health-probe sweep: refresh every live replica's
        telemetry snapshot + digest view, trip failover on a stalled
        step beacon, execute due respawns, and promote joined replicas.
        Deterministic under an injected ``now`` (tests drive it); the
        probe thread loops it."""
        now = time.monotonic() if now is None else now
        with self._lock:
            replicas = list(self._replicas)
        for r in replicas:
            if r.state in ("healthy", "joining"):
                self._refresh(r, now)
                backlog = r.backlog()
                wedged = ((r.busy or backlog)
                          and now - r.last_progress
                          > self.stall_deadline)
                dead_thread = (r.thread is not None
                               and not r.thread.is_alive()
                               and not r.stopping)
                if wedged or dead_thread:
                    self._replica_died(r, "stall" if wedged
                                       else "thread_death")
                    continue
                if r.state == "joining" and not r.retiring and (
                        now - r.started_t >= self.healthy_interval):
                    with self._lock:
                        if r.state == "joining":
                            r.state = "healthy"
                    _flight.record("router_rejoin", replica=r.idx,
                                   deaths=r.deaths)
            elif (r.state == "dead" and r.respawn_at is not None
                    and now >= r.respawn_at and not self._stop.is_set()):
                self._respawn(r, now)
        self._m_healthy.set(self.healthy_count())

    def _refresh(self, r: _Replica, now: float):
        sched = r.scheduler
        try:
            queue_depth = len(sched.waiting)
            slots = sum(a is not None for a in sched.slots)
        except Exception:
            return                  # scheduler mid-replacement
        backlog = r.backlog()
        snap = {"format": _SNAP_FORMAT, "host": r.idx,
                "wall_ts": time.time(), "queue_depth": queue_depth,
                "slots_active": slots, "backlog": backlog,
                "busy": r.busy, "steps_total": r.steps_total,
                "beacon_age_s": max(0.0, now - r.last_progress)}
        digests = r.engine.prefix_digest_snapshot()
        with self._lock:
            r.snap, r.snap_ts = snap, now
            r.view_digests, r.view_ts = digests, now

    def _respawn(self, r: _Replica, now: float):
        """The PR-4 launcher discipline, in-process: rebuild the
        replica's scheduler on its (reset) engine and restart the
        thread as JOINING — routable only after ``healthy_interval``.
        Reusing the engine keeps its compiled programs: compile counts
        stay exactly 1 per watched entry per replica across the wave
        (the process-level respawn of the multi-host path recompiles,
        and is gated there)."""
        r.engine.reset()
        r.scheduler = self._make_scheduler(r)
        r.clear_queues()
        r.stopping = False
        r.retiring = False
        r.busy = False
        with self._lock:
            r.state = "joining"
            r.started_t = now
            r.last_progress = time.monotonic()
            r.snap = r.snap_ts = None
            r.view_digests, r.view_ts = set(), None
            r.epoch += 1
            epoch = r.epoch
        t = threading.Thread(target=r._run, args=(epoch,),
                             name="serve-replica-%d" % r.idx,
                             daemon=True)
        r.thread = t
        t.start()
        _flight.record("router_respawn", replica=r.idx,
                       deaths=r.deaths,
                       backoff=round(r.backoff, 3))

    # -- introspection -----------------------------------------------------

    def healthy_count(self) -> int:
        with self._lock:
            return sum(r.state == "healthy" for r in self._replicas)

    def replica_states(self) -> List[str]:
        with self._lock:
            return [r.state for r in self._replicas]

    def flights(self) -> int:
        with self._lock:
            return len(self._flights)

    def queue_depth(self) -> int:
        """Advisory fleet-wide backlog (healthz): waiting + command
        queues across live replicas — cross-thread reads of plain
        containers, same contract as the front-end's healthz view."""
        n = 0
        for r in self._replicas:
            if r.state in ("healthy", "joining"):
                try:
                    n += len(r.scheduler.waiting) + r.backlog()
                except Exception:
                    pass
        return n

    def slots_active(self) -> int:
        n = 0
        for r in self._replicas:
            if r.state in ("healthy", "joining"):
                try:
                    n += sum(a is not None for a in r.scheduler.slots)
                except Exception:
                    pass
        return n

    @property
    def engines(self):
        return [r.engine for r in self._replicas]

    @property
    def replicas(self):
        return list(self._replicas)
