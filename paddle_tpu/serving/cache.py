"""Static-shape KV cache layouts — the serving engine's memory.

The decode path's non-negotiable TPU precondition is a *static-shape*
program: the legacy cache grew by ``concat`` each token, so its shape
changed every step and **every generated token retraced and recompiled
the whole model**.  Two static layouts live here:

* :class:`SlottedKVCache` — per-slot contiguous (PR 5):

      k, v : (num_slots, layers, max_len, heads, head_dim)
      lengths : (num_slots,) int32           # valid prefix per slot

  Every slot pays (and the decode read streams around) a full
  ``max_len`` buffer no matter how many tokens it actually holds.

* :class:`PagedKVCache` — vLLM-style block-structured memory
  (PagedAttention, SOSP '23) adapted to XLA's static-shape discipline:

      k, v       : (num_pages, layers, page_size, heads, head_dim)
      page_table : (num_slots, max_pages) int32   # page ids per slot
      lengths    : (num_slots,) int32

  A slot's tokens live in the fixed pool pages its page-table row maps;
  decode appends scatter into the slot's current *tail* page and
  attention gathers only mapped pages.  Memory (and the KV read bound a
  page-aware schedule pays) scales with *actual* lengths, and identical
  prompt prefixes can map the SAME refcounted pages (hash-based prefix
  sharing — ``serving/pages.py`` owns the host-side allocator:
  free list, refcounts, prefix hashes, copy-on-write decisions).  All
  of it stays compile-once: the page table, lengths, and gather indices
  are ordinary traced int32 arrays.

Attention over either layout is masked to each slot's valid prefix: the
query token at block offset ``j`` of a slot with pre-append length ``n``
sits at global position ``n + j`` and may attend keys ``t <= n + j``.
That one formula covers batched decode (``j = 0``), multi-token
appends, chunked prefill (``j`` ranges over the chunk), and whole-prompt
prefill (``n = 0`` reduces it to the causal mask).

*Views* adapt a cache to the model's per-layer walk (they are
trace-time carriers, not pytrees — the arrays they hold thread through
``jit`` as ordinary tracers):

* :class:`DecodeView` / :class:`PagedDecodeView` — batched: batch dim ==
  num_slots, every active slot advances together in one fixed-shape
  program.
* :class:`PrefillView` — slotted bucketed prefill: one sequence, one
  (dynamic) slot index, writes rows ``[0, bucket)`` and runs plain
  block-causal attention (nothing prior to attend to).
* :class:`PagedPrefillChunkView` — one fixed-size chunk of one slot's
  prompt: writes positions ``[n, n + valid)`` into mapped pages and
  attends to the full mapped past + itself (the chunked-prefill
  program the engine interleaves with decode).

Dependency note: this module is imported by ``models/gpt.py`` and must
stay model-free (jax + the decode-attention kernel family only).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["SlottedKVCache", "DecodeView", "PrefillView", "PagedKVCache",
           "PagedDecodeView", "PagedPrefillChunkView", "is_cache_view"]


@jax.tree_util.register_pytree_node_class
class SlottedKVCache:
    """The preallocated cache state.  A registered pytree, so it passes
    through ``jax.jit`` boundaries (and ``donate_argnums``) directly."""

    def __init__(self, k, v, lengths):
        self.k = k
        self.v = v
        self.lengths = lengths

    def tree_flatten(self):
        return (self.k, self.v, self.lengths), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @classmethod
    def create(cls, num_slots, num_layers, max_len, num_heads, head_dim,
               dtype="float32"):
        shape = (int(num_slots), int(num_layers), int(max_len),
                 int(num_heads), int(head_dim))
        return cls(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype),
                   jnp.zeros((int(num_slots),), jnp.int32))

    # -- static geometry (python ints — safe at trace time) ----------------
    @property
    def num_slots(self):
        return int(self.k.shape[0])

    @property
    def num_layers(self):
        return int(self.k.shape[1])

    @property
    def max_len(self):
        return int(self.k.shape[2])

    def __repr__(self):
        return ("SlottedKVCache(slots=%d, layers=%d, max_len=%d, heads=%d, "
                "head_dim=%d, dtype=%s)"
                % (self.k.shape + (self.k.dtype,)))


@jax.tree_util.register_pytree_node_class
class PagedKVCache:
    """Block-structured cache state: a fixed pool of fixed-size KV pages
    plus a per-slot page table.  A registered pytree, so it passes through
    ``jax.jit`` boundaries (and ``donate_argnums``) directly.  Unmapped
    page-table entries hold 0 — they gather page 0's bytes, which the
    length mask discards before they reach the softmax."""

    def __init__(self, k, v, page_table, lengths, declared_max_len=None):
        self.k = k
        self.v = v
        self.page_table = page_table
        self.lengths = lengths
        # the DECLARED length budget, when tighter than pool capacity
        # (max_len % page_size != 0 leaves dead rows in the tail page);
        # static aux data, so it survives jit boundaries and tree maps
        self.declared_max_len = (None if declared_max_len is None
                                 else int(declared_max_len))

    def tree_flatten(self):
        return ((self.k, self.v, self.page_table, self.lengths),
                self.declared_max_len)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, declared_max_len=aux)

    @classmethod
    def create(cls, num_pages, num_layers, page_size, num_heads, head_dim,
               num_slots, max_pages, dtype="float32"):
        shape = (int(num_pages), int(num_layers), int(page_size),
                 int(num_heads), int(head_dim))
        return cls(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype),
                   jnp.zeros((int(num_slots), int(max_pages)), jnp.int32),
                   jnp.zeros((int(num_slots),), jnp.int32))

    @classmethod
    def create_dense(cls, num_slots, num_layers, max_len, num_heads,
                     head_dim, page_size, dtype="float32"):
        """A pool with exactly one page set per slot, identity-mapped
        (slot ``i`` owns pages ``[i*max_pages, (i+1)*max_pages)``) — the
        allocator-free layout for model-level use (``gen_paged_cache``):
        capacity matches the slotted cache, only the memory is paged."""
        max_pages = -(-int(max_len) // int(page_size))
        cache = cls.create(int(num_slots) * max_pages, num_layers,
                           page_size, num_heads, head_dim, num_slots,
                           max_pages, dtype)
        table = jnp.arange(int(num_slots) * max_pages,
                           dtype=jnp.int32).reshape(int(num_slots),
                                                    max_pages)
        return cls(cache.k, cache.v, table, cache.lengths,
                   declared_max_len=int(max_len))

    # -- static geometry (python ints — safe at trace time) ----------------
    @property
    def num_pages(self):
        return int(self.k.shape[0])

    @property
    def num_layers(self):
        return int(self.k.shape[1])

    @property
    def page_size(self):
        return int(self.k.shape[2])

    @property
    def num_slots(self):
        return int(self.page_table.shape[0])

    @property
    def max_pages(self):
        return int(self.page_table.shape[1])

    @property
    def max_len(self):
        cap = self.max_pages * self.page_size
        return cap if self.declared_max_len is None \
            else min(self.declared_max_len, cap)

    def __repr__(self):
        return ("PagedKVCache(pages=%d, layers=%d, page_size=%d, heads=%d, "
                "head_dim=%d, slots=%d, max_pages=%d, dtype=%s)"
                % (self.k.shape + self.page_table.shape[:2]
                   + (self.k.dtype,)))


def is_cache_view(obj) -> bool:
    return isinstance(obj, _CacheView)


def _unwrap(x):
    return x._array if hasattr(x, "_array") else x


def paged_scatter(kc, vc, layer, table, pos, valid, k_new, v_new):
    """Scatter ``k_new/v_new: (B, s, heads, head_dim)`` into page rows.

    ``table: (B, max_pages)`` maps each lane's pages; ``pos: (B, s)`` are
    global token positions; entries with ``valid`` False (inactive decode
    lanes, chunk padding) — or positions past the table — are routed to
    page id ``num_pages``, an out-of-bounds index XLA's default scatter
    mode DROPS (the same trick the slotted cache uses for rows past
    ``max_len``).  Distinct valid lanes never collide: the allocator
    copy-on-writes any shared page before a write can target it."""
    P = int(kc.shape[2])
    max_pages = int(table.shape[1])
    num_pages = int(kc.shape[0])
    page_idx = pos // P                                    # (B, s) int32
    safe_idx = jnp.clip(page_idx, 0, max_pages - 1)
    page_id = jnp.take_along_axis(table, safe_idx, axis=1,
                                  mode="promise_in_bounds")
    page_id = jnp.where(valid & (page_idx < max_pages), page_id,
                        jnp.asarray(num_pages, jnp.int32))
    row = pos % P
    l_idx = jnp.asarray(layer, jnp.int32)
    kc = kc.at[page_id, l_idx, row].set(k_new.astype(kc.dtype))
    vc = vc.at[page_id, l_idx, row].set(v_new.astype(vc.dtype))
    return kc, vc


class _CacheView:
    """Trace-time carrier threading the cache arrays through the model's
    per-layer walk.  Layers call :meth:`attend` (Tensor-level, tape-aware)
    or :meth:`attend_raw` (raw arrays, for the scan-layers block body) in
    order; the view allocates layer indices from an internal cursor.

    ``_carry_fields`` names the traced arrays the view threads through a
    re-entrant walk (the scan-layers path passes them across its own
    ``call`` boundary via :meth:`carry_arrays`/:meth:`clone_raw`); the
    first two — k, v — are the only ones a layer MUTATES
    (:meth:`mutated_arrays`)."""

    _carry_fields = ("k", "v", "lengths")

    def __init__(self, cache):
        self.k = _unwrap(cache.k)
        self.v = _unwrap(cache.v)
        self.lengths = _unwrap(cache.lengths)
        self._layer = 0

    def _alloc_layer(self) -> int:
        i = self._layer
        if i >= int(self.k.shape[1]):
            raise ValueError(
                "cache view exhausted: model has more attention layers "
                "than the cache's layer axis (%d)" % (self.k.shape[1],))
        self._layer = i + 1
        return i

    def carry_arrays(self):
        """The traced arrays a re-entrant walk must pass across its own
        trace boundary, in :meth:`clone_raw` order."""
        return tuple(getattr(self, f) for f in self._carry_fields)

    def mutated_arrays(self):
        """The subset of :meth:`carry_arrays` the walk mutates (k, v) —
        what the re-entrant fn returns and :meth:`adopt` takes back."""
        return (self.k, self.v)

    def attend(self, q, k_new, v_new, scale=None):
        """Tensor-level append+attend (dispatches through core.dispatch.call
        so eager autograd bookkeeping stays consistent)."""
        from ..core.dispatch import call
        layer = self._alloc_layer()
        carry = self.carry_arrays()
        n = len(carry)

        def raw(*args):
            out, kc2, vc2 = self._append_attend_raw(
                layer, args[:n], args[n], args[n + 1], args[n + 2], scale)
            return out, kc2, vc2

        out, kc, vc = call(raw, *carry, q, k_new, v_new,
                           name="slotted_kv_attend")
        self.k, self.v = _unwrap(kc), _unwrap(vc)
        return out

    def attend_raw(self, q, k_new, v_new, scale=None):
        """Raw-array append+attend (the scan-layers block body path)."""
        layer = self._alloc_layer()
        out, self.k, self.v = self._append_attend_raw(
            layer, self.carry_arrays(), q, k_new, v_new, scale)
        return out

    def clone_raw(self, *arrays):
        """A fresh same-typed view over explicit raw arrays (in
        ``_carry_fields`` order) — for code that re-enters the per-layer
        walk inside its own traced function (the scan-layers decode
        path): the clone's arrays are that trace's arguments, so no
        tracer ever leaks onto this view."""
        import copy
        if len(arrays) != len(self._carry_fields):
            raise ValueError("clone_raw expects %d arrays %r, got %d"
                             % (len(self._carry_fields),
                                self._carry_fields, len(arrays)))
        c = copy.copy(self)
        for f, a in zip(self._carry_fields, arrays):
            setattr(c, f, _unwrap(a))
        c._layer = 0
        return c

    def adopt(self, k, v, steps=None):
        """Take the (concrete) arrays a traced clone produced as outputs."""
        self.k, self.v = _unwrap(k), _unwrap(v)
        self._layer = int(self.k.shape[1])
        if steps is not None and hasattr(self, "_steps"):
            self._steps = int(steps)


class DecodeView(_CacheView):
    """Batched decode: q/k/v arrive as (num_slots, s, heads, head_dim);
    each slot's ``s`` new tokens are written at rows
    ``[lengths[b], lengths[b] + s)`` and attention is masked to
    ``t <= lengths[b] + j``.  ``active`` gates which slots advance their
    length counter at :meth:`finalize` (inactive slots still compute —
    the program shape never changes — but their writes land past their
    frozen valid prefix and are overwritten on slot reuse)."""

    def __init__(self, cache: SlottedKVCache, active=None):
        super().__init__(cache)
        self.active = None if active is None else _unwrap(active)
        self._steps = 0

    def position_ids(self, batch, seq_len):
        if batch != int(self.k.shape[0]):
            raise ValueError(
                "batched decode needs batch == num_slots (%d), got %d — "
                "use PrefillView for single sequences"
                % (self.k.shape[0], batch))
        return (self.lengths[:, None]
                + jnp.arange(seq_len, dtype=jnp.int32)[None, :])

    def _append_attend_raw(self, layer, carry, q, k_new, v_new, scale):
        from ..kernels.decode_attention import decode_attention
        kc, vc, lengths = carry
        s = int(q.shape[1])
        self._steps = s
        b_idx = jnp.arange(kc.shape[0], dtype=jnp.int32)[:, None]
        t_idx = lengths[:, None] + jnp.arange(s, dtype=jnp.int32)[None, :]
        # one scatter into the (donated) full cache buffer per array; XLA
        # updates in place (the operand chains through each layer's write).
        # Rows past max_len (a slot the scheduler failed to evict) drop.
        kc = kc.at[b_idx, layer, t_idx].set(k_new.astype(kc.dtype))
        vc = vc.at[b_idx, layer, t_idx].set(v_new.astype(vc.dtype))
        out = decode_attention(q, kc[:, layer], vc[:, layer], lengths,
                               scale=scale)
        return out, kc, vc

    def finalize(self) -> SlottedKVCache:
        adv = jnp.asarray(self._steps, jnp.int32)
        if self.active is not None:
            adv = adv * self.active.astype(jnp.int32)
        return SlottedKVCache(self.k, self.v, self.lengths + adv)


class PrefillView(_CacheView):
    """Bucketed single-sequence prefill into one slot: input is
    ``(1, bucket)`` right-padded tokens with ``true_len`` real ones.
    Writes rows ``[0, bucket)`` of the (dynamic) ``slot`` via
    ``dynamic_update_slice`` and attends block-causally — pad rows
    compute garbage that is masked forever (``lengths[slot] = true_len``)
    and progressively overwritten by subsequent decode appends."""

    def __init__(self, cache: SlottedKVCache, slot, true_len):
        super().__init__(cache)
        self.slot = jnp.asarray(_unwrap(slot), jnp.int32)
        self.true_len = jnp.asarray(_unwrap(true_len), jnp.int32)

    def position_ids(self, batch, seq_len):
        if batch != 1:
            raise ValueError("PrefillView is single-sequence (got batch=%d)"
                             % batch)
        return jnp.arange(seq_len, dtype=jnp.int32)[None, :]

    def _append_attend_raw(self, layer, carry, q, k_new, v_new, scale):
        from ..kernels import flash_attention as fa
        from ..nn.functional.attention import sdpa_reference_raw
        kc, vc, lengths = carry
        zero = jnp.zeros((), jnp.int32)
        start = (self.slot, jnp.asarray(layer, jnp.int32), zero, zero, zero)
        kc = jax.lax.dynamic_update_slice(
            kc, k_new.astype(kc.dtype)[:, None], start)
        vc = jax.lax.dynamic_update_slice(
            vc, v_new.astype(vc.dtype)[:, None], start)
        # fresh slot: nothing precedes the block — attention is plain
        # causal over the bucket (bucket^2 logits, not bucket*max_len),
        # through the Pallas flash kernel when the shapes support it
        if fa.supported(q, k_new):
            out = fa.flash_attention_bshd(q, k_new, v_new, causal=True,
                                          scale=scale)
        else:
            out = sdpa_reference_raw(q, k_new, v_new, None, 0.0, True, scale)
        return out, kc, vc

    def finalize(self) -> SlottedKVCache:
        return SlottedKVCache(
            self.k, self.v, self.lengths.at[self.slot].set(self.true_len))


class PagedDecodeView(_CacheView):
    """Batched decode over the paged pool: q/k/v arrive as
    ``(num_slots, s, heads, head_dim)``; each slot's new tokens scatter
    into its mapped pages at rows ``lengths[b] + j`` and attention
    gathers only the slot's page-table row.  Unlike the slotted view,
    writes from INACTIVE lanes are dropped in-program (routed to an
    out-of-bounds page id): a retired slot's stale table row may point at
    pages the allocator has reassigned, so its lane must never write."""

    _carry_fields = ("k", "v", "page_table", "lengths")

    def __init__(self, cache: PagedKVCache, active=None, max_len=None):
        super().__init__(cache)
        self.page_table = _unwrap(cache.page_table)
        self.active = None if active is None else _unwrap(active)
        # write/length cap: the engine's DECLARED max_len can be tighter
        # than the pool capacity when max_len % page_size != 0 — appends
        # at or past it drop and lengths stop advancing, matching the
        # slotted view's rows-past-max_len guard
        self.max_len = (int(max_len) if max_len is not None
                        else int(cache.max_len))
        self._steps = 0

    def position_ids(self, batch, seq_len):
        if batch != int(self.page_table.shape[0]):
            raise ValueError(
                "batched paged decode needs batch == num_slots (%d), got "
                "%d — use PagedPrefillChunkView for single sequences"
                % (self.page_table.shape[0], batch))
        return (self.lengths[:, None]
                + jnp.arange(seq_len, dtype=jnp.int32)[None, :])

    def _append_attend_raw(self, layer, carry, q, k_new, v_new, scale):
        from ..kernels.decode_attention import paged_decode_attention
        kc, vc, table, lengths = carry
        s = int(q.shape[1])
        self._steps = s
        pos = lengths[:, None] + jnp.arange(s, dtype=jnp.int32)[None, :]
        valid = pos < jnp.asarray(self.max_len, jnp.int32)
        if self.active is not None:
            valid = valid & self.active[:, None]
        kc, vc = paged_scatter(kc, vc, layer, table, pos, valid,
                               k_new, v_new)
        out = paged_decode_attention(q, kc[:, layer], vc[:, layer], table,
                                     lengths, scale=scale)
        return out, kc, vc

    def finalize(self) -> PagedKVCache:
        adv = jnp.asarray(self._steps, jnp.int32)
        if self.active is not None:
            adv = adv * self.active.astype(jnp.int32)
        return PagedKVCache(self.k, self.v, self.page_table,
                            jnp.minimum(self.lengths + adv,
                                        jnp.asarray(self.max_len,
                                                    jnp.int32)),
                            declared_max_len=self.max_len)


class PagedPrefillChunkView(_CacheView):
    """One fixed-size prefill chunk of one slot's prompt: input is
    ``(1, chunk)`` right-padded tokens, ``n_valid`` of them real, at
    global positions ``n_before + j``.  Writes land in the slot's mapped
    pages (the engine allocates them host-side before the chunk runs);
    padding writes are dropped in-program.  Attention gathers the slot's
    page-table row and masks ``t <= n_before + j`` — the full mapped
    past (shared prefix pages included) plus the chunk's own causal
    band, so a chunk after a prefix-cache hit attends to pages it never
    computed."""

    _carry_fields = ("k", "v", "page_table", "lengths")

    def __init__(self, cache: PagedKVCache, slot, n_before, n_valid):
        super().__init__(cache)
        self.page_table = _unwrap(cache.page_table)
        self.slot = jnp.asarray(_unwrap(slot), jnp.int32)
        self.n_before = jnp.asarray(_unwrap(n_before), jnp.int32)
        self.n_valid = jnp.asarray(_unwrap(n_valid), jnp.int32)
        self.declared_max_len = cache.declared_max_len

    def position_ids(self, batch, seq_len):
        if batch != 1:
            raise ValueError(
                "PagedPrefillChunkView is single-sequence (got batch=%d)"
                % batch)
        return (self.n_before
                + jnp.arange(seq_len, dtype=jnp.int32))[None, :]

    def _append_attend_raw(self, layer, carry, q, k_new, v_new, scale):
        from ..kernels.decode_attention import paged_decode_attention
        kc, vc, table, lengths = carry
        C = int(q.shape[1])
        max_pages = int(table.shape[1])
        row_tab = jax.lax.dynamic_slice(
            table, (self.slot, jnp.zeros((), jnp.int32)), (1, max_pages))
        j = jnp.arange(C, dtype=jnp.int32)
        pos = (self.n_before + j)[None, :]
        valid = (j < self.n_valid)[None, :]
        kc, vc = paged_scatter(kc, vc, layer, row_tab, pos, valid,
                               k_new, v_new)
        out = paged_decode_attention(q, kc[:, layer], vc[:, layer],
                                     row_tab, self.n_before[None],
                                     scale=scale)
        return out, kc, vc

    def finalize(self) -> PagedKVCache:
        return PagedKVCache(
            self.k, self.v, self.page_table,
            self.lengths.at[self.slot].set(self.n_before + self.n_valid),
            declared_max_len=self.declared_max_len)
