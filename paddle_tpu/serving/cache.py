"""Static-shape KV cache layouts — the serving engine's memory.

The decode path's non-negotiable TPU precondition is a *static-shape*
program: the legacy cache grew by ``concat`` each token, so its shape
changed every step and **every generated token retraced and recompiled
the whole model**.  Two static layouts live here:

* :class:`SlottedKVCache` — per-slot contiguous (PR 5):

      k, v : (num_slots, layers, max_len, heads, head_dim)
      lengths : (num_slots,) int32           # valid prefix per slot

  Every slot pays (and the decode read streams around) a full
  ``max_len`` buffer no matter how many tokens it actually holds.

* :class:`PagedKVCache` — vLLM-style block-structured memory
  (PagedAttention, SOSP '23) adapted to XLA's static-shape discipline:

      k, v       : (num_pages, layers, page_size, heads, head_dim)
      page_table : (num_slots, max_pages) int32   # page ids per slot
      lengths    : (num_slots,) int32

  A slot's tokens live in the fixed pool pages its page-table row maps;
  decode appends scatter into the slot's current *tail* page and
  attention gathers only mapped pages.  Memory (and the KV read bound a
  page-aware schedule pays) scales with *actual* lengths, and identical
  prompt prefixes can map the SAME refcounted pages (hash-based prefix
  sharing — ``serving/pages.py`` owns the host-side allocator:
  free list, refcounts, prefix hashes, copy-on-write decisions).  All
  of it stays compile-once: the page table, lengths, and gather indices
  are ordinary traced int32 arrays.

**int8 quantized KV (ISSUE 8).**  Either layout can store the pool as
int8 codes plus per-(row, head) f32 scales (``kv_dtype="int8"`` at
:meth:`create`): appends *quantize in-program* (symmetric amax/127 grid
— :func:`quantize_kv`) and the decode-attention q8 variants dequantize
inline in the gather, so decode HBM traffic per K/V row drops from
``head_dim * 2`` bytes (bf16) to ``head_dim + 4`` (int8 codes + one f32
scale per head).  The scale pools mirror the code pools' page/slot
structure:

      k_scale, v_scale : (num_pages, layers, page_size, heads)  f32   (paged)
      k_scale, v_scale : (num_slots, layers, max_len, heads)    f32   (slotted)

**fp8 quantized KV (``kv_dtype="fp8"`` — ISSUE 20).**  The same
plumbing runs float8_e4m3fn codes: scale layout, scatter paths and the
dequant-in-gather kernels are shared, and :func:`quantize_kv` swaps only
the grid — amax/448 scaling with a clip to ±448 BEFORE the cast (e4m3
has no inf; an overflowing cast encodes NaN, so saturation must happen
in f32).  The e4m3 row prices exactly like the int8 row (1-byte codes +
one f32 scale per head); the trade is int8's round-to-nearest ~1/254
grid for a 3-mantissa-bit (~1/16 relative step) dtype the MXU can
multiply natively on current TPUs.

Attention over either layout is masked to each slot's valid prefix: the
query token at block offset ``j`` of a slot with pre-append length ``n``
sits at global position ``n + j`` and may attend keys ``t <= n + j``.
That one formula covers batched decode (``j = 0``), multi-token appends
(speculative verify scores ``k + 1`` positions through exactly this
path), chunked prefill (``j`` ranges over the chunk), and whole-prompt
prefill (``n = 0`` reduces it to the causal mask).

*Views* adapt a cache to the model's per-layer walk (they are
trace-time carriers, not pytrees — the arrays they hold thread through
``jit`` as ordinary tracers):

* :class:`DecodeView` / :class:`PagedDecodeView` — batched: batch dim ==
  num_slots, every active slot advances together in one fixed-shape
  program.
* :class:`PrefillView` — slotted bucketed prefill: one sequence, one
  (dynamic) slot index, writes rows ``[0, bucket)`` and runs plain
  block-causal attention (nothing prior to attend to).
* :class:`PagedPrefillChunkView` — one fixed-size chunk of one slot's
  prompt: writes positions ``[n, n + valid)`` into mapped pages and
  attends to the full mapped past + itself (the chunked-prefill
  program the engine interleaves with decode).

A view's *carry fields* — the traced arrays it threads through a
re-entrant walk — are dynamic: ``k, v`` always, ``k_scale, v_scale``
when the cache is quantized, the page table for paged views,
``lengths``, and (opt-in) a ``quant_err`` f32 scalar accumulating the
max abs dequantization error of the step's appends (the
``serving.kv_quant_error`` gauge).  :meth:`_CacheView.carry_fields`
is the single source of that ordering; ``clone_raw``/``adopt`` and the
scan-layers re-entry in ``models/gpt.py`` follow it.

Dependency note: this module is imported by ``models/gpt.py`` and must
stay model-free (jax + the decode-attention kernel family only).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

# the int8 grid lives with the q8 kernels (ONE canonical definition —
# the autotune runners synthesize operands through the same math, so the
# cache's writes and the kernels' reads can never drift); re-exported
# here as serving API
from ..kernels.decode_attention import dequantize_kv, quantize_kv

__all__ = ["SlottedKVCache", "DecodeView", "PrefillView", "PagedKVCache",
           "PagedDecodeView", "PagedPrefillChunkView", "is_cache_view",
           "quantize_kv", "dequantize_kv", "np_native_view",
           "np_restore_view"]


def np_native_view(a):
    """``(host array, original dtype)`` with the array viewed in an
    npz-serializable dtype.  npz cannot round-trip ml_dtypes — a
    bfloat16 pool saves as void ``|V2`` and reloads unusable — so
    non-numpy-native pool dtypes serialize as a byte-exact unsigned
    view; :func:`np_restore_view` undoes it.  The KV spill transports
    (``serving/kv_tier.py``, ``serving/disagg.py``) share this pair so
    their staging files can never drift in dtype handling."""
    a = np.asarray(a)
    dt = a.dtype
    if dt.kind not in "fiu":
        a = a.view("u%d" % dt.itemsize)
    return a, dt


def np_restore_view(a, dtype):
    """Undo :func:`np_native_view`: reinterpret the loaded bytes in the
    original (possibly non-native) dtype."""
    return a.view(dtype) if a.dtype != dtype else a


def _as_kv_dtypes(kv_dtype):
    """(code dtype, scale dtype or None) for a cache ``kv_dtype``.
    Accepts the spelled dtypes plus the ``"fp8"`` shorthand for
    float8_e4m3fn (ISSUE 20: the e4m3 pool shares the int8 layout —
    same scale pools, same 1-byte codes, different grid constant)."""
    if kv_dtype is None:
        return None, None
    if isinstance(kv_dtype, str) and kv_dtype.strip().lower() == "fp8":
        kv_dtype = jnp.float8_e4m3fn
    dt = jnp.dtype(kv_dtype)
    if dt not in (jnp.dtype(jnp.int8), jnp.dtype(jnp.float8_e4m3fn)):
        raise ValueError("kv_dtype %r unsupported (int8 or fp8/"
                         "float8_e4m3fn)" % (kv_dtype,))
    return dt, jnp.float32


def _append_quant_err(prev, pairs):
    """Fold the max abs dequant error of freshly quantized appends into
    the running ``quant_err`` scalar (``prev`` None = tracking off)."""
    if prev is None:
        return None
    err = prev
    for x, q, s in pairs:
        d = dequantize_kv(q, s, jnp.float32) - x.astype(jnp.float32)
        err = jnp.maximum(err, jnp.max(jnp.abs(d)))
    return err


@jax.tree_util.register_pytree_node_class
class SlottedKVCache:
    """The preallocated cache state.  A registered pytree, so it passes
    through ``jax.jit`` boundaries (and ``donate_argnums``) directly.
    ``k_scale``/``v_scale`` are the per-(row, head) f32 scale pools of
    the int8 layout (None for the unquantized one)."""

    def __init__(self, k, v, lengths, k_scale=None, v_scale=None):
        self.k = k
        self.v = v
        self.lengths = lengths
        self.k_scale = k_scale
        self.v_scale = v_scale

    def tree_flatten(self):
        return (self.k, self.v, self.lengths, self.k_scale,
                self.v_scale), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @classmethod
    def create(cls, num_slots, num_layers, max_len, num_heads, head_dim,
               dtype="float32", kv_dtype=None):
        code_dt, scale_dt = _as_kv_dtypes(kv_dtype)
        pool_dt = dtype if code_dt is None else code_dt
        shape = (int(num_slots), int(num_layers), int(max_len),
                 int(num_heads), int(head_dim))
        ks = vs = None
        if scale_dt is not None:
            ks = jnp.zeros(shape[:-1], scale_dt)
            vs = jnp.zeros(shape[:-1], scale_dt)
        return cls(jnp.zeros(shape, pool_dt), jnp.zeros(shape, pool_dt),
                   jnp.zeros((int(num_slots),), jnp.int32),
                   k_scale=ks, v_scale=vs)

    @property
    def quantized(self):
        return self.k_scale is not None

    # -- static geometry (python ints — safe at trace time) ----------------
    @property
    def num_slots(self):
        return int(self.k.shape[0])

    @property
    def num_layers(self):
        return int(self.k.shape[1])

    @property
    def max_len(self):
        return int(self.k.shape[2])

    def __repr__(self):
        return ("SlottedKVCache(slots=%d, layers=%d, max_len=%d, heads=%d, "
                "head_dim=%d, dtype=%s)"
                % (self.k.shape + (self.k.dtype,)))


@jax.tree_util.register_pytree_node_class
class PagedKVCache:
    """Block-structured cache state: a fixed pool of fixed-size KV pages
    plus a per-slot page table.  A registered pytree, so it passes through
    ``jax.jit`` boundaries (and ``donate_argnums``) directly.  Unmapped
    page-table entries hold 0 — they gather page 0's bytes, which the
    length mask discards before they reach the softmax.  ``k_scale``/
    ``v_scale`` are the per-(page row, head) f32 scale pools of the int8
    layout (None for the unquantized one)."""

    def __init__(self, k, v, page_table, lengths, declared_max_len=None,
                 k_scale=None, v_scale=None):
        self.k = k
        self.v = v
        self.page_table = page_table
        self.lengths = lengths
        self.k_scale = k_scale
        self.v_scale = v_scale
        # the DECLARED length budget, when tighter than pool capacity
        # (max_len % page_size != 0 leaves dead rows in the tail page);
        # static aux data, so it survives jit boundaries and tree maps
        self.declared_max_len = (None if declared_max_len is None
                                 else int(declared_max_len))

    def tree_flatten(self):
        return ((self.k, self.v, self.page_table, self.lengths,
                 self.k_scale, self.v_scale), self.declared_max_len)

    @classmethod
    def tree_unflatten(cls, aux, children):
        k, v, table, lengths, ks, vs = children
        return cls(k, v, table, lengths, declared_max_len=aux,
                   k_scale=ks, v_scale=vs)

    @classmethod
    def create(cls, num_pages, num_layers, page_size, num_heads, head_dim,
               num_slots, max_pages, dtype="float32", kv_dtype=None):
        code_dt, scale_dt = _as_kv_dtypes(kv_dtype)
        pool_dt = dtype if code_dt is None else code_dt
        shape = (int(num_pages), int(num_layers), int(page_size),
                 int(num_heads), int(head_dim))
        ks = vs = None
        if scale_dt is not None:
            ks = jnp.zeros(shape[:-1], scale_dt)
            vs = jnp.zeros(shape[:-1], scale_dt)
        return cls(jnp.zeros(shape, pool_dt), jnp.zeros(shape, pool_dt),
                   jnp.zeros((int(num_slots), int(max_pages)), jnp.int32),
                   jnp.zeros((int(num_slots),), jnp.int32),
                   k_scale=ks, v_scale=vs)

    @classmethod
    def create_dense(cls, num_slots, num_layers, max_len, num_heads,
                     head_dim, page_size, dtype="float32", kv_dtype=None):
        """A pool with exactly one page set per slot, identity-mapped
        (slot ``i`` owns pages ``[i*max_pages, (i+1)*max_pages)``) — the
        allocator-free layout for model-level use (``gen_paged_cache``):
        capacity matches the slotted cache, only the memory is paged."""
        max_pages = -(-int(max_len) // int(page_size))
        cache = cls.create(int(num_slots) * max_pages, num_layers,
                           page_size, num_heads, head_dim, num_slots,
                           max_pages, dtype, kv_dtype=kv_dtype)
        table = jnp.arange(int(num_slots) * max_pages,
                           dtype=jnp.int32).reshape(int(num_slots),
                                                    max_pages)
        return cls(cache.k, cache.v, table, cache.lengths,
                   declared_max_len=int(max_len),
                   k_scale=cache.k_scale, v_scale=cache.v_scale)

    @property
    def quantized(self):
        return self.k_scale is not None

    # -- static geometry (python ints — safe at trace time) ----------------
    @property
    def num_pages(self):
        return int(self.k.shape[0])

    @property
    def num_layers(self):
        return int(self.k.shape[1])

    @property
    def page_size(self):
        return int(self.k.shape[2])

    @property
    def num_slots(self):
        return int(self.page_table.shape[0])

    @property
    def max_pages(self):
        return int(self.page_table.shape[1])

    @property
    def max_len(self):
        cap = self.max_pages * self.page_size
        return cap if self.declared_max_len is None \
            else min(self.declared_max_len, cap)

    def __repr__(self):
        return ("PagedKVCache(pages=%d, layers=%d, page_size=%d, heads=%d, "
                "head_dim=%d, slots=%d, max_pages=%d, dtype=%s)"
                % (self.k.shape + self.page_table.shape[:2]
                   + (self.k.dtype,)))


def is_cache_view(obj) -> bool:
    return isinstance(obj, _CacheView)


def _unwrap(x):
    return x._array if hasattr(x, "_array") else x


def paged_scatter(kc, vc, layer, table, pos, valid, k_new, v_new,
                  ksc=None, vsc=None, ks_new=None, vs_new=None):
    """Scatter ``k_new/v_new: (B, s, heads, head_dim)`` into page rows.

    ``table: (B, max_pages)`` maps each lane's pages; ``pos: (B, s)`` are
    global token positions; entries with ``valid`` False (inactive decode
    lanes, chunk padding) — or positions past the table — are routed to
    page id ``num_pages``, an out-of-bounds index XLA's default scatter
    mode DROPS (the same trick the slotted cache uses for rows past
    ``max_len``).  Distinct valid lanes never collide: the allocator
    copy-on-writes any shared page before a write can target it.  For the
    int8 layout, ``ks_new/vs_new: (B, s, heads)`` scale rows scatter into
    the ``ksc/vsc`` scale pools through the SAME routed indices.
    Returns ``(kc, vc, ksc, vsc)`` (scale pools pass through as None
    when unquantized)."""
    P = int(kc.shape[2])
    max_pages = int(table.shape[1])
    num_pages = int(kc.shape[0])
    page_idx = pos // P                                    # (B, s) int32
    safe_idx = jnp.clip(page_idx, 0, max_pages - 1)
    page_id = jnp.take_along_axis(table, safe_idx, axis=1,
                                  mode="promise_in_bounds")
    page_id = jnp.where(valid & (page_idx < max_pages), page_id,
                        jnp.asarray(num_pages, jnp.int32))
    row = pos % P
    l_idx = jnp.asarray(layer, jnp.int32)
    kc = kc.at[page_id, l_idx, row].set(k_new.astype(kc.dtype))
    vc = vc.at[page_id, l_idx, row].set(v_new.astype(vc.dtype))
    if ksc is not None:
        ksc = ksc.at[page_id, l_idx, row].set(ks_new.astype(ksc.dtype))
        vsc = vsc.at[page_id, l_idx, row].set(vs_new.astype(vsc.dtype))
    return kc, vc, ksc, vsc


class _CacheView:
    """Trace-time carrier threading the cache arrays through the model's
    per-layer walk.  Layers call :meth:`attend` (Tensor-level, tape-aware)
    or :meth:`attend_raw` (raw arrays, for the scan-layers block body) in
    order; the view allocates layer indices from an internal cursor.

    :meth:`carry_fields` names the traced arrays the view threads through
    a re-entrant walk (the scan-layers path passes them across its own
    ``call`` boundary via :meth:`carry_arrays`/:meth:`clone_raw`);
    :meth:`mutated_fields` is the subset a layer MUTATES — ``k, v``, plus
    the scale pools when the cache is quantized, plus the ``quant_err``
    accumulator when tracking is on."""

    #: layout-specific carry fields between the scale pools and lengths
    #: (the paged views add "page_table")
    _extra_fields = ()

    def __init__(self, cache, track_quant_err=False):
        self.k = _unwrap(cache.k)
        self.v = _unwrap(cache.v)
        ks = getattr(cache, "k_scale", None)
        vs = getattr(cache, "v_scale", None)
        self.k_scale = None if ks is None else _unwrap(ks)
        self.v_scale = None if vs is None else _unwrap(vs)
        self.lengths = _unwrap(cache.lengths)
        # opt-in per-step quantization-error accumulator (a traced f32
        # scalar carried through the walk; the serving.kv_quant_error
        # gauge reads it from the entry's outputs)
        self.quant_err = (jnp.zeros((), jnp.float32)
                          if (track_quant_err and self.quantized) else None)
        self._layer = 0

    @property
    def quantized(self):
        return self.k_scale is not None

    def carry_fields(self):
        f = ["k", "v"]
        if self.quantized:
            f += ["k_scale", "v_scale"]
        f += list(self._extra_fields)
        f.append("lengths")
        if self.quant_err is not None:
            f.append("quant_err")
        return tuple(f)

    def mutated_fields(self):
        f = ["k", "v"]
        if self.quantized:
            f += ["k_scale", "v_scale"]
        if self.quant_err is not None:
            f.append("quant_err")
        return tuple(f)

    def _alloc_layer(self) -> int:
        i = self._layer
        if i >= int(self.k.shape[1]):
            raise ValueError(
                "cache view exhausted: model has more attention layers "
                "than the cache's layer axis (%d)" % (self.k.shape[1],))
        self._layer = i + 1
        return i

    def carry_arrays(self):
        """The traced arrays a re-entrant walk must pass across its own
        trace boundary, in :meth:`carry_fields` order."""
        return tuple(getattr(self, f) for f in self.carry_fields())

    def mutated_arrays(self):
        """The subset of :meth:`carry_arrays` the walk mutates — what the
        re-entrant fn returns and :meth:`adopt` takes back."""
        return tuple(getattr(self, f) for f in self.mutated_fields())

    def attend(self, q, k_new, v_new, scale=None):
        """Tensor-level append+attend (dispatches through core.dispatch.call
        so eager autograd bookkeeping stays consistent)."""
        from ..core.dispatch import call
        layer = self._alloc_layer()
        carry = self.carry_arrays()
        n = len(carry)

        def raw(*args):
            return self._append_attend_raw(
                layer, args[:n], args[n], args[n + 1], args[n + 2], scale)

        res = call(raw, *carry, q, k_new, v_new,
                   name="slotted_kv_attend")
        for f, a in zip(self.mutated_fields(), res[1:]):
            setattr(self, f, _unwrap(a))
        return res[0]

    def attend_raw(self, q, k_new, v_new, scale=None):
        """Raw-array append+attend (the scan-layers block body path)."""
        layer = self._alloc_layer()
        res = self._append_attend_raw(
            layer, self.carry_arrays(), q, k_new, v_new, scale)
        for f, a in zip(self.mutated_fields(), res[1:]):
            setattr(self, f, a)
        return res[0]

    def clone_raw(self, *arrays):
        """A fresh same-typed view over explicit raw arrays (in
        :meth:`carry_fields` order) — for code that re-enters the
        per-layer walk inside its own traced function (the scan-layers
        decode path): the clone's arrays are that trace's arguments, so
        no tracer ever leaks onto this view."""
        import copy
        fields = self.carry_fields()
        if len(arrays) != len(fields):
            raise ValueError("clone_raw expects %d arrays %r, got %d"
                             % (len(fields), fields, len(arrays)))
        c = copy.copy(self)
        for f, a in zip(fields, arrays):
            setattr(c, f, _unwrap(a))
        c._layer = 0
        return c

    def adopt(self, *arrays, steps=None):
        """Take the (concrete) arrays a traced clone produced as outputs,
        in :meth:`mutated_fields` order."""
        fields = self.mutated_fields()
        if len(arrays) != len(fields):
            raise ValueError("adopt expects %d arrays %r, got %d"
                             % (len(fields), fields, len(arrays)))
        for f, a in zip(fields, arrays):
            setattr(self, f, _unwrap(a))
        self._layer = int(self.k.shape[1])
        if steps is not None and hasattr(self, "_steps"):
            self._steps = int(steps)

    # -- shared quantized-append helper ------------------------------------

    def _quantize_new(self, c, k_new, v_new):
        """Quantize fresh K/V rows and fold their dequant error into the
        carried accumulator; returns (kq, ks, vq, vs, new_err)."""
        # the pool's dtype IS the grid selector (int8 or e4m3)
        kq, ks = quantize_kv(k_new, c["k"].dtype)
        vq, vs = quantize_kv(v_new, c["v"].dtype)
        err = _append_quant_err(c.get("quant_err"),
                                ((k_new, kq, ks), (v_new, vq, vs)))
        return kq, ks, vq, vs, err


class DecodeView(_CacheView):
    """Batched decode: q/k/v arrive as (num_slots, s, heads, head_dim);
    each slot's ``s`` new tokens are written at rows
    ``[lengths[b], lengths[b] + s)`` and attention is masked to
    ``t <= lengths[b] + j``.  ``active`` gates which slots advance their
    length counter at :meth:`finalize` (inactive slots still compute —
    the program shape never changes — but their writes land past their
    frozen valid prefix and are overwritten on slot reuse)."""

    def __init__(self, cache: SlottedKVCache, active=None,
                 track_quant_err=False):
        super().__init__(cache, track_quant_err=track_quant_err)
        self.active = None if active is None else _unwrap(active)
        self._steps = 0

    def position_ids(self, batch, seq_len):
        if batch != int(self.k.shape[0]):
            raise ValueError(
                "batched decode needs batch == num_slots (%d), got %d — "
                "use PrefillView for single sequences"
                % (self.k.shape[0], batch))
        return (self.lengths[:, None]
                + jnp.arange(seq_len, dtype=jnp.int32)[None, :])

    def _append_attend_raw(self, layer, carry, q, k_new, v_new, scale):
        from ..kernels.decode_attention import decode_attention
        c = dict(zip(self.carry_fields(), carry))
        kc, vc, lengths = c["k"], c["v"], c["lengths"]
        s = int(q.shape[1])
        self._steps = s
        b_idx = jnp.arange(kc.shape[0], dtype=jnp.int32)[:, None]
        t_idx = lengths[:, None] + jnp.arange(s, dtype=jnp.int32)[None, :]
        # one scatter into the (donated) full cache buffer per array; XLA
        # updates in place (the operand chains through each layer's write).
        # Rows past max_len (a slot the scheduler failed to evict) drop.
        if self.quantized:
            kq, ks, vq, vs, err = self._quantize_new(c, k_new, v_new)
            kc = kc.at[b_idx, layer, t_idx].set(kq)
            vc = vc.at[b_idx, layer, t_idx].set(vq)
            ksc = c["k_scale"].at[b_idx, layer, t_idx].set(ks)
            vsc = c["v_scale"].at[b_idx, layer, t_idx].set(vs)
            out = decode_attention(q, kc[:, layer], vc[:, layer], lengths,
                                   scale=scale, k_scales=ksc[:, layer],
                                   v_scales=vsc[:, layer])
            mut = (kc, vc, ksc, vsc) + (() if err is None else (err,))
            return (out,) + mut
        kc = kc.at[b_idx, layer, t_idx].set(k_new.astype(kc.dtype))
        vc = vc.at[b_idx, layer, t_idx].set(v_new.astype(vc.dtype))
        out = decode_attention(q, kc[:, layer], vc[:, layer], lengths,
                               scale=scale)
        return out, kc, vc

    def finalize(self, advance=None) -> SlottedKVCache:
        """``advance`` (per-slot int32, optional) overrides the uniform
        per-step advance — the speculative verify entry passes the
        ACCEPTED count + 1 so rejected drafts roll back in-program."""
        adv = (jnp.asarray(self._steps, jnp.int32) if advance is None
               else jnp.asarray(advance, jnp.int32))
        if self.active is not None:
            adv = adv * self.active.astype(jnp.int32)
        return SlottedKVCache(self.k, self.v, self.lengths + adv,
                              k_scale=self.k_scale, v_scale=self.v_scale)


class PrefillView(_CacheView):
    """Bucketed single-sequence prefill into one slot: input is
    ``(1, bucket)`` right-padded tokens with ``true_len`` real ones.
    Writes rows ``[0, bucket)`` of the (dynamic) ``slot`` via
    ``dynamic_update_slice`` and attends block-causally — pad rows
    compute garbage that is masked forever (``lengths[slot] = true_len``)
    and progressively overwritten by subsequent decode appends.  Int8
    caches quantize the written rows; the block attention itself runs on
    the exact pre-quantization K/V (nothing prior to attend to)."""

    def __init__(self, cache: SlottedKVCache, slot, true_len):
        super().__init__(cache)
        self.slot = jnp.asarray(_unwrap(slot), jnp.int32)
        self.true_len = jnp.asarray(_unwrap(true_len), jnp.int32)

    def position_ids(self, batch, seq_len):
        if batch != 1:
            raise ValueError("PrefillView is single-sequence (got batch=%d)"
                             % batch)
        return jnp.arange(seq_len, dtype=jnp.int32)[None, :]

    def _append_attend_raw(self, layer, carry, q, k_new, v_new, scale):
        from ..kernels import flash_attention as fa
        from ..nn.functional.attention import sdpa_reference_raw
        c = dict(zip(self.carry_fields(), carry))
        kc, vc = c["k"], c["v"]
        zero = jnp.zeros((), jnp.int32)
        start = (self.slot, jnp.asarray(layer, jnp.int32), zero, zero, zero)
        if self.quantized:
            kq, ks, vq, vs, _err = self._quantize_new(c, k_new, v_new)
            kc = jax.lax.dynamic_update_slice(kc, kq[:, None], start)
            vc = jax.lax.dynamic_update_slice(vc, vq[:, None], start)
            ksc = jax.lax.dynamic_update_slice(
                c["k_scale"], ks[:, None], start[:-1])
            vsc = jax.lax.dynamic_update_slice(
                c["v_scale"], vs[:, None], start[:-1])
        else:
            kc = jax.lax.dynamic_update_slice(
                kc, k_new.astype(kc.dtype)[:, None], start)
            vc = jax.lax.dynamic_update_slice(
                vc, v_new.astype(vc.dtype)[:, None], start)
        # fresh slot: nothing precedes the block — attention is plain
        # causal over the bucket (bucket^2 logits, not bucket*max_len),
        # through the Pallas flash kernel when the shapes support it
        if fa.supported(q, k_new):
            out = fa.flash_attention_bshd(q, k_new, v_new, causal=True,
                                          scale=scale)
        else:
            out = sdpa_reference_raw(q, k_new, v_new, None, 0.0, True, scale)
        if self.quantized:
            return out, kc, vc, ksc, vsc
        return out, kc, vc

    def finalize(self) -> SlottedKVCache:
        return SlottedKVCache(
            self.k, self.v, self.lengths.at[self.slot].set(self.true_len),
            k_scale=self.k_scale, v_scale=self.v_scale)


class PagedDecodeView(_CacheView):
    """Batched decode over the paged pool: q/k/v arrive as
    ``(num_slots, s, heads, head_dim)``; each slot's new tokens scatter
    into its mapped pages at rows ``lengths[b] + j`` and attention
    gathers only the slot's page-table row.  Unlike the slotted view,
    writes from INACTIVE lanes are dropped in-program (routed to an
    out-of-bounds page id): a retired slot's stale table row may point at
    pages the allocator has reassigned, so its lane must never write."""

    _extra_fields = ("page_table",)

    def __init__(self, cache: PagedKVCache, active=None, max_len=None,
                 track_quant_err=False, tp=1):
        super().__init__(cache, track_quant_err=track_quant_err)
        self.page_table = _unwrap(cache.page_table)
        self.active = None if active is None else _unwrap(active)
        # write/length cap: the engine's DECLARED max_len can be tighter
        # than the pool capacity when max_len % page_size != 0 — appends
        # at or past it drop and lengths stop advancing, matching the
        # slotted view's rows-past-max_len guard
        self.max_len = (int(max_len) if max_len is not None
                        else int(cache.max_len))
        # tensor-parallel degree of the enclosing sharded program: the
        # attention autotune key must price the PER-SHARD head count
        # (trace-time shapes are global under jit-with-sharding)
        self.tp = int(tp)
        self._steps = 0

    def position_ids(self, batch, seq_len):
        if batch != int(self.page_table.shape[0]):
            raise ValueError(
                "batched paged decode needs batch == num_slots (%d), got "
                "%d — use PagedPrefillChunkView for single sequences"
                % (self.page_table.shape[0], batch))
        return (self.lengths[:, None]
                + jnp.arange(seq_len, dtype=jnp.int32)[None, :])

    def _append_attend_raw(self, layer, carry, q, k_new, v_new, scale):
        from ..kernels.decode_attention import paged_decode_attention
        c = dict(zip(self.carry_fields(), carry))
        kc, vc, table, lengths = c["k"], c["v"], c["page_table"], \
            c["lengths"]
        s = int(q.shape[1])
        self._steps = s
        pos = lengths[:, None] + jnp.arange(s, dtype=jnp.int32)[None, :]
        valid = pos < jnp.asarray(self.max_len, jnp.int32)
        if self.active is not None:
            valid = valid & self.active[:, None]
        if self.quantized:
            kq, ks, vq, vs, err = self._quantize_new(c, k_new, v_new)
            kc, vc, ksc, vsc = paged_scatter(
                kc, vc, layer, table, pos, valid, kq, vq,
                ksc=c["k_scale"], vsc=c["v_scale"], ks_new=ks, vs_new=vs)
            out = paged_decode_attention(
                q, kc[:, layer], vc[:, layer], table, lengths, scale=scale,
                k_scales=ksc[:, layer], v_scales=vsc[:, layer],
                tp=self.tp)
            mut = (kc, vc, ksc, vsc) + (() if err is None else (err,))
            return (out,) + mut
        kc, vc, _, _ = paged_scatter(kc, vc, layer, table, pos, valid,
                                     k_new, v_new)
        out = paged_decode_attention(q, kc[:, layer], vc[:, layer], table,
                                     lengths, scale=scale, tp=self.tp)
        return out, kc, vc

    def finalize(self, advance=None) -> PagedKVCache:
        """``advance`` (per-slot int32, optional) overrides the uniform
        per-step advance — the speculative verify entry passes the
        ACCEPTED count + 1, rolling rejected drafts' length advance (and
        so their tail-page rows, overwritten by the next append) back
        in-program."""
        adv = (jnp.asarray(self._steps, jnp.int32) if advance is None
               else jnp.asarray(advance, jnp.int32))
        if self.active is not None:
            adv = adv * self.active.astype(jnp.int32)
        return PagedKVCache(self.k, self.v, self.page_table,
                            jnp.minimum(self.lengths + adv,
                                        jnp.asarray(self.max_len,
                                                    jnp.int32)),
                            declared_max_len=self.max_len,
                            k_scale=self.k_scale, v_scale=self.v_scale)


class PagedPrefillChunkView(_CacheView):
    """One fixed-size prefill chunk of one slot's prompt: input is
    ``(1, chunk)`` right-padded tokens, ``n_valid`` of them real, at
    global positions ``n_before + j``.  Writes land in the slot's mapped
    pages (the engine allocates them host-side before the chunk runs);
    padding writes are dropped in-program.  Attention gathers the slot's
    page-table row and masks ``t <= n_before + j`` — the full mapped
    past (shared prefix pages included) plus the chunk's own causal
    band, so a chunk after a prefix-cache hit attends to pages it never
    computed.  Int8 caches quantize the chunk's writes; its attention
    reads back through the dequantizing gather (the chunk attends its
    own quantized rows — the same values every later decode step sees)."""

    _extra_fields = ("page_table",)

    def __init__(self, cache: PagedKVCache, slot, n_before, n_valid,
                 tp=1):
        super().__init__(cache)
        self.page_table = _unwrap(cache.page_table)
        self.slot = jnp.asarray(_unwrap(slot), jnp.int32)
        self.n_before = jnp.asarray(_unwrap(n_before), jnp.int32)
        self.n_valid = jnp.asarray(_unwrap(n_valid), jnp.int32)
        self.declared_max_len = cache.declared_max_len
        self.tp = int(tp)    # per-shard autotune keys (PagedDecodeView)

    def position_ids(self, batch, seq_len):
        if batch != 1:
            raise ValueError(
                "PagedPrefillChunkView is single-sequence (got batch=%d)"
                % batch)
        return (self.n_before
                + jnp.arange(seq_len, dtype=jnp.int32))[None, :]

    def _append_attend_raw(self, layer, carry, q, k_new, v_new, scale):
        from ..kernels.decode_attention import paged_decode_attention
        c = dict(zip(self.carry_fields(), carry))
        kc, vc, table = c["k"], c["v"], c["page_table"]
        C = int(q.shape[1])
        max_pages = int(table.shape[1])
        row_tab = jax.lax.dynamic_slice(
            table, (self.slot, jnp.zeros((), jnp.int32)), (1, max_pages))
        j = jnp.arange(C, dtype=jnp.int32)
        pos = (self.n_before + j)[None, :]
        valid = (j < self.n_valid)[None, :]
        if self.quantized:
            kq, ks, vq, vs, _err = self._quantize_new(c, k_new, v_new)
            kc, vc, ksc, vsc = paged_scatter(
                kc, vc, layer, row_tab, pos, valid, kq, vq,
                ksc=c["k_scale"], vsc=c["v_scale"], ks_new=ks, vs_new=vs)
            out = paged_decode_attention(
                q, kc[:, layer], vc[:, layer], row_tab, self.n_before[None],
                scale=scale, k_scales=ksc[:, layer], v_scales=vsc[:, layer],
                tp=self.tp)
            return out, kc, vc, ksc, vsc
        kc, vc, _, _ = paged_scatter(kc, vc, layer, row_tab, pos, valid,
                                     k_new, v_new)
        out = paged_decode_attention(q, kc[:, layer], vc[:, layer],
                                     row_tab, self.n_before[None],
                                     scale=scale, tp=self.tp)
        return out, kc, vc

    def finalize(self) -> PagedKVCache:
        return PagedKVCache(
            self.k, self.v, self.page_table,
            self.lengths.at[self.slot].set(self.n_before + self.n_valid),
            declared_max_len=self.declared_max_len,
            k_scale=self.k_scale, v_scale=self.v_scale)
