"""Static-shape slotted KV cache — the serving engine's memory layout.

The decode path's non-negotiable TPU precondition is a *static-shape*
program: the legacy cache grew by ``concat`` each token, so its shape
changed every step and **every generated token retraced and recompiled
the whole model**.  Here the cache is preallocated once as

    k, v : (num_slots, layers, max_len, heads, head_dim)
    lengths : (num_slots,) int32           # valid prefix per slot

and every append is an in-place-aliasable write (scatter at per-slot
positions for batched decode, ``lax.dynamic_update_slice`` for
single-slot prefill) into the *donated* buffers — the jitted decode step
has ONE shape for the life of the process (Orca's iteration-level
batching precondition; vLLM's PagedAttention solves the same problem
with block tables, which static XLA shapes make unnecessary at these
slot counts: a slot IS a page of ``max_len`` tokens).

Attention over the cache is masked to each slot's valid prefix: the
query token at block offset ``j`` of a slot with pre-append length ``n``
sits at global position ``n + j`` and may attend keys ``t <= n + j``.
That one formula covers batched decode (``j = 0``), multi-token
speculative steps, and whole-prompt prefill (``n = 0`` reduces it to the
causal mask).

Two *views* adapt the cache to the model's per-layer walk (they are
trace-time carriers, not pytrees — the arrays they hold thread through
``jit`` as ordinary tracers):

* :class:`DecodeView` — batched: batch dim == num_slots, every active
  slot advances together in one fixed-shape program.
* :class:`PrefillView` — one sequence, one (dynamic) slot index, writes
  rows ``[0, bucket)`` and runs plain block-causal attention (nothing
  prior to attend to).

Dependency note: this module is imported by ``models/gpt.py`` and must
stay model-free (jax + the decode-attention kernel family only).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["SlottedKVCache", "DecodeView", "PrefillView", "is_cache_view"]


@jax.tree_util.register_pytree_node_class
class SlottedKVCache:
    """The preallocated cache state.  A registered pytree, so it passes
    through ``jax.jit`` boundaries (and ``donate_argnums``) directly."""

    def __init__(self, k, v, lengths):
        self.k = k
        self.v = v
        self.lengths = lengths

    def tree_flatten(self):
        return (self.k, self.v, self.lengths), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @classmethod
    def create(cls, num_slots, num_layers, max_len, num_heads, head_dim,
               dtype="float32"):
        shape = (int(num_slots), int(num_layers), int(max_len),
                 int(num_heads), int(head_dim))
        return cls(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype),
                   jnp.zeros((int(num_slots),), jnp.int32))

    # -- static geometry (python ints — safe at trace time) ----------------
    @property
    def num_slots(self):
        return int(self.k.shape[0])

    @property
    def num_layers(self):
        return int(self.k.shape[1])

    @property
    def max_len(self):
        return int(self.k.shape[2])

    def __repr__(self):
        return ("SlottedKVCache(slots=%d, layers=%d, max_len=%d, heads=%d, "
                "head_dim=%d, dtype=%s)"
                % (self.k.shape + (self.k.dtype,)))


def is_cache_view(obj) -> bool:
    return isinstance(obj, _CacheView)


def _unwrap(x):
    return x._array if hasattr(x, "_array") else x


class _CacheView:
    """Trace-time carrier threading the cache arrays through the model's
    per-layer walk.  Layers call :meth:`attend` (Tensor-level, tape-aware)
    or :meth:`attend_raw` (raw arrays, for the scan-layers block body) in
    order; the view allocates layer indices from an internal cursor."""

    def __init__(self, cache: SlottedKVCache):
        self.k = _unwrap(cache.k)
        self.v = _unwrap(cache.v)
        self.lengths = _unwrap(cache.lengths)
        self._layer = 0

    def _alloc_layer(self) -> int:
        i = self._layer
        if i >= int(self.k.shape[1]):
            raise ValueError(
                "cache view exhausted: model has more attention layers "
                "than the cache's layer axis (%d)" % (self.k.shape[1],))
        self._layer = i + 1
        return i

    def attend(self, q, k_new, v_new, scale=None):
        """Tensor-level append+attend (dispatches through core.dispatch.call
        so eager autograd bookkeeping stays consistent)."""
        from ..core.dispatch import call
        layer = self._alloc_layer()

        def raw(kc, vc, lengths, q_, k_, v_):
            out, kc2, vc2 = self._append_attend_raw(
                layer, kc, vc, lengths, q_, k_, v_, scale)
            return out, kc2, vc2

        out, kc, vc = call(raw, self.k, self.v, self.lengths,
                           q, k_new, v_new, name="slotted_kv_attend")
        self.k, self.v = _unwrap(kc), _unwrap(vc)
        return out

    def attend_raw(self, q, k_new, v_new, scale=None):
        """Raw-array append+attend (the scan-layers block body path)."""
        layer = self._alloc_layer()
        out, self.k, self.v = self._append_attend_raw(
            layer, self.k, self.v, self.lengths, q, k_new, v_new, scale)
        return out

    def clone_raw(self, k, v, lengths):
        """A fresh same-typed view over explicit raw arrays — for code that
        re-enters the per-layer walk inside its own traced function (the
        scan-layers decode path): the clone's arrays are that trace's
        arguments, so no tracer ever leaks onto this view."""
        import copy
        c = copy.copy(self)
        c.k, c.v = _unwrap(k), _unwrap(v)
        c.lengths = _unwrap(lengths)
        c._layer = 0
        return c

    def adopt(self, k, v, steps=None):
        """Take the (concrete) arrays a traced clone produced as outputs."""
        self.k, self.v = _unwrap(k), _unwrap(v)
        self._layer = int(self.k.shape[1])
        if steps is not None and hasattr(self, "_steps"):
            self._steps = int(steps)


class DecodeView(_CacheView):
    """Batched decode: q/k/v arrive as (num_slots, s, heads, head_dim);
    each slot's ``s`` new tokens are written at rows
    ``[lengths[b], lengths[b] + s)`` and attention is masked to
    ``t <= lengths[b] + j``.  ``active`` gates which slots advance their
    length counter at :meth:`finalize` (inactive slots still compute —
    the program shape never changes — but their writes land past their
    frozen valid prefix and are overwritten on slot reuse)."""

    def __init__(self, cache: SlottedKVCache, active=None):
        super().__init__(cache)
        self.active = None if active is None else _unwrap(active)
        self._steps = 0

    def position_ids(self, batch, seq_len):
        if batch != int(self.k.shape[0]):
            raise ValueError(
                "batched decode needs batch == num_slots (%d), got %d — "
                "use PrefillView for single sequences"
                % (self.k.shape[0], batch))
        return (self.lengths[:, None]
                + jnp.arange(seq_len, dtype=jnp.int32)[None, :])

    def _append_attend_raw(self, layer, kc, vc, lengths, q, k_new, v_new,
                           scale):
        from ..kernels.decode_attention import decode_attention
        s = int(q.shape[1])
        self._steps = s
        b_idx = jnp.arange(kc.shape[0], dtype=jnp.int32)[:, None]
        t_idx = lengths[:, None] + jnp.arange(s, dtype=jnp.int32)[None, :]
        # one scatter into the (donated) full cache buffer per array; XLA
        # updates in place (the operand chains through each layer's write).
        # Rows past max_len (a slot the scheduler failed to evict) drop.
        kc = kc.at[b_idx, layer, t_idx].set(k_new.astype(kc.dtype))
        vc = vc.at[b_idx, layer, t_idx].set(v_new.astype(vc.dtype))
        out = decode_attention(q, kc[:, layer], vc[:, layer], lengths,
                               scale=scale)
        return out, kc, vc

    def finalize(self) -> SlottedKVCache:
        adv = jnp.asarray(self._steps, jnp.int32)
        if self.active is not None:
            adv = adv * self.active.astype(jnp.int32)
        return SlottedKVCache(self.k, self.v, self.lengths + adv)


class PrefillView(_CacheView):
    """Bucketed single-sequence prefill into one slot: input is
    ``(1, bucket)`` right-padded tokens with ``true_len`` real ones.
    Writes rows ``[0, bucket)`` of the (dynamic) ``slot`` via
    ``dynamic_update_slice`` and attends block-causally — pad rows
    compute garbage that is masked forever (``lengths[slot] = true_len``)
    and progressively overwritten by subsequent decode appends."""

    def __init__(self, cache: SlottedKVCache, slot, true_len):
        super().__init__(cache)
        self.slot = jnp.asarray(_unwrap(slot), jnp.int32)
        self.true_len = jnp.asarray(_unwrap(true_len), jnp.int32)

    def position_ids(self, batch, seq_len):
        if batch != 1:
            raise ValueError("PrefillView is single-sequence (got batch=%d)"
                             % batch)
        return jnp.arange(seq_len, dtype=jnp.int32)[None, :]

    def _append_attend_raw(self, layer, kc, vc, lengths, q, k_new, v_new,
                           scale):
        from ..kernels import flash_attention as fa
        from ..nn.functional.attention import sdpa_reference_raw
        zero = jnp.zeros((), jnp.int32)
        start = (self.slot, jnp.asarray(layer, jnp.int32), zero, zero, zero)
        kc = jax.lax.dynamic_update_slice(
            kc, k_new.astype(kc.dtype)[:, None], start)
        vc = jax.lax.dynamic_update_slice(
            vc, v_new.astype(vc.dtype)[:, None], start)
        # fresh slot: nothing precedes the block — attention is plain
        # causal over the bucket (bucket^2 logits, not bucket*max_len),
        # through the Pallas flash kernel when the shapes support it
        if fa.supported(q, k_new):
            out = fa.flash_attention_bshd(q, k_new, v_new, causal=True,
                                          scale=scale)
        else:
            out = sdpa_reference_raw(q, k_new, v_new, None, 0.0, True, scale)
        return out, kc, vc

    def finalize(self) -> SlottedKVCache:
        return SlottedKVCache(
            self.k, self.v, self.lengths.at[self.slot].set(self.true_len))
