"""Async streaming HTTP front-end over the continuous-batching
scheduler (ISSUE 13 — ROADMAP item 3(ii)).

A stdlib-only asyncio HTTP/1.1 server that turns the in-process serving
engine into a network service:

* ``POST /v1/generate`` — body ``{"prompt": [token ids],
  "max_new_tokens", "temperature", "top_k", "top_p", "eos_token_id",
  "stream"}``.  ``stream: true`` (the default) answers with per-token
  **SSE** (``Content-Type: text/event-stream``): one
  ``data: {"tokens": [...]}`` event per appended run as the scheduler
  commits it, then a final ``data: {"done": true, ...}`` event.
  ``stream: false`` buffers and answers one JSON document.
* ``GET /healthz`` — liveness + drain state, enriched (ISSUE 14) with
  the watchdog's beacon ages (``stalled`` names any beacon past its
  deadline and flips ``status`` to ``"stalled"``), admission queue
  depth, active slots, and open-stream counts — served from the LOOP
  thread, so an external probe detects a scheduler thread that is
  wedged while the socket still accepts.

**Thread model.**  Three kinds of thread touch this object: the asyncio
*loop thread* (owns the server sockets and every stream), the
*scheduler thread* (owns the :class:`~.scheduler.ContinuousBatchingScheduler`
and is the ONLY thread that calls it — the scheduler is not
thread-safe), and callers of :meth:`start`/:meth:`stop`.  Handlers talk
to the scheduler exclusively through two command queues (submissions,
cancels) drained at iteration boundaries; tokens travel back through
per-request ``asyncio.Queue``\\ s via ``loop.call_soon_threadsafe`` (the
scheduler's ``on_token``/``on_finish`` hooks fire on its own thread).
The scheduler runs the OVERLAPPED decode loop by default, so the
per-token HTTP fan-out below rides host time the device never sees.

**Admission control.**  ``queue_limit`` bounds the requests the
front-end will hold in flight (admitted + queued).  Over the bound:
**429** and ``serving.shed_total``; while draining: **503**.  Shed
requests never reach the scheduler — the bounded queue is what keeps
p99 TTFT finite when offered load exceeds capacity (the goodput-vs-QPS
knee the load harness measures).

**Graceful drain (the PR-4 preemption guard).**  Pass a
:class:`~..robustness.preemption.PreemptionGuard`; when its flag flips
(SIGTERM, or chaos ``Preempt``), the front-end stops admitting (503)
and keeps stepping until every in-flight AND already-queued request has
finished — requests are never dropped.  Under page-pool pressure during
the drain the scheduler's recompute preemption still *requeues* victims
rather than dropping them (the chaos suite asserts both).  The drain
completion is observable via :meth:`wait_drained`.

**Mid-stream disconnects.**  A failed SSE write (client went away — or
the ``serve.stream`` faultpoint injected a ``SocketReset``) cancels the
request at the next scheduler iteration: the slot and ALL its pages are
freed refcount-exactly (a shared prefix page only drops a refcount),
counted as HTTP 499.  Tokens that never reached a client are excluded
from ``serving.goodput_tokens`` by construction.

Metrics: ``serving.http_requests{code}``, ``serving.shed_total``,
``serving.open_streams``, ``serving.goodput_tokens`` (catalog'd, with
live drivers in the two-way ratchet).  Tracing: each request's lane
gains an ``http`` span (child of the scheduler's ``request`` root) from
submission to finish, so ``trace-report`` timelines show network-facing
lifetime next to queue/prefill/decode.
"""
from __future__ import annotations

import asyncio
import json
import threading
import time
from typing import Dict, Optional

import numpy as np

from ..observability import liveness as _liveness
from ..observability import registry as _metrics
from ..observability import tracing as _tracing
from ..robustness.faultpoints import declare, faultpoint
from .scheduler import ContinuousBatchingScheduler, Request

__all__ = ["ServingFrontend"]

# liveness beacons over the two frontend worker threads (ISSUE 14): a
# deadlocked scheduler thread or a wedged event loop keeps sockets
# accept-able while no request progresses — exactly the hang /healthz
# must surface.  The sched-thread beacon guards every loop iteration
# (idle waits time out at 20ms, so a healthy thread pulses constantly);
# the loop thread is covered by a heartbeat task pulsing from inside
# the event loop, so a blocked loop (a callback that never returns)
# stops stamping.
_liveness.declare_beacon(
    "serve.frontend_sched",
    "one frontend scheduler-thread loop iteration (submit/cancel "
    "drain + scheduler step)", deadline=600.0)
_liveness.declare_beacon(
    "serve.frontend_loop",
    "asyncio event-loop heartbeat (pulses from a task inside the "
    "loop; a blocked loop stops stamping)", deadline=60.0)

#: chaos site: fired immediately before every SSE event write, so a
#: scheduled SocketReset simulates a mid-stream client disconnect at an
#: exact event index (tests/test_chaos.py asserts the slot AND its pages
#: are freed refcount-exactly).
STREAM_SITE = declare(
    "serve.stream",
    "per-SSE-event client socket write (SocketReset here simulates a "
    "mid-stream client disconnect)")

#: socket errors that mean "the client went away" — everything the
#: stream-write path treats as a disconnect rather than a server bug
_DISCONNECT_ERRORS = (ConnectionResetError, ConnectionAbortedError,
                      BrokenPipeError, TimeoutError)


class _Stream:
    """Loop-thread view of one accepted request: an asyncio queue the
    scheduler thread feeds via ``call_soon_threadsafe``."""

    __slots__ = ("loop", "queue", "rid", "cancelled", "http_span")

    def __init__(self, loop):
        self.loop = loop
        self.queue: asyncio.Queue = asyncio.Queue()
        self.rid: Optional[int] = None    # set by the scheduler thread
        self.cancelled = False            # set before submit happened
        self.http_span = None

    def push(self, item):                 # scheduler thread
        self.loop.call_soon_threadsafe(self.queue.put_nowait, item)


class ServingFrontend:
    """The async serving front: HTTP in, SSE tokens out, a bounded
    admission queue, and a preemption-guarded drain.  ``port=0`` binds
    an ephemeral port (read :attr:`port` after :meth:`start`)."""

    def __init__(self, engine=None, host="127.0.0.1", port=0,
                 queue_limit=64, overlap=None, guard=None, tracer=None,
                 prefill_engine=None, handoff_limit=4, router=None):
        self.engine = engine
        self.host = host
        self.port = int(port)
        self.queue_limit = int(queue_limit)
        self._guard = guard
        self._tracer = (tracer if tracer is not None
                        else _tracing.default_tracer())
        self._router = router
        if router is not None:
            # replicated fleet mode (ISSUE 19): the router owns the
            # replicas and their scheduler threads — the front-end runs
            # NO scheduler thread of its own; handlers call
            # router.submit (pure-CPU hashing + lock-scoped enqueue,
            # never a scheduler call) straight from the event loop and
            # the token/finish callbacks arrive from replica threads.
            # A guard is the single-scheduler drain path; fleets drain
            # via drain()/stop().
            if engine is not None or prefill_engine is not None:
                raise ValueError("pass engines THROUGH the router in "
                                 "fleet mode, not to the front-end")
            if guard is not None:
                raise ValueError("preemption guard is not supported in "
                                 "fleet mode — use drain()")
            self.scheduler = None
            self._prompt_cap = router.prompt_cap
            router.on_token = self._on_token
            router.on_finish = self._on_finish
        elif prefill_engine is not None:
            # disaggregated prefill/decode (ISSUE 15): admissions route
            # to the prefill engine and finished KV hands off into the
            # decode pool; the HTTP surface is unchanged
            from .disagg import DisaggScheduler
            self.scheduler = DisaggScheduler(
                engine, prefill_engine, handoff_limit=handoff_limit,
                tracer=tracer, overlap=overlap,
                on_token=self._on_token, on_finish=self._on_finish)
            self._prompt_cap = engine.prompt_cap
        else:
            self.scheduler = ContinuousBatchingScheduler(
                engine, tracer=tracer, overlap=overlap,
                on_token=self._on_token, on_finish=self._on_finish)
            self._prompt_cap = engine.prompt_cap
        # command queues (handler threads -> scheduler thread)
        self._lock = threading.Lock()
        self._pending = []                # [(Request, _Stream)]
        self._cancels = []                # [rid]
        self._streams: Dict[int, _Stream] = {}
        self._outstanding = 0             # accepted, not yet finished
        self._open_streams = 0
        self._wake = threading.Event()
        self._stop = False
        self._draining = False
        self._drained = threading.Event()
        self._started = threading.Event()
        self._sched_error = None
        self._loop = None
        self._server = None
        self._loop_thread = None
        self._sched_thread = None
        # metric handles, fetched once (no-op singletons when disabled)
        self._m_http = _metrics.counter("serving.http_requests", ("code",))
        self._m_shed = _metrics.counter("serving.shed_total")
        self._m_open = _metrics.gauge("serving.open_streams")
        self._m_goodput = _metrics.counter("serving.goodput_tokens")

    # -- lifecycle ---------------------------------------------------------

    def start(self):
        """Bind the server and start both worker threads; returns
        ``(host, port)``."""
        self._loop_thread = threading.Thread(
            target=self._loop_main, name="serve-frontend-loop",
            daemon=True)
        self._loop_thread.start()
        self._started.wait(10.0)
        if not self._started.is_set():
            raise RuntimeError("frontend event loop failed to start")
        if self._router is not None:
            self._router.start()
        else:
            self._sched_thread = threading.Thread(
                target=self._sched_main, name="serve-frontend-sched",
                daemon=True)
            self._sched_thread.start()
        return self.host, self.port

    def stop(self, timeout=30.0):
        """Graceful shutdown: drain outstanding work (503 for new
        requests), stop the scheduler thread, close the server.
        Re-raises any error the scheduler thread died on."""
        # under the lock: the scheduler thread also writes _draining (on
        # a guard fire) — unlocked cross-thread writes are TPU603
        with self._lock:
            self._draining = True
            self._stop = True
        self._wake.set()
        if self._router is not None:
            self._router.stop(timeout)
        if self._sched_thread is not None:
            self._sched_thread.join(timeout)
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self._loop.stop)
        if self._loop_thread is not None:
            self._loop_thread.join(timeout)
        if self._sched_error is not None:
            raise self._sched_error

    def drain(self):
        """Enter drain mode programmatically (what a guard fire does):
        new requests 503, everything already accepted runs to
        completion."""
        with self._lock:
            self._draining = True
            drained = self._router is not None and self._outstanding == 0
        if drained:
            # fleet mode completes the drain from finish callbacks; an
            # already-idle fleet would otherwise never observe one
            self._drained.set()
        self._wake.set()

    @property
    def draining(self):
        return self._draining

    def wait_drained(self, timeout=None) -> bool:
        """Block until the drain completed (all accepted requests
        finished after :meth:`drain`/a guard fire)."""
        return self._drained.wait(timeout)

    # -- scheduler thread --------------------------------------------------

    def _sched_main(self):
        sched = self.scheduler
        b = _liveness.beacon("serve.frontend_sched")
        b.begin()     # watched for the thread's whole lifetime
        try:
            while True:
                b.pulse()
                if (self._guard is not None and self._guard.preempted
                        and not self._draining):
                    # the guard flipped (SIGTERM / chaos Preempt): stop
                    # admitting, finish what we hold — never drop.  The
                    # scheduler's recompute preemption keeps requeueing
                    # page-pressure victims during the drain.
                    with self._lock:
                        self._draining = True
                with self._lock:
                    pending, self._pending = self._pending, []
                    cancels, self._cancels = self._cancels, []
                for req, stream in pending:
                    if stream.cancelled:      # client left pre-submit
                        with self._lock:
                            self._outstanding -= 1
                        continue
                    try:
                        rid = sched.submit(req)
                    except ValueError as e:
                        # the handler pre-validates, but a submit() rule
                        # it doesn't mirror must degrade to ONE failed
                        # stream — never kill the scheduler thread (and
                        # with it every other open stream)
                        with self._lock:
                            self._outstanding -= 1
                        stream.push(("done", {
                            "rid": None, "finish_reason": "error",
                            "error": str(e), "tokens": [],
                            "ttft_ms": 0.0, "tpot_ms": 0.0,
                            "queue_wait_ms": 0.0}))
                        continue
                    stream.rid = rid
                    with self._lock:
                        self._streams[rid] = stream
                    # the network-facing lifetime on the request lane:
                    # child of the scheduler's "request" root so the
                    # trace tree stays connected
                    stream.http_span = self._tracer.span(
                        "http", parent=sched.request_span(rid))
                    stream.push(("rid", rid))
                    if stream.cancelled:
                        # the client vanished in the window between the
                        # cancelled check above and the rid assignment:
                        # _cancel_stream saw rid=None and could queue
                        # nothing — cancel inline (same thread) so a
                        # dead client's request never holds a slot.
                        # (A post-rid disconnect queues a cancel too;
                        # the second cancel() is a no-op.)
                        sched.cancel(rid)
                for rid in cancels:
                    sched.cancel(rid)
                worked = False
                if sched.has_work():
                    sched.step()
                    worked = True
                else:
                    with self._lock:
                        # _outstanding is incremented BEFORE a request
                        # enters _pending, so an accepted-but-not-yet-
                        # enqueued request keeps this false — the drain
                        # must never report complete with accepted work
                        # still in the handoff window
                        drained = (self._draining and not self._pending
                                   and self._outstanding == 0)
                    if drained:
                        self._drained.set()
                    if self._stop:
                        break
                    self._wake.wait(0.02)
                    self._wake.clear()
                if not worked and self._stop:
                    break
        except BaseException as e:        # surfaced by stop()
            self._sched_error = e
            # the black-box record (ISSUE 14 satellite): this catch
            # keeps the death off threading.excepthook, so the same
            # flight dump every other dying worker thread gets is fired
            # here explicitly — a scheduler-thread crash must not be
            # reconstructable only from a client's "error" event
            from ..observability import flight as _flight
            _flight.thread_exception_dump("serve-frontend-sched", e)
            self._drained.set()
            # never leave a connected client awaiting a queue that can
            # no longer be fed — flush an error-done to every stream
            with self._lock:
                streams = list(self._streams.values())
                self._streams.clear()
            for stream in streams:
                stream.push(("done", {"rid": stream.rid,
                                      "finish_reason": "error",
                                      "tokens": [], "ttft_ms": 0.0,
                                      "tpot_ms": 0.0,
                                      "queue_wait_ms": 0.0}))
        finally:
            b.done()      # thread exiting: stop watching this beacon

    # scheduler-thread callbacks -------------------------------------------

    def _on_token(self, rid, toks):
        # classic mode fires this from the scheduler thread; fleet mode
        # from whichever replica thread owns the rid — _streams is
        # lock-guarded so registration (loop thread) can't race it
        with self._lock:
            stream = self._streams.get(rid)
        if stream is not None:
            stream.push(("tokens", list(toks)))

    def _on_finish(self, result):
        with self._lock:
            stream = self._streams.pop(result.rid, None)
            self._outstanding -= 1
            # fleet mode has no scheduler loop to observe quiescence, so
            # the drain completes at the last finish callback
            drained = (self._router is not None and self._draining
                       and self._outstanding == 0)
        if drained:
            self._drained.set()
        if stream is None:
            return
        if stream.http_span is not None:
            stream.http_span.end(reason=result.finish_reason,
                                 tokens=int(result.tokens.size))
        stream.push(("done", {
            "rid": int(result.rid),
            "finish_reason": result.finish_reason,
            "tokens": [int(t) for t in result.tokens],
            "ttft_ms": round(1e3 * result.ttft, 3),
            "tpot_ms": round(1e3 * result.tpot, 3),
            "queue_wait_ms": round(1e3 * result.queue_wait, 3),
        }))

    # -- loop thread -------------------------------------------------------

    async def _heartbeat(self):
        """Loop-thread liveness: pulse from INSIDE the event loop, so a
        loop blocked by a wedged callback stops stamping and the
        monitor attributes the stall to ``serve.frontend_loop``."""
        b = _liveness.beacon("serve.frontend_loop")
        interval = max(min(
            _liveness.deadline_for("serve.frontend_loop") / 4.0, 1.0),
            0.01)
        b.begin()
        try:
            while True:
                await asyncio.sleep(interval)
                b.pulse()
        finally:
            b.done()

    def _loop_main(self):
        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)

        async def _boot():
            self._server = await asyncio.start_server(
                self._handle, self.host, self.port)
            self.port = self._server.sockets[0].getsockname()[1]

        self._loop.run_until_complete(_boot())
        self._started.set()
        # liveness heartbeat only when a monitor is armed: a disabled
        # stack schedules nothing on the loop
        hb = (self._loop.create_task(self._heartbeat())
              if _liveness.active() is not None else None)
        try:
            self._loop.run_forever()
        finally:
            if hb is not None:
                hb.cancel()
                try:
                    self._loop.run_until_complete(hb)
                except (asyncio.CancelledError, Exception):
                    pass
            self._server.close()
            self._loop.run_until_complete(self._server.wait_closed())
            self._loop.close()

    async def _handle(self, reader, writer):
        try:
            try:
                method, path, headers, body = await self._read_request(
                    reader)
            except (asyncio.IncompleteReadError, ValueError,
                    *_DISCONNECT_ERRORS):
                return
            if method == "GET" and path == "/healthz":
                # liveness-enriched health (ISSUE 14): an external probe
                # must be able to tell "socket alive but not
                # progressing" from healthy.  Beacon ages come from
                # liveness.state() (computed on read — the stall shows
                # as soon as age crosses the deadline, no monitor poll
                # needed), and this handler runs on the LOOP thread, so
                # it still answers while the scheduler thread is wedged
                # — which is exactly the scenario.
                beacons = _liveness.state()
                stalled = sorted(n for n, s in beacons.items()
                                 if s["stalled"])
                doc = {
                    "status": ("stalled" if stalled else
                               "draining" if self._draining else "ok"),
                    "stalled": stalled,
                    "beacons": beacons,
                    "open_streams": self._open_streams,
                    "outstanding": self._outstanding,
                }
                if self._router is not None:
                    # fleet view: depths are summed across replicas and
                    # the per-replica lifecycle state is spelled out so
                    # an external probe can see a respawn in flight
                    doc.update({
                        "queue_depth": self._router.queue_depth(),
                        "slots_active": self._router.slots_active(),
                        "handoff_depth": 0,
                        "replicas": self._router.replica_states(),
                        "replicas_healthy":
                            self._router.healthy_count(),
                    })
                else:
                    doc.update({
                        "queue_depth": len(self.scheduler.waiting),
                        "slots_active": sum(
                            a is not None for a in self.scheduler.slots),
                        # disaggregated schedulers also expose the
                        # handoff pipeline depth (0 when absent)
                        "handoff_depth": getattr(self.scheduler,
                                                 "handoff_depth", 0),
                    })
                await self._respond_json(writer, 200, doc)
                return
            if method != "POST" or path != "/v1/generate":
                await self._respond_json(writer, 404,
                                         {"error": "not found"})
                return
            await self._generate(writer, body)
        except _DISCONNECT_ERRORS:
            pass
        finally:
            try:
                writer.close()
            except Exception:
                pass

    async def _generate(self, writer, body):
        try:
            payload = json.loads(body.decode("utf-8"))
            prompt = np.asarray(payload["prompt"], np.int32).reshape(-1)
            req = Request(
                prompt=prompt,
                max_new_tokens=int(payload.get("max_new_tokens", 20)),
                temperature=float(payload.get("temperature", 1.0)),
                top_k=int(payload.get("top_k", 0)),
                top_p=float(payload.get("top_p", 1.0)),
                eos_token_id=payload.get("eos_token_id"))
            if prompt.size < 1:
                raise ValueError("empty prompt")
            if prompt.size > self._prompt_cap:
                raise ValueError("prompt length %d exceeds capacity %d"
                                 % (prompt.size, self._prompt_cap))
            if req.max_new_tokens < 1:
                raise ValueError("max_new_tokens must be >= 1")
            stream_mode = bool(payload.get("stream", True))
        except (KeyError, TypeError, ValueError,
                json.JSONDecodeError) as e:
            await self._respond_json(writer, 400, {"error": str(e)})
            return
        # -- admission control: 503 while draining, 429 over the bound --
        if self._draining or self._stop:
            self._m_shed.inc()
            await self._respond_json(writer, 503, {"error": "draining"})
            return
        with self._lock:
            if self._outstanding >= self.queue_limit:
                shed = True
            else:
                shed = False
                self._outstanding += 1
        if shed:
            self._m_shed.inc()
            await self._respond_json(writer, 429, {"error": "overloaded"})
            return
        stream = _Stream(asyncio.get_running_loop())
        if self._router is not None:
            # fleet mode: route NOW, on the loop thread — submit() is
            # pure-CPU (digest chain + lock-scoped enqueue onto the
            # chosen replica's command queue), never a scheduler call.
            # on_admit runs BEFORE any replica thread can emit a token
            # for this rid, so the stream registration can't lose a
            # token to the callback racing the admission.
            def _admitted(rid, root):
                stream.rid = rid
                with self._lock:
                    self._streams[rid] = stream
                stream.http_span = self._tracer.span(
                    "http", parent=root)
            from .router import NoHealthyReplicas
            try:
                self._router.submit(req, on_admit=_admitted)
            except NoHealthyReplicas:
                with self._lock:
                    self._outstanding -= 1
                self._m_shed.inc()
                await self._respond_json(
                    writer, 503, {"error": "no healthy replicas"})
                return
            except ValueError as e:
                with self._lock:
                    self._outstanding -= 1
                await self._respond_json(writer, 400,
                                         {"error": str(e)})
                return
        else:
            with self._lock:
                self._pending.append((req, stream))
            self._wake.set()
        if stream_mode:
            await self._stream_response(writer, stream)
        else:
            await self._buffered_response(writer, stream)

    async def _stream_response(self, writer, stream):
        writer.write(b"HTTP/1.1 200 OK\r\n"
                     b"Content-Type: text/event-stream\r\n"
                     b"Cache-Control: no-cache\r\n"
                     b"Connection: close\r\n\r\n")
        self._open_streams += 1
        self._m_open.inc(1)
        try:
            await writer.drain()
            while True:
                kind, item = await stream.queue.get()
                if kind == "rid":
                    continue
                # the chaos disconnect site: a SocketReset scheduled
                # here is indistinguishable from the client vanishing
                faultpoint(STREAM_SITE, rid=stream.rid)
                if kind == "tokens":
                    writer.write(b"data: " + json.dumps(
                        {"tokens": item}).encode() + b"\n\n")
                    await writer.drain()
                    self._m_goodput.inc(len(item))
                elif kind == "done":
                    writer.write(b"data: " + json.dumps(
                        dict(item, done=True)).encode() + b"\n\n")
                    await writer.drain()
                    # 200 means the stream COMPLETED: a cut stream
                    # counts once, as 499 — the code buckets partition
                    # requests (OBSERVABILITY.md documents them as
                    # mutually exclusive outcomes)
                    self._m_http.labels(code="200").inc()
                    return
        except _DISCONNECT_ERRORS:
            self._m_http.labels(code="499").inc()
            self._cancel_stream(stream)
        finally:
            self._open_streams -= 1
            self._m_open.inc(-1)

    async def _buffered_response(self, writer, stream):
        while True:
            kind, item = await stream.queue.get()
            if kind == "done":
                break
        try:
            await self._respond_json(writer, 200, item)
        except _DISCONNECT_ERRORS:
            # the client left before the buffered answer was written:
            # its tokens were never delivered — not goodput, not a 200
            self._m_http.labels(code="499").inc()
            return
        self._m_goodput.inc(len(item["tokens"]))
        self._m_http.labels(code="200").inc()

    def _cancel_stream(self, stream):
        """Client went away mid-stream: route a cancel to the scheduler
        thread (slot + pages freed refcount-exactly at the next
        iteration boundary).  Pre-submit, just mark the stream."""
        stream.cancelled = True
        if stream.rid is not None:
            if self._router is not None:
                # the router forwards to the owning replica's command
                # queue (lock-scoped, non-blocking from the loop thread)
                self._router.cancel(stream.rid)
            else:
                with self._lock:
                    self._cancels.append(stream.rid)
        self._wake.set()

    # -- http plumbing -----------------------------------------------------

    @staticmethod
    async def _read_request(reader):
        line = (await reader.readline()).decode("latin1").rstrip("\r\n")
        parts = line.split(" ")
        if len(parts) < 3:
            raise ValueError("malformed request line: %r" % line)
        method, path = parts[0].upper(), parts[1]
        headers = {}
        while True:
            h = await reader.readline()
            if h in (b"\r\n", b"\n", b""):
                break
            k, _, v = h.decode("latin1").partition(":")
            headers[k.strip().lower()] = v.strip()
        n = int(headers.get("content-length", "0") or 0)
        body = await reader.readexactly(n) if n else b""
        return method, path, headers, body

    async def _respond_json(self, writer, code, obj):
        reason = {200: "OK", 400: "Bad Request", 404: "Not Found",
                  429: "Too Many Requests",
                  503: "Service Unavailable"}.get(code, "Error")
        if code != 200:
            self._m_http.labels(code=str(code)).inc()
        body = json.dumps(obj).encode()
        writer.write(("HTTP/1.1 %d %s\r\n"
                      "Content-Type: application/json\r\n"
                      "Content-Length: %d\r\n"
                      "Connection: close\r\n\r\n"
                      % (code, reason, len(body))).encode() + body)
        await writer.drain()
