"""Self-speculative drafting — prompt-lookup (n-gram) proposals.

The cheapest useful draft model is the request's OWN token history:
natural-language generation constantly re-emits spans it has already
seen (copied entities, quoted context, code identifiers, the system
prompt's phrasing), so "find the most recent earlier occurrence of the
trailing n-gram and propose what followed it" (prompt-lookup decoding;
the n-gram analogue of Leviathan-style drafting with a zero-FLOP draft
model) accepts long runs exactly where decode is cheapest to amortize.

Everything here is host/numpy work over the slot's `prompt + generated`
history — no model FLOPs, no device traffic.  Draft QUALITY only moves
throughput, never correctness: the batched verify step accepts/resamples
against the real model distribution (``sampling.spec_accept``), so a
miss just degenerates that iteration to one token, same as plain decode.
Proposals are always exactly ``k`` tokens (the verify program is one
fixed shape): short matches and no-match slots are padded by repeating
the last token.
"""
from __future__ import annotations

import numpy as np

__all__ = ["propose"]


def propose(history, k, max_ngram=3):
    """Draft ``k`` tokens for a slot from its own token history.

    Tries the longest trailing n-gram first (``n = max_ngram .. 1``),
    scanning for its MOST RECENT earlier occurrence that has at least
    one continuation token; proposes the ``k`` tokens that followed,
    padded by repeating the history's last token.  Returns
    ``(draft (k,) int32, hit bool)`` — ``hit`` False means every
    position is pad (the verify step then degenerates to one token).
    """
    h = np.asarray(history, np.int32).reshape(-1)
    k = int(k)
    n_h = int(h.size)
    fill = int(h[-1]) if n_h else 0
    draft = np.full((k,), fill, np.int32)
    if n_h < 2:
        return draft, False
    for n in range(min(int(max_ngram), n_h - 1), 0, -1):
        tail = h[n_h - n:]
        # windows over h[:-1]: starts 0..n_h-1-n, so every match has at
        # least one continuation token and the trailing n-gram itself
        # (start n_h-n) is excluded
        windows = np.lib.stride_tricks.sliding_window_view(h[:-1], n)
        starts = np.nonzero((windows == tail).all(axis=1))[0]
        if starts.size:
            i = int(starts[-1])                    # most recent match
            cont = h[i + n:i + n + k]
            draft[:cont.size] = cont
            return draft, True
    return draft, False
