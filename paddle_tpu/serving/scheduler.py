"""Continuous batching — Orca-style iteration-level scheduling on the
host side of the compiled decode step.

The unit of scheduling is ONE decode iteration, not one request: after
every batched step the scheduler retires finished slots (EOS /
``max_new_tokens`` / cache-full) and immediately admits waiting requests
into the freed slots via bucketed prefill — the batch composition
changes between iterations while the decode program (fixed shape: all
``num_slots`` lanes every step) never recompiles.

States of a slot: ``free`` → (admit: prefill, samples the first token)
→ ``active`` → (EOS | budget | ``max_len``) → ``free``.  Admission is
strict FIFO over the waiting queue; prefill lengths are bucketed to
powers of two (``engine.buckets``) so the prefill jit cache is bounded
by ``log2(max_len)`` programs.

Per-request timing is recorded for the serving metrics the bench emits:
TTFT (submit → first token — still INCLUDES queue wait, for continuity
with the PR-5 trajectory), ``queue_wait`` (submit → admission, reported
separately so load tests can subtract it: under saturation TTFT is
dominated by queueing, not prefill), and TPOT (mean decode seconds per
subsequent token).  Every iteration also feeds the process-wide metrics
registry (paddle_tpu.observability — TTFT/TPOT/queue-wait histograms,
slot occupancy, prefill bucket hits, finish reasons, tokens); handles are
fetched once at construction, so with metrics disabled the per-token path
is a no-op method call with zero host allocation.
"""
from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Dict, List, Optional

import numpy as np

from ..observability import registry as _metrics

__all__ = ["Request", "RequestResult", "ContinuousBatchingScheduler"]


@dataclasses.dataclass
class Request:
    prompt: "np.ndarray"                 # 1-D int token ids
    max_new_tokens: int = 20
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0
    eos_token_id: Optional[int] = None
    rid: Optional[int] = None            # assigned by submit()


@dataclasses.dataclass
class RequestResult:
    rid: int
    tokens: "np.ndarray"                 # generated ids (prompt excluded)
    finish_reason: str                   # "eos" | "length" | "cache_full"
    ttft: float                          # submit -> first token, seconds
    tpot: float                          # mean secs/token after the first
    queue_wait: float = 0.0              # submit -> admission, seconds


class _ActiveSlot:
    __slots__ = ("req", "generated", "submit_t", "first_tok_t", "last_t",
                 "decode_s", "queue_wait")

    def __init__(self, req, first_token, submit_t, now, queue_wait=0.0):
        self.req = req
        self.generated = [int(first_token)]
        self.submit_t = submit_t
        self.first_tok_t = now
        self.last_t = now
        self.decode_s = 0.0
        self.queue_wait = queue_wait


class ContinuousBatchingScheduler:
    def __init__(self, engine):
        self.engine = engine
        self.waiting: deque = deque()
        self.slots: List[Optional[_ActiveSlot]] = [None] * engine.num_slots
        self.finished: Dict[int, RequestResult] = {}
        self._next_rid = 0
        self._submit_t: Dict[int, float] = {}
        # metric handles, fetched ONCE: with the registry disabled these
        # are the shared no-op singletons — the per-token hot path then
        # does nothing and allocates nothing (tests/test_observability.py
        # asserts the identity)
        self._m_ttft = _metrics.histogram("serving.ttft_seconds")
        self._m_queue_wait = _metrics.histogram("serving.queue_wait_seconds")
        self._m_tpot = _metrics.histogram("serving.tpot_seconds")
        self._m_decode_step = _metrics.histogram(
            "serving.decode_step_seconds")
        self._m_tokens = _metrics.counter("serving.generated_tokens")
        self._m_bucket_hits = _metrics.counter(
            "serving.prefill_bucket_hits", ("bucket",))
        self._m_finished = _metrics.counter(
            "serving.finished_requests", ("reason",))
        self._m_occupancy = _metrics.gauge("serving.slot_occupancy")
        self._m_queue_depth = _metrics.gauge("serving.queue_depth")

    # -- intake ------------------------------------------------------------

    def submit(self, req: Request) -> int:
        prompt = np.asarray(req.prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("empty prompt")
        if prompt.size > self.engine.buckets[-1]:
            raise ValueError(
                "prompt length %d exceeds the largest prefill bucket %d"
                % (prompt.size, self.engine.buckets[-1]))
        if req.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        req = dataclasses.replace(req, prompt=prompt, rid=self._next_rid)
        self._next_rid += 1
        self._submit_t[req.rid] = time.perf_counter()
        self.waiting.append(req)
        self._m_queue_depth.set(len(self.waiting))
        return req.rid

    # -- slot lifecycle ----------------------------------------------------

    def _finish(self, idx: int, reason: str):
        act = self.slots[idx]
        n = len(act.generated)
        tpot = (act.decode_s / (n - 1)) if n > 1 else 0.0
        ttft = act.first_tok_t - act.submit_t
        self.finished[act.req.rid] = RequestResult(
            rid=act.req.rid, tokens=np.asarray(act.generated, np.int32),
            finish_reason=reason, ttft=ttft, tpot=tpot,
            queue_wait=act.queue_wait)
        self.slots[idx] = None
        self._m_finished.labels(reason=reason).inc()
        self._m_ttft.observe(ttft)
        if n > 1:
            self._m_tpot.observe(tpot)

    def _check_finished(self, idx: int, lengths):
        """Retire the slot if its latest token ended the request.
        ``lengths`` is the post-step host copy of the engine's per-slot
        lengths — fetched ONCE per scheduler iteration by the caller (a
        per-slot engine.slot_lengths() here would be a device->host
        round-trip on the decode hot path, per slot per token)."""
        act = self.slots[idx]
        req = act.req
        tok = act.generated[-1]
        if req.eos_token_id is not None and tok == int(req.eos_token_id):
            self._finish(idx, "eos")
        elif len(act.generated) >= req.max_new_tokens:
            self._finish(idx, "length")
        elif int(lengths[idx]) >= self.engine.max_len:
            # no room for another append — retire rather than overflow
            self._finish(idx, "cache_full")

    def admit(self) -> int:
        """Fill free slots from the waiting queue (FIFO).  Each admission
        is one bucketed prefill; returns how many were admitted."""
        n = 0
        for idx in range(self.engine.num_slots):
            if self.slots[idx] is not None or not self.waiting:
                continue
            req = self.waiting.popleft()
            # a request whose prompt+budget exceeds max_len is still
            # admissible — generation just ends early with "cache_full"
            submit_t = self._submit_t.pop(req.rid)
            admit_t = time.perf_counter()
            queue_wait = admit_t - submit_t
            self._m_queue_wait.observe(queue_wait)
            self._m_bucket_hits.labels(
                bucket=self.engine.bucket_for(req.prompt.size)).inc()
            tok, _logits = self.engine.prefill(
                idx, req.prompt, temperature=req.temperature,
                top_k=req.top_k, top_p=req.top_p)
            now = time.perf_counter()
            self.slots[idx] = _ActiveSlot(req, tok, submit_t, now,
                                          queue_wait)
            n += 1
            self._check_finished(idx, self.engine.slot_lengths())
        if n:
            self._m_queue_depth.set(len(self.waiting))
            self._m_occupancy.set(
                sum(a is not None for a in self.slots))
        return n

    def decode_once(self) -> int:
        """One batched decode iteration over the active slots; returns the
        number of tokens appended to live requests."""
        active = [a is not None for a in self.slots]
        if not any(active):
            return 0
        S = self.engine.num_slots
        tokens = np.zeros((S,), np.int32)
        temps = np.ones((S,), np.float32)
        top_ks = np.zeros((S,), np.int32)
        top_ps = np.ones((S,), np.float32)
        for i, act in enumerate(self.slots):
            if act is None:
                continue
            tokens[i] = act.generated[-1]
            temps[i] = act.req.temperature
            top_ks[i] = act.req.top_k
            top_ps[i] = act.req.top_p
        t0 = time.perf_counter()
        next_tok, _logits = self.engine.decode(tokens, active, temps,
                                               top_ks, top_ps)
        t1 = time.perf_counter()
        lengths = self.engine.slot_lengths()   # ONE host copy per step
        n = 0
        for i, act in enumerate(self.slots):
            if act is None:
                continue
            act.generated.append(int(next_tok[i]))
            act.decode_s += t1 - t0
            act.last_t = t1
            n += 1
            self._check_finished(i, lengths)
        # per-ITERATION metrics (not per token): one histogram observe,
        # one counter inc, one gauge set per batched step
        self._m_decode_step.observe(t1 - t0)
        self._m_tokens.inc(n)
        self._m_occupancy.set(sum(a is not None for a in self.slots))
        return n

    def step(self) -> int:
        """One scheduler iteration: admit into free slots, then one
        batched decode.  Returns tokens produced (prefill first-tokens
        excluded)."""
        self.admit()
        return self.decode_once()

    def run(self) -> Dict[int, RequestResult]:
        """Drive to completion; returns {rid: RequestResult}.  Always
        terminates: with work pending, admit() either fills a free slot
        or all slots are active, and then decode_once() appends a token
        to every active request, each of which is finite (max_new_tokens
        / max_len eviction)."""
        while self.waiting or any(a is not None for a in self.slots):
            self.admit()
            self.decode_once()
        return self.finished
