"""Continuous batching — Orca-style iteration-level scheduling on the
host side of the compiled decode step.

The unit of scheduling is ONE decode iteration, not one request: after
every batched step the scheduler retires finished slots (EOS /
``max_new_tokens`` / cache-full) and immediately admits waiting requests
into the freed slots — the batch composition changes between iterations
while the decode program (fixed shape: all ``num_slots`` lanes every
step) never recompiles.

**Chunked prefill (paged engines — the default).**  Admission no longer
runs the whole prompt in one blocking call: it starts a
:class:`~.engine.PrefillTask` and each scheduler iteration advances
every admitting slot by ONE fixed-size chunk *between* decode steps, so
a 32k-token admission costs each in-flight request one chunk of extra
latency per token instead of one whole-prompt stall (TPOT
non-interference — tested).  A prefix-cache hit skips the shared pages
entirely (the counter ``serving.prefix_hit_pages`` meters it) and a
fully-cached prompt admits in a single 1-token chunk.

**Speculative decode (``spec_k`` engines — ISSUE 8).**  When the engine
was built with ``spec_k > 0`` the decode iteration becomes a *verify*
iteration: for every active slot the scheduler proposes ``spec_k``
tokens by prompt-lookup over the slot's own ``prompt + generated``
history (:mod:`.spec` — zero model FLOPs) and ONE compiled verify step
scores all ``spec_k + 1`` positions, accepting a per-slot prefix and
sampling one corrective token (``sampling.spec_accept``).  The
scheduler appends the emitted run, truncating at EOS and the
``max_new_tokens`` budget (truncation always retires the slot, so the
host token list and the device length mirror never diverge for live
slots).  Per-request ``spec_proposed``/``spec_accepted`` land on the
:class:`RequestResult` and on the ``serving.spec_proposed_tokens``/
``serving.spec_accepted_tokens`` counter pair (accept rate =
accepted/proposed).  TPOT keeps meaning seconds per decode-committed
token: a verify step's wall time is divided across every token it
emitted.

**Refcount-aware eviction, preemption by recompute.**  When the page
pool is dry (a decode append or a prefill chunk cannot map a page), the
victim is the active slot with the MOST unshared pages — freeing it
returns the most pages to the pool, whereas evicting a slot whose pages
are mostly shared prefix frees almost nothing (bare FIFO would thrash
exactly those slots under a prefix-heavy workload — tested).  Ties
break oldest-first.  The victim is not lost: it goes back to the front
of the waiting queue and, on re-admission, re-prefills
``prompt + generated-so-far`` (vLLM-style recompute preemption) — a
recompute that mostly prefix-hits the victim's own still-cached pages.
A request evicted more than ``max_preemptions`` times, or one whose
sequence the pool cannot hold even alone, finishes ``"cache_full"``.

States of a slot: ``free`` → (admit: begin prefill) → ``prefilling`` →
(final chunk samples the first token) → ``active`` → (EOS | budget |
``max_len`` | evicted-past-cap) → ``free``, with ``active``/
``prefilling`` → (preempted) → ``waiting`` → ``prefilling``.  Admission
is strict FIFO over the waiting queue.  Slotted engines
(``paged=False``) keep the PR-5 one-shot bucketed prefill.

**Request-scoped tracing (ISSUE 9).**  ``submit()`` mints a
``trace_id`` (threaded onto the :class:`RequestResult`) and opens a
``request`` root span; admission, each prefill chunk, each decode/
spec-verify iteration, preemption (``preempted`` event + ``requeue``
span + ``rework``-tagged recompute chunks), prefix hits, and finish all
land on that lane.  With tracing disabled (the default) the tracer is
the no-op singleton by identity and the decode hot loop spends nothing
(PR-6-style acceptance test); ``python -m paddle_tpu.observability
trace-report`` reconstructs the per-request timelines.

Per-request timing is recorded for the serving metrics the bench emits:
TTFT (submit → first token — still INCLUDES queue wait, for continuity
with the PR-5 trajectory), ``queue_wait`` (submit → admission, reported
separately so load tests can subtract it: under saturation TTFT is
dominated by queueing, not prefill), and TPOT (mean decode seconds per
subsequent token).  Every iteration also feeds the process-wide metrics
registry (paddle_tpu.observability); handles are fetched once at
construction, so with metrics disabled the per-token path is a no-op
method call with zero host allocation.
"""
from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Dict, List, Optional

import numpy as np

from ..observability import hbm as _hbm
from ..observability import registry as _metrics
from ..observability import tracing as _tracing
from .engine import PagePoolExhausted
from .spec import propose as _propose_draft

__all__ = ["Request", "RequestResult", "ContinuousBatchingScheduler"]


@dataclasses.dataclass
class Request:
    prompt: "np.ndarray"                 # 1-D int token ids
    max_new_tokens: int = 20
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0
    eos_token_id: Optional[int] = None
    rid: Optional[int] = None            # assigned by submit()


@dataclasses.dataclass
class RequestResult:
    rid: int
    tokens: "np.ndarray"                 # generated ids (prompt excluded)
    finish_reason: str                   # "eos" | "length" | "cache_full"
    ttft: float                          # submit -> first token, seconds
    tpot: float                          # mean secs per timed decode step
                                         # (prefill-sampled tokens, incl. a
                                         # resume's, are excluded)
    queue_wait: float = 0.0              # submit -> admission, seconds
    prefix_hit_tokens: int = 0           # tokens served from the prefix
                                         # cache, all admissions (a
                                         # preemption resume's hits count)
    spec_proposed: int = 0               # draft tokens proposed for this
                                         # request (spec_k per verify step)
    spec_accepted: int = 0               # draft tokens the verify step
                                         # accepted (rate = accepted /
                                         # proposed; 0/0 when spec off)
    trace_id: int = 0                    # request lane in the span trace
                                         # (ISSUE 9; 0 = tracing disabled)


class _ActiveSlot:
    __slots__ = ("req", "generated", "submit_t", "first_tok_t", "last_t",
                 "decode_s", "decode_steps", "queue_wait", "prefill_task",
                 "admit_order", "prefix_hit_tokens", "spec_proposed",
                 "spec_accepted")

    def __init__(self, req, submit_t, queue_wait, admit_order,
                 prefill_task=None):
        self.req = req
        self.generated: List[int] = []
        self.submit_t = submit_t
        self.first_tok_t = None
        self.last_t = None
        self.decode_s = 0.0
        self.decode_steps = 0          # timed decode-committed TOKENS
                                       # only (a verify step counts every
                                       # token it emitted): a preemption
                                       # resume's prefill-sampled token
                                       # adds no decode_s, so
                                       # len(generated)-1 would deflate
                                       # TPOT
        self.queue_wait = queue_wait
        self.prefill_task = prefill_task   # None once prefill completed
        self.admit_order = admit_order     # FIFO tie-break for eviction
        self.prefix_hit_tokens = (prefill_task.shared_tokens
                                  if prefill_task is not None else 0)
        self.spec_proposed = 0
        self.spec_accepted = 0

    def first_token(self, tok, now):
        self.generated.append(int(tok))
        # a resumed (preempted) slot's recompute-prefill also lands
        # here: its true first-token time is the original one
        if self.first_tok_t is None:
            self.first_tok_t = now
        self.last_t = now


class ContinuousBatchingScheduler:
    # page-pressure evictions per request before the scheduler stops
    # requeueing it and finishes it "cache_full" — bounds wasted
    # recompute and keeps run()'s termination argument trivial
    max_preemptions = 3

    def __init__(self, engine, tracer=None):
        self.engine = engine
        self.waiting: deque = deque()
        self.slots: List[Optional[_ActiveSlot]] = [None] * engine.num_slots
        self.finished: Dict[int, RequestResult] = {}
        self._next_rid = 0
        self._admit_seq = 0
        self._submit_t: Dict[int, float] = {}
        # request-scoped tracing (ISSUE 9): a trace_id minted at submit,
        # a root "request" span, and per-phase child spans.  With tracing
        # disabled (the default) the tracer is the module no-op singleton
        # BY IDENTITY and every call below is an empty method — the
        # PR-6-style acceptance test asserts it.  The decode hot loop
        # additionally short-circuits on `_tron` so the per-slot span
        # bookkeeping costs nothing when off.
        self._tracer = (tracer if tracer is not None
                        else _tracing.default_tracer())
        self._tron = bool(self._tracer.enabled)
        self._trace_ids: Dict[int, int] = {}       # rid -> trace lane
        self._req_spans: Dict[int, object] = {}    # rid -> root span
        self._wait_spans: Dict[int, object] = {}   # rid -> queue/requeue
        # rid -> parked _ActiveSlot (evicted, waiting to resume) and
        # rid -> times evicted; see _preempt()
        self._preempted: Dict[int, _ActiveSlot] = {}
        self._preempt_count: Dict[int, int] = {}
        # metric handles, fetched ONCE: with the registry disabled these
        # are the shared no-op singletons — the per-token hot path then
        # does nothing and allocates nothing (tests/test_observability.py
        # asserts the identity)
        self._m_ttft = _metrics.histogram("serving.ttft_seconds")
        self._m_queue_wait = _metrics.histogram("serving.queue_wait_seconds")
        self._m_tpot = _metrics.histogram("serving.tpot_seconds")
        self._m_decode_step = _metrics.histogram(
            "serving.decode_step_seconds")
        self._m_prefill_chunk = _metrics.histogram(
            "serving.prefill_chunk_seconds")
        self._m_tokens = _metrics.counter("serving.generated_tokens")
        self._m_bucket_hits = _metrics.counter(
            "serving.prefill_bucket_hits", ("bucket",))
        self._m_prefix_hits = _metrics.counter("serving.prefix_hit_pages")
        self._m_preempt = _metrics.counter("serving.preemptions")
        self._m_spec_prop = _metrics.counter(
            "serving.spec_proposed_tokens")
        self._m_spec_acc = _metrics.counter(
            "serving.spec_accepted_tokens")
        self._m_finished = _metrics.counter(
            "serving.finished_requests", ("reason",))
        self._m_occupancy = _metrics.gauge("serving.slot_occupancy")
        self._m_queue_depth = _metrics.gauge("serving.queue_depth")

    # -- intake ------------------------------------------------------------

    def submit(self, req: Request) -> int:
        prompt = np.asarray(req.prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("empty prompt")
        cap = self.engine.prompt_cap
        if prompt.size > cap:
            raise ValueError(
                "prompt length %d exceeds the engine's prompt capacity %d"
                % (prompt.size, cap))
        if req.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        req = dataclasses.replace(req, prompt=prompt, rid=self._next_rid)
        self._next_rid += 1
        self._submit_t[req.rid] = time.perf_counter()
        self.waiting.append(req)
        # the trace is born HERE: root "request" span + the initial
        # "queue" child (ended at admission).  No-op identity calls when
        # tracing is disabled.
        tid = self._tracer.new_trace()
        root = self._tracer.span(
            "request", trace_id=tid, rid=req.rid,
            prompt_len=int(prompt.size),
            max_new_tokens=int(req.max_new_tokens))
        self._trace_ids[req.rid] = tid
        self._req_spans[req.rid] = root
        self._wait_spans[req.rid] = self._tracer.span("queue", parent=root)
        self._m_queue_depth.set(len(self.waiting))
        return req.rid

    # -- slot lifecycle ----------------------------------------------------

    def _finish(self, idx: int, reason: str):
        act = self.slots[idx]
        tpot = (act.decode_s / act.decode_steps) if act.decode_steps \
            else 0.0
        # a request evicted before producing any token (cache_full mid-
        # prefill) has no first-token time: its ttft is reported as 0.0
        # and NOT fed to the histogram — a fabricated eviction-time
        # sample would pollute the p50/p99 TTFT the bench reports
        got_first = act.first_tok_t is not None
        ttft = (act.first_tok_t - act.submit_t) if got_first else 0.0
        self.finished[act.req.rid] = RequestResult(
            rid=act.req.rid, tokens=np.asarray(act.generated, np.int32),
            finish_reason=reason, ttft=ttft, tpot=tpot,
            queue_wait=act.queue_wait,
            prefix_hit_tokens=act.prefix_hit_tokens,
            spec_proposed=act.spec_proposed,
            spec_accepted=act.spec_accepted,
            trace_id=self._trace_ids.pop(act.req.rid, 0))
        ws = self._wait_spans.pop(act.req.rid, None)
        if ws is not None:
            ws.end()
        self._req_spans.pop(act.req.rid, _tracing.NOOP_SPAN).end(
            reason=reason, tokens=len(act.generated))
        self.slots[idx] = None
        self.engine.free_slot(idx)     # paged: pages back to the pool
        self._preempt_count.pop(act.req.rid, None)
        self._m_finished.labels(reason=reason).inc()
        if got_first:
            self._m_ttft.observe(ttft)
        if act.decode_steps:
            self._m_tpot.observe(tpot)

    def _check_finished(self, idx: int, lengths):
        """Retire the slot if its latest token ended the request.
        ``lengths`` is the post-step per-slot lengths — fetched ONCE per
        scheduler iteration by the caller (paged engines serve a host
        mirror; a per-slot device fetch here would be a device->host
        round-trip on the decode hot path, per slot per token)."""
        act = self.slots[idx]
        req = act.req
        if not act.generated:
            return
        tok = act.generated[-1]
        if req.eos_token_id is not None and tok == int(req.eos_token_id):
            self._finish(idx, "eos")
        elif len(act.generated) >= req.max_new_tokens:
            self._finish(idx, "length")
        elif int(lengths[idx]) >= self.engine.max_len:
            # no room for another append — retire rather than overflow
            self._finish(idx, "cache_full")

    # -- refcount-aware eviction (page pool pressure) ----------------------

    def _preempt(self, idx: int):
        """vLLM-style recompute preemption: park the slot's state, free
        its pages, and put the request back at the FRONT of the waiting
        queue.  On re-admission the request re-prefills
        ``prompt + generated`` — greedy continuation is unchanged and
        the recompute mostly prefix-hits the victim's own still-cached
        (refcount-0 but hash-reachable) pages — instead of being
        finished with whatever it had: a victim evicted mid-prefill
        would otherwise silently return an EMPTY token array through
        ``generate()``."""
        act = self.slots[idx]
        rid = act.req.rid
        self.slots[idx] = None
        self.engine.free_slot(idx)     # pages back (shared: refcount--)
        act.prefill_task = None        # chunk state is page-bound: drop
        self.waiting.appendleft(act.req)
        self._submit_t[rid] = act.submit_t
        self._preempted[rid] = act
        # trace: mark the eviction on the request lane and open the
        # "requeue" rework-wait span (ended at re-admission)
        root = self._req_spans.get(rid, _tracing.NOOP_SPAN)
        root.event("preempted", slot=idx, generated=len(act.generated))
        self._wait_spans[rid] = self._tracer.span("requeue", parent=root,
                                                  rework=True)
        self._m_preempt.inc()
        self._m_queue_depth.set(len(self.waiting))

    def _evict_for_pages(self, requester_idx: int) -> bool:
        """Free pages by preempting one slot.  Victim: the occupied
        slot with the MOST unshared pages (what eviction actually
        returns to the pool — a prefix-heavy slot's shared pages only
        drop a refcount), preferring slots other than the requester;
        ties break oldest-admitted-first.  The victim is requeued for
        recompute unless it has already been evicted
        ``max_preemptions`` times (then it finishes "cache_full" — the
        cap bounds wasted recompute and preserves termination).
        Returns False only when the requester itself was the last
        occupant: a sequence the pool cannot hold alone is finished
        "cache_full", never requeued (it would loop forever)."""
        candidates = [i for i, a in enumerate(self.slots)
                      if a is not None and i != requester_idx]
        if not candidates:
            self._finish(requester_idx, "cache_full")
            return False
        victim = max(candidates,
                     key=lambda i: (self.engine.unshared_pages(i),
                                    -self.slots[i].admit_order))
        rid = self.slots[victim].req.rid
        n = self._preempt_count.get(rid, 0) + 1
        self._preempt_count[rid] = n
        if n > self.max_preemptions:
            self._finish(victim, "cache_full")
        else:
            self._preempt(victim)
        return True

    # -- admission ---------------------------------------------------------

    def _begin_paged(self, idx: int, req: Request, ids):
        """Start a chunked-prefill admission of ``ids`` into ``idx`` —
        the one place for the prefill_begin call and its prefix-hit
        metric (fresh admissions and preemption resumes both land
        here)."""
        task = self.engine.prefill_begin(
            idx, ids, temperature=req.temperature,
            top_k=req.top_k, top_p=req.top_p)
        if task.shared_pages:
            self._m_prefix_hits.inc(task.shared_pages)
            self._req_spans.get(req.rid, _tracing.NOOP_SPAN).event(
                "prefix_hit", pages=task.shared_pages,
                tokens=task.shared_tokens)
        return task

    def admit(self) -> int:
        """Fill free slots from the waiting queue (FIFO).  Paged engines
        only BEGIN the prefill here (chunks run in :meth:`step`,
        interleaved with decode); slotted engines run their one-shot
        bucketed prefill.  Returns how many requests were admitted."""
        n = 0
        for idx in range(self.engine.num_slots):
            if self.slots[idx] is not None or not self.waiting:
                continue
            req = self.waiting.popleft()
            # a request whose prompt+budget exceeds max_len is still
            # admissible — generation just ends early with "cache_full"
            submit_t = self._submit_t.pop(req.rid)
            resumed = self._preempted.pop(req.rid, None)
            order = self._admit_seq
            self._admit_seq += 1
            # close the wait span (initial "queue", or a preemption's
            # "requeue") and mark the admission on the request lane
            ws = self._wait_spans.pop(req.rid, None)
            if ws is not None:
                ws.end()
            root = self._req_spans.get(req.rid, _tracing.NOOP_SPAN)
            root.event("readmitted" if resumed is not None else "admitted",
                       slot=idx)
            if resumed is not None:
                # recompute-resume a preempted request: re-prefill
                # prompt + generated so the next sampled token continues
                # the sequence; timing state (ttft, decode_s) and the
                # token list survive on the parked slot.  queue_wait is
                # NOT re-observed — one histogram sample per request.
                ids = req.prompt
                if resumed.generated:
                    ids = np.concatenate(
                        [ids, np.asarray(resumed.generated, np.int32)])
                task = self._begin_paged(idx, req, ids)
                # keep the per-request field consistent with the
                # registry counter: resume hits are cache-served work too
                resumed.prefix_hit_tokens += task.shared_tokens
                resumed.prefill_task = task
                resumed.admit_order = order
                self.slots[idx] = resumed
                n += 1
                continue
            admit_t = time.perf_counter()
            queue_wait = admit_t - submit_t
            self._m_queue_wait.observe(queue_wait)
            if self.engine.paged:
                task = self._begin_paged(idx, req, req.prompt)
                self.slots[idx] = _ActiveSlot(req, submit_t, queue_wait,
                                              order, prefill_task=task)
            else:
                self._m_bucket_hits.labels(
                    bucket=self.engine.bucket_for(req.prompt.size)).inc()
                sp = self._tracer.span("prefill", parent=root, slot=idx)
                tok, _logits = self.engine.prefill(
                    idx, req.prompt, temperature=req.temperature,
                    top_k=req.top_k, top_p=req.top_p)
                sp.end()
                root.event("first_token")
                act = _ActiveSlot(req, submit_t, queue_wait, order)
                act.first_token(tok, time.perf_counter())
                self.slots[idx] = act
                self._check_finished(idx, self.engine.slot_lengths())
            n += 1
        if n:
            self._m_queue_depth.set(len(self.waiting))
            self._m_occupancy.set(
                sum(a is not None for a in self.slots))
        return n

    def prefill_once(self) -> int:
        """Advance every admitting slot by ONE chunk (the chunked-
        prefill interleave).  A chunk that cannot map pages evicts the
        max-unshared victim and retries.  Returns chunks run."""
        n = 0
        for idx, act in enumerate(self.slots):
            if act is None or act.prefill_task is None:
                continue
            task = act.prefill_task
            rid = act.req.rid
            root = self._req_spans.get(rid, _tracing.NOOP_SPAN)
            # chunks run after a preemption are recompute REWORK (the
            # re-prefill of prompt + generated) — tagged so the trace
            # analyzer can attribute them separately from first-admission
            # prefill (rid stays in _preempt_count until finish)
            sp = (self._tracer.span("prefill_chunk", parent=root,
                                    pos=task.pos, rework=True)
                  if rid in self._preempt_count else
                  self._tracer.span("prefill_chunk", parent=root,
                                    pos=task.pos))
            t0 = time.perf_counter()
            while True:
                try:
                    done = self.engine.prefill_step(task)
                    break
                except PagePoolExhausted:
                    if not self._evict_for_pages(idx):
                        done = None    # requester itself was retired
                        break
            sp.end()
            if done is None:
                continue
            now = time.perf_counter()
            self._m_prefill_chunk.observe(now - t0)
            n += 1
            if done:
                act.prefill_task = None
                if act.first_tok_t is None:
                    root.event("first_token")
                act.first_token(task.first_token, now)
                self._check_finished(idx, self.engine.slot_lengths())
        return n

    # -- decode ------------------------------------------------------------

    def decode_once(self) -> int:
        """One batched decode (or speculative verify) iteration over the
        active (fully-prefilled) slots; returns the number of tokens
        appended to live requests."""
        def active_mask():
            return [a is not None and a.prefill_task is None
                    for a in self.slots]

        spec_k = int(getattr(self.engine, "spec_k", 0))
        active = active_mask()
        if not any(active):
            return 0
        if self.engine.paged:
            # pre-step page bookkeeping: every append (k+1 of them per
            # slot for a verify step) needs a mapped private page;
            # pool-dry evicts the max-unshared victim
            while True:
                blocked = self.engine.ensure_decode_ready(
                    active, steps=spec_k + 1)
                if blocked is None:
                    break
                self._evict_for_pages(blocked)
                active = active_mask()
                if not any(active):
                    return 0
        S = self.engine.num_slots
        tokens = np.zeros((S,), np.int32)
        temps = np.ones((S,), np.float32)
        top_ks = np.zeros((S,), np.int32)
        top_ps = np.ones((S,), np.float32)
        drafts = np.zeros((S, max(spec_k, 1)), np.int32)
        for i, act in enumerate(self.slots):
            if not active[i]:
                continue
            tokens[i] = act.generated[-1]
            temps[i] = act.req.temperature
            top_ks[i] = act.req.top_k
            top_ps[i] = act.req.top_p
            if spec_k:
                # self-speculative prompt-lookup draft over the slot's
                # OWN history — host-side, zero model FLOPs; a miss just
                # pads (the verify step then emits one token, like decode)
                hist = np.concatenate(
                    [act.req.prompt,
                     np.asarray(act.generated, np.int32)])
                drafts[i], _hit = _propose_draft(
                    hist, spec_k, getattr(self.engine, "spec_ngram", 3))
        # ONE clock read per boundary, in ns: the step time feeds the
        # histogram AND stamps every involved request's trace span with
        # the SAME interval, so trace-report TPOT reproduces the metric
        t0_ns = time.perf_counter_ns()
        if spec_k:
            emitted, counts, _logits = self.engine.decode_spec(
                tokens, drafts, active, temps, top_ks, top_ps,
                pages_ready=True)
        else:
            next_tok, _logits = self.engine.decode(tokens, active, temps,
                                                   top_ks, top_ps,
                                                   pages_ready=True)
        t1_ns = time.perf_counter_ns()
        step_s = (t1_ns - t0_ns) * 1e-9
        t1 = t1_ns * 1e-9                      # last_t bookkeeping
        lengths = self.engine.slot_lengths()   # ONE fetch per step
        n = 0
        spec_prop = spec_acc = 0               # per-ITERATION counter incs
        for i, act in enumerate(self.slots):
            if not active[i]:
                continue
            if spec_k:
                emit = [int(t) for t in emitted[i, :int(counts[i])]]
                act.spec_proposed += spec_k
                act.spec_accepted += len(emit) - 1
                spec_prop += spec_k
                spec_acc += len(emit) - 1
                # truncate at the budget and at EOS — both retire the
                # slot in _check_finished, so a truncated host token
                # list never belongs to a live (still-decoding) slot
                room = act.req.max_new_tokens - len(act.generated)
                emit = emit[:max(room, 0)]
                if act.req.eos_token_id is not None:
                    eos = int(act.req.eos_token_id)
                    if eos in emit:
                        emit = emit[:emit.index(eos) + 1]
            else:
                emit = [int(next_tok[i])]
            act.generated.extend(emit)
            act.decode_s += step_s
            act.decode_steps += len(emit)   # TPOT = secs per token
            act.last_t = t1
            n += len(emit)
            if self._tron:
                # one span per involved request per iteration, stamped
                # with the shared step interval; `tokens` is the
                # decode-committed count (post-truncation), matching the
                # TPOT accounting exactly
                self._tracer.add_span(
                    "spec_verify" if spec_k else "decode", t0_ns, t1_ns,
                    parent=self._req_spans.get(act.req.rid),
                    tokens=len(emit))
            self._check_finished(i, lengths)
        # per-ITERATION metrics (not per token): one histogram observe,
        # one counter inc, one gauge set per batched step
        self._m_decode_step.observe(step_s)
        self._m_tokens.inc(n)
        if spec_prop:
            self._m_spec_prop.inc(spec_prop)
            self._m_spec_acc.inc(spec_acc)
        self._m_occupancy.set(sum(a is not None for a in self.slots))
        return n

    def step(self) -> int:
        """One scheduler iteration: admit into free slots, advance every
        admitting slot by one prefill chunk, then one batched decode.
        Returns decode tokens produced (prefill first-tokens excluded)."""
        self.admit()
        self.prefill_once()
        n = self.decode_once()
        # HBM ledger sample at the ITERATION boundary (host-side, after
        # the batched step dispatched — never inside a trace).  One
        # module-global None check while the ledger is disarmed, the
        # default (tests assert the noop path).
        _hbm.maybe_sample("serving.iteration")
        return n

    def run(self) -> Dict[int, RequestResult]:
        """Drive to completion; returns {rid: RequestResult}.  Always
        terminates: with work pending, admit() either fills a free slot
        or all slots are occupied; prefill_once() advances every
        admitting prompt by one (finite) chunk — evicting on page
        pressure rather than blocking — and decode_once() appends a
        token to every active request, each of which is finite
        (max_new_tokens / max_len eviction).  Preemption cannot spin
        forever: each request is requeued at most ``max_preemptions``
        times before it finishes "cache_full", and a requester that is
        the sole occupant is finished, never requeued."""
        while self.waiting or any(a is not None for a in self.slots):
            self.step()
        return self.finished
