"""Continuous batching — Orca-style iteration-level scheduling on the
host side of the compiled decode step.

The unit of scheduling is ONE decode iteration, not one request: after
every batched step the scheduler retires finished slots (EOS /
``max_new_tokens`` / cache-full) and immediately admits waiting requests
into the freed slots — the batch composition changes between iterations
while the decode program (fixed shape: all ``num_slots`` lanes every
step) never recompiles.

**Overlapped host/device loop (ISSUE 13 — the default).**  The loop
keeps ONE decode step in flight: iteration t dispatches the compiled
step threading iteration t-1's sampled tokens on DEVICE (jax dispatch
is async — the only blocking point is the token fetch), then consumes
t-1, so EOS/budget truncation, drafting, page bookkeeping, admission
and span/metric emission all overlap the device's compute of step t.
One-step-stale decisions are reconciled at consume time by IDENTITY:
a lane is credited only if the same request still occupies it — the
overshoot token a stale dispatch computed for a since-retired/
preempted/cancelled slot is discarded, its append lands in pages
``free_slot`` already reclaimed (length-masked reads keep stale rows
unreachable), and the host length mirror stays exact.  Greedy output
is BIT-IDENTICAL to the sync loop (``overlap=False`` /
``PADDLE_TPU_SERVE_OVERLAP=0``, kept for A/B); page pressure drains
the in-flight step before evicting.  ``host_gap_seconds`` /
``decode_steps_total`` expose the structural win the bench reports:
wall time per step with NO step in flight (the device-starvation
window) collapses from the whole per-step host budget to true
pipeline bubbles.

**Chunked prefill (paged engines — the default).**  Admission no longer
runs the whole prompt in one blocking call: it starts a
:class:`~.engine.PrefillTask` and each scheduler iteration advances
every admitting slot by ONE fixed-size chunk *between* decode steps, so
a 32k-token admission costs each in-flight request one chunk of extra
latency per token instead of one whole-prompt stall (TPOT
non-interference — tested).  A prefix-cache hit skips the shared pages
entirely (the counter ``serving.prefix_hit_pages`` meters it) and a
fully-cached prompt admits in a single 1-token chunk.

**Speculative decode (``spec_k`` engines — ISSUE 8).**  When the engine
was built with ``spec_k > 0`` the decode iteration becomes a *verify*
iteration: for every active slot the scheduler proposes ``spec_k``
tokens by prompt-lookup over the slot's own ``prompt + generated``
history (:mod:`.spec` — zero model FLOPs) and ONE compiled verify step
scores all ``spec_k + 1`` positions, accepting a per-slot prefix and
sampling one corrective token (``sampling.spec_accept``).  The
scheduler appends the emitted run, truncating at EOS and the
``max_new_tokens`` budget (truncation always retires the slot, so the
host token list and the device length mirror never diverge for live
slots).  Per-request ``spec_proposed``/``spec_accepted`` land on the
:class:`RequestResult` and on the ``serving.spec_proposed_tokens``/
``serving.spec_accepted_tokens`` counter pair (accept rate =
accepted/proposed).  TPOT keeps meaning seconds per decode-committed
token: a verify step's wall time is divided across every token it
emitted.

**Refcount-aware eviction, preemption by recompute.**  When the page
pool is dry (a decode append or a prefill chunk cannot map a page), the
victim is the active slot with the MOST unshared pages — freeing it
returns the most pages to the pool, whereas evicting a slot whose pages
are mostly shared prefix frees almost nothing (bare FIFO would thrash
exactly those slots under a prefix-heavy workload — tested).  Ties
break oldest-first.  The victim is not lost: it goes back to the front
of the waiting queue and, on re-admission, re-prefills
``prompt + generated-so-far`` (vLLM-style recompute preemption) — a
recompute that mostly prefix-hits the victim's own still-cached pages.
A request evicted more than ``max_preemptions`` times, or one whose
sequence the pool cannot hold even alone, finishes ``"cache_full"``.

States of a slot: ``free`` → (admit: begin prefill) → ``prefilling`` →
(final chunk samples the first token) → ``active`` → (EOS | budget |
``max_len`` | evicted-past-cap) → ``free``, with ``active``/
``prefilling`` → (preempted) → ``waiting`` → ``prefilling``.  Admission
is strict FIFO over the waiting queue.  Slotted engines
(``paged=False``) keep the PR-5 one-shot bucketed prefill.

**Request-scoped tracing (ISSUE 9).**  ``submit()`` mints a
``trace_id`` (threaded onto the :class:`RequestResult`) and opens a
``request`` root span; admission, each prefill chunk, each decode/
spec-verify iteration, preemption (``preempted`` event + ``requeue``
span + ``rework``-tagged recompute chunks), prefix hits, and finish all
land on that lane.  With tracing disabled (the default) the tracer is
the no-op singleton by identity and the decode hot loop spends nothing
(PR-6-style acceptance test); ``python -m paddle_tpu.observability
trace-report`` reconstructs the per-request timelines.

Per-request timing is recorded for the serving metrics the bench emits:
TTFT (submit → first token — still INCLUDES queue wait, for continuity
with the PR-5 trajectory), ``queue_wait`` (submit → admission, reported
separately so load tests can subtract it: under saturation TTFT is
dominated by queueing, not prefill), and TPOT (mean decode seconds per
subsequent token).  Every iteration also feeds the process-wide metrics
registry (paddle_tpu.observability); handles are fetched once at
construction, so with metrics disabled the per-token path is a no-op
method call with zero host allocation.
"""
from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Dict, List, Optional

import numpy as np

from ..observability import flight as _flight
from ..observability import hbm as _hbm
from ..observability import liveness as _liveness
from ..observability import registry as _metrics
from ..observability import tracing as _tracing
from ..robustness.faultpoints import declare as _declare, faultpoint
from .engine import PagePoolExhausted, PrefillTask
# importing the tier module also declares its faultpoint site and
# liveness beacon (the scheduler fetches the beacon handle at init)
from .kv_tier import TRANSPORT_ERRORS as _TIER_ERRORS
from .spec import propose as _propose_draft

__all__ = ["Request", "RequestResult", "RequeueState",
           "ContinuousBatchingScheduler"]

#: chaos site on the scheduler's hot iteration, INSIDE the liveness
#: beacon's guard: a scheduled ``Hang`` here simulates a wedged decode
#: loop (stuck collective / device hang) and must trip the watchdog
STEP_SITE = _declare(
    "serve.step",
    "fires at the top of every scheduler iteration (a Hang here "
    "simulates a wedged decode loop for the liveness watchdog)")

#: liveness beacon over one scheduler iteration; generous default —
#: the first iteration pays the decode/prefill XLA compiles
_declare_beacon = _liveness.declare_beacon
_declare_beacon("serve.scheduler_step",
                "one continuous-batching scheduler iteration (admit + "
                "prefill chunk + batched decode dispatch/consume)",
                deadline=600.0)


@dataclasses.dataclass
class Request:
    prompt: "np.ndarray"                 # 1-D int token ids
    max_new_tokens: int = 20
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0
    eos_token_id: Optional[int] = None
    rid: Optional[int] = None            # assigned by submit()


@dataclasses.dataclass
class RequestResult:
    rid: int
    tokens: "np.ndarray"                 # generated ids (prompt excluded)
    finish_reason: str                   # "eos" | "length" |
                                         # "cache_full" | "cancelled"
                                         # (client gone — frontend)
    ttft: float                          # submit -> first token, seconds
    tpot: float                          # mean secs per timed decode step
                                         # (prefill-sampled tokens, incl. a
                                         # resume's, are excluded)
    queue_wait: float = 0.0              # submit -> admission, seconds
    prefix_hit_tokens: int = 0           # tokens served from the prefix
                                         # cache, all admissions (a
                                         # preemption resume's hits count)
    spec_proposed: int = 0               # draft tokens proposed for this
                                         # request (spec_k per verify step)
    spec_accepted: int = 0               # draft tokens the verify step
                                         # accepted (rate = accepted /
                                         # proposed; 0/0 when spec off)
    trace_id: int = 0                    # request lane in the span trace
                                         # (ISSUE 9; 0 = tracing disabled)


@dataclasses.dataclass
class RequeueState:
    """Portable snapshot of ONE unfinished request — the unit of
    scheduler-to-scheduler transfer (ISSUE 19 replica failover, and
    graceful replica decommission).  Produced by
    :meth:`ContinuousBatchingScheduler.export_requeue_state` or
    synthesized by the router from its own admission records when the
    owning replica died too hard to export anything; consumed by
    :meth:`ContinuousBatchingScheduler.import_requeue`, which feeds it
    through the existing recompute-preemption resume path — the
    survivor re-prefills ``prompt + generated`` and the stream picks up
    at the next token."""
    req: Request                          # rid already assigned
    generated: List[int] = dataclasses.field(default_factory=list)
    submit_t: float = 0.0                 # original perf_counter stamp
    first_tok_t: Optional[float] = None   # preserved across the hop
    requeues: int = 0                     # prior evictions + failovers
                                          # (seeds _preempt_count: one
                                          # max_preemptions-style bound
                                          # covers both)
    trace_id: int = 0
    root_span: object = None              # live "request" span, adopted
    queue_wait: Optional[float] = None    # None = never admitted (the
                                          # survivor observes it once)
    decode_s: float = 0.0
    decode_steps: int = 0
    prefix_hit_tokens: int = 0
    spec_proposed: int = 0
    spec_accepted: int = 0


class _ActiveSlot:
    __slots__ = ("req", "generated", "submit_t", "first_tok_t", "last_t",
                 "decode_s", "decode_steps", "queue_wait", "prefill_task",
                 "admit_order", "prefix_hit_tokens", "spec_proposed",
                 "spec_accepted", "cache_len")

    def __init__(self, req, submit_t, queue_wait, admit_order,
                 prefill_task=None):
        self.req = req
        self.generated: List[int] = []
        self.submit_t = submit_t
        self.first_tok_t = None
        self.last_t = None
        self.decode_s = 0.0
        self.decode_steps = 0          # timed decode-committed TOKENS
                                       # only (a verify step counts every
                                       # token it emitted): a preemption
                                       # resume's prefill-sampled token
                                       # adds no decode_s, so
                                       # len(generated)-1 would deflate
                                       # TPOT
        self.queue_wait = queue_wait
        self.prefill_task = prefill_task   # None once prefill completed
        self.admit_order = admit_order     # FIFO tie-break for eviction
        self.prefix_hit_tokens = (prefill_task.shared_tokens
                                  if prefill_task is not None else 0)
        self.spec_proposed = 0
        self.spec_accepted = 0
        # committed cache rows this request holds, mirrored host-side
        # from what the device programs actually advanced (prefill sets
        # it to the prompt length; each CONSUMED decode/verify step adds
        # its in-program advance, clamped at max_len exactly like the
        # device finalize).  The cache_full retire check reads this —
        # no per-iteration device fetch, and it stays exact in the
        # overlapped loop where the engine's dispatch-time mirror runs
        # one step ahead of consumed truth.
        self.cache_len = 0

    def first_token(self, tok, now):
        self.generated.append(int(tok))
        # a resumed (preempted) slot's recompute-prefill also lands
        # here: its true first-token time is the original one
        if self.first_tok_t is None:
            self.first_tok_t = now
        self.last_t = now


class _Inflight:
    """Scheduler-side record of ONE dispatched, unconsumed decode (or
    verify) step: the engine's :class:`~.engine.InflightDecode` plus the
    per-lane occupant identities at dispatch time.  Consume credits a
    lane ONLY if the same :class:`_ActiveSlot` object still occupies it
    — a slot retired (EOS/budget/cache-full), preempted, or cancelled
    after the dispatch simply has its overshoot token(s) discarded,
    which is the whole one-step-stale reconciliation rule."""
    __slots__ = ("rec", "lane_acts", "t0_ns")

    def __init__(self, rec, lane_acts, t0_ns):
        self.rec = rec
        self.lane_acts = lane_acts
        self.t0_ns = t0_ns


class _HostFetch:
    """One in-progress host-tier page fetch (ISSUE 17): the queue-head
    request's prompt misses the device prefix cache but hits the
    host-RAM tier, so its pages are being pulled back through
    ``kv_import`` chunk by chunk — interleaved between decode steps,
    ``is_ready()``-polled, never blocking a decode dispatch.  While the
    fetch runs the request lives HERE (not in ``waiting``, not in a
    slot); completion requeues it at the queue FRONT, where the next
    admission's prefix lookup finds every fetched page device-resident
    and admits in one 1-token chunk.  ``_submit_t`` stays in place
    throughout — TTFT includes the fetch, honestly."""

    __slots__ = ("req", "plan", "pos", "staged", "staged_digests",
                 "pages_in", "chunk_idx", "span", "t0")

    def __init__(self, req, plan, span, t0):
        self.req = req
        self.plan = plan              # [(page_index, digest)] to pull
        self.pos = 0                  # plan entries imported so far
        self.staged = None            # staged device arrays, or None
        self.staged_digests = None    # the digests the staging covers
        self.pages_in = 0             # pages landed (the hits metric)
        self.chunk_idx = 0            # faultpoint/trace chunk counter
        self.span = span              # "kv_tier" request child span
        self.t0 = t0                  # fetch begin, perf_counter


class ContinuousBatchingScheduler:
    # page-pressure evictions per request before the scheduler stops
    # requeueing it and finishes it "cache_full" — bounds wasted
    # recompute and keeps run()'s termination argument trivial
    max_preemptions = 3

    def __init__(self, engine, tracer=None, overlap=None, on_token=None,
                 on_finish=None):
        self.engine = engine
        # -- overlapped host/device decode loop (ISSUE 13) -----------------
        # overlap=True (the default; env escape hatch
        # PADDLE_TPU_SERVE_OVERLAP=0) keeps ONE decode step in flight:
        # each iteration dispatches step t (threading step t-1's sampled
        # tokens on DEVICE — jax dispatch is async) and only then blocks
        # on step t-1's token fetch, so host bookkeeping for step t-1
        # overlaps device compute for step t.  Host-visible effects lag
        # one step; consume reconciles by crediting a lane only when the
        # same request still occupies it (see _Inflight).  Greedy output
        # is BIT-IDENTICAL to the sync loop; seeded temperature>0
        # sampling is reproducible within a mode but not across modes
        # (overshoot steps consume threaded keys).
        import os as _os
        if overlap is None:
            overlap = _os.environ.get("PADDLE_TPU_SERVE_OVERLAP",
                                      "1") != "0"
        self.overlap = bool(overlap)
        self._inflight: Optional[_Inflight] = None
        self._drained_n = 0            # tokens consumed by implicit
                                       # drains (page pressure / cancel)
                                       # since step() last collected
        # host-gap accounting (the bench's A/B line): wall time during
        # which NO decode step was dispatched-and-unconsumed — the only
        # windows where the device can be token-starved by the host.
        # The sync loop pays the whole consume-to-dispatch host window
        # per step; the overlapped loop pays only true pipeline bubbles.
        self.host_gap_seconds = 0.0
        self.decode_steps_total = 0
        self._outstanding = 0          # dispatched, unconsumed steps
        self._last_fetch_ns = None
        self._last_step_end_ns = None
        # streaming hooks (the async front-end): called on the scheduler
        # thread — on_token(rid, [ids...]) per appended run (first
        # tokens included), on_finish(RequestResult) at retirement
        self._on_token = on_token
        self._on_finish = on_finish
        self.waiting: deque = deque()
        self.slots: List[Optional[_ActiveSlot]] = [None] * engine.num_slots
        self.finished: Dict[int, RequestResult] = {}
        self._next_rid = 0
        self._admit_seq = 0
        self._submit_t: Dict[int, float] = {}
        # request-scoped tracing (ISSUE 9): a trace_id minted at submit,
        # a root "request" span, and per-phase child spans.  With tracing
        # disabled (the default) the tracer is the module no-op singleton
        # BY IDENTITY and every call below is an empty method — the
        # PR-6-style acceptance test asserts it.  The decode hot loop
        # additionally short-circuits on `_tron` so the per-slot span
        # bookkeeping costs nothing when off.
        self._tracer = (tracer if tracer is not None
                        else _tracing.default_tracer())
        self._tron = bool(self._tracer.enabled)
        self._trace_ids: Dict[int, int] = {}       # rid -> trace lane
        self._req_spans: Dict[int, object] = {}    # rid -> root span
        self._wait_spans: Dict[int, object] = {}   # rid -> queue/requeue
        # rid -> parked _ActiveSlot (evicted, waiting to resume) and
        # rid -> times evicted; see _preempt()
        self._preempted: Dict[int, _ActiveSlot] = {}
        self._preempt_count: Dict[int, int] = {}
        # metric handles, fetched ONCE: with the registry disabled these
        # are the shared no-op singletons — the per-token hot path then
        # does nothing and allocates nothing (tests/test_observability.py
        # asserts the identity)
        self._m_ttft = _metrics.histogram("serving.ttft_seconds")
        self._m_queue_wait = _metrics.histogram("serving.queue_wait_seconds")
        self._m_tpot = _metrics.histogram("serving.tpot_seconds")
        self._m_decode_step = _metrics.histogram(
            "serving.decode_step_seconds")
        self._m_prefill_chunk = _metrics.histogram(
            "serving.prefill_chunk_seconds")
        self._m_tokens = _metrics.counter("serving.generated_tokens")
        self._m_bucket_hits = _metrics.counter(
            "serving.prefill_bucket_hits", ("bucket",))
        self._m_prefix_hits = _metrics.counter("serving.prefix_hit_pages")
        self._m_preempt = _metrics.counter("serving.preemptions")
        self._m_spec_prop = _metrics.counter(
            "serving.spec_proposed_tokens")
        self._m_spec_acc = _metrics.counter(
            "serving.spec_accepted_tokens")
        self._m_finished = _metrics.counter(
            "serving.finished_requests", ("reason",))
        self._m_occupancy = _metrics.gauge("serving.slot_occupancy")
        self._m_queue_depth = _metrics.gauge("serving.queue_depth")
        # tiered KV host-cache fetches (ISSUE 17): rid -> _HostFetch.
        # The scheduler owns the hit counter (a hit is a page that
        # LANDED) and the fetch histogram; the engine owns the
        # spill/miss/occupancy side.
        self._fetches: Dict[int, _HostFetch] = {}
        self._m_host_hits = _metrics.counter("serving.kv_host_hits")
        self._m_fetch_s = _metrics.histogram(
            "serving.kv_tier_fetch_seconds")
        self._kvt_beacon = _liveness.beacon("serve.kv_tier")
        # liveness beacon, fetched ONCE: disabled (the default) it is
        # the module NOOP_BEACON by identity — the per-iteration guard
        # is then two empty method calls (tests assert the identity)
        self._beacon = _liveness.beacon("serve.scheduler_step")

    # -- intake ------------------------------------------------------------

    def submit(self, req: Request, trace=None) -> int:
        prompt = np.asarray(req.prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("empty prompt")
        cap = self.engine.prompt_cap
        if prompt.size > cap:
            raise ValueError(
                "prompt length %d exceeds the engine's prompt capacity %d"
                % (prompt.size, cap))
        if req.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        # a pre-assigned rid (the router tier mints fleet-unique ids so
        # a stream's rid survives failover to another replica) is
        # honored; local callers keep the auto-assigned counter
        if req.rid is None:
            req = dataclasses.replace(req, prompt=prompt,
                                      rid=self._next_rid)
        else:
            req = dataclasses.replace(req, prompt=prompt)
        self._next_rid = max(self._next_rid, req.rid + 1)
        self._submit_t[req.rid] = time.perf_counter()
        self.waiting.append(req)
        # the trace is born HERE: root "request" span + the initial
        # "queue" child (ended at admission) — unless the caller already
        # minted the lane (``trace=(trace_id, root_span)``: the router
        # owns the request root so the tree survives failover).  No-op
        # identity calls when tracing is disabled.
        if trace is None:
            tid = self._tracer.new_trace()
            root = self._tracer.span(
                "request", trace_id=tid, rid=req.rid,
                prompt_len=int(prompt.size),
                max_new_tokens=int(req.max_new_tokens))
        else:
            tid, root = trace
            if root is None:
                root = _tracing.NOOP_SPAN
        self._trace_ids[req.rid] = tid
        self._req_spans[req.rid] = root
        self._wait_spans[req.rid] = self._tracer.span("queue", parent=root)
        self._m_queue_depth.set(len(self.waiting))
        return req.rid

    # -- slot lifecycle ----------------------------------------------------

    def _finish(self, idx: int, reason: str):
        act = self.slots[idx]
        self.slots[idx] = None
        self.engine.free_slot(idx)     # paged: pages back to the pool
        self._retire(act, reason)

    def _retire(self, act: "_ActiveSlot", reason: str):
        """Result/metric/span bookkeeping of one retiring request —
        slot-list-free, so the disaggregated scheduler's prefill-side
        retirements build the SAME RequestResult (one code path for
        the contract the bench and the front-end consume)."""
        tpot = (act.decode_s / act.decode_steps) if act.decode_steps \
            else 0.0
        # a request evicted before producing any token (cache_full mid-
        # prefill) has no first-token time: its ttft is reported as 0.0
        # and NOT fed to the histogram — a fabricated eviction-time
        # sample would pollute the p50/p99 TTFT the bench reports
        got_first = act.first_tok_t is not None
        ttft = (act.first_tok_t - act.submit_t) if got_first else 0.0
        self.finished[act.req.rid] = RequestResult(
            rid=act.req.rid, tokens=np.asarray(act.generated, np.int32),
            finish_reason=reason, ttft=ttft, tpot=tpot,
            queue_wait=act.queue_wait,
            prefix_hit_tokens=act.prefix_hit_tokens,
            spec_proposed=act.spec_proposed,
            spec_accepted=act.spec_accepted,
            trace_id=self._trace_ids.pop(act.req.rid, 0))
        ws = self._wait_spans.pop(act.req.rid, None)
        if ws is not None:
            ws.end()
        self._req_spans.pop(act.req.rid, _tracing.NOOP_SPAN).end(
            reason=reason, tokens=len(act.generated))
        self._preempt_count.pop(act.req.rid, None)
        self._m_finished.labels(reason=reason).inc()
        if got_first:
            self._m_ttft.observe(ttft)
        if act.decode_steps:
            self._m_tpot.observe(tpot)
        if self._on_finish is not None:
            self._on_finish(self.finished[act.req.rid])

    def _check_finished(self, idx: int, lengths=None):
        """Retire the slot if its latest token ended the request.  The
        cache-full check reads the slot's host-tracked COMMITTED length
        (``act.cache_len`` — what consumed device programs actually
        advanced): no device fetch on the decode hot path, and exact in
        the overlapped loop too, where the engine's dispatch-time mirror
        runs one step ahead of consumed truth.  ``lengths`` is accepted
        for backward compatibility and ignored."""
        act = self.slots[idx]
        req = act.req
        if not act.generated:
            return
        tok = act.generated[-1]
        if req.eos_token_id is not None and tok == int(req.eos_token_id):
            self._finish(idx, "eos")
        elif len(act.generated) >= req.max_new_tokens:
            self._finish(idx, "length")
        elif act.cache_len >= self.engine.max_len:
            # no room for another append — retire rather than overflow
            self._finish(idx, "cache_full")

    # -- refcount-aware eviction (page pool pressure) ----------------------

    def _preempt(self, idx: int):
        """vLLM-style recompute preemption: park the slot's state, free
        its pages, and put the request back at the FRONT of the waiting
        queue.  On re-admission the request re-prefills
        ``prompt + generated`` — greedy continuation is unchanged and
        the recompute mostly prefix-hits the victim's own still-cached
        (refcount-0 but hash-reachable) pages — instead of being
        finished with whatever it had: a victim evicted mid-prefill
        would otherwise silently return an EMPTY token array through
        ``generate()``."""
        act = self.slots[idx]
        rid = act.req.rid
        self.slots[idx] = None
        self.engine.free_slot(idx)     # pages back (shared: refcount--)
        act.prefill_task = None        # chunk state is page-bound: drop
        self.waiting.appendleft(act.req)
        self._submit_t[rid] = act.submit_t
        self._preempted[rid] = act
        # trace: mark the eviction on the request lane and open the
        # "requeue" rework-wait span (ended at re-admission)
        root = self._req_spans.get(rid, _tracing.NOOP_SPAN)
        root.event("preempted", slot=idx, generated=len(act.generated))
        self._wait_spans[rid] = self._tracer.span("requeue", parent=root,
                                                  rework=True)
        self._m_preempt.inc()
        self._m_queue_depth.set(len(self.waiting))

    def _evict_for_pages(self, requester_idx: int) -> bool:
        """Free pages by preempting one slot.  Victim: the occupied
        slot with the MOST unshared pages (what eviction actually
        returns to the pool — a prefix-heavy slot's shared pages only
        drop a refcount), preferring slots other than the requester;
        ties break oldest-admitted-first.  The victim is requeued for
        recompute unless it has already been evicted
        ``max_preemptions`` times (then it finishes "cache_full" — the
        cap bounds wasted recompute and preserves termination).
        Returns False only when the requester itself was the last
        occupant: a sequence the pool cannot hold alone is finished
        "cache_full", never requeued (it would loop forever)."""
        candidates = [i for i, a in enumerate(self.slots)
                      if a is not None and i != requester_idx]
        if not candidates:
            self._finish(requester_idx, "cache_full")
            return False
        victim = max(candidates,
                     key=lambda i: (self.engine.unshared_pages(i),
                                    -self.slots[i].admit_order))
        rid = self.slots[victim].req.rid
        n = self._preempt_count.get(rid, 0) + 1
        self._preempt_count[rid] = n
        if n > self.max_preemptions:
            self._finish(victim, "cache_full")
        else:
            self._preempt(victim)
        return True

    # -- admission ---------------------------------------------------------

    def _begin_paged(self, idx: int, req: Request, ids, engine=None):
        """Start a chunked-prefill admission of ``ids`` into ``idx`` —
        the one place for the prefill_begin call and its prefix-hit
        metric (fresh admissions and preemption resumes both land
        here).  ``engine`` defaults to the decode engine; the
        disaggregated scheduler passes its prefill engine."""
        engine = self.engine if engine is None else engine
        task = engine.prefill_begin(
            idx, ids, temperature=req.temperature,
            top_k=req.top_k, top_p=req.top_p)
        if task.shared_pages:
            self._m_prefix_hits.inc(task.shared_pages)
            self._req_spans.get(req.rid, _tracing.NOOP_SPAN).event(
                "prefix_hit", pages=task.shared_pages,
                tokens=task.shared_tokens)
        return task

    def _admit_paged(self, idx: int, req: Request, engine=None,
                     slots=None):
        """Pop-side bookkeeping for ONE paged admission (fresh or
        preemption resume) into slot ``idx`` of ``slots`` against
        ``engine`` — defaults are the decode engine/slot list; the
        disaggregated scheduler routes admissions to its prefill
        engine through the same path so spans, queue-wait and the
        resume contract cannot drift between roles.  Returns the
        (fresh or resumed) :class:`_ActiveSlot`."""
        engine = self.engine if engine is None else engine
        slots = self.slots if slots is None else slots
        submit_t = self._submit_t.pop(req.rid)
        resumed = self._preempted.pop(req.rid, None)
        order = self._admit_seq
        self._admit_seq += 1
        # close the wait span (initial "queue", or a preemption's
        # "requeue") and mark the admission on the request lane
        ws = self._wait_spans.pop(req.rid, None)
        if ws is not None:
            ws.end()
        root = self._req_spans.get(req.rid, _tracing.NOOP_SPAN)
        root.event("readmitted" if resumed is not None else "admitted",
                   slot=idx)
        if resumed is not None:
            # recompute-resume a preempted request: re-prefill
            # prompt + generated so the next sampled token continues
            # the sequence; timing state (ttft, decode_s) and the
            # token list survive on the parked slot.  queue_wait is
            # NOT re-observed — one histogram sample per request.
            ids = req.prompt
            if resumed.generated:
                ids = np.concatenate(
                    [ids, np.asarray(resumed.generated, np.int32)])
            task = self._begin_paged(idx, req, ids, engine=engine)
            # keep the per-request field consistent with the
            # registry counter: resume hits are cache-served work too
            resumed.prefix_hit_tokens += task.shared_tokens
            resumed.prefill_task = task
            resumed.admit_order = order
            slots[idx] = resumed
            return resumed
        admit_t = time.perf_counter()
        queue_wait = admit_t - submit_t
        self._m_queue_wait.observe(queue_wait)
        task = self._begin_paged(idx, req, req.prompt, engine=engine)
        act = _ActiveSlot(req, submit_t, queue_wait, order,
                          prefill_task=task)
        slots[idx] = act
        return act

    def admit(self) -> int:
        """Fill free slots from the waiting queue (FIFO).  Paged engines
        only BEGIN the prefill here (chunks run in :meth:`step`,
        interleaved with decode); slotted engines run their one-shot
        bucketed prefill.  Returns how many requests were admitted."""
        n = 0
        for idx in range(self.engine.num_slots):
            if self.slots[idx] is not None or not self.waiting:
                continue
            req = self.waiting.popleft()
            # a request whose prompt+budget exceeds max_len is still
            # admissible — generation just ends early with "cache_full"
            if self.engine.paged:
                if (req.rid not in self._preempted
                        and self._begin_host_fetch(req)):
                    # diverted to the host-tier fetch lane: the slot
                    # stays free this round (a later request may take
                    # it next iteration — accepted FIFO relaxation
                    # while the head's pages stream back in)
                    continue
                self._admit_paged(idx, req)
                n += 1
                continue
            submit_t = self._submit_t.pop(req.rid)
            order = self._admit_seq
            self._admit_seq += 1
            ws = self._wait_spans.pop(req.rid, None)
            if ws is not None:
                ws.end()
            root = self._req_spans.get(req.rid, _tracing.NOOP_SPAN)
            root.event("admitted", slot=idx)
            admit_t = time.perf_counter()
            queue_wait = admit_t - submit_t
            self._m_queue_wait.observe(queue_wait)
            self._m_bucket_hits.labels(
                bucket=self.engine.bucket_for(req.prompt.size)).inc()
            sp = self._tracer.span("prefill", parent=root, slot=idx)
            tok, _logits = self.engine.prefill(
                idx, req.prompt, temperature=req.temperature,
                top_k=req.top_k, top_p=req.top_p)
            sp.end()
            root.event("first_token")
            act = _ActiveSlot(req, submit_t, queue_wait, order)
            act.cache_len = int(req.prompt.size)
            act.first_token(tok, time.perf_counter())
            self.slots[idx] = act
            self._notify_tokens(req.rid, act.generated[-1:])
            self._check_finished(idx)
            n += 1
        if n:
            self._m_queue_depth.set(len(self.waiting))
            self._m_occupancy.set(
                sum(a is not None for a in self.slots))
        return n

    # -- tiered KV host-cache fetch (ISSUE 17) -----------------------------
    # The disagg handoff discipline, pointed at a tier instead of a
    # second engine: one phase per fetch per iteration (stage, then
    # ready-poll, then import+adopt), interleaved between decode steps
    # so a fetch in flight never blocks a decode dispatch.

    def _begin_host_fetch(self, req) -> bool:
        """Divert the popped queue-head request into the fetch lane when
        the host tier can extend its device-resident prefix coverage.
        Preemption resumes never divert (their recompute ids already
        mostly prefix-hit their own still-cached pages)."""
        plan = self.engine.host_fetch_plan(req.prompt)
        if not plan:
            return False
        root = self._req_spans.get(req.rid, _tracing.NOOP_SPAN)
        span = self._tracer.span("kv_tier", parent=root,
                                 pages=len(plan))
        self._fetches[req.rid] = _HostFetch(req, plan, span,
                                            time.perf_counter())
        self._m_queue_depth.set(len(self.waiting))
        return True

    def _fetch_advance(self):
        """Advance every in-flight host-tier fetch by ONE phase."""
        for rid in list(self._fetches):
            f = self._fetches.get(rid)
            if f is None:
                continue
            with self._kvt_beacon:
                self._fetch_advance_one(rid, f)

    def _fetch_advance_one(self, rid, f):
        eng = self.engine
        if f.staged is None:
            # phase 1: read the tier entries, npz-roundtrip them through
            # the serve.kv_tier chaos site, and dispatch the device
            # placement (async — the poll below is the only wait)
            digs = [d for _i, d in
                    f.plan[f.pos:f.pos + eng.handoff_pages]]
            try:
                f.staged = eng.host_fetch_stage(digs, rid=rid,
                                                chunk=f.chunk_idx)
            except (KeyError,) + _TIER_ERRORS as e:
                self._fetch_abort(rid, f, digs, e)
                return
            f.staged_digests = digs
            f.chunk_idx += 1
            return
        # phase 2: non-blocking readiness poll — a chunk still in
        # flight just waits another iteration, the decode loop keeps
        # dispatching
        if not all(a.is_ready() for a in f.staged if a is not None):
            return
        # phase 3: land the chunk — allocate destination pages, scatter
        # through the ONE compiled kv_import program (donating the pool;
        # device execution order sequences it against any in-flight
        # decode step, the disagg discipline), and adopt each page as
        # free-but-cached content reachable under its digest
        digs = f.staged_digests
        pids = self._fetch_alloc(rid, f, len(digs))
        if pids is None:
            return                 # aborted, or parked for pages
        try:
            eng.import_pages(f.staged, pids)
        except Exception as e:
            # the scatter tore (device dispatch / staging decode): the
            # fresh pages were never adopted, so release them
            # refcount-exactly and degrade this fetch to recompute —
            # same discipline as a phase-1 transport tear
            for pid in pids:
                eng._alloc._release(pid)
            self._fetch_abort(rid, f, digs, e)
            return
        for pid, d in zip(pids, digs):
            eng._alloc.adopt_page(pid, [d])
        eng._m_pool.set(eng._alloc.pages_used())
        f.pages_in += len(digs)
        f.pos += len(digs)
        f.staged = None
        f.staged_digests = None
        if f.pos >= len(f.plan):
            self._fetch_complete(rid, f)

    def _fetch_alloc(self, rid, f, n):
        """Allocate ``n`` destination pages for a fetch chunk.  Pool
        pressure drains the in-flight decode step first (its
        retirements may free pages); still dry, the fetch PARKS —
        partial allocations released refcount-exactly, the chunk
        retried next iteration once decodes retire — rather than
        preempting active slots for a request that is still waiting.
        A pool that cannot hold the chunk even empty aborts the fetch
        to recompute."""
        alloc = self.engine._alloc
        pids = []
        try:
            for _ in range(n):
                pids.append(alloc.alloc())
            return pids
        except PagePoolExhausted as e:
            for pid in pids:
                alloc._release(pid)
            if self._drain_inflight():
                return None        # retry next iteration
            if any(a is not None for a in self.slots):
                return None        # parked: decodes will free pages
            self._fetch_abort(rid, f, f.staged_digests, e)
            return None

    def _fetch_abort(self, rid, f, digests, exc):
        """A fetch chunk tore (transport error at the ``serve.kv_tier``
        site, a vanished LRU entry, or an unservable pool): degrade to
        recompute.  Earlier chunks' adopted pages REMAIN valid cached
        content; the torn chunk's digests are discarded from the tier
        so the retry's plan is strictly smaller — degradation
        terminates structurally.  The request requeues at the queue
        FRONT (the ``serve.handoff`` requeue discipline) and the next
        admission recomputes whatever the tier no longer covers."""
        tier = self.engine._host_tier
        if tier is not None:
            for d in digests or ():
                tier.discard(d)
            self.engine._m_host_bytes.set(tier.bytes_used())
        _flight.record("kv_tier_abort", rid=rid,
                       error=type(exc).__name__, chunk=f.chunk_idx,
                       pages_in=f.pages_in, planned=len(f.plan))
        _flight.crash_dump({"kind": "kv_tier_abort", "rid": rid,
                            "error": repr(exc)})
        f.span.end(aborted=True, error=type(exc).__name__,
                   pages=f.pages_in)
        del self._fetches[rid]
        self.waiting.appendleft(f.req)
        self._m_queue_depth.set(len(self.waiting))

    def _fetch_complete(self, rid, f):
        """Every planned page landed: requeue at the queue FRONT so the
        next admission's prefix lookup finds the whole prompt device-
        resident and admits it as a full prefix hit (one 1-token
        chunk).  ``kv_host_hits`` counts pages that LANDED — the
        honest hit metric."""
        del self._fetches[rid]
        self.waiting.appendleft(f.req)
        self._m_host_hits.inc(f.pages_in)
        self._m_fetch_s.observe(time.perf_counter() - f.t0)
        f.span.end(pages=f.pages_in)
        self._m_queue_depth.set(len(self.waiting))

    def _run_prefill_chunk(self, act, task, engine, evict, sync=True):
        """ONE chunked-prefill advance — span selection (recompute
        chunks after a preemption are REWORK-tagged so the trace
        analyzer attributes them separately from first-admission
        prefill; rid stays in _preempt_count until finish), the
        PagePoolExhausted retry loop, and the chunk-histogram
        accounting.  Shared by the decode-side loop and the
        disaggregated scheduler's prefill side so none of that can
        drift between roles.  ``evict()`` returns True to retry the
        chunk after freeing pages, False to give up (the requester was
        retired, or parks to wait).  Returns ``prefill_step``'s
        ``done``, or None when evict gave up."""
        rid = act.req.rid
        root = self._req_spans.get(rid, _tracing.NOOP_SPAN)
        sp = (self._tracer.span("prefill_chunk", parent=root,
                                pos=task.pos, rework=True)
              if rid in self._preempt_count else
              self._tracer.span("prefill_chunk", parent=root,
                                pos=task.pos))
        t0 = time.perf_counter()
        while True:
            try:
                done = engine.prefill_step(task, sync=sync)
                break
            except PagePoolExhausted:
                if not evict():
                    done = None
                    break
        sp.end()
        if done is not None:
            self._m_prefill_chunk.observe(time.perf_counter() - t0)
        return done

    def prefill_once(self) -> int:
        """Advance every admitting slot by ONE chunk (the chunked-
        prefill interleave).  A chunk that cannot map pages evicts the
        max-unshared victim and retries.  Returns chunks run."""
        n = 0
        for idx, act in enumerate(self.slots):
            if act is None or act.prefill_task is None:
                continue
            task = act.prefill_task
            if not isinstance(task, PrefillTask):
                # a disaggregated handoff parks its (non-chunk) task in
                # the same field so the slot stays un-decodable; the
                # disagg scheduler advances it, not this loop
                continue

            def evict(idx=idx):
                # drain any in-flight decode step FIRST: its
                # retirements may free enough pages, and a preempted
                # victim must never have an undrained step (the
                # parked token list would then lag the device)
                if self._drain_inflight():
                    return True
                return self._evict_for_pages(idx)

            done = self._run_prefill_chunk(act, task, self.engine,
                                           evict)
            if done is None:
                continue
            n += 1
            if done:
                act.prefill_task = None
                act.cache_len = int(task.ids.size)
                root = self._req_spans.get(act.req.rid,
                                           _tracing.NOOP_SPAN)
                if act.first_tok_t is None:
                    root.event("first_token")
                act.first_token(task.first_token, time.perf_counter())
                self._notify_tokens(act.req.rid, act.generated[-1:])
                self._check_finished(idx)
        return n

    # -- decode ------------------------------------------------------------

    def _active_mask(self):
        return [a is not None and a.prefill_task is None
                for a in self.slots]

    def _notify_tokens(self, rid, toks):
        if self._on_token is not None and toks:
            self._on_token(rid, [int(t) for t in toks])

    def _dispatch_decode(self) -> Optional[_Inflight]:
        """Dispatch ONE batched decode (or speculative verify) step over
        the active, fully-prefilled slots — without consuming it.  When
        an unconsumed step is in flight (the overlapped loop), its
        device-side sampled tokens are threaded straight into this
        dispatch (no host round-trip); lanes that joined since (fresh
        prefills) merge their host-known first token in with one eager
        ``where``.  Page pressure drains the in-flight step FIRST (its
        retirements may free pages, and an eviction victim must never
        carry an undrained step), then evicts refcount-aware.  Returns
        the in-flight record, or None when nothing is active."""
        spec_k = int(getattr(self.engine, "spec_k", 0))
        active = self._active_mask()
        if not any(active):
            return None
        if self.engine.paged:
            # pre-step page bookkeeping: every append (k+1 of them per
            # slot for a verify step) needs a mapped private page.  A
            # verify step's advance is data-dependent, so while one is
            # unconsumed the engine mirror lags it — cover BOTH steps'
            # worst case (non-spec steps advance the mirror at dispatch:
            # no slack needed).
            while True:
                slack = (spec_k + 1
                         if spec_k and self._inflight is not None else 0)
                blocked = self.engine.ensure_decode_ready(
                    active, steps=spec_k + 1 + slack)
                if blocked is None:
                    break
                if self._drain_inflight():
                    active = self._active_mask()
                else:
                    self._evict_for_pages(blocked)
                    active = self._active_mask()
                if not any(active):
                    return None
        S = self.engine.num_slots
        tokens = np.zeros((S,), np.int32)
        fresh = np.zeros((S,), bool)
        temps = np.ones((S,), np.float32)
        top_ks = np.zeros((S,), np.int32)
        top_ps = np.ones((S,), np.float32)
        drafts = np.zeros((S, max(spec_k, 1)), np.int32)
        prev = self._inflight
        if prev is not None and prev.rec.consumed:
            prev = None
        for i, act in enumerate(self.slots):
            if not active[i]:
                continue
            if (prev is None or not prev.rec.active[i]
                    or prev.lane_acts[i] is not act):
                # no in-flight step holds this lane's next token: feed
                # the host-known last token (first dispatch, a fresh
                # prefill, or a drained pipeline)
                tokens[i] = act.generated[-1]
                fresh[i] = True
            temps[i] = act.req.temperature
            top_ks[i] = act.req.top_k
            top_ps[i] = act.req.top_p
            if spec_k:
                # self-speculative prompt-lookup draft over the slot's
                # OWN history — host-side, zero model FLOPs; a miss just
                # pads (the verify step then emits one token, like
                # decode).  With a step in flight the history lags by
                # its unconsumed emit — draft quality moves throughput,
                # never correctness (greedy accept is history-free).
                hist = np.concatenate(
                    [act.req.prompt,
                     np.asarray(act.generated, np.int32)])
                drafts[i], _hit = _propose_draft(
                    hist, spec_k, getattr(self.engine, "spec_ngram", 3))
        if prev is not None and not bool(fresh.all()):
            # thread the in-flight step's sampled tokens on DEVICE: for
            # a verify step the last committed token of lane i is
            # emitted[i, counts[i]-1] (an eager gather on futures)
            import jax.numpy as jnp
            if prev.rec.kind == "spec":
                prev_last = jnp.take_along_axis(
                    prev.rec.emitted,
                    jnp.maximum(prev.rec.counts, 1)[:, None] - 1,
                    axis=1)[:, 0]
            else:
                prev_last = prev.rec.tok
            tok_in = (jnp.where(jnp.asarray(fresh), jnp.asarray(tokens),
                                prev_last)
                      if bool(fresh.any()) else prev_last)
        else:
            tok_in = tokens
        # host-gap accounting: with nothing in flight, the whole window
        # since the last fetch starved the device (the sync loop pays
        # this every step; the overlapped loop only on true bubbles)
        t0_ns = time.perf_counter_ns()
        if self._outstanding == 0 and self._last_fetch_ns is not None:
            self.host_gap_seconds += (t0_ns - self._last_fetch_ns) * 1e-9
        if spec_k:
            rec = self.engine.decode_spec_submit(
                tok_in, drafts, active, temps, top_ks, top_ps,
                pages_ready=True)
        else:
            rec = self.engine.decode_submit(tok_in, active, temps,
                                            top_ks, top_ps,
                                            pages_ready=True)
        self._outstanding += 1
        return _Inflight(rec=rec,
                         lane_acts=[self.slots[i] if active[i] else None
                                    for i in range(S)],
                         t0_ns=t0_ns)

    def _consume_inflight(self, infl: _Inflight) -> int:
        """Consume one dispatched step: fetch its sampled tokens (the
        only blocking device sync of an iteration) and run the host-side
        bookkeeping — extend token lists, truncate at EOS/budget, retire
        finished slots, notify streams.  A lane is credited ONLY if the
        same request still occupies it (see :class:`_Inflight`): the
        overshoot token a one-step-stale dispatch computed for a
        since-retired slot is discarded here, and its cache rows are
        reclaimed by the retire's ``free_slot`` — the host length mirror
        stays exact without a rollback program."""
        rec = infl.rec
        spec_k = self.engine.spec_k if rec.kind == "spec" else 0
        if rec.kind == "spec":
            emitted, counts, _logits = self.engine.decode_spec_fetch(rec)
        else:
            next_tok, _logits = self.engine.decode_fetch(rec)
        t1_ns = time.perf_counter_ns()
        self._outstanding -= 1
        self._last_fetch_ns = t1_ns
        self.decode_steps_total += 1
        # the step interval: clipped at the previous consume so
        # consecutive overlapped steps never double-charge wall time
        # (per-request decode_s must sum to drain wall, not 2x it);
        # feeds the histogram AND every involved request's trace span,
        # so trace-report TPOT reproduces the metric exactly
        t0_ns = (infl.t0_ns if self._last_step_end_ns is None
                 else max(infl.t0_ns, self._last_step_end_ns))
        self._last_step_end_ns = t1_ns
        step_s = (t1_ns - t0_ns) * 1e-9
        t1 = t1_ns * 1e-9                      # last_t bookkeeping
        n = 0
        spec_prop = spec_acc = 0               # per-ITERATION counter incs
        for i, act in enumerate(self.slots):
            if (not rec.active[i] or act is None
                    or infl.lane_acts[i] is not act):
                continue               # retired/preempted/cancelled since
            if spec_k:
                raw = int(counts[i])
                emit = [int(t) for t in emitted[i, :raw]]
                act.spec_proposed += spec_k
                act.spec_accepted += len(emit) - 1
                spec_prop += spec_k
                spec_acc += len(emit) - 1
                # mirror the program's finalize: the device committed
                # `raw` rows for this lane (clamped in-program)
                act.cache_len = min(act.cache_len + raw,
                                    self.engine.max_len)
                # truncate at the budget and at EOS — both retire the
                # slot in _check_finished, so a truncated host token
                # list never belongs to a live (still-decoding) slot
                room = act.req.max_new_tokens - len(act.generated)
                emit = emit[:max(room, 0)]
                if act.req.eos_token_id is not None:
                    eos = int(act.req.eos_token_id)
                    if eos in emit:
                        emit = emit[:emit.index(eos) + 1]
            else:
                emit = [int(next_tok[i])]
                act.cache_len = min(act.cache_len + 1,
                                    self.engine.max_len)
            act.generated.extend(emit)
            act.decode_s += step_s
            act.decode_steps += len(emit)   # TPOT = secs per token
            act.last_t = t1
            n += len(emit)
            self._notify_tokens(act.req.rid, emit)
            if self._tron:
                # one span per involved request per iteration, stamped
                # with the shared step interval; `tokens` is the
                # decode-committed count (post-truncation), matching the
                # TPOT accounting exactly
                self._tracer.add_span(
                    "spec_verify" if spec_k else "decode", t0_ns, t1_ns,
                    parent=self._req_spans.get(act.req.rid),
                    tokens=len(emit))
            self._check_finished(i)
        # per-ITERATION metrics (not per token): one histogram observe,
        # one counter inc, one gauge set per batched step
        self._m_decode_step.observe(step_s)
        self._m_tokens.inc(n)
        if spec_prop:
            self._m_spec_prop.inc(spec_prop)
            self._m_spec_acc.inc(spec_acc)
        self._m_occupancy.set(sum(a is not None for a in self.slots))
        return n

    def _drain_inflight(self) -> bool:
        """Consume the in-flight step now, if any (page pressure, a
        cancel, or an external caller needing consistent host state).
        Tokens it credited land in ``self._drained_n`` for step() to
        collect; returns whether a step was drained."""
        infl = self._inflight
        if infl is None or infl.rec.consumed:
            self._inflight = None
            return False
        self._inflight = None
        self._drained_n += self._consume_inflight(infl)
        return True

    def decode_once(self) -> int:
        """One SYNCHRONOUS batched decode (or speculative verify)
        iteration over the active slots: dispatch + immediate consume
        (the ``overlap=False`` loop, and the direct-caller API).  Any
        leftover overlapped step is drained first; returns the number
        of tokens appended to live requests by THIS iteration."""
        self._drain_inflight()
        infl = self._dispatch_decode()
        if infl is None:
            return 0
        return self._consume_inflight(infl)

    def step(self) -> int:
        """One scheduler iteration: admit into free slots, advance every
        admitting slot by one prefill chunk, then one batched decode.
        Overlapped (the default): dispatch step t BEFORE consuming step
        t-1, so the host bookkeeping below overlaps the device's compute
        for step t.  Returns decode tokens produced this iteration
        (prefill first-tokens excluded).

        The whole iteration runs inside the ``serve.scheduler_step``
        liveness beacon's guard: an iteration that wedges (hung
        collective, injected ``Hang`` at the ``serve.step`` site) is a
        stall the watchdog can attribute, while an idle scheduler
        (between ``run()`` drives) is simply unwatched."""
        with self._beacon:
            faultpoint(STEP_SITE, scheduler=self)
            return self._step_inner()

    def _step_inner(self) -> int:
        self._drained_n = 0
        self.admit()
        self._fetch_advance()
        self.prefill_once()
        if self.overlap:
            prev = self._inflight
            nxt = self._dispatch_decode()   # threads prev's device toks
            self._inflight = nxt
            n = 0
            if prev is not None and not prev.rec.consumed:
                n = self._consume_inflight(prev)
        else:
            n = self.decode_once()
        n += self._drained_n
        self._drained_n = 0
        if self._inflight is None and not self.has_work():
            # pipeline fully idle with NO backlog (drain end / between
            # traffic): the window until the next dispatch is ARRIVAL
            # time, not host work — charging it would book a load
            # test's Poisson gaps as host gap.  A drained pipeline
            # with requests still waiting keeps the clock: that window
            # IS host-side serialization (admission + prefill).
            self._last_fetch_ns = None
        # HBM ledger sample at the ITERATION boundary (host-side, after
        # the batched step dispatched — never inside a trace).  One
        # module-global None check while the ledger is disarmed, the
        # default (tests assert the noop path).
        _hbm.maybe_sample("serving.iteration")
        return n

    def has_work(self) -> bool:
        """Anything left to drive: waiting requests, occupied slots, or
        an unconsumed in-flight step.  ``run()`` and the front-end's
        scheduler thread poll this one predicate (the disaggregated
        scheduler extends it with its prefill-side and handoff
        state)."""
        return bool(self.waiting
                    or self._fetches
                    or any(a is not None for a in self.slots)
                    or self._inflight is not None)

    def run(self) -> Dict[int, RequestResult]:
        """Drive to completion; returns {rid: RequestResult}.  Always
        terminates: with work pending, admit() either fills a free slot
        or all slots are occupied; prefill_once() advances every
        admitting prompt by one (finite) chunk — evicting on page
        pressure rather than blocking — and each consumed decode step
        appends a token to every credited request, each of which is
        finite (max_new_tokens / max_len eviction).  Preemption cannot
        spin forever: each request is requeued at most
        ``max_preemptions`` times before it finishes "cache_full", and a
        requester that is the sole occupant is finished, never requeued.
        The overlapped loop adds one tail iteration that only consumes
        the final in-flight step."""
        while self.has_work():
            self.step()
        return self.finished

    def cancel(self, rid: int) -> bool:
        """Abort a request (a disconnected streaming client): frees its
        slot AND its pages immediately (refcount-exact — a shared prefix
        page only drops a refcount), or removes it from the waiting
        queue / the preemption-parking area.  Tokens generated so far
        ride the ``"cancelled"`` :class:`RequestResult`.  Returns False
        when the rid is unknown or already finished.  Must run on the
        scheduler's thread (the front-end routes cancels through its
        command queue)."""
        if rid in self.finished:
            return False
        # an in-flight step may hold a lane for this request: drain
        # first so the consume's identity check stays meaningful and
        # the engine's spec length mirror (advanced at fetch by the
        # DISPATCH mask) never credits a freed lane
        self._drain_inflight()
        if rid in self.finished:       # the drain itself retired it
            return True
        f = self._fetches.pop(rid, None)
        if f is not None:
            # mid-fetch cancel: no device pages are held between phases
            # (alloc+import+adopt are atomic within one phase call — a
            # staged, unimported chunk holds only transfer buffers),
            # and already-adopted pages are valid shared cache content
            # that simply stays.  Fetches never cover preemption
            # resumes, so there are no parked tokens to report.
            f.span.end(aborted=True, error="cancelled",
                       pages=f.pages_in)
            self._submit_t.pop(rid, None)
            res = RequestResult(
                rid=rid, tokens=np.asarray([], np.int32),
                finish_reason="cancelled", ttft=0.0, tpot=0.0,
                trace_id=self._trace_ids.pop(rid, 0))
            self.finished[rid] = res
            ws = self._wait_spans.pop(rid, None)
            if ws is not None:
                ws.end()
            self._req_spans.pop(rid, _tracing.NOOP_SPAN).end(
                reason="cancelled", tokens=0)
            self._m_finished.labels(reason="cancelled").inc()
            if self._on_finish is not None:
                self._on_finish(res)
            return True
        for idx, act in enumerate(self.slots):
            if act is not None and act.req.rid == rid:
                self._finish(idx, "cancelled")
                return True
        for req in list(self.waiting):
            if req.rid == rid:
                self.waiting.remove(req)
                self._m_queue_depth.set(len(self.waiting))
                parked = self._preempted.pop(rid, None)
                self._submit_t.pop(rid, None)
                self._preempt_count.pop(rid, None)
                got_first = (parked is not None
                             and parked.first_tok_t is not None)
                res = RequestResult(
                    rid=rid,
                    tokens=np.asarray(
                        parked.generated if parked is not None else [],
                        np.int32),
                    finish_reason="cancelled",
                    ttft=((parked.first_tok_t - parked.submit_t)
                          if got_first else 0.0),
                    tpot=((parked.decode_s / parked.decode_steps)
                          if parked is not None and parked.decode_steps
                          else 0.0),
                    queue_wait=(parked.queue_wait
                                if parked is not None else 0.0),
                    prefix_hit_tokens=(parked.prefix_hit_tokens
                                       if parked is not None else 0),
                    trace_id=self._trace_ids.pop(rid, 0))
                self.finished[rid] = res
                ws = self._wait_spans.pop(rid, None)
                if ws is not None:
                    ws.end()
                self._req_spans.pop(rid, _tracing.NOOP_SPAN).end(
                    reason="cancelled", tokens=int(res.tokens.size))
                self._m_finished.labels(reason="cancelled").inc()
                if self._on_finish is not None:
                    self._on_finish(res)
                return True
        return False

    # -- replica failover: in-flight state transfer (ISSUE 19) -------------

    def import_requeue(self, state: "RequeueState") -> int:
        """Adopt one transferred request through the recompute-preemption
        resume path (ISSUE 19 failover / decommission).  The request
        lands at the FRONT of the waiting queue (it already waited on
        its old replica); when it has partial generated tokens a parked
        :class:`_ActiveSlot` is reconstructed so re-admission
        re-prefills ``prompt + generated`` exactly like a page-pressure
        eviction resume — the stream continues at the next token,
        mostly prefix-hitting whatever of the prompt this engine's
        cache already covers.  Timing state (submit_t, first_tok_t,
        decode_s) and the trace lane travel with it; ``state.requeues``
        seeds ``_preempt_count`` so failovers and evictions share one
        ``max_preemptions``-style budget.  Must run on the scheduler's
        thread.  Returns the rid."""
        req = state.req
        rid = req.rid
        assert rid is not None, "RequeueState.req must carry its rid"
        self._next_rid = max(self._next_rid, rid + 1)
        self._submit_t[rid] = state.submit_t
        if state.requeues:
            self._preempt_count[rid] = state.requeues
        root = state.root_span
        if root is None:
            root = _tracing.NOOP_SPAN
        self._trace_ids[rid] = state.trace_id
        self._req_spans[rid] = root
        if state.queue_wait is not None:
            # it was admitted before: park a reconstructed slot so the
            # resume path restores tokens + timing and queue_wait is
            # NOT observed a second time
            act = _ActiveSlot(req, state.submit_t, state.queue_wait,
                              admit_order=0)
            act.generated = list(state.generated)
            act.first_tok_t = state.first_tok_t
            act.decode_s = state.decode_s
            act.decode_steps = state.decode_steps
            act.prefix_hit_tokens = state.prefix_hit_tokens
            act.spec_proposed = state.spec_proposed
            act.spec_accepted = state.spec_accepted
            self._preempted[rid] = act
            root.event("failover_import", tokens=len(act.generated))
            self._wait_spans[rid] = self._tracer.span(
                "requeue", parent=root, rework=True)
        else:
            root.event("failover_import", tokens=0)
            self._wait_spans[rid] = self._tracer.span("queue",
                                                      parent=root)
        self.waiting.appendleft(req)
        self._m_queue_depth.set(len(self.waiting))
        return rid

    def export_requeue_state(self) -> List["RequeueState"]:
        """Drain EVERY unfinished request into portable
        :class:`RequeueState` records, leaving this scheduler empty —
        the graceful half of replica failover (decommission / drain);
        the crash half is synthesized router-side from its admission
        records, since a dead replica exports nothing.  Slots and
        fetch-lane requests free their pages refcount-exactly on the
        way out.  Must run on the scheduler's thread."""
        self._drain_inflight()
        out: List[RequeueState] = []

        def _carry(req, act, queue_wait):
            rid = req.rid
            ws = self._wait_spans.pop(rid, None)
            if ws is not None:
                ws.end()
            root = self._req_spans.pop(rid, None)
            if root is not None and root is not _tracing.NOOP_SPAN:
                root.event("exported")
            st = RequeueState(
                req=req,
                submit_t=self._submit_t.pop(rid, 0.0),
                requeues=self._preempt_count.pop(rid, 0),
                trace_id=self._trace_ids.pop(rid, 0),
                root_span=root,
                queue_wait=queue_wait)
            if act is not None:
                st.generated = list(act.generated)
                st.first_tok_t = act.first_tok_t
                st.queue_wait = act.queue_wait
                st.decode_s = act.decode_s
                st.decode_steps = act.decode_steps
                st.prefix_hit_tokens = act.prefix_hit_tokens
                st.spec_proposed = act.spec_proposed
                st.spec_accepted = act.spec_accepted
            out.append(st)

        for idx, act in enumerate(self.slots):
            if act is None:
                continue
            self.slots[idx] = None
            self.engine.free_slot(idx)
            act.prefill_task = None
            _carry(act.req, act, act.queue_wait)
        for rid, f in list(self._fetches.items()):
            del self._fetches[rid]
            f.span.end(aborted=True, error="exported", pages=f.pages_in)
            _carry(f.req, None, None)
        for req in list(self.waiting):
            parked = self._preempted.pop(req.rid, None)
            _carry(req, parked,
                   parked.queue_wait if parked is not None else None)
        self.waiting.clear()
        self._m_queue_depth.set(0)
        self._m_occupancy.set(0)
        return out

    def request_span(self, rid: int):
        """The live root span of an unfinished request (the front-end
        parents its ``http`` span here so the trace tree stays
        connected); the no-op span when tracing is off or the request
        already retired."""
        return self._req_spans.get(rid, _tracing.NOOP_SPAN)
