"""Tiered KV cache: the host-RAM page tier behind the device pool, plus
the cluster-wide prefix index on top of it.

The device page pool is the ONLY prefix cache the engine had until now:
a refcount-0 cached page survives exactly until the free list runs dry
and the allocator reclaims it (PR 7).  When millions of requests share
system-prompt templates, those cached prefix bytes are the dominant
bytes and repeat-prompt TTFT is the headline SLI — so evicted pages
should fall to host RAM, not to recompute.  Three pieces live here:

* :class:`HostPageTier` — a bounded LRU of spilled pages, keyed by the
  PR-7 **chained content digest**, so a host hit implies exact-prefix
  equality (the same guarantee the device hash cache gives; no token
  comparison is ever needed on the readmit path).  Entries are plain
  host numpy copies of one page's K/V rows — int8 codes + scales
  included — exactly what one row of the ``kv_export`` handoff buffer
  holds.  Budget: ``PADDLE_TPU_KV_HOST_BYTES`` (0/unset = tier off).
* :func:`npz_roundtrip` — the shared host-staging transport: write the
  arrays to a temp ``.npz``, fire the chaos site with the file path
  (``TornFile`` truncates it, ``BitFlip`` corrupts it — ``np.load``
  verifies zip CRCs, so both surface as :data:`TRANSPORT_ERRORS`), read
  them back.  ``serving/disagg.py``'s handoff spill path and the host-
  tier fetch path are the SAME function — one transport, two call
  sites, one failure model.
* :class:`ClusterPrefixIndex` — every host periodically publishes its
  resident digest set to the PR-4 distributed store under
  ``paddle_tpu/kv_index/<host>`` (the PR-13 telemetry discipline:
  ``publish_once()`` is the unit the thread loops over, the store
  client's retry policy covers transient resets, and a publish that
  still fails is logged and skipped — the index must never take down
  serving).  Replicas thereby share one logical system-prompt cache
  view, and the future prefix-affinity router gets its routing table
  for free.

Failure discipline: a torn host-tier read (the ``serve.kv_tier``
faultpoint) aborts the fetch, frees the chunk's freshly allocated pages
refcount-exactly, discards the torn tier entries (each retry fetches
strictly fewer pages — termination is structural), and degrades to
recompute through the scheduler's requeue-at-front path.  A fetch can
be slow or lost; it can never corrupt a splice.
"""
from __future__ import annotations

import json
import os
import sys
import tempfile
import threading
import time
import zipfile
from collections import OrderedDict
from typing import Dict, Iterable, List, Optional, Set

import numpy as np

from ..observability import liveness as _liveness
from ..robustness.faultpoints import declare as _declare, faultpoint

__all__ = [
    "KV_TIER_SITE", "TRANSPORT_ERRORS", "INDEX_KEY_PREFIX",
    "npz_roundtrip", "HostPageTier", "ClusterPrefixIndex", "fetch_index",
    "host_bytes_default",
]

#: chaos site on the host-tier fetch transport: fires between the
#: staging write and its read-back with ``ctx['path']`` = the staging
#: file, so TornFile/BitFlip model a torn host-tier read; the scheduler
#: must degrade the fetch to recompute, never splice corrupt rows
KV_TIER_SITE = _declare(
    "serve.kv_tier",
    "fires once per host-tier fetch chunk, between the staged npz write "
    "and its read-back (ctx['path'] = the staging file, so TornFile/"
    "BitFlip model a torn host-tier read)")

#: liveness beacon over one fetch phase (stage or ready-polled import):
#: a wedged device_put or staging read produces a stall dump naming it
_liveness.declare_beacon(
    "serve.kv_tier",
    "one host-tier fetch phase (tier read -> npz roundtrip -> stage, or "
    "the ready-polled import commit), interleaved between decode steps",
    deadline=600.0)

#: transport errors one tier/handoff transfer treats as "the transfer
#: failed — requeue and recompute" (ConnectionResetError is an OSError;
#: EOFError/ValueError/BadZipFile are what reading a torn or bit-flipped
#: staging file raises — np.load verifies zip CRCs)
TRANSPORT_ERRORS = (OSError, EOFError, ValueError, zipfile.BadZipFile)

#: store key prefix; one key per host, newest digest snapshot wins
#: (set() overwrites — the view is "current residency", not a history)
INDEX_KEY_PREFIX = "paddle_tpu/kv_index/"

#: bound on digests one host remembers for publication (oldest dropped
#: past it — the index is advisory; a dropped digest only costs a
#: remote miss, never correctness)
INDEX_MAX_DIGESTS = 65536

_FORMAT = "paddle_tpu-kv-index-v1"

#: handoff-buffer array names, in export/stage order (ks/vs None for an
#: unquantized pool)
BUF_NAMES = ("k", "v", "ks", "vs")


def host_bytes_default() -> int:
    """The env-configured host-tier budget (0 = tier off).  Degrade
    loudly but safely: a typo'd knob disables the tier rather than
    crashing engine construction."""
    raw = os.environ.get("PADDLE_TPU_KV_HOST_BYTES", "").strip()
    if not raw:
        return 0
    try:
        return max(int(float(raw)), 0)
    except ValueError:
        sys.stderr.write("[kv_tier] ignoring unparseable "
                         "PADDLE_TPU_KV_HOST_BYTES=%r\n" % (raw,))
        return 0


def npz_roundtrip(bufs, site, prefix="paddle_tpu_kv_", **ctx):
    """The shared host-staging transport: spill ``bufs`` (the
    ``(k, v, ks, vs)`` handoff-buffer tuple, scale entries None for an
    unquantized pool) to a temp ``.npz``, fire the chaos ``site`` with
    the file path (TornFile truncates it, BitFlip corrupts it — a torn
    transport), read it back.  Raises one of :data:`TRANSPORT_ERRORS`
    when the transfer tore.

    npz cannot round-trip ml_dtypes (a bfloat16 pool saves as void
    ``|V2`` and reloads unusable — which stage_handoff would raise on
    and the abort path would MISREAD as a torn transport): non-numpy-
    native dtypes spill as a byte-exact unsigned view and the read-back
    restores the dtype (``serving/cache.py`` owns the view helpers)."""
    from .cache import np_native_view, np_restore_view
    arrays, dtypes = {}, {}
    for n, a in zip(BUF_NAMES, bufs):
        if a is None:
            continue
        arrays[n], dtypes[n] = np_native_view(a)
    fd, path = tempfile.mkstemp(suffix=".npz", prefix=prefix)
    os.close(fd)
    try:
        np.savez(path, **arrays)
        faultpoint(site, path=path, **ctx)
        with np.load(path) as doc:
            out = []
            for n in BUF_NAMES:
                if n not in doc.files:
                    out.append(None)
                    continue
                out.append(np_restore_view(doc[n], dtypes[n]))
            return tuple(out)
    finally:
        try:
            os.unlink(path)
        except OSError:
            pass


class HostPageTier:
    """Bounded host-RAM store of spilled KV pages, LRU over chained
    content digests.

    One entry is one page's rows as a ``{"k", "v"[, "ks", "vs"]}`` dict
    of host numpy arrays (what one row of the ``kv_export`` buffer
    holds).  A page reachable under several digests (full + partial-tail
    registrations) stores one entry per digest sharing the SAME arrays;
    the byte ledger prices each entry's nbytes, so shared storage is
    over- rather than under-counted — the budget is a ceiling, never a
    leak.  Thread-safe: the allocator spills from whatever thread ran
    ``alloc()``, the scheduler fetches from its loop, and the flight
    recorder reads occupancy from a dump thread."""

    def __init__(self, budget_bytes: Optional[int] = None):
        if budget_bytes is None:
            budget_bytes = host_bytes_default()
        self.budget_bytes = max(int(budget_bytes), 0)
        self._lock = threading.Lock()
        # digest -> {"arrays": {name: np.ndarray}, "nbytes": int}
        self._entries: "OrderedDict" = OrderedDict()
        self._bytes = 0
        self.spilled = 0        # entries admitted (lifetime)
        self.lru_evicted = 0    # entries LRU-evicted over budget
        # called with the list of LRU-evicted digests AFTER _lock is
        # released
        # (the cluster index withdraws it from the TCPStore; store I/O
        # must never run under a tier lock — the TPU601/TPU604
        # discipline: a wedged store would wedge every put/get)
        self.evict_hook = None

    @property
    def enabled(self) -> bool:
        return self.budget_bytes > 0

    @staticmethod
    def _entry_bytes(arrays: Dict[str, np.ndarray]) -> int:
        return sum(int(a.nbytes) for a in arrays.values())

    def put(self, digest, arrays: Dict[str, np.ndarray]) -> bool:
        """Admit one page's rows under ``digest`` (newest end of the
        LRU), evicting oldest entries past the byte budget.  An entry
        bigger than the whole budget is refused — admitting it would
        empty the tier for a page that immediately evicts itself."""
        if not self.enabled:
            return False
        nb = self._entry_bytes(arrays)
        if nb > self.budget_bytes:
            return False
        evicted = []
        with self._lock:
            old = self._entries.pop(digest, None)
            if old is not None:
                self._bytes -= old["nbytes"]
            self._entries[digest] = {"arrays": arrays, "nbytes": nb}
            self._bytes += nb
            self.spilled += 1
            while self._bytes > self.budget_bytes:
                d, ev = self._entries.popitem(last=False)
                self._bytes -= ev["nbytes"]
                self.lru_evicted += 1
                evicted.append(d)
        hook = self.evict_hook
        if hook is not None and evicted:
            try:
                hook(evicted)
            except Exception:
                # best-effort: a broken index must not fail the spill
                # (the interval publisher republishes the truth)
                pass
        return True

    def get(self, digest) -> Optional[Dict[str, np.ndarray]]:
        """The page rows under ``digest`` (an LRU touch), or None."""
        with self._lock:
            ent = self._entries.get(digest)
            if ent is None:
                return None
            self._entries.move_to_end(digest)
            return ent["arrays"]

    def __contains__(self, digest) -> bool:
        with self._lock:
            return digest in self._entries

    def discard(self, digest):
        """Drop ``digest`` (torn-read hygiene: a digest that fed a
        failed fetch must not feed the retry — each abort shrinks the
        next plan, so degradation to recompute terminates)."""
        with self._lock:
            ent = self._entries.pop(digest, None)
            if ent is not None:
                self._bytes -= ent["nbytes"]

    def clear(self):
        """Drop everything (engine ``refresh_state`` on a parameter
        change: spilled rows from old weights must never splice)."""
        with self._lock:
            self._entries.clear()
            self._bytes = 0

    def digests(self) -> List:
        """Snapshot of resident digests, LRU order (oldest first)."""
        with self._lock:
            return list(self._entries)

    def bytes_used(self) -> int:
        with self._lock:
            return self._bytes

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def state(self) -> Dict[str, int]:
        """JSON-ready occupancy row for flight dumps / ledger_state."""
        with self._lock:
            return {"entries": len(self._entries), "bytes": self._bytes,
                    "budget_bytes": self.budget_bytes,
                    "spilled": self.spilled,
                    "lru_evicted": self.lru_evicted}


def _host_id(host: Optional[int]) -> int:
    if host is not None:
        return int(host)
    return int(os.environ.get("PADDLE_TRAINER_ID", "0"))


def _hex(digest) -> str:
    return digest.hex() if isinstance(digest, (bytes, bytearray)) \
        else str(digest)


class ClusterPrefixIndex:
    """Publishes this host's resident chained page digests to the
    distributed store under ``paddle_tpu/kv_index/<host>`` so replicas
    share one logical prefix-cache view.

    The PR-13 ``HostPublisher`` discipline: :meth:`publish_once` is the
    unit the background thread loops over (tests call it directly), the
    store client already wraps every op in the retry policy, and a
    publish that still fails after retries is logged and skipped — the
    index is advisory and must never take down serving.  ``offer()`` is
    cheap and lock-guarded; the engine calls it at prefix registration
    and spill time from whatever thread ran them."""

    def __init__(self, store, host: Optional[int] = None,
                 interval: Optional[float] = None):
        self.store = store
        self.host = _host_id(host)
        if interval is None:
            v = _liveness._env_float("PADDLE_TPU_KV_INDEX_INTERVAL")
            interval = v if v is not None else 10.0
        self.interval = float(interval)
        self.published = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # guards `_digests` (offered from engine/scheduler threads,
        # snapshotted by the publisher thread) and `published`
        self._lock = threading.Lock()
        # insertion-ordered digest set, oldest dropped past the cap
        self._digests: "OrderedDict" = OrderedDict()

    @property
    def key(self) -> str:
        return INDEX_KEY_PREFIX + str(self.host)

    def offer(self, digests: Iterable):
        """Remember digests now resident on this host (device pool or
        host tier) for the next publication."""
        with self._lock:
            for d in digests:
                h = _hex(d)
                self._digests.pop(h, None)
                self._digests[h] = None
                while len(self._digests) > INDEX_MAX_DIGESTS:
                    self._digests.popitem(last=False)

    def withdraw(self, digests: Iterable):
        """Forget digests (tier clear / torn-entry discard)."""
        with self._lock:
            for d in digests:
                self._digests.pop(_hex(d), None)

    def snapshot_digests(self) -> Set[str]:
        """The currently offered hex digest set — what the next
        :meth:`publish_once` would ship.  The router's prefix-affinity
        consultation (ISSUE 19) reads this for in-process replicas
        instead of round-tripping the store; advisory like the
        published view (a stale entry just mis-scores one routing
        decision — admission re-derives exact coverage)."""
        with self._lock:
            return set(self._digests)

    def publish_once(self) -> str:
        with self._lock:
            digests = list(self._digests)
        doc = {"format": _FORMAT, "host": self.host, "pid": os.getpid(),
               "wall_ts": time.time(), "digests": digests}
        self.store.set(self.key, json.dumps(doc, sort_keys=True).encode())
        with self._lock:
            self.published += 1
        return self.key

    def start(self) -> "ClusterPrefixIndex":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="kv-index-publisher", daemon=True)
            self._thread.start()
        return self

    def stop(self, timeout: float = 5.0, final: bool = True):
        """Stop the loop; ``final=True`` publishes one last snapshot so
        peers hold this host's exit-time residency."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout)
            if t.is_alive():
                # wedged inside a store op: publishing now would race it
                # on the same key — skip the final publish, stay bounded
                sys.stderr.write("[kv_tier] index publisher still busy "
                                 "after %.1fs; skipping final publish\n"
                                 % timeout)
                self._thread = None
                return
        self._thread = None
        if final:
            try:
                self.publish_once()
            except Exception as e:
                sys.stderr.write("[kv_tier] final index publish failed: "
                                 "%r\n" % (e,))

    def _run(self):
        while not self._stop.wait(self.interval):
            try:
                self.publish_once()
            except Exception as e:
                # RetryError after the store policy gave up, or a torn
                # store: drop THIS snapshot, keep the loop alive
                sys.stderr.write("[kv_tier] index publish failed "
                                 "(skipping this interval): %r\n" % (e,))


def fetch_index(store, world_size: int) -> Dict[int, Set[str]]:
    """{host: set(hex digests)} for every host that published; hosts
    that never published (or published garbage) are simply absent — the
    index is advisory, a missing host only costs remote misses."""
    out: Dict[int, Set[str]] = {}
    for h in range(int(world_size)):
        try:
            raw = store.get(INDEX_KEY_PREFIX + str(h), wait=False)
            doc = json.loads(raw.decode("utf-8"))
            if doc.get("format") != _FORMAT:
                raise ValueError("unknown kv-index format %r"
                                 % doc.get("format"))
            out[h] = set(doc.get("digests", ()))
        except KeyError:
            continue
        except (ValueError, UnicodeDecodeError):
            continue
    return out
