"""paddle_tpu.optimizer (reference surface: python/paddle/optimizer/)."""
from . import lr
from .optimizer import Optimizer
from .optimizers import (SGD, Adadelta, Adagrad, Adam, Adamax, AdamW, Lamb,
                         Momentum, RMSProp)
