"""Concrete optimizers (reference: python/paddle/optimizer/{sgd,momentum,adam,
adamw,adagrad,adadelta,rmsprop,adamax,lamb}.py; CUDA kernels they wrapped:
paddle/fluid/operators/optimizers/).

Each defines only the pure per-parameter update; fusion across the parameter
list is done by XLA in the jitted update (see optimizer.py).
"""
from __future__ import annotations

import jax.numpy as jnp

from .optimizer import Optimizer


def _wd_grad(self, g, p):
    """Coupled weight decay: g + wd * p (L2Decay / float) or
    g + wd * sign(p) (regularizer.L1Decay)."""
    if self._wd and not self._decoupled_wd:
        if getattr(self, "_wd_mode", "l2") == "l1":
            return g + jnp.asarray(self._wd, g.dtype) * jnp.sign(p)
        return g + jnp.asarray(self._wd, g.dtype) * p
    return g


class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, multi_precision=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)

    def update_one(self, g, p, slots, lr, step):
        g = _wd_grad(self, g, p)
        return p - lr.astype(p.dtype) * g, slots


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 name=None, multi_precision=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def init_one(self, p):
        return {"velocity": jnp.zeros_like(p)}

    def update_one(self, g, p, slots, lr, step):
        g = _wd_grad(self, g, p)
        mu = jnp.asarray(self._momentum, p.dtype)
        v = mu * slots["velocity"] + g
        if self._nesterov:
            upd = g + mu * v
        else:
            upd = v
        return p - lr.astype(p.dtype) * upd, {"velocity": v}


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, name=None,
                 multi_precision=None, amsgrad=False, moment_dtype=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._amsgrad = amsgrad
        # opt-in reduced-precision optimizer state: moments stored in e.g.
        # bf16 (the update math stays f32).  Cuts the AdamW step's HBM
        # traffic from 28 to 20 B/param — the update bucket is bandwidth-
        # bound at 3x its floor (PERF.md).  Default None keeps exact f32
        # state (reference semantics).
        if moment_dtype is not None:
            from ..core.dtype import convert_dtype
            self._moment_dtype = jnp.dtype(convert_dtype(moment_dtype))

    def _mdt(self):
        return self._moment_dtype or jnp.float32

    def init_one(self, p):
        mdt = self._mdt()
        slots = {"moment1": jnp.zeros(p.shape, mdt),
                 "moment2": jnp.zeros(p.shape, mdt)}
        if self._amsgrad:
            slots["moment2_max"] = jnp.zeros(p.shape, mdt)
        return slots

    # NOTE: a fused Pallas AdamW kernel was tried for the mid-size-param
    # update inefficiency (XLA's per-param fusions run ~250 GB/s vs ~700 on
    # big arrays, PERF.md) and measured SLOWER end-to-end on the 345M bench
    # (45.4k vs 52.2k tokens/s — per-pallas_call overhead x ~150 params
    # dominates); the XLA fusion path below stays.

    def update_one(self, g, p, slots, lr, step):
        g = _wd_grad(self, g, p)
        g32 = g.astype(jnp.float32)
        p32 = p.astype(jnp.float32)
        mdt = self._mdt()
        b1 = self._beta1
        b2 = self._beta2
        # math in f32 regardless of the STORAGE dtype of the moments
        m = b1 * slots["moment1"].astype(jnp.float32) + (1 - b1) * g32
        v = b2 * slots["moment2"].astype(jnp.float32) \
            + (1 - b2) * jnp.square(g32)
        t = step.astype(jnp.float32)
        mhat = m / (1 - b1 ** t)
        if self._amsgrad:
            vmax = jnp.maximum(slots["moment2_max"].astype(jnp.float32), v)
            vhat = vmax / (1 - b2 ** t)
            new_slots = {"moment1": m.astype(mdt), "moment2": v.astype(mdt),
                         "moment2_max": vmax.astype(mdt)}
        else:
            vhat = v / (1 - b2 ** t)
            new_slots = {"moment1": m.astype(mdt),
                         "moment2": v.astype(mdt)}
        if self._decoupled_wd and self._wd:
            p32 = p32 * (1.0 - lr * self._wd)
        new_p = p32 - lr * mhat / (jnp.sqrt(vhat) + self._epsilon)
        return new_p.astype(p.dtype), new_slots


class AdamW(Adam):
    _decoupled_wd = True

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=None, name=None,
                 amsgrad=False, moment_dtype=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip, lazy_mode, name,
                         multi_precision, amsgrad, moment_dtype)
        self._apply_decay_param_fun = apply_decay_param_fun
        if self._wd_mode == "l1":
            # AdamW's decoupled update p *= (1 - lr*wd) is L2-SHAPED — an
            # L1Decay coefficient used to be silently applied as L2.  L1
            # has no decoupled analogue here, so route it through the
            # coupled wd*sign(p) gradient term instead (instance override
            # of the class-level _decoupled_wd; _wd_grad then applies it).
            self._decoupled_wd = False


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, name=None,
                 initial_accumulator_value=0.0):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def init_one(self, p):
        return {"moment": jnp.full(p.shape, self._init_acc, jnp.float32)}

    def update_one(self, g, p, slots, lr, step):
        g = _wd_grad(self, g, p).astype(jnp.float32)
        acc = slots["moment"] + jnp.square(g)
        new_p = p.astype(jnp.float32) - lr * g / (jnp.sqrt(acc) + self._epsilon)
        return new_p.astype(p.dtype), {"moment": acc}


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._epsilon = epsilon
        self._rho = rho

    def init_one(self, p):
        return {"avg_squared_grad": jnp.zeros(p.shape, jnp.float32),
                "avg_squared_update": jnp.zeros(p.shape, jnp.float32)}

    def update_one(self, g, p, slots, lr, step):
        g = _wd_grad(self, g, p).astype(jnp.float32)
        rho, eps = self._rho, self._epsilon
        asg = rho * slots["avg_squared_grad"] + (1 - rho) * jnp.square(g)
        upd = g * jnp.sqrt(slots["avg_squared_update"] + eps) / jnp.sqrt(asg + eps)
        asu = rho * slots["avg_squared_update"] + (1 - rho) * jnp.square(upd)
        new_p = p.astype(jnp.float32) - lr * upd
        return new_p.astype(p.dtype), {"avg_squared_grad": asg,
                                       "avg_squared_update": asu}


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._rho = rho
        self._epsilon = epsilon
        self._momentum = momentum
        self._centered = centered

    def init_one(self, p):
        s = {"mean_square": jnp.zeros(p.shape, jnp.float32),
             "momentum": jnp.zeros(p.shape, jnp.float32)}
        if self._centered:
            s["mean_grad"] = jnp.zeros(p.shape, jnp.float32)
        return s

    def update_one(self, g, p, slots, lr, step):
        g = _wd_grad(self, g, p).astype(jnp.float32)
        rho, eps = self._rho, self._epsilon
        ms = rho * slots["mean_square"] + (1 - rho) * jnp.square(g)
        if self._centered:
            mg = rho * slots["mean_grad"] + (1 - rho) * g
            denom = jnp.sqrt(ms - jnp.square(mg) + eps)
            new_slots = {"mean_square": ms, "mean_grad": mg}
        else:
            denom = jnp.sqrt(ms + eps)
            new_slots = {"mean_square": ms}
        mom = self._momentum * slots["momentum"] + lr * g / denom
        new_slots["momentum"] = mom
        new_p = p.astype(jnp.float32) - mom
        return new_p.astype(p.dtype), new_slots


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def init_one(self, p):
        return {"moment": jnp.zeros(p.shape, jnp.float32),
                "inf_norm": jnp.zeros(p.shape, jnp.float32)}

    def update_one(self, g, p, slots, lr, step):
        g = _wd_grad(self, g, p).astype(jnp.float32)
        b1, b2 = self._beta1, self._beta2
        m = b1 * slots["moment"] + (1 - b1) * g
        u = jnp.maximum(b2 * slots["inf_norm"], jnp.abs(g))
        t = step.astype(jnp.float32)
        new_p = (p.astype(jnp.float32)
                 - (lr / (1 - b1 ** t)) * m / (u + self._epsilon))
        return new_p.astype(p.dtype), {"moment": m, "inf_norm": u}


class Lamb(Optimizer):
    # per-parameter trust-ratio norms: packing params into one flat buffer
    # (TrainStep flat_master) would change the math — keep it per-name
    _flat_safe = False

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._lamb_wd = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def init_one(self, p):
        return {"moment1": jnp.zeros(p.shape, jnp.float32),
                "moment2": jnp.zeros(p.shape, jnp.float32)}

    def update_one(self, g, p, slots, lr, step):
        g32 = g.astype(jnp.float32)
        p32 = p.astype(jnp.float32)
        b1, b2 = self._beta1, self._beta2
        m = b1 * slots["moment1"] + (1 - b1) * g32
        v = b2 * slots["moment2"] + (1 - b2) * jnp.square(g32)
        t = step.astype(jnp.float32)
        mhat = m / (1 - b1 ** t)
        vhat = v / (1 - b2 ** t)
        r = mhat / (jnp.sqrt(vhat) + self._epsilon) + self._lamb_wd * p32
        w_norm = jnp.sqrt(jnp.sum(jnp.square(p32)))
        r_norm = jnp.sqrt(jnp.sum(jnp.square(r)))
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        new_p = p32 - lr * trust * r
        return new_p.astype(p.dtype), {"moment1": m, "moment2": v}
