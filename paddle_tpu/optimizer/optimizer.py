"""Optimizer base (reference: python/paddle/optimizer/optimizer.py).

TPU-native split: every optimizer defines a *functional core*
(``init_one``/``update_one`` pure functions over jax arrays, the analogue of
the reference's per-param CUDA kernels in
paddle/fluid/operators/optimizers/), which serves two callers:

* the eager path — ``opt.step()`` reads ``param.grad`` tensors, runs one
  jitted fused update over the whole parameter list (XLA fuses the elementwise
  chains; the analogue of the reference's multi_tensor adam), writes arrays
  back in place;
* the compiled path — ``paddle_tpu.jit.TrainStep`` calls
  ``opt.apply_gradients(params_tree, grads_tree, state, lr)`` inside the
  traced step function.
"""
from __future__ import annotations

import functools
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp

from ..core.tensor import Parameter, Tensor
from .lr import LRScheduler


def _path_name(key_path) -> str:
    """Dotted leaf name from a jax key path for apply_decay_param_fun:
    DictKey exposes .key, GetAttrKey .name, SequenceKey .idx — str() of
    the entry itself would prepend separators ('.w', '[0]') and produce
    mangled names like 'layer1..w'."""
    parts = []
    for k in key_path:
        for attr in ("key", "name", "idx"):
            if hasattr(k, attr):
                parts.append(str(getattr(k, attr)))
                break
        else:
            parts.append(str(k))
    return ".".join(parts)


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, multi_precision=None):
        if parameters is None:
            raise ValueError(
                "parameters=None: pass model.parameters() (static-graph "
                "implicit collection is not supported in the TPU build)")
        self._param_groups = self._build_groups(parameters)
        self._learning_rate = learning_rate
        self.regularization = weight_decay
        self._grad_clip = grad_clip
        self._wd = self._coeff(weight_decay)
        # regularizer.L1Decay objects flip the coupled term to wd*sign(p)
        self._wd_mode = getattr(weight_decay, "_mode", "l2")
        self._accumulators: Dict[int, dict] = {}
        self._step_count = 0
        self._jit_update = None
        self._name = name or type(self).__name__
        # fp32 master weights for low-precision params (reference:
        # optimizer.py _multi_precision + fluid/dygraph/amp/loss_scaler.py:40).
        # None = auto: on whenever a param is bf16/fp16 — without a master
        # copy, lr~1e-4 updates on O2 bf16 weights vanish below the bf16 ULP.
        self._multi_precision = multi_precision
        #: optimizers that support reduced-precision STATE set this (e.g.
        #: Adam(moment_dtype='bfloat16')); None = keep slots f32
        self._moment_dtype = None

    def _wants_master(self, p) -> bool:
        if self._multi_precision is False:
            return False
        return p.dtype in (jnp.bfloat16, jnp.float16)

    def _init_slots(self, p):
        slots = self.init_one(p)
        if self._wants_master(p):
            if self._moment_dtype is None:
                # all slots f32 from step 0: the master-path update returns
                # f32 slots, and a dtype flip between steps would silently
                # retrace the compiled train step and break buffer donation
                slots = {k: v.astype(jnp.float32)
                         if hasattr(v, "dtype") and jnp.issubdtype(
                             v.dtype, jnp.floating) else v
                         for k, v in slots.items()}
            # reduced-precision moments keep init_one's intentional dtypes
            slots["master"] = p.astype(jnp.float32)
        return slots

    def _update_leaf(self, g, p, slots, lr, step, name=None):
        """update_one, routed through the fp32 master copy when present.

        ``name`` enables AdamW's ``apply_decay_param_fun`` (reference
        adamw.py:54): parameters the predicate rejects update with weight
        decay OFF.  The toggle is a host-side flip of self._wd around the
        (trace-time) update_one call, so each leaf bakes its own decay
        constant without widening the update_one subclass API; it assumes
        the standard single-threaded trace — concurrently tracing the
        SAME optimizer object from multiple threads could observe the
        flipped value."""
        fn = getattr(self, "_apply_decay_param_fun", None)
        if fn is not None and name is not None and self._wd \
                and not fn(name):
            saved = self._wd
            self._wd = 0.0
            try:
                return self._update_leaf(g, p, slots, lr, step)
            finally:
                self._wd = saved
        master = slots.get("master") if isinstance(slots, dict) else None
        if master is None:
            return self.update_one(g, p, slots, lr, step)
        inner = {k: v for k, v in slots.items() if k != "master"}
        new_master, new_inner = self.update_one(
            g.astype(jnp.float32), master, inner, lr, step)
        new_inner["master"] = new_master
        return new_master.astype(p.dtype), new_inner

    @staticmethod
    def _coeff(weight_decay):
        if weight_decay is None:
            return 0.0
        if isinstance(weight_decay, (int, float)):
            return float(weight_decay)
        # L2Decay-like object
        return float(getattr(weight_decay, "_coeff",
                             getattr(weight_decay, "coeff", 0.0)))

    def _build_groups(self, parameters):
        params = list(parameters)
        if params and isinstance(params[0], dict):
            return params
        return [{"params": params}]

    @property
    def _parameter_list(self) -> List[Parameter]:
        out = []
        for g in self._param_groups:
            out.extend(g["params"])
        return out

    # -- lr ------------------------------------------------------------------
    def get_lr(self) -> float:
        if isinstance(self._learning_rate, LRScheduler):
            return float(self._learning_rate())
        return float(self._learning_rate)

    def set_lr(self, value):
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError("cannot set_lr when using an LRScheduler")
        self._learning_rate = float(value)

    # -- functional core (override in subclasses) ---------------------------
    def init_one(self, p):
        """Per-parameter slot init: array -> dict of arrays."""
        return {}

    def update_one(self, g, p, slots, lr, step):
        """Pure update: returns (new_p, new_slots)."""
        raise NotImplementedError

    # decoupled weight decay? (AdamW overrides)
    _decoupled_wd = False

    # update is uniform elementwise over parameters, so TrainStep may pack
    # them into one flat buffer (Lamb overrides: per-param trust norms)
    _flat_safe = True

    # -- compiled-path API ---------------------------------------------------
    def init_state(self, params_tree):
        return {
            "slots": jax.tree_util.tree_map(self._init_slots, params_tree),
            "step": jnp.zeros((), jnp.int32),
        }

    def apply_gradients(self, params_tree, grads_tree, state, lr):
        """Pure function for use inside jit: returns (new_params, new_state)."""
        step = state["step"] + 1
        p_leaves, treedef = jax.tree_util.tree_flatten(params_tree)
        # preserve None grads as leaves — bare tree_leaves would drop them
        # and misalign params with grads
        g_leaves = jax.tree_util.tree_flatten(
            grads_tree, is_leaf=lambda x: x is None)[0]
        # grad clip first (global norm across the whole tree)
        g_leaves = self._clip_tree(p_leaves, g_leaves)
        slot_leaves = _flatten_slots(state["slots"], treedef, len(p_leaves))
        names = [None] * len(p_leaves)
        if getattr(self, "_apply_decay_param_fun", None) is not None:
            # leaf names for the per-name decay filter — same traversal
            # order as tree_flatten
            paths = jax.tree_util.tree_flatten_with_path(params_tree)[0]
            names = [_path_name(kp) for kp, _ in paths]
        new_p, new_slots = [], []
        for p, g, s, nm in zip(p_leaves, g_leaves, slot_leaves, names):
            if g is None:
                new_p.append(p)
                new_slots.append(s)
                continue
            np_, ns = self._update_leaf(g, p, s, lr, step, name=nm)
            new_p.append(np_)
            new_slots.append(ns)
        params_out = jax.tree_util.tree_unflatten(treedef, new_p)
        slots_out = _unflatten_slots(new_slots, treedef)
        return params_out, {"slots": slots_out, "step": step}

    def _clip_tree(self, p_leaves, g_leaves, dist_flags=None):
        from ..nn import (ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue)
        clip = self._grad_clip
        if clip is None:
            return g_leaves
        live = [(i, g) for i, g in enumerate(g_leaves) if g is not None]
        if isinstance(clip, ClipGradByGlobalNorm):
            if hasattr(clip, "_total_norm"):
                # mp-aware subclass (fleet.HybridParallelOptimizer): norms of
                # distributed params are psum'd over the model-parallel axis
                total = clip._total_norm(live, dist_flags)
            else:
                total = jnp.sqrt(
                    sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                        for _, g in live))
            coef = clip.clip_norm / jnp.maximum(total, clip.clip_norm)
            out = list(g_leaves)
            for i, g in live:
                out[i] = (g.astype(jnp.float32) * coef).astype(g.dtype)
            return out
        if isinstance(clip, ClipGradByNorm):
            out = list(g_leaves)
            for i, g in live:
                n = jnp.sqrt(jnp.sum(jnp.square(g.astype(jnp.float32))))
                coef = clip.clip_norm / jnp.maximum(n, clip.clip_norm)
                out[i] = (g.astype(jnp.float32) * coef).astype(g.dtype)
            return out
        if isinstance(clip, ClipGradByValue):
            out = list(g_leaves)
            for i, g in live:
                out[i] = jnp.clip(g, clip.min, clip.max)
            return out
        return g_leaves

    # -- eager path ----------------------------------------------------------
    def step(self):
        params = [p for p in self._parameter_list
                  if (not p.stop_gradient) and p.grad is not None]
        if not params:
            self._step_count += 1
            self._post_step()
            return
        key = tuple(id(p) for p in params)
        if self._jit_update is None or self._jit_key != key:
            self._jit_key = key
            for p in params:
                if id(p) not in self._accumulators:
                    self._accumulators[id(p)] = self._init_slots(p._array)

            flags = [bool(getattr(p, "is_distributed", False))
                     for p in params]

            # host-side constants for the per-name decay filter
            # (apply_decay_param_fun); baked into the jitted update
            names = [getattr(p, "name", None) for p in params]

            def _update(p_arrs, g_arrs, slot_list, lr, step):
                g_arrs = self._clip_tree(p_arrs, list(g_arrs),
                                         dist_flags=flags)
                new_p, new_s = [], []
                for p, g, s, nm in zip(p_arrs, g_arrs, slot_list, names):
                    np_, ns = self._update_leaf(g, p, s, lr, step,
                                                name=nm)
                    new_p.append(np_)
                    new_s.append(ns)
                return new_p, new_s

            self._jit_update = jax.jit(_update)
        slot_list = [self._accumulators[id(p)] for p in params]
        self._step_count += 1
        lr = jnp.asarray(self.get_lr(), jnp.float32)
        step = jnp.asarray(self._step_count, jnp.int32)
        new_p, new_s = self._jit_update(
            [p._array for p in params],
            [p.grad._array.astype(p._array.dtype) for p in params],
            slot_list, lr, step)
        for p, arr, s in zip(params, new_p, new_s):
            p._array = arr
            self._accumulators[id(p)] = s
        self._post_step()

    def _post_step(self):
        pass

    def clear_grad(self, set_to_zero=False):
        for p in self._parameter_list:
            p.clear_gradient(set_to_zero)

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        loss.backward()
        self.step()
        self.clear_grad()
        return None, None

    # -- state dict ----------------------------------------------------------
    def state_dict(self):
        sd = {"_step_count": self._step_count}
        for i, p in enumerate(self._parameter_list):
            slots = self._accumulators.get(id(p))
            if slots:
                for k, v in slots.items():
                    sd[f"{p.name or i}@{k}"] = Tensor(v)
        if isinstance(self._learning_rate, LRScheduler):
            sd["LR_Scheduler"] = self._learning_rate.state_dict()
        return sd

    def set_state_dict(self, sd):
        self._step_count = int(sd.get("_step_count", 0))
        if isinstance(self._learning_rate, LRScheduler) and "LR_Scheduler" in sd:
            self._learning_rate.set_state_dict(sd["LR_Scheduler"])
        for i, p in enumerate(self._parameter_list):
            slots = {}
            prefix = f"{p.name or i}@"
            for k, v in sd.items():
                if isinstance(k, str) and k.startswith(prefix):
                    arr = v._array if isinstance(v, Tensor) else jnp.asarray(v)
                    slots[k[len(prefix):]] = arr
            if slots:
                self._accumulators[id(p)] = slots
                self._jit_update = None  # force refresh

    set_dict = set_state_dict


def _flatten_slots(slots_tree, treedef, n):
    """slots_tree mirrors params_tree but with dict-of-arrays leaves."""
    return jax.tree_util.tree_flatten(
        slots_tree, is_leaf=lambda x: isinstance(x, dict) and
        all(not isinstance(v, dict) for v in x.values()))[0][:n]


def _unflatten_slots(slot_leaves, treedef):
    return jax.tree_util.tree_unflatten(treedef, slot_leaves)
