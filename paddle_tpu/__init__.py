"""paddle_tpu — a TPU-native deep-learning framework.

Brand-new design (JAX/XLA/Pallas/pjit idiomatic) with the capability surface
of the PaddlePaddle reference snapshot (see SURVEY.md).  Eager Tensor/Layer
ergonomics over jax arrays with a tape autograd; compiled (`jit`) training
steps, pjit/GSPMD + shard_map parallelism, Pallas kernels for the hot ops.
"""
from __future__ import annotations

__version__ = "0.1.0"

import jax as _jax

# paddle semantics need real int64 (labels, indices). float defaults stay
# f32 via our own dtype conversion in core.tensor._to_array.
_jax.config.update("jax_enable_x64", True)

from .core import (Generator, Parameter, Tensor, enable_grad,
                   get_rng_state, grad, is_grad_enabled, no_grad, seed,
                   set_grad_enabled, set_rng_state, to_tensor)
from .core.dtype import (bfloat16, bool_, complex64, complex128, float16,
                         float32, float64, get_default_dtype, int8, int16,
                         int32, int64, set_default_dtype, uint8)
from .core.tensor import is_tensor

from . import ops
from .ops import *  # noqa: F401,F403 — the paddle.* tensor-op surface
from .ops import random_ops as _random_ops
from .ops.random_ops import (bernoulli, multinomial, normal, rand, randint,
                             randn, randperm, standard_normal, uniform)

bool = bool_  # paddle.bool


def is_grad_enabled_():
    return is_grad_enabled()


# Subpackages (imported lazily enough to avoid cycles: nn imports ops only)
from . import nn            # noqa: E402
from . import optimizer     # noqa: E402
from . import autograd      # noqa: E402
from . import amp           # noqa: E402
from . import io            # noqa: E402
from . import jit           # noqa: E402
from . import static        # noqa: E402
from . import distributed   # noqa: E402
from . import vision        # noqa: E402
from . import metric        # noqa: E402
from . import distribution  # noqa: E402
from . import device        # noqa: E402
from . import framework     # noqa: E402
from . import utils         # noqa: E402
from . import incubate      # noqa: E402
from . import robustness    # noqa: E402
from . import fft           # noqa: E402
from . import signal        # noqa: E402
from . import linalg        # noqa: E402
from . import regularizer   # noqa: E402
from . import callbacks     # noqa: E402
from . import hub           # noqa: E402
from . import sysconfig     # noqa: E402
from . import tensor        # noqa: E402
from . import inference     # noqa: E402
from . import reader        # noqa: E402
from . import dataset       # noqa: E402
from . import compat        # noqa: E402
from .batch import batch    # noqa: E402
from . import sparse        # noqa: E402
from . import text          # noqa: E402
from . import onnx          # noqa: E402
from . import profiler      # noqa: E402
from . import hapi          # noqa: E402
from .hapi import Model, flops, summary  # noqa: E402
from .framework import load, save  # noqa: E402
from .utils.flags import get_flags, set_flags  # noqa: E402
from .nn import DataParallel  # noqa: E402
from .device import get_device, set_device  # noqa: E402
from .jit import to_static  # noqa: E402

Layer = nn.Layer
