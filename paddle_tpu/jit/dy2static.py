"""dy2static: AST-level conversion of data-dependent Python control flow.

The reference converts a dygraph forward into a static program through ~15
AST transformers (fluid/dygraph/dygraph_to_static/ast_transformer.py,
ifelse_transformer.py, loop_transformer.py) whose output calls runtime
dispatchers (convert_operators.py: convert_ifelse, convert_while_loop) that
pick the tensor path (cond/while ops) or the plain Python path per call.

TPU-native rendering: the same two-phase design — an ``ast.NodeTransformer``
rewrites ``if``/``while`` statements in the forward source into calls to
:func:`convert_ifelse` / :func:`convert_while`, which dispatch on whether
the predicate is a traced value: under ``jax.jit`` tracing they lower to
``lax.cond`` / ``lax.while_loop``; called eagerly they run plain Python.

Supported rewrites (anything else raises Dy2StaticUnsupportedError at
transform time, and ``to_static`` falls back to trace-only compilation —
data-INdependent control flow needs no rewrite under jax tracing anyway):

* ``if``/``elif``/``else`` whose branches only ASSIGN variables: branch
  bodies become local functions over the assigned names (both-branch merge
  semantics; a variable read after the ``if`` must be bound on every path).
* ``if``/``else`` whose branches both END in ``return``: rewritten to
  ``return convert_ifelse(...)``.
* ``while`` whose body assigns previously-bound names: loop-carried
  variables are every name assigned in the body that is bound before the
  loop.
* ``for i in range(...)`` — lax.fori_loop over a computed trip count when
  any bound is a tensor (step must be concrete); ``for x in tensor`` —
  lax.scan over the leading axis; ``for x in <python iterable>`` keeps
  plain-Python unrolling.  Same carried-variable rules as ``while``;
  tuple targets raise.
  (reference: loop_transformer.py:1, convert_operators.py convert_len /
  convert_while_loop)
* ``break``/``continue``/``return`` inside converted loops — desugared by
  a pre-pass into boolean guard flags threaded through the loop carry
  (reference scheme: break_continue_transformer.py:87 BreakContinue,
  return_transformer.py:136 ReturnTransformer): ``break`` sets a carried
  flag that both guards the remaining body and joins the loop condition;
  ``continue`` sets a per-iteration flag guarding the rest of the body;
  ``return expr`` sets a return flag + value, and the statements after the
  loop move into the else of an ``if <ret-flag>: return <value>``.  Loops
  with interrupts lower to ``while`` (early exit stops compute — a
  fori/scan cannot stop early).  Scope: ``return`` is supported in loops
  at function-body top level whose return expression is computable before
  the loop (the lax carry needs a typed initial value — the reference's
  RETURN_NO_VALUE magic-number trick, rendered statically); bare and
  valued returns cannot mix in one loop.

Transform matrix — reference transformer vs this build (statuses:
SUPPORTED = rewritten to lax control flow; TRACE = not rewritten because
jax tracing already handles it (data-independent, unrolled at trace);
UNSUPPORTED = Dy2StaticUnsupportedError at transform time, to_static
falls back to trace-only compilation and keeps the reason on
``_dy2static_error``):

=============================  ===========  ==============================
reference transformer          status       notes / unsupported shapes
=============================  ===========  ==============================
ifelse_transformer             SUPPORTED    assign-only branches, or both
                                            branches ending in ``return``;
                                            mixed shapes, effect-only
                                            branches, break/continue in a
                                            branch: UNSUPPORTED
loop_transformer (while)       SUPPORTED    carried vars must be bound
                                            before the loop;
                                            ``while/else``: UNSUPPORTED
loop_transformer (for-range)   SUPPORTED    lax.fori_loop when a bound is
                                            traced; step must be concrete
loop_transformer (for-tensor)  SUPPORTED    lax.scan over the leading axis
loop_transformer (for-iter)    TRACE        python iterables unroll at
                                            trace; traced-index indexing
                                            of a python sequence and
                                            tensor-predicated ``break``:
                                            UNSUPPORTED
break_continue_transformer     SUPPORTED    desugared to carried guard
                                            flags; inside a converted
                                            ``if`` branch: UNSUPPORTED
return_transformer             SUPPORTED    one carried return slot at
                                            body top level; bare+valued
                                            mixed returns: UNSUPPORTED
logical_transformer            TRACE        and/or/not on traced bools are
                                            jnp ops already
cast/call/print/assert/        TRACE        no ProgramDesc to protect:
tensor_shape/typehint                       python-level casts, prints and
transformers                                shape reads trace through jax
                                            natively (shape is static)
list/dict transformers         UNSUPPORTED  LoDTensorArray has no TPU
                                            analogue: tensor lists inside
                                            converted control flow must be
                                            stacked arrays (lax carries
                                            are fixed pytrees)
decorator/early_return/        TRACE        handled by python semantics
grad (name_load)                            under tracing
=============================  ===========  ==============================
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap
import types
from typing import Callable

import jax
import jax.numpy as jnp

__all__ = ["convert_ifelse", "convert_while", "convert_range_for",
           "convert_iter_for", "convert_bool", "transform_function",
           "Dy2StaticUnsupportedError"]


class Dy2StaticUnsupportedError(Exception):
    """A control-flow shape the converter does not rewrite."""


# ---------------------------------------------------------------------------
# runtime dispatchers (reference: dygraph_to_static/convert_operators.py)
# ---------------------------------------------------------------------------

class _Undefined:
    """Placeholder for a variable not yet bound at the control-flow site
    (reference: dygraph_to_static UndefinedVar).  Write-only in branches;
    reading it raises naturally."""

    def __repr__(self):
        return "<dy2static UNDEFINED>"


UNDEFINED = _Undefined()


def _local_default(lcls, name):
    """Runtime lookup used by generated code: current local value or the
    UNDEFINED placeholder when the name is not bound yet."""
    return lcls.get(name, UNDEFINED)


def _as_array(x):
    from ..core.tensor import Tensor
    return x._array if isinstance(x, Tensor) else x


def _is_traced(x) -> bool:
    x = _as_array(x)
    return isinstance(x, jax.core.Tracer)


def _is_tensorish(x) -> bool:
    from ..core.tensor import Tensor
    return isinstance(x, Tensor) or isinstance(x, jax.Array) or _is_traced(x)


def convert_bool(pred):
    """Predicate for the rewritten condition: jnp bool scalar when traced."""
    a = _as_array(pred)
    if hasattr(a, "dtype"):
        return jnp.asarray(a).astype(bool).reshape(())
    return bool(pred)  # tpu-lint: disable=TPU101 — untraced fallback, guarded by the hasattr above


def _rewrap(arrs, like):
    """Re-wrap branch operands/results as Tensors where the originals were
    (branch bodies were written against the Tensor API)."""
    from ..core.tensor import Tensor
    out = []
    for a, l in zip(arrs, like):
        if isinstance(l, Tensor) and hasattr(a, "dtype"):
            out.append(Tensor(a))
        else:
            out.append(a)
    return tuple(out)


def _unwrap_all(vals):
    from ..core.tensor import Tensor
    return tuple(v._array if isinstance(v, Tensor) else v for v in vals)


def convert_ifelse(pred, true_fn: Callable, false_fn: Callable, args: tuple):
    """reference parity: convert_operators.py convert_ifelse — tensor pred
    lowers to lax.cond; Python pred runs one branch eagerly."""
    from ..core.tensor import Tensor

    if _is_traced(pred) or any(map(_is_traced, _unwrap_all(args))):
        a = convert_bool(pred)
        # UNDEFINED placeholders (vars first bound inside the branches) are
        # write-only: keep them out of the cond carry, splice back for the
        # branch call
        live = [i for i, v in enumerate(args) if v is not UNDEFINED]
        live_args = tuple(args[i] for i in live)

        def wrap(fn):
            def inner(operands):
                full = list(args)
                for i, v in zip(live, _rewrap(operands, live_args)):
                    full[i] = v
                out = fn(*full)
                return jax.tree_util.tree_map(
                    _as_array, out, is_leaf=lambda l: isinstance(l, Tensor))
            return inner

        out = jax.lax.cond(a, wrap(true_fn), wrap(false_fn),
                           _unwrap_all(live_args))
        return jax.tree_util.tree_map(
            lambda l: Tensor(l) if hasattr(l, "dtype") else l, out)
    if _is_tensorish(pred):
        # concrete tensor outside tracing: plain Python dispatch
        return true_fn(*args) if bool(_as_array(pred)) else false_fn(*args)
    return true_fn(*args) if pred else false_fn(*args)


def convert_while(cond_fn: Callable, body_fn: Callable, args: tuple):
    """reference parity: convert_operators.py convert_while_loop."""
    from ..core.tensor import Tensor

    first = cond_fn(*args)
    if _is_traced(first) or any(map(_is_traced, _unwrap_all(args))):
        if any(v is UNDEFINED for v in args):
            raise Dy2StaticUnsupportedError(
                "a variable assigned inside a converted while loop must be "
                "bound before the loop (lax.while_loop carries need a "
                "defined initial value)")
        def cond(operands):
            return convert_bool(cond_fn(*_rewrap(operands, args)))

        def body(operands):
            out = body_fn(*_rewrap(operands, args))
            out = _unwrap_all(out)
            # keep carry dtypes stable for while_loop typing
            return tuple(
                jnp.asarray(o).astype(jnp.asarray(a).dtype)
                if hasattr(a, "dtype") and hasattr(o, "dtype") else o
                for o, a in zip(out, operands))

        out = jax.lax.while_loop(cond, body, _unwrap_all(args))
        return tuple(Tensor(o) if hasattr(o, "dtype") else o for o in out)
    vals = args
    while bool(_as_array(cond_fn(*vals))):
        vals = body_fn(*vals)
    return vals


def convert_range_for(rng_args: tuple, body_fn: Callable, args: tuple,
                      prior=UNDEFINED):
    """``for i in range(...)`` (reference: loop_transformer.py +
    convert_operators.py convert_len semantics).  A tensor-dependent bound
    lowers to lax.fori_loop over a computed trip count; concrete bounds run
    the plain Python loop.  body_fn(i, *carried) -> carried.

    Returns ``(final_target,) + carried`` — Python leaves the loop
    variable bound to its last value after the loop, so the rewrite
    rebinds it (``prior`` = the pre-loop binding, used when the traced
    trip count is 0; with no prior binding the would-be first index is
    the fallback, where Python would have raised NameError)."""
    from ..core.tensor import Tensor

    vals = tuple(rng_args)
    if len(vals) == 1:
        start, stop, step = 0, vals[0], 1
    elif len(vals) == 2:
        start, stop, step = vals[0], vals[1], 1
    else:
        start, stop, step = vals
    traced = any(map(_is_traced, _unwrap_all((start, stop, step)))) or \
        any(map(_is_traced, _unwrap_all(args)))
    if not traced:
        out = args
        cur = prior
        for i in range(int(_as_array(start)) if _is_tensorish(start)
                       else start,
                       int(_as_array(stop)) if _is_tensorish(stop)
                       else stop,
                       int(_as_array(step)) if _is_tensorish(step)
                       else step):
            cur = i
            out = body_fn(i, *out)
        return (cur,) + tuple(out)
    if _is_traced(_as_array(step)):
        raise Dy2StaticUnsupportedError(
            "a converted `for i in range(...)` needs a CONCRETE step (the "
            "trip-count sign must be known at trace time); only start/stop "
            "may be tensors")
    if any(v is UNDEFINED for v in args):
        raise Dy2StaticUnsupportedError(
            "a variable assigned inside a converted for loop must be bound "
            "before the loop (lax loop carries need a defined initial "
            "value)")
    step_i = int(_as_array(step)) if _is_tensorish(step) else int(step)
    if step_i == 0:
        raise ValueError("range() arg 3 must not be zero")
    start_a = jnp.asarray(_as_array(start), jnp.int32).reshape(())
    stop_a = jnp.asarray(_as_array(stop), jnp.int32).reshape(())
    if step_i > 0:
        n = jnp.maximum(0, (stop_a - start_a + step_i - 1) // step_i)
    else:
        n = jnp.maximum(0, (start_a - stop_a + (-step_i) - 1) // (-step_i))

    arrs = _unwrap_all(args)

    def body(idx, carry):
        i = start_a + jnp.asarray(idx, jnp.int32) * step_i
        out = body_fn(Tensor(i), *_rewrap(carry, args))
        out = _unwrap_all(out)
        # keep carry dtypes stable for fori_loop typing
        return tuple(
            jnp.asarray(o).astype(jnp.asarray(a).dtype)
            if hasattr(a, "dtype") and hasattr(o, "dtype") else o
            for o, a in zip(out, carry))

    out = jax.lax.fori_loop(jnp.int32(0), n.astype(jnp.int32), body, arrs)
    last = start_a + jnp.maximum(n - 1, 0).astype(jnp.int32) * step_i
    if prior is not UNDEFINED and _is_tensorish(prior):
        fallback = jnp.asarray(_as_array(prior)).astype(jnp.int32).reshape(())
    elif prior is not UNDEFINED and isinstance(prior, int):
        fallback = jnp.int32(prior)
    else:
        fallback = start_a
    final = Tensor(jnp.where(n > 0, last, fallback))
    return (final,) + tuple(Tensor(o) if hasattr(o, "dtype") else o
                            for o in out)


def convert_iter_for(xs, body_fn: Callable, args: tuple, prior=UNDEFINED):
    """``for x in <iterable>``: a tensor iterable scans its leading axis
    (lax.scan — the static-shape rendering of the reference's while-based
    tensor iteration); any other iterable runs the plain Python loop
    (which simply unrolls under jax tracing).  Like
    :func:`convert_range_for`, returns ``(final_target,) + carried``."""
    from ..core.tensor import Tensor

    if _is_tensorish(xs):
        if any(v is UNDEFINED for v in args):
            raise Dy2StaticUnsupportedError(
                "a variable assigned inside a converted for loop must be "
                "bound before the loop (lax loop carries need a defined "
                "initial value)")
        xs_a = _as_array(xs)

        def body(carry, x_t):
            out = body_fn(Tensor(x_t), *_rewrap(carry, args))
            out = _unwrap_all(out)
            out = tuple(
                jnp.asarray(o).astype(jnp.asarray(a).dtype)
                if hasattr(a, "dtype") and hasattr(o, "dtype") else o
                for o, a in zip(out, carry))
            return out, None
        carry, _ = jax.lax.scan(body, _unwrap_all(args), xs_a)
        final = Tensor(xs_a[-1]) if xs_a.shape[0] > 0 else prior
        return (final,) + tuple(Tensor(o) if hasattr(o, "dtype") else o
                                for o in carry)
    out = args
    cur = prior
    for x in xs:
        cur = x
        out = body_fn(x, *out)
    return (cur,) + tuple(out)


def convert_logical_not(x):
    """Traced-safe ``not`` for generated guard tests."""
    a = _as_array(x)
    if _is_traced(a) or isinstance(a, jax.Array):
        return jnp.logical_not(jnp.asarray(a).astype(bool))
    return not bool(a)


def convert_logical_or(*xs):
    arrs = [_as_array(x) for x in xs]
    if any(_is_traced(a) or isinstance(a, jax.Array) for a in arrs):
        out = jnp.asarray(False)
        for a in arrs:
            out = jnp.logical_or(out, jnp.asarray(a).astype(bool))
        return out
    return any(bool(a) for a in arrs)


def convert_logical_and(*xs):
    arrs = [_as_array(x) for x in xs]
    if any(_is_traced(a) or isinstance(a, jax.Array) for a in arrs):
        out = jnp.asarray(True)
        for a in arrs:
            out = jnp.logical_and(out, jnp.asarray(a).astype(bool))
        return out
    return all(bool(a) for a in arrs)


def convert_len(xs):
    """len() over tensors (leading axis, static) or Python sequences."""
    if _is_tensorish(xs):
        return int(_as_array(xs).shape[0])
    return len(xs)


def convert_index(xs, i):
    """xs[i] with a possibly-traced integer index."""
    from ..core.tensor import Tensor
    if _is_tensorish(xs):
        a = _as_array(xs)
        idx = _as_array(i)
        return Tensor(jnp.take(a, jnp.asarray(idx, jnp.int32), axis=0))
    if _is_traced(i):
        raise Dy2StaticUnsupportedError(
            "indexing a plain Python sequence with a traced loop index — a "
            "loop over a Python iterable cannot break on a tensor "
            "condition under tracing; convert the iterable to a tensor")
    return xs[int(_as_array(i)) if _is_tensorish(i) else i]


def convert_range_cond(i, stop, step):
    """The `i vs stop` test of a desugared range loop; step must be
    concrete (the comparison direction is its sign)."""
    if _is_traced(_as_array(step)):
        raise Dy2StaticUnsupportedError(
            "a converted `for i in range(...)` with break/continue/return "
            "needs a CONCRETE step")
    step_i = int(_as_array(step)) if _is_tensorish(step) else int(step)
    if step_i == 0:
        raise ValueError("range() arg 3 must not be zero")
    ia, sa = _as_array(i), _as_array(stop)
    if step_i > 0:
        return ia < sa
    return ia > sa


def _prior_or(lcls, name, thunk):
    """Pre-binding for a desugared for-loop target: Python leaves a
    PRIOR binding untouched when the loop runs zero trips, so keep it;
    only fall back to the thunk (range start / first element) when the
    name was never bound — the lax carry needs a typed initial value."""
    v = lcls.get(name, UNDEFINED)
    if v is not UNDEFINED:
        return v
    return _retval_init(thunk)


def _retval_init(thunk):
    """Pre-loop evaluation of a loop-return expression, used to give the
    lax carry a typed initial value; unbound names fall back to UNDEFINED
    (fails later with the bound-before error only if tracing needs it)."""
    try:
        return thunk()
    except (NameError, UnboundLocalError, AttributeError, IndexError,
            TypeError):
        # IndexError/TypeError: the typed pre-binding of a for-iter target
        # indexes element 0 — an EMPTY iterable must not fail here (the
        # loop body never runs; plain Python would leave the name unbound)
        return UNDEFINED


# ---------------------------------------------------------------------------
# AST transformer (reference: ifelse_transformer.py / loop_transformer.py)
# ---------------------------------------------------------------------------

_RT = "__dy2static_rt"


def _walk_same_scope(st):
    """ast.walk that does NOT descend into nested function definitions —
    a generated branch fn's `return`/assignments are local to it, not to
    the statement list being analysed."""
    stack = [st]
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _store_names(stmts) -> set:
    names = set()
    for st in stmts:
        for node in _walk_same_scope(st):
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
                names.add(node.id)
            elif isinstance(node, ast.AugAssign) and isinstance(
                    node.target, ast.Name):
                names.add(node.target.id)
    return names


def _has_stmt(stmts, kinds) -> bool:
    return any(isinstance(node, kinds)
               for st in stmts for node in _walk_same_scope(st))


def _ends_in_return(stmts) -> bool:
    return bool(stmts) and isinstance(stmts[-1], ast.Return)


def _make_branch_fn(name, argnames, body, extra_return, return_names=None):
    """def <name>(a, b, ...): <body>; return (a, b, ...).
    ``return_names`` overrides the returned tuple (loop bodies take the
    iteration variable as their first arg but carry only the rest)."""
    args = ast.arguments(
        posonlyargs=[], args=[ast.arg(arg=a) for a in argnames],
        vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None, defaults=[])
    stmts = list(body)
    if extra_return:
        rets = argnames if return_names is None else return_names
        stmts.append(ast.Return(value=ast.Tuple(
            elts=[ast.Name(id=a, ctx=ast.Load()) for a in rets],
            ctx=ast.Load())))
    return ast.FunctionDef(name=name, args=args, body=stmts,
                           decorator_list=[], returns=None, type_params=[])


def _call_rt(fn_name, *args):
    return ast.Call(
        func=ast.Attribute(value=ast.Name(id=_RT, ctx=ast.Load()),
                           attr=fn_name, ctx=ast.Load()),
        args=list(args), keywords=[])


def _args_tuple(names):
    """(rt._local_default(locals(), 'a'), ...) — tolerates names not yet
    bound at the control-flow site (UNDEFINED placeholder)."""
    return ast.Tuple(
        elts=[_call_rt("_local_default",
                       ast.Call(func=ast.Name(id="locals", ctx=ast.Load()),
                                args=[], keywords=[]),
                       ast.Constant(a)) for a in names],
        ctx=ast.Load())


def _name_load(n):
    return ast.Name(id=n, ctx=ast.Load())


def _assign(name, value):
    return ast.Assign(targets=[ast.Name(id=name, ctx=ast.Store())],
                      value=value)


def _owned_interrupts(body):
    """(has_break, has_continue, has_return) belonging to THIS loop body —
    interrupts inside nested loops belong to those loops; nested function
    defs own their returns."""
    brk = cont = ret = False

    def walk(stmts, nested):
        nonlocal brk, cont, ret
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(st, (ast.While, ast.For)):
                walk(st.body, True)
                walk(st.orelse, True)
            elif isinstance(st, ast.If):
                walk(st.body, nested)
                walk(st.orelse, nested)
            elif isinstance(st, ast.Break):
                brk = brk or not nested
            elif isinstance(st, ast.Continue):
                cont = cont or not nested
            elif isinstance(st, ast.Return):
                ret = ret or not nested

    walk(body, False)
    return brk, cont, ret


class _LoopDesugarCtx:
    """Names + usage record for one loop's interrupt flags (reference:
    break_continue_transformer.py's generated __break_/__continue_ vars)."""

    def __init__(self, uid):
        self.brk = "__jst_brk_%d" % uid
        self.cont = "__jst_cont_%d" % uid
        self.ret = "__jst_ret_%d" % uid
        self.retval = "__jst_retval_%d" % uid
        self.used_brk = self.used_cont = self.used_ret = False
        self.ret_values = []     # Return.value nodes (None for bare)

    def exit_flags(self):
        return [f for f, u in ((self.brk, self.used_brk),
                               (self.ret, self.used_ret)) if u]

    def all_flags(self):
        return [f for f, u in ((self.brk, self.used_brk),
                               (self.cont, self.used_cont),
                               (self.ret, self.used_ret)) if u]

    def valued_ret(self):
        vals = [v is not None for v in self.ret_values]
        if vals and any(vals) and not all(vals):
            raise Dy2StaticUnsupportedError(
                "a converted loop cannot mix bare `return` and "
                "`return <value>` (one carried return slot)")
        return bool(vals) and vals[0]


def _guard_test(ctx):
    flags = [_name_load(f) for f in ctx.all_flags()]
    if len(flags) == 1:
        return _call_rt("convert_logical_not", flags[0])
    return _call_rt("convert_logical_not",
                    _call_rt("convert_logical_or", *flags))


def _rewrite_interrupt_stmt(st, ctx, allow_return):
    """-> (replacement stmts, may_set_flag)."""
    if isinstance(st, ast.Break):
        ctx.used_brk = True
        return [_assign(ctx.brk, ast.Constant(True))], True
    if isinstance(st, ast.Continue):
        ctx.used_cont = True
        return [_assign(ctx.cont, ast.Constant(True))], True
    if isinstance(st, ast.Return):
        if not allow_return:
            raise Dy2StaticUnsupportedError(
                "`return` inside a converted loop is supported only when "
                "the loop sits directly in the function body (the "
                "statements after it become the return-dispatch else "
                "branch); restructure the nested loop")
        ctx.used_ret = True
        ctx.ret_values.append(st.value)
        out = [_assign(ctx.ret, ast.Constant(True))]
        if st.value is not None:
            out.append(_assign(ctx.retval, st.value))
        return out, True
    if isinstance(st, ast.If):
        body, b_set = _rewrite_interrupt_stmts(st.body, ctx, allow_return)
        orelse, o_set = _rewrite_interrupt_stmts(st.orelse, ctx,
                                                 allow_return)
        if b_set or o_set:
            return [ast.If(test=st.test, body=body, orelse=orelse)], True
        return [st], False
    if isinstance(st, (ast.While, ast.For)):
        # nested loop: its own break/continue were desugared by the child
        # visit; a surviving Return inside raises in that visit
        return [st], False
    for node in ast.walk(st):
        if isinstance(node, (ast.Break, ast.Continue, ast.Return)) and \
                not isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            raise Dy2StaticUnsupportedError(
                "break/continue/return inside a `%s` statement in a "
                "converted loop is not supported"
                % type(st).__name__.lower())
    return [st], False


def _rewrite_interrupt_stmts(stmts, ctx, allow_return):
    """Boolean-guard rewrite of one statement list: statements after a
    possible flag-set point are wrapped in `if not <flags>:` (reference
    break_continue_transformer.py:87 scheme)."""
    out = []
    for idx, st in enumerate(stmts):
        new, sets = _rewrite_interrupt_stmt(st, ctx, allow_return)
        out.extend(new)
        if sets and idx < len(stmts) - 1:
            rest, _ = _rewrite_interrupt_stmts(stmts[idx + 1:], ctx,
                                               allow_return)
            out.append(ast.If(test=_guard_test(ctx), body=rest, orelse=[]))
            return out, True
        if sets:
            return out, True
    return out, False


def _flag_inits(ctx):
    pre = [_assign(f, ast.Constant(False)) for f in ctx.all_flags()]
    if ctx.used_ret and ctx.valued_ret():
        # typed initial value for the lax carry: the return expression
        # evaluated BEFORE the loop (the reference's RETURN_NO_VALUE
        # magic-number trick, rendered statically); unbound names fall
        # back to UNDEFINED via _retval_init
        first = next(v for v in ctx.ret_values if v is not None)
        lam = ast.Lambda(
            args=ast.arguments(posonlyargs=[], args=[], vararg=None,
                               kwonlyargs=[], kw_defaults=[], kwarg=None,
                               defaults=[]),
            body=first)
        pre.append(_assign(ctx.retval, _call_rt("_retval_init", lam)))
    return pre


def _augmented_test(test, ctx):
    exits = [_name_load(f) for f in ctx.exit_flags()]
    if not exits:
        return test
    inner = exits[0] if len(exits) == 1 else _call_rt(
        "convert_logical_or", *exits)
    return _call_rt("convert_logical_and", test,
                    _call_rt("convert_logical_not", inner))


def _guarded_tail(ctx, stmts):
    """Append loop-footer statements (cursor increments) guarded so a
    break/return iteration leaves the cursor untouched."""
    if not ctx.exit_flags():
        return stmts
    exits = [_name_load(f) for f in ctx.exit_flags()]
    inner = exits[0] if len(exits) == 1 else _call_rt(
        "convert_logical_or", *exits)
    return [ast.If(test=_call_rt("convert_logical_not", inner),
                   body=stmts, orelse=[])]


def _desugar_while(node, ctx, allow_return):
    if node.orelse:
        raise Dy2StaticUnsupportedError("while/else is not supported")
    body, _ = _rewrite_interrupt_stmts(node.body, ctx, allow_return)
    if ctx.used_cont:
        body = [_assign(ctx.cont, ast.Constant(False))] + body
    loop = ast.While(test=_augmented_test(node.test, ctx), body=body,
                     orelse=[])
    return _flag_inits(ctx), loop


def _desugar_for(node, ctx, uid, allow_return):
    """for-with-interrupts lowers to a while (early exit must stop the
    loop — a fori/scan cannot); the loop target tracks the last iteration
    that RAN, matching Python's post-loop binding."""
    if node.orelse:
        raise Dy2StaticUnsupportedError("for/else is not supported")
    if not isinstance(node.target, ast.Name):
        raise Dy2StaticUnsupportedError(
            "only `for <name> in ...` is convertible (tuple unpacking "
            "targets are not)")
    tgt = node.target.id
    body, _ = _rewrite_interrupt_stmts(node.body, ctx, allow_return)
    cursor = "__jst_it_%d" % uid
    is_range = (isinstance(node.iter, ast.Call)
                and isinstance(node.iter.func, ast.Name)
                and node.iter.func.id == "range"
                and not node.iter.keywords)
    pre = []
    if is_range:
        lo, hi, step = "__jst_lo_%d" % uid, "__jst_hi_%d" % uid, \
            "__jst_st_%d" % uid
        rargs = list(node.iter.args)
        if len(rargs) == 1:
            rargs = [ast.Constant(0), rargs[0], ast.Constant(1)]
        elif len(rargs) == 2:
            rargs = rargs + [ast.Constant(1)]
        lo_lam = ast.Lambda(
            args=ast.arguments(posonlyargs=[], args=[], vararg=None,
                               kwonlyargs=[], kw_defaults=[], kwarg=None,
                               defaults=[]),
            body=_name_load(lo))
        pre += [_assign(lo, rargs[0]), _assign(hi, rargs[1]),
                _assign(step, rargs[2]), _assign(cursor, _name_load(lo)),
                # keep a PRIOR binding of the target for zero-trip loops
                # (Python leaves it untouched); fall back to the range
                # start only when the name was never bound
                _assign(tgt, _call_rt(
                    "_prior_or",
                    ast.Call(func=ast.Name(id="locals", ctx=ast.Load()),
                             args=[], keywords=[]),
                    ast.Constant(tgt), lo_lam))]
        test = _call_rt("convert_range_cond", _name_load(cursor),
                        _name_load(hi), _name_load(step))
        bump = _assign(cursor, ast.BinOp(left=_name_load(cursor),
                                         op=ast.Add(),
                                         right=_name_load(step)))
    else:
        xs, n = "__jst_xs_%d" % uid, "__jst_n_%d" % uid
        zero_lam = ast.Lambda(
            args=ast.arguments(posonlyargs=[], args=[], vararg=None,
                               kwonlyargs=[], kw_defaults=[], kwarg=None,
                               defaults=[]),
            body=_call_rt("convert_index", _name_load(xs),
                          ast.Constant(0)))
        pre += [_assign(xs, node.iter),
                _assign(n, _call_rt("convert_len", _name_load(xs))),
                _assign(cursor, ast.Constant(0)),
                # typed pre-binding of the target for the lax carry;
                # a PRIOR binding survives zero-trip loops (Python
                # semantics)
                _assign(tgt, _call_rt(
                    "_prior_or",
                    ast.Call(func=ast.Name(id="locals", ctx=ast.Load()),
                             args=[], keywords=[]),
                    ast.Constant(tgt), zero_lam))]
        test = ast.Compare(left=_name_load(cursor), ops=[ast.Lt()],
                           comparators=[_name_load(n)])
        bump = _assign(cursor, ast.BinOp(left=_name_load(cursor),
                                         op=ast.Add(),
                                         right=ast.Constant(1)))
    cont_reset = ([_assign(ctx.cont, ast.Constant(False))]
                  if ctx.used_cont else [])
    tgt_bind = ([_assign(tgt, _name_load(cursor))] if is_range else
                [_assign(tgt, _call_rt("convert_index", _name_load(xs),
                                       _name_load(cursor)))])
    full_body = cont_reset + tgt_bind + body + _guarded_tail(ctx, [bump])
    loop = ast.While(test=_augmented_test(test, ctx), body=full_body,
                     orelse=[])
    return _flag_inits(ctx) + pre, loop


def _flatten_stmts(stmts):
    """visit() may return lists (desugared loops) — flatten on EVERY
    exit path (an early return with a nested list dies in compile())."""
    flat = []
    for st in stmts:
        flat.extend(st if isinstance(st, list) else [st])
    return flat


class _InterruptDesugarer(ast.NodeTransformer):
    """Pre-pass: rewrite break/continue/return in loops into guard flags
    (reference: break_continue_transformer.py + return_transformer.py).
    Runs before _ControlFlowTransformer, whose plain while/if converters
    then lower the result."""

    def __init__(self):
        self._uid = 0

    def _next_uid(self):
        self._uid += 1
        return self._uid

    def visit_FunctionDef(self, node):
        node.body = self._process_body(node.body)
        return node

    visit_AsyncFunctionDef = visit_FunctionDef

    def _process_body(self, stmts):
        """Function-body statement list: loops with `return` inside get
        the return-dispatch treatment (the function tail moves into the
        else branch)."""
        out = []
        for idx, st in enumerate(stmts):
            if isinstance(st, (ast.While, ast.For)) \
                    and _owned_interrupts(st.body)[2]:
                self.generic_visit(st)          # nested loops first
                uid = self._next_uid()
                ctx = _LoopDesugarCtx(uid)
                if isinstance(st, ast.While):
                    pre, loop = _desugar_while(st, ctx, allow_return=True)
                else:
                    pre, loop = _desugar_for(st, ctx, uid,
                                             allow_return=True)
                tail = self._process_body(list(stmts[idx + 1:]))
                if not _ends_in_return(tail):
                    tail = tail + [ast.Return(value=ast.Constant(None))]
                ret_stmt = (ast.Return(value=_name_load(ctx.retval))
                            if ctx.valued_ret()
                            else ast.Return(value=ast.Constant(None)))
                out.extend(pre)
                out.append(loop)
                out.append(ast.If(test=_name_load(ctx.ret),
                                  body=[ret_stmt], orelse=tail))
                return _flatten_stmts(out)
            out.append(self.visit(st))
        return _flatten_stmts(out)

    def visit_While(self, node):
        self.generic_visit(node)
        brk, cont, ret = _owned_interrupts(node.body)
        if ret:
            raise Dy2StaticUnsupportedError(
                "`return` inside a converted loop is supported only when "
                "the loop sits directly in the function body")
        if not (brk or cont):
            return node
        ctx = _LoopDesugarCtx(self._next_uid())
        pre, loop = _desugar_while(node, ctx, allow_return=False)
        return pre + [loop]

    def visit_For(self, node):
        self.generic_visit(node)
        brk, cont, ret = _owned_interrupts(node.body)
        if ret:
            raise Dy2StaticUnsupportedError(
                "`return` inside a converted loop is supported only when "
                "the loop sits directly in the function body")
        if not (brk or cont):
            return node
        uid = self._next_uid()
        ctx = _LoopDesugarCtx(uid)
        pre, loop = _desugar_for(node, ctx, uid, allow_return=False)
        return pre + [loop]


class _ControlFlowTransformer(ast.NodeTransformer):
    def __init__(self):
        self._uid = 0

    def _next(self, stem):
        self._uid += 1
        return "__jst_%s_%d" % (stem, self._uid)

    # -- if/elif/else ------------------------------------------------------
    def visit_If(self, node: ast.If):
        self.generic_visit(node)
        body, orelse = node.body, node.orelse
        if _has_stmt(body + orelse, (ast.Break, ast.Continue)):
            raise Dy2StaticUnsupportedError(
                "break/continue inside a converted if branch")
        body_returns = _ends_in_return(body)
        orelse_returns = _ends_in_return(orelse)
        if body_returns != orelse_returns or (
                _has_stmt(body[:-1] if body_returns else body, ast.Return)
                or _has_stmt(orelse[:-1] if orelse_returns else orelse,
                             ast.Return)):
            raise Dy2StaticUnsupportedError(
                "if branches must either both end in `return` or contain "
                "no returns at all (reference return_transformer scope); "
                "restructure or use static.nn.cond directly")

        tname, fname = self._next("true"), self._next("false")
        if body_returns:
            # both branches return: wrap bodies, return the dispatch.
            # Names a branch REASSIGNS become parameters — a zero-arg
            # closure would make them function-local and die with
            # UnboundLocalError on a read-then-write like `x = x + 1`
            assigned = sorted(_store_names(body) | _store_names(orelse))
            tfn = _make_branch_fn(tname, assigned, body, extra_return=False)
            ffn = _make_branch_fn(
                fname, assigned,
                orelse or [ast.Return(value=ast.Constant(None))],
                extra_return=False)
            call = _call_rt("convert_ifelse", node.test,
                            ast.Name(id=tname, ctx=ast.Load()),
                            ast.Name(id=fname, ctx=ast.Load()),
                            _args_tuple(assigned))
            return [tfn, ffn, ast.Return(value=call)]

        assigned = sorted(_store_names(body) | _store_names(orelse))
        if not assigned:
            raise Dy2StaticUnsupportedError(
                "if branch assigns nothing and does not return — side "
                "effects inside converted branches are not supported")
        tfn = _make_branch_fn(tname, assigned, body, extra_return=True)
        ffn = _make_branch_fn(fname, assigned,
                              orelse or [ast.Pass()], extra_return=True)
        call = _call_rt("convert_ifelse", node.test,
                        ast.Name(id=tname, ctx=ast.Load()),
                        ast.Name(id=fname, ctx=ast.Load()),
                        _args_tuple(assigned))
        target = ast.Tuple(elts=[ast.Name(id=a, ctx=ast.Store())
                                 for a in assigned], ctx=ast.Store())
        assign = ast.Assign(targets=[target], value=call)
        return [tfn, ffn, assign]

    # -- while -------------------------------------------------------------
    def visit_While(self, node: ast.While):
        self.generic_visit(node)
        if node.orelse:
            raise Dy2StaticUnsupportedError("while/else is not supported")
        if _has_stmt(node.body, (ast.Break, ast.Continue, ast.Return)):
            raise Dy2StaticUnsupportedError(
                "break/continue/return inside a converted while loop; "
                "restructure or use static.nn.while_loop directly")
        carried = sorted(_store_names(node.body))
        if not carried:
            raise Dy2StaticUnsupportedError(
                "while body assigns no variables — infinite or effect-only "
                "loops are not convertible")
        cname, bname = self._next("cond"), self._next("body")
        cfn = _make_branch_fn(cname, carried,
                              [ast.Return(value=node.test)],
                              extra_return=False)
        bfn = _make_branch_fn(bname, carried, node.body, extra_return=True)
        call = _call_rt("convert_while",
                        ast.Name(id=cname, ctx=ast.Load()),
                        ast.Name(id=bname, ctx=ast.Load()),
                        _args_tuple(carried))
        target = ast.Tuple(elts=[ast.Name(id=a, ctx=ast.Store())
                                 for a in carried], ctx=ast.Store())
        return [cfn, bfn, ast.Assign(targets=[target], value=call)]


    # -- for ---------------------------------------------------------------
    def visit_For(self, node: ast.For):
        """reference: loop_transformer.py — ``for i in range(...)`` lowers
        via convert_range_for (lax.fori_loop), ``for x in tensor`` via
        convert_iter_for (lax.scan); break/continue/return raise loudly."""
        self.generic_visit(node)
        if node.orelse:
            raise Dy2StaticUnsupportedError("for/else is not supported")
        if _has_stmt(node.body, (ast.Break, ast.Continue, ast.Return)):
            raise Dy2StaticUnsupportedError(
                "break/continue/return inside a converted for loop; "
                "restructure as a while with an explicit flag or use "
                "static.nn.while_loop directly")
        if not isinstance(node.target, ast.Name):
            raise Dy2StaticUnsupportedError(
                "only `for <name> in ...` is convertible (tuple unpacking "
                "targets are not)")
        tgt = node.target.id
        carried = sorted(_store_names(node.body) - {tgt})
        if not carried:
            raise Dy2StaticUnsupportedError(
                "for body assigns no variables — effect-only loops are "
                "not convertible")
        bname = self._next("forbody")
        bfn = _make_branch_fn(bname, [tgt] + carried, node.body,
                              extra_return=True, return_names=carried)
        # the pre-loop binding of the target (UNDEFINED if none): the
        # converters return (final_target,) + carried so the loop variable
        # stays bound to its last value after the loop, as in Python
        prior = _call_rt(
            "_local_default",
            ast.Call(func=ast.Name(id="locals", ctx=ast.Load()),
                     args=[], keywords=[]),
            ast.Constant(tgt))
        is_range = (isinstance(node.iter, ast.Call)
                    and isinstance(node.iter.func, ast.Name)
                    and node.iter.func.id == "range"
                    and not node.iter.keywords)
        if is_range:
            call = _call_rt(
                "convert_range_for",
                ast.Tuple(elts=list(node.iter.args), ctx=ast.Load()),
                ast.Name(id=bname, ctx=ast.Load()), _args_tuple(carried),
                prior)
        else:
            call = _call_rt(
                "convert_iter_for", node.iter,
                ast.Name(id=bname, ctx=ast.Load()), _args_tuple(carried),
                prior)
        target = ast.Tuple(elts=[ast.Name(id=a, ctx=ast.Store())
                                 for a in [tgt] + carried],
                           ctx=ast.Store())
        return [bfn, ast.Assign(targets=[target], value=call)]


class _NeedsTransform(ast.NodeVisitor):
    """Cheap pre-scan: only rewrite sources that contain control flow."""
    found = False

    def visit_If(self, node):
        self.found = True

    def visit_While(self, node):
        self.found = True

    def visit_For(self, node):
        self.found = True


def transform_function(fn: Callable):
    """Rewrite ``fn``'s if/while statements through the runtime dispatchers.

    Returns the transformed function, or ``fn`` unchanged when there is
    nothing to rewrite.  Raises Dy2StaticUnsupportedError for control-flow
    shapes outside the supported subset (callers catch it and fall back to
    trace-only to_static).
    """
    try:
        src = textwrap.dedent(inspect.getsource(fn))
    except (OSError, TypeError):
        return fn
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return fn
    scan = _NeedsTransform()
    scan.visit(tree)
    if not scan.found:
        return fn

    func_def = tree.body[0]
    if not isinstance(func_def, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return fn
    func_def.decorator_list = []  # do not re-apply @to_static etc.
    new_name = func_def.name + "__dy2static"
    func_def.name = new_name
    tree = ast.fix_missing_locations(_InterruptDesugarer().visit(tree))
    tree = ast.fix_missing_locations(
        _ControlFlowTransformer().visit(tree))

    # rebuild the defining namespace: module globals + closure cells
    glb = dict(getattr(fn, "__globals__", {}))
    try:
        closure = inspect.getclosurevars(fn)
        glb.update(closure.nonlocals)
    except (TypeError, ValueError):
        pass
    import paddle_tpu.jit.dy2static as rt_mod
    glb[_RT] = rt_mod
    code = compile(tree, filename="<dy2static:%s>" % getattr(
        fn, "__qualname__", "fn"), mode="exec")
    ns = {}
    exec(code, glb, ns)
    out = ns[new_name]
    out = functools.wraps(fn)(out)
    out.__dy2static_transformed__ = True
    return out
