"""dy2static: AST-level conversion of data-dependent Python control flow.

The reference converts a dygraph forward into a static program through ~15
AST transformers (fluid/dygraph/dygraph_to_static/ast_transformer.py,
ifelse_transformer.py, loop_transformer.py) whose output calls runtime
dispatchers (convert_operators.py: convert_ifelse, convert_while_loop) that
pick the tensor path (cond/while ops) or the plain Python path per call.

TPU-native rendering: the same two-phase design — an ``ast.NodeTransformer``
rewrites ``if``/``while`` statements in the forward source into calls to
:func:`convert_ifelse` / :func:`convert_while`, which dispatch on whether
the predicate is a traced value: under ``jax.jit`` tracing they lower to
``lax.cond`` / ``lax.while_loop``; called eagerly they run plain Python.

Supported rewrites (anything else raises Dy2StaticUnsupportedError at
transform time, and ``to_static`` falls back to trace-only compilation —
data-INdependent control flow needs no rewrite under jax tracing anyway):

* ``if``/``elif``/``else`` whose branches only ASSIGN variables: branch
  bodies become local functions over the assigned names (both-branch merge
  semantics; a variable read after the ``if`` must be bound on every path).
* ``if``/``else`` whose branches both END in ``return``: rewritten to
  ``return convert_ifelse(...)``.
* ``while`` whose body assigns previously-bound names: loop-carried
  variables are every name assigned in the body that is bound before the
  loop; ``break``/``continue``/``return`` inside are not supported.
* ``for i in range(...)`` — lax.fori_loop over a computed trip count when
  any bound is a tensor (step must be concrete); ``for x in tensor`` —
  lax.scan over the leading axis; ``for x in <python iterable>`` keeps
  plain-Python unrolling.  Same carried-variable rules as ``while``;
  ``break``/``continue``/``return`` and tuple targets raise.
  (reference: loop_transformer.py:1, convert_operators.py convert_len /
  convert_while_loop)
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap
import types
from typing import Callable

import jax
import jax.numpy as jnp

__all__ = ["convert_ifelse", "convert_while", "convert_range_for",
           "convert_iter_for", "convert_bool", "transform_function",
           "Dy2StaticUnsupportedError"]


class Dy2StaticUnsupportedError(Exception):
    """A control-flow shape the converter does not rewrite."""


# ---------------------------------------------------------------------------
# runtime dispatchers (reference: dygraph_to_static/convert_operators.py)
# ---------------------------------------------------------------------------

class _Undefined:
    """Placeholder for a variable not yet bound at the control-flow site
    (reference: dygraph_to_static UndefinedVar).  Write-only in branches;
    reading it raises naturally."""

    def __repr__(self):
        return "<dy2static UNDEFINED>"


UNDEFINED = _Undefined()


def _local_default(lcls, name):
    """Runtime lookup used by generated code: current local value or the
    UNDEFINED placeholder when the name is not bound yet."""
    return lcls.get(name, UNDEFINED)


def _as_array(x):
    from ..core.tensor import Tensor
    return x._array if isinstance(x, Tensor) else x


def _is_traced(x) -> bool:
    x = _as_array(x)
    return isinstance(x, jax.core.Tracer)


def _is_tensorish(x) -> bool:
    from ..core.tensor import Tensor
    return isinstance(x, Tensor) or isinstance(x, jax.Array) or _is_traced(x)


def convert_bool(pred):
    """Predicate for the rewritten condition: jnp bool scalar when traced."""
    a = _as_array(pred)
    if hasattr(a, "dtype"):
        return jnp.asarray(a).astype(bool).reshape(())
    return bool(pred)


def _rewrap(arrs, like):
    """Re-wrap branch operands/results as Tensors where the originals were
    (branch bodies were written against the Tensor API)."""
    from ..core.tensor import Tensor
    out = []
    for a, l in zip(arrs, like):
        if isinstance(l, Tensor) and hasattr(a, "dtype"):
            out.append(Tensor(a))
        else:
            out.append(a)
    return tuple(out)


def _unwrap_all(vals):
    from ..core.tensor import Tensor
    return tuple(v._array if isinstance(v, Tensor) else v for v in vals)


def convert_ifelse(pred, true_fn: Callable, false_fn: Callable, args: tuple):
    """reference parity: convert_operators.py convert_ifelse — tensor pred
    lowers to lax.cond; Python pred runs one branch eagerly."""
    from ..core.tensor import Tensor

    if _is_traced(pred) or any(map(_is_traced, _unwrap_all(args))):
        a = convert_bool(pred)
        # UNDEFINED placeholders (vars first bound inside the branches) are
        # write-only: keep them out of the cond carry, splice back for the
        # branch call
        live = [i for i, v in enumerate(args) if v is not UNDEFINED]
        live_args = tuple(args[i] for i in live)

        def wrap(fn):
            def inner(operands):
                full = list(args)
                for i, v in zip(live, _rewrap(operands, live_args)):
                    full[i] = v
                out = fn(*full)
                return jax.tree_util.tree_map(
                    _as_array, out, is_leaf=lambda l: isinstance(l, Tensor))
            return inner

        out = jax.lax.cond(a, wrap(true_fn), wrap(false_fn),
                           _unwrap_all(live_args))
        return jax.tree_util.tree_map(
            lambda l: Tensor(l) if hasattr(l, "dtype") else l, out)
    if _is_tensorish(pred):
        # concrete tensor outside tracing: plain Python dispatch
        return true_fn(*args) if bool(_as_array(pred)) else false_fn(*args)
    return true_fn(*args) if pred else false_fn(*args)


def convert_while(cond_fn: Callable, body_fn: Callable, args: tuple):
    """reference parity: convert_operators.py convert_while_loop."""
    from ..core.tensor import Tensor

    first = cond_fn(*args)
    if _is_traced(first) or any(map(_is_traced, _unwrap_all(args))):
        if any(v is UNDEFINED for v in args):
            raise Dy2StaticUnsupportedError(
                "a variable assigned inside a converted while loop must be "
                "bound before the loop (lax.while_loop carries need a "
                "defined initial value)")
        def cond(operands):
            return convert_bool(cond_fn(*_rewrap(operands, args)))

        def body(operands):
            out = body_fn(*_rewrap(operands, args))
            out = _unwrap_all(out)
            # keep carry dtypes stable for while_loop typing
            return tuple(
                jnp.asarray(o).astype(jnp.asarray(a).dtype)
                if hasattr(a, "dtype") and hasattr(o, "dtype") else o
                for o, a in zip(out, operands))

        out = jax.lax.while_loop(cond, body, _unwrap_all(args))
        return tuple(Tensor(o) if hasattr(o, "dtype") else o for o in out)
    vals = args
    while bool(_as_array(cond_fn(*vals))):
        vals = body_fn(*vals)
    return vals


def convert_range_for(rng_args: tuple, body_fn: Callable, args: tuple,
                      prior=UNDEFINED):
    """``for i in range(...)`` (reference: loop_transformer.py +
    convert_operators.py convert_len semantics).  A tensor-dependent bound
    lowers to lax.fori_loop over a computed trip count; concrete bounds run
    the plain Python loop.  body_fn(i, *carried) -> carried.

    Returns ``(final_target,) + carried`` — Python leaves the loop
    variable bound to its last value after the loop, so the rewrite
    rebinds it (``prior`` = the pre-loop binding, used when the traced
    trip count is 0; with no prior binding the would-be first index is
    the fallback, where Python would have raised NameError)."""
    from ..core.tensor import Tensor

    vals = tuple(rng_args)
    if len(vals) == 1:
        start, stop, step = 0, vals[0], 1
    elif len(vals) == 2:
        start, stop, step = vals[0], vals[1], 1
    else:
        start, stop, step = vals
    traced = any(map(_is_traced, _unwrap_all((start, stop, step)))) or \
        any(map(_is_traced, _unwrap_all(args)))
    if not traced:
        out = args
        cur = prior
        for i in range(int(_as_array(start)) if _is_tensorish(start)
                       else start,
                       int(_as_array(stop)) if _is_tensorish(stop)
                       else stop,
                       int(_as_array(step)) if _is_tensorish(step)
                       else step):
            cur = i
            out = body_fn(i, *out)
        return (cur,) + tuple(out)
    if _is_traced(_as_array(step)):
        raise Dy2StaticUnsupportedError(
            "a converted `for i in range(...)` needs a CONCRETE step (the "
            "trip-count sign must be known at trace time); only start/stop "
            "may be tensors")
    if any(v is UNDEFINED for v in args):
        raise Dy2StaticUnsupportedError(
            "a variable assigned inside a converted for loop must be bound "
            "before the loop (lax loop carries need a defined initial "
            "value)")
    step_i = int(_as_array(step)) if _is_tensorish(step) else int(step)
    if step_i == 0:
        raise ValueError("range() arg 3 must not be zero")
    start_a = jnp.asarray(_as_array(start), jnp.int32).reshape(())
    stop_a = jnp.asarray(_as_array(stop), jnp.int32).reshape(())
    if step_i > 0:
        n = jnp.maximum(0, (stop_a - start_a + step_i - 1) // step_i)
    else:
        n = jnp.maximum(0, (start_a - stop_a + (-step_i) - 1) // (-step_i))

    arrs = _unwrap_all(args)

    def body(idx, carry):
        i = start_a + jnp.asarray(idx, jnp.int32) * step_i
        out = body_fn(Tensor(i), *_rewrap(carry, args))
        out = _unwrap_all(out)
        # keep carry dtypes stable for fori_loop typing
        return tuple(
            jnp.asarray(o).astype(jnp.asarray(a).dtype)
            if hasattr(a, "dtype") and hasattr(o, "dtype") else o
            for o, a in zip(out, carry))

    out = jax.lax.fori_loop(jnp.int32(0), n.astype(jnp.int32), body, arrs)
    last = start_a + jnp.maximum(n - 1, 0).astype(jnp.int32) * step_i
    if prior is not UNDEFINED and _is_tensorish(prior):
        fallback = jnp.asarray(_as_array(prior)).astype(jnp.int32).reshape(())
    elif prior is not UNDEFINED and isinstance(prior, int):
        fallback = jnp.int32(prior)
    else:
        fallback = start_a
    final = Tensor(jnp.where(n > 0, last, fallback))
    return (final,) + tuple(Tensor(o) if hasattr(o, "dtype") else o
                            for o in out)


def convert_iter_for(xs, body_fn: Callable, args: tuple, prior=UNDEFINED):
    """``for x in <iterable>``: a tensor iterable scans its leading axis
    (lax.scan — the static-shape rendering of the reference's while-based
    tensor iteration); any other iterable runs the plain Python loop
    (which simply unrolls under jax tracing).  Like
    :func:`convert_range_for`, returns ``(final_target,) + carried``."""
    from ..core.tensor import Tensor

    if _is_tensorish(xs):
        if any(v is UNDEFINED for v in args):
            raise Dy2StaticUnsupportedError(
                "a variable assigned inside a converted for loop must be "
                "bound before the loop (lax loop carries need a defined "
                "initial value)")
        xs_a = _as_array(xs)

        def body(carry, x_t):
            out = body_fn(Tensor(x_t), *_rewrap(carry, args))
            out = _unwrap_all(out)
            out = tuple(
                jnp.asarray(o).astype(jnp.asarray(a).dtype)
                if hasattr(a, "dtype") and hasattr(o, "dtype") else o
                for o, a in zip(out, carry))
            return out, None
        carry, _ = jax.lax.scan(body, _unwrap_all(args), xs_a)
        final = Tensor(xs_a[-1]) if xs_a.shape[0] > 0 else prior
        return (final,) + tuple(Tensor(o) if hasattr(o, "dtype") else o
                                for o in carry)
    out = args
    cur = prior
    for x in xs:
        cur = x
        out = body_fn(x, *out)
    return (cur,) + tuple(out)


# ---------------------------------------------------------------------------
# AST transformer (reference: ifelse_transformer.py / loop_transformer.py)
# ---------------------------------------------------------------------------

_RT = "__dy2static_rt"


def _store_names(stmts) -> set:
    names = set()
    for st in stmts:
        for node in ast.walk(st):
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
                names.add(node.id)
            elif isinstance(node, ast.AugAssign) and isinstance(
                    node.target, ast.Name):
                names.add(node.target.id)
    return names


def _has_stmt(stmts, kinds) -> bool:
    return any(isinstance(node, kinds)
               for st in stmts for node in ast.walk(st))


def _ends_in_return(stmts) -> bool:
    return bool(stmts) and isinstance(stmts[-1], ast.Return)


def _make_branch_fn(name, argnames, body, extra_return, return_names=None):
    """def <name>(a, b, ...): <body>; return (a, b, ...).
    ``return_names`` overrides the returned tuple (loop bodies take the
    iteration variable as their first arg but carry only the rest)."""
    args = ast.arguments(
        posonlyargs=[], args=[ast.arg(arg=a) for a in argnames],
        vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None, defaults=[])
    stmts = list(body)
    if extra_return:
        rets = argnames if return_names is None else return_names
        stmts.append(ast.Return(value=ast.Tuple(
            elts=[ast.Name(id=a, ctx=ast.Load()) for a in rets],
            ctx=ast.Load())))
    return ast.FunctionDef(name=name, args=args, body=stmts,
                           decorator_list=[], returns=None, type_params=[])


def _call_rt(fn_name, *args):
    return ast.Call(
        func=ast.Attribute(value=ast.Name(id=_RT, ctx=ast.Load()),
                           attr=fn_name, ctx=ast.Load()),
        args=list(args), keywords=[])


def _args_tuple(names):
    """(rt._local_default(locals(), 'a'), ...) — tolerates names not yet
    bound at the control-flow site (UNDEFINED placeholder)."""
    return ast.Tuple(
        elts=[_call_rt("_local_default",
                       ast.Call(func=ast.Name(id="locals", ctx=ast.Load()),
                                args=[], keywords=[]),
                       ast.Constant(a)) for a in names],
        ctx=ast.Load())


class _ControlFlowTransformer(ast.NodeTransformer):
    def __init__(self):
        self._uid = 0

    def _next(self, stem):
        self._uid += 1
        return "__jst_%s_%d" % (stem, self._uid)

    # -- if/elif/else ------------------------------------------------------
    def visit_If(self, node: ast.If):
        self.generic_visit(node)
        body, orelse = node.body, node.orelse
        if _has_stmt(body + orelse, (ast.Break, ast.Continue)):
            raise Dy2StaticUnsupportedError(
                "break/continue inside a converted if branch")
        body_returns = _ends_in_return(body)
        orelse_returns = _ends_in_return(orelse)
        if body_returns != orelse_returns or (
                _has_stmt(body[:-1] if body_returns else body, ast.Return)
                or _has_stmt(orelse[:-1] if orelse_returns else orelse,
                             ast.Return)):
            raise Dy2StaticUnsupportedError(
                "if branches must either both end in `return` or contain "
                "no returns at all (reference return_transformer scope); "
                "restructure or use static.nn.cond directly")

        tname, fname = self._next("true"), self._next("false")
        if body_returns:
            # both branches return: wrap bodies, return the dispatch
            tfn = _make_branch_fn(tname, [], body, extra_return=False)
            ffn = _make_branch_fn(
                fname, [], orelse or [ast.Return(value=ast.Constant(None))],
                extra_return=False)
            call = _call_rt("convert_ifelse", node.test,
                            ast.Name(id=tname, ctx=ast.Load()),
                            ast.Name(id=fname, ctx=ast.Load()),
                            ast.Tuple(elts=[], ctx=ast.Load()))
            return [tfn, ffn, ast.Return(value=call)]

        assigned = sorted(_store_names(body) | _store_names(orelse))
        if not assigned:
            raise Dy2StaticUnsupportedError(
                "if branch assigns nothing and does not return — side "
                "effects inside converted branches are not supported")
        tfn = _make_branch_fn(tname, assigned, body, extra_return=True)
        ffn = _make_branch_fn(fname, assigned,
                              orelse or [ast.Pass()], extra_return=True)
        call = _call_rt("convert_ifelse", node.test,
                        ast.Name(id=tname, ctx=ast.Load()),
                        ast.Name(id=fname, ctx=ast.Load()),
                        _args_tuple(assigned))
        target = ast.Tuple(elts=[ast.Name(id=a, ctx=ast.Store())
                                 for a in assigned], ctx=ast.Store())
        assign = ast.Assign(targets=[target], value=call)
        return [tfn, ffn, assign]

    # -- while -------------------------------------------------------------
    def visit_While(self, node: ast.While):
        self.generic_visit(node)
        if node.orelse:
            raise Dy2StaticUnsupportedError("while/else is not supported")
        if _has_stmt(node.body, (ast.Break, ast.Continue, ast.Return)):
            raise Dy2StaticUnsupportedError(
                "break/continue/return inside a converted while loop; "
                "restructure or use static.nn.while_loop directly")
        carried = sorted(_store_names(node.body))
        if not carried:
            raise Dy2StaticUnsupportedError(
                "while body assigns no variables — infinite or effect-only "
                "loops are not convertible")
        cname, bname = self._next("cond"), self._next("body")
        cfn = _make_branch_fn(cname, carried,
                              [ast.Return(value=node.test)],
                              extra_return=False)
        bfn = _make_branch_fn(bname, carried, node.body, extra_return=True)
        call = _call_rt("convert_while",
                        ast.Name(id=cname, ctx=ast.Load()),
                        ast.Name(id=bname, ctx=ast.Load()),
                        _args_tuple(carried))
        target = ast.Tuple(elts=[ast.Name(id=a, ctx=ast.Store())
                                 for a in carried], ctx=ast.Store())
        return [cfn, bfn, ast.Assign(targets=[target], value=call)]


    # -- for ---------------------------------------------------------------
    def visit_For(self, node: ast.For):
        """reference: loop_transformer.py — ``for i in range(...)`` lowers
        via convert_range_for (lax.fori_loop), ``for x in tensor`` via
        convert_iter_for (lax.scan); break/continue/return raise loudly."""
        self.generic_visit(node)
        if node.orelse:
            raise Dy2StaticUnsupportedError("for/else is not supported")
        if _has_stmt(node.body, (ast.Break, ast.Continue, ast.Return)):
            raise Dy2StaticUnsupportedError(
                "break/continue/return inside a converted for loop; "
                "restructure as a while with an explicit flag or use "
                "static.nn.while_loop directly")
        if not isinstance(node.target, ast.Name):
            raise Dy2StaticUnsupportedError(
                "only `for <name> in ...` is convertible (tuple unpacking "
                "targets are not)")
        tgt = node.target.id
        carried = sorted(_store_names(node.body) - {tgt})
        if not carried:
            raise Dy2StaticUnsupportedError(
                "for body assigns no variables — effect-only loops are "
                "not convertible")
        bname = self._next("forbody")
        bfn = _make_branch_fn(bname, [tgt] + carried, node.body,
                              extra_return=True, return_names=carried)
        # the pre-loop binding of the target (UNDEFINED if none): the
        # converters return (final_target,) + carried so the loop variable
        # stays bound to its last value after the loop, as in Python
        prior = _call_rt(
            "_local_default",
            ast.Call(func=ast.Name(id="locals", ctx=ast.Load()),
                     args=[], keywords=[]),
            ast.Constant(tgt))
        is_range = (isinstance(node.iter, ast.Call)
                    and isinstance(node.iter.func, ast.Name)
                    and node.iter.func.id == "range"
                    and not node.iter.keywords)
        if is_range:
            call = _call_rt(
                "convert_range_for",
                ast.Tuple(elts=list(node.iter.args), ctx=ast.Load()),
                ast.Name(id=bname, ctx=ast.Load()), _args_tuple(carried),
                prior)
        else:
            call = _call_rt(
                "convert_iter_for", node.iter,
                ast.Name(id=bname, ctx=ast.Load()), _args_tuple(carried),
                prior)
        target = ast.Tuple(elts=[ast.Name(id=a, ctx=ast.Store())
                                 for a in [tgt] + carried],
                           ctx=ast.Store())
        return [bfn, ast.Assign(targets=[target], value=call)]


class _NeedsTransform(ast.NodeVisitor):
    """Cheap pre-scan: only rewrite sources that contain control flow."""
    found = False

    def visit_If(self, node):
        self.found = True

    def visit_While(self, node):
        self.found = True

    def visit_For(self, node):
        self.found = True


def transform_function(fn: Callable):
    """Rewrite ``fn``'s if/while statements through the runtime dispatchers.

    Returns the transformed function, or ``fn`` unchanged when there is
    nothing to rewrite.  Raises Dy2StaticUnsupportedError for control-flow
    shapes outside the supported subset (callers catch it and fall back to
    trace-only to_static).
    """
    try:
        src = textwrap.dedent(inspect.getsource(fn))
    except (OSError, TypeError):
        return fn
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return fn
    scan = _NeedsTransform()
    scan.visit(tree)
    if not scan.found:
        return fn

    func_def = tree.body[0]
    if not isinstance(func_def, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return fn
    func_def.decorator_list = []  # do not re-apply @to_static etc.
    new_name = func_def.name + "__dy2static"
    func_def.name = new_name
    tree = ast.fix_missing_locations(
        _ControlFlowTransformer().visit(tree))

    # rebuild the defining namespace: module globals + closure cells
    glb = dict(getattr(fn, "__globals__", {}))
    try:
        closure = inspect.getclosurevars(fn)
        glb.update(closure.nonlocals)
    except (TypeError, ValueError):
        pass
    import paddle_tpu.jit.dy2static as rt_mod
    glb[_RT] = rt_mod
    code = compile(tree, filename="<dy2static:%s>" % getattr(
        fn, "__qualname__", "fn"), mode="exec")
    ns = {}
    exec(code, glb, ns)
    out = ns[new_name]
    out = functools.wraps(fn)(out)
    out.__dy2static_transformed__ = True
    return out
