"""paddle_tpu.jit — the compiled training/inference path.

The analogue of the reference's dy2static + executors
(python/paddle/jit/to_static, fluid/executor.py, new_executor/InterpreterCore):
instead of AST transformation to a ProgramDesc interpreted by a C++ runtime,
a Layer's forward is *traced through jax.jit* into one XLA executable.

Three pieces:
* ``functional_call(layer, state, *args)`` — run a Layer against an external
  {name: array} state pytree (params + buffers), returning outputs plus the
  updated buffer state (running BN stats etc.).
* ``to_static(layer_or_fn)`` — paddle.jit.to_static equivalent; returns a
  compiled callable with the same signature.
* ``TrainStep`` — the Executor analogue: one jitted (and optionally pjit-
  sharded) function computing loss, grads and optimizer update.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp

from ..core import random as _rnd
from ..core.grad_mode import no_grad
from ..core.tensor import Tensor
from ..nn.layer.layers import Layer
from ..observability import liveness as _liveness
from ..robustness.faultpoints import declare as _declare, faultpoint

_declare("train.grads",
         "mutate the host-side batch before the compiled step (NaNBatch "
         "here yields NaN loss + NaN grads at a chosen step)")

# liveness beacon over one compiled TrainStep call (dispatch + the
# opt-in grad-norm sync); 600s default covers the first call's XLA
# compile — a wedged collective inside the step stalls it
_liveness.declare_beacon(
    "train.step", "one compiled TrainStep call (forward + backward + "
    "optimizer dispatch)", deadline=600.0)

__all__ = ["functional_call", "to_static", "TrainStep", "not_to_static",
           "save", "load", "TranslatedLayer"]


def _unwrap(x):
    return x._array if isinstance(x, Tensor) else x


def _unwrap_tree(tree):
    return jax.tree_util.tree_map(
        _unwrap, tree, is_leaf=lambda l: isinstance(l, Tensor))


def _wrap_tree(tree):
    return jax.tree_util.tree_map(lambda l: Tensor(l) if hasattr(l, "dtype") else l, tree)


def functional_call(layer: Layer, state: Dict[str, Any], *args,
                    rng=None, **kwargs):
    """Run ``layer`` with parameters/buffers taken from ``state``.

    Returns ``(outputs, new_state)`` where new_state reflects any buffer
    mutation during forward (e.g. batch-norm running stats).  Pure w.r.t.
    (state, args, rng) — safe to trace under jit/grad.
    """
    sd = layer.state_dict()
    old = {k: t._array for k, t in sd.items()}
    try:
        for k, arr in state.items():
            if k in sd:
                sd[k]._array = arr
        ctx = _rnd.key_stream(rng) if rng is not None else _nullcontext()
        with no_grad(), ctx:
            out = layer(*args, **kwargs)
        new_state = {k: sd[k]._array for k in state.keys() if k in sd}
        out_arrays = _unwrap_tree(out)
        return out_arrays, new_state
    finally:
        for k, arr in old.items():
            sd[k]._array = arr


class _nullcontext:
    def __enter__(self):
        return None

    def __exit__(self, *a):
        return False


class StaticFunction:
    """Compiled wrapper around a Layer or function
    (reference: program_translator.py:236 StaticFunction).

    Before jitting, the target's source is run through the dy2static AST
    pass (jit/dy2static.py — reference ast_transformer.py) so data-dependent
    Python ``if``/``while`` lower to lax.cond/while_loop instead of raising
    a tracer error.  Unsupported control-flow shapes fall back to trace-only
    compilation; the reason is kept on ``_dy2static_error``."""

    def __init__(self, target, input_spec=None, build_strategy=None,
                 backend=None):
        from .dy2static import Dy2StaticUnsupportedError, transform_function

        self._target = target
        self._input_spec = input_spec
        self._is_layer = isinstance(target, Layer)
        self._dy2static_error = None
        self._forward_override = None   # transformed forward, NOT written
        try:                            # onto the user's eager layer
            if self._is_layer:
                tf = transform_function(type(target).forward)
                if getattr(tf, "__dy2static_transformed__", False):
                    self._forward_override = tf
            else:
                tf = transform_function(target)
                if getattr(tf, "__dy2static_transformed__", False):
                    self._target = tf
        except Dy2StaticUnsupportedError as e:
            self._dy2static_error = e
        if self._is_layer:
            self._jitted = jax.jit(self._layer_core)
        else:
            self._jitted = jax.jit(self._fn_core)

    def _override_ctx(self):
        """Apply the dy2static-converted forward to the layer for the
        duration of a traced call only — the user's eager object stays
        untouched (a permanent rebind would silently change eager behavior
        and freeze closure nonlocals)."""
        import contextlib
        import types as _types

        if self._forward_override is None or not self._is_layer:
            return _nullcontext()

        @contextlib.contextmanager
        def ctx():
            old = self._target.__dict__.get("forward")
            self._target.__dict__["forward"] = _types.MethodType(
                self._forward_override, self._target)
            try:
                yield
            finally:
                if old is None:
                    self._target.__dict__.pop("forward", None)
                else:
                    self._target.__dict__["forward"] = old
        return ctx()

    def _layer_core(self, state, rng, args, kwargs):
        with self._override_ctx():
            out, new_state = functional_call(self._target, state, *args,
                                             rng=rng, **kwargs)
        return out, new_state

    def _fn_core(self, rng, args, kwargs):
        with no_grad(), _rnd.key_stream(rng):
            out = self._target(*_wrap_tree(args), **_wrap_tree(kwargs))
        return _unwrap_tree(out)

    def __call__(self, *args, **kwargs):
        rng = _rnd.next_key()
        args_a = _unwrap_tree(args)
        kwargs_a = _unwrap_tree(kwargs)
        if self._is_layer:
            state = self._target.functional_state()
            out, new_state = self._jitted(state, rng, args_a, kwargs_a)
            self._target.load_functional_state(new_state)
            return _wrap_tree(out)
        return _wrap_tree(self._jitted(rng, args_a, kwargs_a))

    # introspection API parity
    @property
    def code(self):
        import inspect
        try:
            return inspect.getsource(
                self._target.forward if self._is_layer else self._target)
        except Exception:
            return "<source unavailable>"

    def concrete_program(self):
        return self._jitted


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, **kwargs):
    """paddle.jit.to_static equivalent: compile a Layer/function via jax.jit."""
    def deco(target):
        return StaticFunction(target, input_spec, build_strategy, backend)
    if function is not None:
        return deco(function)
    return deco


def not_to_static(fn):
    fn._not_to_static = True
    return fn


def save(layer, path, input_spec=None, **config):
    """paddle.jit.save equivalent (reference: jit/api.py save → dy2static →
    save_inference_model).  Exports a standalone executable artifact via
    jax.export; loadable with :func:`load` WITHOUT the original class."""
    from ..static import save_inference_model

    target = layer._target if isinstance(layer, StaticFunction) else layer
    if not isinstance(target, Layer):
        raise TypeError("jit.save expects a Layer or to_static(Layer); "
                        "got %r" % (type(layer).__name__,))
    if input_spec is None:
        input_spec = getattr(layer, "_input_spec", None)
    if input_spec is None:
        raise ValueError("jit.save needs input_spec=[InputSpec(...), ...] "
                         "(shapes are static under XLA)")
    ctx = (layer._override_ctx() if isinstance(layer, StaticFunction)
           else _nullcontext())
    with ctx:
        return save_inference_model(path, model=target,
                                    input_spec=input_spec, **config)


class TranslatedLayer(Layer):
    """The loaded-artifact Layer (reference: fluid/dygraph/io.py
    TranslatedLayer): callable like a Layer, runs the deserialized exported
    program; no original class needed."""

    def __init__(self, predictor):
        super().__init__()
        self._predictor = predictor

    def forward(self, *args):
        return self._predictor(*args)


def load(path, **config):
    """paddle.jit.load equivalent: returns a callable TranslatedLayer running
    the serialized StableHLO module."""
    from ..static import load_inference_model

    predictor = load_inference_model(path, **config)
    return TranslatedLayer(predictor)


import re as _re

_LAYER_IDX_RE = _re.compile(r"\.(\d+)\.")

#: step-state key holding the single flat f32 master buffer (flat_master mode)
_FLAT_KEY = "__flat_master__"

#: params at or above this element count stay out of the flat buffer: the
#: huge arrays (GPT-2's 51.5M-element wte) already run their optimizer
#: fusion at ~700 GB/s (PERF.md trace) — flattening them would only add
#: concat traffic for no bandwidth win.  The 4-16 MB per-layer params are
#: the ones XLA updates at ~250 GB/s, and those are what the buffer packs.
_FLAT_MAX_ELEMS = 1 << 25


def _make_flat_unflatten(groups):
    """flat 1-D f32 master buffer -> tuple of per-parameter COMPUTE-dtype
    views.  ``groups`` = [(dtype_or_None, g0, g1, [(rel_off, size, shape),
    ...]), ...] with same-compute-dtype members contiguous in the buffer.

    Two measured failure modes shape this design (PERF.md):

    * jax's default slice vjp is pad-into-zeros-and-add — the scatter that
      sank the stacked-params experiment.  custom_vjp makes the backward
      ONE concatenate per dtype group (the exact cotangent for disjoint
      static slices) + one group upcast.
    * casting f32->bf16 per *member* view re-creates ~150 small XLA
      fusions (measured 26.5 ms/step of ``convert_bitcast_fusion`` — the
      same per-fusion overhead the flat buffer exists to kill, moved from
      the update to the cast).  So each dtype group is cast ONCE as a big
      contiguous segment; the member views are then contiguous
      slice+reshape = free bitcasts XLA folds into the consumers.
    """
    @jax.custom_vjp
    def unflatten(flat):
        views = []
        for dt, g0, g1, members in groups:
            seg = jax.lax.slice(flat, (g0,), (g1,))
            if dt is not None:
                seg = seg.astype(dt)
            for off, size, shp in members:
                views.append(
                    jax.lax.slice(seg, (off,), (off + size,)).reshape(shp))
        return tuple(views)

    def fwd(flat):
        return unflatten(flat), None

    def bwd(_, cots):
        segs, i = [], 0
        for dt, g0, g1, members in groups:
            seg = jnp.concatenate(
                [jnp.asarray(c).reshape(-1)
                 for c in cots[i:i + len(members)]])
            segs.append(seg.astype(jnp.float32))
            i += len(members)
        return (segs[0] if len(segs) == 1 else jnp.concatenate(segs),)

    unflatten.defvjp(fwd, bwd)
    return unflatten


def _stack_layout(params):
    """Group parameter names that differ only in ONE numeric segment (the
    repeated-layer index, e.g. ``gpt.h.{0..23}.attn.qkv_proj.weight``) and
    whose shapes match.  Returns {template: [names in index order]} for
    groups of size > 1.

    Rationale: holding each of a deep model's ~300 per-layer params as its
    own array makes the optimizer update ~300 small XLA fusions running at
    ~250 GB/s where stacked (L, ...) arrays run at ~700 GB/s.  MEASURED
    OUTCOME (PERF.md): the per-layer slice views' grad transpose costs more
    than the update saves on the GPT-2 345M bench (49.8k vs 52.2k
    tokens/s), so TrainStep(stack_layers=...) defaults OFF; the machinery
    stays as an opt-in for shapes where the trade goes the other way.  The
    stack is INTERNAL to TrainStep: state_dict()/sync_to_model still speak
    per-layer names.
    """
    groups = {}
    for name, arr in params.items():
        hits = _LAYER_IDX_RE.findall(name)
        if len(hits) != 1:
            continue
        template = _LAYER_IDX_RE.sub(".#.", name)
        groups.setdefault(template, []).append((int(hits[0]), name))
    layout = {}
    for template, members in groups.items():
        if len(members) < 2:
            continue
        members.sort()
        idxs = [i for i, _n in members]
        names = [n for _i, n in members]
        shapes = {params[n].shape for n in names}
        dtypes = {params[n].dtype for n in names}
        if idxs == list(range(len(idxs))) and len(shapes) == 1 \
                and len(dtypes) == 1:
            layout[template] = names
    return layout


class TrainStep:
    """One fused, compiled training step: forward + backward + optimizer.

    The TPU-native Executor: what the reference splits across
    Tracer/autograd/optimizer ops scheduled by InterpreterCore
    (framework/new_executor/interpretercore.cc) is here ONE XLA program —
    loss, grads (jax.grad), update — with every elementwise chain fused.

    Batch convention: ``step(*batch)`` sends ``batch[:num_inputs]`` to the
    model and the rest (labels) to ``loss_fn(*outputs, *labels)`` — all as
    traced arguments, so every batch is fresh data to the same compiled
    program.

    Usage:
        step = TrainStep(model, loss_fn, opt)
        for x, y in loader:
            loss = step(x, y)
        step.sync_to_model()   # write trained arrays back into model/opt
    """

    def __init__(self, model: Layer, loss_fn: Callable, optimizer,
                 num_inputs: int = 1, in_shardings=None, donate=True,
                 zero_stage: Optional[int] = None, zero_axis: str = "sdp",
                 stack_layers: bool = False,
                 flat_master: Optional[bool] = None):
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.num_inputs = num_inputs
        full_state = model.functional_state()
        trainable = {name for name, p in model.named_parameters()
                     if not p.stop_gradient}
        # copy so the first donated step cannot invalidate the eager model's
        # own buffers
        copy = (lambda v: jnp.array(v)) if donate else (lambda v: v)
        self.params = {k: copy(v) for k, v in full_state.items()
                       if k in trainable}
        self.buffers = {k: copy(v) for k, v in full_state.items()
                        if k not in trainable}
        # AMP O2: a low-precision trainable param is held as ONE fp32
        # master array in the step state and cast to its compute dtype
        # inside the compiled step (so the optimizer never creates a
        # separate "master" slot).  Keeping both a bf16 param and an fp32
        # master in the step I/O round-trips every parameter through HBM
        # twice per step — neither buffer can donation-alias the other —
        # measured ~15 ms/step of pure copies on the GPT-2 345M bench
        # (PERF.md "copy lane").
        # stack layout computed on ORIGINAL dtypes: groups whose members
        # mix dtypes (e.g. a partially AMP-decorated layer list) fail the
        # uniformity check here and stay unstacked — after the f32 master
        # promotion below everything is f32 and the mix would be invisible
        self._stack = _stack_layout(self.params) if stack_layers else {}
        self._stacked_names = {n for names in self._stack.values()
                               for n in names}
        self._compute_dtypes = {}
        if getattr(optimizer, "_multi_precision", None) is not False:
            for k, v in list(self.params.items()):
                if hasattr(v, "dtype") and v.dtype in (jnp.bfloat16,
                                                       jnp.float16):
                    self._compute_dtypes[k] = v.dtype
                    self.params[k] = v.astype(jnp.float32)
        for template, names in self._stack.items():
            self.params[template] = jnp.stack(
                [self.params.pop(n) for n in names])
            if names[0] in self._compute_dtypes:
                # sound: the layout's dtype-uniformity check (pre-promotion)
                # guarantees every member shared names[0]'s compute dtype
                self._compute_dtypes[template] = self._compute_dtypes[
                    names[0]]
                for n in names:
                    self._compute_dtypes.pop(n, None)

        # ---- flat master buffer ------------------------------------------
        # One 1-D f32 array holding every small/mid trainable master; the
        # optimizer update over it is ONE fusion running at big-array HBM
        # bandwidth (~700 GB/s) instead of ~150 per-param fusions at
        # ~250 GB/s (PERF.md: the 32.6 ms AdamW bucket vs its 11.8 ms
        # bandwidth floor).  Forward slices per-param views back out via
        # _make_flat_unflatten (concat backward, no scatter).
        self._flat_names: list = []
        self._flat_offsets: list = []
        self._flat_sizes: list = []
        self._flat_shapes: list = []
        self._flat_unflatten = None
        if flat_master is None:
            # default OFF: A/B'd end-to-end on the GPT-2 345M TPU bench in
            # two variants and both LOST (PERF.md round-4 log) — the flat
            # update fusion itself runs at big-array bandwidth (32.6 ->
            # 14.5 ms measured), but params on TPU carry tiled layouts, so
            # the per-name <-> flat 1-D bridge forces retiling copies that
            # cost more than the update saves.  Kept as a tested opt-in
            # for layouts/backends where the trade differs.
            flat_master = False
        elif flat_master and not self._flat_eligible(optimizer, zero_stage):
            raise ValueError(
                "flat_master=True is incompatible with this configuration "
                "(ZeRO/stack_layers/per-param optimizer semantics — see "
                "TrainStep._flat_eligible)")
        if flat_master:
            members = [
                (k, v) for k, v in self.params.items()
                if hasattr(v, "dtype") and v.dtype == jnp.float32
                and v.size < _FLAT_MAX_ELEMS]
            # same-compute-dtype members contiguous, so the per-group cast
            # in _make_flat_unflatten is one big convert (dtype name keys
            # the sort; None/f32 members group together)
            members.sort(key=lambda kv: (
                str(self._compute_dtypes.get(kv[0], "")), kv[0]))
            if len(members) >= 2:
                groups, off = [], 0
                for k, v in members:
                    dt = self._compute_dtypes.get(k)
                    self._flat_names.append(k)
                    self._flat_offsets.append(off)
                    self._flat_sizes.append(int(v.size))
                    self._flat_shapes.append(tuple(v.shape))
                    if not groups or groups[-1][0] != dt:
                        groups.append([dt, off, off, []])
                    groups[-1][3].append(
                        (off - groups[-1][1], int(v.size), tuple(v.shape)))
                    off += int(v.size)
                    groups[-1][2] = off
                self.params[_FLAT_KEY] = jnp.concatenate(
                    [self.params.pop(k).reshape(-1) for k, _ in members])
                self._flat_unflatten = _make_flat_unflatten(
                    tuple((dt, g0, g1, tuple(m))
                          for dt, g0, g1, m in groups))
        self.opt_state = optimizer.init_state(self.params)
        self._dirty = True
        self._step_index = -1  # host-side step counter (faultpoint ctx)

        # ---- ZeRO placement (reference semantics: sharding_stage2.py:43
        # grad reduce-scatter, sharding_stage3.py:50 param slicing;
        # TPU-native: shardings + GSPMD, SURVEY.md §7 table) ----------------
        self._zero_stage = zero_stage
        self._zero_axis = zero_axis
        self._param_specs = None
        self._grad_specs = None
        self._in_shardings = in_shardings
        if zero_stage:
            from ..distributed import mesh as _mesh
            from ..distributed.sharding import _stage_spec_for
            from jax.sharding import NamedSharding, PartitionSpec

            mesh = _mesh.ensure_mesh()
            if _mesh.axis_size(zero_axis) <= 1 and mesh.size > 1:
                raise ValueError(
                    "zero_stage=%d requested but mesh axis %r has size <= 1 "
                    "(mesh axes: %s) — init_mesh({'%s': N, ...}) first or "
                    "the sharding would silently be a no-op"
                    % (zero_stage, zero_axis, dict(
                        zip(mesh.axis_names, mesh.devices.shape)),
                       zero_axis))
            shard = lambda a: _stage_spec_for(a, zero_axis)
            # stage >=1: optimizer slots sharded
            def place_slot(x):
                if hasattr(x, "ndim") and x.ndim > 0:
                    return jax.device_put(
                        x, NamedSharding(mesh, shard(x)))
                return x
            self.opt_state = jax.tree_util.tree_map(place_slot,
                                                    self.opt_state)
            # stage >=2: grads reduce-scattered onto the same layout
            if zero_stage >= 2:
                self._grad_specs = {k: shard(v)
                                    for k, v in self.params.items()}
            # stage 3: parameters themselves sharded (allgather-on-use)
            if zero_stage >= 3:
                self._param_specs = {k: shard(v)
                                     for k, v in self.params.items()}
                self.params = {
                    k: jax.device_put(
                        v, NamedSharding(mesh, self._param_specs[k]))
                    for k, v in self.params.items()}
            else:
                self.params = {
                    k: jax.device_put(
                        v, NamedSharding(mesh, PartitionSpec()))
                    for k, v in self.params.items()}
            self._mesh = mesh
        elif in_shardings is not None:
            from ..distributed import mesh as _mesh
            self._mesh = _mesh.ensure_mesh()
        else:
            self._mesh = None

        def loss_core(params, buffers, rng, batch):
            if self._flat_unflatten is not None:
                # flat 1-D master -> per-param f32 views (concat backward);
                # the per-name compute-dtype cast below then applies to the
                # views exactly as it would to standalone masters
                params = dict(params)
                views = self._flat_unflatten(params.pop(_FLAT_KEY))
                params.update(zip(self._flat_names, views))
            if self._compute_dtypes:
                # fp32 master -> compute dtype; the cast's vjp upcasts the
                # bf16 grads back to f32 for the optimizer update
                params = {k: (p.astype(self._compute_dtypes[k])
                              if k in self._compute_dtypes else p)
                          for k, p in params.items()}
            if self._stack:
                # stacked (L, ...) -> per-layer views for functional_call;
                # the slices are free and their vjp writes each layer's
                # grad into one stacked buffer
                params = dict(params)
                for template, names in self._stack.items():
                    stacked = params.pop(template)
                    for i, n in enumerate(names):
                        params[n] = stacked[i]
            state = {**params, **buffers}
            self.model.train()
            inputs = batch[:self.num_inputs]
            labels = batch[self.num_inputs:]
            out, new_state = functional_call(self.model, state, *inputs,
                                             rng=rng)
            outs = out if isinstance(out, tuple) else (out,)
            with no_grad():  # jax traces the grad; keep the eager tape off
                loss = self.loss_fn(
                    *[Tensor(o) if not isinstance(o, Tensor) else o
                      for o in outs],
                    *[Tensor(l) if not isinstance(l, Tensor) else l
                      for l in labels])
            if isinstance(loss, Tensor):
                loss = loss._array
            new_buffers = {k: new_state[k] for k in buffers.keys()}
            return loss, new_buffers

        def grads_core(params, buffers, rng, batch):
            (loss, new_buffers), grads = jax.value_and_grad(
                loss_core, has_aux=True)(params, buffers, rng, batch)
            if self._grad_specs is not None:
                # ZeRO stage-2: constrain each grad to the slot layout so
                # GSPMD lowers the data-parallel grad sum to reduce-scatter
                # (sharding_stage2.py:43 semantics)
                from jax.sharding import NamedSharding
                grads = {
                    k: jax.lax.with_sharding_constraint(
                        g, NamedSharding(self._mesh, self._grad_specs[k]))
                    for k, g in grads.items()}
            return loss, new_buffers, grads

        # exposed for tests/diagnostics: the exact grad computation the
        # compiled step runs, including ZeRO layout constraints
        self._grads_core = grads_core

        # opt-in grad-norm telemetry: computes the global grad norm inside
        # the compiled step and publishes it as a gauge.  Costs one extra
        # reduction in-program plus ONE device sync per step on the host —
        # that is why it is an env opt-in, not a default
        import os as _env_os
        self._emit_grad_norm = _env_os.environ.get(
            "PADDLE_TPU_METRICS_GRAD_NORM", "0") not in ("0", "", "off")

        def step_fn(params, buffers, opt_state, lr, rng, batch):
            loss, new_buffers, grads = grads_core(params, buffers, rng,
                                                  batch)
            new_params, new_opt_state = self.optimizer.apply_gradients(
                params, grads, opt_state, lr)
            if self._param_specs is not None:
                # ZeRO stage-3: updated params stay sharded
                from jax.sharding import NamedSharding
                new_params = {
                    k: jax.lax.with_sharding_constraint(
                        p, NamedSharding(self._mesh, self._param_specs[k]))
                    for k, p in new_params.items()}
            if self._emit_grad_norm:
                gnorm = jnp.sqrt(sum(
                    jnp.sum(jnp.square(g.astype(jnp.float32)))
                    for g in jax.tree_util.tree_leaves(grads)))
                return loss, new_params, new_buffers, new_opt_state, gnorm
            return loss, new_params, new_buffers, new_opt_state

        donate_args = (0, 1, 2) if donate else ()
        # recorded for the trace-tier donation audit (TPU502 in
        # paddle_tpu.analysis.trace): the registry lowers self._step with
        # trace_args() and verifies each declared donation materializes
        # as input-output aliasing in the compiled entry
        self._donate_argnums = donate_args
        self._step_fn = step_fn   # un-jitted, for audit re-wraps
        # recompile watchdog: one TrainStep is one program — a second
        # compile means a batch shape/dtype is churning underneath the
        # caller (observability.watchdog warns; strict mode raises)
        from ..observability import registry as _obs
        from ..observability.watchdog import watch
        self._step = watch("jit.train_step",
                           jax.jit(step_fn, donate_argnums=donate_args),
                           expected=1)
        self._m_step_seconds = _obs.histogram("train.step_seconds")
        self._m_steps = _obs.counter("train.steps")
        self._m_grad_norm = _obs.gauge("train.grad_norm")
        # fetched once; the NOOP_BEACON singleton when liveness is off
        self._beacon = _liveness.beacon("train.step")

    def trace_args(self, batch):
        """The exact argument tuple ``self._step`` runs with, for
        lowering/audit (``self._step.lower(*step.trace_args(batch))``).
        ``batch`` is the tuple a normal ``step(*batch)`` call would take.

        Uses a FIXED key rather than drawing from the global stream: the
        result is only lowered, never executed, and auditing a live step
        must not shift every subsequent dropout mask of the real run.
        ``jax.random.key`` (typed) matches the aval the production
        ``__call__`` passes, so the audit lowers the identical program."""
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        rng = jax.random.key(0)
        return (self.params, self.buffers, self.opt_state, lr, rng,
                _unwrap_tree(tuple(batch)))

    def cost_report(self, batch):
        """XLA cost/memory analysis of THIS step's compiled program
        (:class:`paddle_tpu.observability.costs.ProgramReport`) — the
        bench `cost` block's source.  Lowers + compiles once per call
        (the jit dispatch cache is separate from the AOT path): cold
        path only — bench.py calls it after the timed loop."""
        from ..observability import costs as _costs
        compiled = jax.jit(self._step_fn,
                           donate_argnums=self._donate_argnums) \
            .lower(*self.trace_args(batch)).compile()
        return _costs.report_from_compiled("jit.train_step", compiled)

    def __call__(self, *batch):
        rng = _rnd.next_key()
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        batch_a = _unwrap_tree(batch)
        # chaos hook: fires per step on the HOST side (a faultpoint inside
        # the jitted step_fn would be traced away); a NaNBatch action
        # poisons one input so loss and every grad behind it go NaN —
        # the deterministic "NaN grads at step k" injection
        self._step_index += 1
        ctx = faultpoint("train.grads", batch=batch_a,
                         step=self._step_index)
        if ctx is not None:
            batch_a = ctx["batch"]
        if self._in_shardings is not None and self._mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec
            specs = self._in_shardings
            # PartitionSpec IS a tuple: without the explicit check a single
            # spec like PartitionSpec("sdp") would be unpacked into one
            # raw axis-name STRING per batch element, which NamedSharding
            # rejects (jax 0.4.x) or silently misreads
            if isinstance(specs, PartitionSpec) or not isinstance(
                    specs, (list, tuple)):
                specs = [specs] * len(batch_a)
            batch_a = tuple(
                jax.device_put(b, NamedSharding(self._mesh, s))
                for b, s in zip(batch_a, specs))
        import time as _time
        t0 = _time.perf_counter()
        with self._beacon:   # liveness: a hang inside the step is a stall
            out = self._step(
                self.params, self.buffers, self.opt_state, lr, rng,
                batch_a)
            if self._emit_grad_norm:
                loss, self.params, self.buffers, self.opt_state, gnorm \
                    = out
                self._m_grad_norm.set(float(gnorm))  # opt-in: syncs step
            else:
                loss, self.params, self.buffers, self.opt_state = out
        self._m_step_seconds.observe(_time.perf_counter() - t0)
        self._m_steps.inc()
        self._dirty = True
        if isinstance(self.optimizer._learning_rate, object) and hasattr(
                self.optimizer._learning_rate, "step"):
            try:
                self.optimizer._learning_rate.step()
            except TypeError:
                pass
        return Tensor(loss)

    def _flat_eligible(self, optimizer, zero_stage) -> bool:
        """flat_master auto-gate.  The flat buffer is only semantics-
        preserving when the optimizer update and grad clip are uniform
        elementwise over parameters:

        * ZeRO re-lays slots/params per-name over the mesh — incompatible.
        * stack_layers is the competing (opt-in, measured-slower) layout.
        * Lamb computes per-parameter trust norms (``_flat_safe = False``).
        * AdamW's ``apply_decay_param_fun`` makes weight decay per-name.
        * ClipGradByNorm clips per-parameter norms (global-norm clip is
          fine: the norm over the flat buffer equals the tree norm).
        """
        if zero_stage or self._stack:
            return False
        if getattr(optimizer, "_flat_safe", True) is False:
            return False
        if getattr(optimizer, "_apply_decay_param_fun", None) is not None:
            return False
        clip = getattr(optimizer, "_grad_clip", None)
        if clip is not None:
            from ..nn import ClipGradByNorm
            if isinstance(clip, ClipGradByNorm):
                return False
        return True

    def _flat_views(self, flat):
        """Eager per-name views of a flat buffer (for state export)."""
        return [flat[o:o + s].reshape(shp)
                for o, s, shp in zip(self._flat_offsets, self._flat_sizes,
                                     self._flat_shapes)]

    def _unstacked_params(self):
        """self.params with stacked groups / the flat buffer expanded back
        to per-layer names (the external contract)."""
        params = dict(self.params)
        for template, names in self._stack.items():
            stacked = params.pop(template)
            for i, n in enumerate(names):
                params[n] = stacked[i]
        if self._flat_names and _FLAT_KEY in params:
            flat = params.pop(_FLAT_KEY)
            params.update(zip(self._flat_names, self._flat_views(flat)))
        return params

    def _restacked(self, params):
        """Inverse of _unstacked_params for incoming per-layer dicts."""
        params = dict(params)
        for template, names in self._stack.items():
            if template in params:
                continue      # already stacked (same-format checkpoint)
            if all(n in params for n in names):
                params[template] = jnp.stack(
                    [jnp.asarray(params.pop(n)) for n in names])
        if self._flat_names and _FLAT_KEY not in params \
                and all(n in params for n in self._flat_names):
            # incoming per-name entries may carry the model-side compute
            # dtype (e.g. a bf16 jit.save re-load); masters are f32
            params[_FLAT_KEY] = jnp.concatenate(
                [jnp.asarray(params.pop(n)).astype(jnp.float32).reshape(-1)
                 for n in self._flat_names])
        return params

    def sync_to_model(self):
        """Write the trained arrays back into the eager model."""
        params = {k: (v.astype(self._compute_dtypes.get(k, v.dtype))
                      if hasattr(v, "dtype") else v)
                  for k, v in self._unstacked_params().items()}
        # per-name compute dtypes were collapsed onto the template; map back
        for template, names in self._stack.items():
            if template in self._compute_dtypes:
                for n in names:
                    params[n] = params[n].astype(
                        self._compute_dtypes[template])
        self.model.load_functional_state({**params, **self.buffers})
        self._dirty = False

    # -- checkpoint contract (incubate.checkpoint) -------------------------
    def state_dict(self):
        """Everything needed to resume: params, buffers, optimizer slots,
        and the LR-scheduler/optimizer bookkeeping."""
        opt_extra = {}
        lr = self.optimizer._learning_rate
        if hasattr(lr, "state_dict"):
            opt_extra["lr_scheduler"] = lr.state_dict()
        # params AND optimizer slots exported UNSTACKED (per-layer names)
        # so the checkpoint format is independent of the internal stacking
        # optimization
        opt_state = self.opt_state
        if self._stack and isinstance(opt_state, dict) \
                and "slots" in opt_state:
            slots = dict(opt_state["slots"])
            for template, names in self._stack.items():
                if template not in slots:
                    continue
                grp = slots.pop(template)
                for i, n in enumerate(names):
                    slots[n] = {k: v[i] for k, v in grp.items()}
            opt_state = {**opt_state, "slots": slots}
        if self._flat_names and isinstance(opt_state, dict) \
                and "slots" in opt_state and _FLAT_KEY in opt_state["slots"]:
            slots = dict(opt_state["slots"])
            grp = slots.pop(_FLAT_KEY)
            for n, o, s, shp in zip(self._flat_names, self._flat_offsets,
                                    self._flat_sizes, self._flat_shapes):
                slots[n] = {k: (v[o:o + s].reshape(shp)
                                if hasattr(v, "shape") and v.ndim == 1
                                else v)
                            for k, v in grp.items()}
            opt_state = {**opt_state, "slots": slots}
        return {"params": self._unstacked_params(), "buffers": self.buffers,
                "opt_state": opt_state, "opt_extra": opt_extra}

    def set_state_dict(self, state):
        """Restore from :meth:`state_dict` output.  Arrays are re-placed on
        their current shardings (ZeRO layouts survive a restore)."""
        def place_like(new, old):
            if hasattr(old, "sharding") and hasattr(new, "shape"):
                # COPY (jnp.array), never alias: the incoming state may
                # reference another live TrainStep's buffers (state_dict
                # returns views), and this step's donation would delete
                # them out from under their owner
                arr = jnp.array(new)
                if hasattr(old, "dtype") and arr.dtype != old.dtype:
                    # e.g. a bf16 model-side save restored into the fp32
                    # master param state
                    arr = arr.astype(old.dtype)
                return jax.device_put(arr, old.sharding)
            return new
        self.params = {k: place_like(v, self.params.get(k))
                       for k, v in self._restacked(
                           state["params"]).items()}
        self.buffers = {k: place_like(v, self.buffers.get(k))
                        for k, v in state["buffers"].items()}
        opt_state = state["opt_state"]
        if self._stack and isinstance(opt_state, dict) \
                and "slots" in opt_state:
            slots = dict(opt_state["slots"])
            for template, names in self._stack.items():
                if template in slots or not all(n in slots for n in names):
                    continue
                per = [slots.pop(n) for n in names]
                slots[template] = {
                    k: jnp.stack([jnp.asarray(p[k]) for p in per])
                    for k in per[0]}
            opt_state = {**opt_state, "slots": slots}
        if self._flat_names and isinstance(opt_state, dict) \
                and "slots" in opt_state \
                and _FLAT_KEY not in opt_state["slots"] \
                and all(n in opt_state["slots"] for n in self._flat_names):
            slots = dict(opt_state["slots"])
            per = [slots.pop(n) for n in self._flat_names]
            # mirror the EXPORT guard (state_dict passes non-param-shaped
            # slot leaves through shared): only concatenate per-name
            # leaves whose size matches the member's flat size — a future
            # optimizer with scalar slots would otherwise produce a
            # tree/shape mismatch against init_state (ADVICE r4)
            slots[_FLAT_KEY] = {
                k: (jnp.concatenate(
                        [jnp.asarray(p[k]).reshape(-1) for p in per])
                    if all(hasattr(p[k], "shape")
                           and int(jnp.asarray(p[k]).size) == sz
                           for p, sz in zip(per, self._flat_sizes))
                    else per[0][k])
                for k in per[0]
                if hasattr(per[0][k], "shape")}
            opt_state = {**opt_state, "slots": slots}
        self.opt_state = jax.tree_util.tree_map(
            place_like, opt_state, self.opt_state)
        lr = self.optimizer._learning_rate
        sched = state.get("opt_extra", {}).get("lr_scheduler")
        if sched is not None and hasattr(lr, "set_state_dict"):
            lr.set_state_dict(sched)
        self._dirty = True
