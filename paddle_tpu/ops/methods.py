"""Attach operator dunders and paddle-style methods to Tensor.

The reference patches methods onto its eager Tensor via
monkey_patch_varbase/monkey_patch_tensor
(reference: python/paddle/fluid/dygraph/varbase_patch_methods.py); we do the
same, binding the functional ops as methods at import time.
"""
from __future__ import annotations

from ..core.tensor import Tensor
from . import (comparison, creation, linalg, manipulation, math, random_ops,
               reduction, search)


def _binary(op, swap=False):
    def method(self, other):
        if swap:
            return op(other, self)
        return op(self, other)
    return method


def install():
    T = Tensor
    # arithmetic
    T.__add__ = _binary(math.add)
    T.__radd__ = _binary(math.add, swap=True)
    T.__sub__ = _binary(math.subtract)
    T.__rsub__ = _binary(math.subtract, swap=True)
    T.__mul__ = _binary(math.multiply)
    T.__rmul__ = _binary(math.multiply, swap=True)
    T.__truediv__ = _binary(math.divide)
    T.__rtruediv__ = _binary(math.divide, swap=True)
    T.__floordiv__ = _binary(math.floor_divide)
    T.__rfloordiv__ = _binary(math.floor_divide, swap=True)
    T.__mod__ = _binary(math.mod)
    T.__rmod__ = _binary(math.mod, swap=True)
    T.__pow__ = _binary(math.pow)
    T.__rpow__ = _binary(math.pow, swap=True)
    T.__matmul__ = _binary(linalg.matmul)
    T.__rmatmul__ = _binary(linalg.matmul, swap=True)
    T.__neg__ = lambda self: math.neg(self)
    T.__abs__ = lambda self: math.abs(self)
    T.__invert__ = lambda self: (comparison.logical_not(self)
                                 if self.dtype == bool else comparison.bitwise_not(self))
    # comparisons
    T.__eq__ = _binary(comparison.equal)
    T.__ne__ = _binary(comparison.not_equal)
    T.__lt__ = _binary(comparison.less_than)
    T.__le__ = _binary(comparison.less_equal)
    T.__gt__ = _binary(comparison.greater_than)
    T.__ge__ = _binary(comparison.greater_equal)
    # bitwise / logical
    T.__and__ = _binary(comparison.bitwise_and)
    T.__or__ = _binary(comparison.bitwise_or)
    T.__xor__ = _binary(comparison.bitwise_xor)
    # indexing
    T.__getitem__ = manipulation.getitem
    T.__setitem__ = manipulation.setitem

    mods = (math, reduction, linalg, manipulation, comparison, search)
    skip = {"where", "Tensor", "wrap_op", "call", "getitem", "setitem",
            "shape", "numel", "nonzero", "unique", "unique_consecutive"}
    for mod in mods:
        for name in dir(mod):
            if name.startswith("_") or name in skip:
                continue
            fn = getattr(mod, name)
            if not callable(fn) or isinstance(fn, type):
                continue
            if not hasattr(T, name):
                setattr(T, name, fn)

    # a few extras / renames
    T.matmul = linalg.matmul
    T.mm = linalg.matmul
    T.dot = linalg.dot
    T.reshape = manipulation.reshape
    T.reshape_ = lambda self, shape: _inplace(self, manipulation.reshape, shape)
    T.nonzero = search.nonzero
    T.unique = search.unique
    T.transpose = manipulation.transpose
    T.flatten = manipulation.flatten
    T.squeeze = manipulation.squeeze
    T.unsqueeze = manipulation.unsqueeze
    T.sum = reduction.sum
    T.mean = reduction.mean
    T.max = reduction.max
    T.min = reduction.min
    T.prod = reduction.prod
    T.std = reduction.std
    T.var = reduction.var
    T.all = reduction.all
    T.any = reduction.any
    T.argmax = search.argmax
    T.argmin = search.argmin
    T.argsort = search.argsort
    T.sort = search.sort
    T.topk = search.topk
    T.where = lambda self, x, y: search.where(self, x, y)
    T.clip = math.clip
    T.clip_ = lambda self, min=None, max=None: _inplace(self, math.clip, min, max)
    T.add_ = lambda self, y: _inplace(self, math.add, y)
    T.subtract_ = lambda self, y: _inplace(self, math.subtract, y)
    T.multiply_ = lambda self, y: _inplace(self, math.multiply, y)
    T.divide_ = lambda self, y: _inplace(self, math.divide, y)
    T.scale_ = lambda self, s, bias=0.0: _inplace(self, math.scale, s, bias)
    T.zero_ = lambda self: _inplace(self, creation.zeros_like)
    T.fill_ = lambda self, v: _inplace(self, creation.full_like, v)
    T.exp_ = lambda self: _inplace(self, math.exp)
    T.ceil_ = lambda self: _inplace(self, math.ceil)
    T.floor_ = lambda self: _inplace(self, math.floor)
    T.reciprocal_ = lambda self: _inplace(self, math.reciprocal)
    T.round_ = lambda self: _inplace(self, math.round)
    T.rsqrt_ = lambda self: _inplace(self, math.rsqrt)
    T.sqrt_ = lambda self: _inplace(self, math.sqrt)
    T.tanh_ = lambda self: _inplace(self, math.tanh)
    T.erfinv_ = lambda self: _inplace(self, math.erfinv)
    T.lerp_ = lambda self, y, weight: _inplace(self, math.lerp, y, weight)
    T.flatten_ = lambda self, start_axis=0, stop_axis=-1: _inplace(
        self, manipulation.flatten, start_axis, stop_axis)
    T.squeeze_ = lambda self, axis=None: _inplace(
        self, manipulation.squeeze, axis)
    T.unsqueeze_ = lambda self, axis: _inplace(
        self, manipulation.unsqueeze, axis)
    T.scatter_ = lambda self, index, updates, overwrite=True: _inplace(
        self, manipulation.scatter, index, updates, overwrite)
    T.put_along_axis_ = lambda self, indices, values, axis, reduce="assign": \
        _inplace(self, manipulation.put_along_axis, indices, values, axis,
                 reduce)
    T.exponential_ = lambda self, lam=1.0: random_ops.exponential_(self, lam)
    T.uniform_ = lambda self, min=-1.0, max=1.0, seed=0: _assign(
        self, random_ops.uniform(self.shape, self.dtype, min, max, seed))
    T.normal_ = lambda self, mean=0.0, std=1.0: _assign(
        self, random_ops.gaussian(self.shape, mean, std, self.dtype))
    T.tile = manipulation.tile
    T.expand = manipulation.expand
    T.expand_as = manipulation.expand_as
    T.gather = manipulation.gather
    T.gather_nd = manipulation.gather_nd
    T.scatter = manipulation.scatter
    T.split = manipulation.split
    T.chunk = manipulation.chunk
    T.concat = manipulation.concat
    T.unbind = manipulation.unbind
    T.numel = lambda self: manipulation.numel(self)
    T.norm = linalg.norm


def _inplace(t, fn, *args, **kwargs):
    """Compute fn over a shadow of t (preserving t's pre-mutation autograd
    identity — see core.dispatch.shadow), then redirect t to the result."""
    from ..core.dispatch import assign_inplace, shadow
    new = fn(shadow(t), *args, **kwargs)
    return assign_inplace(t, new)


def _assign(t, new):
    from ..core.dispatch import assign_inplace
    return assign_inplace(t, new)
