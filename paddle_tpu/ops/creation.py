"""Tensor creation ops (reference surface: python/paddle/tensor/creation.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtype as _dt
from ..core.dispatch import call, wrap_op
from ..core.tensor import Tensor, to_tensor  # re-export to_tensor


def _d(dtype):
    return _dt.convert_dtype(dtype) or _dt.get_default_dtype()


def zeros(shape, dtype=None):
    return Tensor(jnp.zeros(_shape(shape), _d(dtype)))


def ones(shape, dtype=None):
    return Tensor(jnp.ones(_shape(shape), _d(dtype)))


def full(shape, fill_value, dtype=None):
    if isinstance(fill_value, Tensor):
        fill_value = fill_value._array
    return Tensor(jnp.full(_shape(shape), fill_value, _d(dtype)))


def empty(shape, dtype=None):
    return zeros(shape, dtype)


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in np.asarray(shape._array))
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s._array) if isinstance(s, Tensor) else int(s) for s in shape)


@wrap_op
def _zeros_like(x, dtype=None):
    return jnp.zeros_like(x, dtype=_dt.convert_dtype(dtype))


@wrap_op
def _ones_like(x, dtype=None):
    return jnp.ones_like(x, dtype=_dt.convert_dtype(dtype))


@wrap_op
def _full_like(x, fill_value, dtype=None):
    return jnp.full_like(x, fill_value, dtype=_dt.convert_dtype(dtype))


def zeros_like(x, dtype=None):
    return _zeros_like(x, dtype=dtype)


def ones_like(x, dtype=None):
    return _ones_like(x, dtype=dtype)


def full_like(x, fill_value, dtype=None):
    return _full_like(x, fill_value, dtype=dtype)


def empty_like(x, dtype=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None):
    def _v(x):
        return x.item() if isinstance(x, Tensor) else x
    start, end, step = _v(start), _v(end), _v(step)
    if end is None:
        start, end = 0, start
    if dtype is None:
        dtype = ("int64" if all(isinstance(v, (int, np.integer))
                                for v in (start, end, step))
                 else _dt.get_default_dtype())
    return Tensor(jnp.arange(start, end, step, dtype=_d(dtype)))


def linspace(start, stop, num, dtype=None):
    def _v(x):
        return x.item() if isinstance(x, Tensor) else x
    return Tensor(jnp.linspace(_v(start), _v(stop), int(_v(num)), dtype=_d(dtype)))


def logspace(start, stop, num, base=10.0, dtype=None):
    return Tensor(jnp.logspace(start, stop, int(num), base=base, dtype=_d(dtype)))


def eye(num_rows, num_columns=None, dtype=None):
    return Tensor(jnp.eye(num_rows, num_columns, dtype=_d(dtype)))


@wrap_op
def tril(x, diagonal=0):
    return jnp.tril(x, k=diagonal)


@wrap_op
def triu(x, diagonal=0):
    return jnp.triu(x, k=diagonal)


def tril_indices(row, col, offset=0, dtype="int64"):
    r, c = jnp.tril_indices(row, k=offset, m=col)
    return Tensor(jnp.stack([r, c]).astype(_d(dtype)))


def triu_indices(row, col=None, offset=0, dtype="int64"):
    r, c = jnp.triu_indices(row, k=offset, m=col)
    return Tensor(jnp.stack([r, c]).astype(_d(dtype)))


@wrap_op
def diag(x, offset=0, padding_value=0):
    if x.ndim == 1:
        out = jnp.diag(x, k=offset)
        if padding_value != 0:
            mask = jnp.diag(jnp.ones_like(x, dtype=bool), k=offset)
            out = jnp.where(mask, out, jnp.asarray(padding_value, x.dtype))
        return out
    return jnp.diag(x, k=offset)


@wrap_op
def diagflat(x, offset=0):
    return jnp.diagflat(x, k=offset)


@wrap_op
def diag_embed(x, offset=0, dim1=-2, dim2=-1):
    n = x.shape[-1]
    m = n + abs(offset)
    idx = jnp.arange(n)
    out = jnp.zeros(x.shape[:-1] + (m, m), x.dtype)
    if offset >= 0:
        out = out.at[..., idx, idx + offset].set(x)
    else:
        out = out.at[..., idx - offset, idx].set(x)
    if dim1 != -2 or dim2 != -1:
        out = jnp.moveaxis(out, (-2, -1), (dim1, dim2))
    return out


def meshgrid(*args):
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = args[0]
    arrays = [a._array if isinstance(a, Tensor) else jnp.asarray(a) for a in args]
    return [Tensor(m) for m in jnp.meshgrid(*arrays, indexing="ij")]


@wrap_op
def assign(x, output=None):
    return jnp.asarray(x)


def clone(x):
    return call(lambda a: a + 0 if _dt.is_inexact(a.dtype) else jnp.array(a), x, name="clone")


@wrap_op
def complex(real, imag):
    return jax.lax.complex(real, imag)


@wrap_op
def as_complex(x):
    return jax.lax.complex(x[..., 0], x[..., 1])


@wrap_op
def as_real(x):
    return jnp.stack([jnp.real(x), jnp.imag(x)], axis=-1)


@wrap_op
def real(x):
    return jnp.real(x)


@wrap_op
def imag(x):
    return jnp.imag(x)


def one_hot(x, num_classes):
    arr = x._array if isinstance(x, Tensor) else jnp.asarray(x)
    return Tensor(jax.nn.one_hot(arr, num_classes, dtype=_dt.get_default_dtype()))
