"""Random sampling ops (reference surface: python/paddle/tensor/random.py).

Eager calls draw keys from the global generator; inside a compiled step a
scoped key stream (paddle_tpu.core.random.key_stream) supplies deterministic
per-site subkeys of the step key.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtype as _dt
from ..core import random as _rnd
from ..core.dispatch import call
from ..core.tensor import Tensor


def _d(dtype):
    return _dt.convert_dtype(dtype) or _dt.get_default_dtype()


def _shape(shape):
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s._array) if isinstance(s, Tensor) else int(s) for s in shape)


def rand(shape, dtype=None):
    return Tensor(jax.random.uniform(_rnd.next_key(), _shape(shape), _d(dtype)))


def randn(shape, dtype=None):
    return Tensor(jax.random.normal(_rnd.next_key(), _shape(shape), _d(dtype)))


def standard_normal(shape, dtype=None):
    return randn(shape, dtype)


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0):
    key = jax.random.key(seed) if seed else _rnd.next_key()
    return Tensor(jax.random.uniform(key, _shape(shape), _d(dtype),
                                     minval=min, maxval=max))


def normal(mean=0.0, std=1.0, shape=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = mean._array if isinstance(mean, Tensor) else mean
        s = std._array if isinstance(std, Tensor) else std
        shp = jnp.broadcast_shapes(jnp.shape(m), jnp.shape(s))
        return Tensor(jax.random.normal(_rnd.next_key(), shp) * s + m)
    return Tensor(jax.random.normal(_rnd.next_key(), _shape(shape)) * std + mean)


def gaussian(shape, mean=0.0, std=1.0, dtype=None):
    return Tensor(jax.random.normal(_rnd.next_key(), _shape(shape), _d(dtype)) * std + mean)


def randint(low=0, high=None, shape=(1,), dtype="int64"):
    if high is None:
        low, high = 0, low
    return Tensor(jax.random.randint(_rnd.next_key(), _shape(shape), low, high,
                                     _d(dtype)))


def randint_like(x, low=0, high=None, dtype=None):
    # reference allows FLOAT output dtypes (randint_like returns x's dtype
    # by default): sample integers, then cast
    dtype = dtype or x.dtype
    out = randint(low, high, x.shape, "int64")
    return out.astype(dtype)


def randperm(n, dtype="int64"):
    return Tensor(jax.random.permutation(_rnd.next_key(), n).astype(_d(dtype)))


def shuffle(x, axis=0):
    return call(lambda a: jax.random.permutation(_rnd.next_key(), a, axis=axis,
                                                 independent=False),
                x, name="shuffle")


def bernoulli(x):
    return call(lambda p: jax.random.bernoulli(_rnd.next_key(), p).astype(p.dtype),
                x, name="bernoulli")


def poisson(x):
    return call(lambda lam: jax.random.poisson(_rnd.next_key(), lam).astype(lam.dtype),
                x, name="poisson")


def multinomial(x, num_samples=1, replacement=False):
    arr = x._array if isinstance(x, Tensor) else jnp.asarray(x)
    logits = jnp.log(jnp.maximum(arr, 1e-30))
    key = _rnd.next_key()
    if replacement:
        out = jax.random.categorical(key, logits, axis=-1,
                                     shape=(arr.shape[:-1] + (num_samples,))
                                     if arr.ndim > 1 else (num_samples,))
    else:
        # Gumbel top-k trick for sampling without replacement
        g = jax.random.gumbel(key, arr.shape)
        _, out = jax.lax.top_k(logits + g, num_samples)
    return Tensor(out.astype(jnp.int64))


def exponential_(x, lam=1.0):
    arr = jax.random.exponential(_rnd.next_key(), tuple(x.shape),
                                 x._array.dtype) / lam
    # redirect through assign_inplace so a stale grad node never survives
    # the overwrite (the value no longer depends on x's history)
    from ..core.dispatch import assign_inplace
    return assign_inplace(x, Tensor(arr))


def binomial(count, prob):
    c = count._array if isinstance(count, Tensor) else count
    p = prob._array if isinstance(prob, Tensor) else prob
    return Tensor(jax.random.binomial(_rnd.next_key(), c, p).astype(jnp.int64))


def rand_like(x, dtype=None):
    return rand(x.shape, dtype or x.dtype)


def randn_like(x, dtype=None):
    return randn(x.shape, dtype or x.dtype)


def normal_like(x, mean=0.0, std=1.0):
    return gaussian(x.shape, mean, std, x.dtype)
