"""Comparison / logical / bitwise ops
(reference surface: python/paddle/tensor/logic.py)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.dispatch import wrap_op
from ..core.tensor import Tensor

equal = wrap_op(jnp.equal, name="equal")
not_equal = wrap_op(jnp.not_equal, name="not_equal")
greater_than = wrap_op(jnp.greater, name="greater_than")
greater_equal = wrap_op(jnp.greater_equal, name="greater_equal")
less_than = wrap_op(jnp.less, name="less_than")
less_equal = wrap_op(jnp.less_equal, name="less_equal")

logical_and = wrap_op(jnp.logical_and, name="logical_and")
logical_or = wrap_op(jnp.logical_or, name="logical_or")
logical_xor = wrap_op(jnp.logical_xor, name="logical_xor")
logical_not = wrap_op(jnp.logical_not, name="logical_not")

bitwise_and = wrap_op(jnp.bitwise_and, name="bitwise_and")
bitwise_or = wrap_op(jnp.bitwise_or, name="bitwise_or")
bitwise_xor = wrap_op(jnp.bitwise_xor, name="bitwise_xor")
bitwise_not = wrap_op(jnp.bitwise_not, name="bitwise_not")
bitwise_left_shift = wrap_op(jnp.left_shift, name="bitwise_left_shift")
bitwise_right_shift = wrap_op(jnp.right_shift, name="bitwise_right_shift")

isclose = wrap_op(lambda x, y, rtol=1e-5, atol=1e-8, equal_nan=False:
                  jnp.isclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan),
                  name="isclose")


def allclose(x, y, rtol=1e-5, atol=1e-8, equal_nan=False):
    return Tensor(jnp.allclose(
        x._array if isinstance(x, Tensor) else x,
        y._array if isinstance(y, Tensor) else y,
        rtol=rtol, atol=atol, equal_nan=equal_nan))


def equal_all(x, y):
    return Tensor(jnp.array_equal(
        x._array if isinstance(x, Tensor) else x,
        y._array if isinstance(y, Tensor) else y))


def is_empty(x):
    return Tensor(jnp.asarray(int(np.prod(x.shape)) == 0))
