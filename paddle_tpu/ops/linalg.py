"""Linear algebra (reference surface: python/paddle/tensor/linalg.py; matmul
parity with reference paddle.matmul at linalg.py:124).

All matmuls lower to XLA dot_general on the MXU; keep operands bf16 under the
amp policy for peak throughput.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dispatch import wrap_op


@wrap_op
def matmul(x, y, transpose_x=False, transpose_y=False):
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    return jnp.matmul(x, y)


mm = matmul
bmm = wrap_op(jnp.matmul, name="bmm")
dot = wrap_op(lambda x, y: jnp.sum(x * y, axis=-1), name="dot")
mv = wrap_op(jnp.matmul, name="mv")
tensordot = wrap_op(lambda x, y, axes=2: jnp.tensordot(x, y, axes=axes), name="tensordot")
einsum_raw = jnp.einsum


@wrap_op
def einsum(equation, *operands):
    return jnp.einsum(equation, *operands)


@wrap_op
def t(x):
    if x.ndim < 2:
        return x
    if x.ndim == 2:
        return x.T
    raise ValueError("paddle.t only supports ndim<=2; use transpose")


@wrap_op
def matrix_transpose(x):
    return jnp.swapaxes(x, -1, -2)


@wrap_op
def norm(x, p="fro", axis=None, keepdim=False):
    if axis is None and p == "fro":
        return jnp.sqrt(jnp.sum(jnp.square(x)))
    if isinstance(axis, (list, tuple)) and len(axis) == 2:
        return jnp.linalg.norm(x, ord=p if p != "fro" else "fro",
                               axis=tuple(axis), keepdims=keepdim)
    if p == "fro" or p == 2:
        return jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=keepdim))
    if p == 1:
        return jnp.sum(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == float("inf"):
        return jnp.max(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == float("-inf"):
        return jnp.min(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == 0:
        return jnp.sum((x != 0).astype(x.dtype), axis=axis, keepdims=keepdim)
    return jnp.sum(jnp.abs(x) ** p, axis=axis, keepdims=keepdim) ** (1.0 / p)


@wrap_op
def dist(x, y, p=2):
    d = x - y
    if p == 0:
        return jnp.sum((d != 0).astype(d.dtype)).astype(d.dtype)
    if p == float("inf"):
        return jnp.max(jnp.abs(d))
    if p == float("-inf"):
        return jnp.min(jnp.abs(d))
    return jnp.sum(jnp.abs(d) ** p) ** (1.0 / p)


cross = wrap_op(lambda x, y, axis=None: jnp.cross(x, y, axis=-1 if axis is None else axis), name="cross")
cholesky = wrap_op(lambda x, upper=False: jnp.linalg.cholesky(x) if not upper
                   else jnp.swapaxes(jnp.linalg.cholesky(x), -1, -2).conj(), name="cholesky")
inverse = wrap_op(jnp.linalg.inv, name="inverse")
pinv = wrap_op(lambda x, rcond=1e-15, hermitian=False: jnp.linalg.pinv(x, rtol=rcond, hermitian=hermitian), name="pinv")
matrix_power = wrap_op(jnp.linalg.matrix_power, name="matrix_power")
slogdet = wrap_op(lambda x: tuple(jnp.linalg.slogdet(x)), name="slogdet")
det = wrap_op(jnp.linalg.det, name="det")
solve = wrap_op(jnp.linalg.solve, name="solve")
lstsq = wrap_op(lambda x, y, rcond=None: tuple(jnp.linalg.lstsq(x, y, rcond=rcond)), name="lstsq")
qr = wrap_op(lambda x, mode="reduced": tuple(jnp.linalg.qr(x, mode=mode)), name="qr")
svd = wrap_op(lambda x, full_matrices=False: tuple(jnp.linalg.svd(x, full_matrices=full_matrices)), name="svd")
eig = wrap_op(lambda x: tuple(jnp.linalg.eig(x)), name="eig")
eigh = wrap_op(lambda x, UPLO="L": tuple(jnp.linalg.eigh(x, UPLO=UPLO)), name="eigh")
eigvals = wrap_op(jnp.linalg.eigvals, name="eigvals")
eigvalsh = wrap_op(jnp.linalg.eigvalsh, name="eigvalsh")
matrix_rank = wrap_op(lambda x, tol=None, hermitian=False: jnp.linalg.matrix_rank(x, rtol=tol), name="matrix_rank")
multi_dot = wrap_op(lambda xs: jnp.linalg.multi_dot(xs), name="multi_dot")
cond = wrap_op(lambda x, p=None: jnp.linalg.cond(x, p=p), name="cond")
trace = wrap_op(lambda x, offset=0, axis1=0, axis2=1: jnp.trace(x, offset=offset, axis1=axis1, axis2=axis2), name="trace")
triangular_solve = wrap_op(
    lambda x, y, upper=True, transpose=False, unitriangular=False:
    jax.scipy.linalg.solve_triangular(x, y, lower=not upper, trans=1 if transpose else 0,
                                      unit_diagonal=unitriangular),
    name="triangular_solve")
cholesky_solve = wrap_op(
    lambda x, y, upper=False: jax.scipy.linalg.cho_solve((y, not upper), x),
    name="cholesky_solve")
@wrap_op
def lu(x, pivot=True, get_infos=False):
    """reference: paddle.linalg.lu — returns the PACKED factorization
    (LU combined in one matrix, 1-based sequential-swap pivots, and info
    when get_infos=True), consumable by :func:`lu_unpack` (the round-trip
    P@L@U == x is test-asserted).  Previous revisions returned scipy-style
    (P, L, U), which broke the lu -> lu_unpack contract."""
    if not pivot:
        raise NotImplementedError(
            "lu(pivot=False): XLA's LU is always partial-pivoted; "
            "reconstruct with the returned pivots (lu_unpack)")
    packed, pivots, _perm = jax.lax.linalg.lu(x)
    pivots = pivots.astype(jnp.int32) + 1      # paddle pivots are 1-based
    if get_infos:
        return packed, pivots, jnp.zeros(x.shape[:-2], jnp.int32)
    return packed, pivots
corrcoef = wrap_op(lambda x, rowvar=True: jnp.corrcoef(x, rowvar=rowvar), name="corrcoef")
cov = wrap_op(lambda x, rowvar=True, ddof=True, fweights=None, aweights=None:
              jnp.cov(x, rowvar=rowvar, ddof=1 if ddof else 0,
                      fweights=fweights, aweights=aweights), name="cov")


@wrap_op
def histogram(x, bins=100, min=0, max=0):
    if min == 0 and max == 0:
        lo, hi = jnp.min(x), jnp.max(x)
    else:
        lo, hi = min, max
    hist, _ = jnp.histogram(x, bins=bins, range=(lo, hi))
    return hist


@wrap_op
def bincount(x, weights=None, minlength=0):
    return jnp.bincount(x, weights=weights, minlength=minlength,
                        length=None)
