"""Long-tail surface parity ops (reference: the remaining module-level
symbols of python/paddle/tensor/__init__.py — in-place function forms,
TensorArray helpers for static control flow, dtype predicates, printing).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import wrap_op
from ..core.tensor import Tensor

__all__ = [
    "add_n", "diagonal", "logit", "renorm", "lu_unpack", "broadcast_shape",
    "rank", "is_complex", "is_floating_point", "is_integer", "tolist",
    "set_printoptions", "check_shape", "create_array", "array_write",
    "array_read", "array_length",
    # module-level in-place forms (delegate to the Tensor methods)
    "add_", "subtract_", "divide_", "clip_", "ceil_", "exp_", "floor_", "reciprocal_",
    "round_", "rsqrt_", "sqrt_", "scale_", "tanh_", "erfinv_", "lerp_",
    "reshape_", "flatten_", "squeeze_", "unsqueeze_", "scatter_",
    "put_along_axis_", "uniform_", "exponential_",
]


@wrap_op
def add_n(inputs):
    """reference: paddle.add_n — elementwise sum of a tensor list."""
    total = inputs[0]
    for x in inputs[1:]:
        total = total + x
    return total


@wrap_op
def diagonal(x, offset=0, axis1=0, axis2=1):
    return jnp.diagonal(x, offset=offset, axis1=axis1, axis2=axis2)


@wrap_op
def logit(x, eps=None):
    if eps is not None:
        x = jnp.clip(x, eps, 1.0 - eps)
    return jnp.log(x / (1.0 - x))


@wrap_op
def renorm(x, p, axis, max_norm):
    """Per-slice p-norm clamp along ``axis`` (reference: paddle.renorm)."""
    moved = jnp.moveaxis(x, axis, 0)
    flat = moved.reshape(moved.shape[0], -1)
    norms = jnp.sum(jnp.abs(flat) ** p, axis=1) ** (1.0 / p)
    scale = jnp.where(norms > max_norm, max_norm / jnp.maximum(norms, 1e-12),
                      1.0)
    out = flat * scale[:, None]
    return jnp.moveaxis(out.reshape(moved.shape), 0, axis)


@wrap_op
def lu_unpack(x, y, unpack_ludata=True, unpack_pivots=True):
    """Unpack the packed LU factorization (reference: paddle.lu_unpack):
    x = packed LU (…, M, N), y = pivots (…, K).  Returns (P, L, U)."""
    m, n = x.shape[-2], x.shape[-1]
    k = min(m, n)
    lower = jnp.tril(x[..., :, :k], -1) + \
        jnp.eye(m, k, dtype=x.dtype)
    upper = jnp.triu(x[..., :k, :])
    # pivots (1-based sequential row swaps) -> permutation matrix
    piv = y.astype(jnp.int32) - 1

    def perm_of(pv):
        def body(i, perm):
            j = pv[i]
            pi = perm[i]
            pj = perm[j]
            perm = perm.at[i].set(pj)
            return perm.at[j].set(pi)
        return jax.lax.fori_loop(0, pv.shape[0], body, jnp.arange(m))

    if piv.ndim == 1:
        perm = perm_of(piv)
        p = jnp.eye(m, dtype=x.dtype)[perm].T
    else:
        batch = piv.reshape(-1, piv.shape[-1])
        perms = jax.vmap(perm_of)(batch)
        p = jnp.eye(m, dtype=x.dtype)[perms]
        p = jnp.swapaxes(p, -1, -2).reshape(x.shape[:-2] + (m, m))
    return p, lower, upper


def broadcast_shape(x_shape, y_shape):
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def rank(x):
    return Tensor(jnp.asarray(x._array.ndim if isinstance(x, Tensor)
                              else jnp.asarray(x).ndim, jnp.int32))


def is_complex(x):
    d = x._array.dtype if isinstance(x, Tensor) else jnp.asarray(x).dtype
    return jnp.issubdtype(d, jnp.complexfloating)


def is_floating_point(x):
    d = x._array.dtype if isinstance(x, Tensor) else jnp.asarray(x).dtype
    return jnp.issubdtype(d, jnp.floating)


def is_integer(x):
    d = x._array.dtype if isinstance(x, Tensor) else jnp.asarray(x).dtype
    return jnp.issubdtype(d, jnp.integer)


def tolist(x):
    return np.asarray(x._array if isinstance(x, Tensor) else x).tolist()


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """reference: paddle.set_printoptions — numpy printing drives repr."""
    kw = {}
    if precision is not None:
        kw["precision"] = precision
    if threshold is not None:
        kw["threshold"] = threshold
    if edgeitems is not None:
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    np.set_printoptions(**kw)


def check_shape(shape):
    """reference: tensor/creation.py check_shape — validate a shape arg."""
    if isinstance(shape, Tensor):
        return
    for s in shape:
        if not isinstance(s, (int, np.integer)) and s is not None:
            raise TypeError(f"shape entries must be ints, got {type(s)}")
        if s is not None and int(s) < -1:
            raise ValueError(f"shape entries must be >= -1, got {s}")


# -- TensorArray (reference: fluid LoDTensorArray + paddle.tensor.array_*;
# under trace these are the write/read ops of static control flow — here a
# plain Python list works both eagerly and as a scan carrier) -------------

def create_array(dtype="float32", initialized_list=None):
    return list(initialized_list or [])


def _array_index(i):
    """Accept python ints and scalar/shape-[1] int tensors (the reference's
    array ops take a shape-[1] int64 index variable)."""
    if isinstance(i, Tensor):
        i = i._array
    return int(np.asarray(i).reshape(-1)[0]) if hasattr(i, "shape") \
        and getattr(i, "ndim", 0) > 0 else int(i)


def array_write(x, i, array=None):
    if array is None:
        array = []
    i = _array_index(i)
    while len(array) <= i:
        array.append(None)
    array[i] = x
    return array


def array_read(array, i):
    return array[_array_index(i)]


def array_length(array):
    return Tensor(jnp.asarray(len(array), jnp.int64))


# -- module-level in-place forms --------------------------------------------

def _mk_inplace(method_name):
    def fn(x, *args, **kwargs):
        return getattr(x, method_name)(*args, **kwargs)
    fn.__name__ = method_name
    fn.__doc__ = f"Module-level form of Tensor.{method_name} (in-place)."
    return fn


add_ = _mk_inplace("add_")
subtract_ = _mk_inplace("subtract_")
divide_ = _mk_inplace("divide_")
clip_ = _mk_inplace("clip_")
ceil_ = _mk_inplace("ceil_")
exp_ = _mk_inplace("exp_")
floor_ = _mk_inplace("floor_")
reciprocal_ = _mk_inplace("reciprocal_")
round_ = _mk_inplace("round_")
rsqrt_ = _mk_inplace("rsqrt_")
sqrt_ = _mk_inplace("sqrt_")
scale_ = _mk_inplace("scale_")
tanh_ = _mk_inplace("tanh_")
erfinv_ = _mk_inplace("erfinv_")
lerp_ = _mk_inplace("lerp_")
reshape_ = _mk_inplace("reshape_")
flatten_ = _mk_inplace("flatten_")
squeeze_ = _mk_inplace("squeeze_")
unsqueeze_ = _mk_inplace("unsqueeze_")
scatter_ = _mk_inplace("scatter_")
put_along_axis_ = _mk_inplace("put_along_axis_")
uniform_ = _mk_inplace("uniform_")
exponential_ = _mk_inplace("exponential_")
