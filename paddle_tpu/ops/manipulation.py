"""Shape / layout / indexing ops
(reference surface: python/paddle/tensor/manipulation.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtype as _dt
from ..core.dispatch import call, wrap_op
from ..core.tensor import Tensor


def _static_shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(v) for v in np.asarray(shape._array))
    return tuple(int(s._array) if isinstance(s, Tensor) else int(s) for s in shape)


def reshape(x, shape):
    shape = _static_shape(shape)
    return call(lambda a: jnp.reshape(a, shape), x, name="reshape")


view = reshape


@wrap_op
def cast(x, dtype):
    return x.astype(_dt.convert_dtype(dtype))


def transpose(x, perm):
    perm = tuple(int(p) for p in perm)
    return call(lambda a: jnp.transpose(a, perm), x, name="transpose")


@wrap_op
def flatten(x, start_axis=0, stop_axis=-1):
    nd = x.ndim
    if nd == 0:
        return x.reshape((1,))
    start = start_axis % nd
    stop = stop_axis % nd
    new_shape = x.shape[:start] + (-1,) + x.shape[stop + 1:]
    return jnp.reshape(x, new_shape)


@wrap_op
def squeeze(x, axis=None):
    if axis is None:
        return jnp.squeeze(x)
    if isinstance(axis, (list, tuple)):
        axis = tuple(a for a in axis if x.shape[a] == 1)
        return jnp.squeeze(x, axis=axis) if axis else x
    if x.shape[axis] != 1:
        return x
    return jnp.squeeze(x, axis=axis)


@wrap_op
def unsqueeze(x, axis):
    if isinstance(axis, (list, tuple)):
        for a in sorted(axis):
            x = jnp.expand_dims(x, a)
        return x
    return jnp.expand_dims(x, int(axis))


def concat(x, axis=0):
    axis = int(axis) if not isinstance(axis, Tensor) else int(axis._array)
    return call(lambda arrs: jnp.concatenate(arrs, axis=axis), list(x), name="concat")


def stack(x, axis=0):
    return call(lambda arrs: jnp.stack(arrs, axis=axis), list(x), name="stack")


def split(x, num_or_sections, axis=0):
    axis = int(axis) if not isinstance(axis, Tensor) else int(axis._array)
    if isinstance(num_or_sections, int):
        n = num_or_sections
        return list(call(lambda a: tuple(jnp.split(a, n, axis=axis)), x, name="split"))
    sections = [int(s._array) if isinstance(s, Tensor) else int(s) for s in num_or_sections]
    dim = None
    # allow one -1 section
    if -1 in sections:
        known = sum(s for s in sections if s != -1)
        total = x.shape[axis]
        sections = [s if s != -1 else total - known for s in sections]
    offsets = np.cumsum(sections)[:-1].tolist()
    return list(call(lambda a: tuple(jnp.split(a, offsets, axis=axis)), x, name="split"))


def chunk(x, chunks, axis=0):
    return split(x, chunks, axis)


def unbind(x, axis=0):
    n = x.shape[axis]
    return list(call(lambda a: tuple(jnp.moveaxis(a, axis, 0)[i] for i in range(n)),
                     x, name="unbind"))


unstack = unbind


@wrap_op
def tile(x, repeat_times):
    return jnp.tile(x, tuple(int(r) for r in repeat_times))


def expand(x, shape):
    shape = _static_shape(shape)
    shape = tuple(x.shape[i - (len(shape) - x.ndim)] if s in (-1,) else s
                  for i, s in enumerate(shape))
    return call(lambda a: jnp.broadcast_to(a, shape), x, name="expand")


def expand_as(x, y):
    return expand(x, y.shape)


broadcast_to = expand


def broadcast_tensors(inputs):
    shapes = [tuple(t.shape) for t in inputs]
    out_shape = np.broadcast_shapes(*shapes)
    return [expand(t, out_shape) for t in inputs]


@wrap_op
def flip(x, axis):
    if isinstance(axis, int):
        axis = [axis]
    return jnp.flip(x, axis=tuple(axis))


@wrap_op
def roll(x, shifts, axis=None):
    return jnp.roll(x, shifts, axis=axis)


@wrap_op
def rot90(x, k=1, axes=(0, 1)):
    return jnp.rot90(x, k=k, axes=tuple(axes))


@wrap_op
def moveaxis(x, source, destination):
    return jnp.moveaxis(x, source, destination)


@wrap_op
def swapaxes(x, axis0, axis1):
    return jnp.swapaxes(x, axis0, axis1)


# -- gather / scatter family -------------------------------------------------


@wrap_op
def gather(x, index, axis=0):
    index = index.reshape(-1) if index.ndim > 1 else index
    return jnp.take(x, index, axis=axis)


@wrap_op
def gather_nd(x, index):
    return x[tuple(jnp.moveaxis(index, -1, 0))]


@wrap_op
def scatter(x, index, updates, overwrite=True):
    index = index.reshape(-1) if index.ndim > 1 else index
    if overwrite:
        return x.at[index].set(updates)
    # paddle: non-overwrite zeroes target rows then adds
    zeroed = x.at[index].set(jnp.zeros_like(updates))
    return zeroed.at[index].add(updates)


@wrap_op
def scatter_nd_add(x, index, updates):
    return x.at[tuple(jnp.moveaxis(index, -1, 0))].add(updates)


@wrap_op
def scatter_nd(index, updates, shape):
    zeros = jnp.zeros(tuple(shape), updates.dtype)
    return zeros.at[tuple(jnp.moveaxis(index, -1, 0))].add(updates)


@wrap_op
def index_select(x, index, axis=0):
    return jnp.take(x, index.reshape(-1), axis=axis)


@wrap_op
def index_sample(x, index):
    return jnp.take_along_axis(x, index, axis=1)


@wrap_op
def index_add(x, index, axis, value):
    return jnp.apply_along_axis  # placeholder; replaced below


@wrap_op
def take_along_axis(x, indices, axis, broadcast=True):
    return jnp.take_along_axis(x, indices, axis=axis)


@wrap_op
def put_along_axis(x, indices, values, axis, reduce="assign"):
    if reduce == "assign":
        return jnp.put_along_axis(x, indices, values, axis=axis, inplace=False)
    idx = [jnp.arange(s).reshape([-1 if i == d else 1 for i in range(x.ndim)])
           for d, s in enumerate(indices.shape)]
    idx[axis] = indices
    idx = tuple(jnp.broadcast_to(i, indices.shape) for i in idx)
    if reduce == "add":
        return x.at[idx].add(values)
    if reduce == "multiply" or reduce == "mul":
        return x.at[idx].multiply(values)
    raise ValueError(f"unsupported reduce {reduce}")


@wrap_op
def masked_select(x, mask):
    # dynamic output shape — eager only (same restriction as reference to_static)
    return x[mask]


@wrap_op
def masked_fill(x, mask, value):
    v = value if not hasattr(value, "shape") else value
    return jnp.where(mask, jnp.asarray(v, x.dtype), x)


@wrap_op
def fill_diagonal(x, value, offset=0, wrap=False):
    n = min(x.shape[-2], x.shape[-1])
    idx = jnp.arange(n - abs(offset) if offset else n)
    if offset >= 0:
        return x.at[..., idx, idx + offset].set(value)
    return x.at[..., idx - offset, idx].set(value)


@wrap_op
def repeat_interleave(x, repeats, axis=None):
    return jnp.repeat(x, repeats, axis=axis)


@wrap_op
def slice(x, axes, starts, ends):
    slices = [jnp.s_[:]] * x.ndim
    for ax, st, en in zip(axes, starts, ends):
        slices[ax] = jnp.s_[int(st):int(en)]
    return x[tuple(slices)]


@wrap_op
def strided_slice(x, axes, starts, ends, strides):
    slices = [jnp.s_[:]] * x.ndim
    for ax, st, en, sd in zip(axes, starts, ends, strides):
        slices[ax] = jnp.s_[int(st):int(en):int(sd)]
    return x[tuple(slices)]


def shard_index(x, index_num, nshards, shard_id, ignore_value=-1):
    shard_size = (index_num + nshards - 1) // nshards
    return call(
        lambda a: jnp.where(
            (a // shard_size) == shard_id, a % shard_size, ignore_value),
        x, name="shard_index")


@wrap_op
def crop(x, shape, offsets=None):
    if offsets is None:
        offsets = [0] * x.ndim
    slices = tuple(jnp.s_[int(o):int(o) + int(s)] for o, s in zip(offsets, shape))
    return x[slices]


def numel(x):
    return Tensor(jnp.asarray(int(np.prod(x.shape)) if all(isinstance(s, int) for s in x.shape) else x._array.size, jnp.int64))


def shape(x):
    return Tensor(jnp.asarray(x.shape, jnp.int32))


@wrap_op
def unfold(x, kernel_size, strides=1, paddings=0, dilations=1):
    # im2col over NCHW — XLA pattern: extract patches via conv_general_dilated_patches
    ks = kernel_size if isinstance(kernel_size, (list, tuple)) else [kernel_size] * 2
    st = strides if isinstance(strides, (list, tuple)) else [strides] * 2
    pd = paddings if isinstance(paddings, (list, tuple)) else [paddings] * 2
    dl = dilations if isinstance(dilations, (list, tuple)) else [dilations] * 2
    if len(pd) == 2:
        pd = [pd[0], pd[0], pd[1], pd[1]]
    patches = jax.lax.conv_general_dilated_patches(
        x, filter_shape=tuple(ks), window_strides=tuple(st),
        padding=[(pd[0], pd[1]), (pd[2], pd[3])],
        rhs_dilation=tuple(dl), dimension_numbers=("NCHW", "OIHW", "NCHW"))
    n, ckk, oh, ow = patches.shape
    return patches.reshape(n, ckk, oh * ow)


# -- python-level indexing ----------------------------------------------------

def getitem(x, idx):
    return call(lambda a, i: a[i], x, _normalize_index(idx), name="getitem")


def _normalize_index(idx):
    # tuples flatten fine through the dispatch pytree walk; Tensors inside get
    # unwrapped to arrays automatically
    return idx


def setitem(x, idx, value):
    from ..core.dispatch import assign_inplace, shadow
    out = call(lambda a, i, v: a.at[i].set(v), shadow(x),
               _normalize_index(idx), value, name="setitem")
    return assign_inplace(x, out)


# fix placeholder
def index_add(x, index, axis, value):  # noqa: F811
    return call(lambda a, i, v: a.at[tuple(
        jnp.s_[:] if d != axis else i for d in range(a.ndim))].add(v),
        x, index, value, name="index_add")


def index_put(x, indices, value, accumulate=False):
    def raw(a, idx_t, v):
        idx_t = tuple(idx_t)
        if accumulate:
            return a.at[idx_t].add(v)
        return a.at[idx_t].set(v)
    return call(raw, x, tuple(indices), value, name="index_put")


def as_strided(x, shape, stride, offset=0):
    def raw(a):
        flat = a.reshape(-1)[offset:]
        idx = np.zeros(tuple(shape), dtype=np.int64)
        for d, (s, st) in enumerate(zip(shape, stride)):
            rng = np.arange(s) * st
            idx = idx + rng.reshape([-1 if i == d else 1 for i in range(len(shape))])
        return flat[idx]
    return call(raw, x, name="as_strided")
