"""Reductions (reference surface: python/paddle/tensor/math.py sum/mean/...,
stat.py std/var/median, logic.py all/any)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dispatch import wrap_op


def _norm_axis(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


@wrap_op
def sum(x, axis=None, dtype=None, keepdim=False):
    return jnp.sum(x, axis=_norm_axis(axis), dtype=dtype, keepdims=keepdim)


@wrap_op
def mean(x, axis=None, keepdim=False):
    return jnp.mean(x, axis=_norm_axis(axis), keepdims=keepdim)


@wrap_op
def max(x, axis=None, keepdim=False):
    return jnp.max(x, axis=_norm_axis(axis), keepdims=keepdim)


@wrap_op
def min(x, axis=None, keepdim=False):
    return jnp.min(x, axis=_norm_axis(axis), keepdims=keepdim)


@wrap_op
def amax(x, axis=None, keepdim=False):
    return jnp.max(x, axis=_norm_axis(axis), keepdims=keepdim)


@wrap_op
def amin(x, axis=None, keepdim=False):
    return jnp.min(x, axis=_norm_axis(axis), keepdims=keepdim)


@wrap_op
def prod(x, axis=None, keepdim=False, dtype=None):
    return jnp.prod(x, axis=_norm_axis(axis), dtype=dtype, keepdims=keepdim)


@wrap_op
def std(x, axis=None, unbiased=True, keepdim=False):
    return jnp.std(x, axis=_norm_axis(axis), ddof=1 if unbiased else 0,
                   keepdims=keepdim)


@wrap_op
def var(x, axis=None, unbiased=True, keepdim=False):
    return jnp.var(x, axis=_norm_axis(axis), ddof=1 if unbiased else 0,
                   keepdims=keepdim)


@wrap_op
def median(x, axis=None, keepdim=False):
    return jnp.median(x, axis=_norm_axis(axis), keepdims=keepdim)


@wrap_op
def nanmedian(x, axis=None, keepdim=False):
    return jnp.nanmedian(x, axis=_norm_axis(axis), keepdims=keepdim)


@wrap_op
def nansum(x, axis=None, dtype=None, keepdim=False):
    return jnp.nansum(x, axis=_norm_axis(axis), dtype=dtype, keepdims=keepdim)


@wrap_op
def nanmean(x, axis=None, keepdim=False):
    return jnp.nanmean(x, axis=_norm_axis(axis), keepdims=keepdim)


@wrap_op
def all(x, axis=None, keepdim=False):
    return jnp.all(x, axis=_norm_axis(axis), keepdims=keepdim)


@wrap_op
def any(x, axis=None, keepdim=False):
    return jnp.any(x, axis=_norm_axis(axis), keepdims=keepdim)


@wrap_op
def count_nonzero(x, axis=None, keepdim=False):
    return jnp.count_nonzero(x, axis=_norm_axis(axis), keepdims=keepdim).astype(jnp.int64)


@wrap_op
def logsumexp(x, axis=None, keepdim=False):
    return jax.scipy.special.logsumexp(x, axis=_norm_axis(axis), keepdims=keepdim)


@wrap_op
def quantile(x, q, axis=None, keepdim=False, interpolation="linear"):
    return jnp.quantile(x, jnp.asarray(q), axis=_norm_axis(axis),
                        keepdims=keepdim, method=interpolation)


@wrap_op
def nanquantile(x, q, axis=None, keepdim=False):
    return jnp.nanquantile(x, jnp.asarray(q), axis=_norm_axis(axis), keepdims=keepdim)
