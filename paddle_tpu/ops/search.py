"""Search / sort ops (reference surface: python/paddle/tensor/search.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dispatch import call, wrap_op
from ..core.tensor import Tensor


@wrap_op
def argmax(x, axis=None, keepdim=False, dtype="int64"):
    out = jnp.argmax(x, axis=axis, keepdims=keepdim if axis is not None else False)
    return out.astype(jnp.dtype(str(dtype)) if isinstance(dtype, str) else dtype)


@wrap_op
def argmin(x, axis=None, keepdim=False, dtype="int64"):
    out = jnp.argmin(x, axis=axis, keepdims=keepdim if axis is not None else False)
    return out.astype(jnp.dtype(str(dtype)) if isinstance(dtype, str) else dtype)


@wrap_op
def argsort(x, axis=-1, descending=False, stable=True):
    out = jnp.argsort(x, axis=axis, stable=stable, descending=descending)
    return out.astype(jnp.int64)


@wrap_op
def sort(x, axis=-1, descending=False):
    out = jnp.sort(x, axis=axis, descending=descending)
    return out


@wrap_op
def topk(x, k, axis=-1, largest=True, sorted=True):
    if isinstance(k, jnp.ndarray):
        k = int(k)
    axis_ = axis if axis >= 0 else x.ndim + axis
    moved = jnp.moveaxis(x, axis_, -1)
    if largest:
        vals, idx = jax.lax.top_k(moved, k)
    else:
        vals, idx = jax.lax.top_k(-moved, k)
        vals = -vals
    return (jnp.moveaxis(vals, -1, axis_), jnp.moveaxis(idx, -1, axis_).astype(jnp.int64))


@wrap_op
def kthvalue(x, k, axis=-1, keepdim=False):
    axis_ = axis if axis >= 0 else x.ndim + axis
    sorted_vals = jnp.sort(x, axis=axis_)
    sorted_idx = jnp.argsort(x, axis=axis_)
    vals = jnp.take(sorted_vals, k - 1, axis=axis_)
    idx = jnp.take(sorted_idx, k - 1, axis=axis_)
    if keepdim:
        vals = jnp.expand_dims(vals, axis_)
        idx = jnp.expand_dims(idx, axis_)
    return vals, idx.astype(jnp.int64)


@wrap_op
def mode(x, axis=-1, keepdim=False):
    axis_ = axis if axis >= 0 else x.ndim + axis
    moved = jnp.moveaxis(x, axis_, -1)          # (..., n)
    n = moved.shape[-1]
    # O(n^2) pairwise count — fine for the modest n this op sees
    counts = jnp.sum(moved[..., :, None] == moved[..., None, :], axis=-1)
    # break count ties toward the larger value (paddle semantics)
    score = counts.astype(jnp.float32) * (n + 1) + jnp.argsort(jnp.argsort(moved, axis=-1), axis=-1)
    pos = jnp.argmax(score, axis=-1)
    vals = jnp.take_along_axis(moved, pos[..., None], axis=-1)[..., 0]
    # index of the last occurrence of the modal value
    idx = (n - 1) - jnp.argmax(jnp.flip(moved == vals[..., None], axis=-1), axis=-1)
    if keepdim:
        vals = jnp.moveaxis(vals[..., None], -1, axis_)
        idx = jnp.moveaxis(idx[..., None], -1, axis_)
    return vals, idx.astype(jnp.int64)


@wrap_op
def where_raw(cond, x, y):
    return jnp.where(cond, x, y)


def where(condition, x=None, y=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    return where_raw(condition, x, y)


def nonzero(x, as_tuple=False):
    # dynamic shape — eager only
    import numpy as np
    arr = np.asarray(x._array if isinstance(x, Tensor) else x)
    nz = np.nonzero(arr)
    if as_tuple:
        return tuple(Tensor(jnp.asarray(v[:, None], jnp.int64)) for v in nz)
    return Tensor(jnp.asarray(np.stack(nz, axis=1), jnp.int64))


@wrap_op
def searchsorted(sorted_sequence, values, out_int32=False, right=False):
    out = jnp.searchsorted(sorted_sequence, values,
                           side="right" if right else "left")
    return out.astype(jnp.int32 if out_int32 else jnp.int64)


@wrap_op
def bucketize(x, sorted_sequence, out_int32=False, right=False):
    out = jnp.searchsorted(sorted_sequence, x, side="right" if right else "left")
    return out.astype(jnp.int32 if out_int32 else jnp.int64)


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64"):
    # dynamic shape — eager only (reference has the same static-graph caveat)
    import numpy as np
    arr = np.asarray(x._array if isinstance(x, Tensor) else x)
    res = np.unique(arr, return_index=return_index, return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if not isinstance(res, tuple):
        return Tensor(jnp.asarray(res))
    outs = [Tensor(jnp.asarray(r)) for r in res]
    return tuple(outs)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None):
    import numpy as np
    arr = np.asarray(x._array if isinstance(x, Tensor) else x)
    if axis is None:
        flat = arr.reshape(-1)
        keep = np.ones(len(flat), bool)
        keep[1:] = flat[1:] != flat[:-1]
        out = flat[keep]
    else:
        raise NotImplementedError("unique_consecutive with axis")
    outs = [Tensor(jnp.asarray(out))]
    if return_inverse:
        inv = np.cumsum(keep) - 1
        outs.append(Tensor(jnp.asarray(inv, np.int64)))
    if return_counts:
        idx = np.flatnonzero(keep)
        counts = np.diff(np.append(idx, len(flat)))
        outs.append(Tensor(jnp.asarray(counts, np.int64)))
    return outs[0] if len(outs) == 1 else tuple(outs)


def _running_argextreme(x, axis, is_max):
    """Index stream for cummax/cummin."""
    def raw(a):
        moved = jnp.moveaxis(a, axis, -1)
        n = moved.shape[-1]
        vals = jax.lax.cummax(moved, axis=moved.ndim - 1) if is_max \
            else jax.lax.cummin(moved, axis=moved.ndim - 1)
        hits = moved == vals
        idx = jnp.arange(n)
        run_idx = jax.lax.cummax(jnp.where(hits, idx, -1), axis=moved.ndim - 1)
        return jnp.moveaxis(run_idx, -1, axis).astype(jnp.int64)
    return call(raw, x, name="running_argextreme")


@wrap_op
def masked_scatter(x, mask, value):
    flat_value = value.reshape(-1)
    cnt = jnp.cumsum(mask.reshape(-1).astype(jnp.int32)) - 1
    gathered = flat_value[jnp.clip(cnt, 0, flat_value.shape[0] - 1)].reshape(x.shape)
    return jnp.where(mask, gathered, x)
