"""Functional tensor-op surface (the analogue of python/paddle/tensor/)."""
from ..core.tensor import to_tensor  # noqa: F401
from .creation import *  # noqa: F401,F403
from .creation import (arange, assign, clone, diag, empty, empty_like, eye,
                       full, full_like, linspace, meshgrid, one_hot, ones,
                       ones_like, tril, triu, zeros, zeros_like)
from .math import *  # noqa: F401,F403
from .reduction import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .comparison import *  # noqa: F401,F403
from .search import *  # noqa: F401,F403
from .random_ops import (bernoulli, binomial, gaussian, multinomial, normal,
                         poisson, rand, randint, randint_like, randn, randperm,
                         standard_normal, uniform)
from .extras import *  # noqa: F401,F403
# signal-processing ops (reference signal.py ops frame/overlap_add + the
# stft/istft compositions) — re-exported so they carry schema entries
from ..signal import frame, istft, overlap_add, stft  # noqa: F401
from . import methods as _methods

_methods.install()
