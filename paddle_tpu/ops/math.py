"""Elementwise & scalar math ops (reference surface: python/paddle/tensor/math.py).

Every op is a raw jax function wrapped for eager-tape dispatch; under a jit
trace the same functions run tape-free.  XLA fuses these elementwise chains
into surrounding matmuls/reductions — no hand-written fusion needed (the
analogue of the reference's elementwise CUDA kernel family,
paddle/phi/kernels/gpu/elementwise*.cu, comes free from the compiler).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dispatch import wrap_op
from ..core.tensor import Tensor

# -- binary ------------------------------------------------------------------

add = wrap_op(jnp.add, name="add")
subtract = wrap_op(jnp.subtract, name="subtract")
multiply = wrap_op(jnp.multiply, name="multiply")
divide = wrap_op(jnp.divide, name="divide")
mod = wrap_op(jnp.mod, name="mod")
remainder = mod
floor_mod = mod
floor_divide = wrap_op(jnp.floor_divide, name="floor_divide")
pow = wrap_op(jnp.power, name="pow")
maximum = wrap_op(jnp.maximum, name="maximum")
minimum = wrap_op(jnp.minimum, name="minimum")
fmax = wrap_op(jnp.fmax, name="fmax")
fmin = wrap_op(jnp.fmin, name="fmin")
atan2 = wrap_op(jnp.arctan2, name="atan2")
hypot = wrap_op(jnp.hypot, name="hypot")
gcd = wrap_op(jnp.gcd, name="gcd")
lcm = wrap_op(jnp.lcm, name="lcm")
heaviside = wrap_op(jnp.heaviside, name="heaviside")
copysign = wrap_op(jnp.copysign, name="copysign")
nextafter = wrap_op(jnp.nextafter, name="nextafter")
ldexp = wrap_op(jnp.ldexp, name="ldexp")
logaddexp = wrap_op(jnp.logaddexp, name="logaddexp")
inner = wrap_op(jnp.inner, name="inner")
outer = wrap_op(jnp.outer, name="outer")
kron = wrap_op(jnp.kron, name="kron")


@wrap_op
def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None):
    scale = jnp.asarray(scale, x.dtype) if not hasattr(scale, "dtype") else scale.astype(x.dtype)
    if bias_after_scale:
        return x * scale + jnp.asarray(bias, x.dtype)
    return (x + jnp.asarray(bias, x.dtype)) * scale



# -- unary -------------------------------------------------------------------

exp = wrap_op(jnp.exp, name="exp")
expm1 = wrap_op(jnp.expm1, name="expm1")
log = wrap_op(jnp.log, name="log")
log2 = wrap_op(jnp.log2, name="log2")
log10 = wrap_op(jnp.log10, name="log10")
log1p = wrap_op(jnp.log1p, name="log1p")
sqrt = wrap_op(jnp.sqrt, name="sqrt")
rsqrt = wrap_op(jax.lax.rsqrt, name="rsqrt")
abs = wrap_op(jnp.abs, name="abs")
neg = wrap_op(jnp.negative, name="neg")
sign = wrap_op(jnp.sign, name="sign")
sgn = sign
reciprocal = wrap_op(jnp.reciprocal, name="reciprocal")
square = wrap_op(jnp.square, name="square")
floor = wrap_op(jnp.floor, name="floor")
ceil = wrap_op(jnp.ceil, name="ceil")
round = wrap_op(jnp.round, name="round")
trunc = wrap_op(jnp.trunc, name="trunc")
frac = wrap_op(lambda x: x - jnp.trunc(x), name="frac")
sin = wrap_op(jnp.sin, name="sin")
cos = wrap_op(jnp.cos, name="cos")
tan = wrap_op(jnp.tan, name="tan")
asin = wrap_op(jnp.arcsin, name="asin")
acos = wrap_op(jnp.arccos, name="acos")
atan = wrap_op(jnp.arctan, name="atan")
sinh = wrap_op(jnp.sinh, name="sinh")
cosh = wrap_op(jnp.cosh, name="cosh")
tanh = wrap_op(jnp.tanh, name="tanh")
asinh = wrap_op(jnp.arcsinh, name="asinh")
acosh = wrap_op(jnp.arccosh, name="acosh")
atanh = wrap_op(jnp.arctanh, name="atanh")
erf = wrap_op(jax.lax.erf, name="erf")
erfinv = wrap_op(jax.lax.erf_inv, name="erfinv")
sigmoid = wrap_op(jax.nn.sigmoid, name="sigmoid")
digamma = wrap_op(jax.scipy.special.digamma, name="digamma")
lgamma = wrap_op(jax.scipy.special.gammaln, name="lgamma")
gamma = wrap_op(lambda x: jnp.exp(jax.scipy.special.gammaln(x)), name="gamma")
i0 = wrap_op(jax.scipy.special.i0, name="i0")
i1 = wrap_op(jax.scipy.special.i1, name="i1")
rad2deg = wrap_op(jnp.rad2deg, name="rad2deg")
deg2rad = wrap_op(jnp.deg2rad, name="deg2rad")
angle = wrap_op(jnp.angle, name="angle")
conj = wrap_op(jnp.conj, name="conj")
exponent = wrap_op(lambda x: jnp.frexp(x)[1].astype(jnp.int32), name="exponent")


@wrap_op
def clip(x, min=None, max=None):
    return jnp.clip(x, min, max)


@wrap_op
def lerp(x, y, weight):
    return x + weight * (y - x)


@wrap_op
def nan_to_num(x, nan=0.0, posinf=None, neginf=None):
    return jnp.nan_to_num(x, nan=nan, posinf=posinf, neginf=neginf)


isnan = wrap_op(jnp.isnan, name="isnan")
isinf = wrap_op(jnp.isinf, name="isinf")
isfinite = wrap_op(jnp.isfinite, name="isfinite")
isreal = wrap_op(jnp.isreal, name="isreal")

# -- scan-style --------------------------------------------------------------


@wrap_op
def cumsum(x, axis=None, dtype=None):
    return jnp.cumsum(x, axis=axis, dtype=dtype)


@wrap_op
def cumprod(x, dim=None, dtype=None):
    return jnp.cumprod(x, axis=dim, dtype=dtype)


@wrap_op
def cummax_values(x, axis):
    return jax.lax.cummax(x, axis=axis)


def cummax(x, axis=None):
    if axis is None:
        x = x.flatten()
        axis = 0
    vals = cummax_values(x, axis)
    from . import comparison, search
    idx = search._running_argextreme(x, axis, True)
    return vals, idx


def cummin(x, axis=None):
    if axis is None:
        x = x.flatten()
        axis = 0
    vals = wrap_op(lambda a: jax.lax.cummin(a, axis=axis), name="cummin")(x)
    from . import search
    idx = search._running_argextreme(x, axis, False)
    return vals, idx


@wrap_op
def logcumsumexp(x, axis=None):
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    return jax.lax.cumlogsumexp(x, axis=axis)


@wrap_op
def diff(x, n=1, axis=-1, prepend=None, append=None):
    return jnp.diff(x, n=n, axis=axis, prepend=prepend, append=append)


@wrap_op
def trapezoid(y, x=None, dx=None, axis=-1):
    if dx is None and x is None:
        dx = 1.0
    if x is not None:
        return jax.scipy.integrate.trapezoid(y, x=x, axis=axis)
    return jax.scipy.integrate.trapezoid(y, dx=dx, axis=axis)


# -- misc --------------------------------------------------------------------


@wrap_op
def addmm(input, x, y, beta=1.0, alpha=1.0):
    return beta * input + alpha * (x @ y)


@wrap_op
def multiplex(inputs, index):
    stacked = jnp.stack(inputs, axis=0)
    idx = index.reshape(-1)
    return stacked[idx, jnp.arange(stacked.shape[1])]


def increment(x, value=1.0):
    x._array = x._array + jnp.asarray(value, x._array.dtype)
    return x


@wrap_op
def stanh(x, scale_a=0.67, scale_b=1.7159):
    return scale_b * jnp.tanh(scale_a * x)


@wrap_op
def polygamma(x, n):
    return jax.scipy.special.polygamma(n, x)
