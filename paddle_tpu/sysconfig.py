"""paddle.sysconfig — build-config introspection (reference:
python/paddle/sysconfig.py get_include/get_lib)."""
import os

__all__ = ["get_include", "get_lib"]

_PKG_DIR = os.path.dirname(os.path.abspath(__file__))


def get_include():
    """Directory containing the framework's C headers (the csrc shim ABI
    used by utils.cpp_extension custom ops)."""
    return os.path.join(os.path.dirname(_PKG_DIR), "csrc")


def get_lib():
    """Directory containing the framework's native shared libraries (built
    on demand by core.native / utils.cpp_extension)."""
    return os.path.join(os.path.dirname(_PKG_DIR), "csrc", "build")
