"""paddle.distribution.transform — differentiable bijections of random
variables (reference: python/paddle/distribution/transform.py:59 Transform
and its 12 concrete subclasses).

TPU-native: the math is jnp (traced, autodiff-safe); the API speaks
Tensors.  ``t(distribution)`` builds a TransformedDistribution, ``t(other
transform)`` composes a ChainTransform — the reference's __call__
dispatch."""
from __future__ import annotations

import enum
import functools
import math
import operator

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = [
    "Transform", "AbsTransform", "AffineTransform", "ChainTransform",
    "ExpTransform", "IndependentTransform", "PowerTransform",
    "ReshapeTransform", "SigmoidTransform", "SoftmaxTransform",
    "StackTransform", "StickBreakingTransform", "TanhTransform",
]


def _arr(x):
    return x._array if isinstance(x, Tensor) else jnp.asarray(x)


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))


class Type(enum.Enum):
    """Mapping type of a transformation (reference transform.py:44)."""
    BIJECTION = "bijection"     # bijective: one-to-one and onto
    INJECTION = "injection"     # one-to-one
    SURJECTION = "surjection"   # onto
    OTHER = "other"

    @classmethod
    def is_injective(cls, t):
        return t in (cls.BIJECTION, cls.INJECTION)


class _Domain:
    """Light rendering of the reference's variable.Variable: just the
    event_rank and a name (constraint checking is the caller's job under
    XLA's static world)."""

    def __init__(self, event_rank=0, name="real"):
        self.event_rank = int(event_rank)
        self.name = name

    def __repr__(self):
        return "_Domain(%s, event_rank=%d)" % (self.name, self.event_rank)


real = _Domain(0, "real")
positive = _Domain(0, "positive")


class Transform:
    r"""Base class (reference transform.py:59): subclasses implement
    ``_forward``/``_inverse``/``_forward_log_det_jacobian`` (and the shape
    methods when the shape changes)."""

    _type = Type.INJECTION

    @classmethod
    def _is_injective(cls):
        return Type.is_injective(cls._type)

    def __call__(self, input):
        from . import Distribution
        from .transformed_distribution import TransformedDistribution
        if isinstance(input, Distribution):
            return TransformedDistribution(input, [self])
        if isinstance(input, Transform):
            return ChainTransform([self, input])
        return self.forward(_t(input))

    # -- public API ---------------------------------------------------------
    def forward(self, x):
        """y = f(x)."""
        return _t(self._forward(_arr(x)))

    def inverse(self, y):
        """x = f^{-1}(y)."""
        return _t(self._inverse(_arr(y)))

    def forward_log_det_jacobian(self, x):
        """log|det J_f(x)|."""
        a = _arr(x)
        if hasattr(type(self), "_forward_log_det_jacobian") and \
                type(self)._forward_log_det_jacobian is not \
                Transform._forward_log_det_jacobian:
            return _t(self._forward_log_det_jacobian(a))
        if type(self)._inverse_log_det_jacobian is not \
                Transform._inverse_log_det_jacobian:
            return _t(-self._inverse_log_det_jacobian(self._forward(a)))
        raise NotImplementedError(
            "Neither _forward_log_det_jacobian nor "
            "_inverse_log_det_jacobian is implemented.")

    def inverse_log_det_jacobian(self, y):
        """log|det J_{f^{-1}}(y)| = -forward_log_det_jacobian(f^{-1}(y))."""
        a = _arr(y)
        if type(self)._inverse_log_det_jacobian is not \
                Transform._inverse_log_det_jacobian:
            return _t(self._inverse_log_det_jacobian(a))
        return _t(-_arr(self.forward_log_det_jacobian(self._inverse(a))))

    def forward_shape(self, shape):
        return self._forward_shape(tuple(shape))

    def inverse_shape(self, shape):
        return self._inverse_shape(tuple(shape))

    @property
    def _domain(self):
        return real

    @property
    def _codomain(self):
        return real

    # -- subclass hooks -----------------------------------------------------
    def _forward(self, x):
        raise NotImplementedError

    def _inverse(self, y):
        raise NotImplementedError

    def _forward_log_det_jacobian(self, x):
        raise NotImplementedError

    def _inverse_log_det_jacobian(self, y):
        raise NotImplementedError

    def _forward_shape(self, shape):
        return shape

    def _inverse_shape(self, shape):
        return shape


class AbsTransform(Transform):
    r"""y = |x| (reference transform.py:327).  Non-injective: ``inverse``
    returns the positive preimage; log-det is undefined."""
    _type = Type.SURJECTION

    def _forward(self, x):
        return jnp.abs(x)

    def _inverse(self, y):
        return y

    @property
    def _codomain(self):
        return positive


class AffineTransform(Transform):
    r"""y = loc + scale * x (reference transform.py:399)."""
    _type = Type.BIJECTION

    def __init__(self, loc, scale):
        self._loc = _arr(loc)
        self._scale = _arr(scale)

    @property
    def loc(self):
        return _t(self._loc)

    @property
    def scale(self):
        return _t(self._scale)

    def _forward(self, x):
        return self._loc + self._scale * x

    def _inverse(self, y):
        return (y - self._loc) / self._scale

    def _forward_log_det_jacobian(self, x):
        return jnp.broadcast_to(jnp.log(jnp.abs(self._scale)), x.shape)


class ExpTransform(Transform):
    r"""y = exp(x) (reference transform.py:600)."""
    _type = Type.BIJECTION

    def _forward(self, x):
        return jnp.exp(x)

    def _inverse(self, y):
        return jnp.log(y)

    def _forward_log_det_jacobian(self, x):
        return x

    @property
    def _codomain(self):
        return positive


class PowerTransform(Transform):
    r"""y = x^power over the positive reals (reference transform.py:740)."""
    _type = Type.BIJECTION

    def __init__(self, power):
        self._power = _arr(power)

    @property
    def power(self):
        return _t(self._power)

    def _forward(self, x):
        return jnp.power(x, self._power)

    def _inverse(self, y):
        return jnp.power(y, 1.0 / self._power)

    def _forward_log_det_jacobian(self, x):
        return jnp.log(jnp.abs(self._power * jnp.power(x, self._power - 1)))

    @property
    def _domain(self):
        return positive

    @property
    def _codomain(self):
        return positive


class SigmoidTransform(Transform):
    r"""y = 1/(1+exp(-x)) (reference transform.py:910)."""
    _type = Type.BIJECTION

    def _forward(self, x):
        return jax.nn.sigmoid(x)

    def _inverse(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def _forward_log_det_jacobian(self, x):
        return -jax.nn.softplus(-x) - jax.nn.softplus(x)

    @property
    def _codomain(self):
        return _Domain(0, "unit_interval")


class TanhTransform(Transform):
    r"""y = tanh(x) (reference transform.py:1178)."""
    _type = Type.BIJECTION

    def _forward(self, x):
        return jnp.tanh(x)

    def _inverse(self, y):
        return jnp.arctanh(y)

    def _forward_log_det_jacobian(self, x):
        # numerically-stable log(1 - tanh^2): 2(log2 - x - softplus(-2x))
        return 2.0 * (jnp.log(2.0) - x - jax.nn.softplus(-2.0 * x))

    @property
    def _codomain(self):
        return _Domain(0, "interval(-1, 1)")


class SoftmaxTransform(Transform):
    r"""y = softmax over the last axis (reference transform.py:953).
    Not injective (softmax is shift-invariant): no log-det."""
    _type = Type.OTHER

    def _forward(self, x):
        return jax.nn.softmax(x, axis=-1)

    def _inverse(self, y):
        return jnp.log(y)

    @property
    def _domain(self):
        return _Domain(1, "real_vector")

    @property
    def _codomain(self):
        return _Domain(1, "simplex")


class StickBreakingTransform(Transform):
    r"""Unconstrained R^K -> (K+1)-simplex via stick-breaking (reference
    transform.py:1114)."""
    _type = Type.BIJECTION

    def _forward(self, x):
        offset = x.shape[-1] + 1 - jnp.arange(1, x.shape[-1] + 1)
        z = jax.nn.sigmoid(x - jnp.log(offset.astype(x.dtype)))
        zp_cumprod = jnp.cumprod(1 - z, axis=-1)
        pad_width = [(0, 0)] * (x.ndim - 1) + [(0, 1)]
        z_padded = jnp.pad(z, pad_width, constant_values=1.0)
        pad_width = [(0, 0)] * (x.ndim - 1) + [(1, 0)]
        zp_padded = jnp.pad(zp_cumprod, pad_width, constant_values=1.0)
        return z_padded * zp_padded

    def _inverse(self, y):
        y_crop = y[..., :-1]
        offset = y.shape[-1] - jnp.arange(1, y_crop.shape[-1] + 1)
        sf = 1.0 - jnp.cumsum(y_crop, axis=-1)
        x = jnp.log(y_crop / sf) + jnp.log(offset.astype(y.dtype))
        return x

    def _forward_log_det_jacobian(self, x):
        # triangular Jacobian: log|det| = sum_k(-x'_k + logsigmoid(x'_k)
        # + log y_k), x' = x - log(offset) — the log1p(-z)=logsigmoid(-x')
        # identity keeps it stable
        offset = x.shape[-1] + 1 - jnp.arange(1, x.shape[-1] + 1)
        xs = x - jnp.log(offset.astype(x.dtype))
        y = self._forward(x)
        return jnp.sum(-xs + jax.nn.log_sigmoid(xs)
                       + jnp.log(y[..., :-1]), axis=-1)

    def _forward_shape(self, shape):
        if not shape:
            raise ValueError("StickBreakingTransform needs rank >= 1")
        return shape[:-1] + (shape[-1] + 1,)

    def _inverse_shape(self, shape):
        if not shape or shape[-1] < 2:
            raise ValueError("inverse_shape needs last dim >= 2")
        return shape[:-1] + (shape[-1] - 1,)

    @property
    def _domain(self):
        return _Domain(1, "real_vector")

    @property
    def _codomain(self):
        return _Domain(1, "simplex")


class ReshapeTransform(Transform):
    r"""Reshape the event shape (reference transform.py:803)."""
    _type = Type.BIJECTION

    def __init__(self, in_event_shape, out_event_shape):
        in_event_shape = tuple(in_event_shape)
        out_event_shape = tuple(out_event_shape)
        if functools.reduce(operator.mul, in_event_shape, 1) != \
                functools.reduce(operator.mul, out_event_shape, 1):
            raise ValueError(
                "in_event_shape %r and out_event_shape %r have different "
                "sizes" % (in_event_shape, out_event_shape))
        self._in_event_shape = in_event_shape
        self._out_event_shape = out_event_shape

    @property
    def in_event_shape(self):
        return self._in_event_shape

    @property
    def out_event_shape(self):
        return self._out_event_shape

    def _forward(self, x):
        batch = x.shape[:x.ndim - len(self._in_event_shape)]
        return x.reshape(batch + self._out_event_shape)

    def _inverse(self, y):
        batch = y.shape[:y.ndim - len(self._out_event_shape)]
        return y.reshape(batch + self._in_event_shape)

    def _forward_log_det_jacobian(self, x):
        batch = x.shape[:x.ndim - len(self._in_event_shape)]
        return jnp.zeros(batch, x.dtype)

    def _forward_shape(self, shape):
        n = len(self._in_event_shape)
        if len(shape) < n or tuple(shape[len(shape) - n:]) != \
                self._in_event_shape:
            raise ValueError("shape %r does not end in in_event_shape %r"
                             % (shape, self._in_event_shape))
        return tuple(shape[:len(shape) - n]) + self._out_event_shape

    def _inverse_shape(self, shape):
        n = len(self._out_event_shape)
        if len(shape) < n or tuple(shape[len(shape) - n:]) != \
                self._out_event_shape:
            raise ValueError("shape %r does not end in out_event_shape %r"
                             % (shape, self._out_event_shape))
        return tuple(shape[:len(shape) - n]) + self._in_event_shape

    @property
    def _domain(self):
        return _Domain(len(self._in_event_shape), "real")

    @property
    def _codomain(self):
        return _Domain(len(self._out_event_shape), "real")


class IndependentTransform(Transform):
    r"""Reinterpret the rightmost ``reinterpreted_batch_rank`` batch dims
    as event dims: sums that many rightmost dims out of the base's
    log-det (reference transform.py:649)."""

    def __init__(self, base, reinterpreted_batch_rank):
        if not isinstance(base, Transform):
            raise TypeError("base must be a Transform")
        if reinterpreted_batch_rank <= 0:
            raise ValueError("reinterpreted_batch_rank must be positive")
        self._base = base
        self._reinterpreted_batch_rank = int(reinterpreted_batch_rank)
        self._type = base._type

    def _forward(self, x):
        return _arr(self._base.forward(x))

    def _inverse(self, y):
        return _arr(self._base.inverse(y))

    def _forward_log_det_jacobian(self, x):
        ldj = _arr(self._base.forward_log_det_jacobian(x))
        return jnp.sum(ldj, axis=tuple(
            range(-self._reinterpreted_batch_rank, 0)))

    def _forward_shape(self, shape):
        return self._base.forward_shape(shape)

    def _inverse_shape(self, shape):
        return self._base.inverse_shape(shape)

    @property
    def _domain(self):
        return _Domain(self._base._domain.event_rank
                       + self._reinterpreted_batch_rank,
                       self._base._domain.name)

    @property
    def _codomain(self):
        return _Domain(self._base._codomain.event_rank
                       + self._reinterpreted_batch_rank,
                       self._base._codomain.name)


class ChainTransform(Transform):
    r"""Composition f = f_n o ... o f_1 applied left-to-right (reference
    transform.py:476: forward applies in sequence order)."""

    def __init__(self, transforms):
        if not isinstance(transforms, (list, tuple)):
            raise TypeError("transforms must be a sequence of Transform")
        if not all(isinstance(t, Transform) for t in transforms):
            raise TypeError("All elements must be Transform instances")
        self.transforms = list(transforms)
        if not all(t._is_injective() for t in self.transforms):
            self._type = Type.OTHER
        else:
            self._type = Type.INJECTION

    def _is_injective(self):
        return Type.is_injective(self._type)

    def _forward(self, x):
        for t in self.transforms:
            x = _arr(t.forward(x))
        return x

    def _inverse(self, y):
        for t in reversed(self.transforms):
            y = _arr(t.inverse(y))
        return y

    def _forward_log_det_jacobian(self, x):
        value = 0.0
        event_rank = self._domain.event_rank
        for t in self.transforms:
            ldj = _arr(t.forward_log_det_jacobian(x))
            value = value + _sum_rightmost(
                ldj, event_rank - t._domain.event_rank)
            x = _arr(t.forward(x))
            event_rank += t._codomain.event_rank - t._domain.event_rank
        return value

    def _forward_shape(self, shape):
        for t in self.transforms:
            shape = t.forward_shape(shape)
        return shape

    def _inverse_shape(self, shape):
        for t in reversed(self.transforms):
            shape = t.inverse_shape(shape)
        return shape

    @property
    def _domain(self):
        rank = 0
        for t in reversed(self.transforms):
            rank = max(rank + t._domain.event_rank
                       - t._codomain.event_rank, t._domain.event_rank)
        return _Domain(rank, "chain")

    @property
    def _codomain(self):
        rank = 0
        for t in self.transforms:
            rank = max(rank + t._codomain.event_rank
                       - t._domain.event_rank, t._codomain.event_rank)
        return _Domain(rank, "chain")


class StackTransform(Transform):
    r"""Apply a sequence of transforms to slices along ``axis``
    (reference transform.py:1009)."""

    def __init__(self, transforms, axis=0):
        if not transforms or not all(
                isinstance(t, Transform) for t in transforms):
            raise TypeError("transforms must be non-empty Transforms")
        self._transforms = list(transforms)
        self._axis = int(axis)

    @property
    def transforms(self):
        return self._transforms

    @property
    def axis(self):
        return self._axis

    def _split(self, x):
        if x.shape[self._axis] != len(self._transforms):
            raise ValueError(
                "input size along axis %d (%d) must equal the number of "
                "transforms (%d)" % (self._axis, x.shape[self._axis],
                                     len(self._transforms)))
        return [jnp.squeeze(s, self._axis) for s in
                jnp.split(x, len(self._transforms), axis=self._axis)]

    def _forward(self, x):
        return jnp.stack([_arr(t.forward(s)) for t, s in
                          zip(self._transforms, self._split(x))],
                         axis=self._axis)

    def _inverse(self, y):
        return jnp.stack([_arr(t.inverse(s)) for t, s in
                          zip(self._transforms, self._split(y))],
                         axis=self._axis)

    def _forward_log_det_jacobian(self, x):
        return jnp.stack([_arr(t.forward_log_det_jacobian(s)) for t, s in
                          zip(self._transforms, self._split(x))],
                         axis=self._axis)


def _sum_rightmost(value, n):
    return jnp.sum(value, axis=tuple(range(-n, 0))) if n > 0 else value
