"""paddle.distribution.ExponentialFamily (reference:
python/paddle/distribution/exponential_family.py): entropy via the Bregman
divergence of the log-normalizer — the gradient comes from jax.grad
instead of the reference's paddle.grad tape."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["ExponentialFamily"]


def _arr(x):
    return x._array if isinstance(x, Tensor) else jnp.asarray(x)


class ExponentialFamily:
    """Mixin/base for distributions of the form
    f(x; theta) = exp(<t(x), theta> - F(theta) + k(x)).

    Subclasses provide ``_natural_parameters`` (tuple of Tensors),
    ``_log_normalizer(*naturals)`` and ``_mean_carrier_measure``."""

    @property
    def _natural_parameters(self):
        raise NotImplementedError

    def _log_normalizer(self, *natural_parameters):
        raise NotImplementedError

    @property
    def _mean_carrier_measure(self):
        raise NotImplementedError

    def entropy(self):
        """H = F(theta) - <theta, grad F(theta)> - E[k(x)] (Bregman —
        reference exponential_family.py entropy)."""
        naturals = [_arr(p) for p in self._natural_parameters]
        grads = jax.grad(lambda ps: jnp.sum(_arr(self._log_normalizer(
            *[Tensor(p) for p in ps]))))(tuple(naturals))
        log_norm = _arr(self._log_normalizer(
            *[Tensor(p) for p in naturals]))
        entropy_value = -jnp.asarray(self._mean_carrier_measure) + log_norm
        for p, g in zip(naturals, grads):
            term = p * g
            # natural params may carry event dims beyond the batch shape
            # (e.g. Dirichlet's concentration vector): <θ, ∇F> contracts
            # them
            extra = term.ndim - log_norm.ndim
            if extra > 0:
                term = jnp.sum(term, axis=tuple(range(-extra, 0)))
            entropy_value = entropy_value - term
        return Tensor(entropy_value)
