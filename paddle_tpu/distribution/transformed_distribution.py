"""paddle.distribution.TransformedDistribution (reference:
python/paddle/distribution/transformed_distribution.py:22): a base
distribution pushed through a sequence of Transforms."""
from __future__ import annotations

import typing

import jax.numpy as jnp

from ..core.tensor import Tensor
from . import transform as T
from .independent import Independent

__all__ = ["TransformedDistribution"]


def _arr(x):
    return x._array if isinstance(x, Tensor) else jnp.asarray(x)


def _sum_rightmost(value, n):
    return jnp.sum(value, axis=tuple(range(-n, 0))) if n > 0 else value


class TransformedDistribution:
    def __init__(self, base, transforms):
        from . import Distribution
        if not isinstance(base, (Distribution, Independent)):
            raise TypeError("Expected type of 'base' is Distribution, but "
                            "got %s." % type(base).__name__)
        if not isinstance(transforms, typing.Sequence):
            raise TypeError("Expected type of 'transforms' is "
                            "Sequence[Transform], but got %s."
                            % type(transforms).__name__)
        if not all(isinstance(t, T.Transform) for t in transforms):
            raise TypeError("All elements of transforms must be Transform.")
        chain = T.ChainTransform(list(transforms))
        base_shape = tuple(base.batch_shape) + tuple(base.event_shape)
        if len(base_shape) < chain._domain.event_rank:
            raise ValueError(
                "'base' needs to have shape with size at least %d, but got "
                "%d." % (chain._domain.event_rank, len(base_shape)))
        if chain._domain.event_rank > len(base.event_shape):
            base = Independent(
                base, chain._domain.event_rank - len(base.event_shape))
        self._base = base
        self._transforms = list(transforms)
        transformed_shape = chain.forward_shape(
            tuple(base.batch_shape) + tuple(base.event_shape))
        transformed_event_rank = chain._codomain.event_rank + \
            max(len(base.event_shape) - chain._domain.event_rank, 0)
        cut = len(transformed_shape) - transformed_event_rank
        self._batch_shape = tuple(transformed_shape[:cut])
        self._event_shape = tuple(transformed_shape[cut:])

    @property
    def batch_shape(self):
        return list(self._batch_shape)

    @property
    def event_shape(self):
        return list(self._event_shape)

    def sample(self, shape=()):
        x = self._base.sample(shape)
        for t in self._transforms:
            x = t.forward(x)
        return x

    def rsample(self, shape=()):
        x = self._base.rsample(shape)
        for t in self._transforms:
            x = t.forward(x)
        return x

    def log_prob(self, value):
        log_prob = 0.0
        y = _arr(value)
        event_rank = len(self.event_shape)
        for t in reversed(self._transforms):
            x = _arr(t.inverse(y))
            event_rank += t._domain.event_rank - t._codomain.event_rank
            log_prob = log_prob - _sum_rightmost(
                _arr(t.forward_log_det_jacobian(x)),
                event_rank - t._domain.event_rank)
            y = x
        log_prob = log_prob + _sum_rightmost(
            _arr(self._base.log_prob(Tensor(y))),
            event_rank - len(self._base.event_shape))
        return Tensor(log_prob)

    def prob(self, value):
        return Tensor(jnp.exp(_arr(self.log_prob(value))))
