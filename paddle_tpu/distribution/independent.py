"""paddle.distribution.Independent (reference:
python/paddle/distribution/independent.py:18): reinterpret rightmost batch
dims as event dims."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["Independent"]


def _arr(x):
    return x._array if isinstance(x, Tensor) else jnp.asarray(x)


class Independent:
    def __init__(self, base, reinterpreted_batch_rank):
        from . import Distribution
        if not isinstance(base, Distribution):
            raise TypeError("Expected type of 'base' is Distribution, but "
                            "got %s" % type(base).__name__)
        if not 0 < reinterpreted_batch_rank <= len(base.batch_shape):
            raise ValueError(
                "Expected 0 < reinterpreted_batch_rank <= %d, but got %d"
                % (len(base.batch_shape), reinterpreted_batch_rank))
        self._base = base
        self._reinterpreted_batch_rank = int(reinterpreted_batch_rank)
        shape = tuple(base.batch_shape) + tuple(base.event_shape)
        cut = len(base.batch_shape) - self._reinterpreted_batch_rank
        self._batch_shape = shape[:cut]
        self._event_shape = shape[cut:]

    @property
    def batch_shape(self):
        return list(self._batch_shape)

    @property
    def event_shape(self):
        return list(self._event_shape)

    @property
    def mean(self):
        return self._base.mean

    @property
    def variance(self):
        return self._base.variance

    def sample(self, shape=()):
        return self._base.sample(shape)

    def rsample(self, shape=()):
        return self._base.rsample(shape)

    def log_prob(self, value):
        return Tensor(self._sum_rightmost(
            _arr(self._base.log_prob(value)),
            self._reinterpreted_batch_rank))

    def prob(self, value):
        return Tensor(jnp.exp(_arr(self.log_prob(value))))

    def entropy(self):
        return Tensor(self._sum_rightmost(
            _arr(self._base.entropy()), self._reinterpreted_batch_rank))

    @staticmethod
    def _sum_rightmost(value, n):
        return jnp.sum(value, axis=tuple(range(-n, 0))) if n > 0 else value
