"""Probability distributions (reference surface: python/paddle/distribution/
— Normal/Uniform/Categorical/Beta/Dirichlet/Multinomial/... with
sample/log_prob/entropy/kl_divergence)."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..core import random as _rnd
from ..core.dispatch import call
from ..core.tensor import Tensor


def _arr(x):
    return x._array if isinstance(x, Tensor) else jnp.asarray(x, jnp.float32)


class Distribution:
    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return list(self._batch_shape)

    @property
    def event_shape(self):
        return list(self._event_shape)

    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        return self.sample(shape)

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return Tensor(jnp.exp(_arr(self.log_prob(value))))

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        return kl_divergence(self, other)


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    def sample(self, shape=()):
        shape = tuple(shape) + self._batch_shape
        eps = jax.random.normal(_rnd.next_key(), shape)
        return Tensor(self.loc + self.scale * eps)

    def log_prob(self, value):
        v = _arr(value)
        var = jnp.square(self.scale)
        return Tensor(-jnp.square(v - self.loc) / (2 * var)
                      - jnp.log(self.scale) - 0.5 * math.log(2 * math.pi))

    def entropy(self):
        return Tensor(0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(self.scale)
                      + jnp.zeros(self._batch_shape))

    @property
    def mean(self):
        return Tensor(jnp.broadcast_to(self.loc, self._batch_shape))

    @property
    def variance(self):
        return Tensor(jnp.broadcast_to(jnp.square(self.scale), self._batch_shape))

    @property
    def stddev(self):
        return Tensor(jnp.broadcast_to(self.scale, self._batch_shape))


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _arr(low)
        self.high = _arr(high)
        super().__init__(jnp.broadcast_shapes(self.low.shape, self.high.shape))

    def sample(self, shape=()):
        shape = tuple(shape) + self._batch_shape
        u = jax.random.uniform(_rnd.next_key(), shape)
        return Tensor(self.low + (self.high - self.low) * u)

    def log_prob(self, value):
        v = _arr(value)
        inside = (v >= self.low) & (v < self.high)
        lp = -jnp.log(self.high - self.low)
        return Tensor(jnp.where(inside, lp, -jnp.inf))

    def entropy(self):
        return Tensor(jnp.log(self.high - self.low) + jnp.zeros(self._batch_shape))


class Bernoulli(Distribution):
    def __init__(self, probs, name=None):
        self.probs = _arr(probs)
        super().__init__(self.probs.shape)

    def sample(self, shape=()):
        shape = tuple(shape) + self._batch_shape
        return Tensor(jax.random.bernoulli(
            _rnd.next_key(), self.probs, shape).astype(jnp.float32))

    def log_prob(self, value):
        v = _arr(value)
        p = jnp.clip(self.probs, 1e-7, 1 - 1e-7)
        return Tensor(v * jnp.log(p) + (1 - v) * jnp.log1p(-p))

    def entropy(self):
        p = jnp.clip(self.probs, 1e-7, 1 - 1e-7)
        return Tensor(-(p * jnp.log(p) + (1 - p) * jnp.log1p(-p)))

    @property
    def mean(self):
        return Tensor(self.probs)

    @property
    def variance(self):
        return Tensor(self.probs * (1 - self.probs))


class Categorical(Distribution):
    def __init__(self, logits, name=None):
        self.logits = _arr(logits)
        super().__init__(self.logits.shape[:-1])

    def sample(self, shape=()):
        shape = tuple(shape) + self._batch_shape
        return Tensor(jax.random.categorical(_rnd.next_key(),
                                             jnp.log(jnp.maximum(self.logits, 1e-30))
                                             if jnp.all(self.logits >= 0)
                                             else self.logits,
                                             shape=shape).astype(jnp.int64))

    def _log_pmf(self):
        # paddle Categorical accepts unnormalised positive weights
        logits = self.logits
        logits = jnp.where(jnp.all(logits >= 0), jnp.log(jnp.maximum(logits, 1e-30)), logits)
        return jax.nn.log_softmax(logits, axis=-1)

    def log_prob(self, value):
        v = _arr(value).astype(jnp.int32)
        lp = self._log_pmf()
        return Tensor(jnp.take_along_axis(lp, v[..., None], axis=-1)[..., 0])

    def probs(self, value):
        return Tensor(jnp.exp(_arr(self.log_prob(value))))

    def entropy(self):
        lp = self._log_pmf()
        return Tensor(-jnp.sum(jnp.exp(lp) * lp, axis=-1))


class Beta(Distribution):
    def __init__(self, alpha, beta):
        self.alpha = _arr(alpha)
        self.beta = _arr(beta)
        super().__init__(jnp.broadcast_shapes(self.alpha.shape, self.beta.shape))

    def sample(self, shape=()):
        shape = tuple(shape) + self._batch_shape
        return Tensor(jax.random.beta(_rnd.next_key(), self.alpha, self.beta,
                                      shape))

    def log_prob(self, value):
        v = _arr(value)
        a, b = self.alpha, self.beta
        lbeta = (jax.scipy.special.gammaln(a) + jax.scipy.special.gammaln(b)
                 - jax.scipy.special.gammaln(a + b))
        return Tensor((a - 1) * jnp.log(v) + (b - 1) * jnp.log1p(-v) - lbeta)

    @property
    def mean(self):
        return Tensor(self.alpha / (self.alpha + self.beta))

    def entropy(self):
        a, b = self.alpha, self.beta
        dg = jax.scipy.special.digamma
        lbeta = (jax.scipy.special.gammaln(a) + jax.scipy.special.gammaln(b)
                 - jax.scipy.special.gammaln(a + b))
        return Tensor(lbeta - (a - 1) * dg(a) - (b - 1) * dg(b)
                      + (a + b - 2) * dg(a + b))


class Dirichlet(Distribution):
    def __init__(self, concentration):
        self.concentration = _arr(concentration)
        super().__init__(self.concentration.shape[:-1],
                         self.concentration.shape[-1:])

    def sample(self, shape=()):
        shape = tuple(shape) + self._batch_shape
        return Tensor(jax.random.dirichlet(_rnd.next_key(),
                                           self.concentration, shape))

    def log_prob(self, value):
        v = _arr(value)
        c = self.concentration
        norm = (jnp.sum(jax.scipy.special.gammaln(c), axis=-1)
                - jax.scipy.special.gammaln(jnp.sum(c, axis=-1)))
        return Tensor(jnp.sum((c - 1) * jnp.log(v), axis=-1) - norm)


class Exponential(Distribution):
    def __init__(self, rate):
        self.rate = _arr(rate)
        super().__init__(self.rate.shape)

    def sample(self, shape=()):
        shape = tuple(shape) + self._batch_shape
        return Tensor(jax.random.exponential(_rnd.next_key(), shape) / self.rate)

    def log_prob(self, value):
        v = _arr(value)
        return Tensor(jnp.log(self.rate) - self.rate * v)

    def entropy(self):
        return Tensor(1.0 - jnp.log(self.rate))


class Gamma(Distribution):
    def __init__(self, concentration, rate):
        self.concentration = _arr(concentration)
        self.rate = _arr(rate)
        super().__init__(jnp.broadcast_shapes(self.concentration.shape,
                                              self.rate.shape))

    def sample(self, shape=()):
        shape = tuple(shape) + self._batch_shape
        return Tensor(jax.random.gamma(_rnd.next_key(), self.concentration,
                                       shape) / self.rate)

    def log_prob(self, value):
        v = _arr(value)
        a, b = self.concentration, self.rate
        return Tensor(a * jnp.log(b) + (a - 1) * jnp.log(v) - b * v
                      - jax.scipy.special.gammaln(a))


class Laplace(Distribution):
    def __init__(self, loc, scale):
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    def sample(self, shape=()):
        shape = tuple(shape) + self._batch_shape
        return Tensor(self.loc + self.scale
                      * jax.random.laplace(_rnd.next_key(), shape))

    def log_prob(self, value):
        v = _arr(value)
        return Tensor(-jnp.abs(v - self.loc) / self.scale
                      - jnp.log(2 * self.scale))

    def entropy(self):
        return Tensor(1.0 + jnp.log(2 * self.scale))


class Multinomial(Distribution):
    def __init__(self, total_count, probs):
        self.total_count = int(total_count)
        self.probs_arr = _arr(probs)
        super().__init__(self.probs_arr.shape[:-1], self.probs_arr.shape[-1:])

    def sample(self, shape=()):
        n = self.probs_arr.shape[-1]
        draws = jax.random.categorical(
            _rnd.next_key(), jnp.log(jnp.maximum(self.probs_arr, 1e-30)),
            shape=tuple(shape) + self._batch_shape + (self.total_count,))
        return Tensor(jax.nn.one_hot(draws, n).sum(axis=-2))

    def log_prob(self, value):
        v = _arr(value)
        logp = jnp.log(jnp.maximum(self.probs_arr, 1e-30))
        gl = jax.scipy.special.gammaln
        return Tensor(gl(jnp.asarray(self.total_count + 1.0))
                      - jnp.sum(gl(v + 1.0), axis=-1)
                      + jnp.sum(v * logp, axis=-1))


def kl_divergence(p: Distribution, q: Distribution):
    """reference: python/paddle/distribution/kl.py."""
    if isinstance(p, Normal) and isinstance(q, Normal):
        var_ratio = jnp.square(p.scale / q.scale)
        t1 = jnp.square((p.loc - q.loc) / q.scale)
        return Tensor(0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio)))
    if isinstance(p, Categorical) and isinstance(q, Categorical):
        lp, lq = p._log_pmf(), q._log_pmf()
        return Tensor(jnp.sum(jnp.exp(lp) * (lp - lq), axis=-1))
    if isinstance(p, Uniform) and isinstance(q, Uniform):
        return Tensor(jnp.log((q.high - q.low) / (p.high - p.low)))
    if isinstance(p, Bernoulli) and isinstance(q, Bernoulli):
        pp = jnp.clip(p.probs, 1e-7, 1 - 1e-7)
        qq = jnp.clip(q.probs, 1e-7, 1 - 1e-7)
        return Tensor(pp * jnp.log(pp / qq)
                      + (1 - pp) * jnp.log((1 - pp) / (1 - qq)))
    if isinstance(p, Beta) and isinstance(q, Beta):
        gl = jax.scipy.special.gammaln
        dg = jax.scipy.special.digamma
        pa, pb, qa, qb = p.alpha, p.beta, q.alpha, q.beta
        return Tensor(
            gl(pa + pb) - gl(pa) - gl(pb) - gl(qa + qb) + gl(qa) + gl(qb)
            + (pa - qa) * dg(pa) + (pb - qb) * dg(pb)
            + (qa - pa + qb - pb) * dg(pa + pb))
    raise NotImplementedError(
        f"kl_divergence({type(p).__name__}, {type(q).__name__})")
