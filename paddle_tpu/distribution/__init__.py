"""Probability distributions (reference surface: python/paddle/distribution/
— Normal/Uniform/Categorical/Beta/Dirichlet/Multinomial/... with
sample/log_prob/entropy/kl_divergence)."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..core import random as _rnd
from ..core.dispatch import call
from ..core.tensor import Tensor


def _arr(x):
    return x._array if isinstance(x, Tensor) else jnp.asarray(x, jnp.float32)


from .exponential_family import ExponentialFamily as \
    _ExponentialFamilyMixin  # noqa: E402


class Distribution:
    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return list(self._batch_shape)

    @property
    def event_shape(self):
        return list(self._event_shape)

    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        return self.sample(shape)

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return Tensor(jnp.exp(_arr(self.log_prob(value))))

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        return kl_divergence(self, other)


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    def sample(self, shape=()):
        shape = tuple(shape) + self._batch_shape
        eps = jax.random.normal(_rnd.next_key(), shape)
        return Tensor(self.loc + self.scale * eps)

    def log_prob(self, value):
        v = _arr(value)
        var = jnp.square(self.scale)
        return Tensor(-jnp.square(v - self.loc) / (2 * var)
                      - jnp.log(self.scale) - 0.5 * math.log(2 * math.pi))

    def entropy(self):
        return Tensor(0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(self.scale)
                      + jnp.zeros(self._batch_shape, jnp.float32))

    @property
    def mean(self):
        return Tensor(jnp.broadcast_to(self.loc, self._batch_shape))

    @property
    def variance(self):
        return Tensor(jnp.broadcast_to(jnp.square(self.scale), self._batch_shape))

    @property
    def stddev(self):
        return Tensor(jnp.broadcast_to(self.scale, self._batch_shape))


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _arr(low)
        self.high = _arr(high)
        super().__init__(jnp.broadcast_shapes(self.low.shape, self.high.shape))

    def sample(self, shape=()):
        shape = tuple(shape) + self._batch_shape
        u = jax.random.uniform(_rnd.next_key(), shape)
        return Tensor(self.low + (self.high - self.low) * u)

    def log_prob(self, value):
        v = _arr(value)
        inside = (v >= self.low) & (v < self.high)
        lp = -jnp.log(self.high - self.low)
        return Tensor(jnp.where(inside, lp, -jnp.inf))

    def entropy(self):
        return Tensor(jnp.log(self.high - self.low)
                      + jnp.zeros(self._batch_shape, jnp.float32))


class Bernoulli(Distribution):
    def __init__(self, probs, name=None):
        self.probs = _arr(probs)
        super().__init__(self.probs.shape)

    def sample(self, shape=()):
        shape = tuple(shape) + self._batch_shape
        return Tensor(jax.random.bernoulli(
            _rnd.next_key(), self.probs, shape).astype(jnp.float32))

    def log_prob(self, value):
        v = _arr(value)
        p = jnp.clip(self.probs, 1e-7, 1 - 1e-7)
        return Tensor(v * jnp.log(p) + (1 - v) * jnp.log1p(-p))

    def entropy(self):
        p = jnp.clip(self.probs, 1e-7, 1 - 1e-7)
        return Tensor(-(p * jnp.log(p) + (1 - p) * jnp.log1p(-p)))

    @property
    def mean(self):
        return Tensor(self.probs)

    @property
    def variance(self):
        return Tensor(self.probs * (1 - self.probs))


class Categorical(Distribution):
    def __init__(self, logits, name=None):
        self.logits = _arr(logits)
        super().__init__(self.logits.shape[:-1])

    def sample(self, shape=()):
        shape = tuple(shape) + self._batch_shape
        return Tensor(jax.random.categorical(_rnd.next_key(),
                                             jnp.log(jnp.maximum(self.logits, 1e-30))
                                             if jnp.all(self.logits >= 0)
                                             else self.logits,
                                             shape=shape).astype(jnp.int64))

    def _log_pmf(self):
        # paddle Categorical accepts unnormalised positive weights
        logits = self.logits
        logits = jnp.where(jnp.all(logits >= 0), jnp.log(jnp.maximum(logits, 1e-30)), logits)
        return jax.nn.log_softmax(logits, axis=-1)

    def log_prob(self, value):
        v = _arr(value).astype(jnp.int32)
        lp = self._log_pmf()
        return Tensor(jnp.take_along_axis(lp, v[..., None], axis=-1)[..., 0])

    def probs(self, value):
        return Tensor(jnp.exp(_arr(self.log_prob(value))))

    def entropy(self):
        lp = self._log_pmf()
        return Tensor(-jnp.sum(jnp.exp(lp) * lp, axis=-1))


class Beta(_ExponentialFamilyMixin, Distribution):
    def __init__(self, alpha, beta):
        self.alpha = _arr(alpha)
        self.beta = _arr(beta)
        super().__init__(jnp.broadcast_shapes(self.alpha.shape, self.beta.shape))

    def sample(self, shape=()):
        shape = tuple(shape) + self._batch_shape
        return Tensor(jax.random.beta(_rnd.next_key(), self.alpha, self.beta,
                                      shape))

    def log_prob(self, value):
        v = _arr(value)
        a, b = self.alpha, self.beta
        lbeta = (jax.scipy.special.gammaln(a) + jax.scipy.special.gammaln(b)
                 - jax.scipy.special.gammaln(a + b))
        return Tensor((a - 1) * jnp.log(v) + (b - 1) * jnp.log1p(-v) - lbeta)

    @property
    def mean(self):
        return Tensor(self.alpha / (self.alpha + self.beta))

    def entropy(self):
        a, b = self.alpha, self.beta
        dg = jax.scipy.special.digamma
        lbeta = (jax.scipy.special.gammaln(a) + jax.scipy.special.gammaln(b)
                 - jax.scipy.special.gammaln(a + b))
        return Tensor(lbeta - (a - 1) * dg(a) - (b - 1) * dg(b)
                      + (a + b - 2) * dg(a + b))

    # exponential-family hooks (reference beta.py:155)
    @property
    def _natural_parameters(self):
        return (Tensor(self.alpha), Tensor(self.beta))

    def _log_normalizer(self, x, y):
        gl = jax.scipy.special.gammaln
        return Tensor(gl(_arr(x)) + gl(_arr(y)) - gl(_arr(x) + _arr(y)))

    @property
    def _mean_carrier_measure(self):
        # E[-log x - log(1-x)] under Beta(a, b) — with naturals (a, b) the
        # carrier is k(x) = -log x - log(1-x).  (The reference leaves this
        # NotImplemented and overrides entropy; providing it makes the
        # Bregman entropy exact.)
        dg = jax.scipy.special.digamma
        a, b = self.alpha, self.beta
        return -(dg(a) - dg(a + b)) - (dg(b) - dg(a + b))


class Dirichlet(_ExponentialFamilyMixin, Distribution):
    def __init__(self, concentration):
        self.concentration = _arr(concentration)
        super().__init__(self.concentration.shape[:-1],
                         self.concentration.shape[-1:])

    def sample(self, shape=()):
        shape = tuple(shape) + self._batch_shape
        return Tensor(jax.random.dirichlet(_rnd.next_key(),
                                           self.concentration, shape))

    def log_prob(self, value):
        v = _arr(value)
        c = self.concentration
        norm = (jnp.sum(jax.scipy.special.gammaln(c), axis=-1)
                - jax.scipy.special.gammaln(jnp.sum(c, axis=-1)))
        return Tensor(jnp.sum((c - 1) * jnp.log(v), axis=-1) - norm)

    # exponential-family hooks (reference dirichlet.py:147)
    @property
    def _natural_parameters(self):
        return (Tensor(self.concentration),)

    def _log_normalizer(self, x):
        gl = jax.scipy.special.gammaln
        a = _arr(x)
        return Tensor(jnp.sum(gl(a), axis=-1) - gl(jnp.sum(a, axis=-1)))

    @property
    def _mean_carrier_measure(self):
        # E[-sum(log x_i)] under Dirichlet(c) (see Beta note above)
        dg = jax.scipy.special.digamma
        c = self.concentration
        c0 = jnp.sum(c, axis=-1, keepdims=True)
        return -jnp.sum(dg(c) - dg(c0), axis=-1)


class Exponential(Distribution):
    def __init__(self, rate):
        self.rate = _arr(rate)
        super().__init__(self.rate.shape)

    def sample(self, shape=()):
        shape = tuple(shape) + self._batch_shape
        return Tensor(jax.random.exponential(_rnd.next_key(), shape) / self.rate)

    def log_prob(self, value):
        v = _arr(value)
        return Tensor(jnp.log(self.rate) - self.rate * v)

    def entropy(self):
        return Tensor(1.0 - jnp.log(self.rate))


class Gamma(Distribution):
    def __init__(self, concentration, rate):
        self.concentration = _arr(concentration)
        self.rate = _arr(rate)
        super().__init__(jnp.broadcast_shapes(self.concentration.shape,
                                              self.rate.shape))

    def sample(self, shape=()):
        shape = tuple(shape) + self._batch_shape
        return Tensor(jax.random.gamma(_rnd.next_key(), self.concentration,
                                       shape) / self.rate)

    def log_prob(self, value):
        v = _arr(value)
        a, b = self.concentration, self.rate
        return Tensor(a * jnp.log(b) + (a - 1) * jnp.log(v) - b * v
                      - jax.scipy.special.gammaln(a))


class Laplace(Distribution):
    def __init__(self, loc, scale):
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    def sample(self, shape=()):
        shape = tuple(shape) + self._batch_shape
        return Tensor(self.loc + self.scale
                      * jax.random.laplace(_rnd.next_key(), shape))

    def log_prob(self, value):
        v = _arr(value)
        return Tensor(-jnp.abs(v - self.loc) / self.scale
                      - jnp.log(2 * self.scale))

    def entropy(self):
        return Tensor(1.0 + jnp.log(2 * self.scale))


class Multinomial(Distribution):
    def __init__(self, total_count, probs):
        self.total_count = int(total_count)
        self.probs_arr = _arr(probs)
        super().__init__(self.probs_arr.shape[:-1], self.probs_arr.shape[-1:])

    def sample(self, shape=()):
        n = self.probs_arr.shape[-1]
        draws = jax.random.categorical(
            _rnd.next_key(), jnp.log(jnp.maximum(self.probs_arr, 1e-30)),
            shape=tuple(shape) + self._batch_shape + (self.total_count,))
        return Tensor(jax.nn.one_hot(draws, n).sum(axis=-2))

    def log_prob(self, value):
        v = _arr(value)
        logp = jnp.log(jnp.maximum(self.probs_arr, 1e-30))
        gl = jax.scipy.special.gammaln
        return Tensor(gl(jnp.asarray(self.total_count + 1.0))
                      - jnp.sum(gl(v + 1.0), axis=-1)
                      + jnp.sum(v * logp, axis=-1))


#: user-registered KL implementations (reference kl.py:64 register_kl)
_KL_REGISTRY = {}


def register_kl(cls_p, cls_q):
    """Decorator registering a kl_divergence implementation for a
    (type(p), type(q)) pair — reference kl.py:64."""
    if not (isinstance(cls_p, type) and isinstance(cls_q, type)):
        raise TypeError("register_kl expects two Distribution classes")

    def deco(f):
        _KL_REGISTRY[(cls_p, cls_q)] = f
        return f
    return deco


def _kl_expfamily_expfamily(p, q):
    """KL between two SAME-family exponential-family distributions via the
    Bregman divergence of the log-normalizer (reference kl.py
    _kl_expfamily_expfamily): KL(p||q) = F(θ_q) - F(θ_p) - <θ_q-θ_p, ∇F(θ_p)>."""
    from .exponential_family import ExponentialFamily
    if type(p) is not type(q) or not isinstance(p, ExponentialFamily):
        raise NotImplementedError(
            "exponential-family KL needs two instances of the same "
            "ExponentialFamily subclass")
    p_nat = [jnp.asarray(_arr(x), jnp.float32)
             for x in p._natural_parameters]
    q_nat = [jnp.asarray(_arr(x), jnp.float32)
             for x in q._natural_parameters]

    def log_norm(params):
        return jnp.sum(_arr(p._log_normalizer(
            *[Tensor(x) for x in params])))

    _, grads = jax.value_and_grad(log_norm)(tuple(p_nat))
    lq = _arr(q._log_normalizer(*[Tensor(x) for x in q_nat]))
    kl = lq - _arr(p._log_normalizer(*[Tensor(x) for x in p_nat]))
    for pn, qn, g in zip(p_nat, q_nat, grads):
        # - <θ_q - θ_p, ∇F(θ_p)>  ==  + (θ_p - θ_q)·∇F(θ_p)
        term = (pn - qn) * g
        extra = term.ndim - kl.ndim
        if extra > 0:
            term = jnp.sum(term, axis=tuple(range(-extra, 0)))
        kl = kl + term
    return Tensor(kl)


def _dispatch_kl(type_p, type_q):
    """Most-specific registered ancestor pair for (type_p, type_q) — the
    reference dispatcher (kl.py _dispatch_kl) resolves SUBCLASSES, not just
    exact types: all (cls_p, cls_q) pairs with issubclass matches are
    ranked by (mro-distance of cls_p, mro-distance of cls_q) and the
    closest pair wins (left argument tie-broken first, like the
    reference's total ordering on _Match)."""
    exact = _KL_REGISTRY.get((type_p, type_q))
    if exact is not None:
        return exact
    best, best_rank = None, None
    for (cp, cq), fn in _KL_REGISTRY.items():
        if not (issubclass(type_p, cp) and issubclass(type_q, cq)):
            continue
        rank = (type_p.__mro__.index(cp), type_q.__mro__.index(cq))
        if best_rank is None or rank < best_rank:
            best, best_rank = fn, rank
    return best


def kl_divergence(p: Distribution, q: Distribution):
    """reference: python/paddle/distribution/kl.py."""
    fn = _dispatch_kl(type(p), type(q))
    if fn is not None:
        return fn(p, q)
    # same-family exponential-family pairs fall back to the Bregman form
    # (reference kl.py dispatch order)
    from .exponential_family import ExponentialFamily as _EF
    if type(p) is type(q) and isinstance(p, _EF):
        try:
            return _kl_expfamily_expfamily(p, q)
        except NotImplementedError:
            pass
    raise NotImplementedError(
        f"kl_divergence({type(p).__name__}, {type(q).__name__})")


# Built-in analytic KLs are REGISTERED (reference kl.py does the same)
# rather than hidden behind isinstance checks after dispatch fails: the
# subclass-resolving _dispatch_kl ranks by MRO distance, so e.g. a broad
# user registration like (Distribution, Distribution) can never shadow
# the exact Normal/Normal analytic form, and Normal SUBCLASSES still
# dispatch here unless the user registers something more specific.

@register_kl(Normal, Normal)
def _kl_normal_normal(p, q):
    var_ratio = jnp.square(p.scale / q.scale)
    t1 = jnp.square((p.loc - q.loc) / q.scale)
    return Tensor(0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio)))


@register_kl(Categorical, Categorical)
def _kl_categorical_categorical(p, q):
    lp, lq = p._log_pmf(), q._log_pmf()
    return Tensor(jnp.sum(jnp.exp(lp) * (lp - lq), axis=-1))


@register_kl(Uniform, Uniform)
def _kl_uniform_uniform(p, q):
    return Tensor(jnp.log((q.high - q.low) / (p.high - p.low)))


@register_kl(Bernoulli, Bernoulli)
def _kl_bernoulli_bernoulli(p, q):
    pp = jnp.clip(p.probs, 1e-7, 1 - 1e-7)
    qq = jnp.clip(q.probs, 1e-7, 1 - 1e-7)
    return Tensor(pp * jnp.log(pp / qq)
                  + (1 - pp) * jnp.log((1 - pp) / (1 - qq)))


@register_kl(Beta, Beta)
def _kl_beta_beta(p, q):
    gl = jax.scipy.special.gammaln
    dg = jax.scipy.special.digamma
    pa, pb, qa, qb = p.alpha, p.beta, q.alpha, q.beta
    return Tensor(
        gl(pa + pb) - gl(pa) - gl(pb) - gl(qa + qb) + gl(qa) + gl(qb)
        + (pa - qa) * dg(pa) + (pb - qb) * dg(pb)
        + (qa - pa + qb - pb) * dg(pa + pb))


# -- round-5 additions: transforms / wrappers (reference transform.py:59,
# transformed_distribution.py:22, independent.py:18,
# exponential_family.py) ----------------------------------------------------
from .exponential_family import ExponentialFamily  # noqa: E402,F401
from .independent import Independent  # noqa: E402,F401
from .transform import (AbsTransform, AffineTransform,  # noqa: E402,F401
                        ChainTransform, ExpTransform, IndependentTransform,
                        PowerTransform, ReshapeTransform, SigmoidTransform,
                        SoftmaxTransform, StackTransform,
                        StickBreakingTransform, TanhTransform, Transform)
from .transformed_distribution import \
    TransformedDistribution  # noqa: E402,F401
