"""Profiler (reference surface: python/paddle/profiler/ — Profiler context
manager with scheduler windows at profiler.py:264, RecordEvent spans, ips
timer at timer.py).

TPU-native: host spans are recorded by our own lock-free-enough recorder and
exported as chrome://tracing JSON (the reference's chrometracing_logger.cc),
while device activity comes from jax.profiler (XPlane -> TensorBoard /
Perfetto) when a trace dir is given.
"""
from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from enum import Enum
from typing import Callable, Iterable, Optional

import jax


class ProfilerTarget(Enum):
    CPU = 0
    GPU = 1
    TPU = 2
    CUSTOM_DEVICE = 3


class ProfilerState(Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


def make_scheduler(closed: int, ready: int, record: int, repeat: int = 0,
                   skip_first: int = 0):
    """reference parity: profiler.py:67 make_scheduler — step-state machine."""
    period = closed + ready + record

    def scheduler(step: int) -> ProfilerState:
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat and s >= repeat * period:
            return ProfilerState.CLOSED
        pos = s % period
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == period - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return scheduler


class _HostEventRecorder:
    """Per-thread span buffers merged at export
    (reference: host_event_recorder.h)."""

    def __init__(self):
        self._events = []
        self._lock = threading.Lock()

    def add(self, name, ts, dur, tid):
        with self._lock:
            self._events.append((name, ts, dur, tid))

    def drain(self):
        with self._lock:
            ev, self._events = self._events, []
        return ev


_recorder = _HostEventRecorder()

#: chrome-trace counter marks injected by paddle_tpu.observability:
#: (name, ts_ns, value) triples, exported as "ph": "C" events so metric
#: tracks render time-aligned under the host spans.
_metric_marks = []

#: backstop bound on the mark buffer: export_chrome_tracing drains it, but
#: a custom on_trace_ready callback may not — keep only the newest marks
#: so an undrained buffer can never grow for the life of the process.
_MARKS_CAP = 100_000


def _inject_metric_marks():
    """Snapshot the default metrics registry into the mark buffer (no-op
    when observability is disabled or unavailable)."""
    try:
        from ..observability.exporters import inject_profiler_marks
        inject_profiler_marks()
    except Exception:
        pass  # metrics must never break a trace export


class RecordEvent:
    """Span instrumentation (reference: platform::RecordEvent; hooks sat in
    every runtime hot path e.g. interpretercore.cc:581)."""

    def __init__(self, name: str, event_type=None):
        self.name = name
        self._begin = None

    def begin(self):
        self._begin = time.perf_counter_ns()

    def end(self):
        if self._begin is None:
            return
        now = time.perf_counter_ns()
        _recorder.add(self.name, self._begin, now - self._begin,
                      threading.get_ident())
        self._begin = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


def export_chrome_tracing(dir_name: str, worker_name: str = None):
    """Returns an on_trace_ready callback writing chrome://tracing JSON
    (reference: profiler.py:154)."""

    def handler(prof):
        os.makedirs(dir_name, exist_ok=True)
        fname = os.path.join(
            dir_name, f"{worker_name or 'worker'}_{os.getpid()}"
            f"_{int(time.time() * 1000)}.pt.trace.json")
        events = [{
            "name": name, "ph": "X", "ts": ts / 1000.0, "dur": dur / 1000.0,
            "pid": os.getpid(), "tid": tid, "cat": "host",
        } for name, ts, dur, tid in prof._drained_events]
        marks, _metric_marks[:] = list(_metric_marks), []
        events.extend({
            "name": name, "ph": "C", "ts": ts / 1000.0,
            "pid": os.getpid(), "cat": "metric",
            "args": {"value": value},
        } for name, ts, value in marks)
        with open(fname, "w") as f:
            json.dump({"traceEvents": events}, f)
        prof._last_export = fname

    return handler


class Profiler:
    """reference parity: python/paddle/profiler/profiler.py:264."""

    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 record_shapes=False, profile_memory=False, timer_only=False,
                 trace_dir=None):
        if isinstance(scheduler, tuple):
            start, end = scheduler
            scheduler = make_scheduler(closed=max(start, 0), ready=0,
                                       record=end - start, repeat=1)
        self._scheduler = scheduler
        self._on_trace_ready = on_trace_ready
        self._step = 0
        self._state = ProfilerState.RECORD if scheduler is None else \
            ProfilerState.CLOSED
        self._drained_events = []
        self._last_export = None
        self._timer_only = timer_only
        self._trace_dir = trace_dir
        self._jax_tracing = False
        self.benchmark = TimerHub()

    def start(self):
        self.benchmark.begin()
        if self._trace_dir and not self._timer_only:
            jax.profiler.start_trace(self._trace_dir)
            self._jax_tracing = True
        return self

    def stop(self):
        if self._jax_tracing:
            jax.profiler.stop_trace()
            self._jax_tracing = False
        self._drained_events.extend(_recorder.drain())
        if self._on_trace_ready:
            # marks exist solely for the trace-export stream: injecting
            # with no consumer would strand them in the module buffer
            _inject_metric_marks()
            self._on_trace_ready(self)

    def step(self, num_samples=None):
        self.benchmark.step(num_samples)
        self._step += 1
        if self._scheduler:
            self._state = self._scheduler(self._step)
            if self._state == ProfilerState.RECORD_AND_RETURN:
                self._drained_events.extend(_recorder.drain())
                if self._on_trace_ready:
                    _inject_metric_marks()
                    self._on_trace_ready(self)

    def step_info(self, unit="samples"):
        return self.benchmark.step_info(unit)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms"):
        by_name = {}
        for name, ts, dur, tid in self._drained_events:
            agg = by_name.setdefault(name, [0, 0.0])
            agg[0] += 1
            agg[1] += dur / 1e6
        lines = [f"{'name':40s} {'calls':>8s} {'total_ms':>12s}"]
        for name, (calls, total) in sorted(by_name.items(),
                                           key=lambda kv: -kv[1][1]):
            lines.append(f"{name[:40]:40s} {calls:8d} {total:12.3f}")
        return "\n".join(lines)


class TimerHub:
    """Throughput (ips) timer — reference: python/paddle/profiler/timer.py."""

    def __init__(self):
        self.reset()

    def reset(self):
        self._start = None
        self._last = None
        self._steps = 0
        self._samples = 0
        self._window = []

    def begin(self):
        self._start = self._last = time.perf_counter()

    def step(self, num_samples=None):
        now = time.perf_counter()
        if self._last is not None:
            self._window.append(now - self._last)
            if len(self._window) > 100:
                self._window.pop(0)
        self._last = now
        self._steps += 1
        if num_samples:
            self._samples += num_samples

    def step_info(self, unit="samples"):
        if not self._window:
            return "no steps recorded"
        avg = sum(self._window) / len(self._window)
        ips = (self._samples / max(self._steps, 1)) / avg if self._samples else 1.0 / avg
        return (f"avg_step_time: {avg * 1000:.3f} ms, "
                f"ips: {ips:.2f} {unit}/s")


@contextlib.contextmanager
def profiler_guard(**kwargs):
    p = Profiler(**kwargs)
    p.start()
    try:
        yield p
    finally:
        p.stop()


def load_profiler_result(path):
    with open(path) as f:
        return json.load(f)
