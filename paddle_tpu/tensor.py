"""paddle.tensor — the tensor-op namespace (reference:
python/paddle/tensor/__init__.py, which re-exports the per-domain op
modules math/linalg/creation/manipulation/...).

In this build the ops live in paddle_tpu.ops (one dispatch layer over
jnp/lax — SURVEY §2.3); this module mirrors the reference's namespace so
``paddle.tensor.<op>`` resolves for every op the flat API exposes."""
from .ops import *  # noqa: F401,F403

__all__ = [n for n in dir() if not n.startswith("_")]
