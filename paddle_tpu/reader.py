"""paddle.reader — reader-decorator utilities (reference:
python/paddle/reader/decorator.py: cache:52, map_readers:92, shuffle:134,
chain:183, compose:248, buffered:308, firstn:367, xmap_readers:412,
multiprocess_reader:505).

A "reader creator" is a zero-arg callable returning an iterable of samples
(the reference's legacy data-feeding protocol, kept for API parity next to
the io.DataLoader path)."""
from __future__ import annotations

import itertools
import queue as _queue
import random as _random
import threading

__all__ = ["cache", "map_readers", "shuffle", "chain", "compose",
           "buffered", "firstn", "xmap_readers", "multiprocess_reader"]


class ComposeNotAligned(ValueError):
    pass


def cache(reader):
    """Cache the reader's data in memory on first pass."""
    all_data = tuple(reader())

    def cached_reader():
        for item in all_data:
            yield item

    return cached_reader


def map_readers(func, *readers):
    """Yield func(*samples) zipped over the readers."""
    def reader():
        rs = [r() for r in readers]
        for e in map(func, *rs):
            yield e

    return reader


def shuffle(reader, buf_size):
    """Buffered shuffle within windows of ``buf_size`` samples."""
    def data_reader():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                for b in buf:
                    yield b
                buf = []
        if buf:
            _random.shuffle(buf)
            for b in buf:
                yield b

    return data_reader


def chain(*readers):
    """Concatenate readers back-to-back."""
    def reader():
        rs = [r() for r in readers]
        for e in itertools.chain(*rs):
            yield e

    return reader


def compose(*readers, **kwargs):
    """Zip readers into combined samples: (a, b, c) per step.

    check_alignment=True (default) raises ComposeNotAligned when the
    readers have different lengths."""
    check_alignment = kwargs.pop("check_alignment", True)

    def make_tuple(x):
        if isinstance(x, tuple):
            return x
        return (x,)

    def reader():
        rs = [r() for r in readers]
        if not check_alignment:
            for outputs in zip(*rs):
                yield sum(list(map(make_tuple, outputs)), ())
        else:
            for outputs in itertools.zip_longest(*rs):
                for o in outputs:
                    if o is None:
                        raise ComposeNotAligned(
                            "outputs of readers are not aligned.")
                yield sum(list(map(make_tuple, outputs)), ())

    return reader


def buffered(reader, size):
    """Pre-read up to ``size`` samples on a background thread."""
    class _End:
        pass

    def read_worker(r, q):
        for d in r:
            q.put(d)
        q.put(_End())

    def data_reader():
        r = reader()
        q = _queue.Queue(maxsize=size)
        t = threading.Thread(target=read_worker, args=(r, q),
                             daemon=True, name="reader-buffered")
        t.start()
        e = q.get()
        while not isinstance(e, _End):
            yield e
            e = q.get()

    return data_reader


def firstn(reader, n):
    """Limit the reader to its first ``n`` samples."""
    def firstn_reader():
        for i, item in enumerate(reader()):
            if i == n:
                break
            yield item

    return firstn_reader


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Apply ``mapper`` over the reader with ``process_num`` worker threads
    (the reference uses threads too, decorator.py:412)."""
    end = object()
    in_order = order

    def read_worker(r, in_q):
        for i, d in enumerate(r()):
            in_q.put((i, d) if in_order else d)
        in_q.put(end)

    def map_worker(in_q, out_q):
        sample = in_q.get()
        while sample is not end:
            if in_order:
                i, d = sample
                out_q.put((i, mapper(d)))
            else:
                out_q.put(mapper(sample))
            sample = in_q.get()
        in_q.put(end)       # let sibling workers see the sentinel
        out_q.put(end)

    def xreader():
        in_q = _queue.Queue(buffer_size)
        out_q = _queue.Queue(buffer_size)
        t = threading.Thread(target=read_worker, args=(reader, in_q),
                             daemon=True, name="reader-xmap-read")
        t.start()
        workers = []
        for _ in range(process_num):
            w = threading.Thread(target=map_worker, args=(in_q, out_q),
                                 daemon=True, name="reader-xmap-map")
            w.start()
            workers.append(w)
        finished = 0
        next_idx = 0
        pending = {}
        while finished < process_num:
            sample = out_q.get()
            if sample is end:
                finished += 1
                continue
            if in_order:
                i, d = sample
                pending[i] = d
                while next_idx in pending:
                    yield pending.pop(next_idx)
                    next_idx += 1
            else:
                yield sample
        if in_order:
            for i in sorted(pending):
                yield pending[i]

    return xreader


def multiprocess_reader(readers, use_pipe=True, queue_size=1000):
    """Interleave multiple readers concurrently (thread-backed here: jax
    arrays do not pickle across fork, so the reference's fork/pipe scheme
    is replaced by threads with identical yield semantics)."""
    if len(readers) < 1:
        raise ValueError("multiprocess_reader needs at least one reader")
    end = object()

    def worker(r, q):
        try:
            for sample in r():
                if sample is None:
                    raise ValueError("sample has None")
                q.put(sample)
        finally:
            q.put(end)

    def reader():
        q = _queue.Queue(queue_size)
        for r in readers:
            t = threading.Thread(target=worker, args=(r, q),
                                 daemon=True, name="reader-multiprocess")
            t.start()
        finished = 0
        while finished < len(readers):
            sample = q.get()
            if sample is end:
                finished += 1
            else:
                yield sample

    return reader
