"""Elastic training manager (reference:
python/paddle/distributed/fleet/elastic/manager.py — ElasticManager:130,
watch loop :126, etcd node registry :190).

TPU-native redesign: membership lives in our TCPStore (csrc/tcp_store.cpp,
the rendezvous the framework already ships) instead of etcd.  Each node
heartbeats a timestamp key; the watch loop classifies the world as
HOLD (healthy), RESTART (membership changed — a node died or joined, the
job should relaunch workers and auto-resume from checkpoint), or
COMPLETED / EXIT.  The restart contract is incubate.checkpoint auto-resume:
a relaunched worker restores the newest complete checkpoint and
fast-forwards its data stream.
"""
from __future__ import annotations

import enum
import os
import threading
import time
from typing import Dict, List, Optional

__all__ = ["ElasticStatus", "ElasticManager"]


class ElasticStatus(enum.Enum):
    COMPLETED = "completed"
    HOLD = "hold"          # healthy, keep training
    RESTART = "restart"    # membership changed: relaunch + resume
    EXIT = "exit"          # stopped / max restarts exceeded
    ERROR = "error"


class ElasticManager:
    """Heartbeat membership over TCPStore.

    One manager per node.  ``start()`` registers the node and begins
    heartbeating; ``watch()`` returns the current ElasticStatus; a
    supervisor loop reacts to RESTART by relaunching workers (see
    launch_main.Launcher elastic mode for the in-node half).
    """

    def __init__(self, store=None, job_id: Optional[str] = None,
                 np_: Optional[int] = None, node_rank: Optional[int] = None,
                 heartbeat_interval: float = 0.5,
                 node_timeout: float = 3.0,
                 max_np: Optional[int] = None):
        if store is None:
            from ...store import TCPStore
            master = os.getenv("PADDLE_ELASTIC_SERVER",
                               os.getenv("PADDLE_MASTER", "127.0.0.1:0"))
            host, _, port = master.partition(":")
            is_master = int(os.getenv("PADDLE_NODE_RANK", "0")) == 0
            store = TCPStore(host or "127.0.0.1", int(port or 0),
                             is_master=is_master,
                             world_size=int(os.getenv("PADDLE_NNODES", "1")))
        self.store = store
        self.job_id = job_id or os.getenv("PADDLE_ELASTIC_JOB_ID", "default")
        self.np = np_ if np_ is not None else int(os.getenv(
            "PADDLE_NNODES", "1"))
        # scale-UP headroom (reference: PADDLE_ELASTIC_NP "min:max" range):
        # membership scans cover ranks up to max_np so a JOINING node's
        # heartbeat is visible to watch()/replan()
        self.max_np = max_np if max_np is not None else int(os.getenv(
            "PADDLE_ELASTIC_MAX_NP", str(self.np)))
        self.max_np = max(self.max_np, self.np)
        self.node_rank = node_rank if node_rank is not None else int(
            os.getenv("PADDLE_NODE_RANK", "0"))
        self.heartbeat_interval = heartbeat_interval
        self.node_timeout = node_timeout
        self._stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None
        self._beat_n = 0
        # _beat runs on the caller's thread (start) and the heartbeat
        # thread; the counter bump must be atomic — the store.set stays
        # OUTSIDE the lock (blocking network I/O under a lock is TPU604)
        self._beat_lock = threading.Lock()
        self._last_alive: Optional[frozenset] = None
        # liveness is judged by heartbeat-value CHANGE against the watcher's
        # own clock — never by comparing remote wall clocks (cross-node skew
        # larger than node_timeout would otherwise declare healthy nodes
        # dead): {rank: (last_raw_value, watcher_time_first_seen)}
        self._hb_seen: Dict[int, tuple] = {}

    # -- key layout ----------------------------------------------------------
    def _k(self, *parts) -> str:
        return "/".join(("elastic", self.job_id) + tuple(str(p)
                                                         for p in parts))

    # -- lifecycle ------------------------------------------------------------
    def start(self):
        """Register this node and begin heartbeating (manager.py:190
        register + TTL refresh, minus etcd)."""
        self.store.set(self._k("nodes", self.node_rank), b"1")
        self._beat()
        self._hb_thread = threading.Thread(target=self._hb_loop, daemon=True,
                                           name="elastic-heartbeat")
        self._hb_thread.start()
        return self

    def _beat(self):
        # monotonically changing value; watchers detect liveness by change,
        # not by decoding it (clock-skew independent)
        with self._beat_lock:
            self._beat_n += 1
            n = self._beat_n
        self.store.set(self._k("hb", self.node_rank), str(n).encode())

    def _hb_loop(self):
        while not self._stop.wait(self.heartbeat_interval):
            try:
                self._beat()
            except Exception:
                return  # store gone: supervisor will notice via watch()

    def stop(self, completed: bool = False):
        self._stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=2)
        if completed:
            try:
                self.store.set(self._k("completed"), b"1")
            except Exception:
                pass

    # -- membership -----------------------------------------------------------
    def alive_nodes(self) -> List[int]:
        now = time.monotonic()
        alive = []
        for r in range(self.max_np):
            try:
                raw = self.store.get(self._k("hb", r), wait=False)
            except KeyError:
                continue
            last = self._hb_seen.get(r)
            if last is None or last[0] != raw:
                # value changed → the node beat since we last looked
                self._hb_seen[r] = (raw, now)
                alive.append(r)
            elif now - last[1] <= self.node_timeout:
                alive.append(r)
        return alive

    def watch(self) -> ElasticStatus:
        """One classification step of the reference's watch loop
        (manager.py:126)."""
        try:
            try:
                self.store.get(self._k("completed"), wait=False)
                return ElasticStatus.COMPLETED
            except KeyError:
                pass
            alive = frozenset(self.alive_nodes())
        except Exception:
            return ElasticStatus.ERROR
        if self._last_alive is None:
            self._last_alive = alive
            return ElasticStatus.HOLD
        if alive != self._last_alive:
            self._last_alive = alive
            return ElasticStatus.RESTART
        return ElasticStatus.HOLD

    # -- re-planning ----------------------------------------------------------
    def replan(self) -> Dict:
        """Recompute the topology after a RESTART (reference
        manager.py:130: the trainer list is REWRITTEN on scale-up/down, not
        merely restarted at the old world size).

        Dense re-rank of the currently-alive nodes: returns
        ``{"np": new_world, "nodes": [old ranks alive], "rank_map":
        {old: new}, "my_rank": new rank or None}`` — ``my_rank is None``
        means this node was evicted (or died) and must exit.  The caller
        relaunches its workers with the new world size/endpoints and
        resumes from the newest checkpoint (incubate.checkpoint).
        """
        alive = sorted(self.alive_nodes())
        rank_map = {old: new for new, old in enumerate(alive)}
        return {"np": len(alive), "nodes": alive, "rank_map": rank_map,
                "my_rank": rank_map.get(self.node_rank)}

    # -- convenience ----------------------------------------------------------
    def wait_for_np(self, timeout: float = 60.0) -> bool:
        """Block until all np nodes heartbeat (job-start rendezvous)."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            if len(self.alive_nodes()) >= self.np:
                return True
            time.sleep(self.heartbeat_interval)
        return False
