"""Hybrid-parallel optimizer wrapper (reference:
fleet/meta_optimizers/dygraph_optimizer/hybrid_parallel_optimizer.py).

On the GSPMD path gradient synchronisation is already inserted by XLA, so
this wrapper's remaining responsibilities are mp-aware grad clipping and
API parity (step/clear_grad passthrough).
"""
from __future__ import annotations


class HybridParallelOptimizer:
    def __init__(self, optimizer, hcg=None, strategy=None):
        self._inner_opt = optimizer
        self._hcg = hcg
        self._strategy = strategy

    def __getattr__(self, item):
        return getattr(self.__dict__["_inner_opt"], item)

    def step(self):
        self._inner_opt.step()

    def clear_grad(self, *a, **k):
        self._inner_opt.clear_grad(*a, **k)

    def minimize(self, loss, **kw):
        return self._inner_opt.minimize(loss, **kw)

    def state_dict(self):
        return self._inner_opt.state_dict()

    def set_state_dict(self, sd):
        return self._inner_opt.set_state_dict(sd)
