"""Hybrid-parallel optimizer wrapper (reference:
fleet/meta_optimizers/dygraph_optimizer/hybrid_parallel_optimizer.py).

Its one non-trivial responsibility in the reference is **mp-aware global-norm
gradient clipping**: under tensor parallelism each rank holds only a slice of
the distributed parameters, so the global grad norm is

    sqrt( psum_over_mp(sum_sq(distributed grads)) + sum_sq(replicated grads) )

— replicated params counted once, sharded params summed across the mp group
(reference `_obtain_optimizer_parameters_list` + HybridParallelClipGrad).

TPU-native placement of that logic: on the GSPMD path parameter arrays are
*global* logical arrays (XLA inserts the collectives), so the plain global
norm is already correct; inside a ``shard_map`` region, however, a
distributed param's leaf IS the local shard, and the psum is required.
``_HybridClipGradByGlobalNorm`` handles both: it psums the distributed
contribution when the mp axis is bound in the current trace and falls back
to the plain sum otherwise.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


class _HybridClipGradByGlobalNorm:
    """Drop-in for nn.ClipGradByGlobalNorm with an mp-aware total norm.
    Registered as a virtual subclass so Optimizer._clip_tree dispatches to
    the global-norm branch and calls ``_total_norm``."""

    def __init__(self, clip_norm, mp_axis="mp"):
        self.clip_norm = clip_norm
        self.mp_axis = mp_axis

    def _total_norm(self, live, dist_flags):
        rep_sq = jnp.zeros((), jnp.float32)
        dist_sq = jnp.zeros((), jnp.float32)
        have_dist = False
        for i, g in live:
            sq = jnp.sum(jnp.square(g.astype(jnp.float32)))
            if dist_flags is not None and i < len(dist_flags) \
                    and dist_flags[i]:
                dist_sq = dist_sq + sq
                have_dist = True
            else:
                rep_sq = rep_sq + sq
        if have_dist:
            from ..collective import _in_trace
            if _in_trace(self.mp_axis):
                # inside shard_map over the mp axis: local shards → psum
                dist_sq = jax.lax.psum(dist_sq, self.mp_axis)
            # else GSPMD path: leaves are global arrays, sum already global
        return jnp.sqrt(rep_sq + dist_sq)


class HybridParallelOptimizer:
    """reference: hybrid_parallel_optimizer.py — wraps the user optimizer
    with mp-aware clipping; step/clear_grad/minimize pass through."""

    def __init__(self, optimizer, hcg=None, strategy=None):
        self._inner_opt = optimizer
        self._hcg = hcg
        self._strategy = strategy
        clip = getattr(optimizer, "_grad_clip", None)
        mp_degree = 1
        if hcg is not None:
            get_mp = getattr(hcg, "get_model_parallel_world_size", None)
            if get_mp is not None:
                mp_degree = get_mp()
        from ...nn import ClipGradByGlobalNorm
        if clip is not None and isinstance(clip, ClipGradByGlobalNorm) \
                and mp_degree > 1:
            optimizer._grad_clip = _make_mp_clip(clip.clip_norm)

    def __getattr__(self, item):
        inner = self.__dict__.get("_inner_opt")
        if inner is None:
            # copy/pickle probe attributes before __init__ runs — must be
            # AttributeError, not KeyError, for hasattr/copy fallbacks
            raise AttributeError(item)
        return getattr(inner, item)

    def step(self):
        self._inner_opt.step()

    def clear_grad(self, *a, **k):
        self._inner_opt.clear_grad(*a, **k)

    def minimize(self, loss, **kw):
        return self._inner_opt.minimize(loss, **kw)

    def state_dict(self):
        return self._inner_opt.state_dict()

    def set_state_dict(self, sd):
        return self._inner_opt.set_state_dict(sd)


def _make_mp_clip(clip_norm, mp_axis="mp"):
    """Instantiate the mp-aware clip as a real subclass of
    nn.ClipGradByGlobalNorm so existing isinstance dispatch picks it up."""
    from ...nn import ClipGradByGlobalNorm

    class _Clip(ClipGradByGlobalNorm, _HybridClipGradByGlobalNorm):
        def __init__(self):
            ClipGradByGlobalNorm.__init__(self, clip_norm)
            self.mp_axis = mp_axis

    return _Clip()
