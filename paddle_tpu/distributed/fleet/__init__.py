"""Fleet facade (reference surface: python/paddle/distributed/fleet/ —
fleet.init at fleet_base.py:206, distributed_model :932,
distributed_optimizer :875, DistributedStrategy).

TPU-native: `DistributedStrategy` is a dataclass config tree that resolves to
a mesh spec + wrapper choice; `distributed_model` wraps the layer per the
active topology (DataParallel / TensorParallel / PipelineParallel /
ShardingParallel), mirroring fleet_base.py:932 dispatch.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import jax

from .. import mesh as _mesh
from ..mesh import CommunicateTopology, HybridCommunicateGroup
from ..parallel_base import get_rank, get_world_size


@dataclasses.dataclass
class HybridConfig:
    dp_degree: int = 1
    mp_degree: int = 1
    pp_degree: int = 1
    sharding_degree: int = 1
    sep_degree: int = 1
    ep_degree: int = 1


@dataclasses.dataclass
class AMPConfig:
    enable: bool = False
    dtype: str = "bfloat16"
    level: str = "O1"


@dataclasses.dataclass
class RecomputeConfig:
    enable: bool = False
    checkpoints: tuple = ()


@dataclasses.dataclass
class ShardingConfig:
    stage: int = 1
    offload: bool = False


@dataclasses.dataclass
class PipelineConfig:
    accumulate_steps: int = 1
    micro_batch_size: int = 1
    schedule_mode: str = "1F1B"


class DistributedStrategy:
    """reference parity: fleet/base/distributed_strategy.py (protobuf-backed
    in the reference; a typed dataclass tree here — SURVEY.md §5.6)."""

    def __init__(self):
        self.hybrid_configs = HybridConfig()
        self.amp = False
        self.amp_configs = AMPConfig()
        self.recompute = False
        self.recompute_configs = RecomputeConfig()
        self.sharding = False
        self.sharding_configs = ShardingConfig()
        self.pipeline = False
        self.pipeline_configs = PipelineConfig()
        self.gradient_merge = False
        self.gradient_merge_configs = {"k_steps": 1}
        self.find_unused_parameters = False

    def __setattr__(self, k, v):
        if k == "hybrid_configs" and isinstance(v, dict):
            v = HybridConfig(**{kk: vv for kk, vv in v.items()
                                if kk in HybridConfig.__dataclass_fields__})
        if k == "sharding_configs" and isinstance(v, dict):
            v = ShardingConfig(**{kk: vv for kk, vv in v.items()
                                  if kk in ShardingConfig.__dataclass_fields__})
        if k == "pipeline_configs" and isinstance(v, dict):
            v = PipelineConfig(**{kk: vv for kk, vv in v.items()
                                  if kk in PipelineConfig.__dataclass_fields__})
        if k == "amp_configs" and isinstance(v, dict):
            v = AMPConfig(**{kk: vv for kk, vv in v.items()
                             if kk in AMPConfig.__dataclass_fields__})
        object.__setattr__(self, k, v)


class _Fleet:
    def __init__(self):
        self._strategy = None
        self._hcg = None
        self._topology = None
        self._is_initialized = False

    def init(self, role_maker=None, is_collective=True, strategy=None):
        """reference parity: fleet_base.py:206 — builds the hybrid topology
        and the global device mesh."""
        self._strategy = strategy or DistributedStrategy()
        hc = self._strategy.hybrid_configs
        n_dev = len(jax.devices())
        degrees = {"dp": hc.dp_degree, "pp": hc.pp_degree,
                   "sdp": hc.sharding_degree, "sep": hc.sep_degree,
                   "mp": hc.mp_degree, "ep": hc.ep_degree}
        specified = {k: v for k, v in degrees.items() if v > 1}
        total = 1
        for v in specified.values():
            total *= v
        if not specified:
            specified = {"dp": n_dev}
        elif hc.dp_degree <= 1 and total < n_dev:
            specified["dp"] = n_dev // total  # fill remaining onto dp
        _mesh.init_mesh(specified)
        topo = CommunicateTopology(
            ["data", "pipe", "sharding", "model"],
            [specified.get("dp", 1), specified.get("pp", 1),
             specified.get("sdp", 1), specified.get("mp", 1)])
        self._topology = topo
        self._hcg = HybridCommunicateGroup(topo, get_rank())
        self._is_initialized = True
        return self

    def get_hybrid_communicate_group(self):
        return self._hcg

    @property
    def worker_num(self):
        return get_world_size()

    def worker_index(self):
        return get_rank()

    def is_first_worker(self):
        return get_rank() == 0

    def barrier_worker(self):
        from ..collective import barrier
        barrier()

    def distributed_model(self, model):
        """reference parity: fleet_base.py:932 wrapper dispatch."""
        hc = self._strategy.hybrid_configs if self._strategy else HybridConfig()
        if hc.pp_degree > 1:
            from ..pipeline import PipelineParallel
            return PipelineParallel(model, self._hcg, self._strategy)
        if hc.mp_degree > 1:
            from ..mp_layers import TensorParallel
            return TensorParallel(model, self._hcg, self._strategy)
        if hc.sharding_degree > 1:
            from ..sharding import ShardingParallel
            return ShardingParallel(model, self._hcg, self._strategy)
        from ...nn.parallel import DataParallel
        return DataParallel(model)

    def distributed_optimizer(self, optimizer, strategy=None):
        if strategy is not None:
            self._strategy = strategy
        from .hybrid_optimizer import HybridParallelOptimizer
        return HybridParallelOptimizer(optimizer, self._hcg, self._strategy)


fleet = _Fleet()

# module-level convenience mirroring `from paddle.distributed import fleet`
init = fleet.init
distributed_model = fleet.distributed_model
distributed_optimizer = fleet.distributed_optimizer
get_hybrid_communicate_group = fleet.get_hybrid_communicate_group


def worker_num():
    return fleet.worker_num


def worker_index():
    return fleet.worker_index()
