"""Pipeline parallelism (reference:
fleet/meta_parallel/pipeline_parallel.py:30 PipelineParallel — 1F1B at
forward_backward_pipeline:80; parallel_layers/pp_layers.py:132 PipelineLayer,
LayerDesc:31, SegmentLayers:63; C++ twin framework/section_worker.cc:153).

TPU-native rethink (SURVEY.md §7 "hard parts"): no per-op streams or p2p
send_v2/recv_v2 ops.  The whole pipeline is ONE jitted SPMD program:
parameters of the (structurally identical) stages are stacked on a leading
stage dim sharded over the 'pp' mesh axis; microbatches stream through a
``lax.fori_loop`` whose per-tick stage handoff is a single
``lax.ppermute`` over ICI — the schedule the fleet_executor's credit-based
interceptors (N25) approximated with RPC is here a compiled collective
rotation.  Backward comes from jax.grad over the same program (GPipe-style;
XLA overlaps the reverse permutes the same way).
"""
from __future__ import annotations

import functools
from typing import Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..nn.layer.layers import Layer, LayerList


class LayerDesc:
    """reference parity: pp_layers.py:31 — lazy layer description."""

    def __init__(self, layer_class, *args, **kwargs):
        self.layer_class = layer_class
        self.args = args
        self.kwargs = kwargs

    def build_layer(self):
        return self.layer_class(*self.args, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    """reference parity: pp_layers.py:49 — weight shared across stages
    (e.g. embedding/softmax tying)."""

    def __init__(self, key, layer_class, *args, forward_func=None,
                 shared_weight_attr="weight", **kwargs):
        super().__init__(layer_class, *args, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class SegmentLayers:
    """reference parity: pp_layers.py:63 — uniform or param-weighted
    partition of N layers into num_stages segments."""

    def __init__(self, layers_desc, num_parts, method="uniform"):
        self.descs = layers_desc
        self.num_parts = num_parts
        self.method = method

    def do_segment(self) -> List[int]:
        n = len(self.descs)
        if self.method == "uniform":
            base = n // self.num_parts
            rem = n % self.num_parts
            bounds = [0]
            for i in range(self.num_parts):
                bounds.append(bounds[-1] + base + (1 if i < rem else 0))
            return bounds
        raise NotImplementedError(self.method)


class PipelineLayer(Layer):
    """reference parity: pp_layers.py:132 — build only this stage's chunk.

    On TPU the "stage" is a mesh coordinate, not a process; when used under
    the SPMD pipeline all stages exist in one program, so by default the
    full layer list is built and staged via `spmd_pipeline`.
    """

    def __init__(self, layers, num_stages=None, topology=None,
                 loss_fn=None, seg_method="uniform", recompute_interval=0):
        super().__init__()
        self.descs = list(layers)
        self.num_stages = num_stages or 1
        self.loss_fn = loss_fn
        self.recompute_interval = recompute_interval
        built = []
        for d in self.descs:
            if isinstance(d, LayerDesc):
                built.append(d.build_layer())
            else:
                built.append(d)
        self.run_function = LayerList(built)
        self.segment_bounds = SegmentLayers(
            built, self.num_stages, seg_method).do_segment()

    def get_stage_layers(self, stage_id):
        lo, hi = self.segment_bounds[stage_id], self.segment_bounds[stage_id + 1]
        return self.run_function[lo:hi]

    def forward(self, x):
        for layer in self.run_function:
            x = layer(x)
        return x


def _ensure_varying(arr, axis):
    try:
        return jax.lax.pcast(arr, axis, to="varying")
    except (AttributeError, TypeError, ValueError):
        try:
            return jax.lax.pvary(arr, axis)
        except (AttributeError, ValueError):
            return arr


def spmd_pipeline(stage_fn: Callable, stacked_params, x, num_stages: int,
                  num_micro: int, axis: str = "pp"):
    """Run a pipeline INSIDE a shard_map over `axis`.

    stage_fn(params_slice, microbatch) -> microbatch_out
    stacked_params: pytree whose leaves have leading dim == num_stages
        (under shard_map each device sees its slice, leading dim 1).
    x: (num_micro, micro_batch, ...) — full input on stage 0's slot.

    Classic collective-permute schedule: T = num_micro + num_stages - 1 ticks;
    each tick every stage processes one buffer then rotates it forward.
    """
    stage = jax.lax.axis_index(axis)
    params = jax.tree_util.tree_map(lambda p: p[0], stacked_params)

    fwd_perm = [(i, (i + 1) % num_stages) for i in range(num_stages)]

    def tick(t, carry):
        buf, outputs = carry
        # stage 0 ingests microbatch t (if in range); others use rotated buf
        mb_idx = jnp.clip(t, 0, num_micro - 1)
        fresh = jax.lax.dynamic_index_in_dim(x, mb_idx, axis=0, keepdims=False)
        inp = jnp.where(stage == 0, fresh, buf)
        out = stage_fn(params, inp)
        # last stage records its finished microbatch (t - num_stages + 1)
        done_idx = t - (num_stages - 1)
        record = jnp.logical_and(stage == num_stages - 1, done_idx >= 0)
        outputs = jax.lax.cond(
            record,
            lambda o: jax.lax.dynamic_update_index_in_dim(
                o, out, jnp.clip(done_idx, 0, num_micro - 1), axis=0),
            lambda o: o,
            outputs)
        # rotate activations to the next stage
        buf = jax.lax.ppermute(out, axis, fwd_perm)
        return buf, outputs

    buf0 = jnp.zeros_like(stage_fn(params,
                                   jax.lax.dynamic_index_in_dim(
                                       x, 0, axis=0, keepdims=False)))
    outputs0 = jnp.zeros((num_micro,) + buf0.shape, buf0.dtype)
    # newer jax: constants entering the loop must be device-varying; no-op
    # when the value is already varying or pvary doesn't exist
    buf0 = _ensure_varying(buf0, axis)
    outputs0 = _ensure_varying(outputs0, axis)
    _, outputs = jax.lax.fori_loop(0, num_micro + num_stages - 1, tick,
                                   (buf0, outputs0))
    # outputs live on the last stage; broadcast them to all stages so the
    # loss is computable everywhere (psum of masked value)
    mask = (stage == num_stages - 1).astype(outputs.dtype)
    outputs = jax.lax.psum(outputs * mask, axis)
    return outputs


def spmd_pipeline_1f1b(stage_fn: Callable, loss_fn: Callable, stacked_params,
                       x, labels, num_stages: int, num_micro: int,
                       axis: str = "pp"):
    """Compiled 1F1B pipeline-parallel training step (run under shard_map
    over `axis`).  Returns (mean_loss, param_grads) — grads are this stage's
    slice, averaged over microbatches.

    The TPU-native re-design of the reference 1F1B schedule
    (fleet/meta_parallel/pipeline_parallel.py:80 forward_backward_pipeline,
    C++ section_worker.cc:153 Run1F1B): instead of host-driven send_v2/recv_v2
    p2p ops, the whole schedule is ONE XLA program.  Every tick each stage
    runs one forward microbatch (activations handed forward by ppermute) and
    one backward microbatch (cotangents handed backward by ppermute), with
    grads accumulated in the loop carry:

        tick t, stage s:  fwd microbatch  f = t - s
                          bwd microbatch  b = t - 2(num_stages-1) + s

    so stage s holds at most 2(num_stages-1-s)+1 in-flight activations (the
    1F1B memory bound, vs num_micro for GPipe fill-drain).  Only stage
    INPUTS are saved; backward recomputes the stage forward inside jax.vjp
    (same cost as the reference's recompute interval = full).

    stage_fn(params_slice, microbatch) -> microbatch_out, homogeneous across
    stages; loss_fn(last_stage_out, label_microbatch) -> scalar (mean).
    x/labels: (num_micro, micro_batch, ...), read by stage 0 / stage n-1.
    """
    n, m = num_stages, num_micro
    stage = jax.lax.axis_index(axis)
    params = jax.tree_util.tree_map(lambda p: p[0], stacked_params)

    fwd_perm = [(i, i + 1) for i in range(n - 1)]
    bwd_perm = [(i + 1, i) for i in range(n - 1)]
    depth = 2 * n - 1  # input ring depth (stage 0's worst case)

    x0 = jax.lax.dynamic_index_in_dim(x, 0, axis=0, keepdims=False)
    out_shape = jax.eval_shape(stage_fn, params, x0)

    def masked_loss_and_seed(out, f_idx, f_valid):
        """Last stage: loss of this tick's fwd microbatch + its cotangent."""
        lbl = jax.lax.dynamic_index_in_dim(
            labels, jnp.clip(f_idx, 0, m - 1), axis=0, keepdims=False)
        loss, ct = jax.value_and_grad(loss_fn)(out.astype(jnp.float32), lbl)
        keep = f_valid.astype(loss.dtype)
        return loss * keep, ct.astype(out.dtype)

    def tick(t, carry):
        fwd_buf, bwd_buf, ring, grad_acc, loss_acc = carry

        # ---- forward phase -------------------------------------------------
        f = t - stage
        f_valid = jnp.logical_and(f >= 0, f < m)
        fresh = jax.lax.dynamic_index_in_dim(
            x, jnp.clip(f, 0, m - 1), axis=0, keepdims=False)
        x_in = jnp.where(stage == 0, fresh, fwd_buf).astype(fwd_buf.dtype)
        slot = jnp.clip(jnp.remainder(f, depth), 0, depth - 1)
        ring = jax.lax.dynamic_update_index_in_dim(
            ring, jnp.where(f_valid, 1.0, 0.0).astype(ring.dtype) * x_in
            + jnp.where(f_valid, 0.0, 1.0).astype(ring.dtype)
            * jax.lax.dynamic_index_in_dim(ring, slot, 0, keepdims=False),
            slot, axis=0)
        out = stage_fn(params, x_in)

        # last stage computes the loss + backward seed for f (b == f there)
        loss_f, ct_seed = masked_loss_and_seed(
            out, f, jnp.logical_and(f_valid, stage == n - 1))
        loss_acc = loss_acc + loss_f

        # ---- backward phase ------------------------------------------------
        b = t - 2 * (n - 1) + stage
        b_valid = jnp.logical_and(b >= 0, b < m)
        b_slot = jnp.clip(jnp.remainder(b, depth), 0, depth - 1)
        x_b = jax.lax.dynamic_index_in_dim(ring, b_slot, 0, keepdims=False)
        ct_in = jnp.where(stage == n - 1, ct_seed, bwd_buf)
        _, vjp = jax.vjp(stage_fn, params, x_b)
        dparams, dx = vjp(ct_in.astype(out.dtype))
        keep = b_valid
        grad_acc = jax.tree_util.tree_map(
            lambda a, d: a + jnp.where(keep, d.astype(a.dtype), 0.0),
            grad_acc, dparams)

        # ---- rotate --------------------------------------------------------
        fwd_buf = jax.lax.ppermute(out, axis, fwd_perm)
        bwd_buf = jax.lax.ppermute(dx, axis, bwd_perm)
        return fwd_buf, bwd_buf, ring, grad_acc, loss_acc

    fwd_buf0 = jnp.zeros(out_shape.shape, out_shape.dtype)
    bwd_buf0 = jnp.zeros(out_shape.shape, out_shape.dtype)
    ring0 = jnp.zeros((depth,) + x0.shape, x0.dtype)
    grad0 = jax.tree_util.tree_map(
        lambda p: jnp.zeros(p.shape, jnp.float32), params)
    loss0 = jnp.zeros((), jnp.float32)
    carry = tuple(_ensure_varying(c, axis) for c in
                  (fwd_buf0, bwd_buf0, ring0))
    carry += (jax.tree_util.tree_map(lambda g: _ensure_varying(g, axis),
                                     grad0),
              _ensure_varying(loss0, axis))
    _, _, _, grad_acc, loss_acc = jax.lax.fori_loop(
        0, m + 2 * (n - 1), tick, carry)
    # loss lives on the last stage; make it global
    loss = jax.lax.psum(jnp.where(stage == n - 1, loss_acc, 0.0), axis) / m
    grads = jax.tree_util.tree_map(lambda g: (g / m)[None], grad_acc)
    return loss, grads


class PipelineParallel(Layer):
    """Model wrapper for pp mode (fleet dispatch target,
    reference pipeline_parallel.py:30).

    train_batch(data, optimizer, lr_scheduler, scaler) runs the compiled
    SPMD pipeline step (built lazily by paddle_tpu.jit/TrainStep with the
    pipeline transform) — see tests/test_pipeline.py for the shard_map
    driving pattern.
    """

    def __init__(self, layers, hcg=None, strategy=None):
        super().__init__()
        self._layers = layers
        self.add_sublayer("_layers", layers)
        self._hcg = hcg
        self.accumulate_steps = 1
        if strategy is not None:
            self.accumulate_steps = strategy.pipeline_configs.accumulate_steps

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """One pipeline training step: split the batch into
        ``accumulate_steps`` microbatches, run each through the stage
        chunks, accumulate grads, then apply ONE optimizer step — the
        observable contract of the reference's 1F1B train_batch
        (pipeline_parallel.py:80: microbatch grad accumulation + single
        update).  Single-process rendering: stage handoffs are in-process
        (the multi-device compiled schedule is ``spmd_pipeline_1f1b``,
        where the same warmup/steady/cooldown interleave runs as one XLA
        program over the 'pp' mesh axis).
        """
        from .. import ops

        x, y = data
        acc = max(int(self.accumulate_steps), 1)
        batch = x.shape[0]
        if batch % acc:
            raise ValueError(
                "train_batch: batch size %d not divisible by "
                "accumulate_steps %d" % (batch, acc))
        mb = batch // acc
        total = None
        for i in range(acc):
            xi = x[i * mb:(i + 1) * mb]
            yi = y[i * mb:(i + 1) * mb]
            # forward through the stage chunks in order (the in-process
            # analogue of recv_forward -> stage -> send_forward)
            h = xi
            for s in range(self._layers.num_stages):
                for layer in self._layers.get_stage_layers(s):
                    h = layer(h)
            if self._layers.loss_fn is not None:
                loss = self._layers.loss_fn(h, yi)
            else:
                loss = ops.mean(h)
            scaled = loss / acc
            if scaler is not None:
                scaled = scaler.scale(scaled)
            scaled.backward()  # grads ACCUMULATE across microbatches
            total = loss.detach() if total is None else total + loss.detach()
        if scaler is not None:
            scaler.step(optimizer)
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return total / acc
