"""Pipeline parallelism (reference:
fleet/meta_parallel/pipeline_parallel.py:30 PipelineParallel — 1F1B at
forward_backward_pipeline:80; parallel_layers/pp_layers.py:132 PipelineLayer,
LayerDesc:31, SegmentLayers:63; C++ twin framework/section_worker.cc:153).

TPU-native rethink (SURVEY.md §7 "hard parts"): no per-op streams or p2p
send_v2/recv_v2 ops.  The whole pipeline is ONE jitted SPMD program:
parameters of the (structurally identical) stages are stacked on a leading
stage dim sharded over the 'pp' mesh axis; microbatches stream through a
``lax.fori_loop`` whose per-tick stage handoff is a single
``lax.ppermute`` over ICI — the schedule the fleet_executor's credit-based
interceptors (N25) approximated with RPC is here a compiled collective
rotation.  Backward comes from jax.grad over the same program (GPipe-style;
XLA overlaps the reverse permutes the same way).
"""
from __future__ import annotations

import functools
from typing import Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..nn.layer.layers import Layer, LayerList


class LayerDesc:
    """reference parity: pp_layers.py:31 — lazy layer description."""

    def __init__(self, layer_class, *args, **kwargs):
        self.layer_class = layer_class
        self.args = args
        self.kwargs = kwargs

    def build_layer(self):
        return self.layer_class(*self.args, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    """reference parity: pp_layers.py:49 — weight shared across stages
    (e.g. embedding/softmax tying).

    All descs with the same ``key`` share ONE parameter object: the first
    occurrence owns it, later occurrences alias it (so eager autograd
    accumulates both the lookup and the head cotangents on the same
    ``Parameter``, and ``named_parameters``' id-dedup gives the optimizer a
    single entry).  ``forward_func(layer, x)``, when given, replaces the
    later occurrence's forward — e.g. the tied logits matmul.  In the
    compiled pipeline the shared grads are combined by a psum over the
    'pp' axis (the reference's shared-embedding allreduce,
    pipeline_parallel.py cooldown)."""

    def __init__(self, key, layer_class, *args, forward_func=None,
                 shared_weight_attr="weight", **kwargs):
        super().__init__(layer_class, *args, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class _SharedCall(Layer):
    """Wrap a later occurrence of a SharedLayerDesc so its forward runs
    ``forward_func(shared_layer, x)`` (reference: PipelineLayer's
    shared-layer dispatch in pp_layers.py)."""

    def __init__(self, layer, fn):
        super().__init__()
        self.shared = layer
        self._fn = fn

    def forward(self, x):
        if self._fn is None:
            return self.shared(x)
        return self._fn(self.shared, x)


class SegmentLayers:
    """reference parity: pp_layers.py:63 — uniform or param-weighted
    partition of N layers into num_stages segments."""

    def __init__(self, layers_desc, num_parts, method="uniform"):
        self.descs = layers_desc
        self.num_parts = num_parts
        self.method = method

    def do_segment(self) -> List[int]:
        n = len(self.descs)
        if self.method == "uniform":
            base = n // self.num_parts
            rem = n % self.num_parts
            bounds = [0]
            for i in range(self.num_parts):
                bounds.append(bounds[-1] + base + (1 if i < rem else 0))
            return bounds
        raise NotImplementedError(self.method)


class PipelineLayer(Layer):
    """reference parity: pp_layers.py:132 — build only this stage's chunk.

    On TPU the "stage" is a mesh coordinate, not a process; when used under
    the SPMD pipeline all stages exist in one program, so by default the
    full layer list is built and staged via `spmd_pipeline`.
    """

    def __init__(self, layers, num_stages=None, topology=None,
                 loss_fn=None, seg_method="uniform", recompute_interval=0):
        super().__init__()
        self.descs = list(layers)
        self.num_stages = num_stages or 1
        self.loss_fn = loss_fn
        self.recompute_interval = recompute_interval
        built = []
        self.shared_groups = {}   # key -> [(layer, desc), ...]
        for d in self.descs:
            layer = d.build_layer() if isinstance(d, LayerDesc) else d
            if isinstance(d, SharedLayerDesc):
                grp = self.shared_groups.setdefault(d.layer_name, [])
                if grp:
                    first_layer, first_desc = grp[0]
                    # tie: later occurrences alias the first's parameter
                    setattr(layer, d.shared_weight_attr,
                            getattr(first_layer,
                                    first_desc.shared_weight_attr))
                grp.append((layer, d))
                if grp[1:]:
                    layer = _SharedCall(layer, d.forward_func)
            built.append(layer)
        self.run_function = LayerList(built)
        self.segment_bounds = SegmentLayers(
            built, self.num_stages, seg_method).do_segment()

    def get_stage_layers(self, stage_id):
        lo, hi = self.segment_bounds[stage_id], self.segment_bounds[stage_id + 1]
        return self.run_function[lo:hi]

    def forward(self, x):
        for layer in self.run_function:
            x = layer(x)
        return x


from .collective import ensure_varying as _ensure_varying  # noqa: E402


def _ensure_varying_axes(arr, axes):
    for a in axes:
        arr = _ensure_varying(arr, a)
    return arr


# NOTE on manual tensor parallelism inside the pipeline: a Megatron
# column/row-parallel block under shard_map needs NO explicit 'f' operator
# (identity-fwd/allreduce-bwd, reference c_identity_op) — jax's
# varying-manual-axes autodiff inserts the backward psum automatically at
# every unvarying->varying boundary (the transpose of the implicit pvary
# where a replicated activation meets an mp-sharded weight), and the
# forward output psum's transpose is the identity.  Writing the f operator
# by hand DOUBLE-counts dx.  Only the forward output psum is spelled out.


def spmd_pipeline(stage_fn: Callable, stacked_params, x, num_stages: int,
                  num_micro: int, axis: str = "pp"):
    """Run a pipeline INSIDE a shard_map over `axis`.

    stage_fn(params_slice, microbatch) -> microbatch_out
    stacked_params: pytree whose leaves have leading dim == num_stages
        (under shard_map each device sees its slice, leading dim 1).
    x: (num_micro, micro_batch, ...) — full input on stage 0's slot.

    Classic collective-permute schedule: T = num_micro + num_stages - 1 ticks;
    each tick every stage processes one buffer then rotates it forward.
    """
    stage = jax.lax.axis_index(axis)
    params = jax.tree_util.tree_map(lambda p: p[0], stacked_params)

    fwd_perm = [(i, (i + 1) % num_stages) for i in range(num_stages)]

    def tick(t, carry):
        buf, outputs = carry
        # stage 0 ingests microbatch t (if in range); others use rotated buf
        mb_idx = jnp.clip(t, 0, num_micro - 1)
        fresh = jax.lax.dynamic_index_in_dim(x, mb_idx, axis=0, keepdims=False)
        inp = jnp.where(stage == 0, fresh, buf)
        out = stage_fn(params, inp)
        # last stage records its finished microbatch (t - num_stages + 1)
        done_idx = t - (num_stages - 1)
        record = jnp.logical_and(stage == num_stages - 1, done_idx >= 0)
        outputs = jax.lax.cond(
            record,
            lambda o: jax.lax.dynamic_update_index_in_dim(
                o, out, jnp.clip(done_idx, 0, num_micro - 1), axis=0),
            lambda o: o,
            outputs)
        # rotate activations to the next stage
        buf = jax.lax.ppermute(out, axis, fwd_perm)
        return buf, outputs

    buf0 = jnp.zeros_like(stage_fn(params,
                                   jax.lax.dynamic_index_in_dim(
                                       x, 0, axis=0, keepdims=False)))
    outputs0 = jnp.zeros((num_micro,) + buf0.shape, buf0.dtype)
    # newer jax: constants entering the loop must be device-varying; no-op
    # when the value is already varying or pvary doesn't exist
    buf0 = _ensure_varying(buf0, axis)
    outputs0 = _ensure_varying(outputs0, axis)
    _, outputs = jax.lax.fori_loop(0, num_micro + num_stages - 1, tick,
                                   (buf0, outputs0))
    # outputs live on the last stage; broadcast them to all stages so the
    # loss is computable everywhere (psum of masked value)
    mask = (stage == num_stages - 1).astype(outputs.dtype)
    outputs = jax.lax.psum(outputs * mask, axis)
    return outputs


def spmd_pipeline_1f1b(stage_fn: Callable, loss_fn: Callable, stacked_params,
                       x, labels, num_stages: int, num_micro: int,
                       axis: str = "pp"):
    """Compiled 1F1B pipeline-parallel training step (run under shard_map
    over `axis`).  Returns (mean_loss, param_grads) — grads are this stage's
    slice, averaged over microbatches.

    The TPU-native re-design of the reference 1F1B schedule
    (fleet/meta_parallel/pipeline_parallel.py:80 forward_backward_pipeline,
    C++ section_worker.cc:153 Run1F1B): instead of host-driven send_v2/recv_v2
    p2p ops, the whole schedule is ONE XLA program.  Every tick each stage
    runs one forward microbatch (activations handed forward by ppermute) and
    one backward microbatch (cotangents handed backward by ppermute), with
    grads accumulated in the loop carry:

        tick t, stage s:  fwd microbatch  f = t - s
                          bwd microbatch  b = t - 2(num_stages-1) + s

    so stage s holds at most 2(num_stages-1-s)+1 in-flight activations (the
    1F1B memory bound, vs num_micro for GPipe fill-drain).  Only stage
    INPUTS are saved; backward recomputes the stage forward inside jax.vjp
    (same cost as the reference's recompute interval = full).

    stage_fn(params_slice, microbatch) -> microbatch_out, homogeneous across
    stages; loss_fn(last_stage_out, label_microbatch) -> scalar (mean).
    x/labels: (num_micro, micro_batch, ...), read by stage 0 / stage n-1.
    """
    n, m = num_stages, num_micro
    stage = jax.lax.axis_index(axis)
    params = jax.tree_util.tree_map(lambda p: p[0], stacked_params)

    fwd_perm = [(i, i + 1) for i in range(n - 1)]
    bwd_perm = [(i + 1, i) for i in range(n - 1)]
    depth = 2 * n - 1  # input ring depth (stage 0's worst case)

    x0 = jax.lax.dynamic_index_in_dim(x, 0, axis=0, keepdims=False)
    out_shape = jax.eval_shape(stage_fn, params, x0)

    def masked_loss_and_seed(out, f_idx, f_valid):
        """Last stage: loss of this tick's fwd microbatch + its cotangent."""
        lbl = jax.lax.dynamic_index_in_dim(
            labels, jnp.clip(f_idx, 0, m - 1), axis=0, keepdims=False)
        loss, ct = jax.value_and_grad(loss_fn)(out.astype(jnp.float32), lbl)
        # where, not multiply: warmup/drain ticks run loss_fn on garbage
        # ring contents, and NaN*0 = NaN would poison loss_acc (ADVICE r3,
        # same fix as the hetero schedule)
        return jnp.where(f_valid, loss, 0.0), ct.astype(out.dtype)

    def tick(t, carry):
        fwd_buf, bwd_buf, ring, grad_acc, loss_acc = carry

        # ---- forward phase -------------------------------------------------
        f = t - stage
        f_valid = jnp.logical_and(f >= 0, f < m)
        fresh = jax.lax.dynamic_index_in_dim(
            x, jnp.clip(f, 0, m - 1), axis=0, keepdims=False)
        x_in = jnp.where(stage == 0, fresh, fwd_buf).astype(fwd_buf.dtype)
        slot = jnp.clip(jnp.remainder(f, depth), 0, depth - 1)
        ring = jax.lax.dynamic_update_index_in_dim(
            ring, jnp.where(f_valid, 1.0, 0.0).astype(ring.dtype) * x_in
            + jnp.where(f_valid, 0.0, 1.0).astype(ring.dtype)
            * jax.lax.dynamic_index_in_dim(ring, slot, 0, keepdims=False),
            slot, axis=0)
        out = stage_fn(params, x_in)

        # last stage computes the loss + backward seed for f (b == f there)
        loss_f, ct_seed = masked_loss_and_seed(
            out, f, jnp.logical_and(f_valid, stage == n - 1))
        loss_acc = loss_acc + loss_f

        # ---- backward phase ------------------------------------------------
        b = t - 2 * (n - 1) + stage
        b_valid = jnp.logical_and(b >= 0, b < m)
        b_slot = jnp.clip(jnp.remainder(b, depth), 0, depth - 1)
        x_b = jax.lax.dynamic_index_in_dim(ring, b_slot, 0, keepdims=False)
        ct_in = jnp.where(stage == n - 1, ct_seed, bwd_buf)
        _, vjp = jax.vjp(stage_fn, params, x_b)
        dparams, dx = vjp(ct_in.astype(out.dtype))
        keep = b_valid
        grad_acc = jax.tree_util.tree_map(
            lambda a, d: a + jnp.where(keep, d.astype(a.dtype), 0.0),
            grad_acc, dparams)

        # ---- rotate --------------------------------------------------------
        fwd_buf = jax.lax.ppermute(out, axis, fwd_perm)
        bwd_buf = jax.lax.ppermute(dx, axis, bwd_perm)
        return fwd_buf, bwd_buf, ring, grad_acc, loss_acc

    fwd_buf0 = jnp.zeros(out_shape.shape, out_shape.dtype)
    bwd_buf0 = jnp.zeros(out_shape.shape, out_shape.dtype)
    ring0 = jnp.zeros((depth,) + x0.shape, x0.dtype)
    grad0 = jax.tree_util.tree_map(
        lambda p: jnp.zeros(p.shape, jnp.float32), params)
    loss0 = jnp.zeros((), jnp.float32)
    carry = tuple(_ensure_varying(c, axis) for c in
                  (fwd_buf0, bwd_buf0, ring0))
    carry += (jax.tree_util.tree_map(lambda g: _ensure_varying(g, axis),
                                     grad0),
              _ensure_varying(loss0, axis))
    _, _, _, grad_acc, loss_acc = jax.lax.fori_loop(
        0, m + 2 * (n - 1), tick, carry)
    # loss lives on the last stage; make it global
    loss = jax.lax.psum(jnp.where(stage == n - 1, loss_acc, 0.0), axis) / m
    grads = jax.tree_util.tree_map(lambda g: (g / m)[None], grad_acc)
    return loss, grads


def spmd_pipeline_1f1b_hetero(embed_fn: Callable, block_fn: Callable,
                              head_loss_fn: Callable, params, x, labels,
                              num_stages: int, blocks_per_stage: int,
                              num_micro: int, axis: str = "pp",
                              batch_axes: tuple = (), loss_scale=None,
                              embed_grad_shard=None):
    """Compiled 1F1B for HETEROGENEOUS stages (embedding / blocks / head) —
    the shape of a real language model, which the homogeneous
    ``spmd_pipeline_1f1b`` cannot express (VERDICT r2 Missing #2).

    Roles instead of stage clones (reference: pp_layers.py:49
    SharedLayerDesc + the shared-embedding allreduce in
    fleet/meta_parallel/pipeline_parallel.py cooldown):

    * ``params["embed"]`` — replicated over `axis`; the embedding forward
      runs on every stage each tick (cheap) and is SELECTED into the
      pipeline on stage 0; its grads receive the stage-0 lookup cotangent
      AND the last-stage tied-head cotangent, combined by ONE psum over
      `axis` — the TPU rendering of the reference's shared-weight
      allreduce over the embedding group.
    * ``params["blocks"]`` — leaves of shape (num_stages, blocks_per_stage,
      ...), sharded over `axis`; each stage runs its blocks_per_stage
      blocks sequentially.
    * ``params["head"]`` — replicated; consumed by ``head_loss_fn`` on the
      last stage (masked elsewhere).  For tied embeddings the head tree is
      empty and ``head_loss_fn`` reads the weight from the embed tree.

    Signatures:
        embed_fn(embed_params, raw_microbatch) -> h         (uniform)
        block_fn(one_block_params, h) -> h
        head_loss_fn(head_params, embed_params, h, label_mb) -> scalar
    x: (num_micro, mb, ...) raw inputs (any dtype — e.g. int token ids);
    labels: (num_micro, mb, ...).

    ``batch_axes``: data-parallel mesh axes the microbatch dims are sharded
    over (dp×pp composition in ONE program, reference 4-D topology
    fleet/base/topology.py:54): the loss is additionally averaged and every
    grad psum'd over them.  Tensor-parallel axes need no declaration here —
    the forward mp collectives live inside block_fn/head_loss_fn, and the
    backward input-edge allreduce is inserted by jax's vma-typed autodiff
    (see the NOTE above — do NOT hand-write the Megatron 'f' operator).

    ``embed_grad_shard``: optional ``(axis_name, axis_size)`` — shard the
    per-stage f32 embedding-grad ACCUMULATOR's large leaves (row-split)
    over that mesh axis (r4 verdict Weak #5/#10: the hetero schedule
    otherwise replicates the full accumulator per stage — ~8x the grad
    memory of a 256k-vocab model at pp=8).  Each tick's contribution is
    psum_scatter'd (mask first, so warmup garbage never crosses ranks);
    the full grads are restored by ONE tiled all_gather at the end, so
    the return contract is unchanged.

    Returns (mean_loss, grads) with grads matching the params structure
    (blocks grads carry the local leading stage dim of 1).
    """
    n, m = num_stages, num_micro
    stage = jax.lax.axis_index(axis)
    # mark the replicated trees device-varying: under shard_map's varying
    # manual axes, jax.grad of a REPLICATED input auto-psums the cotangent
    # over `axis` (transpose-of-broadcast), which would fold every stage's
    # unmasked garbage partials into each tick's dhead/dembed; pvary keeps
    # grads per-device so the masked accumulation + the one explicit psum
    # below stay the single source of cross-stage combination
    vaxes = (axis,) + tuple(batch_axes)
    embed_p = jax.tree_util.tree_map(
        lambda a: _ensure_varying_axes(a, vaxes), params["embed"])
    head_p = jax.tree_util.tree_map(
        lambda a: _ensure_varying_axes(a, vaxes), params["head"])
    blocks_p = jax.tree_util.tree_map(lambda p: p[0], params["blocks"])
    blocks_p = jax.tree_util.tree_map(
        lambda a: _ensure_varying_axes(a, tuple(batch_axes)), blocks_p)

    fwd_perm = [(i, i + 1) for i in range(n - 1)]
    bwd_perm = [(i + 1, i) for i in range(n - 1)]
    depth = 2 * n - 1

    def stage_fwd(bp, h):
        for i in range(blocks_per_stage):
            h = block_fn(jax.tree_util.tree_map(lambda l: l[i], bp), h)
        return h

    def raw_mb(idx):
        return jax.lax.dynamic_index_in_dim(
            x, jnp.clip(idx, 0, m - 1), axis=0, keepdims=False)

    def label_mb(idx):
        return jax.lax.dynamic_index_in_dim(
            labels, jnp.clip(idx, 0, m - 1), axis=0, keepdims=False)

    x0 = raw_mb(0)
    h_shape = jax.eval_shape(embed_fn, embed_p, x0)

    def masked_add(acc_tree, d_tree, keep):
        return jax.tree_util.tree_map(
            lambda a, d: a + jnp.where(keep, d.astype(a.dtype), 0.0),
            acc_tree, d_tree)

    es_axis, es_n = embed_grad_shard if embed_grad_shard else (None, 1)
    if es_axis is not None and es_axis not in batch_axes:
        raise ValueError(
            "embed_grad_shard axis %r must be one of the batch_axes %r "
            "(its per-tick psum_scatter IS the data-axis grad reduction)"
            % (es_axis, batch_axes))

    def _es_shardable(p):
        # row-split only the big leaves (the wte); small ones stay whole
        return (es_axis is not None and p.ndim >= 2
                and p.shape[0] % es_n == 0
                and p.size >= _EMBED_SHARD_MIN_ELEMS)

    def masked_add_embed(acc_tree, d_tree, keep):
        def one(a, d):
            contrib = jnp.where(keep, d.astype(a.dtype), 0.0)
            if a.shape != d.shape:
                # sharded accumulator row-slice: reduce over the shard
                # axis AND keep only this rank's rows in one collective
                contrib = jax.lax.psum_scatter(
                    contrib, es_axis, scatter_dimension=0, tiled=True)
            return a + contrib
        return jax.tree_util.tree_map(one, acc_tree, d_tree)

    def tick(t, carry):
        (fwd_buf, bwd_buf, ring, g_embed, g_blocks, g_head, loss_acc) = carry

        # ---- forward ------------------------------------------------------
        f = t - stage
        f_valid = jnp.logical_and(f >= 0, f < m)
        h0 = embed_fn(embed_p, raw_mb(f))
        x_in = jnp.where(stage == 0, h0, fwd_buf).astype(fwd_buf.dtype)
        slot = jnp.clip(jnp.remainder(f, depth), 0, depth - 1)
        keep_f = jnp.where(f_valid, 1.0, 0.0).astype(ring.dtype)
        ring = jax.lax.dynamic_update_index_in_dim(
            ring, keep_f * x_in + (1.0 - keep_f) *
            jax.lax.dynamic_index_in_dim(ring, slot, 0, keepdims=False),
            slot, axis=0)
        out = stage_fwd(blocks_p, x_in)

        # last stage: loss + cotangent seed + head/tied-embed grads for f.
        # ``loss_scale`` (fp16 GradScaler, reference loss_scaler.py:40)
        # multiplies the loss INSIDE the grad target so every cotangent —
        # including the fp16 ct_seed fed backward through the stages — is
        # scaled before any half-precision cast can underflow it; grads
        # come out scaled, the caller unscales after the psum.
        is_last_f = jnp.logical_and(f_valid, stage == n - 1)

        def scaled_head_loss(hp, ep, o):
            ls = head_loss_fn(hp, ep, o, label_mb(f))
            return ls * loss_scale if loss_scale is not None else ls

        (loss_f, (dhead_f, dembed_hf, ct_seed)) = jax.value_and_grad(
            scaled_head_loss,
            argnums=(0, 1, 2))(head_p, embed_p, out.astype(jnp.float32))
        # mask with where, not multiply: head_loss_fn runs on EVERY stage
        # every tick, including warmup ticks fed zero/permuted garbage —
        # a bf16 overflow there would make NaN*0 = NaN poison loss_acc
        # permanently even though the tick is masked out (ADVICE r3)
        loss_acc = loss_acc + jnp.where(is_last_f, loss_f, 0.0)
        g_head = masked_add(g_head, dhead_f, is_last_f)
        g_embed = masked_add_embed(g_embed, dembed_hf, is_last_f)

        # ---- backward -----------------------------------------------------
        b = t - 2 * (n - 1) + stage
        b_valid = jnp.logical_and(b >= 0, b < m)
        b_slot = jnp.clip(jnp.remainder(b, depth), 0, depth - 1)
        x_b = jax.lax.dynamic_index_in_dim(ring, b_slot, 0, keepdims=False)
        ct_in = jnp.where(stage == n - 1, ct_seed.astype(out.dtype), bwd_buf)
        _, vjp = jax.vjp(stage_fwd, blocks_p, x_b)
        dblocks, dx = vjp(ct_in.astype(out.dtype))
        g_blocks = masked_add(g_blocks, dblocks, b_valid)
        # stage 0 continues the chain into the embedding for microbatch b
        is_first_b = jnp.logical_and(b_valid, stage == 0)
        _, vjp_e = jax.vjp(lambda ep: embed_fn(ep, raw_mb(b)), embed_p)
        (dembed_b,) = vjp_e(dx.astype(h_shape.dtype))
        g_embed = masked_add_embed(g_embed, dembed_b, is_first_b)

        fwd_buf = jax.lax.ppermute(out, axis, fwd_perm)
        bwd_buf = jax.lax.ppermute(dx, axis, bwd_perm)
        return (fwd_buf, bwd_buf, ring, g_embed, g_blocks, g_head, loss_acc)

    def _zeros_matching_vma(p):
        """Grad accumulator for p: f32 zeros marked varying over the same
        manual axes as p itself (e.g. an mp-sharded block weight's grads
        are mp-varying; a mismatched carry fails shard_map's vma check)."""
        z = jnp.zeros(p.shape, jnp.float32)
        try:
            vma = jax.typeof(p).vma
        except Exception:
            return z
        return _ensure_varying_axes(z, tuple(vma))

    zeros_like_tree = lambda tree: jax.tree_util.tree_map(
        _zeros_matching_vma, tree)

    def _embed_acc_zeros(p):
        z = _zeros_matching_vma(p)
        if _es_shardable(p):
            z = z[: p.shape[0] // es_n]
            # layout assert (r4 verdict #10 done-criterion): the
            # accumulator really is the row slice, not the full tree
            assert z.shape[0] * es_n == p.shape[0]
        return z

    fwd_buf0 = jnp.zeros(h_shape.shape, h_shape.dtype)
    carry = (fwd_buf0, jnp.zeros_like(fwd_buf0),
             jnp.zeros((depth,) + h_shape.shape, h_shape.dtype),
             jax.tree_util.tree_map(_embed_acc_zeros, embed_p),
             zeros_like_tree(blocks_p),
             zeros_like_tree(head_p), jnp.zeros((), jnp.float32))
    carry = jax.tree_util.tree_map(
        lambda c: _ensure_varying_axes(c, vaxes), carry)
    (_, _, _, g_embed, g_blocks, g_head, loss_acc) = jax.lax.fori_loop(
        0, m + 2 * (n - 1), tick, carry)

    loss = jax.lax.psum(
        jnp.where(stage == n - 1, loss_acc, 0.0), axis) / m
    if loss_scale is not None:
        # report the UNSCALED loss; grads stay scaled for the caller's
        # unscale + global finite check (GradScaler contract)
        loss = loss / loss_scale
    # shared/replicated grads: combine the stage-0 (lookup) and last-stage
    # (head) contributions — the reference's shared-embedding allreduce
    g_embed = jax.tree_util.tree_map(
        lambda g: jax.lax.psum(g, axis) / m, g_embed)
    g_head = jax.tree_util.tree_map(
        lambda g: jax.lax.psum(g, axis) / m, g_head)
    g_blocks = jax.tree_util.tree_map(lambda g: (g / m)[None], g_blocks)
    for a in batch_axes:
        # dp composition: batch-sharded microbatches -> grad allreduce and
        # loss mean over the data axis (fleet DP semantics)
        na = jax.lax.psum(1, a)
        loss = jax.lax.psum(loss, a) / na
        g_blocks, g_head = jax.tree_util.tree_map(
            lambda g: jax.lax.psum(g, a) / na, (g_blocks, g_head))
        # sharded embed-grad leaves were already reduced over es_axis by
        # the per-tick psum_scatter — only the mean division remains
        g_embed = jax.tree_util.tree_map(
            lambda g, p: g / na if (a == es_axis and g.shape != p.shape)
            else jax.lax.psum(g, a) / na, g_embed, embed_p)
    # restore full rows for the caller (ONE tiled gather per big leaf)
    g_embed = jax.tree_util.tree_map(
        lambda g, p: jax.lax.all_gather(g, es_axis, axis=0, tiled=True)
        if g.shape != p.shape else g, g_embed, embed_p)
    return loss, {"embed": g_embed, "blocks": g_blocks, "head": g_head}


#: warn when the hetero schedule would replicate more f32 embedding grad
#: accumulator than this per pipeline stage (VERDICT r3 Weak #3)
_EMBED_REPLICATION_WARN_BYTES = 512 * 1024 * 1024

#: embed-grad leaves at or above this element count accumulate ROW-SHARDED
#: (embed_grad_shard): only the big arrays (the wte) are worth the
#: per-tick psum_scatter; small leaves stay whole.  Module-level so tests
#: can lower it to force the sharded path on tiny models.
_EMBED_SHARD_MIN_ELEMS = 1 << 20


def _grads_finite(grads):
    """ONE fused finite check (reference check_finite_and_unscale_op.cc
    semantics): a running per-leaf max(|g|) accumulated to a single scalar
    — inf/nan poison the running max (lax.max propagates NaN), but unlike
    a global |g|-SUM a large-but-finite gradient set cannot overflow f32
    to inf and silently skip the step.  Still one tiny scalar chain that
    fuses into the unscale pass, vs the ~150 per-leaf
    isfinite->all->stack->all reductions it originally replaced (r4
    verdict Weak #6)."""
    total = jnp.float32(0.0)
    for g in jax.tree_util.tree_leaves(grads):
        if g.size == 0:
            continue  # max has no identity for empty leaves (sum had 0)
        total = jnp.maximum(total,
                            jnp.max(jnp.abs(g).astype(jnp.float32)))
    return jnp.isfinite(total)


class _CompiledPipelineStep:
    """Bridge from the fleet PipelineLayer API onto the compiled 1F1B.

    Contract (checked loudly): the layer list is [input/embedding layer,
    N homogeneous blocks, head layer] with N divisible by the 'pp' axis
    size — the shape of a transformer LM.  Tied weights declared through
    SharedLayerDesc are held once (in the embed tree) and their grads
    psum-combined over 'pp' inside the pipeline program."""

    def __init__(self, pipeline_layer: "PipelineLayer", optimizer,
                 num_stages: int, num_micro: int,
                 use_scaler: bool = False, zero_stage: int = 1):
        from jax.sharding import NamedSharding, PartitionSpec
        from . import mesh as mesh_mod
        from ..jit import functional_call

        layers = list(pipeline_layer.run_function)
        if len(layers) < num_stages + 2:
            raise ValueError(
                "compiled pipeline needs [input layer, blocks..., head] "
                "with at least one block per stage; got %d layers for "
                "pp=%d" % (len(layers), num_stages))
        self._embed_layer = layers[0]
        self._head_layer = layers[-1]
        blocks = layers[1:-1]
        if len(blocks) % num_stages:
            raise ValueError(
                "compiled pipeline: %d blocks not divisible by pp=%d"
                % (len(blocks), num_stages))
        states = [b.functional_state() for b in blocks]
        keys0 = sorted(states[0])
        for s in states[1:]:
            if sorted(s) != keys0:
                raise ValueError(
                    "compiled pipeline: blocks are not structurally "
                    "identical (param trees differ) — heterogeneous blocks "
                    "cannot be stacked over the 'pp' axis")
        self._blocks = blocks
        self._block_keys = keys0
        if pipeline_layer.loss_fn is None:
            raise ValueError(
                "the compiled pipeline needs PipelineLayer(loss_fn=...) — "
                "the 1F1B schedule computes loss and cotangents on the last "
                "stage inside the compiled program")
        self._loss_layer = pipeline_layer.loss_fn
        self._optimizer = optimizer
        self._num_stages = num_stages
        self._num_micro = num_micro
        self._use_scaler = use_scaler
        self._zero_stage = zero_stage
        self._mesh = mesh_mod.ensure_mesh()
        # dp x pp composition: microbatch rows sharded over a 'dp' axis
        # when the mesh has one (grads psum'd / loss averaged over it by
        # spmd_pipeline_1f1b_hetero's batch_axes)
        self._dp = dict(zip(self._mesh.axis_names,
                            self._mesh.devices.shape)).get("dp", 1)
        self._fcall = functional_call
        bps = len(blocks) // num_stages
        self._bps = bps

        embed_sd = self._embed_layer.state_dict()
        head_sd = self._head_layer.state_dict()
        # tied params: any head entry whose Parameter IS an embed entry
        embed_by_id = {id(t): k for k, t in embed_sd.items()}
        self._tied = {hk: embed_by_id[id(t)] for hk, t in head_sd.items()
                      if id(t) in embed_by_id}

        embed_p = {k: t._array for k, t in embed_sd.items()}
        # hetero-pipeline cost model (VERDICT r3 Weak #3): embed_fn runs on
        # every stage every tick and each stage carries a full f32 embed
        # grad accumulator — fine at GPT-2 scale (~200 MB/stage), but a
        # 256k-vocab model would replicate GBs per stage.  Warn before the
        # first compile rather than silently ballooning HBM.
        embed_bytes = sum(
            int(np.prod(t.shape)) * 4 for t in embed_p.values()
            if hasattr(t, "shape"))
        if embed_bytes > _EMBED_REPLICATION_WARN_BYTES:
            import warnings
            warnings.warn(
                "compiled pipeline: the embedding tree is %.1f GB (f32 "
                "grad accumulator) and is REPLICATED per pipeline stage "
                "by the hetero 1F1B schedule; at this vocab size consider "
                "tensor-parallel (VocabParallelEmbedding) or a sharded "
                "embedding before pp" % (embed_bytes / 2**30))
        head_p = {k: t._array for k, t in head_sd.items()
                  if k not in self._tied}
        blocks_p = {
            k: jnp.stack([s[k] for s in states]).reshape(
                (num_stages, bps) + states[0][k].shape)
            for k in keys0}
        rep = NamedSharding(self._mesh, PartitionSpec())
        ppshard = NamedSharding(self._mesh, PartitionSpec("pp"))
        self.params = {
            "embed": {k: jax.device_put(v, rep) for k, v in embed_p.items()},
            "blocks": {k: jax.device_put(v, ppshard)
                       for k, v in blocks_p.items()},
            "head": {k: jax.device_put(v, rep) for k, v in head_p.items()},
        }
        self.opt_state = optimizer.init_state(self.params)
        # ZeRO-1 x pipeline (the reference's full 4-D [data, pipe,
        # sharding, model] topology, fleet/base/topology.py:54): with an
        # 'sdp' mesh axis the optimizer slots shard over it — the update
        # runs OUTSIDE the shard_map in the same jitted program, so GSPMD
        # partitions it against the slot layout exactly as
        # TrainStep(zero_stage=1) does
        self._sdp = dict(zip(self._mesh.axis_names,
                             self._mesh.devices.shape)).get("sdp", 1)
        if self._sdp > 1:
            from .sharding import _stage_spec_for, shard_optimizer_state

            def place_block(leaf):
                # block slots keep the stage dim on 'pp' AND shard the
                # largest remaining divisible dim over 'sdp' (same pick +
                # min-size policy as the plain ZeRO-1 layout)
                if not (hasattr(leaf, "ndim") and leaf.ndim > 0):
                    return leaf
                return jax.device_put(leaf, NamedSharding(
                    self._mesh,
                    _stage_spec_for(leaf, "sdp", fixed=("pp",))))

            slots = self.opt_state["slots"]
            slots = {"embed": shard_optimizer_state(slots["embed"], "sdp"),
                     "blocks": jax.tree_util.tree_map(place_block,
                                                      slots["blocks"]),
                     "head": shard_optimizer_state(slots["head"], "sdp")}
            self.opt_state = {**self.opt_state, "slots": slots}
        else:
            self.opt_state = jax.device_put(self.opt_state)  # replicate
        self._step = None

    # -- functional wrappers ------------------------------------------------
    def _embed_fn(self, ep, raw):
        out, _ = self._fcall(self._embed_layer, ep, Tensor(raw))
        return out

    def _block_fn(self, bp, h):
        out, _ = self._fcall(self._blocks[0], bp, Tensor(h))
        return out

    def _head_loss_fn(self, hp, ep, h, lbl):
        state = dict(hp)
        for hk, ek in self._tied.items():
            state[hk] = ep[ek]
        out, _ = self._fcall(self._head_layer, state, Tensor(h))
        loss = self._loss_layer(Tensor(out), Tensor(lbl))
        return loss._array if isinstance(loss, Tensor) else loss

    def _build(self):
        from jax.sharding import PartitionSpec as P
        try:
            from jax import shard_map
        except ImportError:  # older jax: only the experimental spelling
            from jax.experimental.shard_map import shard_map

        n, m, bps = self._num_stages, self._num_micro, self._bps
        pspec = {"embed": jax.tree_util.tree_map(
                     lambda _: P(), self.params["embed"]),
                 "blocks": jax.tree_util.tree_map(
                     lambda _: P("pp"), self.params["blocks"]),
                 "head": jax.tree_util.tree_map(
                     lambda _: P(), self.params["head"])}

        # microbatch rows shard over BOTH 'dp' and 'sdp': in the reference
        # 4-D topology the sharding group IS a data-parallel group
        # (different data per sharding rank, grads combined across it) —
        # replicating batches over 'sdp' would halve data throughput while
        # doing fully redundant compute (ADVICE r3)
        data_axes = tuple(a for a, sz in (("dp", self._dp),
                                          ("sdp", self._sdp)) if sz > 1)
        batch_axes = data_axes
        data_spec = P(None, data_axes) if data_axes else P()
        use_scaler = self._use_scaler

        # shard the per-stage embedding-grad accumulator over 'sdp' when
        # available (r4 verdict #10); 'dp' works identically when there is
        # no sharding axis
        es = None
        if self._sdp > 1:
            es = ("sdp", self._sdp)
        elif self._dp > 1:
            es = ("dp", self._dp)
        pipe = shard_map(
            lambda p, x_, l_, sc: spmd_pipeline_1f1b_hetero(
                self._embed_fn, self._block_fn, self._head_loss_fn,
                p, x_, l_, n, bps, m, batch_axes=batch_axes,
                loss_scale=sc if use_scaler else None,
                embed_grad_shard=es),
            mesh=self._mesh,
            in_specs=(pspec, data_spec, data_spec, P()),
            out_specs=(P(), pspec),
        )

        opt = self._optimizer

        # ZeRO-2 x pipeline (VERDICT r3 Missing #4; reference
        # sharding_optimizer.py hybrid dp/sharding/mp/pp rings): constrain
        # every grad to the SLOT layout over 'sdp' inside the same program
        # — GSPMD then lowers the data-axis grad psum + this layout into a
        # reduce-scatter, so each sdp rank holds only its slot shard of
        # the grads (the same `_stage_spec_for` layout the ZeRO-1 slots
        # already use; stage 2 = slots AND grads scattered).
        zero2 = self._zero_stage >= 2 and self._sdp > 1
        if zero2:
            from jax.sharding import NamedSharding
            from .sharding import _stage_spec_for

            def scatter_grads(grads):
                def c(tree, fixed=()):
                    return jax.tree_util.tree_map(
                        lambda g: jax.lax.with_sharding_constraint(
                            g, NamedSharding(self._mesh, _stage_spec_for(
                                g, "sdp", fixed=fixed)))
                        if hasattr(g, "ndim") and g.ndim > 0 else g, tree)
                return {"embed": c(grads["embed"]),
                        "blocks": c(grads["blocks"], fixed=("pp",)),
                        "head": c(grads["head"])}

            # exposed for tests: the exact grads apply_gradients consumes
            self._grads_debug = jax.jit(
                lambda params, x, labels: scatter_grads(
                    pipe(params, x, labels, jnp.float32(1.0))[1]))

        def full_step(params, opt_state, lr, scale, x, labels):
            loss, grads = pipe(params, x, labels, scale)
            if zero2:
                grads = scatter_grads(grads)
            if use_scaler:
                # fp16 GradScaler semantics (reference loss_scaler.py:40 +
                # pipeline_parallel.py:80 scaler arg): unscale the psum'd
                # grads, global finite-check, SKIP the whole update on
                # overflow (opt_state select reverts the step counter too)
                inv = (1.0 / scale).astype(jnp.float32)
                grads = jax.tree_util.tree_map(
                    lambda g: g * inv.astype(g.dtype), grads)
                finite = _grads_finite(grads)
                new_params, new_opt = opt.apply_gradients(
                    params, grads, opt_state, lr)
                keep = lambda new, old: jax.tree_util.tree_map(
                    lambda a, b: jnp.where(finite, a, b)
                    if hasattr(a, "dtype") else a, new, old)
                return (loss, finite, keep(new_params, params),
                        keep(new_opt, opt_state))
            new_params, new_opt = opt.apply_gradients(
                params, grads, opt_state, lr)
            return loss, jnp.bool_(True), new_params, new_opt

        # recorded for the trace-tier donation audit (TPU502): params and
        # opt_state are the two donated trees; a miss doubles peak HBM
        self._donate_argnums = (0, 1)
        # recompile watchdog: the 1F1B schedule is compile-once — a second
        # program means the microbatch geometry is churning per step
        from ..observability.watchdog import watch
        self._step = watch(
            "pipeline.1f1b_step",
            jax.jit(full_step, donate_argnums=self._donate_argnums),
            expected=1)

    def step(self, x, y, scale=None):
        x_a = x._array if isinstance(x, Tensor) else jnp.asarray(x)
        y_a = y._array if isinstance(y, Tensor) else jnp.asarray(y)
        m = self._num_micro
        batch = x_a.shape[0]
        mb = batch // m
        data_par = self._dp * self._sdp
        if data_par > 1 and mb % data_par:
            raise ValueError(
                "microbatch size %d not divisible by the data-parallel "
                "extent dp*sdp=%d — the compiled pipeline shards "
                "microbatch rows over ('dp', 'sdp')" % (mb, data_par))
        x_a = x_a.reshape((m, mb) + x_a.shape[1:])
        y_a = y_a.reshape((m, mb) + y_a.shape[1:])
        if self._step is None:
            self._build()
        lr = jnp.asarray(self._optimizer.get_lr(), jnp.float32)
        scale_a = jnp.asarray(1.0 if scale is None else scale, jnp.float32)
        loss, finite, self.params, self.opt_state = self._step(
            self.params, self.opt_state, lr, scale_a, x_a, y_a)
        return Tensor(loss), finite

    def adopt_opt_state(self, opt_state):
        """Carry a prior compiled step's optimizer state (same optimizer,
        same param tree) into this one.  Only re-place leaves whose NEW
        slot carries an explicit NamedSharding (the ZeRO 'sdp' layout may
        differ across rebuilds); otherwise KEEP the old placement — the
        fresh init's leaves sit committed on the default device, and
        adopting that would wedge single-device slots against the
        mesh-sharded params."""
        from jax.sharding import NamedSharding

        def place(old, new):
            if hasattr(new, "sharding") \
                    and isinstance(new.sharding, NamedSharding) \
                    and hasattr(old, "shape"):
                return jax.device_put(jnp.asarray(old), new.sharding)
            return old
        self.opt_state = jax.tree_util.tree_map(place, opt_state,
                                                self.opt_state)

    def sync_to_layers(self):
        self._embed_layer.load_functional_state(
            dict(self.params["embed"]))
        head_state = dict(self.params["head"])
        for hk, ek in self._tied.items():
            head_state[hk] = self.params["embed"][ek]
        self._head_layer.load_functional_state(head_state)
        for i, b in enumerate(self._blocks):
            s, j = divmod(i, self._bps)
            b.load_functional_state(
                {k: self.params["blocks"][k][s, j]
                 for k in self._block_keys})


class PipelineParallel(Layer):
    """Model wrapper for pp mode (fleet dispatch target,
    reference pipeline_parallel.py:30).

    train_batch(data, optimizer, lr_scheduler, scaler) runs the compiled
    SPMD pipeline step (built lazily by paddle_tpu.jit/TrainStep with the
    pipeline transform) — see tests/test_pipeline.py for the shard_map
    driving pattern.
    """

    def __init__(self, layers, hcg=None, strategy=None):
        super().__init__()
        self._layers = layers
        self.add_sublayer("_layers", layers)
        self._hcg = hcg
        self.accumulate_steps = 1
        self.sharding_stage = 1
        if strategy is not None:
            self.accumulate_steps = strategy.pipeline_configs.accumulate_steps
            self.sharding_stage = strategy.sharding_configs.stage
        self._compiled = None     # lazy _CompiledPipelineStep

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def _pp_mesh_axis(self):
        """The 'pp' mesh axis size, if a mesh with one is active."""
        from . import mesh as mesh_mod
        mesh = mesh_mod.get_mesh()
        if mesh is not None and "pp" in mesh.axis_names:
            return dict(zip(mesh.axis_names, mesh.devices.shape))["pp"]
        return 1

    def sync_to_layers(self):
        """Write compiled-step arrays back into the eager layers."""
        if self._compiled is not None:
            self._compiled.sync_to_layers()

    def state_dict(self, *args, **kwargs):
        """Fleet parity: the reference's PipelineParallel.state_dict is
        always current.  After the compiled path has trained, the fresh
        arrays live in _CompiledPipelineStep.params — sync them back
        before exporting, or a checkpoint taken through this API would
        silently persist the untrained initial weights (ADVICE r3)."""
        self.sync_to_layers()
        return super().state_dict(*args, **kwargs)

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """One pipeline training step: split the batch into
        ``accumulate_steps`` microbatches, run each through the stage
        chunks, accumulate grads, then apply ONE optimizer step — the
        observable contract of the reference's 1F1B train_batch
        (pipeline_parallel.py:80: microbatch grad accumulation + single
        update).  Single-process rendering: stage handoffs are in-process
        (the multi-device compiled schedule is ``spmd_pipeline_1f1b``,
        where the same warmup/steady/cooldown interleave runs as one XLA
        program over the 'pp' mesh axis).
        """
        from .. import ops

        x, y = data
        acc = max(int(self.accumulate_steps), 1)
        batch = x.shape[0]
        if batch % acc:
            raise ValueError(
                "train_batch: batch size %d not divisible by "
                "accumulate_steps %d" % (batch, acc))
        if self._pp_mesh_axis() > 1:
            # a 'pp' mesh axis is active: run the COMPILED 1F1B schedule
            # (spmd_pipeline_1f1b_hetero) instead of in-process staging
            live_scaler = (scaler is not None
                           and getattr(scaler, "_enable", True))
            old_compiled = None
            if self._compiled is not None and (
                    self._compiled._optimizer is not optimizer
                    or self._compiled._num_micro != acc
                    or self._compiled._use_scaler != live_scaler
                    or self._compiled._zero_stage != self.sharding_stage):
                # rebuild on change (the reference's re-wrap semantics):
                # sync the trained arrays back into the eager layers so
                # the new compiled step starts from them, then recompile
                # with the new optimizer/accumulate_steps/scaler/stage
                self._compiled.sync_to_layers()
                old_compiled = self._compiled
                self._compiled = None
            if self._compiled is None:
                self._compiled = _CompiledPipelineStep(
                    self._layers, optimizer, self._pp_mesh_axis(), acc,
                    use_scaler=live_scaler,
                    zero_stage=self.sharding_stage)
                if old_compiled is not None \
                        and old_compiled._optimizer is optimizer:
                    # SAME optimizer across the rebuild: carry its state
                    # (Adam moments + step counter) instead of silently
                    # restarting bias correction mid-run; a DIFFERENT
                    # optimizer keeps its fresh init
                    self._compiled.adopt_opt_state(old_compiled.opt_state)
            if live_scaler:
                # fp16 loss scaling through the compiled program
                # (reference pipeline_parallel.py:80 takes `scaler`): the
                # jitted step scales the loss, unscales + finite-checks
                # grads and skips the update on overflow; the host-side
                # scaler bookkeeping (good/bad streaks, scale growth and
                # halving) consumes the returned flag
                loss, finite = self._compiled.step(
                    x, y, scale=scaler.get_loss_scaling())
                scaler._found_inf = not bool(finite)
                scaler._update()
            else:
                loss, _ = self._compiled.step(x, y)
            if lr_scheduler is not None:
                lr_scheduler.step()
            return loss
        mb = batch // acc
        total = None
        for i in range(acc):
            xi = x[i * mb:(i + 1) * mb]
            yi = y[i * mb:(i + 1) * mb]
            # forward through the stage chunks in order (the in-process
            # analogue of recv_forward -> stage -> send_forward)
            h = xi
            for s in range(self._layers.num_stages):
                for layer in self._layers.get_stage_layers(s):
                    h = layer(h)
            if self._layers.loss_fn is not None:
                loss = self._layers.loss_fn(h, yi)
            else:
                loss = ops.mean(h)
            scaled = loss / acc
            if scaler is not None:
                scaled = scaler.scale(scaled)
            scaled.backward()  # grads ACCUMULATE across microbatches
            total = loss.detach() if total is None else total + loss.detach()
        if scaler is not None:
            scaler.step(optimizer)
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return total / acc


class PipelinePreconditionError(RuntimeError):
    """This ENVIRONMENT cannot build the canonical pipeline program (e.g.
    too few devices for the mesh) — distinct from a genuinely broken
    builder, so the trace-tier registry can record a skip for the former
    and a hard operational error for the latter."""


def canonical_1f1b_step(num_stages: int = 4, num_micro: int = 4,
                        d: int = 16, mb: int = 2, lr: float = 0.05):
    """Registry hook for the trace-tier audit (paddle_tpu.analysis.trace):
    a self-contained jitted 1F1B train-like step over a ('pp',) mesh —
    shard_map'd :func:`spmd_pipeline_1f1b` plus an SGD update with the
    params donated, i.e. the same donation/collective structure
    :class:`_CompiledPipelineStep` builds, at audit-sized shapes.

    Returns ``(jitted_fn, args, meta)`` where ``meta`` carries the
    declared mesh axes and per-flat-input donation labels the TPU502/503
    passes check against.  Raises :class:`PipelinePreconditionError` when
    fewer than ``num_stages`` devices are available (the registry records
    that as a skip; any OTHER exception is a broken builder and fails the
    audit)."""
    from jax.sharding import Mesh, PartitionSpec as P
    try:
        from jax import shard_map
    except ImportError:  # jax<=0.4.x: only the experimental spelling
        from jax.experimental.shard_map import shard_map

    devices = jax.devices()
    if len(devices) < num_stages:
        raise PipelinePreconditionError(
            "canonical_1f1b_step needs %d devices, have %d (force a CPU "
            "mesh with --xla_force_host_platform_device_count)"
            % (num_stages, len(devices)))
    mesh = Mesh(np.asarray(devices[:num_stages]), ("pp",))

    def stage_fn(params, x):
        h = jnp.tanh(x @ params["w1"] + params["b1"])
        return h @ params["w2"] + x

    def loss_fn(out, label):
        return jnp.mean((out - label) ** 2)

    rng = np.random.RandomState(0)
    params = {
        "w1": jnp.asarray(rng.randn(num_stages, d, d) * 0.3, jnp.float32),
        "b1": jnp.asarray(rng.randn(num_stages, d) * 0.1, jnp.float32),
        "w2": jnp.asarray(rng.randn(num_stages, d, d) * 0.3, jnp.float32),
    }
    x = jnp.asarray(rng.randn(num_micro, mb, d), jnp.float32)
    labels = jnp.asarray(rng.randn(num_micro, mb, d), jnp.float32)

    pspec = jax.tree_util.tree_map(lambda _: P("pp"), params)
    pipe = shard_map(
        lambda p, x_, l_: spmd_pipeline_1f1b(
            stage_fn, loss_fn, p, x_, l_, num_stages, num_micro),
        mesh=mesh, in_specs=(pspec, P(), P()),
        out_specs=(P(), pspec), check_rep=False)

    def full_step(params, x, labels):
        loss, grads = pipe(params, x, labels)
        new_params = jax.tree_util.tree_map(
            lambda p, g: p - lr * g, params, grads)
        return loss, new_params

    jitted = jax.jit(full_step, donate_argnums=(0,))
    flat, _ = jax.tree_util.tree_flatten_with_path((params, x, labels))
    labels_by_idx = {i: "args" + jax.tree_util.keystr(kp)
                     for i, (kp, _v) in enumerate(flat)}
    meta = {"mesh_axes": {"pp": num_stages},
            "donate_labels": labels_by_idx,
            "kind": "pipeline"}
    return jitted, (params, x, labels), meta
