"""Collective communication API (reference surface:
python/paddle/distributed/collective.py — all_reduce:580, new_group:314,
split:1481 etc; kernels: paddle/fluid/operators/collective/ N19,
ProcessGroupNCCL N22).

TPU-native semantics: a collective is *data parallel code inside a
shard_map/pjit trace* — `all_reduce` is `lax.psum` over a mesh axis riding
ICI/DCN, not an NCCL ring kernel.  Outside any trace (plain eager,
single-process), collectives are identities over world_size-1 groups, which
matches reference behavior for a 1-rank group.

Group model: a group names a mesh axis (default axis: "dp"); under
shard_map the axis must be in scope.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from ..core.dispatch import call
from ..core.tensor import Tensor
from . import mesh as _mesh

__all__ = ["ReduceOp", "all_reduce", "all_gather", "reduce_scatter",
           "broadcast", "reduce", "scatter", "alltoall", "all_to_all",
           "send", "recv", "barrier", "new_group", "get_group",
           "wait", "split_group_axis"]


class ReduceOp:
    SUM = 0
    MAX = 1
    MIN = 2
    PROD = 3
    AVG = 4


class Group:
    """A named communication group bound to a mesh axis."""

    def __init__(self, axis: str, ranks=None, gid=0):
        self.axis = axis
        self.ranks = ranks or []
        self.id = gid

    @property
    def nranks(self):
        return max(_mesh.axis_size(self.axis), 1)

    world_size = nranks

    @property
    def rank(self):
        try:
            return int(jax.lax.axis_index(self.axis))
        except NameError:
            return 0

    def __repr__(self):
        return f"Group(axis={self.axis}, nranks={self.nranks})"


_groups = {}
_default_axis = "dp"


def ensure_varying(arr, axis):
    """Promote a constant to device-varying for scan carries inside
    shard_map (vma typing on newer jax).  pcast is the current spelling;
    pvary is the deprecated one (ADVICE r4: the silent no-op fallback
    would break carries once pvary is removed — pcast-first avoids it)."""
    try:
        return jax.lax.pcast(arr, axis, to="varying")
    except (AttributeError, TypeError, ValueError):
        try:
            return jax.lax.pvary(arr, axis)
        except (AttributeError, ValueError):
            return arr


def _axis_of(group) -> str:
    if group is None:
        return _default_axis
    if isinstance(group, Group):
        return group.axis
    if isinstance(group, str):
        return group
    ax = getattr(group, "axis", None)
    if ax is not None:
        return ax
    return _default_axis


def axis_in_trace(axis: str) -> bool:
    """PUBLIC: True when `axis` is bound as a manual mesh axis in the
    current shard_map/pmap trace (both directions pinned by
    tests/test_distributed.py).  Collective dispatch and the
    sequence-parallel attention routing key on this."""
    try:
        jax.lax.axis_index(axis)
        return True
    except NameError:
        return False
    except Exception:
        return False


_in_trace = axis_in_trace  # internal alias (historical name)


def new_group(ranks=None, backend=None, axis=None, timeout=None):
    gid = len(_groups) + 1
    g = Group(axis or _default_axis, ranks, gid)
    _groups[gid] = g
    return g


def get_group(gid=0):
    return _groups.get(gid) or Group(_default_axis)


def split_group_axis(axis: str):
    """Scope helper to retarget the default axis."""
    import contextlib

    @contextlib.contextmanager
    def ctx():
        global _default_axis
        prev = _default_axis
        _default_axis = axis
        try:
            yield
        finally:
            _default_axis = prev

    return ctx()


def _apply(tensor, raw, name):
    if isinstance(tensor, Tensor):
        out = call(raw, tensor, name=name)
        # paddle collectives are in-place on the input tensor
        tensor._array = out._array
        tensor._grad_node = out._grad_node
        tensor._out_index = out._out_index
        if out._grad_node is not None:
            tensor._stop_gradient = False
        return tensor
    return raw(tensor)


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    """reference: collective.py:580; kernel c_allreduce_op.h:348 → on TPU a
    single lax.psum over the group's mesh axis (XLA ICI collective)."""
    axis = _axis_of(group)

    def raw(x):
        if not _in_trace(axis):
            return x  # world of 1 outside shard_map
        if op == ReduceOp.SUM:
            return jax.lax.psum(x, axis)
        if op == ReduceOp.MAX:
            return jax.lax.pmax(x, axis)
        if op == ReduceOp.MIN:
            return jax.lax.pmin(x, axis)
        if op == ReduceOp.AVG:
            return jax.lax.pmean(x, axis)
        if op == ReduceOp.PROD:
            return jnp.exp(jax.lax.psum(jnp.log(x), axis))
        raise ValueError(f"op {op}")

    return _apply(tensor, raw, "all_reduce")


def all_gather(tensor_list, tensor=None, group=None, sync_op=True, axis=0):
    """reference: collective.py all_gather; c_allgather_op."""
    grp_axis = _axis_of(group)
    if tensor is None:
        tensor = tensor_list
        tensor_list = None

    as_list = tensor_list is not None

    def raw(x):
        if not _in_trace(grp_axis):
            return x[None] if as_list else x
        # list form stacks per-rank shards; tensor form concatenates on dim 0
        return jax.lax.all_gather(x, grp_axis, axis=0, tiled=not as_list)

    out = call(raw, tensor, name="all_gather")
    if as_list:
        from .. import ops
        parts = ops.unbind(out, 0)
        tensor_list.clear()
        tensor_list.extend(parts)
        return tensor_list
    return out


def all_gather_object(obj_list, obj, group=None):
    obj_list.clear()
    obj_list.append(obj)
    return obj_list


def reduce_scatter(tensor, tensor_list=None, op=ReduceOp.SUM, group=None,
                   sync_op=True):
    """c_reducescatter_op → lax.psum_scatter."""
    axis = _axis_of(group)

    def raw(x):
        if not _in_trace(axis):
            return x
        return jax.lax.psum_scatter(x, axis, scatter_dimension=0, tiled=True)

    src = tensor_list if tensor_list is not None else tensor
    if isinstance(src, (list, tuple)):
        from .. import ops
        src = ops.concat(list(src), axis=0)
    out = call(raw, src, name="reduce_scatter")
    if isinstance(tensor, Tensor):
        tensor._array = out._array
        return tensor
    return out


def broadcast(tensor, src=0, group=None, sync_op=True):
    """c_broadcast_op → under SPMD all shards already see src's value after
    an all_reduce of the masked value; in-trace uses axis_index masking."""
    axis = _axis_of(group)

    def raw(x):
        if not _in_trace(axis):
            return x
        idx = jax.lax.axis_index(axis)
        masked = jnp.where(idx == src, x, jnp.zeros_like(x))
        return jax.lax.psum(masked, axis)

    return _apply(tensor, raw, "broadcast")


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    axis = _axis_of(group)

    def raw(x):
        if not _in_trace(axis):
            return x
        summed = jax.lax.psum(x, axis) if op == ReduceOp.SUM else \
            jax.lax.pmax(x, axis) if op == ReduceOp.MAX else \
            jax.lax.pmin(x, axis)
        idx = jax.lax.axis_index(axis)
        return jnp.where(idx == dst, summed, x)

    return _apply(tensor, raw, "reduce")


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    axis = _axis_of(group)
    if tensor_list is None:
        return tensor

    def raw(stacked):
        if not _in_trace(axis):
            return stacked[src]
        idx = jax.lax.axis_index(axis)
        return jnp.take(stacked, idx, axis=0)

    from .. import ops
    stacked = ops.stack(list(tensor_list), axis=0)
    out = call(raw, stacked, name="scatter")
    if isinstance(tensor, Tensor):
        tensor._array = out._array
        return tensor
    return out


def alltoall(in_tensor_list, out_tensor_list=None, group=None, sync_op=True):
    """global_scatter/gather sibling (c_alltoall) → lax.all_to_all."""
    axis = _axis_of(group)
    from .. import ops
    if isinstance(in_tensor_list, (list, tuple)):
        x = ops.stack(list(in_tensor_list), axis=0)
    else:
        x = in_tensor_list

    def raw(x):
        if not _in_trace(axis):
            return x
        return jax.lax.all_to_all(x, axis, split_axis=0, concat_axis=0,
                                  tiled=False)

    out = call(raw, x, name="alltoall")
    if out_tensor_list is not None:
        parts = ops.unbind(out, 0)
        out_tensor_list.clear()
        out_tensor_list.extend(parts)
        return out_tensor_list
    return out


all_to_all = alltoall


def all_to_all_single(in_tensor, out_tensor=None, in_split_sizes=None,
                      out_split_sizes=None, group=None, sync_op=True):
    axis = _axis_of(group)

    def raw(x):
        if not _in_trace(axis):
            return x
        n = jax.lax.axis_size(axis) if hasattr(jax.lax, "axis_size") else \
            _mesh.axis_size(axis)
        resh = x.reshape((n, x.shape[0] // n) + x.shape[1:])
        out = jax.lax.all_to_all(resh, axis, split_axis=0, concat_axis=0,
                                 tiled=False)
        return out.reshape(x.shape)

    out = call(raw, in_tensor, name="all_to_all_single")
    if isinstance(out_tensor, Tensor):
        out_tensor._array = out._array
        return out_tensor
    return out


def send(tensor, dst=0, group=None, sync_op=True):
    """p2p send (send_v2). In-trace: expressed as ppermute with the matched
    recv (see parallel.pipeline for the paired usage)."""
    axis = _axis_of(group)

    def raw(x):
        if not _in_trace(axis):
            return x
        n = _mesh.axis_size(axis)
        return jax.lax.ppermute(x, axis, [(i, dst) for i in range(n)])

    return _apply(tensor, raw, "send")


def recv(tensor, src=0, group=None, sync_op=True):
    axis = _axis_of(group)

    def raw(x):
        if not _in_trace(axis):
            return x
        n = _mesh.axis_size(axis)
        return jax.lax.ppermute(x, axis, [(src, i) for i in range(n)])

    return _apply(tensor, raw, "recv")


def isend(tensor, dst=0, group=None):
    send(tensor, dst, group)
    return _DummyTask()


def irecv(tensor, src=0, group=None):
    recv(tensor, src, group)
    return _DummyTask()


class _DummyTask:
    def wait(self):
        return True

    def is_completed(self):
        return True


def barrier(group=None):
    """Execution barrier: on the XLA path programs are already bulk-
    synchronous; across processes use multihost sync when initialized."""
    try:
        import jax.experimental.multihost_utils as mh
        if jax.process_count() > 1:
            mh.sync_global_devices("paddle_tpu_barrier")
    except Exception:
        pass


def wait(tensor, group=None, use_calc_stream=True):
    if isinstance(tensor, Tensor) and hasattr(tensor._array, "block_until_ready"):
        tensor._array.block_until_ready()


def get_world_size(group=None):
    if group is not None:
        return _axis_size_or_world(_axis_of(group))
    try:
        return jax.process_count()
    except Exception:
        return 1


def _axis_size_or_world(axis):
    n = _mesh.axis_size(axis)
    return n if n > 1 else 1
