"""Auto-parallel Engine (reference: python/paddle/distributed/auto_parallel/
engine.py:50 — Engine.prepare:79 / fit:279 / evaluate / predict — plus
interface.py shard_tensor and process_mesh.py ProcessMesh).

TPU-native redesign: the reference builds dist-attr-annotated programs, runs
a Completer to propagate annotations, partitions per rank and inserts
collectives (its own GSPMD).  Here XLA's GSPMD *is* that pipeline, so the
Engine reduces to: annotate parameters (parallelize / per-Parameter pspec),
shard the input batch over the data axes, and drive one compiled TrainStep.
The planner/cost-model stage is subsumed by GSPMD's sharding propagation;
`Engine.cost` reports the mesh the propagation runs over.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec

from ..core.tensor import Tensor
from . import mesh as _mesh
from .parallel_base import parallelize, shard_dataloader

__all__ = ["Engine", "ProcessMesh", "shard_op"]


class ProcessMesh:
    """reference: auto_parallel/process_mesh.py:39 — a named device mesh.
    Thin view over distributed.mesh.init_mesh."""

    def __init__(self, mesh=None, dim_names=None, shape=None,
                 process_ids=None):
        import numpy as np
        devices = None
        if mesh is not None and dim_names is not None:
            arr = np.asarray(mesh)
            axes = {name: dim for name, dim in zip(dim_names, arr.shape)}
            # honor the explicit rank->coordinate assignment: order the
            # jax devices by the given ids (reference: process_mesh.py mesh
            # content IS the rank layout)
            all_devs = {d.id: d for d in __import__("jax").devices()}
            try:
                devices = [all_devs[int(i)] for i in arr.flatten()]
            except KeyError as e:
                raise ValueError(
                    f"ProcessMesh refers to unknown device id {e}") from None
        elif shape is not None and dim_names is not None:
            axes = {name: dim for name, dim in zip(dim_names, shape)}
        else:
            raise ValueError("ProcessMesh needs (mesh|shape) + dim_names")
        if process_ids is not None:
            raise NotImplementedError(
                "ProcessMesh(process_ids=...) is not supported in the TPU "
                "build — pass the ids as the `mesh` array instead")
        self.dim_names = list(dim_names)
        self.shape = [axes[n] for n in self.dim_names]
        # build the Mesh directly: the user's dim order and device layout
        # are honored verbatim (init_mesh would reorder to AXIS_ORDER)
        import numpy as np
        from jax.sharding import Mesh
        import jax as _jax
        if devices is None:
            n = int(np.prod(self.shape))
            devices = _jax.devices()[:n]
        self._jax_mesh = Mesh(
            np.asarray(devices).reshape(self.shape), tuple(self.dim_names))
        _mesh.set_mesh(self._jax_mesh)

    @property
    def mesh(self):
        return self._jax_mesh

    def __repr__(self):
        return f"ProcessMesh(shape={self.shape}, dim_names={self.dim_names})"


def shard_op(op_fn, process_mesh=None, in_shard_specs=None,
             out_shard_specs=None):
    """reference: auto_parallel/interface.py shard_op — constrain an op's
    inputs/outputs to shardings; on TPU this is with_sharding_constraint."""
    mesh = process_mesh.mesh if isinstance(process_mesh, ProcessMesh) else \
        (process_mesh or _mesh.ensure_mesh())

    def constrained(*args, **kwargs):
        from jax.sharding import NamedSharding

        from ..core.dispatch import call

        def put(v, spec):
            if spec is None:
                return v
            s = NamedSharding(mesh, PartitionSpec(*spec))
            if isinstance(v, Tensor):
                # through the dispatch layer so the tape records the
                # (identity-pullback) constraint — a bare Tensor() rebuild
                # would sever autograd for eager inputs
                return call(
                    lambda a: jax.lax.with_sharding_constraint(a, s), v,
                    name="shard_op_constraint")
            return jax.lax.with_sharding_constraint(v, s)

        if in_shard_specs is not None:
            # pad missing specs with None so extra args pass through
            specs = list(in_shard_specs) + \
                [None] * (len(args) - len(in_shard_specs))
            args = tuple(put(a, s) for a, s in zip(args, specs))
        out = op_fn(*args, **kwargs)
        if out_shard_specs is not None:
            if isinstance(out, tuple):
                out = tuple(put(o, s) for o, s in
                            zip(out, out_shard_specs))
            else:
                out = put(out, out_shard_specs[0])
        return out

    return constrained


class Engine:
    """reference: auto_parallel/engine.py:50.

    Usage (mirrors the reference)::

        engine = Engine(model, loss, optimizer, metrics, strategy)
        engine.prepare(mesh_axes={"dp": 4, "mp": 2})   # or a ProcessMesh
        engine.fit(train_dataset, epochs=2, batch_size=64)
        engine.evaluate(val_dataset)
        engine.predict(test_dataset)
    """

    def __init__(self, model=None, loss=None, optimizer=None, metrics=None,
                 strategy=None):
        self.model = model
        self.loss = loss
        self.optimizer = optimizer
        self.metrics = metrics if isinstance(metrics, (list, tuple)) else \
            ([metrics] if metrics is not None else [])
        self.strategy = strategy
        self._step = None
        self._mesh = None
        self._history = []

    # -- prepare ------------------------------------------------------------
    def prepare(self, mesh_axes=None, process_mesh=None, num_inputs=1,
                zero_stage=None, dp_axis="dp", mp_axis="mp", **kwargs):
        """Annotate parameters onto the mesh and build the compiled step
        (the reference's Completer+Partitioner+Resharder collapse into
        GSPMD at jit time)."""
        if isinstance(process_mesh, ProcessMesh):
            self._mesh = process_mesh.mesh
        elif mesh_axes:
            self._mesh = _mesh.init_mesh(mesh_axes)
        else:
            self._mesh = _mesh.ensure_mesh()
        self._dp_axis = dp_axis
        self._num_inputs = num_inputs
        parallelize(self.model, mesh=self._mesh, dp_axis=dp_axis,
                    mp_axis=mp_axis)
        if self.optimizer is not None and self.loss is not None:
            from ..jit import TrainStep
            axis_names = set(self._mesh.axis_names)
            in_spec = PartitionSpec(dp_axis) if dp_axis in axis_names \
                else PartitionSpec()
            self._step = TrainStep(
                self.model, self._loss_fn, self.optimizer,
                num_inputs=num_inputs, in_shardings=in_spec,
                zero_stage=zero_stage, **kwargs)
        return self

    def _loss_fn(self, *args):
        if callable(self.loss):
            return self.loss(*args)
        raise ValueError("Engine needs a callable loss")

    # -- training -----------------------------------------------------------
    def fit(self, train_data, epochs=1, batch_size=64, steps_per_epoch=None,
            log_freq=50, verbose=1):
        if self.optimizer is None or self.loss is None:
            raise ValueError(
                "Engine.fit needs both a loss and an optimizer — "
                "Engine(model, loss=..., optimizer=...) (reference: "
                "engine.py Engine.fit mode='train' requirements)")
        if self._step is None:
            self.prepare()
        loader = self._to_loader(train_data, batch_size, shuffle=True)
        for epoch in range(epochs):
            losses = []
            for i, batch in enumerate(loader):
                if steps_per_epoch is not None and i >= steps_per_epoch:
                    break
                loss = self._step(*self._flatten(batch))
                losses.append(float(loss))
                if verbose and log_freq and i % log_freq == 0:
                    print(f"[AutoParallel Engine] epoch {epoch} step {i} "
                          f"loss {losses[-1]:.5f}")
            self._history.append(
                {"epoch": epoch,
                 "loss": sum(losses) / max(len(losses), 1)})
        self._step.sync_to_model()
        return self._history

    def evaluate(self, valid_data, batch_size=64, steps=None, verbose=0):
        loader = self._to_loader(valid_data, batch_size, shuffle=False)
        was_training = getattr(self.model, "training", True)
        self.model.eval()
        for m in self.metrics:
            m.reset()
        total, count = 0.0, 0
        for i, batch in enumerate(loader):
            if steps is not None and i >= steps:
                break
            parts = self._flatten(batch)
            ni = getattr(self, "_num_inputs", 1)
            out = self.model(*parts[:ni])
            loss = self._loss_fn(out, *parts[ni:])
            total += float(loss)
            count += 1
            for m in self.metrics:
                m.update(m.compute(out, *parts[ni:]))
        if was_training:
            self.model.train()
        result = {"loss": total / max(count, 1)}
        for m in self.metrics:
            result[m.name() if callable(getattr(m, "name", None))
                   else type(m).__name__] = m.accumulate()
        return result

    def predict(self, test_data, batch_size=64, steps=None, verbose=0):
        loader = self._to_loader(test_data, batch_size, shuffle=False)
        was_training = getattr(self.model, "training", True)
        self.model.eval()
        outs = []
        for i, batch in enumerate(loader):
            if steps is not None and i >= steps:
                break
            parts = self._flatten(batch)
            outs.append(self.model(
                *parts[:getattr(self, "_num_inputs", 1)]))
        if was_training:
            self.model.train()
        return outs

    # -- introspection ------------------------------------------------------
    def cost(self, mode="train"):
        """The reference's planner/cost-model stage is subsumed by GSPMD's
        sharding propagation; this reports the active mesh layout the
        propagation runs over."""
        if self._step is None:
            raise RuntimeError("call prepare() first")
        return {"note": "XLA GSPMD subsumes the planner/cost model; the "
                        "compiled step is partitioned over this mesh",
                "mesh": {name: size for name, size in
                         zip(self._mesh.axis_names,
                             self._mesh.devices.shape)}}

    # -- helpers ------------------------------------------------------------
    def _to_loader(self, data, batch_size, shuffle):
        from ..io import DataLoader, Dataset
        if isinstance(data, DataLoader):
            loader = data
        elif isinstance(data, Dataset):
            loader = DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                                drop_last=True)
        else:
            return data  # already an iterable of batches
        dp = getattr(self, "_dp_axis", "dp")
        axis_names = set(self._mesh.axis_names) if self._mesh else set()
        if dp in axis_names and _mesh.axis_size(dp) > 1:
            loader = shard_dataloader(loader, mesh=self._mesh, axis=dp)
        return loader

    @staticmethod
    def _flatten(batch):
        if isinstance(batch, (list, tuple)):
            return tuple(batch)
        return (batch,)
