"""Decomposed collective matmuls for the tensor-parallel axis (ISSUE 20).

GSPMD lowers the Megatron pairs to *monolithic* collectives: the row
matmul's partial sums meet in one all-reduce, the LM head either
all-gathers the vocab-sharded table or all-gathers per-shard logits, and
the fused-qkv slice reshard becomes an all-to-all/all-gather per layer.
Each of those serializes the full transfer before (or after) the full
matmul.  This module rewrites each site as a **ppermute ring under
``shard_map``** on the existing ``('mp',)`` mesh so every step moves one
shard-sized block while the previous block's partial matmul is still on
the MXU — the classic collective-matmul overlap:

* ``row_parallel_matmul``  — matmul→all-reduce becomes partial-accumulate
  + chunked permute (matmul→reduce-scatter ring) followed by a ring
  all-gather.  At step ``t`` device ``d`` computes its partial of output
  block ``(d+t+1) mod n`` and adds the accumulator that just arrived from
  device ``d+1``; after ``n`` steps block ``d`` is fully reduced in place.
* ``column_parallel_matmul`` — forward is collective-free (identity);
  the ``custom_vjp`` backward runs the *transposed* collective (dx's
  matmul→all-reduce) through the same ring.
* ``lm_head_matmul``       — all-gather→matmul becomes a rotate-weights
  ring: each step matmuls the resident vocab shard into its slice of the
  logits while the next shard is in flight.
* ``qkv_heads``            — the fused-qkv reshard (PR 11's named
  follow-up): the column-sharded ``(B,S,3H/tp)`` projection output is
  re-dealt to the head-sharded q/k/v layout with three single-hop
  ppermutes (a bijection whenever ``gcd(3, tp) == 1`` — every
  power-of-two tp) instead of GSPMD's all-to-all + all-gather.

The switch is three-level — per-call arg > :func:`overlap_scope` >
``PADDLE_TPU_MP_OVERLAP`` env — and is read at TRACE time, so a jitted
program's lowering is decided once: off ⇒ the wrappers return ``None``
and callers keep today's GSPMD lowering bit-for-bit.

Numerics: the ring performs the same shard-local partial matmuls as
GSPMD's partitioned dot, summed in a fixed ring order.  For ``n = 2``
the two-term f32 sum is commutative, so greedy decode is bit-identical
to the monolithic lowering; for ``n > 2`` the reduction order differs
(associativity) and parity is tight-tolerance — the same caveat GSPMD
itself carries across all-reduce implementations.

Chunking: each ring block can be split into ``chunks`` column sub-blocks
permuted independently (more, smaller transfers to hide behind shorter
matmuls) — the knob the ``mp_overlap`` autotune family times on chip.
All bodies run with ``check_rep=False``: ppermute results are not
provably replicated to the rep checker even when they are by
construction.
"""
from __future__ import annotations

import contextlib
import os
import threading

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

from . import mesh as _mesh

MP_AXIS = "mp"
ENV_FLAG = "PADDLE_TPU_MP_OVERLAP"

_tls = threading.local()


# ---------------------------------------------------------------------------
# the overlap switch: per-call arg > scope > env, resolved at trace time
# ---------------------------------------------------------------------------

def _stack():
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def env_enabled() -> bool:
    return os.environ.get(ENV_FLAG, "").lower() in ("1", "true", "yes", "on")


@contextlib.contextmanager
def overlap_scope(enabled=True, chunks=None):
    """Pin the overlap switch (and optionally the ring chunk count) for
    everything traced inside — the serving engine wraps its entry traces
    in this so an engine built with ``overlap_comm=False`` stays
    monolithic even under ``PADDLE_TPU_MP_OVERLAP=1``."""
    _stack().append((bool(enabled), chunks))
    try:
        yield
    finally:
        _stack().pop()


def enabled(arg=None) -> bool:
    """Resolve the three-level switch: explicit arg > innermost scope >
    env.  ``None`` means "inherit"."""
    if arg is not None:
        return bool(arg)
    st = _stack()
    if st:
        return st[-1][0]
    return env_enabled()


def scope_chunks():
    st = _stack()
    return st[-1][1] if st else None


def active(arg=None, axis=MP_AXIS):
    """``(mesh, n)`` when an overlapped island should be built at this
    trace point: switch on AND the ambient mesh declares ``axis`` with
    size > 1.  ``None`` ⇒ caller keeps the GSPMD lowering."""
    if not enabled(arg):
        return None
    try:
        mesh = _mesh.get_mesh()
    except Exception:
        return None
    if mesh is None or axis not in getattr(mesh, "axis_names", ()):
        return None
    n = int(mesh.shape[axis])
    if n < 2:
        return None
    return mesh, n


# -- trace-time viability checks (callers branch BEFORE building the op,
# so the off/non-viable path is byte-identical to today's lowering) ---------

def row_viable(k_dim, arg=None):
    """Row matmul: the sharded contraction dim must split over the mesh."""
    act = active(arg)
    return act is not None and int(k_dim) % act[1] == 0


def col_viable(k_dim, n_dim, arg=None):
    """Column matmul: sharded output dim splits; the backward ring also
    blocks the contraction dim over the mesh."""
    act = active(arg)
    return (act is not None and int(n_dim) % act[1] == 0
            and int(k_dim) % act[1] == 0)


def lm_viable(v_dim, arg=None):
    act = active(arg)
    return act is not None and int(v_dim) % act[1] == 0


def qkv_viable(num_heads, head_dim, arg=None):
    """The 3-ppermute re-deal needs gcd(3, tp) == 1 and head-aligned
    shards (``num_heads % tp == 0`` — the engine's own tp precondition)."""
    act = active(arg)
    if act is None:
        return False
    n = act[1]
    return n % 3 != 0 and int(num_heads) % n == 0


def embed_viable(vocab, arg=None):
    act = active(arg)
    return act is not None and int(vocab) % act[1] == 0


# ---------------------------------------------------------------------------
# chunk-count autotuning (the mp_overlap family) + the trace-time counter
# ---------------------------------------------------------------------------

def autotune_key(kind, m, k, n, n_dev, dtype):
    """``kind`` names the ring shape (row / colbwd / lmhead); m/k/n are
    the GLOBAL matmul dims (m = flattened batch rows)."""
    from ..kernels import autotune as at
    return {"kind": str(kind), "m": int(m), "k": int(k), "n": int(n),
            "n_dev": int(n_dev), "dtype": str(jnp.dtype(dtype)),
            "platform": at.platform()}


def _candidates(key):
    """chunks=1 (one permute per ring step — the safe default) first;
    2/4 only when the permuted block splits evenly."""
    n_dev = max(1, int(key.get("n_dev", 1)))
    if key.get("kind") == "lmhead":
        block = int(key.get("n", 0)) // n_dev      # vocab rows per shard
    else:
        block = int(key.get("n", 0)) // n_dev      # output cols per shard
    out = [{"variant": "chunks1", "config": {"chunks": 1}}]
    for c in (2, 4):
        if block > 0 and block % c == 0:
            out.append({"variant": "chunks%d" % c, "config": {"chunks": c}})
    return out


def _runner(cand, key):
    """Time the row ring at the key's shape on the first n_dev local
    devices (chip sessions tune the real transfer/compute ratio; the CPU
    fallback still exercises the code path)."""
    n_dev = int(key["n_dev"])
    devs = jax.devices()
    if len(devs) < n_dev:
        raise RuntimeError("mp_overlap needs %d devices, have %d"
                           % (n_dev, len(devs)))
    from jax.sharding import Mesh
    import numpy as np
    mesh = Mesh(np.asarray(devs[:n_dev]), (MP_AXIS,))
    dtype = jnp.dtype(key["dtype"])
    m, k, n = int(key["m"]), int(key["k"]), int(key["n"])
    chunks = int(cand["config"]["chunks"])
    x = jnp.ones((m, k), dtype)
    w = jnp.ones((k, n), dtype)

    def body(x_l, w_l):
        blk = _ring_mm_rs(x_l, w_l, MP_AXIS, n_dev, chunks)
        return _ring_ag(blk, MP_AXIS, n_dev, chunks)

    fn = jax.jit(shard_map(body, mesh=mesh,
                           in_specs=(P(None, MP_AXIS), P(MP_AXIS, None)),
                           out_specs=P(None, None), check_rep=False))
    fn(x, w).block_until_ready()   # compile outside the timed region

    def run():
        fn(x, w).block_until_ready()
    return run


def _register():
    from ..kernels import autotune as at
    # traceable stays None: the ring is an XLA-level schedule, not a
    # Pallas kernel — the TPU504 VMEM estimator has nothing to price and
    # the pallas/ trace tier must not grow per-chunk twins (the serving
    # tier registers the overlapped PROGRAMS instead)
    at.register_family("mp_overlap", _candidates, runner=_runner,
                       traceable=None)


_register()


def _resolve_chunks(kind, m, k, n, n_dev, dtype, block):
    """Scope pin > autotune resolve (pin > memo > cache > tune > default
    chunks=1), clamped to a divisor of the permuted block."""
    c = scope_chunks()
    if c is None:
        from ..kernels import autotune as at
        cand = at.resolve("mp_overlap",
                          autotune_key(kind, m, k, n, n_dev, dtype))
        c = cand.get("config", {}).get("chunks", 1)
    c = max(1, int(c))
    while block % c:
        c -= 1
    _note_chunks(c)
    return c


def _note_chunks(chunks):
    """Drive the ``mp.overlap_chunks`` counter at trace time — one inc
    per overlapped island built, valued at its ring chunk count (a
    compile-once program contributes once, matching the compile.count
    discipline)."""
    try:
        from ..observability import registry as _reg
        _reg.counter("mp.overlap_chunks").inc(int(chunks))
    except Exception:
        pass


# ---------------------------------------------------------------------------
# ring primitives (shard_map bodies; *_l arrays are per-device shards)
# ---------------------------------------------------------------------------

def _ring_mm_rs(x_l, w_l, axis, n, chunks):
    """matmul→reduce-scatter ring.  ``x_l (..., K/n)``, ``w_l (K/n, N)``;
    returns this device's fully-reduced output block ``(..., N/n)``.
    Block schedule: at step t device d computes its partial of block
    ``(d+t+1) mod n`` and adds the accumulator that just arrived from
    d+1 (permute direction d→d−1), so the in-flight permute hides behind
    the current partial matmul."""
    idx = lax.axis_index(axis)
    nb = w_l.shape[-1] // n
    sub = nb // chunks
    down = [(s, (s - 1) % n) for s in range(n)]

    def piece(i, j):
        return lax.dynamic_slice_in_dim(w_l, i * nb + j * sub, sub, axis=1)

    accs = [x_l @ piece((idx + 1) % n, j) for j in range(chunks)]
    for t in range(1, n):
        accs = [lax.ppermute(a, axis, down) for a in accs]
        accs = [a + x_l @ piece((idx + t + 1) % n, j)
                for j, a in enumerate(accs)]
    return accs[0] if chunks == 1 else jnp.concatenate(accs, axis=-1)


def _ring_ag(y_blk, axis, n, chunks):
    """Ring all-gather of per-device blocks along the last dim: after t
    permutes (direction d→d+1) the resident block is ``(d−t) mod n``;
    each lands in its slice of the full output."""
    idx = lax.axis_index(axis)
    nb = y_blk.shape[-1]
    sub = nb // chunks
    up = [(s, (s + 1) % n) for s in range(n)]
    out = jnp.zeros(y_blk.shape[:-1] + (nb * n,), y_blk.dtype)
    cur = ([y_blk] if chunks == 1 else
           [lax.dynamic_slice_in_dim(y_blk, j * sub, sub, axis=-1)
            for j in range(chunks)])
    for t in range(n):
        blk = (idx - t) % n
        if t + 1 < n:   # issue the permutes before the update slices so
            nxt = [lax.ppermute(p, axis, up) for p in cur]   # they overlap
        for j, piece in enumerate(cur):
            out = lax.dynamic_update_slice_in_dim(
                out, piece, blk * nb + j * sub, axis=-1)
        if t + 1 < n:
            cur = nxt
    return out


def _ring_lm(x_l, w_l, axis, n, chunks):
    """Rotate-weights all-gather→matmul ring for the LM head.  ``x_l``
    is the full ``(..., H)`` activation, ``w_l (V/n, H)`` the resident
    vocab shard; after t permutes (d→d+1) the resident shard is vocab
    block ``(d−t) mod n``.  Each step matmuls the resident shard into
    its logits slice while the next shard is in flight."""
    idx = lax.axis_index(axis)
    vb = w_l.shape[0]
    sub = vb // chunks
    up = [(s, (s + 1) % n) for s in range(n)]
    out = jnp.zeros(x_l.shape[:-1] + (vb * n,), x_l.dtype)
    cur = ([w_l] if chunks == 1 else
           [lax.dynamic_slice_in_dim(w_l, j * sub, sub, axis=0)
            for j in range(chunks)])
    for t in range(n):
        blk = (idx - t) % n
        if t + 1 < n:
            nxt = [lax.ppermute(p, axis, up) for p in cur]
        for j, piece in enumerate(cur):
            out = lax.dynamic_update_slice_in_dim(
                out, x_l @ piece.T, blk * vb + j * sub, axis=-1)
        if t + 1 < n:
            cur = nxt
    return out


def _batch_spec(ndim, axis_last=None):
    return P(*([None] * (ndim - 1) + [axis_last]))


# ---------------------------------------------------------------------------
# row-parallel matmul: ring RS+AG forward, collective-free backward
# ---------------------------------------------------------------------------

def _row_island(x, w, axis, n, chunks):
    mesh = _mesh.get_mesh()

    def body(x_l, w_l):
        blk = _ring_mm_rs(x_l, w_l, axis, n, chunks)
        return _ring_ag(blk, axis, n, chunks)

    return shard_map(
        body, mesh=mesh,
        in_specs=(_batch_spec(x.ndim, axis), P(axis, None)),
        out_specs=_batch_spec(x.ndim), check_rep=False)(x, w)


from functools import partial  # noqa: E402  (decorators below need it)


@partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _row_matmul(x, w, axis, n, chunks):
    return _row_island(x, w, axis, n, chunks)


def _row_fwd(x, w, axis, n, chunks):
    return _row_island(x, w, axis, n, chunks), (x, w)


def _row_bwd(axis, n, chunks, res, dy):
    # Megatron g/f duality: the row forward's all-reduce transposes to
    # identity — both cotangents are shard-local matmuls, no collective
    x, w = res
    mesh = _mesh.get_mesh()

    def body(x_l, w_l, dy_full):
        dx_l = dy_full @ w_l.T
        dw_l = jnp.einsum("...k,...n->kn", x_l, dy_full)
        return dx_l, dw_l

    dx, dw = shard_map(
        body, mesh=mesh,
        in_specs=(_batch_spec(x.ndim, axis), P(axis, None),
                  _batch_spec(dy.ndim)),
        out_specs=(_batch_spec(x.ndim, axis), P(axis, None)),
        check_rep=False)(x, w, dy)
    return dx.astype(x.dtype), dw.astype(w.dtype)


_row_matmul.defvjp(_row_fwd, _row_bwd)


def row_parallel_matmul(x, w, bias=None, arg=None):
    """Overlapped ``x @ w`` with ``w`` sharded on the contraction dim
    (``P('mp', None)``): GSPMD's matmul→all-reduce becomes the
    partial-accumulate + chunked-permute ring.  Returns ``None`` when
    overlap is off / no mp mesh — caller keeps the monolithic path."""
    act = active(arg)
    if act is None:
        return None
    mesh, n = act
    k, nn = int(w.shape[0]), int(w.shape[1])
    if k % n:
        return None
    m = 1
    for d in x.shape[:-1]:
        m *= int(d)
    chunks = _resolve_chunks("row", m, k, nn, n, x.dtype, max(nn // n, 1))
    if (nn // n) % chunks:
        return None
    out = _row_matmul(x, w, MP_AXIS, n, chunks)
    if bias is not None:
        out = out + bias
    return out


# ---------------------------------------------------------------------------
# column-parallel matmul: local forward, ring backward (transposed
# collective interleaved the same way)
# ---------------------------------------------------------------------------

def _col_island(x, w, axis):
    mesh = _mesh.get_mesh()

    def body(x_full, w_l):
        return x_full @ w_l

    return shard_map(
        body, mesh=mesh,
        in_specs=(_batch_spec(x.ndim), P(None, axis)),
        out_specs=_batch_spec(x.ndim, axis), check_rep=False)(x, w)


@partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _col_matmul(x, w, axis, n, chunks):
    return _col_island(x, w, axis)


def _col_fwd(x, w, axis, n, chunks):
    return _col_island(x, w, axis), (x, w)


def _col_bwd(axis, n, chunks, res, dy):
    # dx = dy @ w.T contracts over the SHARDED output dim — the
    # transposed collective.  Ring it exactly like the row forward:
    # a_l = dy shard (..., N/n), b_l = w_l.T (N/n, K).
    x, w = res
    mesh = _mesh.get_mesh()

    def body(x_full, w_l, dy_l):
        dx_blk = _ring_mm_rs(dy_l, w_l.T, axis, n, chunks)
        dx_l = _ring_ag(dx_blk, axis, n, chunks)
        dw_l = jnp.einsum("...k,...n->kn", x_full, dy_l)
        return dx_l, dw_l

    dx, dw = shard_map(
        body, mesh=mesh,
        in_specs=(_batch_spec(x.ndim), P(None, axis),
                  _batch_spec(dy.ndim, axis)),
        out_specs=(_batch_spec(x.ndim), P(None, axis)),
        check_rep=False)(x, w, dy)
    return dx.astype(x.dtype), dw.astype(w.dtype)


_col_matmul.defvjp(_col_fwd, _col_bwd)


def column_parallel_matmul(x, w, bias=None, arg=None):
    """Overlapped ``x @ w`` with ``w`` sharded on the output dim
    (``P(None, 'mp')``).  The forward is collective-free either way; the
    payoff is the custom_vjp backward, whose dx all-reduce runs through
    the ring.  Output stays mp-sharded on the last dim.  ``None`` ⇒
    overlap off."""
    act = active(arg)
    if act is None:
        return None
    mesh, n = act
    k, nn = int(w.shape[0]), int(w.shape[1])
    if nn % n or k % n:
        return None
    m = 1
    for d in x.shape[:-1]:
        m *= int(d)
    chunks = _resolve_chunks("colbwd", m, nn, k, n, x.dtype,
                             max(k // n, 1))
    if (k // n) % chunks:
        return None
    out = _col_matmul(x, w, MP_AXIS, n, chunks)
    if bias is not None:
        out = out + bias
    return out


# ---------------------------------------------------------------------------
# LM head: rotate-weights all-gather→matmul ring over the vocab shards
# ---------------------------------------------------------------------------

def _lm_island(x, w, axis, n, chunks):
    mesh = _mesh.get_mesh()

    def body(x_full, w_l):
        return _ring_lm(x_full, w_l, axis, n, chunks)

    return shard_map(
        body, mesh=mesh,
        in_specs=(_batch_spec(x.ndim), P(axis, None)),
        out_specs=_batch_spec(x.ndim), check_rep=False)(x, w)


@partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _lm_matmul(x, w, axis, n, chunks):
    return _lm_island(x, w, axis, n, chunks)


def _lm_fwd(x, w, axis, n, chunks):
    return _lm_island(x, w, axis, n, chunks), (x, w)


def _lm_bwd(axis, n, chunks, res, dy):
    # dx contracts over the sharded vocab dim: shard-local partial +
    # psum (an all-reduce — permitted; the monolithic ban is on
    # all-gather).  dw is shard-local.
    x, w = res
    mesh = _mesh.get_mesh()
    vb = int(w.shape[0]) // n

    def body(x_full, w_l, dy_full):
        idx = lax.axis_index(axis)
        dy_l = lax.dynamic_slice_in_dim(dy_full, idx * vb, vb, axis=-1)
        dx = lax.psum(dy_l @ w_l, axis)
        dw_l = jnp.einsum("...v,...h->vh", dy_l, x_full)
        return dx, dw_l

    dx, dw = shard_map(
        body, mesh=mesh,
        in_specs=(_batch_spec(x.ndim), P(axis, None),
                  _batch_spec(dy.ndim)),
        out_specs=(_batch_spec(x.ndim), P(axis, None)),
        check_rep=False)(x, w, dy)
    return dx.astype(x.dtype), dw.astype(w.dtype)


_lm_matmul.defvjp(_lm_fwd, _lm_bwd)


def lm_head_matmul(x, wte, arg=None):
    """Overlapped ``x @ wte.T`` with ``wte (V, H)`` vocab-sharded
    (``P('mp', None)``) — the decode LM head.  Replaces GSPMD's
    monolithic table all-gather with the rotate-weights ring; the full
    ``(..., V)`` logits come back replicated.  ``None`` ⇒ overlap off."""
    act = active(arg)
    if act is None:
        return None
    mesh, n = act
    v, h = int(wte.shape[0]), int(wte.shape[1])
    if v % n:
        return None
    m = 1
    for d in x.shape[:-1]:
        m *= int(d)
    chunks = _resolve_chunks("lmhead", m, h, v, n, x.dtype,
                             max(v // n, 1))
    if (v // n) % chunks:
        return None
    return _lm_matmul(x, wte, MP_AXIS, n, chunks)


# ---------------------------------------------------------------------------
# vocab-parallel embedding: masked local gather + psum (no table gather)
# ---------------------------------------------------------------------------

def vocab_embed(ids, wte, arg=None):
    """Vocab-sharded embedding lookup without materialising the table:
    each device gathers the ids that fall in its shard (zeros elsewhere)
    and the rows meet in one psum — an all-reduce of activation bytes
    instead of GSPMD's all-gather of table bytes.  ``None`` ⇒ overlap
    off."""
    act = active(arg)
    if act is None:
        return None
    mesh, n = act
    v = int(wte.shape[0])
    if v % n:
        return None
    vb = v // n

    def body(ids_full, wte_l):
        idx = lax.axis_index(MP_AXIS)
        local = ids_full.astype(jnp.int32) - idx * vb
        ok = (local >= 0) & (local < vb)
        rows = jnp.take(wte_l, jnp.clip(local, 0, vb - 1), axis=0)
        rows = jnp.where(ok[..., None], rows, jnp.zeros((), rows.dtype))
        return lax.psum(rows, MP_AXIS)

    return shard_map(
        body, mesh=mesh,
        in_specs=(P(*([None] * ids.ndim)), P(MP_AXIS, None)),
        out_specs=P(*([None] * (ids.ndim + 1))), check_rep=False)(ids, wte)


# ---------------------------------------------------------------------------
# fused-qkv projection + 3-ppermute head reshard (decode-side consumer)
# ---------------------------------------------------------------------------

def qkv_heads(x, w, b, num_heads, head_dim, arg=None):
    """Fused column qkv projection straight into the head-sharded layout.

    ``x (B,S,E)`` replicated, ``w (E, 3H)`` column-sharded, ``b (3H,)``
    sharded or None → ``(q, k, v)`` each ``(B,S,nh,hd)`` head-sharded
    (``P(None,None,'mp',None)`` — the serving pool's layout).

    The column shard boundary (at 3H/tp) does not align with the q/k/v
    split (at H), so GSPMD reshards with an all-to-all + all-gather per
    layer.  In units of ``Hb = H/tp`` device ``s`` holds global blocks
    ``3s, 3s+1, 3s+2`` while device ``d`` needs blocks ``d, tp+d,
    2tp+d`` — for ``gcd(3, tp) == 1`` (every power-of-two tp) each local
    slot ``l`` maps by the bijection ``s → (3s+l) mod tp``, so three
    single-hop ppermutes re-deal everything; the receiver picks q/k/v
    out of the stacked arrivals as slot ``(tp·j + d) mod 3``.  Falls
    back to ``None`` (GSPMD path) when ``tp % 3 == 0`` or shapes don't
    divide."""
    act = active(arg)
    if act is None:
        return None
    mesh, n = act
    if n % 3 == 0:
        return None
    h = num_heads * head_dim
    if int(w.shape[1]) != 3 * h or h % n or num_heads % n:
        return None
    hb = h // n
    heads_l = num_heads // n
    _note_chunks(1)   # single-hop deal: no chunk knob, still an island

    def _deal(qkv_l):
        blocks = [lax.dynamic_slice_in_dim(qkv_l, l * hb, hb, axis=-1)
                  for l in range(3)]
        recv = [lax.ppermute(blocks[l], MP_AXIS,
                             [(s, (3 * s + l) % n) for s in range(n)])
                for l in range(3)]
        st = jnp.stack(recv)
        d = lax.axis_index(MP_AXIS)
        outs = []
        for j in range(3):
            t = lax.dynamic_index_in_dim(st, (n * j + d) % 3, axis=0,
                                         keepdims=False)
            outs.append(t.reshape(t.shape[:-1] + (heads_l, head_dim)))
        return tuple(outs)

    out_spec = P(None, None, MP_AXIS, None)
    if b is None:
        def body(x_full, w_l):
            return _deal(x_full @ w_l)
        return shard_map(
            body, mesh=mesh,
            in_specs=(P(None, None, None), P(None, MP_AXIS)),
            out_specs=(out_spec,) * 3, check_rep=False)(x, w)

    def body(x_full, w_l, b_l):
        return _deal(x_full @ w_l + b_l)
    return shard_map(
        body, mesh=mesh,
        in_specs=(P(None, None, None), P(None, MP_AXIS), P(MP_AXIS)),
        out_specs=(out_spec,) * 3, check_rep=False)(x, w, b)
