"""paddle.distributed.utils (reference: python/paddle/distributed/utils.py
— global_scatter:57 / global_gather:179 plus launcher helpers).  The MoE
exchange primitives live in distributed.moe; re-exported here at the
reference's import path."""
from .moe import global_gather, global_scatter  # noqa: F401

__all__ = ["global_scatter", "global_gather"]
