"""Mixture-of-Experts with expert parallelism (reference:
python/paddle/incubate/distributed/models/moe/moe_layer.py:226 MoELayer,
gates gate/{naive,gshard,switch}_gate.py, comm global_scatter/global_gather
(python/paddle/distributed/utils.py:57,:179; CUDA ops
operators/collective/global_scatter_op.*, number_count_op,
limit_by_capacity_op, prune_gate_by_capacity_op, random_routing_op).

TPU-native design: capacity-based dense dispatch (GShard style).  Routing
produces a fixed-shape (experts, capacity) buffer per device — static shapes
keep XLA happy — and the global exchange is ONE lax.all_to_all over the 'ep'
mesh axis (replacing the reference's global_scatter/global_gather CUDA+NCCL
pair).  Works identically outside shard_map (single device = all experts
local, all_to_all skipped).
"""
from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from .. import ops
from ..core import random as _rnd
from ..core.dispatch import call
from ..core.tensor import Tensor
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer.common import Linear
from ..nn.layer.layers import Layer, LayerList
from . import mesh as _mesh

EP_AXIS = "ep"


def _in_trace(axis):
    try:
        jax.lax.axis_index(axis)
        return True
    except Exception:
        return False


def top1_routing(logits, capacity, num_experts, key=None, random_routing=False):
    """Switch-style top-1 routing with capacity limiting.

    Returns (dispatch_mask (T, E, C) bool, combine_weights (T, E, C) f32,
    aux_loss scalar).  reference parity: switch_gate.py:23 + the
    number_count/limit_by_capacity op pipeline.
    """
    probs = jax.nn.softmax(logits, axis=-1)          # (T, E)
    expert_idx = jnp.argmax(probs, axis=-1)          # (T,)
    if random_routing and key is not None:
        # reference random_routing_op: escape overloaded experts
        noise = jax.random.uniform(key, expert_idx.shape)
        expert_idx = jnp.where(noise < 0.01,
                               jax.random.randint(key, expert_idx.shape, 0,
                                                  num_experts),
                               expert_idx)
    gate = jnp.take_along_axis(probs, expert_idx[:, None], axis=-1)[:, 0]
    onehot = jax.nn.one_hot(expert_idx, num_experts, dtype=jnp.float32)
    # position of each token within its expert's queue
    position = jnp.cumsum(onehot, axis=0) * onehot   # (T, E)
    pos_in_expert = jnp.sum(position, axis=-1) - 1.0  # (T,)
    keep = pos_in_expert < capacity
    gate = jnp.where(keep, gate, 0.0)
    # aux load-balance loss (GShard eq.4 / switch loss)
    density = jnp.mean(onehot, axis=0)
    density_proxy = jnp.mean(probs, axis=0)
    aux = jnp.sum(density * density_proxy) * num_experts
    pos_clipped = jnp.clip(pos_in_expert, 0, capacity - 1).astype(jnp.int32)
    cap_onehot = jax.nn.one_hot(pos_clipped, capacity, dtype=jnp.float32)
    dispatch = (onehot[:, :, None] * cap_onehot[:, None, :]
                * keep[:, None, None])               # (T, E, C)
    combine = dispatch * gate[:, None, None]
    return dispatch.astype(jnp.bool_), combine, aux


def top2_routing(logits, capacity, num_experts):
    """GShard top-2 routing (reference: gshard_gate.py:23)."""
    probs = jax.nn.softmax(logits, axis=-1)
    top1 = jnp.argmax(probs, axis=-1)
    probs_wo1 = probs * (1.0 - jax.nn.one_hot(top1, num_experts))
    top2 = jnp.argmax(probs_wo1, axis=-1)

    masks = []
    gates = []
    occupancy = jnp.zeros((logits.shape[0], num_experts), jnp.float32)
    for idx in (top1, top2):
        onehot = jax.nn.one_hot(idx, num_experts, dtype=jnp.float32)
        pos = (jnp.cumsum(onehot, axis=0) - onehot
               + occupancy.sum(axis=0, keepdims=True)) * onehot
        pos_in = jnp.sum(pos, axis=-1)
        keep = pos_in < capacity
        g = jnp.take_along_axis(probs, idx[:, None], axis=-1)[:, 0]
        g = jnp.where(keep, g, 0.0)
        pos_clip = jnp.clip(pos_in, 0, capacity - 1).astype(jnp.int32)
        cap_oh = jax.nn.one_hot(pos_clip, capacity, dtype=jnp.float32)
        masks.append(onehot[:, :, None] * cap_oh[:, None, :]
                     * keep[:, None, None])
        gates.append(g)
        occupancy = occupancy + onehot
    g1, g2 = gates
    denom = jnp.maximum(g1 + g2, 1e-9)
    combine = masks[0] * (g1 / denom)[:, None, None] \
        + masks[1] * (g2 / denom)[:, None, None]
    dispatch = (masks[0] + masks[1]) > 0
    density = jnp.mean(jax.nn.one_hot(top1, num_experts), axis=0)
    density_proxy = jnp.mean(probs, axis=0)
    aux = jnp.sum(density * density_proxy) * num_experts
    return dispatch, combine, aux


class NaiveGate(Layer):
    def __init__(self, d_model, num_experts, topk=2):
        super().__init__()
        self.gate = Linear(d_model, num_experts, bias_attr=False)
        self.topk = topk
        self.num_experts = num_experts

    def forward(self, x):
        return self.gate(x)


class SwitchGate(NaiveGate):
    def __init__(self, d_model, num_experts):
        super().__init__(d_model, num_experts, topk=1)


class GShardGate(NaiveGate):
    def __init__(self, d_model, num_experts):
        super().__init__(d_model, num_experts, topk=2)


class MoELayer(Layer):
    """reference parity: moe_layer.py:226.

    experts: LayerList of per-device-local experts (each a Layer like an
    FFN).  Under an 'ep' shard_map the all_to_all exchanges expert slots
    across devices; single-process eager runs all experts locally.
    """

    def __init__(self, d_model, experts, gate="gshard", top_k=2,
                 capacity_factor=1.25, group=None, recompute_interval=0,
                 aux_loss_weight=0.01):
        super().__init__()
        self.d_model = d_model
        self.experts = (experts if isinstance(experts, LayerList)
                        else LayerList(list(experts)))
        self.num_local_experts = len(self.experts)
        self.axis = getattr(group, "axis", EP_AXIS)
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.aux_loss_weight = aux_loss_weight
        self.aux_loss = None
        ep = max(_mesh.axis_size(self.axis), 1)
        self.num_experts = self.num_local_experts * ep
        if isinstance(gate, str):
            cls = {"naive": NaiveGate, "switch": SwitchGate,
                   "gshard": GShardGate}[gate]
            self.gate = cls(d_model, self.num_experts)
        else:
            self.gate = gate

    def forward(self, x):
        """x: (batch, seq, d_model) -> same shape."""
        b, s, d = x.shape
        tokens = ops.reshape(x, [b * s, d])
        logits = self.gate(tokens)                    # (T, E)
        T = b * s
        capacity = int(math.ceil(self.top_k * self.capacity_factor * T
                                 / self.num_experts))
        capacity = max(capacity, 4)

        num_experts = self.num_experts
        top_k = self.top_k
        expert_params = [e for e in self.experts]
        axis = self.axis
        nle = self.num_local_experts

        def raw(tok, lg, *unused):
            if top_k == 1:
                dispatch, combine, aux = top1_routing(lg, capacity, num_experts)
            else:
                dispatch, combine, aux = top2_routing(lg, capacity, num_experts)
            # (T, E, C) x (T, d) -> (E, C, d)
            expert_in = jnp.einsum("tec,td->ecd",
                                   dispatch.astype(tok.dtype), tok)
            in_trace = _in_trace(axis)
            if in_trace:
                # (E, C, d) = (ep*nle, C, d): exchange so each device holds
                # the C-slots of ITS local experts from every source device
                ep = num_experts // nle
                expert_in = expert_in.reshape(ep, nle, capacity, -1)
                expert_in = jax.lax.all_to_all(expert_in, axis, split_axis=0,
                                               concat_axis=0, tiled=False)
                # now (ep, nle, C, d) where leading dim = source shard
                expert_in = jnp.swapaxes(expert_in, 0, 1)  # (nle, ep, C, d)
                expert_in = expert_in.reshape(nle, ep * capacity, -1)
            return expert_in, aux

        expert_in, aux = call(raw, tokens, logits, name="moe_dispatch")
        self.aux_loss = aux * self.aux_loss_weight

        # run local experts (eager path: all experts local)
        outs = []
        in_trace = _in_trace(axis)
        for i, expert in enumerate(self.experts if in_trace else
                                   self.experts):
            outs.append(expert(expert_in[i] if in_trace
                               else expert_in[i]))
        expert_out = ops.stack(outs, axis=0)          # (nle, slots, d)

        def raw_combine(eo, tok, lg):
            if top_k == 1:
                dispatch, combine, _ = top1_routing(lg, capacity, num_experts)
            else:
                dispatch, combine, _ = top2_routing(lg, capacity, num_experts)
            if _in_trace(axis):
                ep = num_experts // nle
                eo = eo.reshape(nle, ep, capacity, -1)
                eo = jnp.swapaxes(eo, 0, 1)            # (ep, nle, C, d)
                eo = jax.lax.all_to_all(eo, axis, split_axis=0,
                                        concat_axis=0, tiled=False)
                eo = eo.reshape(num_experts, capacity, -1)
            else:
                eo = eo.reshape(num_experts, capacity, -1)
            return jnp.einsum("tec,ecd->td", combine.astype(eo.dtype), eo)

        out = call(raw_combine, expert_out, tokens, logits,
                   name="moe_combine")
        return ops.reshape(out, [b, s, d])


class ExpertFFN(Layer):
    """Standard expert: d_model -> hidden -> d_model."""

    def __init__(self, d_model, d_hidden, activation="gelu"):
        super().__init__()
        self.fc1 = Linear(d_model, d_hidden)
        self.fc2 = Linear(d_hidden, d_model)
        self.act = activation

    def forward(self, x):
        return self.fc2(getattr(F, self.act)(self.fc1(x)))


def global_scatter(x, local_count, global_count, group=None):
    """API-parity wrapper (reference: distributed/utils.py:57): dense
    dispatch is folded into MoELayer; provided for direct use under
    shard_map as a plain all_to_all."""
    axis = getattr(group, "axis", EP_AXIS)
    def raw(a):
        if not _in_trace(axis):
            return a
        return jax.lax.all_to_all(a, axis, 0, 0, tiled=True)
    return call(raw, x, name="global_scatter")


def global_gather(x, local_count, global_count, group=None):
    return global_scatter(x, local_count, global_count, group)
