"""Mixture-of-Experts with expert parallelism (reference:
python/paddle/incubate/distributed/models/moe/moe_layer.py:226 MoELayer,
gates gate/{naive,gshard,switch}_gate.py, comm global_scatter/global_gather
(python/paddle/distributed/utils.py:57,:179; CUDA ops
operators/collective/global_scatter_op.*, number_count_op,
limit_by_capacity_op, prune_gate_by_capacity_op, random_routing_op).

TPU-native design: capacity-based dense dispatch (GShard style).  Routing
produces a fixed-shape (experts, capacity) buffer per device — static shapes
keep XLA happy — and the global exchange is ONE lax.all_to_all over the 'ep'
mesh axis (replacing the reference's global_scatter/global_gather CUDA+NCCL
pair).  Works identically outside shard_map (single device = all experts
local, all_to_all skipped).

Hybrid composition: ``moe_apply`` is the SPMD functional form — ep x dp in
ONE program (expert bank sharded P('ep'), tokens P('dp'), per-dp-rank
dispatch like the reference's fleet-hybrid MoE; driven in
__graft_entry__.py §3b and tests/test_distributed.py).  ep-UNDER-pp
(r4 verdict Missing #6; reference moe_layer.py:226 under the full fleet
hybrid) composes through ``spmd_pipeline_1f1b_hetero`` with ``moe_apply``
inside block_fn: the per-tick block runs UNconditionally on every stage
(masking is data-side jnp.where, not lax.cond), so the all_to_all
executes in lockstep across ep ranks.  Grad-combination recipe when
driving it with check_vma=False (the a2a defeats the static vma checker,
which also disables autodiff's replicated-grad reductions): per-rank
grads are full-scale, so replicated leaves pmean over 'ep', and the
expert bank — which accumulates the identical ep token copies through
the a2a backward — divides by ep
(tests/test_distributed.py::test_moe_under_pp_one_program proves loss
AND grad parity vs the sequential model; dryrun §3c).
"""
from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from .. import ops
from ..core import random as _rnd
from ..core.dispatch import call
from ..core.tensor import Tensor
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer.common import Linear
from ..nn.layer.layers import Layer, LayerList
from . import mesh as _mesh

EP_AXIS = "ep"


def _in_trace(axis):
    try:
        jax.lax.axis_index(axis)
        return True
    except Exception:
        return False


def top1_routing(logits, capacity, num_experts, key=None, random_routing=False):
    """Switch-style top-1 routing with capacity limiting.

    Returns (dispatch_mask (T, E, C) bool, combine_weights (T, E, C) f32,
    aux_loss scalar).  reference parity: switch_gate.py:23 + the
    number_count/limit_by_capacity op pipeline.
    """
    probs = jax.nn.softmax(logits, axis=-1)          # (T, E)
    expert_idx = jnp.argmax(probs, axis=-1)          # (T,)
    if random_routing and key is not None:
        # reference random_routing_op: escape overloaded experts
        noise = jax.random.uniform(key, expert_idx.shape)
        expert_idx = jnp.where(noise < 0.01,
                               jax.random.randint(key, expert_idx.shape, 0,
                                                  num_experts),
                               expert_idx)
    gate = jnp.take_along_axis(probs, expert_idx[:, None], axis=-1)[:, 0]
    onehot = jax.nn.one_hot(expert_idx, num_experts, dtype=jnp.float32)
    # position of each token within its expert's queue
    position = jnp.cumsum(onehot, axis=0) * onehot   # (T, E)
    pos_in_expert = jnp.sum(position, axis=-1) - 1.0  # (T,)
    keep = pos_in_expert < capacity
    gate = jnp.where(keep, gate, 0.0)
    # aux load-balance loss (GShard eq.4 / switch loss)
    density = jnp.mean(onehot, axis=0)
    density_proxy = jnp.mean(probs, axis=0)
    aux = jnp.sum(density * density_proxy) * num_experts
    pos_clipped = jnp.clip(pos_in_expert, 0, capacity - 1).astype(jnp.int32)
    cap_onehot = jax.nn.one_hot(pos_clipped, capacity, dtype=jnp.float32)
    dispatch = (onehot[:, :, None] * cap_onehot[:, None, :]
                * keep[:, None, None])               # (T, E, C)
    combine = dispatch * gate[:, None, None]
    return dispatch.astype(jnp.bool_), combine, aux


def top2_routing(logits, capacity, num_experts):
    """GShard top-2 routing (reference: gshard_gate.py:23)."""
    probs = jax.nn.softmax(logits, axis=-1)
    top1 = jnp.argmax(probs, axis=-1)
    probs_wo1 = probs * (1.0 - jax.nn.one_hot(top1, num_experts))
    top2 = jnp.argmax(probs_wo1, axis=-1)

    masks = []
    gates = []
    occupancy = jnp.zeros((logits.shape[0], num_experts), jnp.float32)
    for idx in (top1, top2):
        onehot = jax.nn.one_hot(idx, num_experts, dtype=jnp.float32)
        pos = (jnp.cumsum(onehot, axis=0) - onehot
               + occupancy.sum(axis=0, keepdims=True)) * onehot
        pos_in = jnp.sum(pos, axis=-1)
        keep = pos_in < capacity
        g = jnp.take_along_axis(probs, idx[:, None], axis=-1)[:, 0]
        g = jnp.where(keep, g, 0.0)
        pos_clip = jnp.clip(pos_in, 0, capacity - 1).astype(jnp.int32)
        cap_oh = jax.nn.one_hot(pos_clip, capacity, dtype=jnp.float32)
        masks.append(onehot[:, :, None] * cap_oh[:, None, :]
                     * keep[:, None, None])
        gates.append(g)
        occupancy = occupancy + onehot
    g1, g2 = gates
    denom = jnp.maximum(g1 + g2, 1e-9)
    combine = masks[0] * (g1 / denom)[:, None, None] \
        + masks[1] * (g2 / denom)[:, None, None]
    dispatch = (masks[0] + masks[1]) > 0
    density = jnp.mean(jax.nn.one_hot(top1, num_experts), axis=0)
    density_proxy = jnp.mean(probs, axis=0)
    aux = jnp.sum(density * density_proxy) * num_experts
    return dispatch, combine, aux


def _route(logits, capacity, num_experts, top_k):
    if top_k == 1:
        return top1_routing(logits, capacity, num_experts)
    return top2_routing(logits, capacity, num_experts)


def moe_dispatch(tok, logits, *, top_k, capacity, num_experts, nle, axis):
    """Token -> expert-slot dispatch (pure; shard_map-aware).  Returns
    (expert_in (nle, slots, d), aux).  Under a bound `axis`, ONE
    lax.all_to_all exchanges the (ep, nle, C, d) slots so each device
    holds every source shard's slots for ITS local experts."""
    dispatch, _, aux = _route(logits, capacity, num_experts, top_k)
    expert_in = jnp.einsum("tec,td->ecd", dispatch.astype(tok.dtype), tok)
    if _in_trace(axis):
        ep = num_experts // nle
        expert_in = expert_in.reshape(ep, nle, capacity, -1)
        expert_in = jax.lax.all_to_all(expert_in, axis, split_axis=0,
                                       concat_axis=0, tiled=False)
        expert_in = jnp.swapaxes(expert_in, 0, 1)      # (nle, ep, C, d)
        expert_in = expert_in.reshape(nle, ep * capacity, -1)
    else:
        expert_in = expert_in.reshape(nle, (num_experts // nle) * capacity,
                                      -1)
    return expert_in, aux


def moe_combine(eo, logits, *, top_k, capacity, num_experts, nle, axis,
                dtype=None):
    """Expert outputs -> tokens (inverse all_to_all + weighted combine)."""
    _, combine, _ = _route(logits, capacity, num_experts, top_k)
    if _in_trace(axis):
        ep = num_experts // nle
        eo = eo.reshape(nle, ep, capacity, -1)
        eo = jnp.swapaxes(eo, 0, 1)                    # (ep, nle, C, d)
        eo = jax.lax.all_to_all(eo, axis, split_axis=0, concat_axis=0,
                                tiled=False)
    eo = eo.reshape(num_experts, capacity, -1)
    return jnp.einsum("tec,ecd->td", combine.astype(eo.dtype), eo)


def moe_apply(params, x, *, top_k=1, capacity_factor=2.0, axis=EP_AXIS,
              num_experts=None, act="gelu"):
    """Pure functional MoE block for SPMD driving (ep x dp in ONE program
    — reference moe_layer.py:226 under the fleet hybrid topology).

    params (PER-SHARD leaves inside shard_map):
        gate: (d, E)          — replicated
        w1:   (nle, d, h)     — the shard of the (E, d, h) expert bank
        b1:   (nle, h)          sharded P(axis) on dim 0
        w2:   (nle, h, d)
        b2:   (nle, d)
    x: (b_local, s, d) — this data-parallel rank's tokens (each dp rank
    routes its own tokens with its own capacity, the reference's per-rank
    dispatch semantics).  Returns (out (b_local, s, d), aux_loss)."""
    b, s, d = x.shape
    tok = x.reshape(b * s, d)
    logits = tok @ params["gate"]
    nle = params["w1"].shape[0]
    if num_experts is None:
        ep = jax.lax.psum(1, axis) if _in_trace(axis) else 1
        num_experts = nle * ep
    t = b * s
    capacity = max(int(math.ceil(top_k * capacity_factor * t
                                 / num_experts)), 4)
    expert_in, aux = moe_dispatch(tok, logits, top_k=top_k,
                                  capacity=capacity,
                                  num_experts=num_experts, nle=nle,
                                  axis=axis)
    h = jnp.einsum("ncd,ndh->nch", expert_in, params["w1"]) \
        + params["b1"][:, None, :]
    h = getattr(jax.nn, act)(h)
    eo = jnp.einsum("nch,nhd->ncd", h, params["w2"]) \
        + params["b2"][:, None, :]
    out = moe_combine(eo, logits, top_k=top_k, capacity=capacity,
                      num_experts=num_experts, nle=nle, axis=axis)
    return out.reshape(b, s, d), aux


class NaiveGate(Layer):
    def __init__(self, d_model, num_experts, topk=2):
        super().__init__()
        self.gate = Linear(d_model, num_experts, bias_attr=False)
        self.topk = topk
        self.num_experts = num_experts

    def forward(self, x):
        return self.gate(x)


class SwitchGate(NaiveGate):
    def __init__(self, d_model, num_experts):
        super().__init__(d_model, num_experts, topk=1)


class GShardGate(NaiveGate):
    def __init__(self, d_model, num_experts):
        super().__init__(d_model, num_experts, topk=2)


class MoELayer(Layer):
    """reference parity: moe_layer.py:226.

    experts: LayerList of per-device-local experts (each a Layer like an
    FFN).  Under an 'ep' shard_map the all_to_all exchanges expert slots
    across devices; single-process eager runs all experts locally.
    """

    def __init__(self, d_model, experts, gate="gshard", top_k=2,
                 capacity_factor=1.25, group=None, recompute_interval=0,
                 aux_loss_weight=0.01):
        super().__init__()
        self.d_model = d_model
        self.experts = (experts if isinstance(experts, LayerList)
                        else LayerList(list(experts)))
        self.num_local_experts = len(self.experts)
        self.axis = getattr(group, "axis", EP_AXIS)
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.aux_loss_weight = aux_loss_weight
        self.aux_loss = None
        ep = max(_mesh.axis_size(self.axis), 1)
        self.num_experts = self.num_local_experts * ep
        if isinstance(gate, str):
            cls = {"naive": NaiveGate, "switch": SwitchGate,
                   "gshard": GShardGate}[gate]
            self.gate = cls(d_model, self.num_experts)
        else:
            self.gate = gate

    def forward(self, x):
        """x: (batch, seq, d_model) -> same shape."""
        b, s, d = x.shape
        tokens = ops.reshape(x, [b * s, d])
        logits = self.gate(tokens)                    # (T, E)
        T = b * s
        capacity = int(math.ceil(self.top_k * self.capacity_factor * T
                                 / self.num_experts))
        capacity = max(capacity, 4)

        num_experts = self.num_experts
        top_k = self.top_k
        expert_params = [e for e in self.experts]
        axis = self.axis
        nle = self.num_local_experts

        def raw(tok, lg, *unused):
            return moe_dispatch(tok, lg, top_k=top_k, capacity=capacity,
                                num_experts=num_experts, nle=nle, axis=axis)

        expert_in, aux = call(raw, tokens, logits, name="moe_dispatch")
        self.aux_loss = aux * self.aux_loss_weight

        # run local experts (eager path: all experts local)
        outs = []
        in_trace = _in_trace(axis)
        for i, expert in enumerate(self.experts if in_trace else
                                   self.experts):
            outs.append(expert(expert_in[i] if in_trace
                               else expert_in[i]))
        expert_out = ops.stack(outs, axis=0)          # (nle, slots, d)

        def raw_combine(eo, tok, lg):
            return moe_combine(eo, lg, top_k=top_k, capacity=capacity,
                               num_experts=num_experts, nle=nle, axis=axis)

        out = call(raw_combine, expert_out, tokens, logits,
                   name="moe_combine")
        return ops.reshape(out, [b, s, d])


class ExpertFFN(Layer):
    """Standard expert: d_model -> hidden -> d_model."""

    def __init__(self, d_model, d_hidden, activation="gelu"):
        super().__init__()
        self.fc1 = Linear(d_model, d_hidden)
        self.fc2 = Linear(d_hidden, d_model)
        self.act = activation

    def forward(self, x):
        return self.fc2(getattr(F, self.act)(self.fc1(x)))


def global_scatter(x, local_count, global_count, group=None):
    """API-parity wrapper (reference: distributed/utils.py:57): dense
    dispatch is folded into MoELayer; provided for direct use under
    shard_map as a plain all_to_all."""
    axis = getattr(group, "axis", EP_AXIS)
    def raw(a):
        if not _in_trace(axis):
            return a
        return jax.lax.all_to_all(a, axis, 0, 0, tiled=True)
    return call(raw, x, name="global_scatter")


def global_gather(x, local_count, global_count, group=None):
    return global_scatter(x, local_count, global_count, group)


def build_moe_pp_parity_demo(seed=33, E=2, d=8, h=16, n_stages=2, bps=1,
                             m=4, mb=4, s=4):
    """Tiny MoE-under-pp parity fixture shared by
    tests/test_distributed.py::test_moe_under_pp_one_program and the
    driver dryrun (§3c) — ONE model definition so the two parity checks
    can never drift apart.

    Returns (params, x, labels, embed_fn, block_fn, head_loss_fn, dims)
    with dims = (n_stages, bps, m).  block_fn routes through moe_apply
    over the 'ep' axis."""
    import numpy as _np
    rng = _np.random.RandomState(seed)
    params = {
        "embed": {"we": jnp.asarray(rng.randn(d, d) * 0.3, jnp.float32)},
        "blocks": {
            "gate": jnp.asarray(rng.randn(n_stages, bps, d, E) * 0.5,
                                jnp.float32),
            "w1": jnp.asarray(rng.randn(n_stages, bps, E, d, h) * 0.2,
                              jnp.float32),
            "b1": jnp.zeros((n_stages, bps, E, h), jnp.float32),
            "w2": jnp.asarray(rng.randn(n_stages, bps, E, h, d) * 0.2,
                              jnp.float32),
            "b2": jnp.zeros((n_stages, bps, E, d), jnp.float32),
        },
        "head": {"wh": jnp.asarray(rng.randn(d, d) * 0.3, jnp.float32)},
    }
    x = jnp.asarray(rng.randn(m, mb, s, d), jnp.float32)
    labels = jnp.asarray(rng.randn(m, mb, s, d), jnp.float32)

    def embed_fn(ep_, xb):
        return xb @ ep_["we"]

    def block_fn(bp, hb):
        moe_p = {k: bp[k] for k in ("gate", "w1", "b1", "w2", "b2")}
        out, _aux = moe_apply(moe_p, hb, top_k=1, capacity_factor=2.0,
                              axis=EP_AXIS)
        return hb + out

    def head_loss_fn(hp, ep_, hb, lbl):
        return jnp.mean((hb @ hp["wh"] - lbl) ** 2)

    return params, x, labels, embed_fn, block_fn, head_loss_fn, \
        (n_stages, bps, m)


def moe_pp_sequential_loss(params, x, labels, embed_fn, block_fn,
                           head_loss_fn, dims, dp_axis="dp"):
    """The non-pipelined reference computation for the parity fixture:
    microbatch-mean loss of the sequential model, pmean'd over the data
    axis (matching the pipeline's loss contract)."""
    n_stages, bps, m = dims
    total = 0.0
    for i in range(m):
        hb = embed_fn(params["embed"], x[i])
        for st in range(n_stages):
            for bi in range(bps):
                bp = jax.tree_util.tree_map(lambda a: a[st, bi],
                                            params["blocks"])
                hb = block_fn(bp, hb)
        total = total + head_loss_fn(params["head"], params["embed"], hb,
                                     labels[i])
    return jax.lax.pmean(total / m, dp_axis)
