"""Tensor (model) parallel layers (reference:
fleet/meta_parallel/parallel_layers/mp_layers.py — VocabParallelEmbedding:30,
ColumnParallelLinear:97, RowParallelLinear:170, ParallelCrossEntropy:249;
kernels c_embedding_op, c_softmax_with_cross_entropy_op, c_split/c_concat).

TPU-native design: Megatron layouts as *GSPMD sharding annotations* on
full-logical-shape parameters — Column = weight sharded on the output dim,
Row = weight sharded on the input dim, Vocab = embedding sharded on vocab.
XLA inserts the identity-fwd/allreduce-bwd (and vice versa) collectives that
the reference hand-wrote, and they ride ICI.  Layers therefore hold the FULL
weight logically; under pjit each device stores only its shard.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec

from .. import ops
from ..core.dispatch import call
from ..core.tensor import Tensor
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer.layers import Layer
from . import mesh as _mesh

MP_AXIS = "mp"


class ColumnParallelLinear(Layer):
    """y = x @ W[:, shard] (+b[shard]); gather_output concatenates shards.
    reference parity: mp_layers.py:97."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.gather_output = gather_output
        self.axis = getattr(mp_group, "axis", MP_AXIS)
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.weight.pspec = PartitionSpec(None, self.axis)
        self.weight.is_distributed = True
        if has_bias:
            self.bias = self.create_parameter((out_features,), is_bias=True)
            self.bias.pspec = PartitionSpec(self.axis)
            self.bias.is_distributed = True
        else:
            self.bias = None

    def forward(self, x):
        from . import mp_overlap as _mpo
        if (not self.gather_output
                and _mpo.col_viable(self.in_features, self.out_features)):
            # overlapped column matmul: forward is the same shard-local
            # program; the custom_vjp backward runs dx's transposed
            # all-reduce as the ppermute ring (partial-accumulate +
            # chunked permute).  Off / gather_output ⇒ today's GSPMD
            # lowering unchanged
            return call(
                lambda xr, w, b: _mpo.column_parallel_matmul(xr, w, b),
                x, self.weight, self.bias, name="mp_overlap_col")
        out = F.linear(x, self.weight, self.bias)
        if not self.gather_output:
            # keep activations sharded on the mp axis (Megatron fused pair)
            out = with_sharding_constraint(out, PartitionSpec(None, None, self.axis)
                                           if out.ndim == 3 else
                                           PartitionSpec(None, self.axis))
        return out


class RowParallelLinear(Layer):
    """y = sum_shards(x[shard] @ W[shard, :]) + b — allreduce in fwd.
    reference parity: mp_layers.py:170."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.input_is_parallel = input_is_parallel
        self.axis = getattr(mp_group, "axis", MP_AXIS)
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.weight.pspec = PartitionSpec(self.axis, None)
        self.weight.is_distributed = True
        if has_bias:
            self.bias = self.create_parameter((out_features,), is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        from . import mp_overlap as _mpo
        if _mpo.row_viable(self.in_features):
            # overlapped row matmul: the matmul→all-reduce becomes the
            # matmul→reduce-scatter ring + ring all-gather, every hop a
            # ppermute hidden behind the next partial matmul; the
            # backward is shard-local (Megatron g/f duality).  Off ⇒
            # today's GSPMD lowering unchanged
            return call(
                lambda xr, w, b: _mpo.row_parallel_matmul(xr, w, b),
                x, self.weight, self.bias, name="mp_overlap_row")
        out = F.linear(x, self.weight, self.bias)
        # GSPMD sees (.., k sharded) @ (k sharded, n) and inserts the psum
        out = with_sharding_constraint(
            out, PartitionSpec(*([None] * out.ndim)))
        return out


class VocabParallelEmbedding(Layer):
    """Embedding table sharded on the vocab dim; out-of-shard ids contribute
    zero then psum — all inserted by GSPMD from the sharding annotation.
    reference parity: mp_layers.py:30 (kernel c_embedding_op.cu)."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.axis = getattr(mp_group, "axis", MP_AXIS)
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim), attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.weight.pspec = PartitionSpec(self.axis, None)
        self.weight.is_distributed = True

    def forward(self, x):
        return F.embedding(x, self.weight)


class ParallelCrossEntropy(Layer):
    """Softmax-CE over vocab-sharded logits without materialising the full
    softmax (reference: mp_layers.py:249, kernel
    c_softmax_with_cross_entropy_op.cu).  GSPMD form: constrain logits to
    stay vocab-sharded; the reductions lower to psums over the mp axis."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.axis = getattr(mp_group, "axis", MP_AXIS)
        self.ignore_index = ignore_index

    def forward(self, input, label):
        axis = self.axis

        def raw(logits, lbl):
            logits = _constrain(logits, PartitionSpec(
                *([None] * (logits.ndim - 1) + [axis])))
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            if lbl.ndim == logits.ndim:
                lbl2 = jnp.squeeze(lbl, -1)
            else:
                lbl2 = lbl
            nll = -jnp.take_along_axis(logp, lbl2[..., None], axis=-1)
            mask = (lbl2 != self.ignore_index)[..., None]
            return jnp.where(mask, nll, 0.0)

        return call(raw, input, label, name="parallel_cross_entropy")


def with_sharding_constraint(t, spec):
    """lax.with_sharding_constraint lifted to Tensors; no-op outside pjit."""
    def raw(x):
        return _constrain(x, spec)
    if isinstance(t, Tensor):
        return call(raw, t, name="sharding_constraint")
    return _constrain(t, spec)


def shard_heads(t):
    """Constrain a ``(batch, seq, heads, head_dim)`` activation to be
    HEAD-sharded on the mp axis — the serving engine's tensor-parallel
    decode layout (the KV pool is partitioned over the same axis, so a
    head-sharded q/k/v keeps the whole attention, pool scatter included,
    device-local).  Column-sharding the FUSED qkv projection puts shard
    boundaries at 3H/tp, not at head boundaries, so without this
    constraint GSPMD resolves the q/k/v slices with a resharding
    collective per layer anyway — the constraint just names the layout
    once instead of letting propagation rediscover it.  No-op whenever
    the active mesh does not declare 'mp' (single-chip decode, training
    meshes without tensor parallelism)."""
    return with_sharding_constraint(
        t, PartitionSpec(None, None, MP_AXIS, None))


def _constrain(x, spec):
    try:
        mesh = _mesh.get_mesh()
        if mesh is None:
            return x
        from jax.sharding import NamedSharding
        return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))
    except Exception:
        return x


class TensorParallel(Layer):
    """Model wrapper for mp mode (fleet_base.py:932 dispatch target): applies
    each parameter's pspec annotation onto the global mesh."""

    def __init__(self, layers, hcg=None, strategy=None):
        super().__init__()
        self._layers = layers
        self.add_sublayer("_layers", layers)
        from .parallel_base import parallelize
        parallelize(layers)

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)


class RNGStatesTracker:
    """Deterministic dropout under TP (reference:
    parallel_layers/random.py:32) — per-name PRNG streams derived by folding
    the region name and the mp coordinate into the seed."""

    def __init__(self):
        self.states = {}
        self.seed = 0

    def add(self, name, seed):
        import jax
        self.states[name] = jax.random.fold_in(jax.random.key(seed),
                                               hash(name) & 0x7FFFFFFF)

    def rng_state(self, name="model_parallel_rng"):
        import contextlib
        from ..core import random as _rnd

        @contextlib.contextmanager
        def ctx():
            key = self.states.get(name)
            if key is None:
                import jax
                key = jax.random.key(self.seed)
                self.states[name] = key
            with _rnd.key_stream(key):
                yield

        return ctx()


_rng_tracker = RNGStatesTracker()


def get_rng_state_tracker():
    return _rng_tracker
