"""Ring attention — sequence/context parallelism over the 'sep' mesh axis.

The reference has NO sequence parallelism (SURVEY.md §5.7: no ring
attention/Ulysses/blockwise anywhere in the snapshot); this is a required
capability of the TPU build.  Design:

* ring_attention: each device holds a contiguous sequence shard of Q and of
  K/V.  K/V shards rotate around the ring via lax.ppermute (ICI neighbor
  exchange) in their ORIGINAL dtype (bf16 shards move 2 B/elem; an earlier
  revision rotated f32 and doubled the wire bytes) while each device
  accumulates blockwise-softmax statistics for its Q shard.  The inner
  block is itself BLOCKWISE: a remat'd scan over key chunks with online
  (max, sum, acc) statistics, so per-device memory is O(s_loc * chunk) in
  forward AND backward — never the (s_loc, s_loc) logits block.  Causality
  is enforced from global block positions (axis_index): ring steps holding
  strictly-future shards are skipped entirely (lax.cond — no MXU/VPU
  work), strictly-past shards run mask-free, and only the self shard pays
  the elementwise causal mask.
* ulysses_attention: the all-to-all variant — resharding (seq-sharded ->
  head-sharded) with two lax.all_to_all calls around ordinary local
  attention; composes with TP by splitting the head dim.

Both are plain jax functions intended for use inside shard_map (see
tests/test_distributed.py for the driving pattern); grads flow through
scan+ppermute+cond natively, with jax.checkpoint on the chunk body keeping
the backward blockwise too.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

_NEG_INF = -1e30

#: key-chunk width of the blockwise inner loop (elements of the rotating
#: K/V shard processed per online-softmax update)
DEFAULT_CHUNK = 512


#: below this admitted chunk width the inner scan degenerates into many
#: tiny sequential steps (a prime s_loc would otherwise silently run
#: chunk=1 — ~512x more scan steps; ADVICE r4)
_CHUNK_FLOOR = 128


def _chunk_for(s_blk: int, chunk: int) -> int:
    """Largest divisor of the K/V block length not exceeding ``chunk``."""
    c = min(chunk, s_blk)
    while s_blk % c:
        c -= 1
    if c < min(_CHUNK_FLOOR, s_blk):
        import warnings
        warnings.warn(
            "ring attention inner chunk degraded to %d for shard length "
            "%d (no divisor <= %d above %d) — pad the sequence shard to a "
            "multiple of a power of two to avoid a ~%dx slower inner scan"
            % (c, s_blk, chunk, _CHUNK_FLOOR, max(1, _CHUNK_FLOOR // c)))
    return c


def _pvary(a, axis_name):
    """newer jax: scan carries inside shard_map are vma-typed; constants
    must be promoted to device-varying before entering the carry (shared
    pcast-first helper — ADVICE r4)."""
    if axis_name is None:
        return a
    from .collective import ensure_varying
    return ensure_varying(a, axis_name)


def _blockwise_attn(q, k_blk, v_blk, scale, q_off, k_off, diag, mask_blk,
                    chunk, axis_name=None):
    """Blockwise (chunked, online-softmax) attention of the local Q shard
    against ONE rotating K/V shard.

    q: (B, H, Sq, D); k_blk/v_blk: (B, H, Sk, D) in their original dtype
    (bf16 contractions hit the MXU natively via preferred_element_type).
    q_off/k_off: traced global offsets of the shards (for the causal mask
    when ``diag``, a STATIC bool — the caller picks the masked or unmasked
    trace via lax.cond).  mask_blk: optional ADDITIVE f32 mask
    broadcastable to (B, H, Sq, Sk).  Returns (out, lse): out
    (B, H, Sq, D) f32 normalized within the block, lse (B, H, Sq) f32
    base-e.

    Memory: O(Sq * chunk) — the chunk body is jax.checkpoint'd so scan's
    backward recomputes the chunk logits instead of saving them.
    """
    b, h, sq, d = q.shape
    hk = k_blk.shape[1]
    sk = k_blk.shape[2]
    if h % hk:
        raise ValueError(
            "q heads (%d) must be a multiple of k/v heads (%d)" % (h, hk))
    g = h // hk
    rows = g * sq
    if g > 1:
        # grouped-query attention: fold the g query heads sharing one K/V
        # head into the ROW axis ((b, hk, g*sq, d) — rows ordered g-major),
        # so the contraction batches over the hk axis and K/V stay grouped
        # (this is what keeps ring wire bytes 1/g of dense, r4 Weak #4)
        q = q.reshape(b, hk, rows, d)
        if mask_blk is not None:
            if mask_blk.ndim == 4 and mask_blk.shape[1] == h:
                # per-q-head mask follows the head fold exactly
                mask_blk = mask_blk.reshape(b, hk, rows,
                                            mask_blk.shape[-1])
            else:
                # head-broadcast mask: repeat its row axis g times (the
                # row fold is (g, sq) — g-major)
                reps = [1] * mask_blk.ndim
                reps[-2] = g
                mask_blk = jnp.tile(mask_blk, reps)
    c = _chunk_for(sk, chunk)
    nck = sk // c

    def body(carry, ci):
        m, l, acc = carry
        ks = jax.lax.dynamic_slice_in_dim(k_blk, ci * c, c, 2)
        vs = jax.lax.dynamic_slice_in_dim(v_blk, ci * c, c, 2)
        logits = jax.lax.dot_general(
            q, ks, (((3,), (3,)), ((0, 1), (0, 1))),
            preferred_element_type=jnp.float32) * scale  # (B, HK, rows, c)
        if mask_blk is not None:
            mb = jax.lax.dynamic_slice_in_dim(
                mask_blk, ci * c, c, mask_blk.ndim - 1)
            logits = logits + mb.astype(jnp.float32)
        if diag:
            # elementwise causality on global positions — only the SELF
            # shard takes this branch (strictly-past shards run the
            # mask-free trace; strictly-future ones are skipped upstream).
            # With GQA the row axis is (g, sq) flattened: position = row
            # mod sq
            row_iota = jax.lax.broadcasted_iota(jnp.int32, (rows, c), 0)
            q_pos = q_off + jax.lax.rem(row_iota, jnp.int32(sq))
            k_pos = k_off + ci * c + jax.lax.broadcasted_iota(
                jnp.int32, (rows, c), 1)
            logits = jnp.where((k_pos <= q_pos)[None, None], logits,
                               jnp.float32(_NEG_INF))
        new_m = jnp.maximum(m, jnp.max(logits, axis=-1))
        corr = jnp.exp(m - new_m)
        p = jnp.exp(logits - new_m[..., None])
        new_l = l * corr + jnp.sum(p, axis=-1)
        new_acc = acc * corr[..., None] + jax.lax.dot_general(
            p.astype(vs.dtype), vs, (((3,), (2,)), ((0, 1), (0, 1))),
            preferred_element_type=jnp.float32)
        return (new_m, new_l, new_acc), None

    init = (_pvary(jnp.full((b, hk, rows), _NEG_INF, jnp.float32),
                   axis_name),
            _pvary(jnp.zeros((b, hk, rows), jnp.float32), axis_name),
            _pvary(jnp.zeros((b, hk, rows, d), jnp.float32), axis_name))
    (m, l, acc), _ = jax.lax.scan(jax.checkpoint(body), init,
                                  jnp.arange(nck, dtype=jnp.int32))
    l_safe = jnp.maximum(l, 1e-30)
    out = acc / l_safe[..., None]
    lse = m + jnp.log(l_safe)
    if g > 1:
        out = out.reshape(b, h, sq, d)
        lse = lse.reshape(b, h, sq)
    return out, lse


def _pallas_inner_ok(q, k, attn_mask) -> bool:
    """Static gate: can the Pallas flash kernel serve as the ring inner?
    (TPU only; no additive mask — the kernel has no mask operand; no GQA —
    the kernel computes dense heads; supported shard shape.)"""
    import os
    mode = os.getenv("PADDLE_TPU_RING_INNER", "").lower()
    if mode == "jnp":
        return False
    if mode != "pallas_interpret":      # test hook: interpret-mode on CPU
        try:
            if jax.default_backend() != "tpu":
                return False
        except Exception:
            return False
    if attn_mask is not None or q.shape[1] != k.shape[1]:
        return False
    b, h, s, d = q.shape
    if d not in (64, 128, 256) or s % 128:
        return False
    from ..kernels.flash_attention_pallas import max_supported_seq
    return s <= max_supported_seq(h, d)


def _flash_inner(q, k_blk, v_blk, causal, scale_py):
    """Pallas flash kernel as the ring inner: (B, H, S, D) shards in/out,
    (out f32, lse base-e (B, H, S) f32) — the same contract as
    :func:`_blockwise_attn`."""
    import os

    from ..kernels.flash_attention_pallas import \
        flash_attention_bshd_with_lse
    interpret = (os.getenv("PADDLE_TPU_RING_INNER", "").lower()
                 == "pallas_interpret")
    out, lse = flash_attention_bshd_with_lse(
        jnp.swapaxes(q, 1, 2), jnp.swapaxes(k_blk, 1, 2),
        jnp.swapaxes(v_blk, 1, 2), causal=causal, scale=scale_py,
        interpret=interpret)
    return (jnp.swapaxes(out, 1, 2).astype(jnp.float32),
            jnp.swapaxes(lse, 1, 2))


def ring_attention(q, k, v, axis_name: str, causal: bool = True,
                   scale=None, attn_mask=None, chunk: int = DEFAULT_CHUNK):
    """Blockwise ring attention under shard_map.

    q, k, v: (B, H, S_local, D) — the local CONTIGUOUS sequence shard
    (equal length on every rank; global position of rank r's tokens is
    [r*S_local, (r+1)*S_local)).
    attn_mask: optional ADDITIVE mask, broadcastable to
    (B, H, S_local, S_global) — the caller's local q rows against the FULL
    key axis; each ring step slices the columns of the shard it holds.
    Returns (B, H, S_local, D) in q's dtype.

    INNER BLOCK: on TPU the per-shard attention runs the Pallas flash
    kernel (flash_attention_bshd_with_lse — its lse output is exactly the
    per-block statistic the ring combine needs, and its backward folds the
    lse cotangent as delta − dlse; r4 verdict #3).  The chunked-remat jnp
    blockwise inner remains the fallback (CPU meshes, GQA, additive
    masks) and the parity reference; force it with
    PADDLE_TPU_RING_INNER=jnp.
    """
    n = jax.lax.axis_size(axis_name) if hasattr(jax.lax, "axis_size") else \
        jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    b, h, s_loc, d = q.shape
    if h % k.shape[1]:
        raise NotImplementedError(
            "ring_attention: q heads (%d) must be a multiple of k/v heads "
            "(%d) for grouped-query attention under the 'sep' ring"
            % (h, k.shape[1]))
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    use_pallas_inner = _pallas_inner_ok(q, k, attn_mask)
    scale_py = None
    if use_pallas_inner:
        try:
            scale_py = float(scale)   # static copy for the Pallas kernel
        except (TypeError, jax.errors.ConcretizationTypeError):
            use_pallas_inner = False  # traced scale: jnp inner handles it
    scale = jnp.float32(scale)
    if attn_mask is not None and attn_mask.shape[-2] != s_loc:
        raise ValueError(
            "ring_attention: attn_mask rows (%d) must cover the LOCAL q "
            "shard (%d); its columns cover the global key axis"
            % (attn_mask.shape[-2], s_loc))

    perm = [(i, (i + 1) % n) for i in range(n)]
    my = jnp.asarray(my, jnp.int32)       # x64 mode: keep index math i32
    n32 = jnp.int32(n)
    q_off = my * jnp.int32(s_loc)

    def step(carry, i):
        out_acc, lse_acc, k_cur, v_cur = carry
        src = jax.lax.rem(my - i + n32, n32)   # whose shard we hold
        k_off = src * jnp.int32(s_loc)

        def attend_with(diag):
            def fn(operand):
                k_b, v_b = operand
                if use_pallas_inner:
                    # diag == self shard (standard causal); past shards
                    # attend unmasked — the kernel covers both
                    ob, lb = _flash_inner(q, k_b, v_b, diag and causal,
                                          scale_py)
                    out_b, lse_b = ob, lb
                else:
                    mask_blk = None
                    if attn_mask is not None:
                        mask_blk = jax.lax.dynamic_slice_in_dim(
                            attn_mask, k_off, s_loc, attn_mask.ndim - 1)
                    out_b, lse_b = _blockwise_attn(
                        q, k_b, v_b, scale, q_off, k_off, diag, mask_blk,
                        chunk, axis_name)
                # flash-style two-level combine of normalized block results
                new_lse = jnp.logaddexp(lse_acc, lse_b)
                a = jnp.exp(lse_acc - new_lse)
                bb = jnp.exp(lse_b - new_lse)
                return (out_acc * a[..., None] + out_b * bb[..., None],
                        new_lse)
            return fn

        def skip(operand):
            return out_acc, lse_acc

        if causal:
            # strictly-future shards contribute nothing (no matmuls at
            # all); only the SELF shard pays the elementwise causal mask
            out_new, lse_new = jax.lax.cond(
                src > my, skip,
                lambda op: jax.lax.cond(src == my, attend_with(True),
                                        attend_with(False), op),
                (k_cur, v_cur))
        else:
            out_new, lse_new = attend_with(False)((k_cur, v_cur))
        # rotate K/V to the next device IN THEIR ORIGINAL DTYPE (bf16
        # shards move half the bytes of the old f32 rotation)
        k_next = jax.lax.ppermute(k_cur, axis_name, perm)
        v_next = jax.lax.ppermute(v_cur, axis_name, perm)
        return (out_new, lse_new, k_next, v_next), None

    out0 = _pvary(jnp.zeros((b, h, s_loc, d), jnp.float32), axis_name)
    lse0 = _pvary(jnp.full((b, h, s_loc), _NEG_INF, jnp.float32), axis_name)

    (out, _lse, _k, _v), _ = jax.lax.scan(
        step, (out0, lse0, k, v), jnp.arange(n, dtype=jnp.int32))
    return out.astype(q.dtype)


def ulysses_attention(q, k, v, axis_name: str, causal: bool = True,
                      scale=None, attn_fn=None):
    """DeepSpeed-Ulysses style: all_to_all heads<->sequence, local attention,
    all_to_all back.  q/k/v: (B, H, S_local, D) with H divisible by the axis
    size; inside, each device sees (B, H/n, S_full, D)."""
    n = jax.lax.psum(1, axis_name)

    def seq_to_head(x):
        b, h, s_loc, d = x.shape
        x = x.reshape(b, n, h // n, s_loc, d)
        x = jnp.moveaxis(x, 1, 0)                      # (n, b, h/n, s_loc, d)
        x = jax.lax.all_to_all(x, axis_name, split_axis=0, concat_axis=0,
                               tiled=False)            # n dim now = seq chunks
        x = jnp.moveaxis(x, 0, 3)                      # (b, h/n, s_loc, n, d)
        b2, hn, s_loc2, n2, d2 = x.shape
        # (b, h/n, n, s_loc, d) -> concat seq chunks in ring order
        return jnp.reshape(jnp.swapaxes(x, 2, 3), (b2, hn, n2 * s_loc2, d2))

    def head_to_seq(x):
        b, hn, s_full, d = x.shape
        s_loc = s_full // n
        x = x.reshape(b, hn, n, s_loc, d)
        x = jnp.moveaxis(x, 2, 0)                      # (n, b, h/n, s_loc, d)
        x = jax.lax.all_to_all(x, axis_name, split_axis=0, concat_axis=0,
                               tiled=False)
        x = jnp.moveaxis(x, 0, 1)                      # (b, n, h/n, s_loc, d)
        return x.reshape(b, x.shape[1] * x.shape[2], s_loc, d)

    q2, k2, v2 = seq_to_head(q), seq_to_head(k), seq_to_head(v)
    if attn_fn is None:
        def attn_fn(q_, k_, v_):
            d = q_.shape[-1]
            s = scale if scale is not None else 1.0 / (d ** 0.5)
            if _pallas_inner_ok(q_, k_, None):
                try:
                    s_py = float(s)
                except (TypeError, jax.errors.ConcretizationTypeError):
                    s_py = None       # traced scale: jnp inner below
                if s_py is not None:
                    # full local attention needs no lse — the plain flash
                    # custom_vjp serves directly (r4 verdict Weak #8)
                    import os

                    from ..kernels.flash_attention_pallas import \
                        flash_attention_bshd_native
                    interp = (os.getenv("PADDLE_TPU_RING_INNER",
                                        "").lower()
                              == "pallas_interpret")
                    out = flash_attention_bshd_native(
                        jnp.swapaxes(q_, 1, 2), jnp.swapaxes(k_, 1, 2),
                        jnp.swapaxes(v_, 1, 2), causal=causal,
                        scale=s_py, interpret=interp)
                    return jnp.swapaxes(out, 1, 2).astype(q_.dtype)
            # blockwise inner fallback: the gathered S_full axis is the
            # long one — never materialise (S_full, S_full) logits
            out, _ = _blockwise_attn(
                q_, k_, v_, jnp.float32(s), jnp.int32(0), jnp.int32(0),
                causal, None, DEFAULT_CHUNK, axis_name)
            return out.astype(q_.dtype)
    out = attn_fn(q2, k2, v2)
    return head_to_seq(out)
