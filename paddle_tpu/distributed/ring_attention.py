"""Ring attention — sequence/context parallelism over the 'sep' mesh axis.

The reference has NO sequence parallelism (SURVEY.md §5.7: no ring
attention/Ulysses/blockwise anywhere in the snapshot); this is a required
capability of the TPU build.  Design:

* ring_attention: each device holds a sequence shard of Q and of K/V.
  K/V shards rotate around the ring via lax.ppermute (ICI neighbor
  exchange) while each device accumulates blockwise-softmax statistics for
  its Q shard — O(S_local) memory, compute overlapped with the rotation by
  XLA's async collectives.  Causality is enforced from global block
  positions (axis_index).
* ulysses_attention: the all-to-all variant — resharding (seq-sharded ->
  head-sharded) with two lax.all_to_all calls around ordinary local
  attention; composes with TP by splitting the head dim.

Both are plain jax functions intended for use inside shard_map (see
tests/test_distributed.py for the driving pattern); grads flow through
scan+ppermute natively.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


def _local_attn_block(q, k, v, scale, mask):
    """One (Sq_local x Sk_block) attention block in f32 stats.

    q: (B, H, Sq, D); k/v: (B, H, Sk, D); mask: (Sq, Sk) bool or None.
    Returns (m, l, acc): running max (B,H,Sq), denom (B,H,Sq),
    weighted values (B,H,Sq,D).
    """
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if mask is not None:
        logits = jnp.where(mask[None, None], logits, _NEG_INF)
    m = jnp.max(logits, axis=-1)
    p = jnp.exp(logits - m[..., None])
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    return m, l, acc


def ring_attention(q, k, v, axis_name: str, causal: bool = True,
                   scale=None):
    """Blockwise ring attention under shard_map.

    q, k, v: (B, H, S_local, D) — the local sequence shard.
    Returns (B, H, S_local, D).
    """
    n = jax.lax.axis_size(axis_name) if hasattr(jax.lax, "axis_size") else \
        jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    b, h, s_loc, d = q.shape
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    scale = jnp.float32(scale)

    q32 = q.astype(jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, i):
        m, l, acc, kv = carry
        k_blk, v_blk = kv
        src = (my - i) % n   # which shard's K/V we currently hold
        if causal:
            # block-level causality on global positions
            q_pos = my * s_loc + jax.lax.broadcasted_iota(
                jnp.int32, (s_loc, s_loc), 0)
            k_pos = src * s_loc + jax.lax.broadcasted_iota(
                jnp.int32, (s_loc, s_loc), 1)
            mask = k_pos <= q_pos
        else:
            mask = None
        bm, bl, bacc = _local_attn_block(q32, k_blk, v_blk, scale, mask)
        new_m = jnp.maximum(m, bm)
        corr = jnp.exp(m - new_m)
        bcorr = jnp.exp(bm - new_m)
        new_l = l * corr + bl * bcorr
        new_acc = acc * corr[..., None] + bacc * bcorr[..., None]
        # rotate K/V to the next device (skipped result unused on last step)
        k_next = jax.lax.ppermute(k_blk, axis_name, perm)
        v_next = jax.lax.ppermute(v_blk, axis_name, perm)
        return (new_m, new_l, new_acc, (k_next, v_next)), None

    m0 = jnp.full((b, h, s_loc), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, s_loc), jnp.float32)
    acc0 = jnp.zeros((b, h, s_loc, d), jnp.float32)
    def _vary(a):  # newer jax: carry constants must be device-varying
        try:
            return jax.lax.pvary(a, axis_name)
        except (AttributeError, ValueError):
            return a

    m0, l0, acc0 = _vary(m0), _vary(l0), _vary(acc0)
    (m, l, acc, _), _ = jax.lax.scan(
        step, (m0, l0, acc0, (k.astype(jnp.float32), v.astype(jnp.float32))),
        jnp.arange(n))
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.astype(q.dtype)


def ulysses_attention(q, k, v, axis_name: str, causal: bool = True,
                      scale=None, attn_fn=None):
    """DeepSpeed-Ulysses style: all_to_all heads<->sequence, local attention,
    all_to_all back.  q/k/v: (B, H, S_local, D) with H divisible by the axis
    size; inside, each device sees (B, H/n, S_full, D)."""
    n = jax.lax.psum(1, axis_name)

    def seq_to_head(x):
        b, h, s_loc, d = x.shape
        x = x.reshape(b, n, h // n, s_loc, d)
        x = jnp.moveaxis(x, 1, 0)                      # (n, b, h/n, s_loc, d)
        x = jax.lax.all_to_all(x, axis_name, split_axis=0, concat_axis=0,
                               tiled=False)            # n dim now = seq chunks
        x = jnp.moveaxis(x, 0, 3)                      # (b, h/n, s_loc, n, d)
        b2, hn, s_loc2, n2, d2 = x.shape
        # (b, h/n, n, s_loc, d) -> concat seq chunks in ring order
        return jnp.reshape(jnp.swapaxes(x, 2, 3), (b2, hn, n2 * s_loc2, d2))

    def head_to_seq(x):
        b, hn, s_full, d = x.shape
        s_loc = s_full // n
        x = x.reshape(b, hn, n, s_loc, d)
        x = jnp.moveaxis(x, 2, 0)                      # (n, b, h/n, s_loc, d)
        x = jax.lax.all_to_all(x, axis_name, split_axis=0, concat_axis=0,
                               tiled=False)
        x = jnp.moveaxis(x, 0, 1)                      # (b, n, h/n, s_loc, d)
        return x.reshape(b, x.shape[1] * x.shape[2], s_loc, d)

    q2, k2, v2 = seq_to_head(q), seq_to_head(k), seq_to_head(v)
    if attn_fn is None:
        def attn_fn(q_, k_, v_):
            d = q_.shape[-1]
            s = scale if scale is not None else 1.0 / (d ** 0.5)
            logits = jnp.einsum("bhqd,bhkd->bhqk", q_, k_).astype(jnp.float32) * s
            if causal:
                sq = logits.shape[-2]
                mask = jnp.tril(jnp.ones((sq, sq), bool))
                logits = jnp.where(mask[None, None], logits, _NEG_INF)
            p = jax.nn.softmax(logits, axis=-1)
            return jnp.einsum("bhqk,bhkd->bhqd", p,
                              v_.astype(jnp.float32)).astype(q_.dtype)
    out = attn_fn(q2, k2, v2)
    return head_to_seq(out)
