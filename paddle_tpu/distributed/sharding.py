"""ZeRO-style sharded training (reference surface:
fleet/meta_parallel/sharding/sharding_stage2.py:43, sharding_stage3.py:50,
python/paddle/distributed/sharding/group_sharded.py group_sharded_parallel).

TPU-native: ZeRO = *sharding annotations*, not runtime hooks (SURVEY.md §7
table): stage1/2 shard optimizer slots (and grads) over the 'sdp' axis;
stage3 additionally shards the parameters, with XLA inserting the
allgather-on-use in fwd/bwd (the weight-gather pattern).  The shardings are
applied by TrainStep via the sharding_spec helpers below.
"""
from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from ..core.tensor import Parameter
from ..nn.layer.layers import Layer
from . import mesh as _mesh


def _stage_spec_for(arr, axis: str, min_size=2 ** 12, fixed=()):
    """Shard the largest divisible dim of `arr` over `axis` (ZeRO slicing is
    layout-free in the reference; on TPU we pick a dim so XLA keeps layouts
    tileable).  ``fixed`` pins the leading dims to the given axis names
    (e.g. ("pp",) for pipeline-stacked slots) — those dims keep their
    sharding and are excluded from the pick."""
    n = _mesh.axis_size(axis)
    base = list(fixed) + [None] * (arr.ndim - len(fixed))
    if n <= 1 or arr.size < min_size:
        return PartitionSpec(*base) if fixed else PartitionSpec()
    free = [d for d in np.argsort(arr.shape)[::-1] if d >= len(fixed)]
    for d in free:
        if arr.shape[d] % n == 0:
            base[int(d)] = axis
            return PartitionSpec(*base)
    return PartitionSpec(*base) if fixed else PartitionSpec()


def shard_optimizer_state(opt_state, axis="sdp"):
    """Stage-1: place optimizer slots sharded over the sharding axis."""
    mesh = _mesh.ensure_mesh()

    def place(x):
        if hasattr(x, "shape") and hasattr(x, "dtype") and x.ndim > 0:
            return jax.device_put(x, NamedSharding(mesh, _stage_spec_for(x, axis)))
        return x

    return jax.tree_util.tree_map(place, opt_state)


def shard_params(model: Layer, axis="sdp"):
    """Stage-3: shard the parameters themselves."""
    mesh = _mesh.ensure_mesh()
    for _, p in model.named_parameters():
        spec = _stage_spec_for(p._array, axis)
        p._array = jax.device_put(p._array, NamedSharding(mesh, spec))
        p.pspec = spec
    return model


class ShardingParallel(Layer):
    """Model wrapper for the sharding mode (fleet dispatch target)."""

    def __init__(self, layers, hcg=None, strategy=None):
        super().__init__()
        self._layers = layers
        self.add_sublayer("_layers", layers)
        stage = 1
        if strategy is not None:
            stage = strategy.sharding_configs.stage
        if stage >= 3:
            shard_params(layers)

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)


def group_sharded_parallel(model, optimizer, level="os_g", scaler=None,
                           group=None, offload=False, sync_buffers=False,
                           buffer_max_size=2 ** 23, segment_size=2 ** 20,
                           sync_comm=False):
    """reference parity: python/paddle/distributed/sharding/group_sharded.py.

    level: "os" (stage1) | "os_g" (stage2) | "p_g_os" (stage3).
    """
    if level in ("p_g_os",):
        shard_params(model)
    # optimizer accumulators shard lazily at first step via init_one shapes;
    # for the compiled path TrainStep calls shard_optimizer_state.
    model._sharding_level = level
    if scaler is not None:
        return model, optimizer, scaler
    return model, optimizer


def save_group_sharded_model(model, output, optimizer=None):
    from .. import framework
    framework.save(model.state_dict(), output + ".pdparams")
    if optimizer is not None:
        framework.save(optimizer.state_dict(), output + ".pdopt")
