"""Process-level parallel environment + GSPMD parallelize
(reference: python/paddle/distributed/parallel.py:91 init_parallel_env,
fluid/dygraph/parallel.py:76 ParallelEnv).

TPU-native: `init_parallel_env` = jax.distributed.initialize (the TCPStore/
ncclUniqueId exchange analogue, N23) + global mesh creation.  `parallelize`
applies GSPMD shardings to a Layer's parameters — the pjit answer to the
reference's auto_parallel Completer/Partitioner (SURVEY.md §2.2 last rows).
"""
from __future__ import annotations

import os
from typing import Dict, Optional

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from ..core.tensor import Parameter, Tensor
from ..nn.parallel import DataParallel  # re-export
from . import mesh as _mesh


class ParallelEnv:
    """reference parity: fluid/dygraph/parallel.py:76 — env-var view of the
    cluster (PADDLE_TRAINER_ID etc. honored for compatibility)."""

    def __init__(self):
        self._rank = int(os.getenv("PADDLE_TRAINER_ID",
                                   str(_safe_process_index())))
        self._world_size = int(os.getenv("PADDLE_TRAINERS_NUM",
                                         str(_safe_process_count())))
        self._device_id = 0
        self._trainer_endpoints = os.getenv("PADDLE_TRAINER_ENDPOINTS",
                                            "").split(",")
        self._current_endpoint = os.getenv("PADDLE_CURRENT_ENDPOINT", "")

    @property
    def rank(self):
        return self._rank

    @property
    def world_size(self):
        return self._world_size

    @property
    def device_id(self):
        return self._device_id

    @property
    def trainer_endpoints(self):
        return self._trainer_endpoints

    @property
    def current_endpoint(self):
        return self._current_endpoint

    local_rank = rank
    nranks = world_size


def _safe_process_index():
    try:
        return jax.process_index()
    except Exception:
        return 0


def _safe_process_count():
    try:
        return jax.process_count()
    except Exception:
        return 1


_initialized = [False]


def _jax_distributed_initialized() -> bool:
    return bool(jax.distributed.is_initialized())


def init_parallel_env(backend=None, mesh_axes: Optional[Dict[str, int]] = None):
    """reference parity: parallel.py:91.

    Multi-host: set PADDLE_MASTER (host:port) + PADDLE_TRAINER_ID +
    PADDLE_TRAINERS_NUM and this calls jax.distributed.initialize (rendezvous
    = the reference's TCPStore exchange).  Single-host: creates the global
    device mesh immediately.
    """
    if _initialized[0]:
        return ParallelEnv()
    master = os.getenv("PADDLE_MASTER") or os.getenv("MASTER_ADDR")
    nprocs = int(os.getenv("PADDLE_TRAINERS_NUM", "1"))
    if master and nprocs > 1 and not _jax_distributed_initialized():
        # NOTE: jax.distributed.initialize must run before the XLA backend
        # initializes; if anything touched jax first, call
        # jax.distributed.initialize(...) at the very top of the worker
        # (see tests/test_multiprocess_dp.py) — this branch then skips.
        port = os.getenv("MASTER_PORT")
        addr = master if ":" in master or not port else f"{master}:{port}"
        jax.distributed.initialize(
            coordinator_address=addr,
            num_processes=nprocs,
            process_id=int(os.getenv("PADDLE_TRAINER_ID", "0")))
    if mesh_axes:
        _mesh.init_mesh(mesh_axes)
    else:
        _mesh.ensure_mesh()
    _initialized[0] = True
    return ParallelEnv()


def get_rank(group=None):
    return _safe_process_index()


def get_world_size(group=None):
    if group is not None:
        from .collective import _axis_of
        return max(_mesh.axis_size(_axis_of(group)), 1)
    return _safe_process_count()


def is_initialized():
    return _initialized[0]


# -- GSPMD annotation API ----------------------------------------------------


def shard_tensor(x, mesh=None, placement=None, process_mesh=None,
                 shard_spec=None):
    """reference parity: auto_parallel/interface.py:34 shard_tensor — but on
    TPU the annotation IS the implementation: device_put with a
    NamedSharding; XLA GSPMD propagates and inserts collectives."""
    spec = placement if placement is not None else shard_spec
    mesh = mesh or process_mesh or _mesh.ensure_mesh()
    if spec is None:
        spec = PartitionSpec()
    elif isinstance(spec, (list, tuple)) and not isinstance(spec, PartitionSpec):
        spec = PartitionSpec(*[s if s is not None else None for s in spec])
    sharding = NamedSharding(mesh, spec)
    if isinstance(x, Tensor):
        arr = jax.device_put(x._array, sharding)
        if isinstance(x, Parameter):
            x._array = arr
            x.pspec = spec
            return x
        t = Tensor(arr, stop_gradient=x.stop_gradient)
        return t
    return jax.device_put(x, sharding)


def parallelize(model, mesh=None, dp_axis="dp", mp_axis=None,
                param_rules=None):
    """Apply shardings to every parameter of `model`.

    * default: replicate params (data parallel — inputs sharded on dp_axis)
    * mp_axis + built-in rules: Megatron layout for Linear/Embedding weights
      when the layer was built with ColumnParallel/RowParallel markers (see
      distributed.mp_layers), honoring each Parameter's `pspec` annotation.
    """
    mesh = mesh or _mesh.ensure_mesh()
    for name, p in model.named_parameters():
        spec = p.pspec if p.pspec is not None else PartitionSpec()
        if param_rules:
            for pattern, s in param_rules.items():
                if pattern in name:
                    spec = s if isinstance(s, PartitionSpec) else PartitionSpec(*s)
        p._array = jax.device_put(p._array, NamedSharding(mesh, spec))
        p.pspec = spec
    for _, b in model.named_buffers():
        b._array = jax.device_put(b._array, NamedSharding(mesh, PartitionSpec()))
    return model


def shard_dataloader(dataloader, mesh=None, axis="dp"):
    """Wrap a DataLoader so each yielded batch is device_put with its leading
    axis sharded over `axis` — the input half of data parallelism."""
    mesh = mesh or _mesh.ensure_mesh()
    sharding = NamedSharding(mesh, PartitionSpec(axis))

    class _Sharded:
        def __init__(self, inner):
            self._inner = inner

        def __iter__(self):
            for batch in self._inner:
                yield jax.tree_util.tree_map(
                    lambda t: (Tensor(jax.device_put(t._array, sharding))
                               if isinstance(t, Tensor) else
                               jax.device_put(t, sharding)),
                    batch, is_leaf=lambda l: isinstance(l, Tensor))

        def __len__(self):
            return len(self._inner)

    return _Sharded(dataloader)
