"""Distributed launch CLI (reference: python/paddle/distributed/launch/main.py:18
+ launch/controllers/collective.py — spawn per-host workers, wire the cluster
env, write per-rank logs; elastic restart per fleet/elastic/manager.py:130).

TPU-native shape: one worker process per host is the normal topology (the
single-controller pjit model fans out across the host's chips), so
``--nproc_per_node`` defaults to 1; multiple local procs are supported for
CPU-mesh testing and multi-process simulation.

Usage::

    python -m paddle_tpu.distributed.launch_main \
        [--nnodes 1] [--node_rank 0] [--nproc_per_node N] \
        [--master host:port] [--log_dir log] \
        [--elastic] [--max_restarts 3] \
        training_script [args...]

Env contract given to every worker (reference names, launch/controllers):
``PADDLE_TRAINER_ID`` (global rank), ``PADDLE_TRAINERS_NUM`` (world size),
``PADDLE_MASTER``, ``PADDLE_LOCAL_RANK``, ``PADDLE_CURRENT_ENDPOINT``,
``PADDLE_TRAINER_ENDPOINTS``; `init_parallel_env` consumes these
(parallel_base.py).  With ``--elastic``, a worker that dies is restarted (up
to ``--max_restarts`` times) and is expected to resume from its newest
checkpoint (incubate.checkpoint auto-resume contract).
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time
from typing import List, Optional

from ..robustness.faultpoints import declare as _declare, faultpoint
from ..robustness.preemption import PREEMPTED_RC

__all__ = ["main", "Launcher"]

_declare("launch.respawn",
         "fires before an elastic worker respawn (rc + local_rank in ctx)")

#: crash-loop backoff ceiling — doubling stops here
_MAX_RESTART_DELAY = 60.0


def _parse(argv):
    p = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="paddle_tpu distributed launcher")
    p.add_argument("--nnodes", type=int,
                   default=int(os.getenv("PADDLE_NNODES", "1")))
    p.add_argument("--node_rank", type=int,
                   default=int(os.getenv("PADDLE_NODE_RANK", "0")))
    p.add_argument("--nproc_per_node", type=int,
                   default=int(os.getenv("PADDLE_NPROC_PER_NODE", "1")))
    p.add_argument("--master", type=str,
                   default=os.getenv("PADDLE_MASTER", ""))
    p.add_argument("--ips", type=str,
                   default=os.getenv("PADDLE_NODE_IPS", ""),
                   help="comma-separated node hostnames/IPs, one per node "
                        "(required for --nnodes > 1)")
    p.add_argument("--log_dir", type=str, default="log")
    p.add_argument("--elastic", action="store_true",
                   help="restart dead workers (fleet/elastic semantics)")
    p.add_argument("--max_restarts", type=int, default=3)
    p.add_argument("--poll_interval", type=float, default=0.2)
    p.add_argument("--restart_delay", type=float, default=1.0,
                   help="base delay before an elastic respawn; doubled per "
                        "consecutive fast failure (crash-loop backoff)")
    p.add_argument("--healthy_interval", type=float, default=30.0,
                   help="a worker alive at least this long resets its "
                        "crash-loop backoff to --restart_delay")
    p.add_argument("script", type=str)
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


class Launcher:
    """Spawns + supervises this node's worker processes."""

    def __init__(self, nnodes=1, node_rank=0, nproc_per_node=1, master="",
                 ips="", log_dir="log", elastic=False, max_restarts=3,
                 poll_interval=0.2, restart_delay=1.0,
                 healthy_interval=30.0):
        self.nnodes = nnodes
        self.node_rank = node_rank
        self.nproc = nproc_per_node
        self.master = master
        self.ips = [h for h in ips.split(",") if h] if ips else []
        if nnodes > 1 and len(self.ips) != nnodes:
            raise ValueError(
                f"--nnodes {nnodes} needs --ips with exactly {nnodes} "
                "hostnames (endpoints cannot be 127.0.0.1 across nodes)")
        self.log_dir = log_dir
        self.elastic = elastic
        self.max_restarts = max_restarts
        self.poll_interval = poll_interval
        self.restart_delay = restart_delay
        self.healthy_interval = healthy_interval
        self.world_size = nnodes * nproc_per_node
        self._procs: List[Optional[subprocess.Popen]] = []
        self._logs: List = []
        self._restarts = [0] * nproc_per_node
        # crash-loop backoff state: next respawn delay + last spawn time,
        # per local worker; backoff_log records every applied delay (the
        # chaos tests assert the doubling schedule from it)
        self._delay = [restart_delay] * nproc_per_node
        self._spawned_at = [0.0] * nproc_per_node
        self.backoff_log: List[float] = []   # crash-backoff delays applied
        self.preempt_respawns = 0            # budget-free preempt restarts

    # -- env wiring ---------------------------------------------------------
    def _worker_env(self, local_rank: int) -> dict:
        rank = self.node_rank * self.nproc + local_rank
        env = dict(os.environ)
        base_port = int(os.getenv("PADDLE_WORKER_PORT_BASE", "6170"))

        def host_of(r):
            return self.ips[r // self.nproc] if self.ips else "127.0.0.1"

        endpoints = ",".join(
            f"{host_of(r)}:{base_port + r % self.nproc}"
            for r in range(self.world_size))
        env.update({
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(self.world_size),
            "PADDLE_LOCAL_RANK": str(local_rank),
            "PADDLE_RANK_IN_NODE": str(local_rank),
            "PADDLE_CURRENT_ENDPOINT":
                f"{host_of(rank)}:{base_port + local_rank}",
            "PADDLE_TRAINER_ENDPOINTS": endpoints,
            "PADDLE_RESTART_COUNT": str(self._restarts[local_rank]),
        })
        if self.master:
            env["PADDLE_MASTER"] = self.master
        return env

    # -- process control ----------------------------------------------------
    def _start_one(self, local_rank: int, cmd: List[str]):
        rank = self.node_rank * self.nproc + local_rank
        os.makedirs(self.log_dir, exist_ok=True)
        log = open(os.path.join(self.log_dir, f"workerlog.{rank}"), "ab",
                   buffering=0)
        proc = subprocess.Popen(cmd, env=self._worker_env(local_rank),
                                stdout=log, stderr=subprocess.STDOUT)
        self._spawned_at[local_rank] = time.time()
        return proc, log

    def run(self, cmd: List[str]) -> int:
        """Start all local workers and supervise until done.  Returns the
        job exit code (0 = every worker exited 0)."""
        self._procs, self._logs = [], []
        for lr in range(self.nproc):
            p, log = self._start_one(lr, cmd)
            self._procs.append(p)
            self._logs.append(log)
        try:
            return self._supervise(cmd)
        finally:
            self._kill_all()
            for log in self._logs:
                try:
                    log.close()
                except Exception:
                    pass

    def _respawn(self, lr: int, cmd, rc: int):
        faultpoint("launch.respawn", local_rank=lr, rc=rc)
        p, log = self._start_one(lr, cmd)
        self._procs[lr] = p
        try:
            # close the dead worker's log handle before replacing it —
            # appending leaked one fd per restart across long elastic runs
            self._logs[lr].close()
        except Exception:
            pass
        self._logs[lr] = log

    def _supervise(self, cmd) -> int:
        live = set(range(self.nproc))
        # lr -> (monotonic respawn deadline, rc): crash-loop backoff is a
        # per-worker DEADLINE, not an inline sleep — supervision of every
        # other worker (including "abort the job on a non-elastic death")
        # keeps polling while one worker waits out its backoff
        pending = {}
        while live:
            time.sleep(self.poll_interval)
            now = time.monotonic()
            for lr in sorted(pending):
                when, rc = pending[lr]
                if now >= when:
                    del pending[lr]
                    self._respawn(lr, cmd, rc)
            for lr in sorted(live):
                if lr in pending:
                    continue  # dead, waiting out its backoff
                rc = self._procs[lr].poll()
                if rc is None:
                    continue
                if rc == 0:
                    live.discard(lr)
                    continue
                # worker death (reference: elastic watch → restart)
                if self.elastic and rc == PREEMPTED_RC:
                    # the worker drained an emergency checkpoint and left on
                    # preemption notice — restart-eligible, NOT a crash: it
                    # consumes no restart budget.  It still rides the
                    # delay/doubling machinery (budget-free): a scheduler
                    # draining the node SIGTERMs every fresh incarnation,
                    # and an undelayed respawn loop would hammer the shared
                    # checkpoint filesystem with emergency saves
                    uptime = time.time() - self._spawned_at[lr]
                    if uptime >= self.healthy_interval:
                        self._delay[lr] = self.restart_delay
                    delay = self._delay[lr]
                    self.preempt_respawns += 1
                    sys.stderr.write(
                        f"[launch] worker {lr} preempted (rc={rc}) after "
                        f"{uptime:.1f}s; restarting in {delay:.1f}s "
                        "without consuming restart budget\n")
                    pending[lr] = (now + delay, rc)
                    if uptime < self.healthy_interval:
                        self._delay[lr] = min(delay * 2, _MAX_RESTART_DELAY)
                elif self.elastic and self._restarts[lr] < self.max_restarts:
                    uptime = time.time() - self._spawned_at[lr]
                    if uptime >= self.healthy_interval:
                        # it ran long enough to be considered healthy before
                        # dying — not a crash loop; restart promptly
                        self._delay[lr] = self.restart_delay
                    self._restarts[lr] += 1
                    delay = self._delay[lr]
                    sys.stderr.write(
                        f"[launch] worker {lr} exited rc={rc} after "
                        f"{uptime:.1f}s; elastic restart "
                        f"{self._restarts[lr]}/{self.max_restarts} in "
                        f"{delay:.1f}s\n")
                    self.backoff_log.append(delay)
                    pending[lr] = (now + delay, rc)
                    if uptime < self.healthy_interval:
                        # consecutive fast failure: double toward the cap so
                        # a crash-looping worker cannot hot-spin through
                        # max_restarts (and hammer the store/cluster)
                        self._delay[lr] = min(delay * 2, _MAX_RESTART_DELAY)
                else:
                    sys.stderr.write(
                        f"[launch] worker {lr} exited rc={rc}; aborting job\n")
                    return rc
        return 0

    def _kill_all(self):
        for p in self._procs:
            if p is not None and p.poll() is None:
                try:
                    p.send_signal(signal.SIGTERM)
                except Exception:
                    pass
        deadline = time.time() + 5
        for p in self._procs:
            if p is None:
                continue
            while p.poll() is None and time.time() < deadline:
                time.sleep(0.05)
            if p.poll() is None:
                try:
                    p.kill()
                except Exception:
                    pass


def main(argv=None) -> int:
    args = _parse(sys.argv[1:] if argv is None else argv)
    cmd = [sys.executable, args.script] + args.script_args
    launcher = Launcher(
        nnodes=args.nnodes, node_rank=args.node_rank,
        nproc_per_node=args.nproc_per_node, master=args.master,
        ips=args.ips, log_dir=args.log_dir, elastic=args.elastic,
        max_restarts=args.max_restarts, poll_interval=args.poll_interval,
        restart_delay=args.restart_delay,
        healthy_interval=args.healthy_interval)
    return launcher.run(cmd)


if __name__ == "__main__":
    sys.exit(main())
