"""Distributed launch CLI (reference: python/paddle/distributed/launch/main.py:18
+ launch/controllers/collective.py — spawn per-host workers, wire the cluster
env, write per-rank logs; elastic restart per fleet/elastic/manager.py:130).

TPU-native shape: one worker process per host is the normal topology (the
single-controller pjit model fans out across the host's chips), so
``--nproc_per_node`` defaults to 1; multiple local procs are supported for
CPU-mesh testing and multi-process simulation.

Usage::

    python -m paddle_tpu.distributed.launch_main \
        [--nnodes 1] [--node_rank 0] [--nproc_per_node N] \
        [--master host:port] [--log_dir log] \
        [--elastic] [--max_restarts 3] \
        training_script [args...]

Env contract given to every worker (reference names, launch/controllers):
``PADDLE_TRAINER_ID`` (global rank), ``PADDLE_TRAINERS_NUM`` (world size),
``PADDLE_MASTER``, ``PADDLE_LOCAL_RANK``, ``PADDLE_CURRENT_ENDPOINT``,
``PADDLE_TRAINER_ENDPOINTS``; `init_parallel_env` consumes these
(parallel_base.py).  With ``--elastic``, a worker that dies is restarted (up
to ``--max_restarts`` times) and is expected to resume from its newest
checkpoint (incubate.checkpoint auto-resume contract).
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time
from typing import List, Optional

__all__ = ["main", "Launcher"]


def _parse(argv):
    p = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="paddle_tpu distributed launcher")
    p.add_argument("--nnodes", type=int,
                   default=int(os.getenv("PADDLE_NNODES", "1")))
    p.add_argument("--node_rank", type=int,
                   default=int(os.getenv("PADDLE_NODE_RANK", "0")))
    p.add_argument("--nproc_per_node", type=int,
                   default=int(os.getenv("PADDLE_NPROC_PER_NODE", "1")))
    p.add_argument("--master", type=str,
                   default=os.getenv("PADDLE_MASTER", ""))
    p.add_argument("--ips", type=str,
                   default=os.getenv("PADDLE_NODE_IPS", ""),
                   help="comma-separated node hostnames/IPs, one per node "
                        "(required for --nnodes > 1)")
    p.add_argument("--log_dir", type=str, default="log")
    p.add_argument("--elastic", action="store_true",
                   help="restart dead workers (fleet/elastic semantics)")
    p.add_argument("--max_restarts", type=int, default=3)
    p.add_argument("--poll_interval", type=float, default=0.2)
    p.add_argument("script", type=str)
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


class Launcher:
    """Spawns + supervises this node's worker processes."""

    def __init__(self, nnodes=1, node_rank=0, nproc_per_node=1, master="",
                 ips="", log_dir="log", elastic=False, max_restarts=3,
                 poll_interval=0.2):
        self.nnodes = nnodes
        self.node_rank = node_rank
        self.nproc = nproc_per_node
        self.master = master
        self.ips = [h for h in ips.split(",") if h] if ips else []
        if nnodes > 1 and len(self.ips) != nnodes:
            raise ValueError(
                f"--nnodes {nnodes} needs --ips with exactly {nnodes} "
                "hostnames (endpoints cannot be 127.0.0.1 across nodes)")
        self.log_dir = log_dir
        self.elastic = elastic
        self.max_restarts = max_restarts
        self.poll_interval = poll_interval
        self.world_size = nnodes * nproc_per_node
        self._procs: List[Optional[subprocess.Popen]] = []
        self._logs: List = []
        self._restarts = [0] * nproc_per_node

    # -- env wiring ---------------------------------------------------------
    def _worker_env(self, local_rank: int) -> dict:
        rank = self.node_rank * self.nproc + local_rank
        env = dict(os.environ)
        base_port = int(os.getenv("PADDLE_WORKER_PORT_BASE", "6170"))

        def host_of(r):
            return self.ips[r // self.nproc] if self.ips else "127.0.0.1"

        endpoints = ",".join(
            f"{host_of(r)}:{base_port + r % self.nproc}"
            for r in range(self.world_size))
        env.update({
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(self.world_size),
            "PADDLE_LOCAL_RANK": str(local_rank),
            "PADDLE_RANK_IN_NODE": str(local_rank),
            "PADDLE_CURRENT_ENDPOINT":
                f"{host_of(rank)}:{base_port + local_rank}",
            "PADDLE_TRAINER_ENDPOINTS": endpoints,
            "PADDLE_RESTART_COUNT": str(self._restarts[local_rank]),
        })
        if self.master:
            env["PADDLE_MASTER"] = self.master
        return env

    # -- process control ----------------------------------------------------
    def _start_one(self, local_rank: int, cmd: List[str]):
        rank = self.node_rank * self.nproc + local_rank
        os.makedirs(self.log_dir, exist_ok=True)
        log = open(os.path.join(self.log_dir, f"workerlog.{rank}"), "ab",
                   buffering=0)
        proc = subprocess.Popen(cmd, env=self._worker_env(local_rank),
                                stdout=log, stderr=subprocess.STDOUT)
        return proc, log

    def run(self, cmd: List[str]) -> int:
        """Start all local workers and supervise until done.  Returns the
        job exit code (0 = every worker exited 0)."""
        self._procs, self._logs = [], []
        for lr in range(self.nproc):
            p, log = self._start_one(lr, cmd)
            self._procs.append(p)
            self._logs.append(log)
        try:
            return self._supervise(cmd)
        finally:
            self._kill_all()
            for log in self._logs:
                try:
                    log.close()
                except Exception:
                    pass

    def _supervise(self, cmd) -> int:
        live = set(range(self.nproc))
        while live:
            time.sleep(self.poll_interval)
            for lr in sorted(live):
                rc = self._procs[lr].poll()
                if rc is None:
                    continue
                if rc == 0:
                    live.discard(lr)
                    continue
                # worker death (reference: elastic watch → restart)
                if self.elastic and self._restarts[lr] < self.max_restarts:
                    self._restarts[lr] += 1
                    sys.stderr.write(
                        f"[launch] worker {lr} exited rc={rc}; elastic "
                        f"restart {self._restarts[lr]}/{self.max_restarts}\n")
                    p, log = self._start_one(lr, cmd)
                    self._procs[lr] = p
                    try:
                        # close the dead worker's log handle before
                        # replacing it — appending leaked one fd per
                        # restart across long elastic runs
                        self._logs[lr].close()
                    except Exception:
                        pass
                    self._logs[lr] = log
                else:
                    sys.stderr.write(
                        f"[launch] worker {lr} exited rc={rc}; aborting job\n")
                    return rc
        return 0

    def _kill_all(self):
        for p in self._procs:
            if p is not None and p.poll() is None:
                try:
                    p.send_signal(signal.SIGTERM)
                except Exception:
                    pass
        deadline = time.time() + 5
        for p in self._procs:
            if p is None:
                continue
            while p.poll() is None and time.time() < deadline:
                time.sleep(0.05)
            if p.poll() is None:
                try:
                    p.kill()
                except Exception:
                    pass


def main(argv=None) -> int:
    args = _parse(sys.argv[1:] if argv is None else argv)
    cmd = [sys.executable, args.script] + args.script_args
    launcher = Launcher(
        nnodes=args.nnodes, node_rank=args.node_rank,
        nproc_per_node=args.nproc_per_node, master=args.master,
        ips=args.ips, log_dir=args.log_dir, elastic=args.elastic,
        max_restarts=args.max_restarts, poll_interval=args.poll_interval)
    return launcher.run(cmd)


if __name__ == "__main__":
    sys.exit(main())
