"""TCPStore — Python interface over the native C++ store (csrc/tcp_store.cpp).

reference parity: paddle/fluid/distributed/store/tcp_store.h:91 (TCPStore,
MasterDaemon) and python `core.TCPStore(master_addr, port, is_master,
world_size)` used by init_parallel_env (parallel.py:235).  Pure-Python
fallback server keeps everything working without the native build.
"""
from __future__ import annotations

import socket
import struct
import threading
import time
from typing import Optional

from ..core import native as _native


class TCPStore:
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 is_master: bool = False, world_size: int = 1,
                 timeout: float = 30.0):
        self.host = host
        self.is_master = is_master
        self.world_size = world_size
        self._server = None
        self._py_server = None
        lib = _native.load()
        self._lib = lib
        if is_master:
            if lib is not None:
                self._server = lib.tcp_store_server_create(port)
                if not self._server:
                    raise RuntimeError(f"TCPStore: cannot bind port {port}")
                port = lib.tcp_store_server_port(self._server)
            else:
                self._py_server = _PyStoreServer(port)
                port = self._py_server.port
        self.port = port
        if lib is not None:
            self._client = lib.tcp_store_client_create(host.encode(), port)
            if not self._client:
                raise RuntimeError(f"TCPStore: cannot connect {host}:{port}")
        else:
            self._client = _PyStoreClient(host, port, timeout)

    def set(self, key: str, value):
        data = value if isinstance(value, bytes) else str(value).encode()
        if self._lib is not None:
            rc = self._lib.tcp_store_set(self._client, key.encode(), data,
                                         len(data))
            if rc != 0:
                raise RuntimeError("TCPStore.set failed")
        else:
            self._client.set(key, data)

    def get(self, key: str, wait: bool = True) -> bytes:
        if self._lib is not None:
            import ctypes
            cap = 1 << 20
            buf = ctypes.create_string_buffer(cap)
            n = self._lib.tcp_store_get(self._client, key.encode(), buf, cap,
                                        1 if wait else 0)
            if n == -1:
                raise KeyError(key)
            if n < 0:
                raise RuntimeError("TCPStore.get failed")
            return buf.raw[:n]
        return self._client.get(key, wait)

    def add(self, key: str, amount: int = 1) -> int:
        if self._lib is not None:
            out = self._lib.tcp_store_add(self._client, key.encode(), amount)
            if out == -(1 << 63):
                raise RuntimeError("TCPStore.add failed")
            return int(out)
        return self._client.add(key, amount)

    def wait(self, keys, timeout: Optional[float] = None):
        keys = keys if isinstance(keys, (list, tuple)) else [keys]
        for k in keys:
            self.get(k, wait=True)

    def barrier(self, key: str = "_barrier", timeout: float = 60.0):
        """All world_size participants block until everyone arrived."""
        n = self.add(key + ":cnt", 1)
        target = self.world_size
        if n % target == 0:
            self.set(key + f":gen{n // target}", b"1")
        gen = (n + target - 1) // target
        deadline = time.time() + timeout
        while time.time() < deadline:
            try:
                self.get(key + f":gen{gen}", wait=False)
                return
            except KeyError:
                time.sleep(0.01)
        raise TimeoutError("TCPStore.barrier timed out")

    def __del__(self):
        try:
            if self._lib is not None:
                if getattr(self, "_client", None):
                    self._lib.tcp_store_client_destroy(self._client)
                if getattr(self, "_server", None):
                    self._lib.tcp_store_server_destroy(self._server)
        except Exception:
            pass


# -- pure-Python fallback ----------------------------------------------------


class _PyStoreServer:
    def __init__(self, port):
        self._data = {}
        self._cv = threading.Condition()
        self._sock = socket.socket()
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("0.0.0.0", port))
        self._sock.listen(64)
        self.port = self._sock.getsockname()[1]
        threading.Thread(target=self._accept, daemon=True).start()

    def _accept(self):
        while True:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn):
        def read_full(n):
            buf = b""
            while len(buf) < n:
                chunk = conn.recv(n - len(buf))
                if not chunk:
                    raise ConnectionError
                buf += chunk
            return buf

        try:
            while True:
                op = read_full(1)[0]
                klen = struct.unpack("<I", read_full(4))[0]
                key = read_full(klen).decode()
                if op == 1:    # SET
                    vlen = struct.unpack("<I", read_full(4))[0]
                    val = read_full(vlen)
                    with self._cv:
                        self._data[key] = val
                        self._cv.notify_all()
                    conn.sendall(b"\x01")
                elif op in (2, 4):  # GET / WAIT
                    with self._cv:
                        if op == 4:
                            self._cv.wait_for(lambda: key in self._data)
                        val = self._data.get(key)
                    if val is None:
                        conn.sendall(struct.pack("<I", 0xFFFFFFFF))
                    else:
                        conn.sendall(struct.pack("<I", len(val)) + val)
                elif op == 3:  # ADD
                    vlen = struct.unpack("<I", read_full(4))[0]
                    inc = struct.unpack("<q", read_full(vlen))[0]
                    with self._cv:
                        cur = struct.unpack(
                            "<q", self._data.get(key, b"\0" * 8))[0]
                        out = cur + inc
                        self._data[key] = struct.pack("<q", out)
                        self._cv.notify_all()
                    conn.sendall(struct.pack("<q", out))
        except (ConnectionError, OSError):
            pass
        finally:
            conn.close()


class _PyStoreClient:
    def __init__(self, host, port, timeout):
        deadline = time.time() + timeout
        while True:
            try:
                self._sock = socket.create_connection((host, port), timeout=5)
                break
            except OSError:
                if time.time() > deadline:
                    raise
                time.sleep(0.1)
        self._lock = threading.Lock()

    def _read_full(self, n):
        buf = b""
        while len(buf) < n:
            chunk = self._sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError
            buf += chunk
        return buf

    def set(self, key, data):
        with self._lock:
            kb = key.encode()
            self._sock.sendall(bytes([1]) + struct.pack("<I", len(kb)) + kb
                               + struct.pack("<I", len(data)) + data)
            self._read_full(1)

    def get(self, key, wait):
        with self._lock:
            kb = key.encode()
            self._sock.sendall(bytes([4 if wait else 2])
                               + struct.pack("<I", len(kb)) + kb)
            ln = struct.unpack("<I", self._read_full(4))[0]
            if ln == 0xFFFFFFFF:
                raise KeyError(key)
            return self._read_full(ln)

    def add(self, key, amount):
        with self._lock:
            kb = key.encode()
            self._sock.sendall(bytes([3]) + struct.pack("<I", len(kb)) + kb
                               + struct.pack("<I", 8)
                               + struct.pack("<q", amount))
            return struct.unpack("<q", self._read_full(8))[0]
