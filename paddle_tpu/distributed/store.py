"""TCPStore — Python interface over the native C++ store (csrc/tcp_store.cpp).

reference parity: paddle/fluid/distributed/store/tcp_store.h:91 (TCPStore,
MasterDaemon) and python `core.TCPStore(master_addr, port, is_master,
world_size)` used by init_parallel_env (parallel.py:235).  Pure-Python
fallback server keeps everything working without the native build.
"""
from __future__ import annotations

import socket
import struct
import threading
import time
from typing import Optional

from ..core import native as _native
from ..observability import liveness as _liveness
from ..robustness import retry as _retry
from ..robustness.faultpoints import declare as _declare, faultpoint

_declare("store.client_op",
         "raise before a TCPStore client op (socket reset, transient IO)")

# liveness beacon over one client op INCLUDING its whole retry schedule
# (wait()/barrier() poll with fast non-blocking probes, so a healthy
# rendezvous pulses steadily; a server-side wedge stalls it).  600s
# default sits above the store's own 300s wait deadline: the store's
# typed TimeoutError is the first line of defense, the watchdog catches
# the ops with no deadline of their own (a blocking native get).
_liveness.declare_beacon(
    "store.op", "one TCPStore client op (set/get/add) through the "
    "retry policy", deadline=600.0)


class StoreReplyLostError(ConnectionError):
    """A non-idempotent op's request reached the wire but the reply was
    lost — the server MAY have applied it.  Never auto-retried (a blind
    reissue of ``add`` would double-increment rendezvous counters and
    desynchronize ``barrier``'s generation math); the caller decides."""


def _store_timeout(default: float) -> float:
    """PADDLE_TPU_STORE_TIMEOUT overrides every fixed store timeout
    (wait/barrier) in one place."""
    return _retry.env_float("PADDLE_TPU_STORE_TIMEOUT", default)


class TCPStore:
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 is_master: bool = False, world_size: int = 1,
                 timeout: float = 30.0):
        self.host = host
        self.is_master = is_master
        self.world_size = world_size
        self._server = None
        self._py_server = None
        self._native_buf = None
        self._native_buf_lock = threading.Lock()
        # fetched once; the NOOP_BEACON singleton when liveness is off
        self._beacon = _liveness.beacon("store.op")
        lib = _native.load()
        self._lib = lib
        if is_master:
            if lib is not None:
                self._server = lib.tcp_store_server_create(port)
                if not self._server:
                    raise RuntimeError(f"TCPStore: cannot bind port {port}")
                port = lib.tcp_store_server_port(self._server)
            else:
                self._py_server = _PyStoreServer(port)
                port = self._py_server.port
        self.port = port
        if lib is not None:
            # the native client honors the caller's connect deadline the
            # same way the pure-Python fallback does — a cluster CLI
            # probing a dead master with --timeout 0.5 must not hang 30s
            self._client = lib.tcp_store_client_create_t(
                host.encode(), port, int(max(timeout, 0.0) * 1000))
            if not self._client:
                raise RuntimeError(f"TCPStore: cannot connect {host}:{port}")
        else:
            self._client = _PyStoreClient(host, port, timeout)

    def _op(self, opname: str, fn):
        """Every client op goes through one retry policy: transient socket
        errors (reset/refused/timeout — real or injected at the
        ``store.client_op`` faultpoint) are retried with jittered backoff,
        reconnecting the pure-Python client's broken stream between
        attempts.  Non-transient errors (KeyError, protocol bugs)
        propagate immediately.  :class:`StoreReplyLostError` (an ``add``
        whose request may already have been applied server-side) is
        deliberately excluded from retry — reissuing it would
        double-increment and desynchronize ``barrier``; it surfaces typed
        so the caller can re-rendezvous instead."""
        def attempt():
            faultpoint("store.client_op", op=opname, store=self)
            return fn()

        def retryable(exc):
            if isinstance(exc, StoreReplyLostError):
                return False
            return _retry.transient(exc)

        def reconnect(exc, attempt_no, delay):
            client = self._client
            if isinstance(client, _PyStoreClient):
                try:
                    client.reconnect()
                except OSError:
                    pass  # next attempt surfaces the (still-broken) link

        with self._beacon:   # liveness: a wedged store op is a stall
            return _retry.retry_call(attempt, retry_on=retryable,
                                     on_retry=reconnect,
                                     name="TCPStore.%s" % opname)

    def set(self, key: str, value):
        data = value if isinstance(value, bytes) else str(value).encode()

        def do_set():
            if self._lib is not None:
                rc = self._lib.tcp_store_set(self._client, key.encode(),
                                             data, len(data))
                if rc != 0:
                    raise RuntimeError("TCPStore.set failed")
            else:
                self._client.set(key, data)
        return self._op("set", do_set)

    def get(self, key: str, wait: bool = True) -> bytes:
        def do_get():
            if self._lib is not None:
                import ctypes
                n_cap = 1 << 20
                if wait:
                    # a wait=True get blocks server-side until the key
                    # exists — it must NOT hold the shared buffer lock
                    # (a concurrent barrier/wait poll would deadlock
                    # behind it); a blocking get is rare, so a private
                    # buffer per call is fine
                    buf = ctypes.create_string_buffer(n_cap)
                    return self._native_get(key, buf, n_cap, 1)
                # non-blocking probes are the hot path (wait()/barrier()
                # poll at up to 100 Hz per rank): reuse one cached buffer
                # under the lock instead of a fresh 1 MiB per probe
                with self._native_buf_lock:
                    if self._native_buf is None:
                        self._native_buf = ctypes.create_string_buffer(
                            n_cap)
                    return self._native_get(key, self._native_buf, n_cap,
                                            0)
            return self._client.get(key, wait)
        return self._op("get", do_get)

    def _native_get(self, key, buf, cap, wait_flag):
        n = self._lib.tcp_store_get(self._client, key.encode(), buf, cap,
                                    wait_flag)
        if n == -1:
            raise KeyError(key)
        if n < 0:
            raise RuntimeError("TCPStore.get failed")
        return buf.raw[:n]

    def add(self, key: str, amount: int = 1) -> int:
        def do_add():
            if self._lib is not None:
                out = self._lib.tcp_store_add(self._client, key.encode(),
                                              amount)
                if out == -(1 << 63):
                    raise RuntimeError("TCPStore.add failed")
                return int(out)
            return self._client.add(key, amount)
        return self._op("add", do_add)

    def wait(self, keys, timeout: Optional[float] = None):
        """Block until every key exists.  Polls with exponential backoff
        (0.01 s → 0.5 s) under a deadline — default 300 s, overridable per
        call or via ``PADDLE_TPU_STORE_TIMEOUT`` — and the timeout error
        NAMES the keys still missing (debugging "rank 3 never published
        its endpoint" from a bare TimeoutError is guesswork)."""
        keys = list(keys) if isinstance(keys, (list, tuple)) else [keys]
        if timeout is None:
            timeout = _store_timeout(300.0)
        deadline = time.monotonic() + timeout
        delays = _retry.backoff_delays(base=0.01, cap=0.5)
        pending = list(keys)
        while True:
            pending = [k for k in pending if not self._has_key(k)]
            if not pending:
                return
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(
                    "TCPStore.wait timed out after %.1fs; keys still "
                    "missing: %r (of %r) — override the deadline with "
                    "PADDLE_TPU_STORE_TIMEOUT" % (timeout, pending, keys))
            time.sleep(min(next(delays), remaining))

    def _has_key(self, key: str) -> bool:
        try:
            self.get(key, wait=False)
            return True
        except KeyError:
            return False

    def barrier(self, key: str = "_barrier",
                timeout: Optional[float] = None):
        """All world_size participants block until everyone arrived.
        Polls with backoff (not a tight 0.01 s spin); the default 60 s
        deadline honors ``PADDLE_TPU_STORE_TIMEOUT``; a timeout names the
        generation key it was waiting on and how many peers arrived."""
        if timeout is None:
            timeout = _store_timeout(60.0)
        n = self.add(key + ":cnt", 1)
        target = self.world_size
        if n % target == 0:
            self.set(key + f":gen{n // target}", b"1")
        gen = (n + target - 1) // target
        gen_key = key + f":gen{gen}"
        deadline = time.monotonic() + timeout
        delays = _retry.backoff_delays(base=0.01, cap=0.25)
        while True:
            if self._has_key(gen_key):
                return
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                arrived = self.add(key + ":cnt", 0)  # read without bumping
                raise TimeoutError(
                    "TCPStore.barrier(%r) timed out after %.1fs waiting "
                    "for key %r: %d arrival(s) total, generation %d needs "
                    "%d — override the deadline with "
                    "PADDLE_TPU_STORE_TIMEOUT"
                    % (key, timeout, gen_key, arrived, gen, gen * target))
            time.sleep(min(next(delays), remaining))

    def __del__(self):
        try:
            if self._lib is not None:
                if getattr(self, "_client", None):
                    self._lib.tcp_store_client_destroy(self._client)
                if getattr(self, "_server", None):
                    self._lib.tcp_store_server_destroy(self._server)
        except Exception:
            pass


# -- pure-Python fallback ----------------------------------------------------


class _PyStoreServer:
    def __init__(self, port):
        self._data = {}
        self._cv = threading.Condition()
        self._sock = socket.socket()
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("0.0.0.0", port))
        self._sock.listen(64)
        self.port = self._sock.getsockname()[1]
        threading.Thread(target=self._accept, daemon=True,
                         name="store-accept").start()

    def _accept(self):
        while True:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True, name="store-serve").start()

    def _serve(self, conn):
        def read_full(n):
            buf = b""
            while len(buf) < n:
                chunk = conn.recv(n - len(buf))
                if not chunk:
                    raise ConnectionError
                buf += chunk
            return buf

        try:
            while True:
                op = read_full(1)[0]
                klen = struct.unpack("<I", read_full(4))[0]
                key = read_full(klen).decode()
                if op == 1:    # SET
                    vlen = struct.unpack("<I", read_full(4))[0]
                    val = read_full(vlen)
                    with self._cv:
                        self._data[key] = val
                        self._cv.notify_all()
                    conn.sendall(b"\x01")
                elif op in (2, 4):  # GET / WAIT
                    with self._cv:
                        if op == 4:
                            self._cv.wait_for(lambda: key in self._data)
                        val = self._data.get(key)
                    if val is None:
                        conn.sendall(struct.pack("<I", 0xFFFFFFFF))
                    else:
                        conn.sendall(struct.pack("<I", len(val)) + val)
                elif op == 3:  # ADD
                    vlen = struct.unpack("<I", read_full(4))[0]
                    inc = struct.unpack("<q", read_full(vlen))[0]
                    with self._cv:
                        cur = struct.unpack(
                            "<q", self._data.get(key, b"\0" * 8))[0]
                        out = cur + inc
                        self._data[key] = struct.pack("<q", out)
                        self._cv.notify_all()
                    conn.sendall(struct.pack("<q", out))
        except (ConnectionError, OSError):
            pass
        finally:
            conn.close()


class _PyStoreClient:
    def __init__(self, host, port, timeout):
        self._host = host
        self._port = port
        self._timeout = timeout
        self._lock = threading.Lock()
        self._sock = self._connect(timeout)

    def _connect(self, timeout):
        deadline = time.time() + timeout
        while True:
            try:
                return socket.create_connection((self._host, self._port),
                                                timeout=5)
            except OSError:
                if time.time() > deadline:
                    raise
                time.sleep(0.1)

    def reconnect(self):
        """Drop the (possibly broken) stream and dial again — called by the
        TCPStore retry policy between attempts after a transient error."""
        with self._lock:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = self._connect(min(self._timeout, 5.0))

    def _read_full(self, n):
        buf = b""
        while len(buf) < n:
            chunk = self._sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError
            buf += chunk
        return buf

    def set(self, key, data):
        with self._lock:
            kb = key.encode()
            self._sock.sendall(bytes([1]) + struct.pack("<I", len(kb)) + kb
                               + struct.pack("<I", len(data)) + data)
            self._read_full(1)

    def get(self, key, wait):
        with self._lock:
            kb = key.encode()
            self._sock.sendall(bytes([4 if wait else 2])
                               + struct.pack("<I", len(kb)) + kb)
            ln = struct.unpack("<I", self._read_full(4))[0]
            if ln == 0xFFFFFFFF:
                raise KeyError(key)
            return self._read_full(ln)

    def add(self, key, amount):
        with self._lock:
            kb = key.encode()
            self._sock.sendall(bytes([3]) + struct.pack("<I", len(kb)) + kb
                               + struct.pack("<I", 8)
                               + struct.pack("<q", amount))
            # the request is on the wire: from here the server may have
            # applied the increment, so a lost reply must NOT be blindly
            # reissued (StoreReplyLostError is excluded from retry)
            try:
                return struct.unpack("<q", self._read_full(8))[0]
            except (ConnectionError, OSError) as e:
                raise StoreReplyLostError(
                    "TCPStore.add(%r, %d): reply lost after the request "
                    "was sent — the increment may or may not have been "
                    "applied; not reissuing" % (key, amount)) from e
